"""Device-wedge circuit breaker: closed / open / half-open.

Round 5's bench evidence motivated this module: two 600-second device
timeouts ate the whole bench window because the device path defends
against dispatches that *fail* (transient ``XlaRuntimeError`` retry,
``RESOURCE_EXHAUSTED`` halving) but not against dispatches that simply
never return. The breaker is the process's memory of device weather:

- **closed** — normal operation. Clean resolves reset the failure score;
  permanently-failed dispatches (retries exhausted -> host fallback) add
  one point each, and a *deadline overrun* (a dispatch the resolver
  abandoned, ops/kernel.py) or a canary failure trips the breaker
  immediately — a wedge is categorical evidence, not a data point.
- **open** — every :meth:`OffloadRouter.decide
  <fgumi_tpu.ops.router.OffloadRouter.decide>` call routes host with zero
  device waits (including explicitly forced ``FGUMI_TPU_ROUTE=device``
  runs, unless the breaker itself is disabled: a wedged feeder thread
  would otherwise stack every later dispatch behind the hang). After a
  cooldown the breaker moves to half-open. Re-trips while half-open
  double the cooldown (bounded) — close hysteresis, so a flapping link
  converges to long host-only stretches instead of oscillating.
- **half-open** — at most one probe dispatch is outstanding at a time
  (the router routes it like any other batch; the batch IS the probe,
  reusing the ``FGUMI_TPU_ROUTE_PROBE`` idea of sacrificing one batch to
  measurement). ``probe_successes`` consecutive clean resolves close the
  breaker; any failure reopens it.

Env contract (docs/resilience.md "Self-healing"):

- ``FGUMI_TPU_BREAKER=0`` — disable entirely (always closed).
- ``FGUMI_TPU_BREAKER_FAILURES`` — closed-state failure score that opens
  the breaker (default 3 permanent dispatch failures).
- ``FGUMI_TPU_BREAKER_COOLDOWN_S`` — open -> half-open delay (default 15;
  doubles per consecutive re-trip up to 8x).
- ``FGUMI_TPU_BREAKER_PROBES`` — consecutive half-open successes required
  to close (default 2).
- ``FGUMI_TPU_AUDIT_READMIT`` — audited probe dispatches required to lift
  an ``sdc`` quarantine (default 4; ``0`` = an SDC-tripped device is
  never re-admitted this process). See below.
- ``FGUMI_TPU_HEALTH_PERIOD_S`` — health-monitor canary period for
  long-lived processes (the serve daemon); 0 (default) = no monitor.

SDC quarantine (ISSUE 14, ops/sentinel.py): a shadow-audit divergence —
the device returned an answer the f64 host oracle refutes — trips the
breaker via :meth:`DeviceBreaker.record_sdc` and is categorically worse
than a wedge: a wedged device is *slow*, a silently-corrupting device is
*lying*, and time alone is no evidence it stopped. So unlike every other
trip reason, the cooldown does NOT half-open the breaker back on its own:
while quarantined, re-admission requires ``FGUMI_TPU_AUDIT_READMIT``
probe dispatches that are themselves *fully audited* (the sentinel forces
an inline shadow audit on every dispatch while
:meth:`DeviceBreaker.audit_required` is true); only the sentinel's
:meth:`DeviceBreaker.record_audit_clean` verdicts count toward closing —
an ordinary clean resolve proves the device answered, not that it
answered *correctly*. A fresh divergence during probing re-trips with the
usual doubled cooldown.

Like the router's EWMAs, breaker state is a per-process fact (the device
is shared by every job in the process); the *metrics* it stamps
(``device.breaker.state`` gauge, ``device.breaker.transitions`` counter)
land in whichever telemetry scope observed the transition, and the run
report carries :meth:`DeviceBreaker.snapshot` so a degraded run is
diagnosable from its artifact alone.
"""

import logging
import os
import threading
import time

log = logging.getLogger("fgumi_tpu")

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

#: cooldown growth cap: re-trips double the cooldown up to this factor.
MAX_COOLDOWN_FACTOR = 8


def _env_int(name, default):
    try:
        return max(int(os.environ.get(name, str(default))), 1)
    except ValueError:
        return default


def _env_float(name, default):
    try:
        return max(float(os.environ.get(name, str(default))), 0.1)
    except ValueError:
        return default


def audit_readmit_probes() -> int:
    """Audited probe dispatches required to lift an SDC quarantine
    (``FGUMI_TPU_AUDIT_READMIT``, default 4; 0 = never re-admit)."""
    try:
        return max(int(os.environ.get("FGUMI_TPU_AUDIT_READMIT", "4")), 0)
    except ValueError:
        return 4


class DeviceBreaker:
    """The closed/open/half-open state machine (thread-safe).

    ``now`` is injectable for tests; production uses ``time.monotonic``.
    Feeding methods are called from the kernel's resolve paths and the
    health monitor; :meth:`allow` is consulted by the offload router.
    """

    def __init__(self, now=time.monotonic):
        self._now = now
        self._lock = threading.Lock()
        self.reset()

    # ------------------------------------------------------------- config

    @staticmethod
    def enabled() -> bool:
        return os.environ.get("FGUMI_TPU_BREAKER", "1").strip().lower() \
            not in ("0", "false", "off")

    @staticmethod
    def _failure_threshold() -> int:
        return _env_int("FGUMI_TPU_BREAKER_FAILURES", 3)

    @staticmethod
    def _cooldown_s() -> float:
        return _env_float("FGUMI_TPU_BREAKER_COOLDOWN_S", 15.0)

    @staticmethod
    def _probes_to_close() -> int:
        return _env_int("FGUMI_TPU_BREAKER_PROBES", 2)

    # -------------------------------------------------------------- state

    def reset(self):
        """Back to pristine closed (tests; per-process otherwise)."""
        with self._lock:
            self._state = CLOSED
            self._score = 0              # closed-state failure score
            self._opened_at = None
            self._trips = 0              # consecutive re-trips (hysteresis)
            self._probe_inflight = False
            self._probe_claimed_at = None
            self._probe_successes = 0
            # SDC quarantine (ops/sentinel.py): while set, cooldown alone
            # cannot re-admit the device — only audited-clean probes can
            self._sdc_tripped = False
            self._audit_probe_ok = 0
            self.transitions = []        # [(t_mono, from, to, reason)]
            self.deadline_overruns = 0
            self.transient_failures = 0
            self.canary_failures = 0
            self.sdc_trips = 0
            self.successes = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._advance_locked()

    def _advance_locked(self) -> str:
        """Open -> half-open once the cooldown has elapsed; release a
        probe slot whose batch provably lost its feeder."""
        if self._state == OPEN:
            cool = self._cooldown_s() * min(2 ** max(self._trips - 1, 0),
                                            MAX_COOLDOWN_FACTOR)
            if self._now() - self._opened_at >= cool:
                if self._sdc_tripped and audit_readmit_probes() <= 0:
                    # quarantined with re-admission disabled: the device
                    # stays host-forced for the rest of the process — a
                    # corrupting chip earns no automatic second chance
                    pass
                elif self._sdc_tripped:
                    self._transition_locked(
                        HALF_OPEN, "cooldown elapsed (sdc quarantine: "
                        "re-admission requires audited probes)")
                else:
                    self._transition_locked(HALF_OPEN, "cooldown elapsed")
        if (self._state == HALF_OPEN and self._probe_inflight
                and self._probe_claimed_at is not None
                and self._now() - self._probe_claimed_at
                > self._probe_timeout_s()):
            # the probe batch died without feeding back — a non-weather
            # exception (pad/pack error, programming bug) between the
            # router's allow() and the resolve bypasses record_success /
            # record_*_failure. Without this release the slot leaks and
            # the breaker denies the device for the rest of the process.
            log.warning("device breaker: half-open probe never resolved; "
                        "releasing the probe slot")
            self._probe_inflight = False
        return self._state

    @staticmethod
    def _probe_timeout_s() -> float:
        """How long a claimed probe slot may stay outstanding: the
        dispatch-deadline ceiling (the longest a live probe can possibly
        wait before its own overrun feeds the breaker) plus slack."""
        import sys

        kern = sys.modules.get("fgumi_tpu.ops.kernel")
        ceil = None
        if kern is not None:
            try:
                ceil = kern._deadline_bounds()[1]
            except Exception:  # noqa: BLE001 - config probe only
                ceil = None
        return (ceil if ceil else 300.0) + 60.0

    def _transition_locked(self, new: str, reason: str):
        old = self._state
        if old == new:
            return
        self._state = new
        self.transitions.append(
            (round(self._now(), 3), old, new, reason))
        del self.transitions[:-64]  # bounded
        if new == OPEN:
            self._opened_at = self._now()
            self._trips += 1
        if new == HALF_OPEN:
            self._probe_inflight = False
            self._probe_successes = 0
            self._audit_probe_ok = 0
        if new == CLOSED:
            self._score = 0
            self._trips = 0
        level = logging.WARNING if new == OPEN else logging.INFO
        log.log(level, "device breaker: %s -> %s (%s)", old, new, reason)
        self._stamp_metrics(new)
        # flight-ring note only — the black-box dump happens outside this
        # lock (the record_* callers), because dump() re-enters snapshot()
        from ..observe.flight import FLIGHT

        FLIGHT.note("breaker.transition", state=new, previous=old,
                    reason=reason)

    @staticmethod
    def _stamp_metrics(state: str):
        # import inside: breaker must stay importable before observe
        from ..observe.metrics import METRICS

        METRICS.set("device.breaker.state", state)
        METRICS.inc("device.breaker.transitions")
        if state == OPEN:
            METRICS.inc("device.breaker.opened")

    # ------------------------------------------------------------- gating

    def allow(self) -> bool:
        """May the next batch go to the device?

        closed -> yes. open -> no. half-open -> yes for ONE outstanding
        probe at a time (this call claims the probe slot; the matching
        record_success / failure releases it)."""
        if not self.enabled():
            return True
        with self._lock:
            state = self._advance_locked()
            if state == CLOSED:
                return True
            if state == OPEN:
                return False
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            self._probe_claimed_at = self._now()
            return True

    def blocked(self) -> bool:
        """Non-claiming check: True when the device must not be used
        (open, or half-open with the probe slot taken). Cheap enough for
        the elementwise combine stages that bypass the router."""
        if not self.enabled():
            return False
        with self._lock:
            state = self._advance_locked()
            return state == OPEN or (state == HALF_OPEN
                                     and self._probe_inflight)

    # ------------------------------------------------------------ feeding

    def record_success(self):
        """One clean device resolve (or canary pass)."""
        with self._lock:
            self.successes += 1
            state = self._advance_locked()
            if state == CLOSED:
                self._score = 0
                return
            if state == HALF_OPEN:
                self._probe_inflight = False
                if self._sdc_tripped:
                    # a clean resolve proves the probe *answered*, not that
                    # it answered correctly — under SDC quarantine only the
                    # sentinel's audited verdict (record_audit_clean, fed
                    # after the inline shadow audit compares this very
                    # probe against the f64 oracle) counts toward closing
                    return
                self._probe_successes += 1
                if self._probe_successes >= self._probes_to_close():
                    self._transition_locked(
                        CLOSED,
                        f"{self._probe_successes} consecutive probe "
                        "successes")

    def _failure_locked(self, reason: str, weight: int):
        state = self._advance_locked()
        if state == HALF_OPEN:
            self._probe_inflight = False
            self._transition_locked(OPEN, f"probe failed: {reason}")
            return
        if state == CLOSED:
            self._score += weight
            if self._score >= self._failure_threshold():
                self._transition_locked(OPEN, reason)

    def _dump_if_tripped(self, was: str):
        """Black-box a closed/half-open -> open transition (flight
        recorder). Called OUTSIDE the breaker lock: the dump re-enters
        :meth:`snapshot`."""
        with self._lock:
            now = self._state
        if now == OPEN and was != OPEN:
            from ..observe.flight import FLIGHT

            FLIGHT.dump("breaker-open")

    def record_deadline_overrun(self):
        """A dispatch blew its deadline and was abandoned: categorical
        wedge evidence — trips a closed breaker immediately."""
        with self._lock:
            was = self._state
            self.deadline_overruns += 1
            self._failure_locked("dispatch deadline overrun",
                                 self._failure_threshold())
        self._dump_if_tripped(was)

    def record_transient_failure(self):
        """A dispatch failed permanently (bounded retry exhausted, host
        fallback taken): one point toward the closed-state threshold."""
        with self._lock:
            was = self._state
            self.transient_failures += 1
            self._failure_locked("repeated transient dispatch failures", 1)
        self._dump_if_tripped(was)

    def record_canary_failure(self):
        """The health monitor's canary dispatch failed or timed out."""
        with self._lock:
            was = self._state
            self.canary_failures += 1
            self._failure_locked("health canary failed",
                                 self._failure_threshold())
        self._dump_if_tripped(was)

    # --------------------------------------------------- SDC quarantine

    def record_sdc(self, detail: str = ""):
        """The shadow audit (ops/sentinel.py) caught the device returning
        a result the f64 host oracle refutes: silent data corruption.
        Trips immediately from any state and arms the quarantine — the
        cooldown alone can no longer re-admit the device (see the module
        docstring's SDC section)."""
        reason = "silent data corruption (audit divergence)"
        if detail:
            reason += f": {detail}"
        with self._lock:
            was = self._state
            self.sdc_trips += 1
            self._sdc_tripped = True
            self._audit_probe_ok = 0
            self._failure_locked(reason, self._failure_threshold())
        self._dump_if_tripped(was)

    def audit_required(self) -> bool:
        """True while SDC-quarantined: every dispatch the router still
        admits (a half-open re-admission probe) must be fully audited
        inline — the sentinel consults this at its resolve tap."""
        if not self.enabled():
            return False
        with self._lock:
            return self._sdc_tripped

    def sdc_quarantined(self) -> bool:
        """Alias for router stamping (why=sdc-quarantine vs breaker-open)."""
        return self.audit_required()

    def record_audit_clean(self):
        """One SDC re-admission probe came back byte-identical to the f64
        oracle under a full inline audit (the only feedback that counts
        toward lifting the quarantine). ``FGUMI_TPU_AUDIT_READMIT``
        consecutive such verdicts close the breaker and clear the
        quarantine; a divergence meanwhile re-trips via record_sdc."""
        with self._lock:
            if not self._sdc_tripped:
                return
            if self._advance_locked() != HALF_OPEN:
                return
            self._audit_probe_ok += 1
            need = audit_readmit_probes()
            if need and self._audit_probe_ok >= need:
                self._sdc_tripped = False
                self._transition_locked(
                    CLOSED, f"{self._audit_probe_ok} fully-audited probes "
                    "clean (sdc quarantine lifted)")

    # ----------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        with self._lock:
            state = self._advance_locked()
            out = {
                "state": state,
                "enabled": self.enabled(),
                "deadline_overruns": self.deadline_overruns,
                "transient_failures": self.transient_failures,
                "canary_failures": self.canary_failures,
                "successes": self.successes,
                "trips": self._trips,
                "transitions": [
                    {"t": t, "from": a, "to": b, "reason": r}
                    for t, a, b, r in self.transitions],
            }
            if self.sdc_trips or self._sdc_tripped:
                out["sdc_trips"] = self.sdc_trips
                out["sdc_quarantined"] = self._sdc_tripped
                out["audit_probe_ok"] = self._audit_probe_ok
            return out


class HealthMonitor:
    """Background canary loop for long-lived processes (the serve daemon).

    Every ``period_s`` it runs a tiny device dispatch under its own short
    deadline (``fgumi_tpu.ops.kernel.device_canary``) and feeds the
    breaker — so a chip that wedges *between* jobs is detected before the
    next job pays for the discovery — plus the router's link-rate EWMA.
    The canary only touches the device once jax is already initialized in
    this process (it must never be the thing that first wakes a wedged
    tunnel and hangs a thread the daemon is waiting on — the feeder
    submit + bounded ticket wait keeps even that case abandonable).
    """

    def __init__(self, breaker: "DeviceBreaker", period_s: float = 30.0,
                 canary_timeout_s: float = 10.0):
        self.breaker = breaker
        self.period_s = period_s
        self.canary_timeout_s = canary_timeout_s
        self._stop = threading.Event()
        self._thread = None
        self.canaries = 0

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="fgumi-health-monitor",
                                        daemon=True)
        self._thread.start()
        log.info("health monitor: canary every %.0fs (timeout %.0fs)",
                 self.period_s, self.canary_timeout_s)

    def stop(self, timeout: float = 5.0):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)

    def _loop(self):
        while not self._stop.wait(self.period_s):
            try:
                self._canary_once()
            except Exception:  # noqa: BLE001 - monitor must survive
                log.exception("health monitor: canary raised")

    def _canary_once(self):
        import sys

        kern = sys.modules.get("fgumi_tpu.ops.kernel")
        if kern is None or not getattr(kern, "_jax_ready", False):
            return  # nothing warm to check yet; never force a jax init
        if kern.DEVICE_FEEDER.queue_depth() > 0:
            # real dispatches are in flight: they are the health signal
            # (their resolves feed the breaker under their own deadlines),
            # and a canary queued behind them would time out on queue wait
            # alone — tripping the breaker open on a busy-but-healthy
            # device, the opposite of this monitor's job
            return
        self.canaries += 1
        ok, wall_s, err = kern.device_canary(self.canary_timeout_s)
        from ..observe.metrics import METRICS

        METRICS.inc("device.canary." + ("ok" if ok else "failed"))
        if ok:
            self.breaker.record_success()
        else:
            log.warning("health canary failed in %.2fs: %s", wall_s, err)
            self.breaker.record_canary_failure()


def monitor_period_s() -> float:
    """Configured health-monitor period (0 = disabled)."""
    try:
        return max(float(os.environ.get("FGUMI_TPU_HEALTH_PERIOD_S", "0")),
                   0.0)
    except ValueError:
        return 0.0


#: process-wide singleton: device weather is a per-process fact.
BREAKER = DeviceBreaker()
