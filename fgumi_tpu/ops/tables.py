"""Precomputed per-quality lookup tables for consensus calling.

Mirrors ConsensusBaseBuilder::new (/root/reference/crates/fgumi-consensus/src/base_builder.rs:566-595)
and VanillaUmiConsensusCaller::compute_single_input_consensus_quals
(/root/reference/crates/fgumi-consensus/src/vanilla_caller.rs:470-489).

Tables are built once per (pre, post) error-rate pair in f64 on host; the device kernel
consumes f32 casts of these (the f64 values remain the parity reference).
"""

from dataclasses import dataclass
from functools import lru_cache

import numpy as np

from ..constants import MAX_PHRED
from . import phred as P

LN_3 = np.log(3.0)


@dataclass(frozen=True)
class QualityTables:
    """Per-quality log-probability tables for one (pre, post) error-rate pair."""

    error_rate_pre_umi: int
    error_rate_post_umi: int
    # ln P(observed base | true base), adjusted for post-UMI error; index = Phred 0..93.
    adjusted_correct: np.ndarray
    # ln(P(error)/3) for a specific wrong base; index = Phred 0..93.
    adjusted_error_per_alt: np.ndarray
    # ln P(pre-UMI error).
    ln_error_pre_umi: float
    # Single-read consensus output quality per input quality (u8; vanilla_caller.rs:470-489).
    single_input_quals: np.ndarray


@lru_cache(maxsize=64)
def quality_tables(error_rate_pre_umi: int, error_rate_post_umi: int) -> QualityTables:
    """Build (and memoize) the quality tables for one error-rate pair."""
    quals = np.arange(MAX_PHRED + 1, dtype=np.float64)
    ln_error_seq = P.phred_to_ln_error(quals)
    ln_error_post = float(P.phred_to_ln_error(error_rate_post_umi))

    # adjusted error = two-trials(post-UMI, sequencing) (base_builder.rs:574-581)
    adjusted_error = P.ln_error_prob_two_trials(
        np.full_like(ln_error_seq, ln_error_post), ln_error_seq
    )
    adjusted_correct = P.ln_not(adjusted_error)
    adjusted_error_per_alt = adjusted_error - LN_3

    ln_error_pre_umi = float(P.phred_to_ln_error(error_rate_pre_umi))

    # Single-input consensus quality: two-trials(seq, min(pre, post)) -> Phred,
    # capped at MAX_PHRED (vanilla_caller.rs:470-489).
    labeling = min(error_rate_pre_umi, error_rate_post_umi)
    ln_labeling = float(P.phred_to_ln_error(labeling))
    single = P.ln_prob_to_phred(
        P.ln_error_prob_two_trials(ln_error_seq, np.full_like(ln_error_seq, ln_labeling))
    )
    single = np.minimum(single, MAX_PHRED).astype(np.uint8)

    return QualityTables(
        error_rate_pre_umi=error_rate_pre_umi,
        error_rate_post_umi=error_rate_post_umi,
        adjusted_correct=adjusted_correct,
        adjusted_error_per_alt=adjusted_error_per_alt,
        ln_error_pre_umi=ln_error_pre_umi,
        single_input_quals=single,
    )
