"""Host<->device data-path primitives: constant cache + shape buckets.

BENCH_r05 measured the consensus kernel at 7.3 ms/dispatch while the
end-to-end dispatch cost 2.9 s (``kernel_reads_per_sec`` 8.9M vs
``kernel_e2e_reads_per_sec`` 22.5k) — a ~400x gap that is entirely
host-side: per-dispatch ``device_put`` of constant tables, unbounded
padded-shape vocabulary (cold compiles), and serialized
upload/compute/fetch. This module holds the two stateless-ish pieces of
the fix; the pipelined feeder lives with the dispatch machinery in
``ops/kernel.py``:

- :class:`DeviceConstantCache` — quality tables (``correct_tab`` /
  ``err_tab``), wire dictionaries (``dict_tab``) and any other per-run
  lookup array are ``device_put`` **once per (device, table content)**
  and the resident handle is reused by every later dispatch. Keyed by
  content, not identity, so several :class:`~fgumi_tpu.ops.kernel.ConsensusKernel`
  instances with identical error rates (and every warm serve-daemon job)
  share entries.

- :class:`ShapeBucketRegistry` — pads ``(rows, segments)`` up to a small
  geometric ladder (default x1.0625 steps, configurable via
  ``--shape-buckets`` / ``FGUMI_TPU_SHAPE_BUCKETS``) so XLA compiles a
  bounded set of executables, padding waste stays below ~6.25% worst-case
  (~3% expected), and the persistent compile cache actually hits across
  runs. Each dispatch's final padded shape is ``observe()``-d:
  ``device.shape_bucket.hits`` / ``.misses`` / ``.shapes`` land in
  METRICS, and ``device.shape_bucket.recompiles`` counts the misses that
  triggered a *real* XLA backend compile (attributed through
  ``observe/compilewatch.py`` via a context flag that travels with the
  dispatch into the device-feeder thread).

Both are process-wide singletons (:data:`CONST_CACHE`,
:data:`SHAPE_REGISTRY`): device residency and the compiled-shape
vocabulary are per-process facts, not per-job ones — the scope-resolving
``METRICS`` proxy still attributes the counters to the submitting job.
"""

import contextlib
import contextvars
import hashlib
import os
import threading
from bisect import bisect_left
from collections import OrderedDict

import numpy as np

#: default geometric growth between adjacent ladder buckets; 6.25%
#: worst-case padding waste per dispatch, ~3% in expectation.
DEFAULT_GROWTH = 1.0625
#: ladder top; row counts beyond it pad to multiples of the cap instead
#: of growing the ladder (bounded vocabulary either way).
DEFAULT_CAP = 1 << 24


def parse_shape_buckets(spec):
    """``"GROWTH[:CAP]"`` -> (growth, cap), with loud errors.

    growth: geometric step between ladder buckets, in [1.01, 2.0] (2.0 ==
    pow2 padding). cap: largest ladder value (>= 1024); sizes above it
    round to multiples of the cap. None/"" -> defaults.
    """
    from ..utils.knobs import knob_error

    grammar = "GROWTH[:CAP] with growth in [1.01, 2.0] and cap >= 1024"

    def _err(problem):
        return ValueError(knob_error("FGUMI_TPU_SHAPE_BUCKETS", spec,
                                     problem, grammar))

    if spec is None or str(spec).strip() == "":
        return DEFAULT_GROWTH, DEFAULT_CAP
    parts = str(spec).strip().split(":")
    if len(parts) > 2:
        raise _err(f"{len(parts)} ':'-separated fields")
    try:
        growth = float(parts[0])
    except ValueError:
        raise _err(f"growth {parts[0]!r} is not a number") from None
    # 1.01 floor: growths within rounding of 1.0 degenerate into a ladder
    # with one entry per alignment step — ~1M entries built up front
    if not 1.01 <= growth <= 2.0:
        raise _err(f"growth {growth} is out of range")
    cap = DEFAULT_CAP
    if len(parts) == 2:
        try:
            cap = int(parts[1])
        except ValueError:
            raise _err(f"cap {parts[1]!r} is not an integer") from None
        if cap < 1024:
            raise _err(f"cap {cap} is below the 1024 floor")
    return growth, cap


# set while a dispatch whose bucketed shape is NEW this process is being
# built/submitted; it rides contextvars.copy_context() into the device
# feeder thread, so a jax backend-compile event fired there can be
# attributed to the shape miss (device.shape_bucket.recompiles).
_MISS_FLAG = contextvars.ContextVar("fgumi_tpu_shape_miss", default=False)


def compile_is_shape_miss() -> bool:
    """True when the current (context-carried) dispatch was a shape miss
    — called by observe/compilewatch on every backend-compile event."""
    return _MISS_FLAG.get()


class ShapeBucketRegistry:
    """Geometric bucket ladder + compiled-shape accounting.

    ``bucket_rows`` / ``bucket_segments`` quantize a dimension up to the
    ladder; ``observe`` records whether a dispatch's final padded shape
    was already seen this process (a guaranteed jit-cache hit) or is new
    (a compile candidate — the persistent cache may still absorb the
    actual XLA work, which ``device.backend_compiles`` tracks
    separately). Thread-safe; dirt cheap (one bisect + one set lookup
    per dispatch).
    """

    def __init__(self, growth=None, cap=None):
        self._lock = threading.Lock()
        self._explicit = (growth, cap) if growth is not None else None
        self._growth = growth
        self._cap = cap if cap is not None else (
            DEFAULT_CAP if growth is not None else None)
        self._ladders = {}  # align -> ascending bucket list
        self._seen = set()
        self._gen = 0  # bumped per reconfigure (guarded restores)
        self.hits = 0
        self.misses = 0

    # ------------------------------------------------------------ config

    def _config(self):
        if self._growth is None:
            self._growth, self._cap = parse_shape_buckets(
                os.environ.get("FGUMI_TPU_SHAPE_BUCKETS"))
        return self._growth, self._cap

    def reconfigure(self, spec=None, only_if_gen=None) -> int:
        """Re-read configuration (``spec`` wins over the environment) and
        drop the ladders; the seen-shape set and counters survive — the
        process's compiled executables don't go away.

        Returns a generation token. ``only_if_gen``: apply only when no
        other reconfigure happened since that token was issued — the CLI's
        per-invocation restore passes it so a finished daemon job cannot
        clobber the ladder a *later* job just configured."""
        with self._lock:
            if only_if_gen is not None and self._gen != only_if_gen:
                return self._gen
            if spec is not None:
                self._growth, self._cap = parse_shape_buckets(spec)
            else:
                self._growth = self._cap = None
                if self._explicit is not None:
                    self._growth, self._cap = self._explicit
            self._ladders.clear()
            self._gen += 1
            return self._gen

    def reset(self):
        """Forget seen shapes + counters (tests; per-process otherwise)."""
        with self._lock:
            self._seen.clear()
            self.hits = 0
            self.misses = 0

    # ------------------------------------------------------------ ladder

    def _ladder(self, align: int):
        lad = self._ladders.get(align)
        if lad is None:
            growth, cap = self._config()
            v = max(align, 8)
            lad = [v]
            while v < cap:
                nxt = -(-int(v * growth) // align) * align
                v = max(nxt, v + align)  # strictly increasing
                lad.append(min(v, -(-cap // align) * align))
            self._ladders[align] = lad
        return lad

    def bucket(self, n: int, align: int = 16) -> int:
        """Smallest ladder value >= n (multiples of the cap above it)."""
        n = max(int(n), 1)
        with self._lock:
            lad = self._ladder(align)
            if n > lad[-1]:
                cap = lad[-1]
                return -(-n // cap) * cap
            return lad[bisect_left(lad, n)]

    def bucket_rows(self, n: int) -> int:
        """Padded row count for a dense (N, L) dispatch layout."""
        return self.bucket(n, 16)

    def bucket_segments(self, j: int) -> int:
        """Padded segment count (static ``num_segments`` jit arg).

        Multiples of 8 keep ``_pad_out_segments``'s fetch-slice arithmetic
        and the hard-column 4-per-byte winner packing exact.
        """
        return self.bucket(max(j, 1), 8)

    def bucket_segments_sharded(self, j: int, parts: int) -> int:
        """Per-shard segment count for a family axis split ``parts`` ways.

        Quantizes ``ceil(j / parts)`` up the SAME 8-aligned ladder as the
        single-device ``bucket_segments``, so the global family axis rounds
        to ``parts * F_loc`` (a multiple of the mesh's dp by construction)
        while each shard's static jit shape comes from the one fleet-wide
        shape vocabulary — a dp=4 run and a dp=8 run compile the same
        per-shard executables when their shard sizes land on the same
        ladder rung (ISSUE 10: one vocabulary across mesh sizes)."""
        parts = max(int(parts), 1)
        return self.bucket(max(-(-int(j) // parts), 1), 8)

    # ------------------------------------------------------- observation

    def observe(self, kind: str, *dims) -> bool:
        """Record a dispatch's final padded shape; True when new.

        Folds ``device.shape_bucket.{hits,misses}`` counters and the
        ``.shapes`` distinct-count gauge into METRICS (submitter scope).
        """
        key = (kind, *map(int, dims))
        with self._lock:
            new = key not in self._seen
            if new:
                self._seen.add(key)
                self.misses += 1
            else:
                self.hits += 1
            n_shapes = len(self._seen)
        from ..observe.metrics import METRICS

        METRICS.inc("device.shape_bucket.misses" if new
                    else "device.shape_bucket.hits")
        METRICS.set("device.shape_bucket.shapes", n_shapes)
        return new

    @staticmethod
    @contextlib.contextmanager
    def attribute_compiles(is_miss: bool):
        """Flag the surrounding dispatch build/submit as a shape miss so a
        backend compile it triggers counts as ``.recompiles`` (the flag
        travels into the feeder via its context copy)."""
        if not is_miss:
            yield
            return
        token = _MISS_FLAG.set(True)
        try:
            yield
        finally:
            _MISS_FLAG.reset(token)


class DeviceConstantCache:
    """Content-keyed cache of device-resident constant arrays.

    ``put(name, arr)`` returns a device handle for ``arr``, uploading at
    most once per (default device, name, content) per process. The
    quality tables are a few hundred bytes each — the win is not the
    bytes, it's skipping a blocking ``device_put`` round-trip per table
    per dispatch on a link where small transfers cost hundreds of ms of
    latency (DeviceFeeder docstring).

    LRU-bounded (pathological inputs could mint a new wire dictionary per
    batch); ``invalidate()`` drops every handle — called before a
    transient-error retry, since the device runtime may have restarted
    under us and old buffers died with it.
    """

    MAX_ENTRIES = 256

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = OrderedDict()
        self.hits = 0
        self.uploads = 0
        self.upload_bytes = 0

    @staticmethod
    def _fingerprint(arr: np.ndarray):
        raw = arr.tobytes()
        if len(raw) > 4096:
            raw = hashlib.blake2b(raw, digest_size=16).digest()
        return arr.dtype.str, arr.shape, raw

    @staticmethod
    def _is_pending(entry) -> bool:
        return isinstance(entry, tuple) and entry and entry[0] == "pending"

    def put(self, name: str, arr: np.ndarray, sharding=None):
        """Device-resident handle for ``arr`` (jax must be initialized —
        callers sit inside dispatch closures, after ``_ensure_jax``).

        ``sharding``: optional ``jax.sharding.Sharding`` (the mesh compile
        path passes a replicated ``NamedSharding`` so constants live on
        every chip of the mesh); keyed into the cache alongside the
        content, so single-device and mesh dispatches of the same tables
        coexist without thrashing each other's residency.

        At-most-once per (device, content) even under concurrent misses
        (the sync dispatch paths run on arbitrary resolve workers, not
        just the feeder): the first thread to miss installs a pending
        marker under the lock and uploads with the lock RELEASED — a
        ``device_put`` can block hundreds of ms on the tunnel, and holding
        the cache lock for it would serialize every other dispatch thread
        behind one upload. Racing threads wait on the marker's event and
        re-read."""
        import jax

        if sharding is not None:
            dev = sharding
            placement = ("mesh",
                         tuple(sorted(d.id for d in sharding.device_set)),
                         str(getattr(sharding, "spec", "")))
        else:
            dev = jax.devices()[0]
            placement = (dev.platform, dev.id)
        key = (*placement, name, *self._fingerprint(arr))
        from ..observe.metrics import METRICS
        from .kernel import DEVICE_STATS

        while True:
            with self._lock:
                entry = self._entries.get(key)
                if entry is None:
                    marker = ("pending", threading.Event())
                    self._entries[key] = marker
                    break  # this thread owns the upload
                if not self._is_pending(entry):
                    self._entries.move_to_end(key)
                    self.hits += 1
                    hit_handle = entry
                else:
                    hit_handle = None
            if hit_handle is not None:
                METRICS.inc("device.const_cache.hits")
                DEVICE_STATS.add_const_hit()
                return hit_handle
            entry[1].wait()  # another thread is uploading; re-read
        try:
            handle = jax.device_put(arr, dev)
        except BaseException:
            with self._lock:
                if self._entries.get(key) is marker:
                    del self._entries[key]
            marker[1].set()
            raise
        with self._lock:
            # only publish if our marker survived (an invalidate() during
            # the upload means the handle may point at dead device state)
            if self._entries.get(key) is marker:
                self._entries[key] = handle
            self.uploads += 1
            self.upload_bytes += arr.nbytes
            while len(self._entries) > self.MAX_ENTRIES:
                for k in list(self._entries):
                    if not self._is_pending(self._entries[k]):
                        del self._entries[k]
                        break
                else:
                    break
        marker[1].set()
        METRICS.inc("device.const_cache.misses")
        METRICS.inc("device.const_cache.bytes_uploaded", arr.nbytes)
        DEVICE_STATS.add_const_upload(arr.nbytes)
        return handle

    def invalidate(self):
        """Drop every cached handle (device weather: next dispatch
        re-uploads fresh)."""
        with self._lock:
            self._entries.clear()

    def reset(self):
        """invalidate + zero the counters (tests)."""
        with self._lock:
            self._entries.clear()
            self.hits = 0
            self.uploads = 0
            self.upload_bytes = 0

    def __len__(self):
        with self._lock:
            return len(self._entries)


class HostStagingPool:
    """Recycled host staging buffers for the upload path (ISSUE 11).

    The wire build used to mint a fresh (N_pad, L) array per dispatch; with
    the shape-bucket ladder bounding the vocabulary of padded shapes, a
    small keyed free-list turns that into zero per-dispatch staging
    allocations after warm-up (the donation regression check in
    microbench.py gates on exactly this). Buffers are released back at
    dispatch *resolve* time — by then the device has consumed the upload
    even on backends where ``device_put`` aliases host memory — via the
    feeder's ``mark_resolved`` (an abandoned/wedged dispatch leaks its
    buffer rather than risking a recycle under a still-running upload).

    Bounded by ``FGUMI_TPU_STAGING_POOL`` bytes (default 64 MiB; ``0``
    disables pooling entirely): the free list evicts oldest-first, and a
    buffer larger than the whole budget is simply never pooled.
    """

    def __init__(self, max_bytes: int = None):
        self._lock = threading.Lock()
        self._max_bytes = max_bytes
        self._free = {}          # (shape, dtype.str) -> [ndarray]
        self._order = []         # FIFO of keys for eviction
        self._held_bytes = 0
        self.allocs = 0
        self.reuses = 0

    def _budget(self) -> int:
        if self._max_bytes is None:
            try:
                self._max_bytes = max(
                    int(os.environ.get("FGUMI_TPU_STAGING_POOL",
                                       str(64 << 20))), 0)
            except ValueError:
                self._max_bytes = 64 << 20
        return self._max_bytes

    def acquire(self, shape, dtype) -> np.ndarray:
        """A writable array of exactly (shape, dtype) — recycled when one
        is free, freshly allocated (and counted) otherwise."""
        key = (tuple(shape), np.dtype(dtype).str)
        with self._lock:
            lst = self._free.get(key)
            if lst:
                arr = lst.pop()
                self._held_bytes -= arr.nbytes
                # keep the FIFO in lockstep with the free lists: one entry
                # per HELD buffer, so a steady acquire/release cycle cannot
                # grow it without bound
                self._order.remove(key)
                self.reuses += 1
                from ..observe.metrics import METRICS

                METRICS.inc("device.staging.reuses")
                return arr
            self.allocs += 1
        from ..observe.metrics import METRICS

        METRICS.inc("device.staging.allocs")
        return np.empty(shape, dtype=dtype)

    def acquire_filled(self, shape, dtype, fill) -> np.ndarray:
        """``acquire`` + constant fill: the coalescer's merged row layouts
        (ops/coalesce.py) start as all-pad buffers (N_CODE codes / zero
        quals) that partner blocks are copied into, so merged builds mint
        zero fresh allocations once the shape vocabulary is warm — the
        same recycling contract as the wire staging buffers."""
        arr = self.acquire(shape, dtype)
        arr.fill(fill)
        return arr

    def release(self, arr: np.ndarray):
        """Return a buffer to the pool (drop it when over budget)."""
        if arr is None:
            return
        budget = self._budget()
        if budget <= 0 or arr.nbytes > budget:
            return
        key = (arr.shape, arr.dtype.str)
        with self._lock:
            self._free.setdefault(key, []).append(arr)
            self._order.append(key)
            self._held_bytes += arr.nbytes
            while self._held_bytes > budget and self._order:
                old = self._order.pop(0)
                lst = self._free.get(old)
                if lst:
                    dropped = lst.pop(0)
                    self._held_bytes -= dropped.nbytes

    def snapshot(self):
        with self._lock:
            return {"allocs": self.allocs, "reuses": self.reuses,
                    "held_bytes": self._held_bytes}

    def reset(self):
        with self._lock:
            self._free.clear()
            self._order.clear()
            self._held_bytes = 0
            self.allocs = 0
            self.reuses = 0
            self._max_bytes = None


def as_device_operand(a, dtype=None):
    """``a`` itself when it is already a C-contiguous ndarray (of
    ``dtype``, when given), else one conversion copy. The dispatch paths
    used to run every operand through ``np.asarray`` /
    ``np.ascontiguousarray`` unconditionally; those are no-ops for the
    common already-dense case, but this makes the no-copy contract
    explicit and catches the genuinely strided inputs (sliced views,
    transposed gathers) that would otherwise force ``device_put`` to copy
    internally. The one rule for both the jax dispatch operands and the
    native C++ entry points (``native/batch._as_c`` is an alias).
    Regression-benched in microbench.py (``dispatch_prep_*``)."""
    if (isinstance(a, np.ndarray) and a.flags.c_contiguous
            and (dtype is None or a.dtype == dtype)):
        return a
    return np.ascontiguousarray(a, dtype)


#: process-wide singletons (see module docstring).
SHAPE_REGISTRY = ShapeBucketRegistry()
CONST_CACHE = DeviceConstantCache()
STAGING_POOL = HostStagingPool()
