"""Adaptive host/device offload policy (ROADMAP item 1, round 6).

The classify-and-export-hard-columns design answered a 0.4-76 MB/s tunnel
by keeping ~86% of the consensus arithmetic on the host; with the constant
cache, the shape-bucket ladder, and the pipelined feeder in place the right
split is no longer a compile-time constant — it is a per-batch economic
decision. This module holds that decision in one place:

- :class:`OffloadRouter` — routes each consensus batch ``device`` (the
  full-column 1-byte-wire kernel) or ``host`` (the native f64 engine) from
  an online cost model: EWMAs of the measured upload link rate, the
  per-dispatch device overhead (compute + transfer + relay latency, the
  part that does not scale with bytes), and the host engine's measured
  throughput in pileup cells/s. The predicted times

      t_device = up_bytes/link + down_bytes/link + overhead
                 + in_flight * ewma_dispatch_wall          (queue delay)
      t_host   = cells / host_cells_per_s

  are compared per batch, so a mixed-family config lands on the winning
  side of its crossover automatically instead of by a static threshold.
  Every route is byte-identical by construction (the device path patches
  its suspects through the f64 oracle; the host path IS the f64 engine),
  so routing is a pure performance decision — including the probe batches
  the model occasionally sends to the losing side to keep both EWMAs live.

- :class:`AdaptiveChooser` — the same idea for cheap elementwise stages
  (the duplex strand-combine / CODEC concordance device stages): EWMA of
  seconds-per-cell on each side, alternate probes until both sides are
  measured, then pick the predicted winner with a periodic refresh probe.

Env contract (docs/performance-tuning.md):

- ``FGUMI_TPU_ROUTE=device|host|auto`` — force every batch to one side, or
  (default ``auto``) let the cost model decide. ``host`` falls back to
  ``device`` with a warning when the native engine is unavailable.
- ``FGUMI_TPU_MAX_INFLIGHT`` — when set explicitly, the pre-round-6 static
  backlog policy is honored verbatim (``0`` = always host; otherwise
  device unless that many dispatches are already in flight). Unset =
  adaptive (the backlog folds into the queue-delay term instead).
- ``FGUMI_TPU_ROUTE_PROBE`` — probe period (default 64): after this many
  consecutive same-side routes one batch goes to the other side so its
  EWMA tracks the link weather. ``0`` disables probing.

Like the datapath singletons, the measured rates are per-process facts
(the link and the host are shared by every job); the per-scope route
*counters* land in METRICS/DeviceStats via the callers.
"""

import os
import threading

import numpy as np  # noqa: F401  (kept: callers pass numpy scalars)

#: EWMA smoothing for rate estimates: ~the last dozen batches dominate.
ALPHA = 0.2
#: default probe period (batches of one side before sampling the other)
DEFAULT_PROBE = 64


def _env_route():
    v = os.environ.get("FGUMI_TPU_ROUTE", "auto").strip().lower()
    return v if v in ("device", "host", "auto") else "auto"


class _Ewma:
    __slots__ = ("value", "samples")

    def __init__(self):
        self.value = None
        self.samples = 0

    def add(self, x: float):
        x = float(x)
        self.value = x if self.value is None else \
            (1.0 - ALPHA) * self.value + ALPHA * x
        self.samples += 1

    def get(self, default: float):
        return self.value if self.value is not None else default

    def seed(self, value, samples: int = 1):
        """Install a measured prior (tune/profile.py). ``samples`` counts
        as real history for the decide() probe gates — a profile-seeded
        host rate must NOT re-fire probe-unmeasured — but live ``add()``
        measurements still converge away from it at the normal ALPHA."""
        if value is not None:
            self.value = float(value)
            self.samples = max(int(samples), 1)

    def export(self):
        return {"value": self.value, "samples": self.samples}

    def restore(self, state):
        if isinstance(state, dict) and state.get("value") is not None:
            self.seed(state["value"], state.get("samples", 1))


class OffloadRouter:
    """Per-batch device/host routing for the consensus engines."""

    # priors used before the first measurement lands: a mid-range tunnel
    # (10 MB/s) and the host engine's order of magnitude (20M cells/s) —
    # they only steer the first handful of batches, after which measured
    # EWMAs take over.
    PRIOR_LINK_BPS = 10e6
    PRIOR_HOST_CELLS_PER_S = 20e6
    PRIOR_OVERHEAD_S = 0.05

    def __init__(self):
        self._lock = threading.Lock()
        self._tls = threading.local()  # per-thread last prediction
        self._warned_no_host = False
        self.reset()

    def reset(self):
        with self._lock:
            # where the EWMAs' starting point came from: "cold" (static
            # class priors), "profile" (tune/profile.py seeded measured
            # priors), or "snapshot" (daemon warm-start restore). Stamped
            # into snapshot() + device.routing telemetry so a first-batch
            # routing decision is attributable to its prior.
            self.prior_source = "cold"
            # device-side EWMAs are PER MESH SIZE (ISSUE 10 (c)): an N-chip
            # mesh has its own link rate (N overlapping upload slices), its
            # own per-dispatch overhead (shard_map relay + collectives),
            # and its own service wall — pricing a dp4 dispatch with the
            # 1-device EWMAs would mis-place the host/device crossover in
            # exactly the configs the mesh exists for. Keyed by device
            # count; entry 1 is the classic single-device model.
            self._mesh = {1: self._new_mesh_ewmas()}
            self._host_cps = _Ewma()       # host engine cells/s (shared)
            # fused consensus→filter keep rate (ISSUE 11): the fraction of
            # device-routed reads the filter keeps, which is what the fused
            # route's fetch-bytes term scales with
            self._filter_keep = _Ewma()
            self._streak_side = None
            self._streak = 0
            self._last = {}                # last decision detail (snapshot)

    @staticmethod
    def _new_mesh_ewmas():
        return {"link_bps": _Ewma(), "overhead_s": _Ewma(),
                "dispatch_wall_s": _Ewma()}

    def _mesh_ewmas(self, devices: int):
        """The EWMA triple for one mesh size (caller holds the lock)."""
        e = self._mesh.get(devices)
        if e is None:
            e = self._mesh[devices] = self._new_mesh_ewmas()
        return e

    # ------------------------------------------------------------ feeding

    def observe_device(self, up_bytes: int, down_bytes: int,
                       upload_s: float, other_s: float, service_s: float,
                       devices: int = 1):
        """One resolved device dispatch. ``other_s`` is the non-upload,
        non-queue remainder (host fetch wait in practice); the download
        time it contains is netted out against the link estimate before
        feeding the overhead EWMA, since decide() prices down_bytes/link
        as its own term — without the subtraction the download would be
        charged twice and the device systematically overpriced near the
        crossover. ``service_s`` is the dispatch's serial occupancy of the
        feeder+link (upload + fetch wait), NOT including queue wait —
        decide() multiplies it by the in-flight count for the queue-delay
        term, so queue time must not be baked in twice. ``devices``: the
        mesh size this dispatch ran on (its own EWMA set)."""
        with self._lock:
            e = self._mesh_ewmas(int(devices) if devices else 1)
            if upload_s > 1e-6 and up_bytes > 0:
                e["link_bps"].add(up_bytes / upload_s)
            link = e["link_bps"].value
            if other_s >= 0:
                if link and down_bytes > 0:
                    other_s = max(other_s - down_bytes / link, 0.0)
                e["overhead_s"].add(other_s)
            if service_s > 0:
                e["dispatch_wall_s"].add(service_s)

    def observe_filter_keep(self, kept: int, total: int):
        """One fused-filter gather: how many device-routed reads survived.
        Feeds the keep-rate EWMA the fused route's fetch-bytes pricing
        scales with (``decide_batch(filtered=True)``)."""
        if total > 0:
            with self._lock:
                self._filter_keep.add(kept / total)

    def observe_host(self, cells: int, seconds: float):
        """One host-engine batch (cells = rows * positions of the pileup)."""
        if seconds > 1e-6 and cells > 0:
            with self._lock:
                self._host_cps.add(cells / seconds)

    def device_overhead_s(self, devices: int = 1) -> float:
        """Current per-dispatch device overhead estimate: the mesh size's
        measured EWMA, borrowing the 1-device chain (then the static
        prior) while unmeasured. The dispatch coalescer prices its hold
        window against this (ops/coalesce.py): merging saves ~one
        overhead per extra partner, so holding a batch longer than one
        overhead can only lose to just dispatching now."""
        with self._lock:
            e = self._mesh_ewmas(int(devices) if devices else 1)
            base = self._mesh[1]
            return e["overhead_s"].get(
                base["overhead_s"].get(self.PRIOR_OVERHEAD_S))

    # ----------------------------------------------------------- deciding

    @staticmethod
    def _probe_period():
        try:
            return max(int(os.environ.get("FGUMI_TPU_ROUTE_PROBE",
                                          str(DEFAULT_PROBE))), 0)
        except ValueError:
            return DEFAULT_PROBE

    def decide_batch(self, kernel, n_rows: int, n_segments: int,
                     L: int, devices: int = 1,
                     filtered: bool = False) -> str:
        """Route one consensus batch from its shape — the one place that
        knows the wire-path economics: upload is 1 B/position of dense rows
        plus 4 B/row of segment ids; the full-column fetch is 5.25 B/column
        (qual|suspect byte + 2-bit winner + uint16 depth + uint16 errors);
        host cost scales with the pileup cells (rows x positions).
        ``devices``: the mesh size a device route would dispatch on —
        selects that mesh's EWMA set so auto-routing stays correct when
        the device side is N chips. ``filtered``: price the fused
        consensus→filter route's fetch instead — a 28 B/read stats row
        plus the survivors' 6 B/position masked columns, scaled by the
        measured keep-rate EWMA (prior 0.5)."""
        if filtered:
            with self._lock:
                keep = self._filter_keep.get(0.5)
            down = 28 * n_segments + int(keep * 6 * n_segments * L)
        else:
            down = (21 * n_segments * L) // 4
        return self.decide(kernel, n_rows * L + 4 * n_rows, down,
                           n_rows * L, devices=devices)

    def decide(self, kernel, up_bytes: int, down_bytes: int,
               cells: int, devices: int = 1) -> str:
        """Route one batch: ``"device"`` or ``"host"``.

        ``kernel`` supplies the mode gates (hybrid/native availability);
        callers have already excluded host_mode(). The decision and its
        inputs are stamped into METRICS (``device.route.*``) so a wrong
        crossover is diagnosable from any run report.
        """
        from ..native import batch as nb
        from .kernel import DEVICE_STATS, default_max_inflight, log

        self._tls.pred = None  # only the cost branch produces a prediction
        forced = _env_route()
        if forced == "host":
            # an explicit ROUTE=host wins over FGUMI_TPU_HYBRID=0 (the
            # newer, more specific knob); only a missing native engine can
            # override it, and loudly
            if nb.available():
                return self._stamp("host", forced=True, why="forced")
            if not self._warned_no_host:  # once, not per batch
                self._warned_no_host = True
                log.warning("FGUMI_TPU_ROUTE=host but the native f64 engine "
                            "is unavailable; routing to the device")
            forced = "device"
        can_host = nb.available() and kernel.hybrid_mode()
        if not can_host:
            # nothing to degrade to: the device runs the batch regardless
            # of breaker state (the retry/fallback machinery still applies)
            return self._stamp("device", forced=forced != "auto",
                               why="forced" if forced == "device"
                               else "no-host-engine")
        # circuit breaker (ops/breaker.py): with the device declared
        # wedged, every batch routes host with ZERO device waits — the
        # feeder thread may be hung inside a dispatch, so queueing more
        # work behind it would stack deadlines. This overrides even an
        # explicit FGUMI_TPU_ROUTE=device (disable via FGUMI_TPU_BREAKER=0
        # to reproduce raw-device behavior); in half-open, allow() admits
        # one probe batch at a time and the resolve outcome feeds back.
        from .breaker import BREAKER

        if forced == "device":
            if not BREAKER.allow():
                return self._stamp("host", why=self._deny_reason(BREAKER))
            return self._stamp("device", forced=True, why="forced")

        env_cap = os.environ.get("FGUMI_TPU_MAX_INFLIGHT", "").strip()
        if env_cap:
            # legacy static policy, honored verbatim when explicitly set
            cap = default_max_inflight()
            side = "host" if (cap <= 0
                              or DEVICE_STATS.in_flight_count() >= cap) \
                else "device"
            if side == "device" and not BREAKER.allow():
                side = "host"
                return self._stamp(side, why=self._deny_reason(BREAKER))
            return self._stamp(side, why="max-inflight")

        with self._lock:
            e = self._mesh_ewmas(int(devices) if devices else 1)
            # an unmeasured mesh size borrows the 1-device EWMAs as its
            # prior (the link hardware is shared; only the measured
            # sharded behavior can correct it) before the static priors
            base = self._mesh[1]
            link = e["link_bps"].get(
                base["link_bps"].get(self.PRIOR_LINK_BPS))
            overhead = e["overhead_s"].get(
                base["overhead_s"].get(self.PRIOR_OVERHEAD_S))
            host_cps = self._host_cps.get(self.PRIOR_HOST_CELLS_PER_S)
            wall = e["dispatch_wall_s"].get(overhead)
            host_samples = self._host_cps.samples
            # on the default 1-device path e IS base — summing would
            # double-count and fire the probe-unmeasured branch a batch
            # early (legacy-behavior regression)
            dev_samples = e["overhead_s"].samples + \
                (base["overhead_s"].samples if e is not base else 0)
        in_flight = DEVICE_STATS.in_flight_count()
        t_dev = (up_bytes + down_bytes) / link + overhead + in_flight * wall
        t_host = cells / host_cps
        self._tls.pred = (t_dev, t_host)
        side = "device" if t_dev <= t_host else "host"
        why = "cost"
        # keep both EWMAs alive: sample the unmeasured/stale side
        probe = self._probe_period()
        if side == "device" and host_samples == 0 and dev_samples >= 2:
            side, why = "host", "probe-unmeasured"
        elif probe:
            with self._lock:
                streak = self._streak if self._streak_side == side else 0
            if streak >= probe:
                side = "host" if side == "device" else "device"
                why = "probe-refresh"
        if side == "device" and not BREAKER.allow():
            side, why = "host", self._deny_reason(BREAKER)
        return self._stamp(side, why=why, t_dev=t_dev, t_host=t_host,
                           link_bps=link, host_cps=host_cps,
                           overhead_s=overhead, in_flight=in_flight)

    @staticmethod
    def _deny_reason(breaker) -> str:
        """Why the breaker denied the device: an SDC quarantine (the
        shadow audit caught corruption — ops/sentinel.py) is stamped
        distinctly from an ordinary wedge/transient trip so a host-forced
        run's artifact names the actual cause."""
        return "sdc-quarantine" if breaker.sdc_quarantined() \
            else "breaker-open"

    def _stamp(self, side, forced=False, why="", t_dev=None, t_host=None,
               link_bps=None, host_cps=None, overhead_s=None, in_flight=0):
        from ..observe.metrics import METRICS

        with self._lock:
            if self._streak_side == side:
                self._streak += 1
            else:
                self._streak_side, self._streak = side, 1
            self._last = {"side": side, "why": why, "forced": forced}
            if t_dev is not None:
                self._last.update(pred_device_s=round(t_dev, 5),
                                  pred_host_s=round(t_host, 5))
        from .kernel import DEVICE_STATS

        METRICS.inc(f"device.route.{side}")
        DEVICE_STATS.add_route(side)
        if t_dev is not None:
            METRICS.set("device.route.pred_device_ms", round(t_dev * 1e3, 3))
            METRICS.set("device.route.pred_host_ms", round(t_host * 1e3, 3))
        if link_bps is not None:
            METRICS.set("device.route.link_mbps", round(link_bps / 1e6, 3))
            METRICS.set("device.route.host_mcells_per_s",
                        round(host_cps / 1e6, 3))
        return side

    def last_prediction(self):
        """(pred_device_s, pred_host_s) of THIS THREAD's latest cost-model
        decision, or None when it was forced/stamp-free — thread-local so a
        concurrent engine thread's decision cannot be paired with the wrong
        dispatch in the predicted-vs-actual timeline stamps."""
        return getattr(self._tls, "pred", None)

    # ------------------------------------------------- seeding / warm start

    def seed_priors(self, priors: dict, source: str = "profile") -> bool:
        """Install measured priors from a deployment profile
        (tune/profile.py). Only COLD EWMAs are seeded: once a live
        measurement has landed (samples > 0) the learned state wins — this
        also makes re-entry safe when daemon jobs re-run cli.main in fresh
        scoped contexts. Returns True when anything was seeded."""
        if not isinstance(priors, dict):
            return False
        seeded = False
        with self._lock:
            base = self._mesh_ewmas(1)
            for key, ewma in (("link_mbps", base["link_bps"]),
                              ("overhead_s", base["overhead_s"]),
                              ("dispatch_wall_s", base["dispatch_wall_s"])):
                v = priors.get(key)
                if v is not None and ewma.samples == 0:
                    ewma.seed(v * 1e6 if key == "link_mbps" else v)
                    seeded = True
            v = priors.get("host_mcells_per_s")
            if v is not None and self._host_cps.samples == 0:
                self._host_cps.seed(v * 1e6)
                seeded = True
            v = priors.get("filter_keep_rate")
            if v is not None and self._filter_keep.samples == 0:
                self._filter_keep.seed(v)
                seeded = True
            for n, mp in (priors.get("mesh") or {}).items():
                try:
                    e = self._mesh_ewmas(int(n))
                except (TypeError, ValueError):
                    continue
                for key, ewma in (("link_mbps", e["link_bps"]),
                                  ("overhead_s", e["overhead_s"]),
                                  ("dispatch_wall_s",
                                   e["dispatch_wall_s"])):
                    v = mp.get(key) if isinstance(mp, dict) else None
                    if v is not None and ewma.samples == 0:
                        ewma.seed(v * 1e6 if key == "link_mbps" else v)
                        seeded = True
            if seeded and self.prior_source == "cold":
                self.prior_source = source
        return seeded

    def export_state(self):
        """Full EWMA state (values + sample counts, every mesh size) for
        the daemon's warm-start snapshot — unlike the rounded snapshot()
        this is lossless, so a restore reproduces routing exactly."""
        with self._lock:
            return {
                "mesh": {str(n): {k: e[k].export() for k in e}
                         for n, e in self._mesh.items()},
                "host_cps": self._host_cps.export(),
                "filter_keep": self._filter_keep.export(),
            }

    def restore_state(self, state: dict, source: str = "snapshot") -> bool:
        """Reload an export_state() dict (daemon restart warm start).
        Cold-EWMA-only, like seed_priors: live measurements always win."""
        if not isinstance(state, dict):
            return False
        restored = False
        with self._lock:
            for n, me in (state.get("mesh") or {}).items():
                try:
                    e = self._mesh_ewmas(int(n))
                except (TypeError, ValueError):
                    continue
                if not isinstance(me, dict):
                    continue
                for k in ("link_bps", "overhead_s", "dispatch_wall_s"):
                    st = me.get(k)
                    if isinstance(st, dict) and st.get("value") is not None \
                            and e[k].samples == 0:
                        e[k].restore(st)
                        restored = True
            for attr, key in ((self._host_cps, "host_cps"),
                              (self._filter_keep, "filter_keep")):
                st = state.get(key)
                if isinstance(st, dict) and st.get("value") is not None \
                        and attr.samples == 0:
                    attr.restore(st)
                    restored = True
            if restored and self.prior_source == "cold":
                self.prior_source = source
        return restored

    # ----------------------------------------------------------- snapshot

    def snapshot(self):
        """Cost-model state for run reports / bench stamps."""
        with self._lock:
            base = self._mesh[1]
            out = {
                "prior_source": self.prior_source,
                "link_mbps": round(base["link_bps"].get(0.0) / 1e6, 3),
                "link_samples": base["link_bps"].samples,
                "overhead_s": round(base["overhead_s"].get(0.0), 5),
                "dispatch_wall_s": round(
                    base["dispatch_wall_s"].get(0.0), 5),
                "host_mcells_per_s": round(self._host_cps.get(0.0) / 1e6, 3),
                "host_samples": self._host_cps.samples,
            }
            if self._filter_keep.samples:
                out["filter_keep_rate"] = round(self._filter_keep.get(0.0),
                                                4)
            mesh_out = {}
            for n, e in sorted(self._mesh.items()):
                if n == 1 or not (e["link_bps"].samples
                                  or e["overhead_s"].samples):
                    continue
                mesh_out[str(n)] = {
                    "link_mbps": round(e["link_bps"].get(0.0) / 1e6, 3),
                    "link_samples": e["link_bps"].samples,
                    "overhead_s": round(e["overhead_s"].get(0.0), 5),
                    "dispatch_wall_s": round(
                        e["dispatch_wall_s"].get(0.0), 5),
                }
            if mesh_out:
                out["mesh"] = mesh_out
            if self._last:
                out["last_decision"] = dict(self._last)
            return out


class AdaptiveChooser:
    """Two-sided seconds-per-cell chooser for elementwise device stages.

    Used by the duplex strand-combine and CODEC concordance stages: both
    sides produce byte-identical output, so the chooser alternates probes
    until each side has two samples, then picks the predicted winner with
    a refresh probe every ``FGUMI_TPU_ROUTE_PROBE`` decisions. An env
    override (passed per call: ``"device"``/``"host"``) always wins."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._spc = {"device": _Ewma(), "host": _Ewma()}
        self._streak_side = None
        self._streak = 0

    def observe(self, side: str, cells: int, seconds: float):
        if cells > 0 and seconds >= 0:
            with self._lock:
                self._spc[side].add(seconds / cells)

    def seed(self, device_s_per_mcell=None, host_s_per_mcell=None) -> bool:
        """Install measured seconds-per-million-cells priors (profile
        units match snapshot()). Seeded with samples=2 so the first
        decide() picks the measured winner instead of alternating; cold
        sides only, so live daemons keep their learned state."""
        seeded = False
        with self._lock:
            for side, v in (("device", device_s_per_mcell),
                            ("host", host_s_per_mcell)):
                if v is not None and self._spc[side].samples == 0:
                    self._spc[side].seed(v / 1e6, samples=2)
                    seeded = True
        return seeded

    def export_state(self):
        with self._lock:
            return {side: e.export() for side, e in self._spc.items()}

    def restore_state(self, state: dict) -> bool:
        if not isinstance(state, dict):
            return False
        restored = False
        with self._lock:
            for side in ("device", "host"):
                st = state.get(side)
                if isinstance(st, dict) and st.get("value") is not None \
                        and self._spc[side].samples == 0:
                    self._spc[side].restore(st)
                    restored = True
        return restored

    def decide(self, cells: int, override: str = "auto") -> str:
        from ..observe.metrics import METRICS

        if override in ("device", "host"):
            METRICS.inc(f"device.route.{self.name}.{override}")
            return override
        probe = OffloadRouter._probe_period()
        with self._lock:
            d, h = self._spc["device"], self._spc["host"]
            if d.samples < 2 or h.samples < 2:
                # alternate until both sides are measured
                side = "device" if d.samples <= h.samples else "host"
            else:
                side = "device" if d.value <= h.value else "host"
                if probe and self._streak_side == side \
                        and self._streak >= probe:
                    side = "host" if side == "device" else "device"
            if self._streak_side == side:
                self._streak += 1
            else:
                self._streak_side, self._streak = side, 1
        METRICS.inc(f"device.route.{self.name}.{side}")
        return side

    def snapshot(self):
        with self._lock:
            return {side: {"s_per_mcell": round(e.get(0.0) * 1e6, 6),
                           "samples": e.samples}
                    for side, e in self._spc.items()}


def run_adaptive_stage(chooser: AdaptiveChooser, cells: int, override: str,
                       device_fn, host_fn):
    """Run one elementwise stage on the chooser's preferred side under the
    shared degrade contract: whichever side runs is timed and fed to its
    EWMA; a transient/OOM device failure is charged to the device side
    (including its retry/backoff time — the chooser must learn, not
    re-try a dead stage every batch), warned once per occurrence, and
    falls back to ``host_fn``; non-device-weather errors re-raise.
    Returns (result, side-that-produced-it)."""
    import time

    from .breaker import BREAKER
    from .kernel import _is_oom, _is_transient, log

    if cells > 0 and not BREAKER.blocked() \
            and chooser.decide(cells, override) == "device":
        t0 = time.monotonic()
        try:
            out = device_fn()
            chooser.observe("device", cells, time.monotonic() - t0)
            return out, "device"
        except BaseException as e:  # noqa: BLE001 - classified below
            if not (_is_oom(e) or _is_transient(e)):
                raise
            chooser.observe("device", cells, time.monotonic() - t0)
            log.warning("%s device stage failed (%s: %s); using the host "
                        "path", chooser.name, type(e).__name__, e)
    t0 = time.monotonic()
    out = host_fn()
    chooser.observe("host", cells, time.monotonic() - t0)
    return out, "host"


#: process-wide singletons (measured rates are per-process facts)
ROUTER = OffloadRouter()
DUPLEX_COMBINE = AdaptiveChooser("duplex_combine")
CODEC_COMBINE = AdaptiveChooser("codec_combine")
