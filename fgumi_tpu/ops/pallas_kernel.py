"""Hand-tiled Pallas TPU kernel for the fused wire consensus(+filter) path.

ROADMAP item 3 / ISSUE 19: BENCH_r05 measured ~23 GFLOP/s achieved on a
~200 TFLOP/s chip because the XLA lowering of the wire kernels widens the
1-byte packed observations to f32 one-hots in HBM and round-trips HBM
between the segment reduction, the posterior-Q epilogue, and the PR 11
filter mask. This module re-expresses the same computation as ONE Pallas
kernel that keeps every intermediate in VMEM:

    grid (S_tiles, W) — segment-tile-major, windowed over row tiles

    ┌ wire (R_TILE, L) u8 block ──────────────┐   VMEM, one DMA per
    │ qidx=b>>2, code=b&3, dict select (SMEM) │   in-window row tile
    └──────────────┬──────────────────────────┘
                   │  one-hot matmul  A(S_TILE,R_TILE) @ X(R_TILE,L)
                   ▼  (MXU, precision=HIGHEST — guard-band contract)
    ┌ VMEM scratch: contrib/obs (4,S_TILE,L) f32, poison (S_TILE,L) ┐
    │ accumulated across the w window; epilogue at w == W-1:       │
    │ vote → loser-gap posterior → Phred → suspect guard band      │
    └──────────────┬───────────────────────────────────────────────┘
                   ▼
    winner/qual/depth/errors/suspect (S_TILE, L) i32 output blocks

Windowing: seg_ids are sorted, so the rows of segment tile ``s`` live in
a contiguous row-tile range. The per-tile window base and width ride the
scalar-prefetch channel (SMEM) and the BlockSpec index_map clamps
out-of-window steps to the last in-window block — no DMA is issued for a
revisited block and ``pl.when`` skips the compute, so a skewed ladder
batch pays for the rows it has, not ``S_tiles * n_row_tiles``. ``W`` is
bucketed to powers of two to keep the compile vocabulary bounded
(same philosophy as the shape-bucket ladder feeding it).

Numerics contract (docs/device-datapath.md "Suspect guard band"): the
guard-band derivation in ops/kernel.py holds for summing nonnegative f32
terms in ANY order, so the matmul segment reduction (different order
than XLA's segment_sum) stays inside the band: non-suspect positions are
provably exact in both backends and the backends' CLI bytes agree after
the standard host patching of (possibly different) suspect sets. Q0-class
nonfinite dictionary entries cannot ride the matmul (0 * inf = NaN would
poison the whole segment tile), so they are zeroed per observation and a
poison-count matmul forces ``suspect`` at exactly the (segment, position)
cells XLA's NaN propagation would have flagged.

The small (J, L)-scale epilogues — split-result packing and the PR 11
filter mask + 7-col stats row (``f_emin_tab`` is a 32768-entry table; an
in-kernel one-hot gather of it would blow VMEM) — run as jnp ops inside
the SAME jit around pallas_call: all-integer, bit-exact, and operating on
segment-scale (not row-scale) arrays, so no O(N*L) HBM round-trip is
reintroduced.

Selection: ``FGUMI_TPU_KERNEL=pallas|xla|auto`` (default auto = Pallas on
real TPU backends only; CPU/GPU hosts keep XLA). Forcing ``pallas`` on a
CPU host runs Mosaic interpret mode — the parity-test path; production
CPU runs fall back to XLA so tier-1 latency is unchanged. The XLA kernels
remain the permanent parity oracle. Covered dispatch kinds: the
full-column wire kernel (``segwfp``) and the fused consensus→filter
kernel (``segwxp``); resident/duplex, mesh, packed2-fallback, and gather
dispatches stay XLA. Upload donation is a no-op here (Pallas manages its
own blocks); the donation knob simply does not apply.
"""

import functools
import logging
import os

import numpy as np

from ..constants import MAX_PHRED, MIN_PHRED, N_CODE

log = logging.getLogger("fgumi_tpu")

#: row-tile (matmul contraction dim) and segment-tile (output sublanes)
R_TILE = 128
S_TILE = 8

_IMPORT_OK = None  # cached pallas-import probe
_WARNED = set()    # loud-once keys (bad env value / forced-but-unavailable)


# ---------------------------------------------------------------- selection

def kernel_backend() -> str:
    """Parsed ``FGUMI_TPU_KERNEL``: ``"pallas"``, ``"xla"`` or ``"auto"``.

    Invalid values are a LOUD error (logged once per distinct value) and
    fall back to ``auto`` — a typo must never silently pin a production
    fleet to the wrong kernel."""
    v = os.environ.get("FGUMI_TPU_KERNEL", "auto").strip().lower()
    if v in ("", "auto", "default"):
        return "auto"
    if v in ("pallas", "xla"):
        return v
    key = ("badenv", v)
    if key not in _WARNED:
        _WARNED.add(key)
        log.error("FGUMI_TPU_KERNEL=%r: expected pallas, xla or auto; "
                  "using auto", v)
    return "auto"


def available() -> bool:
    """Whether the Pallas lowering can be used in this process.

    ``FGUMI_TPU_PALLAS_UNAVAILABLE=1`` forces False (the fallback-path
    test hook — simulates a jaxlib built without Mosaic support)."""
    if os.environ.get("FGUMI_TPU_PALLAS_UNAVAILABLE", "").strip().lower() \
            in ("1", "true", "on"):
        return False
    global _IMPORT_OK
    if _IMPORT_OK is None:
        try:
            from jax.experimental import pallas as _pl  # noqa: F401
            from jax.experimental.pallas import tpu as _pltpu  # noqa: F401

            _IMPORT_OK = True
        except Exception as exc:  # noqa: BLE001 - any import failure
            log.warning("pallas kernels unavailable: %s", exc)
            _IMPORT_OK = False
    return _IMPORT_OK


def interpreted() -> bool:
    """True when Pallas would run in Mosaic interpret mode (no real TPU
    backend) — microbench/report results must carry this flag so CPU CI
    numbers are never mistaken for silicon evidence."""
    from .kernel import _ensure_jax

    jax = _ensure_jax()
    return jax.default_backend() != "tpu"


def selected_backend() -> str:
    """The kernel backend for the next wire dispatch: ``"pallas"`` or
    ``"xla"``.

    - ``xla`` forced: XLA.
    - ``pallas`` forced: Pallas (interpret mode off-TPU — the test
      path); if Pallas is unavailable, a loud error + XLA fallback.
    - ``auto``: Pallas only on a real TPU backend; CPU/GPU hosts keep
      the XLA path so production latency never pays interpret mode.
    """
    mode = kernel_backend()
    if mode == "xla":
        return "xla"
    if mode == "pallas":
        if available():
            return "pallas"
        if "forced-unavailable" not in _WARNED:
            _WARNED.add("forced-unavailable")
            log.error("FGUMI_TPU_KERNEL=pallas but the Pallas lowering is "
                      "unavailable in this jax install; falling back to "
                      "the XLA kernels (parity is unaffected)")
        return "xla"
    # auto
    return "pallas" if (available() and not interpreted()) else "xla"


# ------------------------------------------------------------- host prepare

def _bucket_pow2(n: int) -> int:
    v = 1
    while v < n:
        v <<= 1
    return v


class _Prepared:
    """Host-side layout of one Pallas wire dispatch (window metadata +
    row-tile-padded arrays), plus the device handles after upload."""

    __slots__ = ("wire_p", "seg2d", "base", "cnt", "dictbits", "s_tiles",
                 "w_tiles", "dev")

    def __init__(self, wire_p, seg2d, base, cnt, dictbits, s_tiles,
                 w_tiles):
        self.wire_p = wire_p
        self.seg2d = seg2d
        self.base = base
        self.cnt = cnt
        self.dictbits = dictbits
        self.s_tiles = s_tiles
        self.w_tiles = w_tiles
        self.dev = None


def _prepare(wire: np.ndarray, seg_ids: np.ndarray, dict32: np.ndarray,
             num_segments: int) -> _Prepared:
    """Row-tile padding + per-segment-tile window computation (numpy).

    Pad rows carry seg id ``s_pad`` (outside every tile's range) and
    WIRE_INVALID bytes — double-masked no-ops. Windows: seg_ids are
    sorted, so segment tile s's rows span
    ``searchsorted(s*S_TILE) .. searchsorted((s+1)*S_TILE)``."""
    n_rows, L = wire.shape
    s_tiles = -(-int(num_segments) // S_TILE)
    s_pad = s_tiles * S_TILE
    n_rt = max(-(-n_rows // R_TILE), 1)
    n_full = n_rt * R_TILE
    if n_full != n_rows:
        from .kernel import WIRE_INVALID

        wire_p = np.full((n_full, L), WIRE_INVALID, dtype=np.uint8)
        wire_p[:n_rows] = wire
        segp = np.full(n_full, s_pad, dtype=np.int32)
        segp[:n_rows] = seg_ids
    else:
        wire_p = wire
        segp = np.ascontiguousarray(seg_ids, dtype=np.int32)
    seg2d = segp.reshape(n_rt, R_TILE)
    edges = np.arange(s_tiles + 1, dtype=np.int64) * S_TILE
    bounds = np.searchsorted(seg_ids, edges, side="left")
    lo, hi = bounds[:-1], bounds[1:]
    base = (lo // R_TILE).astype(np.int32)
    cnt = np.where(hi > lo, -(-(hi - base.astype(np.int64) * R_TILE)
                              // R_TILE), 0).astype(np.int32)
    base = np.clip(base, 0, n_rt - 1).astype(np.int32)
    w_tiles = min(_bucket_pow2(int(cnt.max()) if len(cnt) else 1) or 1,
                  n_rt)
    w_tiles = max(w_tiles, 1)
    dictbits = np.ascontiguousarray(dict32, dtype=np.float32).view(np.int32)
    return _Prepared(wire_p, seg2d, base, cnt, dictbits, s_tiles, w_tiles)


def upload(wire: np.ndarray, seg_ids: np.ndarray, dict32: np.ndarray,
           num_segments: int) -> _Prepared:
    """Prepare + device_put everything a Pallas wire dispatch uploads
    (called on the feeder thread inside the upload-timing window)."""
    from .kernel import _ensure_jax

    jax = _ensure_jax()
    prep = _prepare(wire, seg_ids, dict32, num_segments)
    prep.dev = (jax.device_put(prep.wire_p), jax.device_put(prep.seg2d),
                jax.device_put(prep.base), jax.device_put(prep.cnt),
                jax.device_put(prep.dictbits))
    return prep


# ------------------------------------------------------------ kernel proper

def _consensus_kernel(s_tiles: int, w_tiles: int, last_w: int):
    """The Pallas kernel body factory (closed over static grid dims)."""
    from .kernel import (_EPS32, _LN_4_3_F32, _PHRED_PER_LN,
                         _QUAL_GUARD_FLOOR, _TIE_GUARD_FLOOR, _ensure_jax)

    jax = _ensure_jax()
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    highest = jax.lax.Precision.HIGHEST
    neg_inf = float("-inf")

    def dot(a, b):
        return jax.lax.dot_general(
            a, b, dimension_numbers=(((1,), (0,)), ((), ())),
            precision=highest, preferred_element_type=jnp.float32)

    def kernel(base_ref, cnt_ref, dictbits_ref, prebits_ref, seg_ref,
               wire_ref, win_ref, qual_ref, dep_ref, err_ref, sus_ref,
               contrib_ref, obs_ref, poison_ref):
        s = pl.program_id(0)
        w = pl.program_id(1)

        @pl.when(w == 0)
        def _zero():
            contrib_ref[...] = jnp.zeros_like(contrib_ref)
            obs_ref[...] = jnp.zeros_like(obs_ref)
            poison_ref[...] = jnp.zeros_like(poison_ref)

        @pl.when(w < cnt_ref[s])
        def _accumulate():
            wire = wire_ref[...]  # (R_TILE, L) u8
            qidx = (wire >> 2).astype(jnp.int32)
            code = (wire & 3).astype(jnp.int32)
            valid = qidx != 63
            # dictionary select off the SMEM scalar channel: 63 unrolled
            # compare-selects (entry 63 is the invalid sentinel == 0).
            # Nonfinite (Q0-class) entries are zeroed per observation and
            # tracked in `pois` — 0 * inf through the matmul would NaN
            # the whole segment tile, where XLA's segment_sum NaNs only
            # the observation's own segment.
            L = wire.shape[1]
            delta = jnp.zeros((R_TILE, L), jnp.float32)
            pois = jnp.zeros((R_TILE, L), jnp.float32)
            for k in range(63):
                tab_k = jax.lax.bitcast_convert_type(
                    dictbits_ref[k], jnp.float32)
                fin_k = jnp.isfinite(tab_k)
                sel = qidx == k
                delta = jnp.where(sel, jnp.where(fin_k, tab_k, 0.0), delta)
                pois = jnp.where(sel & ~fin_k, 1.0, pois)
            # local segment one-hot: A[t, r] = [seg[r] == s*S_TILE + t]
            s_local = seg_ref[...].astype(jnp.int32) - s * S_TILE  # (1, R)
            iota_t = jax.lax.broadcasted_iota(jnp.int32,
                                              (S_TILE, R_TILE), 0)
            a = (iota_t == s_local).astype(jnp.float32)
            for b in range(4):
                hot = ((code == b) & valid).astype(jnp.float32)
                contrib_ref[b] += dot(a, delta * hot)
                obs_ref[b] += dot(a, hot)
            poison_ref[...] += dot(a, pois)

        @pl.when(w == last_w)
        def _epilogue():
            pre = jax.lax.bitcast_convert_type(prebits_ref[0], jnp.float32)
            c = [contrib_ref[b][...] for b in range(4)]
            o = [obs_ref[b][...] for b in range(4)]
            depth_f = o[0] + o[1] + o[2] + o[3]
            depth = depth_f.astype(jnp.int32)
            max_c = jnp.maximum(jnp.maximum(c[0], c[1]),
                                jnp.maximum(c[2], c[3]))
            # first-max winner mask (argmax + one_hot twin)
            m = []
            taken = None
            for b in range(4):
                hit = c[b] == max_c
                m.append(hit if taken is None else (hit & ~taken))
                taken = m[b] if taken is None else (taken | m[b])
            winner = (jnp.where(m[1], 1, 0) + jnp.where(m[2], 2, 0)
                      + jnp.where(m[3], 3, 0)).astype(jnp.int32)
            # loser-gap frame (ops/kernel._call_epilogue twin, f32)
            s_sum = jnp.zeros_like(max_c)
            for b in range(4):
                s_sum = s_sum + jnp.where(m[b], 0.0,
                                          jnp.exp(-(max_c - c[b])))
            ln_cons_err = jnp.log(s_sum) - jnp.log1p(s_sum)
            hi = jnp.maximum(pre, ln_cons_err)
            lo = jnp.minimum(pre, ln_cons_err)
            diff = hi - lo
            quick = ~(diff < 6.0)
            safe_diff = jnp.where(quick, 6.0, diff)
            term1 = hi + jnp.log1p(jnp.exp(-safe_diff))
            term2_minus_term1 = (_LN_4_3_F32 + lo
                                 - jnp.log1p(jnp.exp(-safe_diff)))
            full = term1 + jnp.log1p(
                -jnp.exp(jnp.minimum(term2_minus_term1, -_EPS32)))
            ln_final = jnp.where(quick, hi, full)
            phred_f = -ln_final * _PHRED_PER_LN + 0.001
            qual = jnp.clip(jnp.floor(phred_f), MIN_PHRED,
                            MAX_PHRED).astype(jnp.int32)
            # suspect guard band (identical formulas; the band is valid
            # for any nonnegative summation order, so it covers the
            # matmul accumulation too)
            eps_gap = _EPS32 * (depth_f + 2.0) * (1.0 + max_c)
            second = jnp.full_like(max_c, neg_inf)
            for b in range(4):
                second = jnp.maximum(second,
                                     jnp.where(m[b], neg_inf, c[b]))
            margin = max_c - second
            tie_suspect = margin <= (2.0 * eps_gap + _TIE_GUARD_FLOOR)
            took_pre = quick & (ln_cons_err < pre)
            err_phred = jnp.where(took_pre, 0.0,
                                  _PHRED_PER_LN * 2.0 * eps_gap)
            frac = phred_f - jnp.floor(phred_f)
            near_boundary = (jnp.minimum(frac, 1.0 - frac)
                             <= (err_phred + _QUAL_GUARD_FLOOR))
            clamped = ((phred_f <= MIN_PHRED)
                       | (phred_f >= MAX_PHRED + 0.5))
            branch_suspect = jnp.abs(diff - 6.0) <= (2.0 * eps_gap + 1e-4)
            nonfinite = (~jnp.isfinite(max_c)) | (poison_ref[...] > 0.0)
            suspect = (tie_suspect | branch_suspect | nonfinite
                       | (near_boundary & ~clamped))
            no_call = depth == 0
            winner = jnp.where(no_call | tie_suspect, N_CODE, winner)
            qual = jnp.where(no_call | tie_suspect, MIN_PHRED, qual)
            suspect = suspect & ~no_call
            winner_obs = jnp.zeros_like(depth_f)
            for b in range(4):
                winner_obs = winner_obs + jnp.where(m[b], o[b], 0.0)
            errors = depth - jnp.where(winner == N_CODE, 0,
                                       winner_obs.astype(jnp.int32))
            win_ref[...] = winner
            qual_ref[...] = qual
            dep_ref[...] = depth
            err_ref[...] = errors
            sus_ref[...] = suspect.astype(jnp.int32)

    return kernel


def _pallas_consensus(wire_p, seg2d, base, cnt, dictbits, prebits,
                      s_tiles: int, w_tiles: int, interpret: bool):
    """pallas_call plumbing: grid/specs/scratch for the windowed kernel.
    Traced inside the jit wrappers below."""
    from .kernel import _ensure_jax

    jax = _ensure_jax()
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_rt, _ = seg2d.shape
    L = wire_p.shape[1]
    s_pad = s_tiles * S_TILE

    def _row_tile(s, w, base_ref, cnt_ref, _db, _pb):
        wc = jnp.minimum(w, jnp.maximum(cnt_ref[s] - 1, 0))
        return (jnp.minimum(base_ref[s] + wc, n_rt - 1), 0)

    out_shape = [jax.ShapeDtypeStruct((s_pad, L), jnp.int32)
                 for _ in range(5)]
    out_specs = [pl.BlockSpec((S_TILE, L), lambda s, w, *_: (s, 0))
                 for _ in range(5)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(s_tiles, w_tiles),
        in_specs=[
            pl.BlockSpec((1, R_TILE), _row_tile),   # seg2d
            pl.BlockSpec((R_TILE, L), _row_tile),   # wire
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((4, S_TILE, L), jnp.float32),  # contrib
            pltpu.VMEM((4, S_TILE, L), jnp.float32),  # obs
            pltpu.VMEM((S_TILE, L), jnp.float32),     # poison
        ],
    )
    fn = pl.pallas_call(
        _consensus_kernel(s_tiles, w_tiles, w_tiles - 1),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )
    return fn(base, cnt, dictbits, prebits, seg2d, wire_p)


# --------------------------------------------------- jitted entry wrappers

def _pack_split(winner, qual, suspect, out_segments: int):
    """jnp twin of ops/kernel._pack_result_split over i32 planes."""
    import jax.numpy as jnp

    qs = (qual | (suspect << 7))[:out_segments]
    w4 = jnp.where(winner > 3, 0, winner)[:out_segments]
    w4 = w4.reshape(out_segments, -1, 4)
    wp = (w4[..., 0] | (w4[..., 1] << 2) | (w4[..., 2] << 4)
          | (w4[..., 3] << 6))
    return qs.astype(jnp.uint8), wp.astype(jnp.uint8)


@functools.lru_cache(maxsize=64)
def _full_jit(out_segments: int, s_tiles: int, w_tiles: int,
              interpret: bool):
    from .kernel import _ensure_jax

    jax = _ensure_jax()
    import jax.numpy as jnp

    def fn(wire_p, seg2d, base, cnt, dictbits, prebits):
        win, qual, dep, err, sus = _pallas_consensus(
            wire_p, seg2d, base, cnt, dictbits, prebits, s_tiles, w_tiles,
            interpret)
        qs, wp = _pack_split(win, qual, sus, out_segments)
        return (qs, wp, dep[:out_segments].astype(jnp.uint16),
                err[:out_segments].astype(jnp.uint16))

    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _filter_jit(out_segments: int, s_tiles: int, w_tiles: int,
                interpret: bool):
    from .kernel import _I16_MAX, _ensure_jax

    jax = _ensure_jax()
    import jax.numpy as jnp

    def fn(wire_p, seg2d, base, cnt, dictbits, prebits, min_reads_c,
           min_qual_c, lens, f_min_reads, f_emin_tab, f_min_base_q,
           f_per_base):
        win, qual, dep, err, sus = _pallas_consensus(
            wire_p, seg2d, base, cnt, dictbits, prebits, s_tiles, w_tiles,
            interpret)
        qs, wp = _pack_split(win, qual, sus, out_segments)
        # filter epilogue — _wire_filter_fn twin over the kernel's
        # (out_segments, L) planes: consensus thresholds, the integer
        # emin-table mask, and the 7-col stats reduction. All-integer →
        # bit-exact vs the XLA kernel; runs at segment scale inside the
        # same jit (the 32768-entry emin gather is why this half stays
        # out of the Pallas body — see the module docstring).
        w = win[:out_segments]
        q = qual[:out_segments]
        d = dep[:out_segments]
        e = err[:out_segments]
        sus_o = sus[:out_segments].astype(jnp.bool_)
        low_depth = d < min_reads_c
        low_qual = q < min_qual_c
        tb = jnp.where(low_depth | low_qual, N_CODE, w)
        tq = jnp.where(low_depth, 0, jnp.where(low_qual, MIN_PHRED, q))
        L = wire_p.shape[1]
        in_len = jnp.arange(L, dtype=jnp.int32)[None, :] < lens[:, None]
        d16 = jnp.minimum(d, _I16_MAX)
        e16 = jnp.minimum(e, _I16_MAX)
        fmask = (f_per_base > 0) & ((d16 < f_min_reads)
                                    | ((d16 > 0) & (e16 >= f_emin_tab[d16])))
        fmask = fmask | ((f_min_base_q >= 0) & (tq < f_min_base_q))
        fmask = fmask & in_len
        fb = jnp.where(fmask, N_CODE, tb)
        fq = jnp.where(fmask, MIN_PHRED, tq)
        z32 = jnp.int32(0)
        stats = jnp.stack([
            jnp.max(jnp.where(in_len, d16, z32), axis=1),
            jnp.sum(jnp.where(in_len, d16, z32), axis=1),
            jnp.sum(jnp.where(in_len, e16, z32), axis=1),
            jnp.sum(jnp.where(in_len, tq, z32), axis=1),
            jnp.sum((in_len & (fb == N_CODE)).astype(jnp.int32), axis=1),
            jnp.sum((fmask & (tb != N_CODE)).astype(jnp.int32), axis=1),
            jnp.any(sus_o & in_len, axis=1).astype(jnp.int32),
        ], axis=1).astype(jnp.int32)
        return (stats, fb.astype(jnp.uint8), fq.astype(jnp.uint8),
                d.astype(jnp.uint16), e.astype(jnp.uint16), qs, wp)

    return jax.jit(fn)


def _prebits(ln_error_pre_umi) -> np.ndarray:
    return np.asarray([np.float32(ln_error_pre_umi)],
                      dtype=np.float32).view(np.int32)


def call_full(prep: _Prepared, ln_error_pre_umi, out_segments: int):
    """Full-column Pallas dispatch: the _wire_full_fn contract —
    (qs u8, wp u8, depth u16, errors u16), sliced to out_segments."""
    fn = _full_jit(int(out_segments), prep.s_tiles, prep.w_tiles,
                   interpreted())
    return fn(*prep.dev, _prebits(ln_error_pre_umi))


def call_filter(prep: _Prepared, ln_error_pre_umi, min_reads_c, min_qual_c,
                lens_pad: np.ndarray, fparams, out_segments: int):
    """Fused consensus→filter Pallas dispatch: the
    ``_consensus_segments_wire_filter_jit`` contract —
    (stats i32(J,7), fb, fq, d16, e16, qs, wp)."""
    from .datapath import CONST_CACHE
    from .kernel import _ensure_jax

    jax = _ensure_jax()
    fn = _filter_jit(int(out_segments), prep.s_tiles, prep.w_tiles,
                     interpreted())
    ld = jax.device_put(np.ascontiguousarray(lens_pad, dtype=np.int32))
    etab = CONST_CACHE.put("filter_emin", fparams.emin_tab)
    return fn(*prep.dev, _prebits(ln_error_pre_umi),
              np.int32(min_reads_c), np.int32(min_qual_c), ld,
              fparams.min_reads, etab, fparams.min_base_q,
              np.int32(1 if fparams.per_base else 0))
