"""Batched TPU consensus kernel (JAX/XLA).

Re-expresses the reference's per-position scalar hot loop
(/root/reference/crates/fgumi-consensus/src/base_builder.rs:612-644,795-852 — the
reset/add/call loop at vanilla_caller.rs:1396-1437) as one fused XLA computation over a
whole batch of padded UMI families at once:

    codes (F, R, L) uint8, quals (F, R, L) uint8  ->  per-position consensus
    winner/qual/depth/errors (F, L)

Numerics strategy (SURVEY.md §7 "architecture stance"): the device computes in f32
using per-quality tables precomputed in f64 on host, with a *suspect mask*: positions
whose result could plausibly round to a different integer Phred (or whose winner margin
is within f32 noise) are flagged and recomputed on host by the f64 oracle
(fgumi_tpu.ops.oracle). This mirrors the reference's own fast-path-with-margin-gate
design (base_builder.rs:186-263): a fast path that is exact outside a guard band,
deferring to the exact computation inside it.

Key algebraic reformulation (device only; guarded by the suspect mask): the four lane
likelihoods are ll[b] = S_err + C[b], where S_err = sum over valid observations of
ln(err/3) is lane-independent and C[b] = sum over observations matching b of
(ln_correct - ln_err) >= 0 is the per-lane match contribution. Winner selection and
every posterior quantity depend only on lane *differences*, so S_err is never
materialized: gaps = C_max - C[b], s = sum_losers exp(-gap), and
ln_consensus_error = ln(s) - log1p(s). This is the same shifted-gap frame the
reference uses for its unanimous fast path (base_builder.rs:364-385) generalized to
non-unanimous positions, and it keeps f32 magnitudes at ~|C| (tens per matching read)
instead of |ll| (hundreds to thousands), which is what makes f32 viable at depth.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..constants import MAX_PHRED, MIN_PHRED, N_CODE
from .tables import QualityTables

_LN10_F32 = np.float32(np.log(10.0))
_LN_4_3_F32 = np.float32(np.log(4.0 / 3.0))
_EPS32 = np.float32(np.finfo(np.float32).eps)
_PHRED_PER_LN = np.float32(10.0 / np.log(10.0))

# Conservative multipliers for the suspect guard band; calibrated by
# tests/test_kernel_parity.py (which asserts zero integer mismatches after host
# fallback AND a bounded fallback rate).
_GUARD_C_SCALE = 16.0  # multiplier on eps32 * max(C) for the gap error estimate
_QUAL_GUARD_FLOOR = 3e-4  # minimum guard band in Phred units (< the 1e-3 precision nudge)
_TIE_GUARD_FLOOR = 1e-5  # minimum winner-margin guard in ln units


def _reduce_contributions(codes, quals, correct_tab, err_tab):
    """Per-position match-contribution + observation-count reduction over reads.

    codes/quals: (..., R, L). Returns C (..., L, 4) f32 (lane match contributions),
    obs (..., L, 4) int32. N/pad codes contribute nothing (base_builder.rs:616-619).
    """
    q_idx = jnp.minimum(quals, MAX_PHRED).astype(jnp.int32)
    delta_tab = correct_tab - err_tab  # (94,) f32, >= 0 for sane rates
    valid = codes != N_CODE
    one_hot = jax.nn.one_hot(jnp.minimum(codes, 3), 4, dtype=jnp.float32)
    one_hot = one_hot * valid[..., None].astype(jnp.float32)
    delta = jnp.where(valid, delta_tab[q_idx], 0.0)  # (..., R, L)
    contrib = jnp.einsum("...rl,...rlb->...lb", delta, one_hot)
    obs = jnp.sum(one_hot, axis=-3).astype(jnp.int32)  # (..., L, 4)
    return contrib, obs


def _call_epilogue(contrib, obs, ln_error_pre_umi):
    """Winner/tie/posterior/Phred epilogue over (..., L, 4) lane contributions.

    Returns winner (int32, N_CODE for no-call), qual (int32), depth, errors (int32),
    suspect (bool): positions requiring f64 host recomputation.
    """
    depth = jnp.sum(obs, axis=-1)
    max_c = jnp.max(contrib, axis=-1)
    winner = jnp.argmax(contrib, axis=-1).astype(jnp.int32)
    lane_is_winner = jax.nn.one_hot(winner, 4, dtype=jnp.bool_)

    # Loser-gap frame: s = sum over losing lanes of exp(-(max - C_b)).
    gaps = max_c[..., None] - contrib  # >= 0; 0 at the winner lane
    exp_neg = jnp.where(lane_is_winner, 0.0, jnp.exp(-gaps))
    s = jnp.sum(exp_neg, axis=-1)
    # ln consensus error = ln(s / (1 + s)); s == 0 underflows to -inf (cap region).
    ln_cons_err = jnp.log(s) - jnp.log1p(s)

    # two-trials combination with the pre-UMI prior (phred.rs:248-267), f32.
    pre = jnp.float32(ln_error_pre_umi)
    hi = jnp.maximum(pre, ln_cons_err)
    lo = jnp.minimum(pre, ln_cons_err)
    diff = hi - lo
    quick = ~(diff < 6.0)  # catches NaN (lo = -inf) as quick
    safe_diff = jnp.where(quick, 6.0, diff)
    term1 = hi + jnp.log1p(jnp.exp(-safe_diff))  # ln(exp(hi) + exp(lo))
    term2_minus_term1 = _LN_4_3_F32 + lo - jnp.log1p(jnp.exp(-safe_diff))
    full = term1 + jnp.log1p(-jnp.exp(jnp.minimum(term2_minus_term1, -_EPS32)))
    ln_final = jnp.where(quick, hi, full)

    phred_f = -ln_final * _PHRED_PER_LN + 0.001
    qual = jnp.clip(jnp.floor(phred_f), MIN_PHRED, MAX_PHRED).astype(jnp.int32)

    # ---- suspect guard band ----
    eps_gap = _GUARD_C_SCALE * _EPS32 * (1.0 + max_c)
    # winner margin: distance between best and second-best lane contribution
    second = jnp.max(jnp.where(lane_is_winner, -jnp.inf, contrib), axis=-1)
    margin = max_c - second
    tie_suspect = margin <= (2.0 * eps_gap + _TIE_GUARD_FLOOR)
    # Phred rounding proximity. The ln_final error is ~eps_gap on the consensus-error
    # path; when the quick path selected the pre-UMI constant the result is exact.
    took_pre = quick & (ln_cons_err < pre)
    err_phred = jnp.where(took_pre, 0.0, _PHRED_PER_LN * 2.0 * eps_gap)
    frac = phred_f - jnp.floor(phred_f)
    near_boundary = jnp.minimum(frac, 1.0 - frac) <= (err_phred + _QUAL_GUARD_FLOOR)
    clamped = (phred_f <= MIN_PHRED) | (phred_f >= MAX_PHRED + 0.5)
    # The quick-vs-full two-trials branch (diff >= 6) is decided in f32 here but f64
    # in the oracle; the formulas differ by up to ln(1+e^-6) ≈ 0.0215 Phred at the
    # boundary, so positions near it must fall back.
    branch_suspect = jnp.abs(diff - 6.0) <= (2.0 * eps_gap + 1e-4)
    # Non-finite contributions (a Q0 observation's -inf table entry times the one-hot
    # zero gives NaN through the einsum) poison every comparison below into False;
    # force those positions to the exact host path.
    nonfinite = ~jnp.isfinite(max_c)
    suspect = tie_suspect | branch_suspect | nonfinite | (near_boundary & ~clamped)

    no_call = depth == 0
    winner = jnp.where(no_call | tie_suspect, N_CODE, winner)
    qual = jnp.where(no_call | tie_suspect, MIN_PHRED, qual)
    suspect = suspect & ~no_call

    winner_obs = jnp.sum(obs * lane_is_winner.astype(jnp.int32), axis=-1)
    errors = depth - jnp.where(winner == N_CODE, 0, winner_obs)
    return winner, qual, depth, errors, suspect


@jax.jit
def _consensus_batch_jit(codes, quals, correct_tab, err_tab, ln_error_pre_umi):
    contrib, obs = _reduce_contributions(codes, quals, correct_tab, err_tab)
    return _call_epilogue(contrib, obs, ln_error_pre_umi)


class ConsensusKernel:
    """Compiled batched consensus caller for one (pre, post) error-rate pair.

    Call with padded uint8 arrays codes/quals of shape (F, R, L); returns NumPy
    arrays (winner, qual, depth, errors) with all suspect positions already
    recomputed on host by the f64 oracle, so results are integer-exact against
    fgumi_tpu.ops.oracle by construction.
    """

    def __init__(self, tables: QualityTables):
        self.tables = tables
        self._correct_f32 = jnp.asarray(tables.adjusted_correct, dtype=jnp.float32)
        self._err_f32 = jnp.asarray(tables.adjusted_error_per_alt, dtype=jnp.float32)
        self._pre = np.float32(tables.ln_error_pre_umi)
        self.fallback_positions = 0
        self.total_positions = 0

    def device_call(self, codes, quals):
        """Raw device outputs (winner, qual, depth, errors, suspect) as jax arrays."""
        return _consensus_batch_jit(
            jnp.asarray(codes), jnp.asarray(quals), self._correct_f32, self._err_f32, self._pre
        )

    def __call__(self, codes: np.ndarray, quals: np.ndarray):
        winner, qual, depth, errors, suspect = jax.device_get(
            self.device_call(codes, quals)
        )
        winner = winner.astype(np.uint8)
        qual = qual.astype(np.uint8)
        depth = depth.astype(np.int64)
        errors = errors.astype(np.int64)
        self.total_positions += suspect.size
        n_suspect = int(suspect.sum())
        if n_suspect:
            self.fallback_positions += n_suspect
            self._host_fallback(codes, quals, winner, qual, depth, errors, suspect)
        return winner, qual, depth, errors

    def _host_fallback(self, codes, quals, winner, qual, depth, errors, suspect):
        """Recompute suspect positions exactly with the f64 oracle (in place)."""
        from . import oracle

        fam_idx, pos_idx = np.nonzero(suspect)
        for f in np.unique(fam_idx):
            positions = pos_idx[fam_idx == f]
            sub_codes = np.ascontiguousarray(codes[f][:, positions])
            sub_quals = np.ascontiguousarray(quals[f][:, positions])
            w, q, d, e = oracle.call_family(sub_codes, sub_quals, self.tables)
            winner[f, positions] = w
            qual[f, positions] = q
            depth[f, positions] = d
            errors[f, positions] = e
