"""Batched TPU consensus kernel (JAX/XLA).

Re-expresses the reference's per-position scalar hot loop
(/root/reference/crates/fgumi-consensus/src/base_builder.rs:612-644,795-852 — the
reset/add/call loop at vanilla_caller.rs:1396-1437) as one fused XLA computation over a
whole batch of padded UMI families at once:

    codes (F, R, L) uint8, quals (F, R, L) uint8  ->  per-position consensus
    winner/qual/depth/errors (F, L)

Numerics strategy (SURVEY.md §7 "architecture stance"): the device computes in f32
using per-quality tables precomputed in f64 on host, with a *suspect mask*: positions
whose result could plausibly round to a different integer Phred (or whose winner margin
is within f32 noise) are flagged and recomputed on host by the f64 oracle
(fgumi_tpu.ops.oracle). This mirrors the reference's own fast-path-with-margin-gate
design (base_builder.rs:186-263): a fast path that is exact outside a guard band,
deferring to the exact computation inside it.

Key algebraic reformulation (device only; guarded by the suspect mask): the four lane
likelihoods are ll[b] = S_err + C[b], where S_err = sum over valid observations of
ln(err/3) is lane-independent and C[b] = sum over observations matching b of
(ln_correct - ln_err) >= 0 is the per-lane match contribution. Winner selection and
every posterior quantity depend only on lane *differences*, so S_err is never
materialized: gaps = C_max - C[b], s = sum_losers exp(-gap), and
ln_consensus_error = ln(s) - log1p(s). This is the same shifted-gap frame the
reference uses for its unanimous fast path (base_builder.rs:364-385) generalized to
non-unanimous positions, and it keeps f32 magnitudes at ~|C| (tens per matching read)
instead of |ll| (hundreds to thousands), which is what makes f32 viable at depth.
"""

import collections
import logging
import threading
import time
from functools import wraps

import numpy as np

log = logging.getLogger("fgumi_tpu")

# jax is imported lazily (_ensure_jax): a CPU-pinned run that routes every
# dispatch to the native f64 host engine (host_kernel.py) never pays the
# ~2s jax import — which lands on every stage of a multi-process chain.
# The module globals `jax`/`jnp` start as import-on-first-touch proxies and
# are rebound to the real modules by _ensure_jax, so traced bodies resolve
# them normally at trace time — including when an external module (e.g.
# parallel/mesh.py) wraps this module's body functions in its own jit
# without ever calling a lazy-jit entry point here.
_jax_ready = False


class _LazyJaxProxy:
    def __init__(self, which):
        self._which = which

    def __getattr__(self, attr):
        _ensure_jax()
        return getattr(jax if self._which == "jax" else jnp, attr)


jax = _LazyJaxProxy("jax")
jnp = _LazyJaxProxy("jnp")


def _ensure_jax():
    global jax, jnp, _jax_ready
    if not _jax_ready:
        import jax as _jax
        import jax.numpy as _jnp

        jax = _jax
        jnp = _jnp
        _jax_ready = True
        # before the first jit compile so device executables land on disk
        # and compile events are counted (device.backend_compiles — the
        # warm-kernel evidence the serve daemon's smoke gate asserts on)
        _enable_persistent_compile_cache()
        from ..observe import compilewatch

        compilewatch.install()
    return jax


def shard_map_compat(*args, **kwargs):
    """jax.shard_map across the API move: the public alias appears in
    jax >= 0.5; on 0.4.x only jax.experimental.shard_map exists. One
    shim so every sharded kernel keeps working on both."""
    _ensure_jax()
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
    return fn(*args, **kwargs)


def _lazy_jit(fn=None, *, static_argnames=(), donate_argnums=()):
    """@jax.jit that defers both the jax import and the jit wrapping to the
    first call (same compiled-function caching afterwards).
    ``donate_argnums``: forwarded to jax.jit — the upload-donation variants
    of the wire kernels pass their input-buffer argnums so XLA may reuse
    the uploaded pages for outputs/temporaries instead of allocating fresh
    device memory per dispatch (SNIPPETS [1]/[3] pattern)."""
    def deco(f):
        box = []

        @wraps(f)
        def wrapper(*a, **k):
            if not box:
                _ensure_jax()
                kwargs = {}
                if static_argnames:
                    kwargs["static_argnames"] = static_argnames
                if donate_argnums:
                    kwargs["donate_argnums"] = donate_argnums
                box.append(jax.jit(f, **kwargs))
            return box[0](*a, **k)

        return wrapper

    return deco(fn) if fn is not None else deco


def upload_donation_enabled() -> bool:
    """Whether wire-upload buffers are donated to the consensus jits.

    ``FGUMI_TPU_DONATE=1/0`` forces; the default (``auto``) donates on any
    non-CPU backend — the CPU backend ignores donation with a per-call
    warning, so auto keeps host-only runs quiet. Read per dispatch (cheap)
    so tests can flip it between in-process runs."""
    import os

    v = os.environ.get("FGUMI_TPU_DONATE", "auto").strip().lower()
    if v in ("1", "true", "on", "force"):
        return True
    if v in ("0", "false", "off"):
        return False
    _ensure_jax()
    return jax.default_backend() != "cpu"

from ..constants import MAX_PHRED, MIN_PHRED, N_CODE
from .datapath import CONST_CACHE, SHAPE_REGISTRY, as_device_operand
from .tables import QualityTables

def _enable_persistent_compile_cache():
    """Cross-process XLA compile cache (kernel shapes are a small fixed set,
    so warm-up compiles amortize to ~zero across CLI invocations). Called at
    ConsensusKernel construction, not import, so merely importing the library
    never mutates global jax config. One shared implementation with the CLI
    (utils/compile_cache.py); opt out with FGUMI_TPU_NO_XLA_CACHE=1."""
    from ..utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()

_LN10_F32 = np.float32(np.log(10.0))
_LN_4_3_F32 = np.float32(np.log(4.0 / 3.0))
_EPS32 = np.float32(np.finfo(np.float32).eps)
_PHRED_PER_LN = np.float32(10.0 / np.log(10.0))

# ---------------------------------------------------------------------------
# Suspect guard band — derivation (the analog of the reference's fast-path
# margin proof, base_builder.rs:186-301).
#
# Sources of f32 error in a lane contribution C[b] = sum over matching
# observations of delta[q] (delta = ln_correct - ln_err >= 0, from tables
# computed in f64 and rounded once to f32):
#
#   (1) table rounding:  |fl(delta) - delta| <= eps32/2 * delta per term;
#   (2) accumulation:    summing n nonnegative terms in ANY order (XLA may
#       reduce sequentially or as a tree) has error <= eps32 * n * sum(x_i)
#       to first order, since every partial sum is <= the final sum for
#       nonnegative terms. sum(x_i) = C[b] <= max_c.
#
# A position's lane has at most `depth` matching observations, so
#   |C_err| <= eps32 * (depth + 1) * max_c.
# The gap g = max_c - C[b] adds one subtraction (a half-ulp of max_c) and is
# computed from two such sums, giving the per-gap bound used below:
#   |g_err| <= eps_gap = eps32 * (depth + 2) * (1 + max_c),
# where the "+1" inside the parenthesis covers max_c < 1 (absolute floor).
# This is depth-aware on purpose: a fixed multiplier is unsound for deep
# families (n grows) and wastefully wide for shallow ones.
#
# Downstream of the gaps:
#   s = sum over losing lanes of exp(-g): |ds| <= s * eps_gap + O(eps32)*s
#       (exp is 1-ulp; d exp(-g) = exp(-g) |dg|);
#   ln_cons_err = ln(s) - log1p(s): |d| <= |ds|/s + |ds|/(1+s) + 2 ulp
#       <= 2 * eps_gap + O(eps32).
# So the Phred-scale error is  err_phred <= PHRED_PER_LN * 2 * eps_gap plus
# a handful of 1-ulp function evaluations; PHRED_PER_LN * 5 * eps32 ~ 2.6e-6,
# absorbed by _QUAL_GUARD_FLOOR = 3e-4 (kept < the 0.001 fgbio precision
# nudge so the floor can never mask the intended rounding offset).
#
# Guard gates (any triggers the exact f64 host recompute):
#   tie:      winner margin <= 2 * eps_gap + _TIE_GUARD_FLOOR  (the margin is
#             a difference of two gap-accurate quantities; the floor covers
#             exact-tie ulp jitter);
#   quality:  distance of phred_f to the nearest integer boundary <=
#             err_phred + _QUAL_GUARD_FLOOR;
#   branch:   |diff - 6| within the gap error of the f32/f64 quick-path
#             disagreement region of the two-trials combination;
#   NaN:      any non-finite contribution (Q0 -inf table entries).
#
# tests/test_kernel_parity.py + the adversarial edge sweep in
# tests/test_guard_band.py assert the safety property this analysis promises:
# no non-suspect position ever disagrees with the f64 oracle.
# ---------------------------------------------------------------------------
_QUAL_GUARD_FLOOR = 3e-4  # Phred units; absorbs O(eps32) evaluation error
_TIE_GUARD_FLOOR = 1e-5  # ln units; exact-tie ulp jitter

# sentinel returned by device_call_segments in host mode: the resolve half
# runs the native f64 engine on the rows it receives (no device round-trip)
HOST_DISPATCH = ("host-dispatch",)


class DeadlineExceeded(Exception):
    """A device dispatch overran its deadline and was abandoned.

    Raised by the deadline-aware waits in the resolve paths — never by the
    device itself. The batch reroutes to the native f64 host engine
    (byte-identical by construction) and the breaker records a wedge."""


def _deadline_bounds():
    """(floor_s, ceiling_s) from ``FGUMI_TPU_DISPATCH_DEADLINE_S``, or
    (None, None) when dispatch deadlines are disabled.

    Accepted forms: ``""`` (defaults 30:300), ``"CEILING"``,
    ``"FLOOR:CEILING"``, or ``0``/``off``/``inf`` to disable. The floor
    absorbs first-dispatch XLA compiles (which run inside the dispatch
    wall); the ceiling bounds what a wedged chip can cost even when the
    cost model has no prediction yet."""
    import os

    spec = os.environ.get("FGUMI_TPU_DISPATCH_DEADLINE_S", "").strip().lower()
    if spec in ("off", "none", "inf"):
        return None, None
    floor, ceil = 30.0, 300.0
    if spec:
        try:
            parts = [float(p) for p in spec.split(":", 1)]
        except ValueError:
            log.warning("FGUMI_TPU_DISPATCH_DEADLINE_S=%r is not "
                        "S or FLOOR:CEILING; using the default", spec)
            return floor, ceil
        if len(parts) == 1:
            ceil = parts[0]
            floor = min(floor, ceil)
        else:
            floor, ceil = parts
        if ceil <= 0:
            return None, None
        floor = min(max(floor, 0.01), ceil)
    return floor, ceil


def dispatch_deadline_s(pred_s=None):
    """Deadline (seconds) for one dispatch's resolve wait, or None when
    disabled. ``pred_s``: the router cost model's predicted dispatch wall
    — the deadline is predicted wall x safety factor
    (``FGUMI_TPU_DEADLINE_FACTOR``, default 20), clamped to the
    floor/ceiling; with no prediction the ceiling applies."""
    import os

    floor, ceil = _deadline_bounds()
    if ceil is None:
        return None
    if pred_s is None or pred_s <= 0:
        return ceil
    try:
        factor = float(os.environ.get("FGUMI_TPU_DEADLINE_FACTOR", "20"))
    except ValueError:
        factor = 20.0
    return min(max(pred_s * factor, floor), ceil)


def use_host_engine() -> bool:
    """Whether consensus dispatches route to the native f64 host engine.

    Uncached on purpose (kernel instances cache per-instance): tests flip
    FGUMI_TPU_HOST_ENGINE between in-process CLI runs. Env semantics as in
    ConsensusKernel.host_mode."""
    import os

    env = os.environ.get("FGUMI_TPU_HOST_ENGINE", "auto").lower()
    from ..native import batch as nb

    if env in ("1", "true", "force"):
        if not nb.available():
            import logging

            logging.getLogger("fgumi_tpu").warning(
                "FGUMI_TPU_HOST_ENGINE=1 but the native library is "
                "unavailable; using the device kernel")
        return nb.available()
    if env in ("0", "false", "off"):
        return False
    if not nb.available():
        return False
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # CPU explicitly pinned: decide without importing jax (the whole
        # point of host mode on a multi-process chain)
        return True
    _ensure_jax()
    return jax.default_backend() == "cpu"

def device_path() -> str:
    """Which device route the engines use for whole batches: ``"full"``
    (the 1-byte-wire full-column kernel; round-6 default) or ``"columns"``
    (the round-5 classify-and-export-hard-columns path, kept for A/B
    comparison via FGUMI_TPU_DEVICE_PATH=columns)."""
    import os

    v = os.environ.get("FGUMI_TPU_DEVICE_PATH", "full").strip().lower()
    return v if v in ("full", "columns") else "full"


# bf16 systolic peak FLOP/s and HBM GB/s per chip, keyed by substrings of
# jax device_kind — for the MFU/bandwidth utilization estimate below. The
# consensus kernel is VPU/elementwise-dominated, so low MFU is expected and
# bandwidth is the honest utilization axis; both are reported.
_DEVICE_PEAKS = {"v5e": (197e12, 819e9), "v5p": (459e12, 2765e9),
                 "v4": (275e12, 1228e9), "v6": (918e12, 1640e9)}


class DeviceStats:
    """Device-interaction accounting (the §5.1 analog of PipelineStats'
    per-step timers, scoped to the device boundary): dispatch count, host
    time blocked on fetch (dispatch-to-fetch on an async backend ==
    remaining compute + transfer), bytes fetched, and a model-FLOP tally
    from the dispatched shapes. Thread-safe; one module-wide instance
    aggregates across kernels so a CLI run can report a single device
    fraction regardless of how many callers it built."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        """Zero the counters (e.g. between a warm-up and a timed run)."""
        self.dispatches = 0
        self.fetch_wait_s = 0.0
        self.bytes_fetched = 0
        self.bytes_uploaded = 0
        self.model_flops = 0
        self.rows_real = 0
        self.rows_padded = 0
        self.in_flight = 0
        # resilience accounting (retry / degrade path, docs/resilience.md):
        # transient-dispatch retries, RESOURCE_EXHAUSTED batch halvings,
        # whole-batch falls back to the native f64 host engine, and batches
        # abandoned at their dispatch deadline (self-healing layer)
        self.retries = 0
        self.batch_splits = 0
        self.host_fallbacks = 0
        self.deadline_fallbacks = 0
        # pipelined-upload accounting (docs/device-datapath.md): feeder-fn
        # seconds that overlapped an earlier dispatch's device compute, the
        # feeder queue's high-water mark, and constant-cache traffic
        self.upload_overlap_s = 0.0
        self.feeder_queue_peak = 0
        self.const_uploads = 0
        self.const_hits = 0
        self.const_upload_bytes = 0
        # adaptive-offload accounting (ops/router.py): batches routed to
        # the device vs the native f64 host engine
        self.route_device = 0
        self.route_host = 0
        # device-resident pipeline accounting (ISSUE 11): dispatches whose
        # upload buffers were donated to XLA, and the live/peak bytes of
        # ResidentHandles arrays pinned on the device between stages
        self.donated_uploads = 0
        self.resident_bytes = 0
        self.resident_bytes_peak = 0
        # kernel-backend accounting (ISSUE 19): wire dispatches executed
        # by the hand-tiled Pallas kernel vs the XLA-lowered oracle
        self.kernel_pallas = 0
        self.kernel_xla = 0
        self.timeline = []  # per-dispatch dicts (capped; --stats report)
        # stamps for dispatches past the timeline cap, alive only until
        # resolve (begin_in_flight/end_in_flight; bounded)
        self._tail_entries = {}
        self._next_slot = 0
        self._t0 = time.monotonic()

    def add_retry(self):
        with self._lock:
            self.retries += 1

    def add_split(self):
        with self._lock:
            self.batch_splits += 1

    def add_host_fallback(self):
        with self._lock:
            self.host_fallbacks += 1

    def add_deadline_fallback(self):
        with self._lock:
            self.deadline_fallbacks += 1
            n = self.deadline_fallbacks
            dispatches = self.dispatches
        # the wedge signature: note it in the always-on flight ring and —
        # when a dump dir is configured — freeze a black box naming the
        # still-unresolved dispatch(es). Outside the stats lock: the dump
        # re-enters snapshot()/timeline_snapshot().
        from ..observe.flight import FLIGHT

        FLIGHT.note("device.deadline_fallback", count=n,
                    dispatches=dispatches)
        FLIGHT.dump("dispatch-deadline", deadline_fallbacks=n)

    def add_upload_overlap(self, dt: float):
        with self._lock:
            self.upload_overlap_s += dt

    def note_queue_depth(self, depth: int):
        with self._lock:
            if depth > self.feeder_queue_peak:
                self.feeder_queue_peak = depth

    def add_const_upload(self, nbytes: int):
        with self._lock:
            self.const_uploads += 1
            self.const_upload_bytes += int(nbytes)

    def add_const_hit(self):
        with self._lock:
            self.const_hits += 1

    def add_donated_upload(self):
        with self._lock:
            self.donated_uploads += 1

    def add_kernel_backend(self, slot: int, backend: str):
        """Record which kernel backend ran a wire dispatch (ISSUE 19):
        counter + timeline stamp, so a flight dump on a wedge names the
        kernel that wedged."""
        with self._lock:
            if backend == "pallas":
                self.kernel_pallas += 1
            else:
                self.kernel_xla += 1
            entry = self._entry_locked(slot)
            if entry is not None:
                entry["kernel_backend"] = backend
        from ..observe.metrics import METRICS

        METRICS.inc(f"device.kernel.{backend}")

    def add_resident_bytes(self, n: int):
        with self._lock:
            self.resident_bytes += int(n)
            if self.resident_bytes > self.resident_bytes_peak:
                self.resident_bytes_peak = self.resident_bytes
            now = self.resident_bytes
        from ..observe.metrics import METRICS

        METRICS.set("device.resident_bytes", now)

    def release_resident_bytes(self, n: int):
        with self._lock:
            self.resident_bytes -= int(n)
            now = self.resident_bytes
        from ..observe.metrics import METRICS

        METRICS.set("device.resident_bytes", now)

    def add_route(self, side: str):
        with self._lock:
            if side == "device":
                self.route_device += 1
            else:
                self.route_host += 1

    def add_dispatch(self, flops: int):
        with self._lock:
            self.dispatches += 1
            self.model_flops += int(flops)

    def begin_in_flight(self, upload_bytes: int, pack_s: float = 0.0) -> int:
        """Count a dispatch in flight (host->device submitted, result not
        yet fetched). Returns a timeline slot id for end_in_flight."""
        from ..observe import xprof

        if xprof.armed():  # one-shot --xla-profile capture (off: one call)
            xprof.on_dispatch_begin()
        with self._lock:
            self.in_flight += 1
            self.bytes_uploaded += int(upload_bytes)
            slot = self._next_slot
            self._next_slot += 1
            entry = {"t_dispatch": round(time.monotonic() - self._t0, 4),
                     "up_bytes": int(upload_bytes),
                     "pack_s": round(pack_s, 4)}
            if slot < 4096:
                self.timeline.append(entry)
            elif len(self._tail_entries) < 1024:
                # past the persistent-timeline cap, stamps live only until
                # resolve (end_in_flight pops them) so latency histograms
                # and router feedback keep working on arbitrarily long
                # runs; the side map is bounded against abandon leaks
                self._tail_entries[slot] = entry
            return slot

    def _entry_locked(self, slot: int):
        """The live entry for a slot — persistent timeline or tail map —
        or None. Caller holds the lock."""
        if 0 <= slot < len(self.timeline):
            return self.timeline[slot]
        return self._tail_entries.get(slot)

    def note_upload(self, slot: int, upload_s: float):
        """Record a dispatch's device_put wall time (feeder thread)."""
        with self._lock:
            entry = self._entry_locked(slot)
            if entry is not None:
                entry["upload_s"] = round(upload_s, 4)

    def note_exec(self, slot: int):
        """Stamp upload+enqueue completion: the window from here to fetch
        start is device compute overlapped with host work."""
        with self._lock:
            entry = self._entry_locked(slot)
            if entry is not None:
                entry["t_exec"] = round(time.monotonic() - self._t0, 4)

    def note_pred(self, slot: int, pred_s: float):
        """Stamp the cost model's predicted dispatch time (ops/router.py)
        so BENCH artifacts carry predicted vs actual per dispatch."""
        with self._lock:
            entry = self._entry_locked(slot)
            if entry is not None:
                entry["pred_s"] = round(pred_s, 4)

    def note_mesh(self, slot: int, shards: int, shard_bytes: int,
                  psums: int):
        """Stamp a mesh dispatch's shard geometry into the timeline: shard
        count, upload bytes per shard, and hot-path psum count (0 on a
        dp-only mesh — families are independent; 2 with sp > 1: the
        contribution and observation combines)."""
        with self._lock:
            entry = self._entry_locked(slot)
            if entry is not None:
                entry["shards"] = int(shards)
                entry["shard_up_bytes"] = int(shard_bytes)
                entry["psums"] = int(psums)

    def timeline_entry(self, slot: int):
        """Copy of one timeline slot (router feedback at resolve time)."""
        with self._lock:
            entry = self._entry_locked(slot)
            return dict(entry) if entry is not None else None

    def end_in_flight(self, slot: int, fetched_bytes: int, wait_s: float):
        entry = None
        with self._lock:
            self.in_flight -= 1
            live = self._entry_locked(slot)
            if live is not None:
                live.update(
                    t_fetched=round(time.monotonic() - self._t0, 4),
                    down_bytes=int(fetched_bytes),
                    fetch_wait_s=round(wait_s, 4))
                entry = dict(live)
                self._tail_entries.pop(slot, None)
        if entry is not None:
            _observe_dispatch_latency(entry)

    def in_flight_count(self) -> int:
        with self._lock:
            return self.in_flight

    def add_pad(self, real_rows: int, padded_rows: int):
        """Padding-waste accounting: real vs device-layout rows per dispatch
        (ragged-batch economics, SURVEY hard-part #2)."""
        with self._lock:
            self.rows_real += int(real_rows)
            self.rows_padded += int(padded_rows)

    def add_fetch(self, nbytes: int, wait_s: float):
        """Credit fetch accounting without performing the device_get: the
        coalescer fetches a merged result once and attributes each
        partner's byte share + measured resolve wait to the partner's own
        scope (ops/coalesce.py)."""
        with self._lock:
            self.fetch_wait_s += float(wait_s)
            self.bytes_fetched += int(nbytes)

    def fetch(self, dev):
        """Timed jax.device_get — route every device->host fetch through
        here so fetch_wait_s captures all host time blocked on the device.
        Accepts a single array or a tuple (fetched in one device_get)."""
        from ..observe.trace import span

        _ensure_jax()
        t0 = time.monotonic()
        with span("device.fetch") as sp:
            got = jax.device_get(dev)
            if isinstance(got, (tuple, list)):
                out = tuple(np.asarray(g) for g in got)
                nbytes = sum(g.nbytes for g in out)
            else:
                out = np.asarray(got)
                nbytes = out.nbytes
            sp.set(bytes=nbytes)
        dt = time.monotonic() - t0
        with self._lock:
            self.fetch_wait_s += dt
            self.bytes_fetched += nbytes
        return out

    def snapshot(self):
        with self._lock:
            out = {"dispatches": self.dispatches,
                   "fetch_wait_s": round(self.fetch_wait_s, 3),
                   "bytes_fetched": self.bytes_fetched,
                   "model_gflops": round(self.model_flops / 1e9, 3)}
            if self.bytes_uploaded:
                out["bytes_uploaded"] = self.bytes_uploaded
            if self.rows_padded:
                out["pad_rows_real"] = self.rows_real
                out["pad_rows_device"] = self.rows_padded
                out["padding_waste"] = round(
                    self.rows_padded / max(self.rows_real, 1) - 1.0, 4)
            if self.retries:
                out["dispatch_retries"] = self.retries
            if self.batch_splits:
                out["batch_splits"] = self.batch_splits
            if self.host_fallbacks:
                out["host_fallbacks"] = self.host_fallbacks
            if self.deadline_fallbacks:
                out["deadline_fallbacks"] = self.deadline_fallbacks
            if self.upload_overlap_s:
                out["upload_overlap_s"] = round(self.upload_overlap_s, 3)
            if self.feeder_queue_peak:
                out["feeder_queue_depth"] = self.feeder_queue_peak
            if self.const_uploads or self.const_hits:
                out["const_uploads"] = self.const_uploads
                out["const_hits"] = self.const_hits
                out["const_upload_bytes"] = self.const_upload_bytes
            if self.route_device or self.route_host:
                out["route_device"] = self.route_device
                out["route_host"] = self.route_host
            if self.donated_uploads:
                out["donated_uploads"] = self.donated_uploads
            if self.kernel_pallas or self.kernel_xla:
                out["kernel_pallas"] = self.kernel_pallas
                out["kernel_xla"] = self.kernel_xla
            if self.resident_bytes_peak:
                out["resident_bytes_peak"] = self.resident_bytes_peak
                if self.resident_bytes:
                    out["resident_bytes"] = self.resident_bytes
            return out

    def timeline_snapshot(self):
        """Per-dispatch device timeline for the --stats report (VERDICT r4
        item 9): dispatch time, upload/fetch bytes, fetch wait each.
        Entries carry their ``slot``; past the persistent cap the live
        (still-in-flight) tail-map entries are appended in slot order, so
        a flight dump on a >4096-dispatch run still names the wedged
        dispatch instead of showing only ancient history."""
        with self._lock:
            out = [dict(t, slot=i) for i, t in enumerate(self.timeline)]
            out.extend(dict(self._tail_entries[s], slot=s)
                       for s in sorted(self._tail_entries))
            return out

    def load_from(self, other: "DeviceStats"):
        """Adopt another instance's counters wholesale (scope publishing:
        a finished command's per-scope stats become the process-global view
        that bench/probe harnesses read after cli_main)."""
        with other._lock:
            state = {k: getattr(other, k) for k in (
                "dispatches", "fetch_wait_s", "bytes_fetched",
                "bytes_uploaded", "model_flops", "rows_real", "rows_padded",
                "in_flight", "retries", "batch_splits", "host_fallbacks",
                "deadline_fallbacks",
                "upload_overlap_s", "feeder_queue_peak", "const_uploads",
                "const_hits", "const_upload_bytes", "route_device",
                "route_host", "donated_uploads", "resident_bytes",
                "resident_bytes_peak", "kernel_pallas", "kernel_xla",
                "_t0", "_next_slot")}
            timeline = [dict(t) for t in other.timeline]
            tail = {s: dict(t) for s, t in other._tail_entries.items()}
        with self._lock:
            for k, v in state.items():
                setattr(self, k, v)
            self.timeline = timeline
            self._tail_entries = tail

    def format_summary(self, wall_s: float = None) -> str:
        s = self.snapshot()
        parts = [f"device: {s['dispatches']} dispatches, "
                 f"fetch-wait {s['fetch_wait_s']:.3f}s, "
                 f"{s['bytes_fetched'] / 1e6:.1f} MB fetched, "
                 f"model {s['model_gflops']:.2f} GFLOP"]
        if self.fetch_wait_s > 0 and _jax_ready:
            gfs = self.model_flops / self.fetch_wait_s / 1e9
            parts.append(f"~{gfs:.1f} GFLOP/s incl. transfer")
            kind = getattr(jax.devices()[0], "device_kind", "").lower()
            for key, (peak_f, _peak_b) in _DEVICE_PEAKS.items():
                if key in kind:
                    parts.append(
                        f"MFU ~{100.0 * gfs * 1e9 / peak_f:.4f}%")
                    break
        if wall_s:
            parts.append(f"device fraction {self.fetch_wait_s / wall_s:.2%} "
                         f"of {wall_s:.2f}s wall")
        return "; ".join(parts)


def _observe_dispatch_latency(entry: dict) -> None:
    """Fold one resolved dispatch's timeline stamps into the latency
    histograms (observe/metrics.py): per-dispatch pack/upload/compute/fetch
    walls, the end-to-end dispatch wall, and the offload cost model's
    predicted-vs-actual error. Called once per resolve, outside the
    DeviceStats lock."""
    from ..observe import xprof
    from ..observe.metrics import METRICS

    if xprof.armed():  # close an in-flight --xla-profile capture
        xprof.on_dispatch_end()

    METRICS.observe("device.dispatch.pack_s", entry.get("pack_s", 0.0))
    if "upload_s" in entry:
        METRICS.observe("device.dispatch.upload_s", entry["upload_s"])
    fetch_s = entry.get("fetch_wait_s", 0.0)
    METRICS.observe("device.dispatch.fetch_s", fetch_s)
    # per-dispatch fetched bytes (ISSUE 11): makes the fused-filter
    # "bytes-fetched reduced >= 5x" claim machine-readable from any run
    # report (device.dispatch.fetch_bytes histogram + the bytes_fetched
    # counter the device section already carries)
    METRICS.observe("device.dispatch.fetch_bytes",
                    entry.get("down_bytes", 0))
    t_fetched = entry.get("t_fetched")
    if t_fetched is not None and "t_exec" in entry:
        METRICS.observe("device.dispatch.compute_s",
                        max(t_fetched - fetch_s - entry["t_exec"], 0.0))
    if t_fetched is not None and "t_dispatch" in entry:
        wall = max(t_fetched - entry["t_dispatch"], 0.0)
        METRICS.observe("device.dispatch.wall_s", wall)
        pred = entry.get("pred_s")
        if pred is not None:
            METRICS.observe("device.router.pred_err_s", abs(wall - pred))
        # always-on dispatch history for the flight ring: a black box from
        # a run without --trace still shows the recent device activity
        # leading up to the failure (one note per dispatch, not per record)
        from ..observe.flight import FLIGHT

        FLIGHT.note("device.dispatch", wall_s=round(wall, 4),
                    up_bytes=entry.get("up_bytes", 0),
                    down_bytes=entry.get("down_bytes", 0),
                    kernel=entry.get("kernel_backend", "xla"))


#: Fallback instance used when no telemetry scope is active (library use,
#: tests, plain single-command CLI runs).
_GLOBAL_DEVICE_STATS = DeviceStats()


class _DeviceStatsProxy:
    """Scope-resolving stand-in for the old module-wide DeviceStats.

    Every attribute access (method or counter) resolves the active
    telemetry scope (observe.scope) first — one DeviceStats per daemon job
    — and falls back to the process-global instance, so the dozens of
    existing ``DEVICE_STATS.xxx`` call sites keep working unchanged while
    two concurrent jobs in one process never share counters."""

    __slots__ = ()

    @staticmethod
    def _target() -> DeviceStats:
        from ..observe.scope import current_scope

        scope = current_scope()
        if scope is not None:
            return scope.device_stats(DeviceStats)
        return _GLOBAL_DEVICE_STATS

    def __getattr__(self, name):
        return getattr(self._target(), name)

    def __setattr__(self, name, value):
        # tests monkeypatch counters (e.g. in_flight) straight through
        setattr(self._target(), name, value)


DEVICE_STATS = _DeviceStatsProxy()


class DispatchTicket:
    """Future for a device dispatch submitted to the feeder thread.

    wait() returns the device result handle (or re-raises the feeder
    exception); the fetch itself stays with the caller (resolve worker).
    A ticket whose wait timed out must be handed to
    :meth:`DeviceFeeder.abandon` — the late result is discarded and the
    feeder slot reclaimed whenever the wedged dispatch finally returns."""

    __slots__ = ("_event", "_result", "_exc", "slot", "upload_bytes",
                 "_released", "_abandoned", "mesh_gather", "mesh_devices",
                 "mesh_f_loc", "staging", "filter_mode", "filter_ctx")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._exc = None
        self.slot = -1
        self.upload_bytes = 0
        self._released = False
        self._abandoned = False
        # pooled host staging buffers backing this dispatch's upload —
        # recycled at mark_resolved (never on abandon: the wedged upload
        # may still be reading them)
        self.staging = None
        # fused consensus→filter dispatch (resolve_segments_wire_filtered)
        # + its host-side filter parameters, retained so the sentinel's
        # fused-route audit tap can rebuild the f64 oracle stats row
        self.filter_mode = False
        self.filter_ctx = None
        # mesh dispatches (device_call_segments_wire mesh=...): the
        # family-order gather over the shard-ordered device output, the
        # mesh size the router's per-mesh cost model is keyed by, and the
        # per-shard family count the audit sentinel attributes divergent
        # rows with (shard = gather[row] // F_loc)
        self.mesh_gather = None
        self.mesh_devices = 1
        self.mesh_f_loc = None

    def _set(self, result=None, exc=None):
        self._result = result
        self._exc = exc
        self._event.set()

    def wait(self, timeout: float = None):
        """Result handle, or raise. ``timeout`` seconds (None = forever);
        on expiry raises :class:`DeadlineExceeded` WITHOUT abandoning —
        deciding what to do with the wedged slot is the caller's call."""
        if not self._event.wait(timeout):
            raise DeadlineExceeded(
                f"device dispatch did not complete within {timeout:.1f}s")
        if self._exc is not None:
            raise self._exc
        return self._result


class DeviceFeeder:
    """Depth-N upload pipeline on one background thread.

    jax.device_put blocks the calling thread for the whole transfer on the
    tunnel-attached device (probe: 16 MB put blocks 0.2-0.9 s, while a jit
    dispatch on device-resident args returns in 0.1 ms), so uploads must not
    run on the processing thread. The feeder runs puts+dispatches in
    submission order on its own thread, keeping up to ``depth`` dispatches
    (default 2, ``FGUMI_TPU_FEEDER_DEPTH``) in flight — submitted to the
    device but not yet resolved — within a byte budget
    (``FGUMI_TPU_FEEDER_BYTES``, default 256 MiB of upload payload), so
    batch k+1's upload overlaps batch k's device compute while queued
    uploads can never pile unbounded input buffers onto the device.
    Device->host fetches run on the resolve workers and overlap the
    feeder's uploads from the other side (the link carries both directions
    concurrently — measured 32 MB bidirectional in the time of 20 MB
    one-way), with ``copy_to_host_async`` started the moment a dispatch is
    enqueued. This is the Q4->Process double-buffering analog (reference
    base.rs:1724-1920) lifted to the device boundary.

    Resolve sites MUST call :meth:`mark_resolved` (their ``finally``
    blocks do, next to the in-flight accounting) or the pipeline stalls at
    ``depth`` outstanding dispatches. Resolution must follow submission
    order per process — every caller already resolves in order, and
    ``depth >= 2`` tolerates the split-halving path's nested tickets.
    """

    def __init__(self):
        self._q = collections.deque()
        self._cv = threading.Condition()
        self._thread = None
        self._exit = False
        self._active = False  # an item is currently executing
        self._inflight = 0  # dispatched to device, not yet resolved
        self._inflight_bytes = 0
        # device bytes pinned by live ResidentHandles (ISSUE 11): counted
        # against the same governed byte budget as the in-flight uploads,
        # so resident stage-1 outputs can no longer pin HBM invisibly —
        # a held resident narrows the gate until its consumer releases it
        self._resident_bytes = 0
        self._depth = None
        self._byte_budget = None  # DynamicBudget once configured
        self._gov_token = None
        self.gate_wait_s = 0.0  # time the depth/byte gate held a dispatch
        self._async_copy_warned = set()  # leaf types logged once (debug)

    def _budget_resized(self):
        # a governor grow must release a gate-blocked feeder immediately
        with self._cv:
            self._cv.notify_all()

    def _config(self):
        # under the feeder condition (an RLock, so the feeder loop's locked
        # call re-enters fine): first use races the unlocked readers (the
        # depth property) against the feeder thread, and the governor
        # registration below must happen exactly once — a double register
        # would count a phantom 256 MiB against the global cap forever
        with self._cv:
            if self._depth is None:
                import os

                try:
                    # floor 2, not 1: the OOM-recovery path resolves a
                    # failed ticket and then dispatches+resolves its two
                    # halves in order, which needs one slot of headroom
                    # past the batch a deferred-resolve caller may still
                    # hold (the class invariant above: depth >= 2
                    # tolerates nested tickets)
                    depth = max(
                        int(os.environ.get("FGUMI_TPU_FEEDER_DEPTH", "2")),
                        2)
                except ValueError:
                    depth = 2
                try:
                    budget = max(
                        int(os.environ.get("FGUMI_TPU_FEEDER_BYTES",
                                           str(256 << 20))), 1 << 20)
                except ValueError:
                    budget = 256 << 20
                # the upload budget is a governed DynamicBudget: the env
                # value seeds it, the ResourceGovernor may grow it when the
                # gate is the contended queue (demand signal: gate_wait_s)
                # or shrink it toward the floor under memory pressure
                # (utils/governor.py)
                from ..utils.governor import GOVERNOR, DynamicBudget

                b = DynamicBudget("device.feeder", budget,
                                  floor=min(budget, 32 << 20))
                b.on_resize = self._budget_resized
                # re-registering (env-driven reconfigure, per-test feeders)
                # must not leak the previous entry: stale budgets would
                # keep counting against the governor's global cap forever
                GOVERNOR.unregister_budget(self._gov_token)
                self._gov_token = GOVERNOR.register_budget(
                    b, demand_fn=lambda: {"put_wait_s": self.gate_wait_s,
                                          "get_wait_s": 0.0})
                self._byte_budget = b
                self._depth = depth
            return self._depth, self._byte_budget.limit

    def ungovern(self):
        """Release this feeder's governor registration (tests tearing down
        throwaway feeders; the process singleton keeps its entry)."""
        from ..utils.governor import GOVERNOR

        GOVERNOR.unregister_budget(self._gov_token)
        self._gov_token = None

    @property
    def depth(self) -> int:
        """Configured in-flight pipeline depth (>= 2)."""
        return self._config()[0]

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._exit = False
            self._thread = threading.Thread(target=self._loop,
                                            name="fgumi-device-feeder",
                                            daemon=True)
            self._thread.start()

    def submit(self, fn, upload_bytes: int = 0,
               slot: int = -1) -> DispatchTicket:
        """Run fn() (puts + jit dispatch) on the feeder thread.

        The submitter's context travels with the work item: the feeder is
        one process-wide thread shared by every job, so retry counters,
        dispatch spans, and compile events raised inside fn() must resolve
        the *submitting* job's telemetry scope, not the feeder's empty
        one. ``upload_bytes`` feeds the byte budget; ``slot`` is the
        DeviceStats timeline slot (set before submission so the feeder can
        stamp upload/exec times into it without racing the caller)."""
        import contextvars

        ticket = DispatchTicket()
        ticket.upload_bytes = int(upload_bytes)
        ticket.slot = slot
        ctx = contextvars.copy_context()
        with self._cv:
            self._ensure_thread()
            self._q.append((fn, ctx, ticket))
            depth_now = len(self._q) + (1 if self._active else 0)
            self._cv.notify_all()
        DEVICE_STATS.note_queue_depth(depth_now)
        return ticket

    def add_resident_bytes(self, n: int):
        """Count live ResidentHandles bytes against the byte gate."""
        with self._cv:
            self._resident_bytes += int(n)

    def release_resident_bytes(self, n: int):
        with self._cv:
            self._resident_bytes -= int(n)
            self._cv.notify_all()

    def mark_resolved(self, ticket: DispatchTicket):
        """Release a dispatch's in-flight pipeline slot + bytes
        (idempotent; resolve paths call it in their ``finally``)."""
        with self._cv:
            if ticket._released:
                return
            ticket._released = True
            self._inflight -= 1
            self._inflight_bytes -= ticket.upload_bytes
            staging = ticket.staging
            recycle = staging is not None and not ticket._abandoned
            ticket.staging = None
            self._cv.notify_all()
        if recycle:
            # by resolve time the device has consumed the upload (the
            # result was fetched or the dispatch failed), so the pooled
            # staging buffers are safe to hand out again — even on
            # backends where device_put aliases host memory. An abandoned
            # dispatch may still be mid-upload: its buffers are leaked to
            # the wedge instead of recycled.
            from .datapath import STAGING_POOL

            for arr in staging:
                STAGING_POOL.release(arr)

    def abandon(self, ticket: DispatchTicket):
        """Give up on a dispatch that overran its deadline.

        The resolver walks away NOW; whenever the wedged dispatch finally
        completes (or fails), its result is discarded and the feeder slot
        reclaimed through the ordinary :meth:`mark_resolved` path — so a
        single wedge degrades one batch, never wedges the pipeline's
        depth gate permanently. Safe against every interleaving with the
        worker loop: completion state is read under the lock, and
        ``mark_resolved`` is idempotent."""
        with self._cv:
            ticket._abandoned = True
            completed = ticket._event.is_set()
        from ..observe.flight import FLIGHT

        FLIGHT.note("device.feeder.abandon", slot=ticket.slot,
                    upload_bytes=ticket.upload_bytes,
                    completed_late=completed)
        if completed:
            # raced the completion: the result exists but the caller is
            # not going to fetch it — reclaim the slot here
            self.mark_resolved(ticket)

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._q) + (1 if self._active else 0)

    def drain(self, timeout: float = None) -> bool:
        """Run the queue dry, then let the feeder thread exit when idle.

        The serve daemon's SIGTERM drain calls this after the scheduler
        quiesces so the process never leaves a dispatch half-uploaded.
        Returns True when the queue emptied (and the thread, if any,
        exited) within ``timeout`` seconds (None = wait indefinitely).
        The feeder restarts transparently on the next submit() — the
        worker clears ``_thread`` under the lock when it commits to exit,
        so a racing submit either lands on the live worker before that
        point or starts a fresh one."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            self._exit = True
            self._cv.notify_all()
            while self._q or self._active:
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    return False
                self._cv.wait(left if left is not None else 0.5)
            thread = self._thread
        if thread is not None and thread.is_alive():
            left = None if deadline is None else \
                max(deadline - time.monotonic(), 0.0)
            thread.join(left)
            return not thread.is_alive()
        return True

    def _run_item(self, fn, ticket, overlapped, t0):
        """Execute one work item inside the submitter's context (so
        DEVICE_STATS / METRICS resolve the submitting job's scope)."""
        result = fn()
        dt = time.monotonic() - t0
        if overlapped:
            # this fn ran while an earlier dispatch was still UNRESOLVED —
            # an upper bound on upload/compute overlap (in deferred-resolve
            # modes the earlier result may already sit on host), which is
            # how docs/observability.md defines upload_overlap_s
            DEVICE_STATS.add_upload_overlap(dt)
        if ticket.slot >= 0:
            DEVICE_STATS.note_exec(ticket.slot)
        # start the device->host copy NOW (non-blocking): by the time the
        # resolve stage calls device_get, the result bytes are already on
        # host (or in flight), so the fetch costs a wait-for-arrival
        # instead of a full round trip. Backends without
        # copy_to_host_async just fetch at resolve time.
        try:
            for leaf in jax.tree_util.tree_leaves(result):
                if hasattr(leaf, "copy_to_host_async"):
                    leaf.copy_to_host_async()
        except Exception as e:  # noqa: BLE001 - fetch-time path still works
            # once per leaf/exception type, at debug: a silently dead
            # fetch-overlap path regresses e2e latency with zero signal
            key = type(e).__name__
            if key not in self._async_copy_warned:
                self._async_copy_warned.add(key)
                log.debug("copy_to_host_async failed (%s: %s); results "
                          "will be fetched synchronously at resolve time",
                          key, e)
        return result

    def _loop(self):
        while True:
            with self._cv:
                self._active = False
                self._cv.notify_all()
                while not self._q:
                    if self._exit:
                        # commit to exit UNDER the lock: a concurrent
                        # submit() sees _thread is None and starts a fresh
                        # worker instead of queueing onto a dying one
                        self._thread = None
                        return
                    self._cv.wait()
                depth, _ = self._config()
                # depth/byte gate: hold the NEXT dispatch until an earlier
                # one resolves. Skipped in drain mode — the queue must run
                # dry even if no resolver is coming back for stragglers.
                # Bounded wait: a caller that died without resolving its
                # ticket (dropped pending chunk on a crashed pipeline)
                # must degrade to the old unpipelined behavior, never
                # freeze every later dispatch in the process. The byte
                # limit is re-read every iteration: the governor may grow it
                # mid-wait (its resize hook notifies this condition).
                ticket = self._q[0][2]
                deadline = None
                while (not self._exit and self._q
                       and (self._inflight >= depth
                            or (self._inflight > 0
                                and self._inflight_bytes
                                + self._resident_bytes
                                + ticket.upload_bytes
                                > self._byte_budget.limit))):
                    # the demand signal must name the *byte budget* as the
                    # gate, not the depth clause: growing bytes cannot
                    # release a depth-held dispatch, and a device-bound run
                    # waits here constantly — counting that would make the
                    # governor inflate this budget to its ceiling for
                    # nothing (starving genuinely byte-bound queues of the
                    # global cap)
                    byte_bound = self._inflight < depth
                    if deadline is None:
                        deadline = time.monotonic() + 60.0
                    left = deadline - time.monotonic()
                    if left <= 0:
                        log.warning(
                            "device feeder depth gate timed out with %d "
                            "dispatch(es) unresolved; proceeding (a "
                            "dispatch ticket was likely dropped without "
                            "resolution)", self._inflight)
                        break
                    t_wait = time.monotonic()
                    self._cv.wait(min(left, 1.0))
                    if byte_bound:
                        self.gate_wait_s += time.monotonic() - t_wait
                    ticket = self._q[0][2] if self._q else None
                if not self._q:
                    continue
                fn, ctx, ticket = self._q.popleft()
                if ticket._abandoned:
                    # abandoned while still queued (a deadline fired on a
                    # batch stuck behind a wedged dispatch): never start
                    # work nobody will fetch — especially not work that
                    # may hang this thread too
                    ticket._released = True  # never held a slot
                    ticket._set(exc=DeadlineExceeded(
                        "dispatch abandoned before it started"))
                    continue
                self._inflight += 1
                self._inflight_bytes += ticket.upload_bytes
                overlapped = self._inflight > 1
                self._active = True
            t0 = time.monotonic()
            try:
                result = ctx.run(self._run_item, fn, ticket, overlapped, t0)
                exc = None
            except BaseException as e:  # noqa: BLE001 - relayed to waiter
                result, exc = None, e
            with self._cv:
                ticket._set(result=result, exc=exc)
                late = ticket._abandoned
            if late:
                # the resolver gave up at its deadline while this dispatch
                # was running: discard the late result, reclaim the slot —
                # including any device-resident arrays it produced, whose
                # byte accounting would otherwise leak with the abandon
                log.warning("device dispatch completed %.1fs after its "
                            "deadline; late result discarded",
                            time.monotonic() - t0)
                _release_residents(result)
                self.mark_resolved(ticket)


DEVICE_FEEDER = DeviceFeeder()


@_lazy_jit
def _canary_sum_jit(x):
    return jnp.sum(x.astype(jnp.int32))


#: canary payload size: big enough that the upload wall is a usable link
#: sample, small enough to cost <3s even on the slowest observed tunnel.
_CANARY_BYTES = 1 << 20


def device_canary(timeout_s: float = 10.0):
    """One tiny end-to-end device round trip under its own deadline.

    Returns ``(ok, wall_s, error)``. Goes through the ordinary feeder
    submit + bounded ticket wait, so a wedged feeder/link shows up as a
    timeout (the canary is abandoned like any other dispatch, never
    hangs the caller), and a healthy round trip feeds the router's
    link-rate EWMA. Used by the health monitor
    (:class:`fgumi_tpu.ops.breaker.HealthMonitor`); callers feed the
    breaker from the result."""
    t0 = time.monotonic()
    payload = np.zeros(_CANARY_BYTES, dtype=np.uint8)

    def _fn():
        # t_start is captured ON the feeder thread so the router sample
        # below excludes time spent queued behind real dispatches — the
        # resolve paths price queue wait via decide()'s in_flight term,
        # and a canary in a busy daemon must not fold it into the
        # overhead EWMA (it would overprice a healthy device)
        _ensure_jax()
        t_start = time.monotonic()
        dev = jax.device_put(payload)
        return _canary_sum_jit(dev), time.monotonic() - t_start, t_start

    ticket = DEVICE_FEEDER.submit(_fn, upload_bytes=payload.nbytes)
    try:
        dev_out, up_s, t_start = ticket.wait(timeout_s)
        left = max(timeout_s - (time.monotonic() - t0), 0.5)
        got = _fetch_with_deadline(dev_out, left)
    except DeadlineExceeded as e:
        DEVICE_FEEDER.abandon(ticket)
        return False, time.monotonic() - t0, str(e)
    except BaseException as e:  # noqa: BLE001 - canary outcome, not crash
        DEVICE_FEEDER.mark_resolved(ticket)
        if not (_is_oom(e) or _is_transient(e)):
            raise
        return False, time.monotonic() - t0, f"{type(e).__name__}: {e}"
    DEVICE_FEEDER.mark_resolved(ticket)
    wall = time.monotonic() - t0
    if int(got) != 0:  # payload is zeros; anything else is corruption
        return False, wall, f"canary sum mismatch: {int(got)}"
    from .router import ROUTER

    active_s = max(time.monotonic() - t_start, up_s)
    ROUTER.observe_device(payload.nbytes, 4, up_s,
                          max(active_s - up_s, 0.0), active_s)
    return True, wall, None


def default_max_inflight() -> int:
    """Hybrid backlog cap shared by the consensus engines (simplex /
    duplex / codec): dispatches in flight at or beyond it route to the
    native f64 host engine instead of queueing behind the link. Explicit
    ``FGUMI_TPU_MAX_INFLIGHT`` wins (``0`` = always host); the default
    tracks the feeder's pipeline depth + 1 (``depth`` uploads in flight
    plus one packed in its queue)."""
    import os

    env_cap = os.environ.get("FGUMI_TPU_MAX_INFLIGHT", "").strip()
    if env_cap:
        try:
            return int(env_cap)
        except ValueError:
            log.warning("FGUMI_TPU_MAX_INFLIGHT=%r is not an integer; "
                        "using the default", env_cap)
    return DEVICE_FEEDER.depth + 1


def device_backlogged(max_inflight: int) -> bool:
    """True when the upload pipeline already holds ``max_inflight``
    dispatches — the one backlog test behind every hybrid engine's
    route-to-host-engine decision (simplex / duplex / codec)."""
    return DEVICE_STATS.in_flight_count() >= max_inflight


# ---------------------------------------------------------------------------
# Device resilience: bounded retry on transient XLA failures, batch halving
# on RESOURCE_EXHAUSTED, final whole-batch fallback to the native f64 host
# engine. All three preserve output bytes exactly — the host engine and the
# device+oracle path share the same integer-exactness contract — so a flaky
# device degrades throughput, never correctness (docs/resilience.md).
# ---------------------------------------------------------------------------

def _is_oom(exc) -> bool:
    """An XLA out-of-memory (batch too big for device HBM): halve, don't
    retry — re-dispatching the same shape fails the same way."""
    return "RESOURCE_EXHAUSTED" in str(exc)


# XLA status codes that a retry can plausibly fix (link hiccup, preempted
# device, transient runtime state); INVALID_ARGUMENT-class failures are
# programming errors and re-raise immediately.
_TRANSIENT_MARKERS = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED",
                      "INTERNAL", "CANCELLED", "UNKNOWN",
                      "connection", "socket", "reset by peer")


def _is_transient(exc) -> bool:
    from ..utils.faults import InjectedFault

    if isinstance(exc, InjectedFault):
        return not _is_oom(exc)
    if type(exc).__name__ != "XlaRuntimeError":
        return False
    s = str(exc)
    return any(m in s for m in _TRANSIENT_MARKERS)


def _retry_budget():
    import os

    tries = max(int(os.environ.get("FGUMI_TPU_DEVICE_RETRIES", "3")), 0)
    base = float(os.environ.get("FGUMI_TPU_DEVICE_BACKOFF_S", "0.05"))
    return tries, base


def device_retry_call(fn, what: str = "dispatch"):
    """Run fn() (device upload + jit dispatch) with bounded exponential
    backoff on transient errors. Non-transient errors and OOM re-raise
    immediately (OOM is handled by batch splitting at resolve time). The
    device.dispatch fault point fires on every attempt, so chaos tests
    exercise exactly this loop."""
    from ..observe.trace import span
    from ..utils import faults

    # chaos point for the wedge class of failure (kind `hang`, stall via
    # FGUMI_TPU_FAULT_HANG_S): fires ONCE per dispatch, before the retry
    # loop, on whichever thread runs the dispatch — for the async paths
    # that is the feeder thread, exactly where a wedged device_put stalls,
    # so the deadline/breaker machinery is exercised end to end
    faults.fire("device.wedge")
    retries, delay = _retry_budget()
    for attempt in range(retries + 1):
        try:
            faults.fire("device.dispatch")
            # one span per attempt, on whichever thread runs the dispatch
            # (the caller for sync paths, fgumi-device-feeder for async)
            with span("device.dispatch", what=what, attempt=attempt):
                return fn()
        except BaseException as e:  # noqa: BLE001 - classified below
            if _is_oom(e) or not _is_transient(e) or attempt >= retries:
                raise
            DEVICE_STATS.add_retry()
            # the device runtime may have restarted under us; resident
            # constants died with it, so the retry re-uploads fresh
            CONST_CACHE.invalidate()
            log.warning("device %s failed (%s: %s); retry %d/%d in %.2fs",
                        what, type(e).__name__, e, attempt + 1, retries,
                        delay)
            time.sleep(delay)
            delay = min(delay * 2, 2.0)


class _DeadlineRunner:
    """Reusable helper threads for deadline-bounded calls into jax.

    ``jax.device_get`` (and, on a wedged runtime, even ``device_put``/jit
    dispatch) can block indefinitely, so a bounded call runs on a helper
    thread (with the caller's context, so scope-resolved stats land
    correctly) while the caller waits at most the deadline. Workers are
    kept on a free list between calls — the deadline default is *on*, so
    every hot-path fetch comes through here and must not pay a
    thread-create — and each concurrent call gets its own worker, so
    bounding adds no serialization. A worker that blows its deadline is
    simply not returned to the free list: it is left to die with the
    wedge (daemon thread), and the next call starts a fresh one."""

    def __init__(self, name: str):
        self._name = name
        self._lock = threading.Lock()
        self._free = []     # idle worker queues
        self._seq = 0

    def run(self, fn, deadline_s, what: str):
        if deadline_s is None:
            return fn()
        import contextvars
        import queue as _queue

        ctx = contextvars.copy_context()
        box = {}
        done = threading.Event()
        with self._lock:
            if self._free:
                q = self._free.pop()
            else:
                q = _queue.SimpleQueue()
                self._seq += 1
                threading.Thread(target=self._loop, args=(q,),
                                 name=f"{self._name}-{self._seq}",
                                 daemon=True).start()
        q.put((ctx, fn, box, done))
        if not done.wait(deadline_s):
            # wedged: the worker is abandoned with its call (never reused;
            # if the wedge ever clears it parks in q.get() forever)
            raise DeadlineExceeded(
                f"{what} did not complete within {deadline_s:.1f}s")
        with self._lock:
            self._free.append(q)
        if "exc" in box:
            raise box["exc"]
        return box["result"]

    @staticmethod
    def _loop(q):
        while True:
            ctx, fn, box, done = q.get()
            try:
                box["result"] = ctx.run(fn)
            except BaseException as e:  # noqa: BLE001 - relayed to waiter
                box["exc"] = e
            finally:
                done.set()


_FETCH_RUNNER = _DeadlineRunner("fgumi-device-fetch")
_DISPATCH_RUNNER = _DeadlineRunner("fgumi-device-dispatch")


def _fetch_with_deadline(dev, deadline_s):
    """DEVICE_STATS.fetch(dev) bounded by ``deadline_s`` seconds (None =
    plain inline fetch); raises :class:`DeadlineExceeded` on expiry."""
    if deadline_s is None:
        return DEVICE_STATS.fetch(dev)
    return _FETCH_RUNNER.run(lambda: DEVICE_STATS.fetch(dev), deadline_s,
                             "device fetch")


def segments_flops(n_rows: int, length: int, num_segments: int) -> int:
    """Model FLOPs for one _segments_body execution (counting f32 mul/add):
    one_hot*valid mask (4) + delta*one_hot (4) + two segment_sum adds (8)
    per (row, position), ~40 epilogue flops per (segment, position)."""
    return n_rows * length * 16 + num_segments * length * 40


def _observation_terms(codes, quals, correct_tab, err_tab):
    """Per-observation lane one-hot + match-contribution delta.

    codes/quals: any shape. Returns one_hot (..., 4) f32 (zeroed at N/pad
    observations) and delta (...,) f32 — the shared per-observation math of
    both the uniform-R and ragged-segment reductions.
    """
    q_idx = jnp.minimum(quals, MAX_PHRED).astype(jnp.int32)
    delta_tab = correct_tab - err_tab  # (94,) f32, >= 0 for sane rates
    valid = codes != N_CODE
    one_hot = jax.nn.one_hot(jnp.minimum(codes, 3), 4, dtype=jnp.float32)
    one_hot = one_hot * valid[..., None].astype(jnp.float32)
    delta = jnp.where(valid, delta_tab[q_idx], 0.0)
    return one_hot, delta


def _reduce_contributions(codes, quals, correct_tab, err_tab):
    """Per-position match-contribution + observation-count reduction over reads.

    codes/quals: (..., R, L). Returns C (..., L, 4) f32 (lane match contributions),
    obs (..., L, 4) int32. N/pad codes contribute nothing (base_builder.rs:616-619).
    """
    one_hot, delta = _observation_terms(codes, quals, correct_tab, err_tab)
    # HIGHEST precision: the guard-band derivation assumes true f32 products;
    # TPU MXU default precision multiplies in bf16 (~2e-3 relative), which
    # would blow straight through an eps32-scale band undetected.
    contrib = jnp.einsum("...rl,...rlb->...lb", delta, one_hot,
                         precision=jax.lax.Precision.HIGHEST)
    obs = jnp.sum(one_hot, axis=-3).astype(jnp.int32)  # (..., L, 4)
    return contrib, obs


def _pack_result(winner, qual, suspect):
    """The (qual | winner<<7 | suspect<<10) uint16 wire word (see
    _unpack_device_result for the inverse)."""
    packed = qual | (winner << 7) | (suspect.astype(jnp.int32) << 10)
    return packed.astype(jnp.uint16)


def _pack_result_split(winner, qual, suspect, out_segments):
    """Split packed result at 1.25 B/position, sliced to out_segments rows.

    qs (out_segments, L) uint8 = qual (7b) | suspect (1b); wp
    (out_segments, L/4) uint8 = winner 2-bit packed 4-per-byte along L.
    The N winner (tie or no-call) is NOT encoded: tie positions carry the
    suspect bit (the host's exact recompute overwrites them) and no-call
    positions are recomputed on host as depth==0 from the codes it already
    holds — so 2 bits per winner suffice and the fetch drops from 2 B to
    1.25 B per position (VERDICT r4 item 4)."""
    qs = (qual | (suspect.astype(jnp.int32) << 7))[:out_segments]
    w4 = jnp.where(winner > 3, 0, winner)[:out_segments]
    w4 = w4.reshape(out_segments, -1, 4)
    wp = w4[..., 0] | (w4[..., 1] << 2) | (w4[..., 2] << 4) | (w4[..., 3] << 6)
    return qs.astype(jnp.uint8), wp.astype(jnp.uint8)


def unpack_result_split(qs: np.ndarray, wp: np.ndarray, J: int):
    """(winner 0..3, qual, suspect) host arrays from a split packed fetch."""
    qs = qs[:J]
    qual = (qs & 0x7F).astype(np.uint8)
    suspect = (qs >> 7).astype(bool)
    shifts = np.array([0, 2, 4, 6], dtype=np.uint8)
    w4 = (wp[:J, :, None] >> shifts) & 3
    winner = w4.reshape(J, -1).astype(np.uint8)
    return winner, qual, suspect


def _call_epilogue(contrib, obs, ln_error_pre_umi):
    """Winner/tie/posterior/Phred epilogue over (..., L, 4) lane contributions.

    Returns winner (int32, N_CODE for no-call), qual (int32), depth, errors (int32),
    suspect (bool): positions requiring f64 host recomputation.
    """
    depth = jnp.sum(obs, axis=-1)
    max_c = jnp.max(contrib, axis=-1)
    winner = jnp.argmax(contrib, axis=-1).astype(jnp.int32)
    lane_is_winner = jax.nn.one_hot(winner, 4, dtype=jnp.bool_)

    # Loser-gap frame: s = sum over losing lanes of exp(-(max - C_b)).
    gaps = max_c[..., None] - contrib  # >= 0; 0 at the winner lane
    exp_neg = jnp.where(lane_is_winner, 0.0, jnp.exp(-gaps))
    s = jnp.sum(exp_neg, axis=-1)
    # ln consensus error = ln(s / (1 + s)); s == 0 underflows to -inf (cap region).
    ln_cons_err = jnp.log(s) - jnp.log1p(s)

    # two-trials combination with the pre-UMI prior (phred.rs:248-267), f32.
    pre = jnp.float32(ln_error_pre_umi)
    hi = jnp.maximum(pre, ln_cons_err)
    lo = jnp.minimum(pre, ln_cons_err)
    diff = hi - lo
    quick = ~(diff < 6.0)  # catches NaN (lo = -inf) as quick
    safe_diff = jnp.where(quick, 6.0, diff)
    term1 = hi + jnp.log1p(jnp.exp(-safe_diff))  # ln(exp(hi) + exp(lo))
    term2_minus_term1 = _LN_4_3_F32 + lo - jnp.log1p(jnp.exp(-safe_diff))
    full = term1 + jnp.log1p(-jnp.exp(jnp.minimum(term2_minus_term1, -_EPS32)))
    ln_final = jnp.where(quick, hi, full)

    phred_f = -ln_final * _PHRED_PER_LN + 0.001
    qual = jnp.clip(jnp.floor(phred_f), MIN_PHRED, MAX_PHRED).astype(jnp.int32)

    # ---- suspect guard band (derivation in the module-level comment) ----
    eps_gap = _EPS32 * (depth.astype(jnp.float32) + 2.0) * (1.0 + max_c)
    # winner margin: distance between best and second-best lane contribution
    second = jnp.max(jnp.where(lane_is_winner, -jnp.inf, contrib), axis=-1)
    margin = max_c - second
    tie_suspect = margin <= (2.0 * eps_gap + _TIE_GUARD_FLOOR)
    # Phred rounding proximity. The ln_final error is ~eps_gap on the consensus-error
    # path; when the quick path selected the pre-UMI constant the result is exact.
    took_pre = quick & (ln_cons_err < pre)
    err_phred = jnp.where(took_pre, 0.0, _PHRED_PER_LN * 2.0 * eps_gap)
    frac = phred_f - jnp.floor(phred_f)
    near_boundary = jnp.minimum(frac, 1.0 - frac) <= (err_phred + _QUAL_GUARD_FLOOR)
    clamped = (phred_f <= MIN_PHRED) | (phred_f >= MAX_PHRED + 0.5)
    # The quick-vs-full two-trials branch (diff >= 6) is decided in f32 here but f64
    # in the oracle; the formulas differ by up to ln(1+e^-6) ≈ 0.0215 Phred at the
    # boundary, so positions near it must fall back.
    branch_suspect = jnp.abs(diff - 6.0) <= (2.0 * eps_gap + 1e-4)
    # Non-finite contributions (a Q0 observation's -inf table entry times the one-hot
    # zero gives NaN through the einsum) poison every comparison below into False;
    # force those positions to the exact host path.
    nonfinite = ~jnp.isfinite(max_c)
    suspect = tie_suspect | branch_suspect | nonfinite | (near_boundary & ~clamped)

    no_call = depth == 0
    winner = jnp.where(no_call | tie_suspect, N_CODE, winner)
    qual = jnp.where(no_call | tie_suspect, MIN_PHRED, qual)
    suspect = suspect & ~no_call

    winner_obs = jnp.sum(obs * lane_is_winner.astype(jnp.int32), axis=-1)
    errors = depth - jnp.where(winner == N_CODE, 0, winner_obs)
    return winner, qual, depth, errors, suspect


@_lazy_jit
def _consensus_batch_jit(codes, quals, correct_tab, err_tab, ln_error_pre_umi):
    contrib, obs = _reduce_contributions(codes, quals, correct_tab, err_tab)
    return _call_epilogue(contrib, obs, ln_error_pre_umi)


def _segments_body(codes, quals, seg_ids, correct_tab, err_tab,
                   ln_error_pre_umi, num_segments):
    """Ragged-family consensus body: dense (N, L) read rows + sorted segment
    ids -> packed (num_segments, L) uint16. Shared by the single-device jit
    and the shard_map-per-device sharded variant."""
    one_hot, delta = _observation_terms(codes, quals, correct_tab, err_tab)
    row_contrib = delta[..., None] * one_hot  # (N, L, 4)
    contrib = jax.ops.segment_sum(row_contrib, seg_ids,
                                  num_segments=num_segments,
                                  indices_are_sorted=True)
    obs = jax.ops.segment_sum(one_hot, seg_ids, num_segments=num_segments,
                              indices_are_sorted=True).astype(jnp.int32)
    winner, qual, _depth, _errors, suspect = _call_epilogue(
        contrib, obs, ln_error_pre_umi)
    return _pack_result(winner, qual, suspect)


# ---------------------------------------------------------------------------
# 1-byte/position wire format: code (2b) | qual-dictionary index (6b), with
# index 63 reserved for invalid (N base or pad row). Sequencers emit a small
# set of distinct quality values (2-16 typical; overlap correction sums and
# differences push it to ~60), so a per-dispatch dictionary of <=63
# f64-derived f32 delta entries re-expresses the (94,) quality tables
# losslessly — identical f32 table values, just re-indexed — and HALVES
# upload bytes on the ~17-76 MB/s tunnel vs the 2-byte codes+quals layout.
# Numerics and the guard band are unchanged. Batches with >63 distinct
# quals fall back to 1.25 B/position (2-bit packed codes + qual bytes).
# ---------------------------------------------------------------------------
WIRE_INVALID = np.uint8(0xFC)  # qidx 63, code 0
QUAL_INVALID = np.uint8(127)  # fallback-layout qual sentinel for N/pad


def _wire_terms(wire, dict_tab):
    """Per-observation lane one-hot + delta from the 1-byte wire format.

    dict_tab: (64,) f32 delta values with dict_tab[63] == 0, so invalid
    positions contribute nothing without a separate select."""
    qidx = (wire >> 2).astype(jnp.int32)
    valid = qidx != 63
    one_hot = jax.nn.one_hot(wire & 3, 4, dtype=jnp.float32)
    one_hot = one_hot * valid[..., None].astype(jnp.float32)
    delta = dict_tab[qidx]
    return one_hot, delta


def _wire_epilogue(wire, seg_ids, dict_tab, ln_error_pre_umi, num_segments):
    """Shared reduction+epilogue of every wire-layout segment kernel:
    (N, L) wire rows -> (winner, qual, depth, errors, suspect, obs)."""
    one_hot, delta = _wire_terms(wire, dict_tab)
    row_contrib = delta[..., None] * one_hot
    contrib = jax.ops.segment_sum(row_contrib, seg_ids,
                                  num_segments=num_segments,
                                  indices_are_sorted=True)
    obs = jax.ops.segment_sum(one_hot, seg_ids, num_segments=num_segments,
                              indices_are_sorted=True).astype(jnp.int32)
    return _call_epilogue(contrib, obs, ln_error_pre_umi) + (obs,)


def _packed2_terms(codes_packed, quals, correct_tab, err_tab):
    """Per-observation lane one-hot + delta from the 1.25 B/position
    fallback layout (>63 distinct quals): 2-bit packed codes + sentinel
    quals, device-side unpack is a shift-and-mask. The one copy of this
    math — shared by the single-device epilogue and the shard_map mesh
    kernel so the two can never drift apart."""
    shifts = jnp.arange(0, 8, 2, dtype=jnp.uint8)
    c4 = (codes_packed[..., None] >> shifts) & 3
    codes = c4.reshape(codes_packed.shape[0], -1)
    valid = quals != QUAL_INVALID
    q_idx = jnp.minimum(quals, MAX_PHRED).astype(jnp.int32)
    delta_tab = correct_tab - err_tab
    one_hot = jax.nn.one_hot(codes, 4, dtype=jnp.float32)
    one_hot = one_hot * valid[..., None].astype(jnp.float32)
    delta = jnp.where(valid, delta_tab[q_idx], 0.0)
    return one_hot, delta


def _packed2_epilogue(codes_packed, quals, seg_ids, correct_tab, err_tab,
                      ln_error_pre_umi, num_segments):
    """Shared reduction+epilogue of the 1.25 B/position fallback layout."""
    one_hot, delta = _packed2_terms(codes_packed, quals, correct_tab,
                                    err_tab)
    row_contrib = delta[..., None] * one_hot
    contrib = jax.ops.segment_sum(row_contrib, seg_ids,
                                  num_segments=num_segments,
                                  indices_are_sorted=True)
    obs = jax.ops.segment_sum(one_hot, seg_ids, num_segments=num_segments,
                              indices_are_sorted=True).astype(jnp.int32)
    return _call_epilogue(contrib, obs, ln_error_pre_umi) + (obs,)


def _wire_split_fn(wire, seg_ids, dict_tab, ln_error_pre_umi,
                   num_segments, out_segments):
    """Ragged-family consensus over the 1-byte wire layout with split packed
    output: (N, L) wire rows -> (out_segments, L) qs + (out_segments, L/4) wp.
    """
    winner, qual, _depth, _errors, suspect, _obs = _wire_epilogue(
        wire, seg_ids, dict_tab, ln_error_pre_umi, num_segments)
    return _pack_result_split(winner, qual, suspect, out_segments)


def _wire_full_fn(wire, seg_ids, dict_tab, ln_error_pre_umi, num_segments,
                  out_segments):
    """Full-column wire kernel: winner/qual AND depth/errors per column.

    The device computes the integer depth/error counts it already holds as
    lane observation sums (exact in f32 below 2^24 observations), so the
    host never re-walks the dense rows at resolve time — the family's data
    crosses the link once, as wire bytes. depth/errors fetch as uint16
    (+4 B/column); callers gate on max family size < 65536 (ROADMAP item 1,
    round 6)."""
    winner, qual, depth, errors, suspect, _obs = _wire_epilogue(
        wire, seg_ids, dict_tab, ln_error_pre_umi, num_segments)
    qs, wp = _pack_result_split(winner, qual, suspect, out_segments)
    return (qs, wp, depth[:out_segments].astype(jnp.uint16),
            errors[:out_segments].astype(jnp.uint16))


# plain + upload-donation compilations of each wire-layout kernel: the
# donated variants let XLA alias the (wire, seg_ids) upload pages for
# outputs/temporaries instead of allocating fresh device memory per
# dispatch; chosen per dispatch by upload_donation_enabled().
_W_STATIC = ("num_segments", "out_segments")
_consensus_segments_wire_jit = _lazy_jit(
    static_argnames=_W_STATIC)(_wire_split_fn)
_consensus_segments_wire_donated_jit = _lazy_jit(
    static_argnames=_W_STATIC, donate_argnums=(0, 1))(_wire_split_fn)
_consensus_segments_wire_full_jit = _lazy_jit(
    static_argnames=_W_STATIC)(_wire_full_fn)
_consensus_segments_wire_full_donated_jit = _lazy_jit(
    static_argnames=_W_STATIC, donate_argnums=(0, 1))(_wire_full_fn)


_I16_MAX = 32767  # fgbio Short tag clamp (vanilla.py I16_MAX twin)


class ResidentHandles:
    """Device-resident stage-1 outputs kept for a fused follow-up stage.

    NOT a jax pytree on purpose: the feeder's fetch-overlap pass
    (copy_to_host_async over tree leaves) must never start copying these —
    they exist precisely so their bytes never cross the link.

    Accounting (ISSUE 11 satellite): the arrays' device bytes were
    invisible to every budget — a long duplex run could pin HBM with
    stage-1 outputs the governor never saw. Construction now registers the
    byte total with DeviceStats (``device.resident_bytes`` gauge + peak)
    AND the device feeder's DynamicBudget byte gate, and every consumer
    calls :meth:`release` when the fused stage has used (or abandoned) the
    arrays — combine/fetch/degrade paths and the feeder's late-result
    discard all release, so a wedge cannot leak the accounting."""

    __slots__ = ("arrays", "nbytes", "_released")

    def __init__(self, arrays):
        self.arrays = arrays
        self.nbytes = sum(int(getattr(a, "nbytes", 0) or 0)
                          for a in arrays)
        self._released = False
        if self.nbytes:
            DEVICE_STATS.add_resident_bytes(self.nbytes)
            DEVICE_FEEDER.add_resident_bytes(self.nbytes)

    def release(self):
        """Drop the device arrays + their byte accounting (idempotent)."""
        if self._released:
            return
        self._released = True
        self.arrays = None
        if self.nbytes:
            DEVICE_STATS.release_resident_bytes(self.nbytes)
            DEVICE_FEEDER.release_resident_bytes(self.nbytes)


def _release_residents(result):
    """Release every ResidentHandles inside a discarded dispatch result
    (the feeder's late-completion path after an abandon)."""
    if isinstance(result, ResidentHandles):
        result.release()
    elif isinstance(result, (tuple, list)):
        for item in result:
            _release_residents(item)


def _wire_resident_fn(wire, seg_ids, dict_tab, ln_error_pre_umi, min_reads,
                      min_qual, num_segments, out_segments):
    """Full-column wire kernel + device-resident thresholded outputs.

    Beyond the full fetch tuple, returns (tb, tq, obs) sliced to
    out_segments and kept on device for the fused duplex strand-combine
    stage (_duplex_combine_jit): tb/tq apply the consensus thresholds
    (oracle.apply_consensus_thresholds twin — depth < min_reads -> (N, 0),
    qual < min_qual -> (N, MIN_PHRED)) and obs holds the per-lane
    observation counts the combine's exact error recount needs. Suspect
    positions differ from the host's oracle-patched values; the combine
    resolve recomputes any output row touching one on host."""
    winner, qual, depth, errors, suspect, obs = _wire_epilogue(
        wire, seg_ids, dict_tab, ln_error_pre_umi, num_segments)
    qs, wp = _pack_result_split(winner, qual, suspect, out_segments)
    w_sl = winner[:out_segments]
    q_sl = qual[:out_segments]
    d_sl = depth[:out_segments]
    low_depth = d_sl < min_reads
    low_qual = q_sl < min_qual
    tb = jnp.where(low_depth | low_qual, N_CODE, w_sl).astype(jnp.uint8)
    tq = jnp.where(low_depth, 0,
                   jnp.where(low_qual, MIN_PHRED, q_sl)).astype(jnp.uint8)
    return (qs, wp, d_sl.astype(jnp.uint16),
            errors[:out_segments].astype(jnp.uint16), tb, tq,
            obs[:out_segments])


_consensus_segments_wire_resident_jit = _lazy_jit(
    static_argnames=_W_STATIC)(_wire_resident_fn)
_consensus_segments_wire_resident_donated_jit = _lazy_jit(
    static_argnames=_W_STATIC, donate_argnums=(0, 1))(_wire_resident_fn)


def _wire_filter_fn(wire, seg_ids, dict_tab, ln_error_pre_umi, min_reads_c,
                    min_qual_c, lens, f_min_reads, f_emin_tab, f_min_base_q,
                    f_per_base, num_segments, out_segments):
    """Fused consensus→filter wire kernel (ISSUE 11 tentpole).

    One dispatch computes the full consensus columns, applies the
    consensus thresholds (apply_consensus_thresholds twin, as in the
    resident kernel) AND the filter library's simplex per-base masks
    (mask_bases twin) over them, and reduces everything the read-level
    verdicts need to a 7-int32 stats row per read — the only thing fetched
    home by default. The masked output columns (fb/fq), the raw
    depth/error columns, and the pre-threshold packed winner/qual/suspect
    words stay DEVICE-RESIDENT for the survivors-only gather
    (:func:`ConsensusKernel.filter_gather_filtered` /
    :meth:`ConsensusKernel.filter_resolve_suspect_rows`).

    Exactness: every per-base decision here is integer arithmetic —
    ``f_emin_tab`` (consensus/filter.base_error_rate_table) reformulates
    the host's f64 error-rate division as a threshold-integer gather, so
    the device mask can never disagree with ``mask_bases``. Stats columns:
    [max d16, sum d16, sum e16, sum qual, N-after-mask, newly-masked,
    any-suspect] with every reduction restricted to positions < lens."""
    winner, qual, depth, errors, suspect, _obs = _wire_epilogue(
        wire, seg_ids, dict_tab, ln_error_pre_umi, num_segments)
    qs, wp = _pack_result_split(winner, qual, suspect, out_segments)
    w = winner[:out_segments]
    q = qual[:out_segments]
    d = depth[:out_segments]
    e = errors[:out_segments]
    sus = suspect[:out_segments]
    low_depth = d < min_reads_c
    low_qual = q < min_qual_c
    tb = jnp.where(low_depth | low_qual, N_CODE, w)
    tq = jnp.where(low_depth, 0, jnp.where(low_qual, MIN_PHRED, q))
    L = wire.shape[1]
    in_len = jnp.arange(L, dtype=jnp.int32)[None, :] < lens[:, None]
    d16 = jnp.minimum(d, _I16_MAX)
    e16 = jnp.minimum(e, _I16_MAX)
    fmask = (f_per_base > 0) & ((d16 < f_min_reads)
                                | ((d16 > 0) & (e16 >= f_emin_tab[d16])))
    fmask = fmask | ((f_min_base_q >= 0) & (tq < f_min_base_q))
    fmask = fmask & in_len
    fb = jnp.where(fmask, N_CODE, tb)
    fq = jnp.where(fmask, MIN_PHRED, tq)
    z32 = jnp.int32(0)
    stats = jnp.stack([
        jnp.max(jnp.where(in_len, d16, z32), axis=1),
        jnp.sum(jnp.where(in_len, d16, z32), axis=1),
        jnp.sum(jnp.where(in_len, e16, z32), axis=1),
        jnp.sum(jnp.where(in_len, tq, z32), axis=1),
        jnp.sum((in_len & (fb == N_CODE)).astype(jnp.int32), axis=1),
        jnp.sum((fmask & (tb != N_CODE)).astype(jnp.int32), axis=1),
        jnp.any(sus & in_len, axis=1).astype(jnp.int32),
    ], axis=1).astype(jnp.int32)
    return (stats, fb.astype(jnp.uint8), fq.astype(jnp.uint8),
            d.astype(jnp.uint16), e.astype(jnp.uint16), qs, wp)


_consensus_segments_wire_filter_jit = _lazy_jit(
    static_argnames=_W_STATIC)(_wire_filter_fn)
_consensus_segments_wire_filter_donated_jit = _lazy_jit(
    static_argnames=_W_STATIC, donate_argnums=(0, 1))(_wire_filter_fn)


@_lazy_jit(static_argnames=("out_rows",))
def _filter_gather_jit(fb, fq, d16, e16, idx, out_rows):
    """Survivors-only gather over the fused filter kernel's resident
    columns: only the kept reads' masked bases/quals + depth/errors cross
    the link (6 B/position instead of 5.25 B/position for everyone)."""
    return (fb[idx][:out_rows], fq[idx][:out_rows],
            d16[idx][:out_rows], e16[idx][:out_rows])


@_lazy_jit(static_argnames=("out_rows",))
def _filter_gather_raw_jit(qs, wp, d16, e16, idx, out_rows):
    """Raw-column gather for suspect rows: the pre-threshold packed
    winner/qual/suspect words + depth/errors, exactly what the ordinary
    host completion (unpack + oracle patch) consumes."""
    return (qs[idx][:out_rows], wp[idx][:out_rows],
            d16[idx][:out_rows], e16[idx][:out_rows])


@_lazy_jit(static_argnames=("out_rows",))
def _duplex_combine_jit(tb, tq, obs, a_idx, b_idx, lens, out_rows):
    """Fused duplex strand-combine over stage-1 resident SS arrays.

    Integer-exact twin of the numpy combine in
    fast_duplex._serialize_outputs (every op is int32 select/clip
    arithmetic, so device and host agree bit-for-bit): gathers the AB/BA
    thresholded rows by index, combines base/qual, and recounts the
    per-position errors against the raw combined base from the resident
    per-lane observation sums — the SS pileups never re-cross the link;
    only the (K, L) combined outputs are fetched."""
    a_b = tb[a_idx].astype(jnp.int32)
    b_b = tb[b_idx].astype(jnp.int32)
    a_q = tq[a_idx].astype(jnp.int32)
    b_q = tq[b_idx].astype(jnp.int32)
    agree = a_b == b_b
    a_wins = (~agree) & (a_q > b_q)
    b_wins = (~agree) & (b_q > a_q)
    tie = (~agree) & (a_q == b_q)
    raw_base = jnp.where(agree | a_wins, a_b, b_b)
    raw_qual = jnp.where(
        agree, jnp.clip(a_q + b_q, MIN_PHRED, MAX_PHRED),
        jnp.where(a_wins, jnp.clip(a_q - b_q, MIN_PHRED, MAX_PHRED),
                  jnp.where(b_wins, jnp.clip(b_q - a_q, MIN_PHRED, MAX_PHRED),
                            MIN_PHRED)))
    either_n = (a_b == N_CODE) | (b_b == N_CODE)
    mask = either_n | (raw_qual == MIN_PHRED) | tie
    L = tb.shape[1]
    in_len = jnp.arange(L, dtype=jnp.int32)[None, :] < lens[:, None]
    out_b = jnp.where(in_len & ~mask, raw_base, N_CODE)
    out_b = jnp.where(in_len, out_b, 0)
    out_q = jnp.where(in_len & ~mask, raw_qual, MIN_PHRED)
    out_q = jnp.where(in_len, out_q, 0)
    # exact per-base errors vs the pre-mask raw duplex base: per side,
    # (valid obs) - (obs matching raw_base) == segment_depth_errors_ranges
    rb_l = jnp.minimum(raw_base, 3)[..., None]
    errs = jnp.zeros(a_b.shape, dtype=jnp.int32)
    for idx in (a_idx, b_idx):
        side = obs[idx]
        depth = jnp.sum(side, axis=-1)
        match = jnp.take_along_axis(side, rb_l, axis=-1)[..., 0]
        errs = errs + (depth - match)
    errs = jnp.where((raw_base == N_CODE) | ~in_len, 0, errs)
    return (out_b[:out_rows].astype(jnp.uint8),
            out_q[:out_rows].astype(jnp.uint8),
            jnp.minimum(errs, _I16_MAX)[:out_rows].astype(jnp.int32))


def _codec_combine_body(ba, bb, qa, qb, da, db, ea, eb):
    """CODEC concordance/duplex combine math (elementwise int32 select
    arithmetic end to end) — shared by the single-device jit and the
    shard_map mesh variant (zero collectives: every output element depends
    only on its own index)."""
    from ..constants import NO_CALL_BASE, NO_CALL_BASE_LOWER

    ba = ba.astype(jnp.int32)
    bb = bb.astype(jnp.int32)
    qa = qa.astype(jnp.int32)
    qb = qb.astype(jnp.int32)
    da = da.astype(jnp.int32)
    db = db.astype(jnp.int32)
    ea = ea.astype(jnp.int32)
    eb = eb.astype(jnp.int32)
    a_has = (ba != NO_CALL_BASE) & (ba != NO_CALL_BASE_LOWER)
    b_has = (bb != NO_CALL_BASE) & (bb != NO_CALL_BASE_LOWER)
    both = a_has & b_has
    agree = both & (ba == bb)
    a_wins = both & ~agree & (qa > qb)
    b_wins = both & ~agree & (qb > qa)
    tie = both & ~agree & (qa == qb)
    raw_base = jnp.where(b_wins, bb, ba)
    raw_qual = jnp.where(
        agree, jnp.minimum(93, qa + qb),
        jnp.where(a_wins, jnp.maximum(MIN_PHRED, qa - qb),
                  jnp.where(b_wins, jnp.maximum(MIN_PHRED, qb - qa),
                            jnp.where(tie, MIN_PHRED, 0))))
    q_masked = both & (raw_qual == MIN_PHRED)
    dup_base = jnp.where(q_masked, NO_CALL_BASE, raw_base)
    dup_qual = jnp.where(q_masked, MIN_PHRED, raw_qual)
    cap = lambda x: jnp.minimum(x, _I16_MAX)  # noqa: E731
    dup_depth = cap(da) + cap(db)
    chose_a = agree | a_wins | tie
    dup_err = jnp.where(agree, ea + eb,
                        jnp.where(chose_a, ea + jnp.maximum(db - eb, 0),
                                  eb + jnp.maximum(da - ea, 0)))
    only_a = a_has & ~b_has
    only_b = b_has & ~a_has
    a_q2 = qa == MIN_PHRED
    b_q2 = qb == MIN_PHRED
    base = jnp.where(
        both, dup_base,
        jnp.where(only_a, jnp.where(a_q2, NO_CALL_BASE, ba),
                  jnp.where(only_b, jnp.where(b_q2, NO_CALL_BASE, bb),
                            NO_CALL_BASE)))
    qual = jnp.where(
        both, dup_qual,
        jnp.where(only_a & ~a_q2, qa,
                  jnp.where(only_b & ~b_q2, qb, MIN_PHRED)))
    depth = jnp.where(both, dup_depth,
                      jnp.where(only_a, da, jnp.where(only_b, db, 0)))
    errors = jnp.where(both, dup_err,
                       jnp.where(only_a, ea,
                                 jnp.where(only_b, eb, cap(ea + eb))))
    n_mask = (ba == NO_CALL_BASE) | (bb == NO_CALL_BASE)
    base = jnp.where(n_mask, NO_CALL_BASE, base)
    qual = jnp.where(n_mask, MIN_PHRED, qual)
    return (base.astype(jnp.uint8), qual.astype(jnp.uint8),
            jnp.minimum(depth, 2 * _I16_MAX).astype(jnp.int32),
            jnp.minimum(errors, _I16_MAX).astype(jnp.int32),
            both, (a_wins | b_wins | tie))


@_lazy_jit(static_argnames=("out_rows",))
def _codec_combine_jit(ba, bb, qa, qb, da, db, ea, eb, out_rows):
    """CODEC concordance/duplex combine as a device stage.

    Integer-exact twin of consensus/codec.combine_arrays over the batch
    engine's concatenated position arrays; inputs arrive post-oracle, so
    there is no suspect surface — device output equals the numpy combine
    bit-for-bit."""
    out = _codec_combine_body(ba, bb, qa, qb, da, db, ea, eb)
    return tuple(o[:out_rows] for o in out)


@_lazy_jit(static_argnames=("mesh",))
def _codec_combine_mesh_jit(ba, bb, qa, qb, da, db, ea, eb, mesh):
    """Mesh variant of the CODEC combine: the position axis shards over
    every mesh axis with explicit PartitionSpec rules — purely elementwise,
    so the shard_map body is the single-device body verbatim and the wire
    cost is one NamedSharding upload slice per device. The host slices the
    fetched result to the real row count (no static out_rows: a fetch
    slice would have to respect shard boundaries for no byte win)."""
    from jax.sharding import PartitionSpec as P

    spec = P(mesh.axis_names)
    mapped = shard_map_compat(_codec_combine_body, mesh=mesh,
                              in_specs=(spec,) * 8, out_specs=(spec,) * 6)
    return mapped(ba, bb, qa, qb, da, db, ea, eb)


def _packed2_split_fn(codes_packed, quals, seg_ids, correct_tab,
                      err_tab, ln_error_pre_umi, num_segments,
                      out_segments):
    """1.25 B/position fallback of the wire dispatch (batches with >63
    distinct quals): 2-bit packed codes + sentinel quals, split packed
    output + fetch slice."""
    winner, qual, _depth, _errors, suspect, _obs = _packed2_epilogue(
        codes_packed, quals, seg_ids, correct_tab, err_tab,
        ln_error_pre_umi, num_segments)
    return _pack_result_split(winner, qual, suspect, out_segments)


def _packed2_full_fn(codes_packed, quals, seg_ids, correct_tab, err_tab,
                     ln_error_pre_umi, num_segments, out_segments):
    """Full-column variant of the >63-distinct-quals fallback: same
    on-device depth/error counts as the full wire kernel."""
    winner, qual, depth, errors, suspect, _obs = _packed2_epilogue(
        codes_packed, quals, seg_ids, correct_tab, err_tab,
        ln_error_pre_umi, num_segments)
    qs, wp = _pack_result_split(winner, qual, suspect, out_segments)
    return (qs, wp, depth[:out_segments].astype(jnp.uint16),
            errors[:out_segments].astype(jnp.uint16))


_consensus_segments_packed2_jit = _lazy_jit(
    static_argnames=_W_STATIC)(_packed2_split_fn)
_consensus_segments_packed2_donated_jit = _lazy_jit(
    static_argnames=_W_STATIC, donate_argnums=(0, 1))(_packed2_split_fn)
_consensus_segments_packed2_full_jit = _lazy_jit(
    static_argnames=_W_STATIC)(_packed2_full_fn)
_consensus_segments_packed2_full_donated_jit = _lazy_jit(
    static_argnames=_W_STATIC, donate_argnums=(0, 1))(_packed2_full_fn)


def build_wire(codes2d: np.ndarray, quals2d: np.ndarray, delta94: np.ndarray,
               out: np.ndarray = None):
    """Host-side wire build: (wire (N, L) uint8, dict64 (64,) f32) or None
    when the batch has more than 63 distinct quality values (fall back to
    the packed-codes layout). delta94 = correct_f32 - err_f32 per Phred.
    ``out``: optional preallocated (N, L) uint8 staging buffer (the
    feeder's recycled pool) filled in place instead of minting a fresh
    array per dispatch."""
    hist = np.bincount(quals2d.ravel(), minlength=256)
    vals = np.nonzero(hist)[0]
    if len(vals) > 63:
        return None
    lut = np.full(256, 63, dtype=np.uint8)
    lut[vals] = np.arange(len(vals), dtype=np.uint8)
    if out is not None:
        np.take(lut, quals2d, out=out)
        np.left_shift(out, 2, out=out)
        np.bitwise_or(out, np.minimum(codes2d, 3), out=out)
        wire = out
    else:
        wire = (lut[quals2d] << 2) | np.minimum(codes2d, 3)
    wire[codes2d == N_CODE] = WIRE_INVALID
    dict64 = np.zeros(64, dtype=np.float32)
    dict64[: len(vals)] = delta94[np.minimum(vals, MAX_PHRED)]
    return wire, dict64


def pack_codes2(codes2d: np.ndarray, quals2d: np.ndarray):
    """Fallback 1.25 B/position layout: 2-bit codes packed 4-per-byte along
    L plus qual bytes with QUAL_INVALID marking N/pad positions (quals are
    irrelevant there — the kernel zeroes their contribution)."""
    c = np.minimum(codes2d, 3).astype(np.uint8)
    N, L = c.shape
    c4 = c.reshape(N, L // 4, 4)
    cp = (c4[..., 0] | (c4[..., 1] << 2) | (c4[..., 2] << 4)
          | (c4[..., 3] << 6))
    q = np.where(codes2d == N_CODE, QUAL_INVALID, quals2d).astype(np.uint8)
    return np.ascontiguousarray(cp), q


def _columns_body(one_hot, delta, depths, ln_error_pre_umi, num_segments,
                  out_segments):
    """Shared hard-column reduction: per-observation (one_hot, delta) ->
    sliced split-packed per-column result. Segment ids are reconstructed on
    device from the depths (saves 4 B/obs of seg-id upload); the output
    packing delegates to _pack_result_split so the suspect-bit/2-bit-winner
    wire word has exactly one encoder."""
    n_rows = one_hot.shape[0]
    seg_ids = jnp.repeat(jnp.arange(num_segments, dtype=jnp.int32), depths,
                         total_repeat_length=n_rows)
    contrib = jax.ops.segment_sum(delta[:, None] * one_hot, seg_ids,
                                  num_segments=num_segments,
                                  indices_are_sorted=True)
    obs = jax.ops.segment_sum(one_hot, seg_ids, num_segments=num_segments,
                              indices_are_sorted=True).astype(jnp.int32)
    winner, qual, _depth, _errors, suspect = _call_epilogue(
        contrib, obs, ln_error_pre_umi)
    # (C,) columns pack as one L=4-wide pseudo-row group: same wire word
    qs, wp = _pack_result_split(winner.reshape(-1, 4),
                                qual.reshape(-1, 4),
                                suspect.reshape(-1, 4), out_segments // 4)
    return qs.reshape(-1)[:out_segments], wp.reshape(-1)


@_lazy_jit(static_argnames=("num_segments", "out_segments"))
def _consensus_columns_wire_jit(wire_obs, depths, dict_tab, ln_error_pre_umi,
                                num_segments, out_segments):
    """Hard-column consensus: a flat wire-format observation stream with
    per-column depths -> per-column (qual|suspect u8, 2-bit winner) packed.

    The device never sees easy columns (the native classify resolved them
    at byte-scan cost, fgumi_native.cc fgumi_consensus_classify); this
    kernel gets only the compute-worthy pileup columns, so the upload is
    ~1 byte per OBSERVATION of the hard few percent instead of 1 byte per
    position of everything."""
    one_hot, delta = _wire_terms(wire_obs, dict_tab)
    return _columns_body(one_hot, delta, depths, ln_error_pre_umi,
                         num_segments, out_segments)


@_lazy_jit(static_argnames=("num_segments", "out_segments"))
def _consensus_columns_raw_jit(codes_obs, quals_obs, depths, correct_tab,
                               err_tab, ln_error_pre_umi, num_segments,
                               out_segments):
    """2 B/observation fallback of the hard-column kernel (>63 distinct
    quals in the stream): raw codes+quals, N_CODE marks pad rows."""
    one_hot, delta = _observation_terms(codes_obs, quals_obs, correct_tab,
                                        err_tab)
    return _columns_body(one_hot, delta, depths, ln_error_pre_umi,
                         num_segments, out_segments)


@_lazy_jit(static_argnames=("num_segments",))
def _consensus_segments_packed_jit(codes, quals, seg_ids, correct_tab,
                                   err_tab, ln_error_pre_umi, num_segments):
    """Ragged-family variant: dense (N, L) read rows + sorted segment ids.

    One execution covers every family of a record batch regardless of family
    size — the per-execution relay overhead (~hundreds of ms through the
    tunnel) dwarfs the compute, so the hot path runs exactly one dispatch and
    one uint16 fetch per batch. Pad rows are all-N (zero contribution) and
    may use any in-range id.
    """
    return _segments_body(codes, quals, seg_ids, correct_tab, err_tab,
                          ln_error_pre_umi, num_segments)


@_lazy_jit(static_argnames=("num_segments", "mesh"))
def _consensus_segments_sharded_jit(codes, quals, seg_ids, correct_tab,
                                    err_tab, ln_error_pre_umi, num_segments,
                                    mesh):
    """dp-sharded ragged variant: (dp, N, L) rows -> (dp, num_segments, L).

    Families are embarrassingly parallel (SURVEY §5.7), so each device runs
    the segment body on its own contiguous slice of families — data parallel
    over the dp mesh axis with zero collectives in the hot path; the host
    splits jobs into balanced contiguous shards (consensus/fast.py).
    """
    from jax.sharding import PartitionSpec as P

    def local(c, q, s):
        return _segments_body(c[0], q[0], s[0], correct_tab, err_tab,
                              ln_error_pre_umi, num_segments)[None]

    # shard the leading axis over every mesh axis (a dp-only mesh has sp=1)
    spec = P(tuple(mesh.axis_names))
    mapped = shard_map_compat(local, mesh=mesh,
                              in_specs=(spec, spec, spec), out_specs=spec)
    return mapped(codes, quals, seg_ids)


@_lazy_jit(static_argnames=("num_segments", "mesh"))
def _consensus_segments_dp_sp_jit(codes, quals, seg_ids, correct_tab,
                                  err_tab, ln_error_pre_umi, num_segments,
                                  mesh):
    """(dp, sp) ragged variant: (dp, sp, N, L) rows -> (dp, num_segments, L).

    The read axis shards over sp: each sp rank segment-sums its local rows'
    contributions, one psum over "sp" combines them (the only collective in
    the hot path, riding ICI — parallel/mesh.py design note), and the
    epilogue runs replicated. Segments may span sp chunk boundaries freely:
    partial sums are exact under addition. This is the production analog of
    the uniform-R sharded_consensus_fn, for the dense segment layout the
    fast engines actually ship (VERDICT r2 weakness 5)."""
    from jax.sharding import PartitionSpec as P

    def local(c, q, s):
        c, q, s = c[0, 0], q[0, 0], s[0, 0]
        one_hot, delta = _observation_terms(c, q, correct_tab, err_tab)
        row_contrib = delta[..., None] * one_hot
        contrib = jax.ops.segment_sum(row_contrib, s,
                                      num_segments=num_segments,
                                      indices_are_sorted=True)
        obs = jax.ops.segment_sum(one_hot, s, num_segments=num_segments,
                                  indices_are_sorted=True)
        contrib = jax.lax.psum(contrib, "sp")
        obs = jax.lax.psum(obs, "sp").astype(jnp.int32)
        winner, qual, _depth, _errors, suspect = _call_epilogue(
            contrib, obs, ln_error_pre_umi)
        return _pack_result(winner, qual, suspect)[None]

    spec = P("dp", "sp")
    mapped = shard_map_compat(local, mesh=mesh,
                              in_specs=(spec, spec, spec),
                              out_specs=P("dp"))
    return mapped(codes, quals, seg_ids)


# ---------------------------------------------------------------------------
# Production mesh compile path (ISSUE 10): shard_map-wrapped variants of the
# full-column wire kernels with explicit PartitionSpec rules. The host packs
# the dense row layout into dp x sp chunks (pad_segments_mesh); each (d, s)
# shard segment-sums its local rows' contributions over its dp shard's LOCAL
# segment ids, one psum over "sp" combines the read-axis partials (the only
# collective in the hot path), and the epilogue + wire packing run per dp
# shard. Outputs concatenate over "dp" to the (dp * F_loc, ...) global
# layout; the host's gather index (mesh_gather) restores family order at
# resolve time. A 1-device mesh never reaches these: the callers fall back
# to the single-device jit path (SNIPPETS [3]'s mesh-size-aware compile).
# ---------------------------------------------------------------------------

def _wire_mesh_local(wire, seg_ids, dict_tab, ln_error_pre_umi, num_local):
    """Per-shard body of the mesh wire kernels: local segment reduction,
    sp psum combine, shared epilogue. Returns the epilogue tuple + obs."""
    one_hot, delta = _wire_terms(wire, dict_tab)
    row_contrib = delta[..., None] * one_hot
    contrib = jax.ops.segment_sum(row_contrib, seg_ids,
                                  num_segments=num_local,
                                  indices_are_sorted=True)
    obs = jax.ops.segment_sum(one_hot, seg_ids, num_segments=num_local,
                              indices_are_sorted=True)
    contrib = jax.lax.psum(contrib, "sp")
    obs = jax.lax.psum(obs, "sp").astype(jnp.int32)
    return _call_epilogue(contrib, obs, ln_error_pre_umi) + (obs,)


@_lazy_jit(static_argnames=("num_local", "mesh", "full"))
def _consensus_segments_wire_mesh_jit(wire, seg_ids, dict_tab,
                                      ln_error_pre_umi, num_local, mesh,
                                      full):
    """Mesh variant of _consensus_segments_wire_{jit,full_jit}.

    wire/seg_ids: (dp * sp * N_chunk, L) / (dp * sp * N_chunk,) in the
    chunked global layout (pad_segments_mesh), row axis sharded over every
    mesh axis. Returns (dp * F_loc, ...) outputs sharded along dp."""
    from jax.sharding import PartitionSpec as P

    rows = P(mesh.axis_names)
    out = P("dp")

    def local(w, s):
        winner, qual, depth, errors, suspect, _obs = _wire_mesh_local(
            w, s, dict_tab, ln_error_pre_umi, num_local)
        qs, wp = _pack_result_split(winner, qual, suspect, num_local)
        if full:
            return (qs, wp, depth.astype(jnp.uint16),
                    errors.astype(jnp.uint16))
        return qs, wp

    mapped = shard_map_compat(local, mesh=mesh, in_specs=(rows, rows),
                              out_specs=(out,) * (4 if full else 2))
    return mapped(wire, seg_ids)


@_lazy_jit(static_argnames=("num_local", "mesh"))
def _consensus_segments_wire_resident_mesh_jit(wire, seg_ids, dict_tab,
                                               ln_error_pre_umi, min_reads,
                                               min_qual, num_local, mesh):
    """Mesh variant of the resident wire kernel: full-column outputs plus
    device-resident thresholded (tb, tq) + per-lane obs, all sharded along
    dp in the shard-ordered (dp * F_loc, ...) layout. The fused duplex
    combine consumes the resident arrays through the ordinary jit
    (_duplex_combine_jit) — XLA partitions its gathers over the mesh, the
    pjit-style half of the compile path (SNIPPETS [1]/[3])."""
    from jax.sharding import PartitionSpec as P

    rows = P(mesh.axis_names)
    out = P("dp")

    def local(w, s):
        winner, qual, depth, errors, suspect, obs = _wire_mesh_local(
            w, s, dict_tab, ln_error_pre_umi, num_local)
        qs, wp = _pack_result_split(winner, qual, suspect, num_local)
        low_depth = depth < min_reads
        low_qual = qual < min_qual
        tb = jnp.where(low_depth | low_qual, N_CODE,
                       winner).astype(jnp.uint8)
        tq = jnp.where(low_depth, 0,
                       jnp.where(low_qual, MIN_PHRED,
                                 qual)).astype(jnp.uint8)
        return (qs, wp, depth.astype(jnp.uint16),
                errors.astype(jnp.uint16), tb, tq, obs)

    mapped = shard_map_compat(local, mesh=mesh, in_specs=(rows, rows),
                              out_specs=(out,) * 7)
    return mapped(wire, seg_ids)


@_lazy_jit(static_argnames=("num_local", "mesh", "full"))
def _consensus_segments_packed2_mesh_jit(codes_packed, quals, seg_ids,
                                         correct_tab, err_tab,
                                         ln_error_pre_umi, num_local, mesh,
                                         full):
    """Mesh variant of the 1.25 B/position >63-distinct-quals fallback
    (_consensus_segments_packed2_{jit,full_jit}): same chunked row layout,
    2-bit packed codes + sentinel quals sharded over every mesh axis."""
    from jax.sharding import PartitionSpec as P

    rows = P(mesh.axis_names)
    out = P("dp")

    def local(cp, q, s):
        one_hot, delta = _packed2_terms(cp, q, correct_tab, err_tab)
        row_contrib = delta[..., None] * one_hot
        contrib = jax.ops.segment_sum(row_contrib, s,
                                      num_segments=num_local,
                                      indices_are_sorted=True)
        obs = jax.ops.segment_sum(one_hot, s, num_segments=num_local,
                                  indices_are_sorted=True)
        contrib = jax.lax.psum(contrib, "sp")
        obs = jax.lax.psum(obs, "sp").astype(jnp.int32)
        winner, qual, depth, errors, suspect = _call_epilogue(
            contrib, obs, ln_error_pre_umi)
        qs, wp = _pack_result_split(winner, qual, suspect, num_local)
        if full:
            return (qs, wp, depth.astype(jnp.uint16),
                    errors.astype(jnp.uint16))
        return qs, wp

    mapped = shard_map_compat(local, mesh=mesh,
                              in_specs=(rows, rows, rows),
                              out_specs=(out,) * (4 if full else 2))
    return mapped(codes_packed, quals, seg_ids)


@_lazy_jit
def _consensus_batch_packed_jit(codes, quals, correct_tab, err_tab,
                                ln_error_pre_umi):
    """Packed variant: one (F, L) uint16 output, qual | winner<<7 | suspect<<10.

    The device->host link is the scarce resource (~30 MB/s through the tunnel,
    vs ~1.3 GB/s up), so the device returns 2 bytes/position — only what the
    host cannot cheaply recompute: depth and errors are pure integer counts
    over the uint8 codes the host already holds (ConsensusKernel._host_counts),
    and qual (7 bits), winner (3 bits), suspect (1 bit) share one uint16.
    """
    winner, qual, _depth, _errors, suspect = _consensus_batch_jit(
        codes, quals, correct_tab, err_tab, ln_error_pre_umi)
    return _pack_result(winner, qual, suspect)


def _pad_rows(n: int) -> int:
    """Row-count bucket: smallest shape-registry ladder value >= n.

    The geometric ladder (ops/datapath.py, default x1.0625 steps aligned
    to 16, configurable via --shape-buckets / FGUMI_TPU_SHAPE_BUCKETS)
    replaces the old per-octave pow2-fraction scheme: waste is bounded by
    one ladder step (<= 6.25% worst case, ~3% expected, vs 41%/25%/12.5%
    at the old octave bottoms), the vocabulary of XLA row shapes is fixed
    per process AND per fleet — every run quantizes to the same ladder,
    so the persistent compile cache hits across runs instead of each
    run's batch sizes minting private shapes (VERDICT r4 item 5,
    BENCH_r05 padding_waste 7-10%).
    """
    return SHAPE_REGISTRY.bucket_rows(n)


def _pad_out_segments(j: int, f_pad: int) -> int:
    """Fetch-slice bucket for the real segment count: multiple of f_pad/8.

    segment_sum still runs over the bucketed f_pad, but only the first
    j-rounded-up segments cross the link — the padded tail was up to half
    the fetched bytes (VERDICT r4 items 4/5). <=8 slice shapes per f_pad
    keeps the jit vocabulary bounded."""
    m = max(f_pad // 8, 1)
    return min(-(-j // m) * m, f_pad)


def pad_segments(codes2d: np.ndarray, quals2d: np.ndarray,
                 counts: np.ndarray):
    """Bucket-pad a dense (N, L) row layout for device_call_segments.

    Returns (codes_dev, quals_dev, seg_ids, starts, num_segments): rows pad
    to the next shape-registry ladder bucket (_pad_rows) with all-N no-op
    rows carrying the LAST real segment's id (keeps seg_ids sorted without
    growing num_segments — kernel pad invariant), and num_segments pads to
    the registry's segment ladder so the XLA shape vocabulary stays tiny
    under the persistent compile cache. Shared by the fast simplex engine
    and the classic callers (VERDICT r2: one copy of this subtle pad
    logic).
    """
    counts = np.asarray(counts, dtype=np.int64)
    starts = np.concatenate(([0], np.cumsum(counts)))
    N = int(starts[-1])
    J = len(counts)
    N_pad = _pad_rows(N)
    F_pad = SHAPE_REGISTRY.bucket_segments(J)
    seg_ids = np.repeat(np.arange(J, dtype=np.int32), counts)
    DEVICE_STATS.add_pad(N, N_pad)
    if N_pad != N:
        L = codes2d.shape[1]
        pad_c = np.full((N_pad - N, L), N_CODE, dtype=np.uint8)
        pad_q = np.zeros((N_pad - N, L), dtype=np.uint8)
        codes_dev = np.concatenate([codes2d[:N], pad_c])
        quals_dev = np.concatenate([quals2d[:N], pad_q])
        seg_ids = np.concatenate(
            [seg_ids, np.full(N_pad - N, J - 1, dtype=np.int32)])
    else:
        codes_dev, quals_dev = codes2d, quals2d
    return codes_dev, quals_dev, seg_ids, starts, F_pad


def pad_segments_gather(codes: np.ndarray, quals: np.ndarray,
                        rows: np.ndarray, L_max: int, counts: np.ndarray):
    """Fused gather + bucket-pad: one copy instead of pad_segments' two.

    Gathers `rows` out of the packed (R, L_stride) arrays directly into the
    padded (N_pad, L_max) device layout (same pad invariants as
    pad_segments). Returns (codes_dev, quals_dev, seg_ids, starts, F_pad, N);
    codes_dev[:N] / quals_dev[:N] are the dense views resolve_segments needs.
    """
    counts = np.asarray(counts, dtype=np.int64)
    starts = np.concatenate(([0], np.cumsum(counts)))
    N = int(starts[-1])
    J = len(counts)
    N_pad = _pad_rows(N)
    F_pad = SHAPE_REGISTRY.bucket_segments(J)
    DEVICE_STATS.add_pad(N, N_pad)
    codes_dev = np.full((N_pad, L_max), N_CODE, dtype=np.uint8)
    quals_dev = np.zeros((N_pad, L_max), dtype=np.uint8)
    codes_dev[:N] = codes[rows, :L_max]
    quals_dev[:N] = quals[rows, :L_max]
    seg_ids = np.full(N_pad, max(J - 1, 0), dtype=np.int32)
    seg_ids[:N] = np.repeat(np.arange(J, dtype=np.int32), counts)
    return codes_dev, quals_dev, seg_ids, starts, F_pad, N


def pad_segments_mesh(codes2d: np.ndarray, quals2d: np.ndarray,
                      counts: np.ndarray, mesh):
    """Chunked global row layout for the shard_map wire kernels.

    Splits the J families into dp contiguous shards (row-balanced where
    that stays within the per-shard segment bucket, equal-count otherwise),
    splits each shard's rows into sp contiguous chunks, and pads every
    chunk to a common ladder-bucketed N_chunk — so the global
    (dp * sp * N_chunk, L) array shards evenly over the mesh with
    ``PartitionSpec(mesh.axis_names)`` and every ``jax.device_put`` lands
    one slice per device (the overlapping per-shard upload, ISSUE 10 (b)).
    Segment ids are LOCAL to each dp shard (0..F_loc-1, sorted within
    every chunk; pad rows carry their chunk's last real id — all-N no-ops,
    the pad_segments invariant). The family axis rounds to dp * F_loc with
    F_loc from the same 8-aligned segment ladder as the single-device
    path, one shape vocabulary across mesh sizes.

    Returns (codes_g, quals_g, seg_g, starts, F_loc, gather) where
    ``gather[j]`` is family j's row in the (dp * F_loc, ...) shard-ordered
    device output (resolve_segments_wire applies it).
    """
    from ..consensus.fast import split_row_balanced

    counts = np.asarray(counts, dtype=np.int64)
    starts = np.concatenate(([0], np.cumsum(counts)))
    J = len(counts)
    N = int(starts[-1])
    dp = int(mesh.shape["dp"])
    sp = int(dict(mesh.shape).get("sp", 1))
    L = codes2d.shape[1]
    F_loc = SHAPE_REGISTRY.bucket_segments_sharded(J, dp)
    jb = split_row_balanced(counts, dp) if J else np.zeros(dp + 1, np.int64)
    if J and int(np.diff(jb).max()) > F_loc:
        # a row-balanced split that overflows the per-shard segment bucket
        # (deep-family skew) falls back to equal family counts: the static
        # shape stays a function of (J, dp) only, never of the skew
        per = -(-J // dp)
        jb = np.minimum(np.arange(dp + 1, dtype=np.int64) * per, J)
    n_rows = starts[jb[1:]] - starts[jb[:-1]]
    chunk = -(-np.maximum(n_rows, 1) // sp)
    N_chunk = _pad_rows(int(chunk.max()) if J else 1)
    codes_g = np.full((dp * sp * N_chunk, L), N_CODE, dtype=np.uint8)
    quals_g = np.zeros((dp * sp * N_chunk, L), dtype=np.uint8)
    seg_g = np.zeros(dp * sp * N_chunk, dtype=np.int32)
    gather = np.zeros(J, dtype=np.int64)
    for d in range(dp):
        lo_j, hi_j = int(jb[d]), int(jb[d + 1])
        if hi_j <= lo_j:
            continue
        base = int(starts[lo_j])
        n = int(starts[hi_j]) - base
        seg_local = np.repeat(
            np.arange(hi_j - lo_j, dtype=np.int32),
            counts[lo_j:hi_j])
        c = int(chunk[d])
        for s in range(sp):
            lo = min(s * c, n)
            hi = min(lo + c, n)
            m = hi - lo
            row0 = (d * sp + s) * N_chunk
            if m:
                codes_g[row0:row0 + m] = codes2d[base + lo:base + hi]
                quals_g[row0:row0 + m] = quals2d[base + lo:base + hi]
                seg_g[row0:row0 + m] = seg_local[lo:hi]
                seg_g[row0 + m:row0 + N_chunk] = seg_local[hi - 1]
        gather[lo_j:hi_j] = d * F_loc + np.arange(hi_j - lo_j)
    DEVICE_STATS.add_pad(N, dp * sp * N_chunk)
    return codes_g, quals_g, seg_g, starts, F_loc, gather


class _WirePlan:
    """One built-but-unsubmitted wire dispatch (ConsensusKernel.
    _wire_dispatch_plan): the dispatch closure plus everything the
    submitter needs to account/submit it — shared by the solo path and
    the cross-job coalescer (ops/coalesce.py)."""

    __slots__ = ("dispatch", "upload", "new", "staging", "filter_mode")

    def __init__(self, dispatch, upload, new, staging, filter_mode):
        self.dispatch = dispatch
        self.upload = upload
        self.new = new
        self.staging = staging
        self.filter_mode = filter_mode


def _unpack_device_result(packed: np.ndarray):
    """(winner uint8, qual uint8, suspect bool) from the packed uint16."""
    qual = (packed & 0x7F).astype(np.uint8)
    winner = ((packed >> 7) & 0x7).astype(np.uint8)
    suspect = (packed >> 10).astype(bool)
    return winner, qual, suspect


class ConsensusKernel:
    """Compiled batched consensus caller for one (pre, post) error-rate pair.

    Call with padded uint8 arrays codes/quals of shape (F, R, L); returns NumPy
    arrays (winner, qual, depth, errors) with all suspect positions already
    recomputed on host by the f64 oracle, so results are integer-exact against
    fgumi_tpu.ops.oracle by construction.
    """

    def __init__(self, tables: QualityTables):
        # f32 table casts stay host-side numpy: jit accepts them directly
        # (tiny per-dispatch transfer), and building jnp arrays here would
        # force backend init even when every dispatch routes to the host
        # engine. The persistent compile cache is enabled at first device
        # dispatch for the same reason.
        self.tables = tables
        self._correct_f32 = np.asarray(tables.adjusted_correct, dtype=np.float32)
        self._err_f32 = np.asarray(tables.adjusted_error_per_alt, dtype=np.float32)
        self._pre = np.float32(tables.ln_error_pre_umi)
        self.fallback_positions = 0
        self.total_positions = 0
        # fallback counters are updated from whichever thread resolves a
        # dispatch (the pipeline's writer stage as well as the caller thread)
        self._counter_lock = threading.Lock()
        self._host_engine = None
        self._use_host = None
        self._hybrid = None
        self._delta94 = self._correct_f32 - self._err_f32
        self._coalesce_key_cache = None

    def host_mode(self) -> bool:
        """True when segment dispatches should run on the native f64 host
        engine instead of XLA (ops/host_kernel.py): no accelerator attached
        (jax backend == cpu) and the native library is available.
        FGUMI_TPU_HOST_ENGINE=1/0 forces either way (parity tests run both)."""
        if self._use_host is None:
            self._use_host = use_host_engine()
        return self._use_host

    def set_force_device(self, force: bool = True):
        """Public pin to the XLA device path (ADVICE r4: benches were poking
        the private _use_host cache). force=False re-enables auto."""
        self._use_host = False if force else None

    def hybrid_mode(self) -> bool:
        """True when an accelerator is attached AND the native f64 host
        engine is available: batches the device link cannot absorb run on
        the host engine concurrently, so throughput is device + host rather
        than min(device, host) (the round-5 answer to 'the TPU loses to its
        own host engine'). FGUMI_TPU_HYBRID=0 disables (device-only)."""
        if self._hybrid is None:
            import os

            if self.host_mode():
                self._hybrid = False
            else:
                from ..native import batch as nb

                env = os.environ.get("FGUMI_TPU_HYBRID", "auto").lower()
                self._hybrid = (env not in ("0", "false", "off")
                                and nb.available())
        return self._hybrid

    def _host(self):
        if self._host_engine is None:
            from .host_kernel import HostConsensusEngine

            self._host_engine = HostConsensusEngine(self.tables)
        return self._host_engine

    def _tables_dev(self):
        """Device-resident quality tables via the process-wide constant
        cache: uploaded once per (device, content), reused by every later
        dispatch of any kernel instance with the same error rates. Callers
        run inside dispatch closures, after jax init."""
        return (CONST_CACHE.put("correct_tab", self._correct_f32),
                CONST_CACHE.put("err_tab", self._err_f32))

    def _coalesce_key(self) -> str:
        """Constant-table content fingerprint for cross-job merge
        compatibility (ops/coalesce.py): two kernels whose f32 quality
        tables and pre-UMI prior are byte-identical produce identical
        per-family results inside a merged dispatch — the wire dictionary
        re-indexes the same delta values, and every suspect/oracle gate is
        derived from them. Content-keyed like the constant cache, so warm
        serve jobs with the same error rates merge across kernel
        instances."""
        if self._coalesce_key_cache is None:
            import hashlib

            h = hashlib.blake2b(digest_size=16)
            h.update(self._correct_f32.tobytes())
            h.update(self._err_f32.tobytes())
            h.update(np.float32(self._pre).tobytes())
            self._coalesce_key_cache = h.hexdigest()
        return self._coalesce_key_cache

    def device_call(self, codes, quals):
        """Raw device outputs (winner, qual, depth, errors, suspect) as jax arrays."""
        codes = as_device_operand(codes)
        quals = as_device_operand(quals)
        _ensure_jax()
        ct, et = self._tables_dev()
        return _consensus_batch_jit(codes, quals, ct, et, self._pre)

    def device_call_packed(self, codes, quals):
        """One (F, L) uint16 device output (see _consensus_batch_packed_jit).

        2 bytes/position crosses the link instead of 17 (4 x int32 + bool), and
        one fetch instead of five; depth/errors come from _host_counts.
        """
        F, R, L = codes.shape
        DEVICE_STATS.add_dispatch(segments_flops(F * R, L, F))
        codes = as_device_operand(codes)
        quals = as_device_operand(quals)
        new = SHAPE_REGISTRY.observe("batch", F, R, L)

        def _dispatch():
            _ensure_jax()
            ct, et = self._tables_dev()
            return _consensus_batch_packed_jit(codes, quals, ct, et,
                                               self._pre)

        def _bounded():
            with SHAPE_REGISTRY.attribute_compiles(new):
                return device_retry_call(_dispatch, "batch dispatch")

        # sync path: the dispatch itself runs under the deadline (a wedged
        # device_put/jit call would otherwise hang the CALLER thread
        # unboundedly — the async paths get the same protection from the
        # feeder's bounded ticket wait). __call__ degrades the overrun.
        return _DISPATCH_RUNNER.run(_bounded, dispatch_deadline_s(),
                                    "batch dispatch")

    @staticmethod
    def _host_counts(codes: np.ndarray, winner: np.ndarray):
        """depth/errors (F, L) int32 recomputed from host-resident codes.

        depth = valid (non-N) observations per position; errors = valid
        observations disagreeing with the winner (all of them when the winner
        is N) — exactly _call_epilogue's obs arithmetic, in integer space.
        """
        valid = codes != N_CODE
        depth = valid.sum(axis=-2, dtype=np.int32)
        winner_obs = ((codes == winner[..., None, :]) & valid).sum(
            axis=-2, dtype=np.int32)
        return depth, depth - winner_obs

    def resolve_packed(self, dev, codes: np.ndarray, quals: np.ndarray):
        """Fetch + unpack a device_call_packed result: host depth/error counts,
        counter updates, and exact f64 fallback on suspect positions.

        Thread-safe; this is the single completion path shared by the direct
        __call__ and the pipeline's deferred (writer-stage) resolution.
        """
        try:
            packed = _fetch_with_deadline(dev, dispatch_deadline_s())
        except DeadlineExceeded as e:
            return self._recover_packed(e, codes, quals, overran=True)
        except BaseException as e:  # noqa: BLE001 - classified below
            if not (_is_oom(e) or _is_transient(e)):
                raise
            return self._recover_packed(e, codes, quals)
        from .breaker import BREAKER

        BREAKER.record_success()  # clean resolve: resets the failure score
        winner, qual, suspect = _unpack_device_result(packed)
        depth, errors = self._host_counts(codes, winner)
        depth = depth.astype(np.int64)
        errors = errors.astype(np.int64)
        self._count_suspects(suspect)
        if suspect.any():
            self._oracle_patch(suspect, winner, qual, depth, errors,
                               lambda f: (codes[f], quals[f]))
        return winner, qual, depth, errors

    def _recover_packed(self, exc, codes: np.ndarray, quals: np.ndarray,
                        overran: bool = False):
        """Host-engine completion of a failed uniform-batch fetch: the
        (F, R, L) batch is one R-row segment per family for the native f64
        engine. Re-raises when the native library is unavailable.
        ``overran``: the fetch hit its dispatch deadline rather than
        erroring — counted and breaker-fed as a wedge, not a failure."""
        from ..native import batch as nb

        if not nb.available():
            raise exc
        from .breaker import BREAKER

        F, R, L = codes.shape
        if overran:
            DEVICE_STATS.add_deadline_fallback()
            BREAKER.record_deadline_overrun()
        else:
            DEVICE_STATS.add_host_fallback()
            if not _is_oom(exc):
                BREAKER.record_transient_failure()
        log.warning(
            "device fetch %s (%s: %s); computing %d "
            "families on the native f64 host engine",
            "overran its deadline" if overran else "failed after retries",
            type(exc).__name__, exc, F)
        starts = np.arange(F + 1, dtype=np.int64) * R
        engine = self._host()
        winner, qual, depth, errors, n_slow = engine.call_segments_counted(
            codes.reshape(F * R, L), quals.reshape(F * R, L), starts)
        with self._counter_lock:
            self.total_positions += winner.size
            self.fallback_positions += n_slow
        return (winner, qual, depth.astype(np.int64),
                errors.astype(np.int64))

    def __call__(self, codes: np.ndarray, quals: np.ndarray):
        try:
            dev = self.device_call_packed(codes, quals)
        except DeadlineExceeded as e:
            return self._recover_packed(e, codes, quals, overran=True)
        except BaseException as e:  # noqa: BLE001 - classified below
            # dispatch-time failure (sync path): same degradation contract
            # as the resolve paths — OOM or exhausted retries run the batch
            # on the native f64 host engine rather than aborting the run
            if not (_is_oom(e) or _is_transient(e)):
                raise
            return self._recover_packed(e, codes, quals)
        return self.resolve_packed(dev, codes, quals)

    # ------------------------------------------------------- ragged (segment)

    def device_call_segments(self, codes2d, quals2d, seg_ids,
                             num_segments: int):
        """Dispatch dense (N, L) read rows with sorted per-row segment ids.

        In host mode this is a no-op returning HOST_DISPATCH: the matching
        resolve_segments call runs the native f64 engine on the unpadded
        rows it receives, so callers that pre-padded simply wasted the pad
        (the hot simplex path skips padding entirely in host mode)."""
        if self.host_mode():
            return HOST_DISPATCH
        DEVICE_STATS.add_dispatch(segments_flops(
            codes2d.shape[0], codes2d.shape[1], num_segments))
        codes2d = as_device_operand(codes2d)
        quals2d = as_device_operand(quals2d)
        seg_ids = as_device_operand(seg_ids)
        new = SHAPE_REGISTRY.observe("seg", codes2d.shape[0],
                                     codes2d.shape[1], num_segments)

        def _dispatch():
            _ensure_jax()
            ct, et = self._tables_dev()
            return _consensus_segments_packed_jit(
                codes2d, quals2d, seg_ids, ct, et, self._pre, num_segments)

        def _bounded():
            with SHAPE_REGISTRY.attribute_compiles(new):
                return device_retry_call(_dispatch, "segment dispatch")

        try:
            # sync path: the dispatch itself runs under the deadline (see
            # device_call_packed) — a wedge here must not hang the caller
            return _DISPATCH_RUNNER.run(_bounded, dispatch_deadline_s(),
                                        "segment dispatch")
        except DeadlineExceeded as e:
            from ..native import batch as nb

            if not nb.available():
                raise  # nothing to degrade to
            from .breaker import BREAKER

            DEVICE_STATS.add_deadline_fallback()
            BREAKER.record_deadline_overrun()
            log.warning(
                "device dispatch overran its deadline (%s); completing on "
                "the native f64 host engine", e)
            # the matching resolve_segments call completes byte-identically
            # on the unpadded rows it receives
            return HOST_DISPATCH

    def dispatch_segments(self, codes2d, quals2d, counts):
        """Pad + dispatch ragged segments, or skip both in host mode.

        The one-stop shop for single-device callers holding dense (N, L)
        rows and per-segment counts: returns (dev, starts) for the matching
        resolve_segments(dev, codes2d, quals2d, starts) call. In host mode
        no padded copies are built and no DEVICE_STATS pad rows are charged
        — the native f64 engine reads the dense rows directly."""
        if self.host_mode():
            starts = np.concatenate(([0], np.cumsum(counts)))
            return HOST_DISPATCH, starts
        codes_dev, quals_dev, seg_ids, starts, F_pad = pad_segments(
            codes2d, quals2d, counts)
        return (self.device_call_segments(codes_dev, quals_dev, seg_ids,
                                          F_pad), starts)

    def device_call_segments_wire(self, codes2d_padded, quals2d_padded,
                                  seg_ids, num_segments: int, J: int,
                                  pack_t0: float = None, full: bool = False,
                                  resident_thresholds=None,
                                  pred_s: float = None, mesh=None,
                                  mesh_gather=None, filter_params=None):
        """Async wire-format dispatch via the feeder pipeline.

        codes2d_padded/quals2d_padded: the full padded (N_pad, L) row layout
        (L % 4 == 0). Builds the 1-byte wire (or the 1.25 B/position
        packed-codes fallback when the batch has >63 distinct quals),
        submits the upload + jit dispatch
        to the feeder thread, and returns a DispatchTicket immediately —
        the processing thread never blocks on the link, and with feeder
        depth >= 2 this batch's upload overlaps the previous batch's
        device compute. The wire dictionary rides the constant cache (a
        stable sequencer qual set re-uploads nothing). ``pack_t0``: when
        the caller timed its own gather/pad start, the timeline's pack_s
        covers it too. Resolve with
        resolve_segments_wire(ticket, dense_codes, dense_quals, starts).

        ``full=True`` selects the full-column kernels: depth/errors are
        computed on device and fetched as uint16 (+4 B/column), so the
        resolve never re-walks the dense rows — callers must gate on max
        family size < 65536 (the engines do, from their counts arrays).
        ``resident_thresholds=(min_reads, min_qual)`` additionally keeps
        thresholded (tb, tq) + per-lane obs device-resident for the fused
        duplex combine stage (wire layout only; the rare >63-qual fallback
        ignores it and the combine runs on host). ``pred_s``: the cost
        model's predicted dispatch seconds, stamped into the timeline.

        ``filter_params=(min_reads, min_qual, lens_padded, DeviceFilterParams)``
        selects the fused consensus→filter kernel (ISSUE 11): per-read
        stats are the only default fetch, every column stays resident for
        the survivors-only gather, and the ticket resolves through
        :meth:`resolve_segments_wire_filtered`. Wire layout only (callers
        must pass ``full=True``); the >63-distinct-quals fallback silently
        dispatches the ordinary full-column kernel instead and the filter
        runs host-side on the fetched columns (``ticket.filter_mode``
        records which happened).

        ``mesh``: a live jax Mesh with > 1 device selects the shard_map
        compile path — the inputs must be in pad_segments_mesh's chunked
        layout with ``num_segments`` the PER-SHARD F_loc and
        ``mesh_gather`` its family-order gather; uploads go through
        ``jax.device_put(..., NamedSharding)`` so every device's slice
        copies concurrently, and the device output is the shard-ordered
        (dp * F_loc, ...) global that resolve_segments_wire re-gathers.
        A 1-device (or None) mesh is exactly the legacy single-device
        path — bit-for-bit, including the compiled executables."""
        t_pack0 = pack_t0 if pack_t0 is not None else time.monotonic()
        mesh_active = mesh is not None and mesh.size > 1
        if mesh_active:
            return self._dispatch_wire_mesh(
                codes2d_padded, quals2d_padded, seg_ids, num_segments, J,
                t_pack0, full, resident_thresholds, pred_s, mesh,
                mesh_gather)
        if resident_thresholds is None and filter_params is None:
            # cross-job coalescing seam (ops/coalesce.py): while the serve
            # daemon's merge window is armed, compatible plain wire
            # dispatches from concurrent jobs merge into one device launch.
            # The CoalescedTicket resolves through the same
            # resolve_segments_wire call — sliced back per partner there.
            from .coalesce import COALESCER

            merged = COALESCER.maybe_submit(
                self, codes2d_padded, quals2d_padded, seg_ids,
                num_segments, J, full=full, pack_t0=t_pack0, pred_s=pred_s)
            if merged is not None:
                return merged
        plan = self._wire_dispatch_plan(
            codes2d_padded, quals2d_padded, seg_ids, num_segments, J,
            full=full, resident_thresholds=resident_thresholds,
            filter_params=filter_params)
        DEVICE_STATS.add_dispatch(segments_flops(
            codes2d_padded.shape[0], codes2d_padded.shape[1], num_segments))
        slot = DEVICE_STATS.begin_in_flight(
            plan.upload, pack_s=time.monotonic() - t_pack0)
        if pred_s is not None:
            DEVICE_STATS.note_pred(slot, pred_s)
        with SHAPE_REGISTRY.attribute_compiles(plan.new):
            ticket = DEVICE_FEEDER.submit(
                lambda: device_retry_call(lambda: plan.dispatch(slot),
                                          "wire dispatch"),
                upload_bytes=plan.upload, slot=slot)
        ticket.filter_mode = plan.filter_mode
        if plan.filter_mode:
            # retained for the sentinel's fused-route audit tap
            # (resolve_segments_wire_filtered -> SENTINEL.maybe_audit_filter)
            ticket.filter_ctx = filter_params
        if plan.staging:
            ticket.staging = plan.staging
        return ticket

    def _wire_dispatch_plan(self, codes2d_padded, quals2d_padded, seg_ids,
                            num_segments: int, J: int, full: bool = False,
                            resident_thresholds=None, filter_params=None):
        """Build — but do not submit — one wire-layout dispatch.

        The shared dispatch seam of the solo path and the cross-job
        coalescer (ops/coalesce.py): the coalescer builds a merged row
        layout and submits this plan under its own per-partner
        accounting. Returns a :class:`_WirePlan` holding the dispatch
        closure (runs on the feeder thread), the upload byte count for
        the feeder's governed budget, the shape-registry new-shape flag,
        the pooled staging buffers to recycle at resolve, and whether the
        fused-filter kernel was actually selected."""
        out_segments = _pad_out_segments(J, num_segments)
        from .datapath import STAGING_POOL

        staging = [STAGING_POOL.acquire(codes2d_padded.shape, np.uint8)]
        w = build_wire(codes2d_padded, quals2d_padded, self._delta94,
                       out=staging[0])
        pre = self._pre
        tables_dev = self._tables_dev
        filt = filter_params is not None
        if w is not None:
            wire, dict32 = w
            upload = wire.nbytes + seg_ids.nbytes
            resident = resident_thresholds is not None
            # ISSUE 19: the hand-tiled Pallas kernel covers the
            # full-column and fused-filter wire dispatches; resident
            # (duplex-combine), plain, packed2 and mesh stay XLA. The
            # backend is pinned at plan-build time so the shape registry
            # attributes compiles to the kernel that actually runs.
            use_pallas = False
            if filt or (full and not resident):
                from . import pallas_kernel as _pk

                use_pallas = _pk.selected_backend() == "pallas"
            kind = (("segwxp" if use_pallas else "segwx") if filt
                    else "segwr" if resident
                    else (("segwfp" if use_pallas else "segwf") if full
                          else "segw"))
            new = SHAPE_REGISTRY.observe(
                kind, wire.shape[0], wire.shape[1], num_segments,
                out_segments)
            if resident:
                mr, mq = (np.int32(resident_thresholds[0]),
                          np.int32(resident_thresholds[1]))
            if filt:
                mr, mq, lens_j, fparams = filter_params
                lens_pad = np.zeros(out_segments, dtype=np.int32)
                lens_pad[:J] = lens_j

            def _dispatch(slot):
                _ensure_jax()
                if use_pallas:
                    # Pallas manages its own blocks — upload donation is
                    # a no-op here (not counted), and the wire dictionary
                    # rides the kernel's scalar-prefetch channel (256 B)
                    # instead of the constant cache.
                    from . import pallas_kernel as _pk

                    t0 = time.monotonic()
                    prep = _pk.upload(wire, seg_ids, dict32, num_segments)
                    DEVICE_STATS.note_upload(slot, time.monotonic() - t0)
                    DEVICE_STATS.add_kernel_backend(slot, "pallas")
                    if filt:
                        out = _pk.call_filter(prep, pre, mr, mq, lens_pad,
                                              fparams, out_segments)
                        return (out[0], ResidentHandles(out[1:]))
                    return _pk.call_full(prep, pre, out_segments)
                donate = upload_donation_enabled()
                t0 = time.monotonic()
                wd = jax.device_put(wire)
                sd = jax.device_put(seg_ids)
                dtab = CONST_CACHE.put("dict_tab", dict32)
                DEVICE_STATS.note_upload(slot, time.monotonic() - t0)
                DEVICE_STATS.add_kernel_backend(slot, "xla")
                if donate:
                    DEVICE_STATS.add_donated_upload()
                if filt:
                    ld = jax.device_put(lens_pad)
                    etab = CONST_CACHE.put("filter_emin", fparams.emin_tab)
                    fn = (_consensus_segments_wire_filter_donated_jit
                          if donate else _consensus_segments_wire_filter_jit)
                    out = fn(wd, sd, dtab, pre, mr, mq, ld,
                             fparams.min_reads, etab, fparams.min_base_q,
                             np.int32(1 if fparams.per_base else 0),
                             num_segments, out_segments)
                    return (out[0], ResidentHandles(out[1:]))
                if resident:
                    fn = (_consensus_segments_wire_resident_donated_jit
                          if donate
                          else _consensus_segments_wire_resident_jit)
                    out = fn(wd, sd, dtab, pre, mr, mq, num_segments,
                             out_segments)
                    return out[:4] + (ResidentHandles(out[4:]),)
                if full:
                    fn = (_consensus_segments_wire_full_donated_jit
                          if donate else _consensus_segments_wire_full_jit)
                    return fn(wd, sd, dtab, pre, num_segments, out_segments)
                fn = (_consensus_segments_wire_donated_jit if donate
                      else _consensus_segments_wire_jit)
                return fn(wd, sd, dtab, pre, num_segments, out_segments)
        else:
            STAGING_POOL.release(staging.pop())
            cp, qsent = pack_codes2(codes2d_padded, quals2d_padded)
            upload = cp.nbytes + qsent.nbytes + seg_ids.nbytes
            new = SHAPE_REGISTRY.observe(
                "segp2f" if full else "segp2", cp.shape[0], cp.shape[1],
                num_segments, out_segments)

            def _dispatch(slot):
                _ensure_jax()
                donate = upload_donation_enabled()
                t0 = time.monotonic()
                cd = jax.device_put(cp)
                qd = jax.device_put(qsent)
                sd = jax.device_put(seg_ids)
                ct, et = tables_dev()
                DEVICE_STATS.note_upload(slot, time.monotonic() - t0)
                DEVICE_STATS.add_kernel_backend(slot, "xla")
                if donate:
                    DEVICE_STATS.add_donated_upload()
                if full:
                    fn = (_consensus_segments_packed2_full_donated_jit
                          if donate else _consensus_segments_packed2_full_jit)
                else:
                    fn = (_consensus_segments_packed2_donated_jit
                          if donate else _consensus_segments_packed2_jit)
                return fn(cd, qd, sd, ct, et, pre, num_segments,
                          out_segments)
        return _WirePlan(_dispatch, upload, new, staging,
                         filt and w is not None)

    def _dispatch_wire_mesh(self, codes_g, quals_g, seg_g, F_loc: int,
                            J: int, t_pack0: float, full: bool,
                            resident_thresholds, pred_s, mesh, mesh_gather):
        """The mesh half of device_call_segments_wire: NamedSharding
        uploads + the shard_map wire kernels (see the caller's docstring).
        Split out so the single-device fast path stays exactly the legacy
        code path when no mesh is configured."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        rows_sh = NamedSharding(mesh, P(mesh.axis_names))
        repl_sh = NamedSharding(mesh, P())
        dp = int(mesh.shape["dp"])
        sp = int(dict(mesh.shape).get("sp", 1))
        pre = self._pre
        w = build_wire(codes_g, quals_g, self._delta94)
        if w is not None:
            wire, dict32 = w
            upload = wire.nbytes + seg_g.nbytes
            resident = resident_thresholds is not None
            kind = "segwrm" if resident else ("segwfm" if full else "segwm")
            new = SHAPE_REGISTRY.observe(
                kind, wire.shape[0], wire.shape[1], F_loc, dp, sp)
            if resident:
                mr, mq = (np.int32(resident_thresholds[0]),
                          np.int32(resident_thresholds[1]))

            def _dispatch(slot):
                _ensure_jax()
                t0 = time.monotonic()
                wd = jax.device_put(wire, rows_sh)
                sd = jax.device_put(seg_g, rows_sh)
                dtab = CONST_CACHE.put("dict_tab", dict32,
                                       sharding=repl_sh)
                DEVICE_STATS.note_upload(slot, time.monotonic() - t0)
                DEVICE_STATS.add_kernel_backend(slot, "xla")
                if resident:
                    out = _consensus_segments_wire_resident_mesh_jit(
                        wd, sd, dtab, pre, mr, mq, F_loc, mesh)
                    return out[:4] + (ResidentHandles(out[4:]),)
                return _consensus_segments_wire_mesh_jit(
                    wd, sd, dtab, pre, F_loc, mesh, full)
        else:
            cp, qsent = pack_codes2(codes_g, quals_g)
            upload = cp.nbytes + qsent.nbytes + seg_g.nbytes
            tables_dev = self._tables_dev
            new = SHAPE_REGISTRY.observe(
                "segp2fm" if full else "segp2m", cp.shape[0], cp.shape[1],
                F_loc, dp, sp)

            def _dispatch(slot):
                _ensure_jax()
                t0 = time.monotonic()
                cd = jax.device_put(cp, rows_sh)
                qd = jax.device_put(qsent, rows_sh)
                sd = jax.device_put(seg_g, rows_sh)
                ct = CONST_CACHE.put("correct_tab", self._correct_f32,
                                     sharding=repl_sh)
                et = CONST_CACHE.put("err_tab", self._err_f32,
                                     sharding=repl_sh)
                DEVICE_STATS.note_upload(slot, time.monotonic() - t0)
                DEVICE_STATS.add_kernel_backend(slot, "xla")
                return _consensus_segments_packed2_mesh_jit(
                    cd, qd, sd, ct, et, pre, F_loc, mesh, full)
        DEVICE_STATS.add_dispatch(segments_flops(
            codes_g.shape[0], codes_g.shape[1], dp * F_loc))
        slot = DEVICE_STATS.begin_in_flight(
            upload, pack_s=time.monotonic() - t_pack0)
        DEVICE_STATS.note_mesh(slot, mesh.size, upload // mesh.size,
                               2 if sp > 1 else 0)
        if pred_s is not None:
            DEVICE_STATS.note_pred(slot, pred_s)
        with SHAPE_REGISTRY.attribute_compiles(new):
            ticket = DEVICE_FEEDER.submit(
                lambda: device_retry_call(lambda: _dispatch(slot),
                                          "mesh wire dispatch"),
                upload_bytes=upload, slot=slot)
        ticket.mesh_gather = mesh_gather
        ticket.mesh_devices = mesh.size
        ticket.mesh_f_loc = F_loc
        return ticket

    def resolve_segments_wire(self, ticket, codes2d: np.ndarray,
                              quals2d: np.ndarray, starts: np.ndarray,
                              _split_depth: int = 0,
                              want_extras: bool = False):
        """Fetch + complete a device_call_segments_wire ticket.

        Same contract as resolve_segments: (winner, qual, depth, errors)
        (J, L) arrays, suspects recomputed exactly by the f64 oracle. A
        full-column dispatch carries device-computed depth/errors (no host
        re-walk of the dense rows); a classic 2-tuple recomputes them here.
        ``want_extras=True`` appends a 5th element: a dict with the raw
        ``suspect`` mask and the ``resident`` device handles (both None on
        any degraded path) for the fused duplex combine stage. A
        dispatch/fetch failure that survived the feeder's bounded retry
        degrades instead of raising: RESOURCE_EXHAUSTED batches are halved
        and re-dispatched (output order preserved), anything else falls
        back to the native f64 host engine for this batch.

        A :class:`~fgumi_tpu.ops.coalesce.CoalescedTicket` (the dispatch
        was merged with other jobs' batches) resolves through the
        coalescer: shared fetch, this job's family slice, the identical
        host completion below — per-partner degrade on failure."""
        from .coalesce import COALESCER, CoalescedTicket

        if isinstance(ticket, CoalescedTicket):
            return COALESCER.resolve_partner(
                self, ticket, codes2d, quals2d, starts,
                split_depth=_split_depth, want_extras=want_extras)
        t0 = time.monotonic()
        fetched = 0
        failure = None
        d16 = e16 = resident = None
        tl0 = DEVICE_STATS.timeline_entry(ticket.slot)
        deadline = dispatch_deadline_s((tl0 or {}).get("pred_s"))
        try:
            dev = ticket.wait(deadline)
            if isinstance(dev[-1], ResidentHandles):
                resident = dev[-1]
                dev = dev[:-1]
            left = None if deadline is None else \
                max(deadline - (time.monotonic() - t0), 1.0)
            got = _fetch_with_deadline(dev, left)
            # SDC chaos point (ops/sentinel.py): `corrupt-result` flips
            # bits in the fetched arrays exactly where a defective chip
            # would have — after the device, before any host consumer
            from ..utils import faults

            got = faults.fire("device.fetch", got)
            if len(got) == 4:
                qs, wp, d16, e16 = got
            else:
                qs, wp = got
            fetched = sum(g.nbytes for g in got)
        except BaseException as e:  # noqa: BLE001 - recovered below
            failure = e
        finally:
            # decrement even when the feeder/fetch raised — a leaked
            # in-flight count would silently route every later hybrid batch
            # to the host engine while the run still claims platform=tpu,
            # and a leaked feeder slot would stall the upload pipeline at
            # depth outstanding dispatches. A deadline overrun abandons
            # instead: the slot is reclaimed when (if) the wedged dispatch
            # finally returns, and its late result is discarded.
            DEVICE_STATS.end_in_flight(ticket.slot, fetched,
                                       time.monotonic() - t0)
            if isinstance(failure, DeadlineExceeded):
                DEVICE_FEEDER.abandon(ticket)
            else:
                DEVICE_FEEDER.mark_resolved(ticket)
        if failure is not None:
            # only device weather is recoverable; KeyboardInterrupt /
            # SystemExit and INVALID_ARGUMENT-class programming errors
            # propagate (in-flight accounting above already balanced).
            # A resident handle that made it out before the failure is
            # dead weight — release its byte accounting now.
            if resident is not None:
                resident.release()
                resident = None
            if isinstance(failure, DeadlineExceeded):
                out = self._deadline_fallback_segments(failure, codes2d,
                                                       quals2d, starts)
            elif not (_is_oom(failure) or _is_transient(failure)):
                raise failure
            else:
                out = self._recover_segments(failure, codes2d, quals2d,
                                             starts, _split_depth)
            if want_extras:
                return out + ({"suspect": None, "resident": None,
                               "gather": None},)
            return out
        from .breaker import BREAKER

        BREAKER.record_success()
        # feed the offload cost model with this dispatch's measured pieces
        # (docs/device-datapath.md "Adaptive offload policy"). Slots past
        # the timeline cap have no entry — skip the feed rather than
        # polluting the EWMAs with degenerate zero samples.
        tl = DEVICE_STATS.timeline_entry(ticket.slot)
        if tl is not None:
            up_s = tl.get("upload_s", 0.0)
            wait_s = tl.get("fetch_wait_s", 0.0)
            from .router import ROUTER

            # service time = upload + fetch wait (the dispatch's serial
            # occupancy of the feeder+link); queue wait is priced
            # separately by decide()'s in_flight term, so it must not be
            # folded in here
            ROUTER.observe_device(ticket.upload_bytes, fetched, up_s,
                                  wait_s, up_s + wait_s,
                                  devices=ticket.mesh_devices)
        return self._complete_wire_columns(
            qs, wp, d16, e16, codes2d, quals2d, starts,
            want_extras=want_extras, resident=resident,
            gather=ticket.mesh_gather, devices=ticket.mesh_devices,
            f_loc=ticket.mesh_f_loc, slot=ticket.slot)

    def _complete_wire_columns(self, qs, wp, d16, e16,
                               codes2d: np.ndarray, quals2d: np.ndarray,
                               starts, want_extras: bool = False,
                               resident=None, gather=None, devices: int = 1,
                               f_loc=None, slot: int = -1, partner=None):
        """Host completion of fetched wire columns: unpack, depth/error
        counts, no-call restore, f64 oracle patch, shadow-audit tap.

        The shared resolve tail of resolve_segments_wire and the
        coalescer's per-partner split (ops/coalesce.py resolves each
        partner's family slice through exactly this code, so a merged
        job's bytes can never diverge from its solo run). ``partner``:
        merge attribution forwarded to the audit sentinel — a divergence
        inside a merged dispatch names the affected partner slice."""
        J = len(starts) - 1
        if J == 0:
            L = qs.shape[-1]
            z = np.zeros((0, L))
            out = (z.astype(np.uint8), z.astype(np.uint8),
                   z.astype(np.int64), z.astype(np.int64))
            if want_extras:
                return out + ({"suspect": None, "resident": resident,
                               "gather": None},)
            return out
        if gather is not None:
            # mesh dispatch: the fetched global arrays are shard-ordered
            # (dp * F_loc rows); one host gather restores family order.
            # The resident handles stay shard-ordered ON DEVICE — the
            # duplex combine maps its indices through ``gather`` instead
            # of paying a device-side re-shuffle.
            qs = qs[gather]
            wp = wp[gather]
            if d16 is not None:
                d16 = d16[gather]
                e16 = e16[gather]
        winner, qual, suspect = unpack_result_split(qs, wp, J)
        if d16 is not None:
            # full-column dispatch: the device already counted depth/errors
            # (exact integer lane sums); the dense rows are not re-walked
            depth = d16[:J].astype(np.int32)
            errors = e16[:J].astype(np.int32)
        else:
            from ..native import batch as nb

            if nb.available():
                # int32 end to end (host_kernel.call_segments_counted keeps
                # the same dtype): every consumer is dtype-agnostic, so the
                # old whole-(J,L) int64 casts were pure memory traffic
                depth, errors = nb.segment_depth_errors(codes2d, winner,
                                                        starts)
            else:
                valid = (codes2d != N_CODE).astype(np.int32)
                depth = np.add.reduceat(valid, starts[:-1], axis=0)
                counts = np.diff(starts)
                winner_rows = np.repeat(winner, counts, axis=0)
                match = ((codes2d == winner_rows)
                         & (codes2d != N_CODE)).astype(np.int32)
                errors = depth - np.add.reduceat(match, starts[:-1], axis=0)
        # no-call: depth==0 is not encodable in the 2-bit winner — restore it
        # from the depth counts (device guaranteed qual=MIN_PHRED there)
        no_call = depth == 0
        if no_call.any():
            winner[no_call] = N_CODE
            qual[no_call] = MIN_PHRED
            errors[no_call] = 0
        self._count_suspects(suspect)
        if suspect.any():
            self._oracle_patch(
                suspect, winner, qual, depth, errors,
                lambda f: (codes2d[starts[f]:starts[f + 1]],
                           quals2d[starts[f]:starts[f + 1]]))
        # shadow-audit tap (ops/sentinel.py): a deterministic sample of
        # clean device resolves is re-executed on the f64 host oracle and
        # compared exactly; an inline (`all`/quarantine-probe) audit that
        # catches a divergence hands back the oracle tuple to publish
        # instead of the corrupt device buffers
        from .sentinel import SENTINEL

        repaired = SENTINEL.maybe_audit(
            self, codes2d, quals2d, starts, winner, qual, depth, errors,
            devices=devices, gather=gather, f_loc=f_loc, slot=slot,
            partner=partner)
        if repaired is not None:
            winner, qual, depth, errors = repaired
            if resident is not None:
                # device-resident columns from the same dispatch are as
                # untrustworthy as the fetched result: drop them and let
                # the combine stage take its host path
                resident.release()
                resident = None
            if want_extras:
                return winner, qual, depth, errors, {
                    "suspect": None, "resident": None, "gather": None}
        if want_extras:
            return winner, qual, depth, errors, {"suspect": suspect,
                                                 "resident": resident,
                                                 "gather": gather}
        if resident is not None:
            # no consumer is coming for the resident arrays: release
            resident.release()
        return winner, qual, depth, errors

    # ------------------------------------------- fused consensus→filter

    def resolve_segments_wire_filtered(self, ticket, codes2d: np.ndarray,
                                       quals2d: np.ndarray,
                                       starts: np.ndarray):
        """Resolve a ``filter_params`` wire ticket (ISSUE 11).

        Returns ``("stats", stats, resident)`` on the fused path — stats
        is the (J, 7) int32 per-read reduction fetch, resident the
        device-side (fb, fq, d16, e16, qs, wp) columns for the
        survivors-only gather — or ``("columns", winner, qual, depth,
        errors)`` when the dispatch took the >63-qual fallback or degraded
        (deadline / transient / OOM): full post-oracle columns, the
        caller's host filter pass takes over. Byte-identity holds on every
        branch by the same exactness contract as resolve_segments_wire."""
        if not ticket.filter_mode:
            out = self.resolve_segments_wire(ticket, codes2d, quals2d,
                                             starts)
            return ("columns",) + out
        t0 = time.monotonic()
        fetched = 0
        failure = None
        resident = None
        tl0 = DEVICE_STATS.timeline_entry(ticket.slot)
        deadline = dispatch_deadline_s((tl0 or {}).get("pred_s"))
        try:
            stats_dev, resident = ticket.wait(deadline)
            left = None if deadline is None else \
                max(deadline - (time.monotonic() - t0), 1.0)
            stats = _fetch_with_deadline(stats_dev, left)
            from ..utils import faults

            # fault-injection seam (tools/chaos_smoke.py): the fused
            # route's only default fetch is the stats rows — corrupt-result
            # SDC drills must be able to hit it like any other fetch
            stats = faults.fire("device.fetch", stats)
            fetched = stats.nbytes
        except BaseException as e:  # noqa: BLE001 - recovered below
            failure = e
        finally:
            DEVICE_STATS.end_in_flight(ticket.slot, fetched,
                                       time.monotonic() - t0)
            if isinstance(failure, DeadlineExceeded):
                DEVICE_FEEDER.abandon(ticket)
            else:
                DEVICE_FEEDER.mark_resolved(ticket)
        if failure is not None:
            if resident is not None:
                resident.release()
            if isinstance(failure, DeadlineExceeded):
                out = self._deadline_fallback_segments(failure, codes2d,
                                                       quals2d, starts)
            elif not (_is_oom(failure) or _is_transient(failure)):
                raise failure
            else:
                out = self._recover_segments(failure, codes2d, quals2d,
                                             np.asarray(starts, np.int64),
                                             0)
            return ("columns",) + out
        from .breaker import BREAKER

        BREAKER.record_success()
        tl = DEVICE_STATS.timeline_entry(ticket.slot)
        if tl is not None:
            from .router import ROUTER

            up_s = tl.get("upload_s", 0.0)
            wait_s = tl.get("fetch_wait_s", 0.0)
            ROUTER.observe_device(ticket.upload_bytes, fetched, up_s,
                                  wait_s, up_s + wait_s,
                                  devices=ticket.mesh_devices)
        J = len(starts) - 1
        stats = np.asarray(stats[:J])
        # fused-route audit tap (ISSUE 19, closing the PR 13 gap): the
        # sentinel re-derives the stats rows (and, inline, the survivor
        # gather) from the f64 host oracle. An inline divergence returns
        # repaired pre-threshold columns — hand those to the caller's
        # host filter pass exactly like a degraded dispatch.
        from .sentinel import SENTINEL

        repaired = SENTINEL.maybe_audit_filter(
            self, codes2d, quals2d, starts, stats, resident,
            ticket.filter_ctx, slot=ticket.slot)
        if repaired is not None:
            resident.release()
            return ("columns",) + repaired
        return ("stats", stats, resident)

    def filter_resolve_suspect_rows(self, resident, rows, starts,
                                    codes2d: np.ndarray,
                                    quals2d: np.ndarray):
        """Ordinary host completion of the fused route's suspect rows.

        Gathers the raw packed winner/qual/suspect words + depth/errors
        for ``rows`` (indices into the dispatch's J segments) off the
        resident columns, then runs exactly the standard resolve tail:
        unpack, no-call restore, f64 oracle patch over the host-side
        dense rows. Returns post-oracle (winner, qual, depth, errors)
        for those rows — PRE consensus-thresholds, like every resolve."""
        _fb, _fq, d16, e16, qs_full, wp_full = resident.arrays
        rows = np.asarray(rows, dtype=np.int64)
        got = self._filter_gather(
            (qs_full, wp_full, d16, e16), rows, "fgathr")
        qs_r, wp_r, d_r, e_r = got
        k = len(rows)
        winner, qual, suspect = unpack_result_split(qs_r, wp_r, k)
        depth = d_r[:k].astype(np.int32)
        errors = e_r[:k].astype(np.int32)
        no_call = depth == 0
        if no_call.any():
            winner[no_call] = N_CODE
            qual[no_call] = MIN_PHRED
            errors[no_call] = 0
        self._count_suspects(suspect)
        starts = np.asarray(starts, dtype=np.int64)
        if suspect.any():
            self._oracle_patch(
                suspect, winner, qual, depth, errors,
                lambda f: (codes2d[starts[rows[f]]:starts[rows[f] + 1]],
                           quals2d[starts[rows[f]]:starts[rows[f] + 1]]))
        return winner, qual, depth, errors

    def filter_gather_filtered(self, resident, rows):
        """Survivors-only gather off the fused route's resident columns:
        (masked bases u8, masked quals u8, depth i32, errors i32) for
        ``rows``, in row order — the only per-position bytes the fused
        route ever fetches."""
        fb, fq, d16, e16 = resident.arrays[:4]
        rows = np.asarray(rows, dtype=np.int64)
        fb_r, fq_r, d_r, e_r = self._filter_gather(
            (fb, fq, d16, e16), rows, "fgath")
        k = len(rows)
        return (fb_r[:k], fq_r[:k], d_r[:k].astype(np.int32),
                e_r[:k].astype(np.int32))

    def _filter_gather(self, arrays, rows, kind: str):
        """One synchronous gather dispatch over four resident arrays
        (shape-bucketed index upload, sliced fetch, the usual retry +
        accounting). Raises on device failure — the fused stage falls
        back to the host engine for the affected rows."""
        K = len(rows)
        K_pad = SHAPE_REGISTRY.bucket(K, 8)
        K_out = _pad_out_segments(K, K_pad)
        idx = np.zeros(K_pad, dtype=np.int32)
        idx[:K] = rows
        L = int(arrays[0].shape[1])
        new = SHAPE_REGISTRY.observe(kind, K_pad, L, K_out)
        DEVICE_STATS.add_dispatch(K_pad * L * 4)
        slot = DEVICE_STATS.begin_in_flight(idx.nbytes)
        t0 = time.monotonic()
        fetched = 0
        try:
            fn = (_filter_gather_raw_jit if kind == "fgathr"
                  else _filter_gather_jit)

            def _dispatch():
                _ensure_jax()
                return fn(*arrays, idx, K_out)

            with SHAPE_REGISTRY.attribute_compiles(new):
                dev = device_retry_call(_dispatch, "filter gather")
            got = DEVICE_STATS.fetch(dev)
            fetched = sum(g.nbytes for g in got)
        finally:
            DEVICE_STATS.end_in_flight(slot, fetched,
                                       time.monotonic() - t0)
        return got

    def _recover_segments(self, exc, codes2d: np.ndarray,
                          quals2d: np.ndarray, starts, split_depth: int):
        """Degraded completion of a failed segment dispatch (never changes
        output bytes — both recovery paths share the exactness contract).

        RESOURCE_EXHAUSTED with more than one segment: halve at a segment
        boundary and re-dispatch both halves through the wire path (depth
        bounded by FGUMI_TPU_MAX_SPLITS, default 4), concatenating results
        in order. Everything else — transient errors that exhausted the
        bounded retry, OOM on a single segment, or split-depth exhaustion —
        runs this batch on the native f64 host engine. Re-raises only when
        the native library is unavailable."""
        import os

        starts = np.asarray(starts, dtype=np.int64)
        J = len(starts) - 1
        max_splits = int(os.environ.get("FGUMI_TPU_MAX_SPLITS", "4"))
        # the wire layout packs 4 positions/byte, so halving re-dispatches
        # only layouts the wire path can express (L % 4 == 0)
        can_split = (_is_oom(exc) and J > 1 and split_depth < max_splits
                     and codes2d.ndim == 2 and codes2d.shape[1] % 4 == 0)
        if can_split:
            DEVICE_STATS.add_split()
            mid = J // 2
            log.warning(
                "device batch exhausted memory (%s); halving %d segments "
                "into %d + %d and re-dispatching", exc, J, mid, J - mid)
            halves = []
            from .coalesce import bypassed as _coalesce_bypassed

            # halves bypass the merge window: they exist because the
            # (possibly merged) parent OOM'd, so re-entering the window
            # could re-merge them straight back into an over-size batch
            with _coalesce_bypassed():
                for lo, hi in ((0, mid), (mid, J)):
                    row_lo, row_hi = int(starts[lo]), int(starts[hi])
                    c = codes2d[row_lo:row_hi]
                    q = quals2d[row_lo:row_hi]
                    counts = np.diff(starts[lo:hi + 1])
                    cd, qd, seg_ids, sub_starts, f_pad = pad_segments(
                        c, q, counts)
                    ticket = self.device_call_segments_wire(
                        cd, qd, seg_ids, f_pad, hi - lo)
                    halves.append((ticket, c, q, sub_starts))
            # resolve BOTH halves even if the first raises: an unresolved
            # ticket would leak its in-flight slot (and silently route
            # every later hybrid batch to the host engine)
            parts, first_exc = [], None
            for t, c, q, s in halves:
                try:
                    parts.append(self.resolve_segments_wire(
                        t, c, q, s, _split_depth=split_depth + 1))
                except BaseException as e:  # noqa: BLE001 - re-raised below
                    if first_exc is None:
                        first_exc = e
            if first_exc is not None:
                raise first_exc
            return tuple(np.concatenate([p[i] for p in parts], axis=0)
                         for i in range(4))
        from ..native import batch as nb

        if not nb.available():
            raise exc
        DEVICE_STATS.add_host_fallback()
        if not _is_oom(exc):
            # repeated permanent transient failures are breaker fuel; an
            # OOM is a sizing problem, not device weather
            from .breaker import BREAKER

            BREAKER.record_transient_failure()
        log.warning(
            "device dispatch failed after retries (%s: %s); computing "
            "batch of %d segments on the native f64 host engine",
            type(exc).__name__, exc, J)
        return self._host_engine_complete(codes2d, quals2d, starts)

    def _host_engine_complete(self, codes2d, quals2d, starts):
        """Native-f64-host-engine completion of one segment batch (the
        shared tail of every degraded path: transient-failure fallback,
        deadline abandonment). Byte-identical to the device path by the
        engines' shared exactness contract."""
        engine = self._host()
        t0 = time.monotonic()
        winner, qual, depth, errors, n_slow = engine.call_segments_counted(
            codes2d, quals2d, np.asarray(starts, dtype=np.int64))
        from .router import ROUTER

        ROUTER.observe_host(codes2d.size, time.monotonic() - t0)
        with self._counter_lock:
            self.total_positions += winner.size
            self.fallback_positions += n_slow
        return winner, qual, depth, errors

    def _deadline_fallback_segments(self, exc, codes2d, quals2d, starts):
        """Degraded completion of a dispatch abandoned at its deadline:
        count it, feed the breaker (a wedge is categorical evidence), and
        complete on the native f64 host engine. Re-raises only when the
        native library is unavailable — there is nothing to degrade to."""
        from ..native import batch as nb

        if not nb.available():
            raise exc
        from .breaker import BREAKER

        DEVICE_STATS.add_deadline_fallback()
        BREAKER.record_deadline_overrun()
        log.warning(
            "%s; abandoning the in-flight dispatch and computing batch of "
            "%d segments on the native f64 host engine",
            exc, len(starts) - 1)
        return self._host_engine_complete(codes2d, quals2d, starts)

    # --------------------------------------------------- hard-column hybrid

    def dispatch_hard_columns(self, codes2d: np.ndarray, quals2d: np.ndarray,
                              starts: np.ndarray):
        """Classify + async-dispatch: the production device path (round 5).

        The native classify (fgumi_consensus_classify) resolves easy
        columns on host at byte-scan cost and exports the hard few percent
        as a compact observation stream; only that stream crosses the link
        (~2 orders of magnitude fewer bytes than whole pileups), so the
        device offload stays profitable at any tunnel speed. Returns an
        opaque pending resolved by resolve_hard_columns (possibly with no
        device work at all when every column was easy)."""
        from ..native import batch as nb

        t_pack0 = time.monotonic()  # classify + wire build == pack time
        host = self._host()
        if host._tab1 is None:
            host._build_tables()
        t = self.tables
        with np.errstate(invalid="ignore"):
            delta64 = np.asarray(t.adjusted_correct, np.float64) - \
                np.asarray(t.adjusted_error_per_alt, np.float64)
        winner, qual, depth, errors, hard_idx, hard_depth, hard_counts, \
            hc, hq = nb.consensus_classify(
                codes2d, quals2d, starts, delta64, host.g_sat,
                host.qual_const, MIN_PHRED, host._tab1[0], host._tab1[1],
                host._tab2[0], host._tab2[1])
        easy = (winner, qual, depth, errors)  # int32 end to end
        C = len(hard_idx)
        if C == 0:
            with self._counter_lock:
                self.total_positions += winner.size
            return ("cols_done", easy)
        M = len(hc)
        N_pad = _pad_rows(M)
        C_pad = max(8, SHAPE_REGISTRY.bucket_segments(C))
        # fetch-slice step: a multiple of 4 (the 2-bit winner packs 4
        # columns per byte) that divides the fetch into <= ~8 slice shapes
        m_out = max(4 * (C_pad // 32), 4)
        C_out = min(-(-C // m_out) * m_out, C_pad)
        depths_dev = np.zeros(C_pad, dtype=np.int32)
        depths_dev[:C] = hard_depth
        depths_dev[C_pad - 1] += N_pad - M  # pad obs fold into the last id
        DEVICE_STATS.add_dispatch(M * 16 + C_pad * 40)
        DEVICE_STATS.add_pad(M, N_pad)
        pre = self._pre
        tables_dev = self._tables_dev
        w = build_wire(hc.reshape(1, -1), hq.reshape(1, -1), self._delta94)
        if w is not None:
            wire, dict64 = w
            wire_pad = np.full(N_pad, WIRE_INVALID, dtype=np.uint8)
            wire_pad[:M] = wire.ravel()
            upload = wire_pad.nbytes + depths_dev.nbytes
            new = SHAPE_REGISTRY.observe("colsw", N_pad, C_pad, C_out)

            def _dispatch(slot):
                _ensure_jax()
                t0 = time.monotonic()
                wd = jax.device_put(wire_pad)
                dd = jax.device_put(depths_dev)
                dtab = CONST_CACHE.put("dict_tab", dict64)
                DEVICE_STATS.note_upload(slot, time.monotonic() - t0)
                return _consensus_columns_wire_jit(wd, dd, dtab, pre,
                                                   C_pad, C_out)
        else:
            codes_pad = np.full(N_pad, N_CODE, dtype=np.uint8)
            codes_pad[:M] = hc
            quals_pad = np.zeros(N_pad, dtype=np.uint8)
            quals_pad[:M] = hq
            upload = codes_pad.nbytes + quals_pad.nbytes + depths_dev.nbytes
            new = SHAPE_REGISTRY.observe("colsr", N_pad, C_pad, C_out)

            def _dispatch(slot):
                _ensure_jax()
                t0 = time.monotonic()
                cd = jax.device_put(codes_pad)
                qd = jax.device_put(quals_pad)
                dd = jax.device_put(depths_dev)
                ct, et = tables_dev()
                DEVICE_STATS.note_upload(slot, time.monotonic() - t0)
                return _consensus_columns_raw_jit(cd, qd, dd, ct, et,
                                                  pre, C_pad, C_out)
        slot = DEVICE_STATS.begin_in_flight(
            upload, pack_s=time.monotonic() - t_pack0)
        with SHAPE_REGISTRY.attribute_compiles(new):
            ticket = DEVICE_FEEDER.submit(
                lambda: device_retry_call(lambda: _dispatch(slot),
                                          "hard-column dispatch"),
                upload_bytes=upload, slot=slot)
        return ("cols_dev", easy, hard_idx, hard_depth, hard_counts, hc, hq,
                ticket)

    def resolve_hard_columns(self, pending):
        """Fetch + scatter a dispatch_hard_columns pending.

        Returns (winner, qual, depth, errors) (J, L) with hard columns
        filled from the device result and suspects recomputed exactly by
        the f64 oracle over the exported observation stream."""
        if pending[0] == "cols_done":
            return pending[1]
        _, easy, hard_idx, hard_depth, hard_counts, hc, hq, ticket = pending
        winner, qual, depth, errors = easy
        C = len(hard_idx)
        t0 = time.monotonic()
        fetched = 0
        failure = None
        tl0 = DEVICE_STATS.timeline_entry(ticket.slot)
        deadline = dispatch_deadline_s((tl0 or {}).get("pred_s"))
        try:
            dev = ticket.wait(deadline)
            left = None if deadline is None else \
                max(deadline - (time.monotonic() - t0), 1.0)
            qs, wp = _fetch_with_deadline(dev, left)
            fetched = qs.nbytes + wp.nbytes
        except BaseException as e:  # noqa: BLE001 - recovered below
            failure = e
        finally:
            DEVICE_STATS.end_in_flight(ticket.slot, fetched,
                                       time.monotonic() - t0)
            if isinstance(failure, DeadlineExceeded):
                DEVICE_FEEDER.abandon(ticket)
            else:
                DEVICE_FEEDER.mark_resolved(ticket)
        if failure is not None:
            from .breaker import BREAKER

            overran = isinstance(failure, DeadlineExceeded)
            if not overran and not (_is_oom(failure)
                                    or _is_transient(failure)):
                raise failure
            # degrade: the exported observation stream is exactly what the
            # host f64 patch path consumes — recompute every hard column
            # there (native guaranteed: classify already required it)
            if overran:
                DEVICE_STATS.add_deadline_fallback()
                BREAKER.record_deadline_overrun()
            else:
                DEVICE_STATS.add_host_fallback()
                if not _is_oom(failure):
                    BREAKER.record_transient_failure()
            log.warning(
                "device dispatch %s (%s: %s); resolving "
                "%d hard columns on the native f64 host engine",
                "overran its deadline" if overran
                else "failed after retries",
                type(failure).__name__, failure, C)
            self._patch_hard_columns(
                np.ones(C, dtype=bool), hard_idx, hard_depth, hc, hq,
                winner.ravel(), qual.ravel(), depth.ravel(), errors.ravel())
            with self._counter_lock:
                self.total_positions += winner.size
                self.fallback_positions += C
            return winner, qual, depth, errors
        from .breaker import BREAKER

        BREAKER.record_success()
        w_col, q_col, suspect = unpack_result_split(
            qs.reshape(1, -1), wp.reshape(1, -1), 1)
        w_col = w_col.ravel()[:C].astype(np.uint8)
        q_col = q_col.ravel()[:C].astype(np.uint8)
        suspect = suspect.ravel()[:C]
        e_col = hard_depth - hard_counts[np.arange(C), w_col]
        wf = winner.ravel()
        qf = qual.ravel()
        df = depth.ravel()
        ef = errors.ravel()
        wf[hard_idx] = w_col
        qf[hard_idx] = q_col
        df[hard_idx] = hard_depth
        ef[hard_idx] = e_col
        with self._counter_lock:
            self.total_positions += winner.size
            self.fallback_positions += int(suspect.sum())
        if suspect.any():
            self._patch_hard_columns(suspect, hard_idx, hard_depth, hc, hq,
                                     wf, qf, df, ef)
        return winner, qual, depth, errors

    @staticmethod
    def _concat_aranges(counts):
        """Concatenated arange(0, c_i) for each count, no Python loop."""
        counts = np.asarray(counts, dtype=np.int64)
        total = int(counts.sum())
        offs = np.repeat(np.concatenate(([0], np.cumsum(counts)[:-1])),
                         counts)
        return np.arange(total, dtype=np.int64) - offs

    def _patch_hard_columns(self, suspect, hard_idx, hard_depth, hc, hq,
                            wf, qf, df, ef):
        """Exact f64 recompute of suspect hard columns from the exported
        observation stream.

        Each suspect column becomes one length-1 segment of the native f64
        host engine (its observations are a run of depth-R "reads" of
        length 1, in the original read order, so the Kahan accumulation
        order matches the oracle exactly — the engine's bit-exactness
        contract covers this shape like any other). The engine resolves
        them in one native pass + one vectorized oracle epilogue for its
        own borderline positions, replacing a per-read Python loop that
        dominated the patch cost. The native library is guaranteed here:
        every pending came from dispatch_hard_columns, whose classify pass
        already required it."""
        obs_starts = np.concatenate(([0], np.cumsum(hard_depth)))
        sus = np.nonzero(suspect)[0]
        lo = obs_starts[sus]
        counts = obs_starts[sus + 1] - lo
        total = int(counts.sum())
        rows = np.repeat(lo, counts) + self._concat_aranges(counts)
        starts = np.concatenate(([0], np.cumsum(counts)))
        w, q, d, e = self._host().call_segments(
            hc[rows].reshape(total, 1), hq[rows].reshape(total, 1), starts)
        flat = hard_idx[sus]
        wf[flat] = w.ravel()
        qf[flat] = q.ravel()
        df[flat] = d.ravel()
        ef[flat] = e.ravel()

    def device_call_segments_sharded(self, codes3d, quals3d, seg_ids2d,
                                     num_segments: int, mesh):
        """Dispatch (dp, N, L) rows, one contiguous family shard per device.

        Dryrun/test surface (``__graft_entry__.dryrun_multichip``,
        tests/test_mesh.py): production traffic routes through the wire
        mesh path (:meth:`_dispatch_wire_mesh`) instead."""
        dp, N, L = codes3d.shape
        DEVICE_STATS.add_dispatch(segments_flops(dp * N, L, dp * num_segments))
        SHAPE_REGISTRY.observe("shard", dp, N, L, num_segments)
        return _consensus_segments_sharded_jit(
            as_device_operand(codes3d), as_device_operand(quals3d),
            as_device_operand(seg_ids2d),
            self._correct_f32, self._err_f32, self._pre, num_segments, mesh)

    def device_call_segments_dp_sp(self, codes4, quals4, seg3,
                                   num_segments: int, mesh):
        """Dispatch (dp, sp, N, L) rows: family shards over dp, each shard's
        read rows over sp with a psum combine.

        Dryrun/test surface like :meth:`device_call_segments_sharded`;
        production traffic uses the wire mesh path."""
        dp, sp, N, L = codes4.shape
        DEVICE_STATS.add_dispatch(segments_flops(dp * sp * N, L,
                                                 dp * num_segments))
        SHAPE_REGISTRY.observe("shard_sp", dp, sp, N, L, num_segments)
        return _consensus_segments_dp_sp_jit(
            as_device_operand(codes4), as_device_operand(quals4),
            as_device_operand(seg3),
            self._correct_f32, self._err_f32, self._pre, num_segments, mesh)

    def resolve_segments(self, dev, codes2d: np.ndarray, quals2d: np.ndarray,
                         starts: np.ndarray):
        """Fetch + complete a device_call_segments result.

        `starts` is the (J+1,) row-boundary array of the J real segments (the
        device result may be padded to more segments; extras are dropped).
        Returns (winner, qual, depth, errors) as (J, L) arrays with suspect
        positions recomputed exactly by the f64 oracle.
        """
        if dev is HOST_DISPATCH:
            engine = self._host()
            t0 = time.monotonic()
            winner, qual, depth, errors, n_slow = engine.call_segments_counted(
                codes2d, quals2d, np.asarray(starts, dtype=np.int64))
            from .router import ROUTER

            ROUTER.observe_host(codes2d.size, time.monotonic() - t0)
            with self._counter_lock:
                self.total_positions += winner.size
                self.fallback_positions += n_slow
            return winner, qual, depth, errors
        try:
            packed = _fetch_with_deadline(dev, dispatch_deadline_s())
            from ..utils import faults

            packed = faults.fire("device.fetch", packed)
        except DeadlineExceeded as e:
            return self._deadline_fallback_segments(e, codes2d, quals2d,
                                                    starts)
        except BaseException as e:  # noqa: BLE001 - classified below
            if not (_is_oom(e) or _is_transient(e)):
                raise
            return self._recover_segments(e, codes2d, quals2d,
                                          np.asarray(starts, np.int64), 0)
        from .breaker import BREAKER

        BREAKER.record_success()  # clean resolve: resets the failure score
        out = self._finish_segments(packed, codes2d, quals2d, starts)
        if len(starts) - 1 > 0:
            # shadow-audit tap (see resolve_segments_wire): classic
            # packed-segment dispatches are sampled/audited the same way
            from .sentinel import SENTINEL

            repaired = SENTINEL.maybe_audit(
                self, codes2d, quals2d, starts, *out)
            if repaired is not None:
                out = repaired
        return out

    def _finish_segments(self, packed: np.ndarray, codes2d, quals2d, starts):
        J = len(starts) - 1
        if J == 0:  # empty shard (more devices than jobs)
            L = packed.shape[-1]
            z = np.zeros((0, L))
            return (z.astype(np.uint8), z.astype(np.uint8),
                    z.astype(np.int32), z.astype(np.int32))
        winner, qual, suspect = _unpack_device_result(packed)
        winner = winner[:J]
        qual = qual[:J]
        suspect = suspect[:J]
        # depth/errors per segment: one native pass over the dense rows when
        # available (i32, not i16: the i16 clamp happens at tag-write time
        # downstream, matching the reference); numpy reduceat fallback
        from ..native import batch as nb

        if nb.available():
            # int32 end to end (host_kernel.call_segments_counted keeps the
            # same dtype): every consumer is dtype-agnostic, so the old
            # whole-(J,L) int64 casts were pure memory traffic
            depth, errors = nb.segment_depth_errors(codes2d, winner, starts)
        else:
            valid = (codes2d != N_CODE).astype(np.int32)
            depth = np.add.reduceat(valid, starts[:-1], axis=0)
            counts = np.diff(starts)
            winner_rows = np.repeat(winner, counts, axis=0)
            match = ((codes2d == winner_rows)
                     & (codes2d != N_CODE)).astype(np.int32)
            errors = depth - np.add.reduceat(match, starts[:-1], axis=0)
        self._count_suspects(suspect)
        if suspect.any():
            self._oracle_patch(
                suspect, winner, qual, depth, errors,
                lambda f: (codes2d[starts[f]:starts[f + 1]],
                           quals2d[starts[f]:starts[f + 1]]))
        return winner, qual, depth, errors

    def _count_suspects(self, suspect: np.ndarray):
        with self._counter_lock:
            self.total_positions += suspect.size
            self.fallback_positions += int(suspect.sum())

    def _oracle_patch(self, suspect, winner, qual, depth, errors, family_rows):
        """Recompute suspect positions exactly with the f64 oracle (in place).

        `family_rows(f) -> (codes (R, L), quals (R, L))` abstracts the layout
        difference between the uniform-R batch and the ragged segment path.

        Suspect (family, position) pairs are stacked as columns of a shared
        (R_bucket, C) pileup and recomputed in one oracle call per pow2
        family-depth bucket — accumulate_likelihoods is already vectorized
        over its position axis, and end-padding with N rows is a no-op for
        it, so this is semantically identical to the per-family loop it
        replaces while doing ~C fewer Python/NumPy round trips (the patch
        showed up at ~20% of simplex CPU wall time as a per-family loop).
        Bucketing by depth class caps pad waste at 2x, so one deep family
        cannot inflate every other column to its row count.
        """
        from . import oracle

        fam_idx, pos_idx = np.nonzero(suspect)
        fams, first = np.unique(fam_idx, return_index=True)
        bounds = np.append(first, len(fam_idx))  # fam_idx is sorted (nonzero)
        buckets = {}  # depth class -> [(R_f, P_f) codes, quals, col pair idxs]
        for i, f in enumerate(fams):
            sel = slice(bounds[i], bounds[i + 1])
            positions = pos_idx[sel]
            fam_codes, fam_quals = family_rows(f)
            cls = max(int(fam_codes.shape[0]) - 1, 0).bit_length()
            buckets.setdefault(cls, []).append(
                (fam_codes[:, positions], fam_quals[:, positions], sel))
        for cols in buckets.values():
            r_max = max(cc.shape[0] for cc, _, _ in cols)
            c_tot = sum(cc.shape[1] for cc, _, _ in cols)
            col_codes = np.full((r_max, c_tot), N_CODE, dtype=np.uint8)
            col_quals = np.zeros((r_max, c_tot), dtype=np.uint8)
            c0 = 0
            for cc, cq, _ in cols:
                col_codes[:cc.shape[0], c0:c0 + cc.shape[1]] = cc
                col_quals[:cq.shape[0], c0:c0 + cq.shape[1]] = cq
                c0 += cc.shape[1]
            w, q, d, e = oracle.call_family(col_codes, col_quals, self.tables)
            c0 = 0
            for cc, _, sel in cols:
                c1 = c0 + cc.shape[1]
                fi, pi = fam_idx[sel], pos_idx[sel]
                winner[fi, pi] = w[c0:c1]
                qual[fi, pi] = q[c0:c1]
                depth[fi, pi] = d[c0:c1]
                errors[fi, pi] = e[c0:c1]
                c0 = c1


def route_and_call_segments(kernel: "ConsensusKernel", codes2d, quals2d,
                            counts, starts, mesh=None):
    """Route one dense (N, L) segment batch through the adaptive offload
    policy and resolve it synchronously: the host f64 engine, the round-5
    hard-column export (FGUMI_TPU_DEVICE_PATH=columns), or the full-column
    wire kernel (default device route; sharded over ``mesh`` when one with
    > 1 device is passed). The one shared implementation of the decide ->
    dispatch -> resolve sequence for the synchronous callers (fast_codec,
    the classic vanilla path); the async engines (simplex pending chunks,
    duplex defer/resident) keep their specialized flows but share
    ROUTER.decide_batch and the same dispatch entry points."""
    from .router import ROUTER

    mesh_active = mesh is not None and mesh.size > 1
    route = "host"
    if not kernel.host_mode():
        route = ROUTER.decide_batch(kernel, codes2d.shape[0], len(counts),
                                    codes2d.shape[1],
                                    devices=mesh.size if mesh_active else 1)
    if route == "host":
        return kernel.resolve_segments(HOST_DISPATCH, codes2d, quals2d,
                                       starts)
    if device_path() == "columns":
        # the round-5 comparison route is single-device by design (the
        # compact hard-column stream defeats the point of sharding); an
        # explicit FGUMI_TPU_DEVICE_PATH=columns wins over the mesh
        pending = kernel.dispatch_hard_columns(codes2d, quals2d, starts)
        return kernel.resolve_hard_columns(pending)
    t_pack0 = time.monotonic()
    pred = ROUTER.last_prediction()
    full = bool(np.max(counts) < 65536)
    if mesh_active:
        cg, qg, seg_g, _st, f_loc, gather = pad_segments_mesh(
            codes2d, quals2d, counts, mesh)
        ticket = kernel.device_call_segments_wire(
            cg, qg, seg_g, f_loc, len(counts), pack_t0=t_pack0, full=full,
            pred_s=pred[0] if pred else None, mesh=mesh,
            mesh_gather=gather)
        return kernel.resolve_segments_wire(ticket, codes2d, quals2d,
                                            starts)
    cd, qd, seg_ids, _sp, f_pad = pad_segments(codes2d, quals2d, counts)
    ticket = kernel.device_call_segments_wire(
        cd, qd, seg_ids, f_pad, len(counts), pack_t0=t_pack0,
        full=full,
        pred_s=pred[0] if pred else None)
    return kernel.resolve_segments_wire(ticket, codes2d, quals2d, starts)


# ------------------------------------------------------ fused device stages

def duplex_combine_device(resident: "ResidentHandles", a_idx, b_idx, lens):
    """Fused duplex strand-combine dispatch on stage-1 resident SS arrays.

    a_idx/b_idx index rows of the resident (out_segments, L) arrays; lens
    are the per-output combined lengths. Returns host
    (out_b u8, out_q u8, out_e i32) arrays, byte-identical to the numpy
    combine for rows whose inputs carry no oracle patch (the caller routes
    suspect-touched rows to the host combine). Upload is just the three
    index vectors; raises on device failure (caller falls back to host)."""
    tb, tq, obs = resident.arrays
    K = len(a_idx)
    K_pad = SHAPE_REGISTRY.bucket(K, 8)
    K_out = _pad_out_segments(K, K_pad)
    ai = np.zeros(K_pad, dtype=np.int32)
    bi = np.zeros(K_pad, dtype=np.int32)
    ln = np.zeros(K_pad, dtype=np.int32)
    ai[:K] = a_idx
    bi[:K] = b_idx
    ln[:K] = lens
    L = int(tb.shape[1])
    new = SHAPE_REGISTRY.observe("dupcomb", K_pad, L, K_out)
    DEVICE_STATS.add_dispatch(K_pad * L * 24)
    slot = DEVICE_STATS.begin_in_flight(ai.nbytes * 3)
    t0 = time.monotonic()
    try:
        def _dispatch():
            _ensure_jax()
            return _duplex_combine_jit(tb, tq, obs, ai, bi, ln, K_out)

        # attribute a first-sight-shape compile to the bucket miss, like
        # every other dispatch site (warm-serve compiles==0 evidence)
        with SHAPE_REGISTRY.attribute_compiles(new):
            dev = device_retry_call(_dispatch, "duplex combine")
        out_b, out_q, out_e = DEVICE_STATS.fetch(dev)
        fetched = out_b.nbytes + out_q.nbytes + out_e.nbytes
    except BaseException:
        fetched = 0
        raise
    finally:
        DEVICE_STATS.end_in_flight(slot, fetched, time.monotonic() - t0)
    return out_b[:K], out_q[:K], out_e[:K]


def codec_combine_device(ba, bb, qa, qb, da, db, ea, eb, mesh=None):
    """CODEC concordance combine as a device dispatch.

    Same contract as consensus/codec.combine_arrays over the batch
    engine's concatenated 1-D position arrays (int32-capped inputs);
    integer-exact vs the numpy version. Raises on device failure — the
    caller falls back to the host combine. With a > 1-device ``mesh`` the
    position axis shards over it: aligned padding keeps the global shape
    evenly divisible, the eight operands upload as NamedSharding slices,
    and the elementwise shard_map variant runs collective-free."""
    import math

    T = len(ba)
    mesh_active = mesh is not None and mesh.size > 1
    align = math.lcm(16, mesh.size) if mesh_active else 16
    T_pad = SHAPE_REGISTRY.bucket(T, align)
    T_out = T_pad if mesh_active else _pad_out_segments(T, T_pad)

    def pad(a, dtype):
        out = np.zeros(T_pad, dtype=dtype)
        out[:T] = a
        return out

    ops = (pad(ba, np.uint8), pad(bb, np.uint8), pad(qa, np.uint8),
           pad(qb, np.uint8), pad(da, np.int32), pad(db, np.int32),
           pad(ea, np.int32), pad(eb, np.int32))
    if mesh_active:
        new = SHAPE_REGISTRY.observe("codeccombm", T_pad, mesh.size)
    else:
        new = SHAPE_REGISTRY.observe("codeccomb", T_pad, T_out)
    DEVICE_STATS.add_dispatch(T_pad * 40)
    slot = DEVICE_STATS.begin_in_flight(sum(o.nbytes for o in ops))
    if mesh_active:
        DEVICE_STATS.note_mesh(slot, mesh.size,
                               sum(o.nbytes for o in ops) // mesh.size, 0)
    t0 = time.monotonic()
    try:
        def _dispatch():
            _ensure_jax()
            if mesh_active:
                from jax.sharding import NamedSharding, PartitionSpec as P

                sh = NamedSharding(mesh, P(mesh.axis_names))
                dev_ops = tuple(jax.device_put(o, sh) for o in ops)
                return _codec_combine_mesh_jit(*dev_ops, mesh)
            return _codec_combine_jit(*ops, T_out)

        with SHAPE_REGISTRY.attribute_compiles(new):
            dev = device_retry_call(_dispatch, "codec combine")
        got = DEVICE_STATS.fetch(dev)
        fetched = sum(g.nbytes for g in got)
    except BaseException:
        fetched = 0
        raise
    finally:
        DEVICE_STATS.end_in_flight(slot, fetched, time.monotonic() - t0)
    # .copy(): device_get may hand back read-only buffers and the codec
    # quality-mask pass writes into cq in place
    base, qual, depth, errors, both, disag = got
    return (base[:T].copy(), qual[:T].copy(), depth[:T].copy(),
            errors[:T].copy(), both[:T].copy(), disag[:T].copy())
