"""f64 scalar-semantics consensus oracle (NumPy, host).

This is the correctness anchor for the TPU kernel: an exact reimplementation of
ConsensusBaseBuilder::add / call_full
(/root/reference/crates/fgumi-consensus/src/base_builder.rs:612-644,795-852), including

- Kahan-compensated accumulation of per-read log-likelihoods, in read order,
- fgbio's lane-ordered log-sum-exp normalization (phred.rs:324-351),
- the exact tie rule: a tie exists iff some lane *after* the first-occurrence maximum
  is within f64 epsilon (absolute) of the maximum (call_full's running-max loop),
- the posterior -> consensus-error -> two-trials(pre-UMI) -> Phred chain.

The device kernel must agree with this oracle on every integer output; positions the
kernel flags as numerically suspect are recomputed here.
"""

import numpy as np

from ..constants import MAX_PHRED, MIN_PHRED, N_CODE
from . import phred as P
from .tables import QualityTables


def accumulate_likelihoods(codes: np.ndarray, quals: np.ndarray, tables: QualityTables):
    """Kahan-accumulate per-position log-likelihoods over reads.

    Args:
      codes: (R, L) uint8 base codes, 0..3 = ACGT, 4 = N/pad (skipped).
      quals: (R, L) uint8 Phred qualities.
      tables: QualityTables for the (pre, post) error rates.

    Returns:
      likelihoods: (L, 4) f64 accumulated log-likelihoods.
      observations: (L, 4) int64 per-base observation counts.
    """
    R, L = codes.shape
    sums = np.zeros((L, 4), dtype=np.float64)
    comps = np.zeros((L, 4), dtype=np.float64)
    observations = np.zeros((L, 4), dtype=np.int64)

    lanes = np.arange(4, dtype=np.uint8)
    for r in range(R):
        code_r = codes[r]
        valid = code_r != N_CODE
        q_idx = np.minimum(quals[r], MAX_PHRED).astype(np.intp)
        ln_correct = tables.adjusted_correct[q_idx]
        ln_err_alt = tables.adjusted_error_per_alt[q_idx]
        one_hot = code_r[:, None] == lanes[None, :]
        values = np.where(one_hot, ln_correct[:, None], ln_err_alt[:, None])

        # Kahan step, exactly as base_builder.rs:633-641, masked per position.
        # (-inf values legitimately produce NaN compensation terms, as in the
        # reference; see test_q0_pileup_nan_poisoning_matches_reference.)
        with np.errstate(invalid="ignore"):
            y = values - comps
            t = sums + y
            new_comps = (t - sums) - y
        sums = np.where(valid[:, None], t, sums)
        comps = np.where(valid[:, None], new_comps, comps)
        observations += np.where(valid[:, None], one_hot.astype(np.int64), 0)

    return sums, observations


def call_full(likelihoods: np.ndarray, observations: np.ndarray, tables: QualityTables):
    """The full consensus call for each position (call_full, base_builder.rs:795-852).

    Args:
      likelihoods: (L, 4) f64.
      observations: (L, 4) int64.

    Returns:
      winner: (L,) uint8 base code (0..3, or 4 = N for no-call/tie/no-observations).
      qual: (L,) uint8 Phred (MIN_PHRED for no-call rows).
      depth: (L,) int64 total contributing observations.
      errors: (L,) int64 depth minus winner-base observations (== depth for no-call,
        matching vanilla_caller.rs:1423 where observations_for_base(N) == 0).
    """
    L = likelihoods.shape[0]
    depth = observations.sum(axis=1)

    # NaN lanes (Kahan poisoning after a -inf ln_correct, i.e. a Q0 observation
    # followed by more adds on that lane) are *skipped* by the reference's
    # partial_cmp-based running-max loop (Ordering::None => ignored); mirror that by
    # treating them as -inf for winner selection. The NaN still flows into the
    # normalization sum, so the final quality saturates to 0 (see ln_prob_to_phred).
    ll_for_max = np.where(np.isnan(likelihoods), -np.inf, likelihoods)
    max_ll = ll_for_max.max(axis=1)
    winner = ll_for_max.argmax(axis=1)  # first occurrence == strict-> update order

    # Tie iff any lane after the first-occurrence max is within f64 eps (absolute) of
    # the max; lanes before it were forgotten when the running max last updated.
    lane_idx = np.arange(4)
    after = lane_idx[None, :] > winner[:, None]
    with np.errstate(invalid="ignore"):
        close = np.abs(likelihoods - max_ll[:, None]) <= P.F64_EPSILON
    tie = np.any(after & close, axis=1)
    # All-lanes -inf (every observation had ln_correct == -inf) is a tie at lane 0.
    tie |= np.isneginf(max_ll)

    ln_sum = P.ln_sum_exp4(likelihoods)
    ln_posterior = max_ll - ln_sum
    ln_consensus_error = P.ln_not(ln_posterior)
    ln_final_error = P.ln_error_prob_two_trials(
        np.full(L, tables.ln_error_pre_umi), ln_consensus_error
    )
    qual = P.ln_prob_to_phred(ln_final_error)

    no_call = tie | (depth == 0)
    winner = np.where(no_call, N_CODE, winner).astype(np.uint8)
    qual = np.where(no_call, MIN_PHRED, qual).astype(np.uint8)

    winner_obs = np.where(
        no_call, 0, np.take_along_axis(observations, np.minimum(winner, 3)[:, None], axis=1)[:, 0]
    )
    errors = depth - winner_obs
    return winner, qual, depth, errors


def call_family(codes: np.ndarray, quals: np.ndarray, tables: QualityTables):
    """Accumulate + call for one family's padded (R, L) arrays. See call_full."""
    likelihoods, observations = accumulate_likelihoods(codes, quals, tables)
    return call_full(likelihoods, observations, tables)


def apply_consensus_thresholds(winner, qual, depth, min_reads: int, min_consensus_qual: int):
    """Post-call masking (vanilla_caller.rs:1427-1433).

    depth < min_reads -> (N, 0); qual < min_consensus_base_quality -> (N, MIN_PHRED).
    Returns (bases_code, quals) after masking.
    """
    low_depth = depth < min_reads
    low_qual = qual < min_consensus_qual
    out_base = np.where(low_depth | low_qual, N_CODE, winner).astype(np.uint8)
    out_qual = np.where(low_depth, 0, np.where(low_qual, MIN_PHRED, qual)).astype(np.uint8)
    return out_base, out_qual


def single_read_consensus(codes: np.ndarray, quals: np.ndarray, tables: QualityTables,
                          min_consensus_qual: int):
    """Single-read fast path (vanilla_caller.rs:1361-1392).

    Quality is remapped through the single-input table; bases below the consensus
    threshold mask to (N, MIN_PHRED); depth = 1 where base != N; errors = 0.
    """
    codes = np.asarray(codes)
    q_idx = np.minimum(quals, MAX_PHRED).astype(np.intp)
    adj = tables.single_input_quals[q_idx]
    low = adj < min_consensus_qual
    out_base = np.where(low, N_CODE, codes).astype(np.uint8)
    out_qual = np.where(low, MIN_PHRED, adj).astype(np.uint8)
    depth = (codes != N_CODE).astype(np.int64)
    errors = np.zeros_like(depth)
    return out_base, out_qual, depth, errors
