"""Phred / log-probability math, vectorized NumPy f64.

This module is the numerics parity anchor for the whole framework: it reproduces the
exact operation chains of fgbio's NumericTypes.scala as realized by the reference
(/root/reference/crates/fgumi-consensus/src/phred.rs), including branch thresholds and
floating-point evaluation order, so that integer Phred outputs match bit-for-bit.

All functions accept scalars or NumPy arrays (f64) and are branch-free via np.where /
np.piecewise-style masking, preserving the scalar code's per-element semantics.
"""

import numpy as np

from ..constants import MAX_PHRED, MIN_PHRED

LN_10 = np.log(10.0)
LN_TWO = np.log(2.0)
# ln(4/3), the two-trials cross term (phred.rs:19).
LN_FOUR_THIRDS = 0.2876820724517809
# Precision constant in Phred conversion, matching fgbio (phred.rs:31).
PHRED_PRECISION = 0.001
# phred_to_ln_error(MAX_PHRED), the Q93 saturation threshold (phred.rs:34).
MAX_PHRED_AS_LN_ERROR = -float(MAX_PHRED) * LN_10 / 10.0

F64_EPSILON = np.finfo(np.float64).eps


def phred_to_ln_error(phred):
    """ln P(error) for a Phred score: -Q * ln(10) / 10 (phred.rs:66-68)."""
    return -np.asarray(phred, dtype=np.float64) * LN_10 / 10.0


def log1pexp(x):
    """log(1 + exp(x)) with fgbio's threshold scheme (phred.rs:148-158).

    Thresholds: x<=-37 -> exp(x); x<=18 -> log1p(exp(x)); x<=33.3 -> x+exp(-x); else x.
    """
    x = np.asarray(x, dtype=np.float64)
    return np.where(
        x <= -37.0,
        np.exp(np.minimum(x, 0.0)),
        np.where(
            x <= 18.0,
            np.log1p(np.exp(np.minimum(x, 18.0))),
            np.where(x <= 33.3, x + np.exp(-np.maximum(x, 18.0)), x),
        ),
    )


def ln_one_minus_exp(x):
    """ln(1 - exp(x)) for x <= 0, stable (phred.rs:168-181).

    x >= 0 -> -inf; x >= -ln2 -> log(-expm1(x)); else log1p(-exp(x)).
    """
    x = np.asarray(x, dtype=np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        near = np.log(-np.expm1(np.minimum(x, 0.0)))
        far = np.log1p(-np.exp(np.minimum(x, 0.0)))
    return np.where(x >= 0.0, -np.inf, np.where(x >= -LN_TWO, near, far))


def phred_to_ln_correct(phred):
    """ln P(correct) = ln(1 - P(error)) (phred.rs:89-92)."""
    return ln_one_minus_exp(phred_to_ln_error(phred))


def ln_prob_to_phred(ln_prob):
    """Log error probability -> integer Phred, fgbio rounding (phred.rs:119-135).

    floor(-10 * ln/LN10 + 0.001) clamped to [MIN_PHRED, MAX_PHRED]; inputs below the
    Q93-as-ln threshold short-circuit to MAX_PHRED.
    """
    ln_prob = np.asarray(ln_prob, dtype=np.float64)
    phred = np.floor(-10.0 * ln_prob / LN_10 + PHRED_PRECISION)
    phred = np.clip(phred, float(MIN_PHRED), float(MAX_PHRED))
    out = np.where(ln_prob < MAX_PHRED_AS_LN_ERROR, float(MAX_PHRED), phred)
    # NaN input (a NaN-poisoned likelihood chain, e.g. a Q0 observation followed by
    # further observations) saturates to 0, matching Rust's `NaN as u8` cast in
    # phred.rs:119-135's clamp-then-cast.
    out = np.where(np.isnan(out), 0.0, out)
    return out.astype(np.uint8)


def ln_sum_exp(ln_a, ln_b):
    """log(exp(a) + exp(b)), fgbio's `or` (phred.rs:291-302).

    -inf operands are absorbed; otherwise min + log1pexp(max - min), evaluated with the
    smaller operand first exactly as the scalar code orders it.
    """
    ln_a = np.asarray(ln_a, dtype=np.float64)
    ln_b = np.asarray(ln_b, dtype=np.float64)
    lo = np.minimum(ln_a, ln_b)
    hi = np.maximum(ln_a, ln_b)
    with np.errstate(invalid="ignore"):
        combined = lo + log1pexp(hi - lo)
    a_ninf = np.isneginf(ln_a)
    b_ninf = np.isneginf(ln_b)
    return np.where(a_ninf, ln_b, np.where(b_ninf, ln_a, combined))


def ln_sum_exp4(values):
    """log-sum-exp over the last axis of a (..., 4) array, fgbio lane ordering.

    Mirrors ln_sum_exp_array (phred.rs:324-351): the accumulator is seeded with the
    minimum lane (first occurrence), then the remaining lanes are folded **in index
    order** via pairwise ln_sum_exp. The fold order affects the final ulp, so it is
    replicated exactly. All-(-inf) rows return -inf.
    """
    values = np.asarray(values, dtype=np.float64)
    assert values.shape[-1] == 4
    # First-occurrence argmin matches the scalar loop's strict `<` update.
    min_idx = np.argmin(values, axis=-1)
    acc = np.take_along_axis(values, min_idx[..., None], axis=-1)[..., 0]
    for lane in range(4):
        lane_vals = values[..., lane]
        folded = ln_sum_exp(acc, lane_vals)
        acc = np.where(min_idx == lane, acc, folded)
    all_ninf = np.all(np.isneginf(values), axis=-1)
    return np.where(all_ninf, -np.inf, acc)


def ln_a_minus_b(a, b):
    """log(exp(a) - exp(b)) for a >= b (phred.rs:203-215).

    b = -inf -> a; |a-b| < f64 eps -> -inf; genuine a < b is a caller error (asserted).
    """
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    b_ninf = np.isneginf(b)
    with np.errstate(invalid="ignore"):
        near_equal = np.abs(a - b) < F64_EPSILON
        bad = (a < b) & ~near_equal & ~b_ninf
    if np.any(bad):
        raise FloatingPointError("ln_a_minus_b: subtraction would be negative")
    with np.errstate(invalid="ignore"):
        diff = a + ln_one_minus_exp(np.minimum(b - a, 0.0))
    return np.where(b_ninf, a, np.where(near_equal, -np.inf, diff))


def ln_error_prob_two_trials(ln_p1, ln_p2):
    """P(error over two independent trials), f(X,Y) = X + Y - 4/3*X*Y in log space.

    Mirrors phred.rs:248-267: operands ordered so the larger is first; a log-space gap
    >= 6 short-circuits to the larger; otherwise ln_a_minus_b(ln_sum_exp(p1,p2),
    ln(4/3)+p1+p2).
    """
    ln_p1 = np.asarray(ln_p1, dtype=np.float64)
    ln_p2 = np.asarray(ln_p2, dtype=np.float64)
    hi = np.maximum(ln_p1, ln_p2)
    lo = np.minimum(ln_p1, ln_p2)
    with np.errstate(invalid="ignore"):
        quick = (hi - lo) >= 6.0
    term1 = ln_sum_exp(hi, lo)
    term2 = LN_FOUR_THIRDS + hi + lo
    # Where the quick path applies term2 may exceed term1; feed safe values through
    # ln_a_minus_b there and overwrite with the quick answer afterwards.
    safe_term2 = np.where(quick, -np.inf, term2)
    full = ln_a_minus_b(term1, safe_term2)
    return np.where(quick, hi, full)


def ln_not(x):
    """ln(1 - exp(x)) (phred.rs:365-367)."""
    return ln_one_minus_exp(x)
