"""Cross-job dispatch coalescer: merged device launches for the serve fleet.

ROADMAP §2's perf half (ISSUE 15). In the warm serve daemon every job
dispatches its own wire-format segment batches, so N concurrent small jobs
each pay the full pack→upload→launch overhead on a chip that could serve
them in one launch — BENCH_r05 measured that overhead at ~400x the kernel
compute, which is exactly the regime where amortizing it across jobs wins.
This module is the continuous-batching analog for consensus dispatches:
instead of serializing per-job launches, *compatible* pending batches are
admitted into one in-flight super-batch.

Mechanics
---------

- ``ConsensusKernel.device_call_segments_wire`` offers every plain (non-
  resident, non-filter, non-mesh) wire dispatch to :meth:`DispatchCoalescer.
  maybe_submit`. While the window is armed, the batch is held for up to
  ``FGUMI_TPU_COALESCE_WINDOW_MS`` (default 2 ms; 0 disables) waiting for
  partners with the same merge key — same kernel variant (``full`` flag,
  wire/packed2 chosen per merged batch like solo), same constant-table
  content (the quality-table/pre-UMI fingerprint), same padded read length
  — then all partners concatenate along the family/segment axis into one
  shape-bucketed dispatch through the ordinary feeder pipeline. The
  feeder's governed byte budget is charged ONCE for the merged upload.
- The window arms only when it can pay for itself: the serve scheduler
  reports the live running-job count (:meth:`set_active_jobs`) and the
  window opens at >= 2 (auto-off for single jobs — zero hold, zero
  regression), and the hold is additionally priced against the router's
  measured per-dispatch overhead (merging k batches saves ~(k-1) x
  overhead, so holding longer than one overhead can only lose to just
  dispatching now). ``FGUMI_TPU_COALESCE=1`` forces the window regardless
  (bench/chaos harnesses); ``0`` disables it entirely.
- At resolve each partner receives exactly its own family slice of the
  merged fetch and runs the UNCHANGED host completion — unpack, no-call
  restore, f64 oracle patch, shadow-audit tap — over its own dense rows
  under its own telemetry scope, so per-job output stays byte-identical to
  standalone (the PR 3 invariant: every integer output is oracle-exact on
  both paths, whatever the f32 reduction order of the merged shape did).
  Dispatch wall/bytes are attributed proportionally: each partner charges
  its own scope the flops/bytes/pad its solo dispatch would have.
- Faults degrade per partner: a raise/hang/OOM inside a merged dispatch
  (chaos point ``serve.coalesce``) surfaces to every partner's resolve,
  and each one independently falls back — deadline abandon, transient
  host fallback, or OOM split-halving over its OWN rows (re-dispatched
  halves bypass the window via :func:`bypassed`).

Fairness
--------

A large job cannot starve small partners: a batch above
``FGUMI_TPU_COALESCE_PARTNER_ROWS`` (default 64 Ki rows) never rides — or
holds open — a merge window (it dispatches solo immediately), a group
closes at ``FGUMI_TPU_COALESCE_PARTNERS`` partners or
``FGUMI_TPU_COALESCE_MAX_ROWS`` merged rows, and admission is strictly
arrival-ordered — a newcomer that would overflow a group flushes it and
opens the next, never reorders past it. Priority classes are respected
upstream: the scheduler already orders job *execution* by priority, so
arrival order at the coalescer inherits it.

Telemetry (satellite): ``device.coalesce.*`` counters + histograms —
``merged_batches`` / ``solo_flushes`` / ``partners`` / ``oversize_solo``
counters, ``fill_ratio`` and ``window_wait_s`` histograms (the per-partner
wait lands in the partner's scope, so per-job run reports carry it), a
flight-ring note per merge, and :meth:`snapshot` feeding the serve
``stats`` op / ``/metrics`` ``coalesce`` section.
"""

import contextlib
import contextvars
import logging
import os
import threading
import time

import numpy as np

from ..constants import N_CODE

log = logging.getLogger("fgumi_tpu")

_BYPASS = contextvars.ContextVar("fgumi_tpu_coalesce_bypass", default=False)


@contextlib.contextmanager
def bypassed():
    """Disable coalescing for dispatches made inside the block (the OOM
    split-halving recovery: re-dispatched halves must not re-enter the
    window their parent just failed out of)."""
    token = _BYPASS.set(True)
    try:
        yield
    finally:
        _BYPASS.reset(token)


class CoalesceFlushError(RuntimeError):
    """The merged build/submit itself failed. Routed through the ordinary
    host-fallback recovery per partner — a coalescer defect degrades
    throughput, never correctness (and never kills a job)."""


def window_s() -> float:
    """Configured hold window: ``FGUMI_TPU_COALESCE_WINDOW_MS`` (default
    2 ms; 0 disables coalescing entirely)."""
    try:
        ms = float(os.environ.get("FGUMI_TPU_COALESCE_WINDOW_MS", "2"))
    except ValueError:
        ms = 2.0
    return max(ms, 0.0) / 1e3


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def max_partners() -> int:
    """Group closes (flushes) at this many partners."""
    return max(_env_int("FGUMI_TPU_COALESCE_PARTNERS", 8), 2)


def partner_row_cap() -> int:
    """Fairness guard: a batch with more padded rows than this never
    joins (or holds open) a merge window — it dispatches solo now."""
    return max(_env_int("FGUMI_TPU_COALESCE_PARTNER_ROWS", 1 << 16), 1)


def merged_row_cap() -> int:
    """Merged-batch row budget; a joining partner that would overflow it
    flushes the group and opens the next (arrival order preserved)."""
    return max(_env_int("FGUMI_TPU_COALESCE_MAX_ROWS", 1 << 18), 1)


def _force_mode() -> str:
    v = os.environ.get("FGUMI_TPU_COALESCE", "").strip().lower()
    if v in ("1", "true", "on", "force"):
        return "force"
    if v in ("0", "false", "off"):
        return "off"
    return "auto"


def _raw_fetch(dev, deadline):
    """Deadline-bounded device_get WITHOUT DeviceStats accounting: the
    merged fetch is shared, so its bytes/wait are attributed per partner
    (DeviceStats.add_fetch shares) rather than charged wholesale to
    whichever partner's scope happened to resolve first."""
    from . import kernel as K

    def _get():
        got = K.jax.device_get(dev)
        return tuple(np.asarray(g) for g in got)

    if deadline is None:
        return _get()
    return K._FETCH_RUNNER.run(_get, deadline, "coalesced fetch")


class CoalescedTicket:
    """Resolve-side handle for one partner of a merged dispatch.

    Returned by ``device_call_segments_wire`` in place of a
    :class:`~fgumi_tpu.ops.kernel.DispatchTicket`; the matching
    ``resolve_segments_wire`` call detects it and routes through
    :meth:`DispatchCoalescer.resolve_partner`."""

    __slots__ = ("group", "index")
    #: never a fused consensus→filter dispatch (those dispatch solo), so
    #: resolve_segments_wire_filtered's ``ticket.filter_mode`` gate holds
    filter_mode = False

    def __init__(self, group, index: int):
        self.group = group
        self.index = index


class _Partner:
    """One job's pending batch inside a merge group."""

    __slots__ = ("kernel", "codes", "quals", "seg_ids", "f_pad", "j",
                 "rows", "pred_s", "slot", "ctx", "t_submit")

    def __init__(self, kernel, codes, quals, seg_ids, f_pad, j, pred_s,
                 slot):
        self.kernel = kernel
        self.codes = codes
        self.quals = quals
        self.seg_ids = seg_ids
        self.f_pad = f_pad
        self.j = int(j)
        self.rows = int(codes.shape[0])
        self.pred_s = pred_s
        self.slot = slot
        # the submitter's context: merged-dispatch accounting raised on
        # the flusher/feeder threads must resolve THIS job's telemetry
        # scope, exactly like the feeder's own context copy
        self.ctx = contextvars.copy_context()
        self.t_submit = time.monotonic()


class _MergeGroup:
    """Partners sharing one merged dispatch + its shared fetch."""

    __slots__ = ("key", "seq", "partners", "deadline", "opened", "closed",
                 "rows", "total_j", "dispatched", "feeder_ticket",
                 "flush_failure", "seg_bases", "upload", "t_flush",
                 "_fetch_lock", "_result", "_failure", "_settle_lock",
                 "_ticket_settled")

    def __init__(self, key, seq: int, deadline: float):
        self.key = key
        self.seq = seq
        self.partners = []
        self.opened = time.monotonic()
        self.deadline = deadline
        self.closed = False
        self.rows = 0
        self.total_j = 0
        #: set once the merged dispatch is in the feeder (or failed)
        self.dispatched = threading.Event()
        self.feeder_ticket = None
        self.flush_failure = None
        self.seg_bases = None
        self.upload = 0
        self.t_flush = None
        self._fetch_lock = threading.Lock()
        self._result = None
        self._failure = None
        # feeder-slot settlement: exactly one of {first fetcher, flusher}
        # must abandon/mark_resolved the feeder ticket, even when every
        # partner's deadline fired BEFORE the flush submitted it (a
        # leaked slot would stall the upload pipeline at depth)
        self._settle_lock = threading.Lock()
        self._ticket_settled = False

    # ------------------------------------------------------ shared fetch

    def fetch(self, deadline):
        """(arrays, total_bytes, fetch_wall_s) of the merged result.

        The first partner to arrive performs the wait+fetch (bounded by
        its dispatch deadline) and settles the group; every later partner
        gets the cached result or re-raises the recorded failure — each
        then degrades over its OWN rows, which is what makes a merged
        fault a per-partner event."""
        with self._fetch_lock:
            if self._result is None and self._failure is None:
                self._do_fetch(deadline)
            if self._failure is not None:
                raise self._failure
            return self._result

    def settle_ticket(self, completed=False):
        """Release the feeder ticket's slot exactly once.

        Callable from the first fetcher (either verdict), the flusher's
        exception handler, and the flusher's orphan sweep (every
        partner's deadline fired before the flush submitted — nobody is
        coming back for the ticket): whoever arrives first settles it,
        later callers no-op, and a settle attempt before the ticket
        exists defers to the flusher (the only later caller).

        ``completed=True`` means the ticket's wait finished (result or
        dispatch exception) and ``mark_resolved`` may recycle its
        staging buffers; anything else must ``abandon`` — the dispatch
        may still be running, and recycling a staging buffer under a
        live upload would corrupt whoever reuses it (abandon reclaims
        the slot at late completion and leaks the staging on purpose,
        the feeder's standing wedge contract)."""
        from . import kernel as K

        with self._settle_lock:
            if self._ticket_settled or self.feeder_ticket is None:
                return
            self._ticket_settled = True
            ticket = self.feeder_ticket
        if completed:
            K.DEVICE_FEEDER.mark_resolved(ticket)
        else:
            K.DEVICE_FEEDER.abandon(ticket)

    def _do_fetch(self, deadline):
        from ..utils import faults
        from . import kernel as K

        t0 = time.monotonic()
        try:
            if not self.dispatched.wait(deadline):
                raise K.DeadlineExceeded(
                    f"coalesced dispatch was not flushed within "
                    f"{deadline:.1f}s")
            if self.flush_failure is not None:
                raise CoalesceFlushError(
                    f"merged dispatch build failed: "
                    f"{type(self.flush_failure).__name__}: "
                    f"{self.flush_failure}") from self.flush_failure
            # the router-feed wall starts HERE, once the dispatch is in
            # the feeder — matching the solo resolve's fetch_wait_s
            # (ticket.wait + fetch); the window hold and flush build
            # before this point are queue-shaped time observe_device's
            # contract excludes
            t_disp = time.monotonic()
            left = None if deadline is None else \
                max(deadline - (time.monotonic() - t0), 0.1)
            dev = self.feeder_ticket.wait(left)
            left = None if deadline is None else \
                max(deadline - (time.monotonic() - t0), 1.0)
            # scope-NEUTRAL fetch on purpose: DEVICE_STATS.fetch would
            # charge the full merged bytes + wall to whichever partner
            # resolved first, double-counting against the per-partner
            # add_fetch shares resolve_partner attributes
            got = _raw_fetch(dev, left)
            # SDC chaos point: merged results corrupt exactly like solo
            # ones; the per-partner audit tap attributes the damage
            got = faults.fire("device.fetch", got)
        except BaseException as e:  # noqa: BLE001 - replayed per partner
            self._failure = e
            # only a failure raised BY the ticket's wait proves the
            # dispatch finished; deadline/flush failures must abandon
            # (the dispatch may still be mid-upload)
            self.settle_ticket(
                completed=not isinstance(
                    e, (K.DeadlineExceeded, CoalesceFlushError)))
            return
        self.settle_ticket(completed=True)
        total = sum(int(g.nbytes) for g in got)
        wall = time.monotonic() - t_disp
        self._result = (got, total, wall)
        from .breaker import BREAKER

        BREAKER.record_success()
        # one cost-model feed with the true merged economics — this is
        # what keeps the router's overhead EWMA (and hence the pricing
        # gate in _effective_window_s) honest about merged dispatches.
        # The lambda defers the DEVICE_STATS proxy resolution INTO the
        # leader's context: an eagerly-bound method would read the
        # resolving partner's DeviceStats, where the leader's slot id
        # names an unrelated dispatch.
        leader = self.partners[0]
        tl = leader.ctx.run(
            lambda: K.DEVICE_STATS.timeline_entry(leader.slot))
        if tl is not None:
            from .router import ROUTER

            up_s = tl.get("upload_s", 0.0)
            ROUTER.observe_device(self.upload, total, up_s, wall,
                                  up_s + wall)


class DispatchCoalescer:
    """Process-wide merge window between the engines and the feeder."""

    #: flusher pool cap: distinct-key groups (different jobs' configs)
    #: build independently, so solo flushes from incompatible jobs are
    #: not serialized onto one core in exactly the many-small-jobs
    #: regime the coalescer targets
    MAX_FLUSHERS = 4

    def __init__(self):
        self._lock = threading.Condition()
        self._groups = {}    # key -> the currently OPEN group
        self._pending = []   # open/closed groups not yet flushed
        self._threads = []
        self._seq = 0
        self._serving = False
        self._active_jobs = 0
        self._reset_counters_locked()

    def _reset_counters_locked(self):
        self.merged_batches = 0
        self.solo_flushes = 0
        self.partners_merged = 0
        self.max_partners_seen = 0
        self.oversize_solo = 0
        self.rows_in = 0
        self.rows_dispatched = 0

    def reset(self):
        """Tests: flush pending groups, zero the counters, keep arming
        state (env is re-read per call anyway)."""
        self.drain(timeout=10.0)
        with self._lock:
            self._reset_counters_locked()

    # ----------------------------------------------------------- arming

    def set_serving(self, serving: bool):
        """Daemon lifecycle signal (serve/daemon.py): the window can only
        auto-arm inside a serve process."""
        with self._lock:
            self._serving = bool(serving)

    def set_active_jobs(self, n: int):
        """Live running-job count from the scheduler; the window auto-arms
        at >= 2 and auto-disarms below (single jobs pay zero hold)."""
        with self._lock:
            self._active_jobs = int(n)
        from ..observe.metrics import METRICS

        METRICS.set("device.coalesce.active_jobs", int(n))

    def armed(self) -> bool:
        mode = _force_mode()
        if mode == "off" or window_s() <= 0:
            return False
        if mode == "force":
            return True
        with self._lock:
            return self._serving and self._active_jobs >= 2

    def _effective_window_s(self) -> float:
        """min(configured window, the router's measured per-dispatch
        overhead): merging k batches saves ~(k-1) x overhead, so a hold
        longer than one overhead can only lose to dispatching now — the
        pricing that keeps coalescing strictly non-regressive when
        dispatch is cheap."""
        win = window_s()
        if win <= 0:
            return 0.0
        from .router import ROUTER

        return min(win, max(ROUTER.device_overhead_s(), 0.0))

    # --------------------------------------------------------- admission

    def maybe_submit(self, kernel, codes2d_padded, quals2d_padded, seg_ids,
                     num_segments: int, J: int, full: bool = False,
                     pack_t0: float = None, pred_s: float = None):
        """Admit one plain wire dispatch into the window, or return None
        (caller dispatches solo, unchanged). Runs on the submitting
        engine thread, under the job's telemetry scope."""
        if J <= 0 or _BYPASS.get() or not self.armed():
            return None
        # force mode honors the configured window verbatim (the bench /
        # chaos harness contract: FGUMI_TPU_COALESCE=1 merges regardless
        # of what the overhead EWMA thinks of this host); only auto mode
        # prices the hold against the router
        win = window_s() if _force_mode() == "force" \
            else self._effective_window_s()
        if win <= 0:
            return None
        from ..observe.metrics import METRICS

        rows = int(codes2d_padded.shape[0])
        if rows > partner_row_cap():
            # fairness guard: an oversized batch neither rides nor holds
            # open a merge window
            with self._lock:
                self.oversize_solo += 1
            METRICS.inc("device.coalesce.oversize_solo")
            return None
        from . import kernel as K

        # per-partner accounting under the SUBMITTER's scope — exactly
        # what this batch's solo dispatch would have charged, so per-job
        # run reports stay proportional by construction. The merged
        # upload itself is charged once, to the feeder's byte budget.
        L = int(codes2d_padded.shape[1])
        K.DEVICE_STATS.add_dispatch(K.segments_flops(rows, L, num_segments))
        t0 = pack_t0 if pack_t0 is not None else time.monotonic()
        slot = K.DEVICE_STATS.begin_in_flight(
            rows * L + seg_ids.nbytes, pack_s=time.monotonic() - t0)
        if pred_s is not None:
            K.DEVICE_STATS.note_pred(slot, pred_s)
        partner = _Partner(kernel, codes2d_padded, quals2d_padded, seg_ids,
                           num_segments, J, pred_s, slot)
        key = (kernel._coalesce_key(), L, bool(full))
        now = time.monotonic()
        with self._lock:
            self.rows_in += rows
            group = self._groups.get(key)
            if group is not None and (
                    group.closed
                    or group.rows + rows > merged_row_cap()
                    or len(group.partners) >= max_partners()):
                # arrival order: a newcomer that would overflow flushes
                # the full group and opens the next — never reorders past
                self._close_locked(group)
                group = None
            if group is None:
                self._seq += 1
                group = _MergeGroup(key, self._seq, deadline=now + win)
                self._groups[key] = group
                self._pending.append(group)
            group.partners.append(partner)
            group.rows += rows
            group.total_j += partner.j
            ticket = CoalescedTicket(group, len(group.partners) - 1)
            # early flush once every live job has joined: with the
            # scheduler reporting N running jobs, an N-partner group has
            # nobody left to wait for — the window bounds the straggler
            # case, it is not a mandatory tax on the common one
            target = self._active_jobs if (self._serving
                                           and self._active_jobs >= 2) \
                else None
            if (len(group.partners) >= max_partners()
                    or group.rows >= merged_row_cap()
                    or (target is not None
                        and len(group.partners) >= target)):
                self._close_locked(group)
            self._ensure_thread_locked()
            self._lock.notify_all()
        METRICS.inc("device.coalesce.joined")
        return ticket

    def _close_locked(self, group: _MergeGroup):
        group.closed = True
        if self._groups.get(group.key) is group:
            del self._groups[group.key]

    # ------------------------------------------------------------ flusher

    def _ensure_thread_locked(self):
        self._threads = [t for t in self._threads if t.is_alive()]
        want = min(self.MAX_FLUSHERS, max(len(self._pending), 1))
        while len(self._threads) < want:
            t = threading.Thread(
                target=self._loop,
                name=f"fgumi-coalesce-flush-{len(self._threads)}",
                daemon=True)
            t.start()
            self._threads.append(t)

    def _loop(self):
        while True:
            with self._lock:
                group = None
                while group is None:
                    now = time.monotonic()
                    for g in self._pending:
                        if g.closed or g.deadline <= now:
                            group = g
                            break
                    if group is not None:
                        self._pending.remove(group)
                        self._close_locked(group)
                        break
                    nxt = min((g.deadline for g in self._pending),
                              default=None)
                    self._lock.wait(None if nxt is None
                                    else max(nxt - now, 0.0005))
            try:
                self._flush(group)
            except BaseException as e:  # noqa: BLE001 - degrade, don't die
                log.exception("coalesce: merged dispatch build failed; "
                              "%d partner(s) will degrade to host",
                              len(group.partners))
                group.flush_failure = e
                group.dispatched.set()
                # a raise AFTER the feeder submit with every partner
                # already deadline-expired would otherwise orphan the
                # ticket (idempotent; no-op when no ticket exists yet)
                group.settle_ticket()

    def drain(self, timeout: float = 5.0) -> bool:
        """Flush every held group now (daemon shutdown; tests). True when
        everything reached the feeder within ``timeout``."""
        with self._lock:
            pend = list(self._pending)
            for g in pend:
                g.closed = True
            self._lock.notify_all()
        deadline = time.monotonic() + timeout
        for g in pend:
            left = max(deadline - time.monotonic(), 0.0)
            if not g.dispatched.wait(left):
                return False
        return True

    def _flush(self, group: _MergeGroup):
        """Build + submit one merged dispatch (flusher thread)."""
        from ..observe.flight import FLIGHT
        from ..observe.metrics import METRICS
        from ..utils import faults
        from . import kernel as K
        from .datapath import SHAPE_REGISTRY, STAGING_POOL

        group.t_flush = time.monotonic()
        partners = group.partners
        leader = partners[0]
        kernel = leader.kernel
        k = len(partners)
        L = int(leader.codes.shape[1])
        full = bool(group.key[2])
        real_rows = sum(p.rows for p in partners)
        if k == 1:
            # a window that closed alone dispatches the partner's own
            # arrays verbatim — the solo shape, the solo executable
            codes_m, quals_m = leader.codes, leader.quals
            seg_m, f_pad_m, j_m = leader.seg_ids, leader.f_pad, leader.j
            release, rows_m = (), leader.rows
            group.seg_bases = (0,)
        else:
            # concatenate the PADDED partner layouts: each partner's pad
            # rows are all-N no-ops carrying its last real family id, so
            # the merged seg ids stay sorted after offsetting and the pad
            # rows keep contributing nothing (the pad_segments invariant)
            j_m = group.total_j
            f_pad_m = SHAPE_REGISTRY.bucket_segments(j_m)
            n_pad = SHAPE_REGISTRY.bucket_rows(real_rows)
            codes_m = STAGING_POOL.acquire_filled((n_pad, L), np.uint8,
                                                  N_CODE)
            quals_m = STAGING_POOL.acquire_filled((n_pad, L), np.uint8, 0)
            seg_m = np.full(n_pad, j_m - 1, dtype=np.int32)
            seg_bases = []
            row = base = 0
            for p in partners:
                seg_bases.append(base)
                codes_m[row:row + p.rows] = p.codes
                quals_m[row:row + p.rows] = p.quals
                seg_m[row:row + p.rows] = p.seg_ids
                seg_m[row:row + p.rows] += np.int32(base)
                row += p.rows
                base += p.j
            group.seg_bases = tuple(seg_bases)
            release, rows_m = (codes_m, quals_m), n_pad
        plan = kernel._wire_dispatch_plan(codes_m, quals_m, seg_m, f_pad_m,
                                          j_m, full=full)
        # the merged staging rows were only inputs to the wire build —
        # the plan holds its own (wire/packed) upload buffers
        for arr in release:
            STAGING_POOL.release(arr)
        group.upload = plan.upload

        def _fn():
            # chaos point (utils/faults.py serve.coalesce): a raise/hang
            # INSIDE a merged dispatch must degrade only its partners
            faults.fire("serve.coalesce")
            return plan.dispatch(leader.slot)

        def _submit():
            with SHAPE_REGISTRY.attribute_compiles(plan.new):
                t = K.DEVICE_FEEDER.submit(
                    lambda: K.device_retry_call(_fn,
                                                "coalesced wire dispatch"),
                    upload_bytes=plan.upload, slot=leader.slot)
            t.staging = plan.staging or None
            return t

        # submit inside the leader's context so feeder-side stamps
        # (upload wall, compile events) land in the leader job's scope
        group.feeder_ticket = leader.ctx.run(_submit)
        fill = real_rows / max(rows_m, 1)
        with self._lock:
            self.rows_dispatched += rows_m
            if k > 1:
                self.merged_batches += 1
                self.partners_merged += k
                if k > self.max_partners_seen:
                    self.max_partners_seen = k
            else:
                self.solo_flushes += 1
        if k > 1:
            METRICS.inc("device.coalesce.merged_batches")
            METRICS.inc("device.coalesce.partners", k)
        else:
            METRICS.inc("device.coalesce.solo_flushes")
        METRICS.observe("device.coalesce.fill_ratio", fill)
        FLIGHT.note("device.coalesce.merge", partners=k, rows=rows_m,
                    segments=j_m, upload=plan.upload,
                    fill=round(fill, 4))
        group.dispatched.set()
        # orphan sweep: if every partner's deadline already fired while
        # this flush was still building (their wait-for-flush timed out
        # BEFORE the ticket existed), nobody is coming back to resolve
        # it — settle the slot here or the feeder pipeline leaks it
        if group._failure is not None:
            group.settle_ticket()

    # ------------------------------------------------------------ resolve

    def resolve_partner(self, kernel, ticket: CoalescedTicket, codes2d,
                        quals2d, starts, split_depth: int = 0,
                        want_extras: bool = False):
        """One partner's half of resolve_segments_wire: shared fetch,
        per-partner slice, unchanged host completion — or per-partner
        degrade over its own rows on any merged-dispatch failure."""
        from ..observe.metrics import METRICS
        from . import kernel as K

        group = ticket.group
        partner = group.partners[ticket.index]
        t0 = time.monotonic()
        deadline = K.dispatch_deadline_s(partner.pred_s)
        failure = None
        share = 0
        got = None
        try:
            got, total, _wall = group.fetch(deadline)
            share = int(total * partner.j / max(group.total_j, 1))
        except BaseException as e:  # noqa: BLE001 - classified below
            failure = e
        wait = time.monotonic() - t0
        # proportional attribution under the partner's own scope: its
        # bytes share, its measured resolve wait, its own timeline slot
        K.DEVICE_STATS.add_fetch(share, wait)
        K.DEVICE_STATS.end_in_flight(partner.slot, share, wait)
        METRICS.observe(
            "device.coalesce.window_wait_s",
            max((group.t_flush or t0) - partner.t_submit, 0.0))
        if failure is not None:
            METRICS.inc("device.coalesce.partner_degraded")
            starts64 = np.asarray(starts, dtype=np.int64)
            if isinstance(failure, K.DeadlineExceeded):
                out = kernel._deadline_fallback_segments(
                    failure, codes2d, quals2d, starts64)
            elif (isinstance(failure, CoalesceFlushError)
                    or K._is_oom(failure) or K._is_transient(failure)):
                out = kernel._recover_segments(failure, codes2d, quals2d,
                                               starts64, split_depth)
            else:
                raise failure
            if want_extras:
                return out + ({"suspect": None, "resident": None,
                               "gather": None},)
            return out
        base = group.seg_bases[ticket.index]
        j = partner.j
        if len(got) == 4:
            qs, wp, d16, e16 = got
            d_sl, e_sl = d16[base:base + j], e16[base:base + j]
        else:
            qs, wp = got
            d_sl = e_sl = None
        return kernel._complete_wire_columns(
            qs[base:base + j], wp[base:base + j], d_sl, e_sl,
            codes2d, quals2d, starts, want_extras=want_extras,
            slot=partner.slot,
            partner={"group": group.seq, "index": ticket.index,
                     "partners": len(group.partners)})

    # ----------------------------------------------------------- surface

    def has_activity(self) -> bool:
        with self._lock:
            return bool(self.merged_batches or self.solo_flushes
                        or self.oversize_solo or self._pending)

    def snapshot(self) -> dict:
        """The serve ``stats`` op / ``/metrics`` ``coalesce`` section."""
        armed = self.armed()
        with self._lock:
            return {
                "armed": armed,
                "mode": _force_mode(),
                "window_ms": round(window_s() * 1e3, 3),
                "serving": self._serving,
                "active_jobs": self._active_jobs,
                "merged_batches": self.merged_batches,
                "solo_flushes": self.solo_flushes,
                "partners": self.partners_merged,
                "max_partners": self.max_partners_seen,
                "oversize_solo": self.oversize_solo,
                "rows_in": self.rows_in,
                "rows_dispatched": self.rows_dispatched,
                "pending_groups": len(self._pending),
            }


#: process-wide singleton: the merge window spans every job in the daemon.
COALESCER = DispatchCoalescer()
