"""Silent-corruption sentinel: online shadow audit of device results.

Every robustness layer before this one defends against faults that
*announce themselves* — exceptions, wedges, ENOSPC, dead peers. Nothing
defended against an accelerator that silently returns the wrong answer:
the defective-but-non-crashing core class of failure large fleets report
as the hardest to catch (Google's "Cores that don't count", Meta's SDC
study). fgumi's whole value proposition is byte-exact output, and one
flaky chip in a fleet corrupts consensus calls with zero signal in any
existing metric, breaker, or flight dump.

The sentinel closes that gap with an *online shadow audit*: a
deterministic counter-based sample of resolved device dispatches
(``FGUMI_TPU_AUDIT`` rate, default 1 in :data:`DEFAULT_RATE`; ``off`` and
``all`` supported) is re-executed on the native f64 host oracle — the
same engine every degraded path already trusts for byte-identical
completion — and the device's winner/qual/depth/errors are compared
exactly against the oracle's. Any mismatch is an SDC verdict:

- the :class:`~fgumi_tpu.ops.breaker.DeviceBreaker` trips with the new
  ``sdc`` reason (quarantine: cooldown does NOT half-open back
  automatically — re-admission requires ``FGUMI_TPU_AUDIT_READMIT``
  probe dispatches that are themselves fully audited);
- the offload router is forced host-side (open breaker) for every
  later batch, including explicitly forced ``FGUMI_TPU_ROUTE=device``;
- the flight recorder freezes a black box carrying both result buffers'
  sha256 digests;
- the run report grows an ``audit.divergence`` record — the corrupt
  result was already consumed by the caller (sampled mode), so the
  artifact must tell the operator which output to distrust.

Execution model, two modes:

- **sampled** (rate N > 1): the audit runs on one low-priority background
  thread. The resolve thread only pays the sample decision plus one copy
  of the dispatch's dense inputs into recycled
  :class:`~fgumi_tpu.ops.datapath.HostStagingPool` buffers (released when
  the audit finishes, either verdict — audit never extends
  staging-buffer lifetime unboundedly; the pending queue is bounded and
  overflow *drops* the sample, counted, rather than accumulating).
- **inline** (``all``, or any dispatch while the breaker is
  SDC-quarantined): the audit runs synchronously on the resolve thread
  and a divergent dispatch is *repaired* — the resolve returns the
  oracle result the audit just computed, so the published output stays
  byte-identical to a pure-host run. This is the chaos/CI mode and the
  re-admission probe mode.

Scoreboards ride ``METRICS`` (``device.audit.{sampled,clean,divergent,
dropped}``) and the per-device attribution map (mesh dispatches name the
shard each divergent family was computed on) rides the run report /
``stats`` op / Prometheus, where the fleet balancer ejects any backend
whose stats report ``divergent > 0``.

The output-side integrity pass (``--audit-output``, io/bam.py +
io/bgzf.py) records its verdicts here too, so one ``audit`` section
answers both "did the device lie" and "did the written file survive the
page cache".
"""

import hashlib
import logging
import os
import threading
import time
from collections import deque

import numpy as np

log = logging.getLogger("fgumi_tpu")

#: Default sample rate: one audited dispatch per this many device resolves.
DEFAULT_RATE = 64

#: Default bound on queued (not yet executed) background audits; overflow
#: drops the newest sample (counted in ``dropped``) instead of retaining
#: staging buffers without bound.
DEFAULT_QUEUE = 4

#: Bounded evidence kept for the run report.
MAX_DIVERGENCE_RECORDS = 16
MAX_OUTPUT_RECORDS = 8
#: Recent sampled dispatch ordinals (debug/determinism tests).
MAX_SAMPLED_ORDINALS = 64


def audit_rate() -> int:
    """Parsed ``FGUMI_TPU_AUDIT``: 0 = off, 1 = every dispatch (inline),
    N > 1 = one audited dispatch per N resolves (background)."""
    v = os.environ.get("FGUMI_TPU_AUDIT", "").strip().lower()
    if v in ("", "default"):
        return DEFAULT_RATE
    if v in ("off", "0", "false", "none"):
        return 0
    if v in ("all", "always", "1"):
        return 1
    try:
        return max(int(v), 0)
    except ValueError:
        log.warning("FGUMI_TPU_AUDIT=%r: expected off/all/N; using the "
                    "default 1/%d", v, DEFAULT_RATE)
        return DEFAULT_RATE


def _queue_cap() -> int:
    try:
        return max(int(os.environ.get("FGUMI_TPU_AUDIT_QUEUE",
                                      str(DEFAULT_QUEUE))), 1)
    except ValueError:
        return DEFAULT_QUEUE


_FIELDS = ("winner", "qual", "depth", "errors")


def _digest(arrays) -> str:
    h = hashlib.sha256()
    for a in arrays:
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


class AuditSentinel:
    """The process-wide shadow-audit machinery (singleton :data:`SENTINEL`).

    Like the breaker and the router, audit state is a per-process fact —
    the device under audit is shared by every job in the process — while
    the ``device.audit.*`` METRICS land in whichever telemetry scope
    observed them (the audit worker runs under the sampling resolve's
    captured context, exactly like the device feeder)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._q = deque()
        self._busy = False
        self._thread = None
        self._reset_locked()

    def _reset_locked(self):
        self._counter = 0
        self.sampled = 0
        self.clean = 0
        self.divergent = 0
        self.dropped = 0
        self.inline_audits = 0
        self.sampled_ordinals = deque(maxlen=MAX_SAMPLED_ORDINALS)
        # device index -> {"sampled", "clean", "divergent"}; single-device
        # dispatches attribute to device 0, mesh dispatches to every shard
        # that contributed rows (divergent rows name their shard exactly)
        self.devices = {}
        self.divergences = deque(maxlen=MAX_DIVERGENCE_RECORDS)
        self.output_audits = deque(maxlen=MAX_OUTPUT_RECORDS)

    def reset(self):
        """Tests: drop counters/evidence and any queued audits (their
        staging buffers are released)."""
        with self._lock:
            items, self._q = list(self._q), deque()
            self._reset_locked()
        for item in items:
            self._release(item)

    # ----------------------------------------------------------- sampling

    def maybe_audit(self, kernel, codes2d, quals2d, starts,
                    winner, qual, depth, errors, devices: int = 1,
                    gather=None, f_loc=None, slot: int = -1, partner=None):
        """The resolve-path tap: decide, retain, and (maybe) audit.

        Called once per cleanly-resolved *device* dispatch with the dense
        host-side inputs and the final post-oracle outputs the caller is
        about to consume. Returns ``None`` (caller proceeds unchanged) or,
        for an inline audit that found a divergence, the repaired
        ``(winner, qual, depth, errors)`` oracle tuple the caller must
        publish instead. Never raises: a broken audit must not fail a
        healthy resolve.

        ``partner``: merge attribution for a coalesced dispatch
        (ops/coalesce.py) — ``{"group", "index", "partners"}`` naming this
        job's slice of the merged launch; a divergence record carries it
        so the operator knows which partner's output (and which merge) to
        distrust. Each partner's resolve taps here separately, over its
        own family slice."""
        try:
            return self._maybe_audit(kernel, codes2d, quals2d, starts,
                                     winner, qual, depth, errors,
                                     devices, gather, f_loc, slot, partner)
        except Exception:  # noqa: BLE001 - audit failure != batch failure
            log.exception("audit sentinel: tap failed; dispatch unaudited")
            return None

    def _maybe_audit(self, kernel, codes2d, quals2d, starts, winner, qual,
                     depth, errors, devices, gather, f_loc, slot, partner):
        rate = audit_rate()
        from .breaker import BREAKER

        # while SDC-quarantined every admitted dispatch IS a re-admission
        # probe and must be fully audited, whatever the sample rate
        forced = BREAKER.audit_required()
        if rate <= 0 and not forced:
            return None
        from ..native import batch as nb

        if not nb.available():
            return None  # no oracle to shadow against
        t0 = time.monotonic()
        with self._lock:
            self._counter += 1
            ordinal = self._counter
        if not (forced or rate == 1 or ordinal % rate == 0):
            return None
        from ..observe.metrics import METRICS

        inline = forced or rate == 1
        with self._lock:
            if not inline and len(self._q) >= _queue_cap():
                # bounded retention: drop THIS sample — before paying the
                # input copies — rather than pile staging buffers behind
                # a slow oracle (an overloaded audit path must be nearly
                # free, not the most expensive tap outcome)
                self.sampled += 1
                self.sampled_ordinals.append(ordinal)
                self.dropped += 1
                drop = True
            else:
                drop = False
                self.sampled += 1
                self.sampled_ordinals.append(ordinal)
                for d in range(max(int(devices), 1)):
                    self._device_locked(d)["sampled"] += 1
        METRICS.inc("device.audit.sampled")
        if drop:
            METRICS.inc("device.audit.dropped")
            return None
        item = self._retain(kernel, codes2d, quals2d, starts, winner, qual,
                            depth, errors, devices, gather, f_loc, slot,
                            ordinal, partner)
        # only a FORCED (quarantine-probe) audit may later feed
        # record_audit_clean: a stale background sample taken before the
        # trip proves nothing about the quarantined device's probes
        item["forced"] = forced
        if inline:
            # inline: verdict before the caller consumes the result, so a
            # divergent dispatch can be repaired with the oracle tuple the
            # audit just computed (byte-identity preserved end to end)
            with self._lock:
                self.inline_audits += 1
            repaired = self._audit_one(item)
            METRICS.observe("device.audit.tap_s", time.monotonic() - t0)
            return repaired
        with self._lock:
            # benign overshoot: concurrent resolvers may each have passed
            # the pre-retain check; the queue grows past the cap by at
            # most the feeder depth
            import contextvars

            self._q.append((contextvars.copy_context(), item))
            self._ensure_thread_locked()
            self._cv.notify_all()
        METRICS.observe("device.audit.tap_s", time.monotonic() - t0)
        return None

    # ------------------------------------------- fused-filter route tap

    def maybe_audit_filter(self, kernel, codes2d, quals2d, starts, stats,
                           resident, filter_ctx, slot: int = -1):
        """The fused consensus→filter resolve tap (ISSUE 19, closing the
        PR 13 gap): `--device-filter` dispatches fetch only a (J, 7) i32
        stats row, so the standard column tap never sees them.

        Audits against the f64 host oracle + the numpy twin of the
        device's integer filter epilogue
        (consensus.device_filter.fused_stats_oracle), restricted to rows
        whose device stats carry suspect == 0 — the guard band proves
        those rows exact on every backend, and device-suspect rows are
        re-resolved host-side downstream regardless (a corrupt bit that
        turns suspect ON costs performance, never bytes; one that turns
        it OFF exposes the row to this comparison). Inline audits
        additionally verify the survivors-gather bytes off the resident
        columns. Returns None, or — inline divergence — the repaired
        pre-threshold (winner, qual, depth, errors) oracle tuple; the
        caller then releases the resident columns and falls back to its
        host filter pass. Never raises."""
        try:
            return self._maybe_audit_filter(kernel, codes2d, quals2d,
                                            starts, stats, resident,
                                            filter_ctx, slot)
        except Exception:  # noqa: BLE001 - audit failure != batch failure
            log.exception("audit sentinel: filter tap failed; dispatch "
                          "unaudited")
            return None

    def _maybe_audit_filter(self, kernel, codes2d, quals2d, starts, stats,
                            resident, filter_ctx, slot):
        rate = audit_rate()
        from .breaker import BREAKER

        forced = BREAKER.audit_required()
        if (rate <= 0 and not forced) or filter_ctx is None:
            return None
        from ..native import batch as nb

        if not nb.available():
            return None
        t0 = time.monotonic()
        with self._lock:
            self._counter += 1
            ordinal = self._counter
        if not (forced or rate == 1 or ordinal % rate == 0):
            return None
        from ..observe.metrics import METRICS

        inline = forced or rate == 1
        with self._lock:
            self.sampled += 1
            self.sampled_ordinals.append(ordinal)
            if not inline and len(self._q) >= _queue_cap():
                self.dropped += 1
                drop = True
            else:
                drop = False
                self._device_locked(0)["sampled"] += 1
        METRICS.inc("device.audit.sampled")
        if drop:
            METRICS.inc("device.audit.dropped")
            return None
        mr, mq, lens_j, fparams = filter_ctx
        item = self._retain(kernel, codes2d, quals2d, starts,
                            *(np.zeros(0, np.int32),) * 4, 1, None, None,
                            slot, ordinal)
        item["forced"] = forced
        item["filter"] = {
            "stats": np.array(stats, copy=True),
            "mr": int(mr), "mq": int(mq),
            "lens": np.array(lens_j, dtype=np.int64, copy=True),
            "fparams": fparams,
            # resident columns only ride an INLINE audit: a background
            # sample must not race the caller's survivor gather/release
            "resident": resident if inline else None,
        }
        if inline:
            with self._lock:
                self.inline_audits += 1
            repaired = self._audit_filter_one(item)
            METRICS.observe("device.audit.tap_s", time.monotonic() - t0)
            return repaired
        with self._lock:
            import contextvars

            self._q.append((contextvars.copy_context(), item))
            self._ensure_thread_locked()
            self._cv.notify_all()
        METRICS.observe("device.audit.tap_s", time.monotonic() - t0)
        return None

    def _audit_filter_one(self, item):
        """Oracle re-derivation of one fused-filter dispatch: stats rows
        always; survivor-gather bytes when the resident columns rode
        along (inline). Returns the repaired pre-threshold oracle tuple
        on divergence, else None."""
        try:
            from ..consensus.device_filter import (S_SUSPECT,
                                                   fused_stats_oracle)

            fctx = item["filter"]
            engine = item["kernel"]._host()
            # same deliberate bypass of _host_engine_complete as
            # _audit_one: measurement, not workload
            w, q, d, e, _n_slow = engine.call_segments_counted(
                item["codes"], item["quals"], item["starts"])
            host_stats, host_fb, host_fq = fused_stats_oracle(
                w, q, d, e, fctx["lens"], fctx["mr"], fctx["mq"],
                fctx["fparams"])
            dev_stats = item["stats"] = fctx["stats"]
            trusted = dev_stats[:, S_SUSPECT] == 0
            mask = trusted & (dev_stats[:, :S_SUSPECT]
                              != host_stats[:, :S_SUSPECT]).any(axis=1)
            bad_fields = ["stats"] if mask.any() else []
            resident = fctx["resident"]
            if resident is not None and not mask.any():
                gmask = self._gather_divergence(
                    item["kernel"], resident, trusted, fctx["lens"],
                    host_fb, host_fq, d, e)
                if gmask is None:
                    return None  # gather weather: unaudited, no verdict
                if gmask.any():
                    mask = gmask
                    bad_fields = ["gather"]
            if not bad_fields:
                self._verdict_clean(item)
                return None
            self._filter_divergent(item, host_stats, bad_fields, mask)
            return w, q, d, e
        finally:
            self._release(item)

    def _gather_divergence(self, kernel, resident, trusted, lens,
                           host_fb, host_fq, host_d, host_e):
        """Inline-only survivor-gather audit: fetch every row's masked
        columns off the resident arrays and compare the consumed surface
        (in-length positions of non-suspect rows) against the oracle.
        None = gather failed (device weather), no verdict either way."""
        J = len(lens)
        try:
            fb, fq, dd, ee = kernel.filter_gather_filtered(
                resident, np.arange(J, dtype=np.int64))
        except Exception as exc:  # noqa: BLE001 - weather, not corruption
            log.warning("audit sentinel: survivor-gather audit skipped "
                        "(gather failed: %s)", exc)
            return None
        in_len = (np.arange(host_fb.shape[1], dtype=np.int64)[None, :]
                  < np.asarray(lens)[:, None])
        keep = trusted[:, None] & in_len
        diff = ((fb != host_fb) | (fq != host_fq)
                | (dd != host_d) | (ee != host_e)) & keep
        return diff.any(axis=1)

    def _filter_divergent(self, item, host_stats, bad_fields, fam_mask):
        """Divergence verdict for the fused-filter route: same evidence
        chain as _verdict_divergent (record, flight note + black box,
        SDC quarantine), with the stats rows as the compared buffers."""
        fam_idx = np.nonzero(fam_mask)[0]
        record = {
            "ordinal": item["ordinal"],
            "slot": item["slot"],
            "route": "device-filter",
            "families": int(len(fam_idx)),
            "first_families": [int(f) for f in fam_idx[:8]],
            "fields": bad_fields,
            "devices": [0],
            "device_digest": _digest([item["stats"]]),
            "host_digest": _digest([host_stats]),
        }
        from ..observe.metrics import METRICS

        with self._lock:
            self.divergent += 1
            self.divergences.append(record)
            self._device_locked(0)["divergent"] += 1
        METRICS.inc("device.audit.divergent")
        log.error(
            "AUDIT DIVERGENCE: fused-filter dispatch (slot %d) disagrees "
            "with the f64 host oracle on %d/%d reads (fields: %s) — "
            "silent data corruption; quarantining the device (device "
            "digest %.12s..., host digest %.12s...)",
            item["slot"], len(fam_idx), len(fam_mask),
            ",".join(bad_fields), record["device_digest"],
            record["host_digest"])
        from ..observe.flight import FLIGHT

        FLIGHT.note("audit.divergence", **{k: v for k, v in record.items()
                                           if k != "first_families"})
        from .breaker import BREAKER

        BREAKER.record_sdc(
            f"{len(fam_idx)} reads, fused-filter fields "
            f"{','.join(bad_fields)}")
        FLIGHT.dump("sdc-divergence", **record)

    def _retain(self, kernel, codes2d, quals2d, starts, winner, qual,
                depth, errors, devices, gather, f_loc, slot, ordinal,
                partner=None):
        """Copy everything the audit needs: inputs into recycled staging
        buffers (the caller may mutate or free its arrays the moment the
        resolve returns), outputs into plain copies (small)."""
        from .datapath import STAGING_POOL

        codes = STAGING_POOL.acquire(codes2d.shape, codes2d.dtype)
        np.copyto(codes, codes2d)
        quals = STAGING_POOL.acquire(quals2d.shape, quals2d.dtype)
        np.copyto(quals, quals2d)
        return {
            "kernel": kernel,
            "codes": codes,
            "quals": quals,
            "starts": np.array(starts, dtype=np.int64, copy=True),
            "device_result": tuple(np.array(a, copy=True) for a in
                                   (winner, qual, depth, errors)),
            "devices": max(int(devices), 1),
            "gather": None if gather is None
            else np.array(gather, copy=True),
            "f_loc": f_loc,
            "slot": slot,
            "ordinal": ordinal,
            "partner": dict(partner) if partner else None,
        }

    @staticmethod
    def _release(item):
        """Return the retained input buffers to the staging pool (both
        verdicts, and on drop/reset)."""
        from .datapath import STAGING_POOL

        STAGING_POOL.release(item.pop("codes", None))
        STAGING_POOL.release(item.pop("quals", None))

    def _device_locked(self, d: int) -> dict:
        entry = self.devices.get(int(d))
        if entry is None:
            entry = self.devices[int(d)] = {"sampled": 0, "clean": 0,
                                            "divergent": 0}
        return entry

    # ------------------------------------------------------ audit worker

    def _ensure_thread_locked(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._loop,
                                            name="fgumi-audit-sentinel",
                                            daemon=True)
            self._thread.start()

    def _loop(self):
        while True:
            with self._lock:
                self._busy = False
                self._cv.notify_all()
                while not self._q:
                    self._cv.wait()
                ctx, item = self._q.popleft()
                self._busy = True
            try:
                # the submitting resolve's context rides along so the
                # clean/divergent metrics land in its telemetry scope
                ctx.run(self._audit_filter_one if "filter" in item
                        else self._audit_one, item)
            except Exception:  # noqa: BLE001 - worker must survive
                log.exception("audit sentinel: background audit raised")

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait for every queued background audit to finish (command exit,
        before the run report is built). True when idle within timeout."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while self._q or self._busy:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(min(left, 0.5))
        return True

    # -------------------------------------------------------- the audit

    def _audit_one(self, item):
        """Re-execute one retained dispatch on the f64 host oracle and
        compare exactly. Returns the oracle tuple when divergent (the
        inline caller's repair value), else None."""
        try:
            engine = item["kernel"]._host()
            # deliberately NOT routed through _host_engine_complete: the
            # audit must not feed the router's host-rate EWMA (it would
            # skew the offload crossover) nor the kernel's oracle-fallback
            # accounting — the shadow run is measurement, not workload
            w, q, d, e, _n_slow = engine.call_segments_counted(
                item["codes"], item["quals"], item["starts"])
            host = (w, q, d, e)
            dev = item["device_result"]
            bad_fields = [name for name, da, ha in
                          zip(_FIELDS, dev, host)
                          if not np.array_equal(da, ha)]
            if not bad_fields:
                self._verdict_clean(item)
                return None
            return self._verdict_divergent(item, host, bad_fields)
        finally:
            self._release(item)

    def _verdict_clean(self, item):
        from ..observe.metrics import METRICS

        with self._lock:
            self.clean += 1
            for dv in range(item["devices"]):
                self._device_locked(dv)["clean"] += 1
        METRICS.inc("device.audit.clean")
        if item.get("forced"):
            # a fully-audited re-admission probe came back clean: this is
            # the ONLY feedback that counts toward lifting the quarantine.
            # Checking the item's own flag — not the breaker's live state
            # — so a stale background sample taken BEFORE the trip can
            # never masquerade as a probe verdict after it.
            from .breaker import BREAKER

            BREAKER.record_audit_clean()

    def _verdict_divergent(self, item, host, bad_fields):
        dev = item["device_result"]
        # which families (and, on a mesh dispatch, which shard devices)
        # produced corrupt rows — the per-device attribution the fleet
        # tier ejects on
        mask = np.zeros(len(item["starts"]) - 1, dtype=bool)
        for name, da, ha in zip(_FIELDS, dev, host):
            if name in bad_fields:
                diff = np.asarray(da) != np.asarray(ha)
                mask[: len(mask)] |= diff.reshape(len(mask), -1).any(axis=1)
        fam_idx = np.nonzero(mask)[0]
        gather, f_loc = item["gather"], item["f_loc"]
        if gather is not None and f_loc:
            shards = sorted(set(
                int(gather[f]) // int(f_loc) for f in fam_idx))
        else:
            shards = [0]
        record = {
            "ordinal": item["ordinal"],
            "slot": item["slot"],
            "families": int(len(fam_idx)),
            "first_families": [int(f) for f in fam_idx[:8]],
            "fields": bad_fields,
            "devices": shards,
            "device_digest": _digest(dev),
            "host_digest": _digest(host),
        }
        if item.get("partner"):
            # coalesced dispatch: name the merge + the partner slice the
            # corruption landed in (ops/coalesce.py attribution)
            record["partner"] = item["partner"]
        from ..observe.metrics import METRICS

        with self._lock:
            self.divergent += 1
            self.divergences.append(record)
            for dv in shards:
                self._device_locked(dv)["divergent"] += 1
            for dv in range(item["devices"]):
                if dv not in shards:
                    self._device_locked(dv)["clean"] += 1
        METRICS.inc("device.audit.divergent")
        log.error(
            "AUDIT DIVERGENCE: device dispatch (slot %d) disagrees with "
            "the f64 host oracle on %d/%d families (fields: %s; shard "
            "devices %s) — silent data corruption; quarantining the "
            "device (device digest %.12s..., host digest %.12s...)",
            item["slot"], len(fam_idx), len(mask), ",".join(bad_fields),
            shards, record["device_digest"], record["host_digest"])
        from ..observe.flight import FLIGHT

        FLIGHT.note("audit.divergence", **{k: v for k, v in record.items()
                                           if k != "first_families"})
        from .breaker import BREAKER

        BREAKER.record_sdc(
            f"{len(fam_idx)} families, fields {','.join(bad_fields)}")
        # the black box carries both buffers' digests (the breaker's own
        # trip dump may have fired first under reason breaker-open; this
        # one is audit-specific and carries the divergence evidence)
        FLIGHT.dump("sdc-divergence", **record)
        return host

    # ------------------------------------------------------ output audit

    def note_output_audit(self, path: str, ok: bool, members: int = 0,
                          records: int = 0, error: str = None):
        """Record one ``--audit-output`` pre-commit verification verdict
        (io/bam.py) so the run report's ``audit`` section covers the
        output side too."""
        rec = {"path": path, "ok": bool(ok), "members": int(members),
               "records": int(records)}
        if error:
            rec["error"] = str(error)[:300]
        with self._lock:
            self.output_audits.append(rec)
        from ..observe.metrics import METRICS

        METRICS.inc("io.output_audit." + ("ok" if ok else "failed"))
        if not ok:
            from ..observe.flight import FLIGHT

            FLIGHT.note("audit.output_failed", path=path,
                        error=rec.get("error"))

    # ---------------------------------------------------------- snapshot

    def has_activity(self) -> bool:
        with self._lock:
            return bool(self.sampled or self.dropped or self.divergent
                        or self.output_audits)

    def snapshot(self) -> dict:
        """The run report / ``stats`` op ``audit`` section."""
        with self._lock:
            out = {
                "rate": os.environ.get("FGUMI_TPU_AUDIT", "") or
                f"1/{DEFAULT_RATE}",
                "sampled": self.sampled,
                "clean": self.clean,
                "divergent": self.divergent,
                "dropped": self.dropped,
                "pending": len(self._q) + (1 if self._busy else 0),
                "devices": {str(k): dict(v)
                            for k, v in sorted(self.devices.items())},
            }
            if self.divergences:
                out["divergence"] = [dict(r) for r in self.divergences]
            if self.output_audits:
                out["output"] = [dict(r) for r in self.output_audits]
            return out


#: Process-wide singleton: the device under audit is a per-process fact.
SENTINEL = AuditSentinel()
