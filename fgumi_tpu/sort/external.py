"""External merge sort for BAM records.

Analog of /root/reference/crates/fgumi-sort (RawExternalSorter, external.rs:1594):
phase 1 accumulates records to a memory budget, sorts by extracted keys, spills
compressed runs; phase 2 k-way merges the runs. Three orders (keys.rs:180-241):

- coordinate: (tid, pos) with unmapped-last, SO:coordinate;
- queryname: natural (digit runs compare numerically) or lexicographic name order
  with R1-before-R2 within a template, SO:queryname;
- template-coordinate: both template ends' unclipped 5' (earlier end first), strand
  (reverse first), library, name, lower-end-record first — SO:unsorted GO:query
  SS:unsorted:template-coordinate (TemplateKey, fgumi-sort/src/inline.rs:620-694).

Spill runs use raw-deflate frames (zlib level 1), the Python analog of the zstd-1
spill codec choice (codec.rs:7-8).
"""

import heapq
import os
import re
import struct
import tempfile
import time
import zlib

from ..core.overlap import parse_soft_clips_and_ref_len
from ..utils.governor import GOVERNOR, reraise_enospc
from ..core.template import library_lookup_from_header, unclipped_5prime
from ..io.bam import (FLAG_FIRST, FLAG_LAST, FLAG_MATE_REVERSE,
                      FLAG_MATE_UNMAPPED, FLAG_PAIRED, FLAG_REVERSE,
                      FLAG_SECONDARY, FLAG_SUPPLEMENTARY, FLAG_UNMAPPED, RawRecord)

_DIGITS = re.compile(rb"(\d+)")


def natural_name_key(name: bytes):
    """Natural queryname ordering: digit runs compare numerically (keys.rs natural).

    Elements are type-tagged (digit runs sort before text at the same position) so
    mixed structures stay comparable."""
    parts = _DIGITS.split(name)
    return tuple((0, int(p), b"") if p.isdigit() else (1, 0, p)
                 for p in parts if p != b"")


def _within_name_rank(flag: int) -> tuple:
    """Sub-order records of one template: primaries first, R1 before R2."""
    return (
        bool(flag & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY)),
        0 if not flag & FLAG_PAIRED else (1 if flag & FLAG_FIRST else 2),
        flag,
    )


def coordinate_key(rec: RawRecord):
    """samtools coordinate order: mapped by (tid, pos), unmapped (tid<0) last."""
    tid = rec.ref_id
    return (tid < 0, tid, rec.pos)


def queryname_key(rec: RawRecord, lexicographic: bool = False):
    name = rec.name
    return ((name if lexicographic else natural_name_key(name)),
            _within_name_rank(rec.flag))


# tid sentinel above any real reference id (tids are int32 < 2^31); a 16-bit
# sentinel would misorder assemblies with >65k contigs
_UNMAPPED_SENTINEL = (1 << 31, 0x7FFFFFFF, False)


def _mate_end_info(rec: RawRecord):
    """Mate's (tid, unclipped 5' pos, reverse) from next_* fields + MC tag."""
    if not rec.flag & FLAG_PAIRED or rec.flag & FLAG_MATE_UNMAPPED \
            or rec.next_ref_id < 0:
        return _UNMAPPED_SENTINEL
    mate_rev = bool(rec.flag & FLAG_MATE_REVERSE)
    mate_pos = rec.next_pos + 1  # 1-based
    mc = rec.get_str(b"MC")
    leading = ref_len = trailing = 0
    if mc is not None:
        parsed = parse_soft_clips_and_ref_len(mc)
        if parsed is not None:
            leading, ref_len, trailing = parsed
    if mate_rev:
        pos = mate_pos - 1 + max(ref_len, 1) - 1 + trailing + 1  # unclipped end, 1-based
    else:
        pos = mate_pos - leading
    return (rec.next_ref_id, pos, mate_rev)


def template_coordinate_key(rec: RawRecord, library_ord: int, mi: tuple):
    """TemplateKey analog (inline.rs:620-694): earlier end first; reverse strand
    sorts before forward; the record at the lower end sorts before its mate."""
    flag = rec.flag
    if flag & FLAG_UNMAPPED:
        own = _UNMAPPED_SENTINEL
    else:
        own = (rec.ref_id, unclipped_5prime(rec) + 1, bool(flag & FLAG_REVERSE))
    mate = _mate_end_info(rec)
    if own <= mate:
        lo, hi, is_upper = own, mate, False
    else:
        lo, hi, is_upper = mate, own, True
    tid1, pos1, neg1 = lo
    tid2, pos2, neg2 = hi
    # reverse sorts before forward (inverted flags, inline.rs:679-681)
    return (tid1, tid2, pos1, pos2, not neg1, not neg2, library_ord, mi,
            rec.name, is_upper)


class SortContext:
    """Header-derived context for key extraction."""

    def __init__(self, header):
        lookup = library_lookup_from_header(header.text)
        libs = sorted(set(lookup.values()) | {"unknown"})
        self._lib_ord = {lib: i for i, lib in enumerate(libs)}
        self._rg_to_ord = {rg: self._lib_ord[lib] for rg, lib in lookup.items()}

    def library_ordinal(self, rec: RawRecord) -> int:
        rg = rec.get_str(b"RG")
        return self._rg_to_ord.get(rg, self._lib_ord["unknown"])


def _mi_key(rec: RawRecord) -> tuple:
    mi = rec.get_str(b"MI")
    if mi is None:
        return (0, 0)
    base, _, suffix = mi.partition("/")
    try:
        value = int(base)
    except ValueError:
        value = 0
    return (value, 0 if suffix == "A" else 1)


def make_key_fn(order: str, header, subsort: str = "natural"):
    """Key function for one of coordinate|queryname|template-coordinate."""
    if order == "coordinate":
        return coordinate_key
    if order == "queryname":
        lex = subsort == "lex"
        return lambda rec: queryname_key(rec, lexicographic=lex)
    if order == "template-coordinate":
        ctx = SortContext(header)
        return lambda rec: template_coordinate_key(rec, ctx.library_ordinal(rec),
                                                   _mi_key(rec))
    raise ValueError(f"unknown sort order: {order}")


def header_tags_for_order(order: str, subsort: str = "natural"):
    """(SO, GO, SS) header values (keys.rs:205-241)."""
    if order == "coordinate":
        return "coordinate", None, None
    if order == "queryname":
        # SAM-spec sub-sort keywords: "natural" / "lexicographical" (keys.rs SORT3-10)
        spelled = "lexicographical" if subsort == "lex" else subsort
        return "queryname", None, f"queryname:{spelled}"
    return "unsorted", "query", "unsorted:template-coordinate"


# Target uncompressed bytes per spill frame: bounds merge-phase memory to
# O(runs * frame size) instead of O(total), mirroring the reference's block-framed
# spill streams (zspill_stream.rs).
_FRAME_BYTES = 4 << 20

# Per-entry bookkeeping overhead charged against the byte budget (tuple +
# bytes objects + list slot).
_ENTRY_OVERHEAD = 120


def _pressure_spill_floor(max_bytes: int) -> int:
    """Smallest chunk worth spilling early under memory pressure: the
    governor's soft watermark forces spills at 1/8th of the budget (never
    below 4 MiB — tiny runs would explode the merge fan-in)."""
    return max(max_bytes // 8, 4 << 20)


class _SpillRun:
    """One sorted run on disk: raw length-prefixed frames, zlib-format
    deflate-1 (native libdeflate when available — the closest analog of the
    reference's zstd-1 spill codec, fgumi-sort/src/codec.rs:7-8).

    Frame payload is a sequence of [<HQI> header (klen, ordinal, rlen) | key |
    record] — keys are the packed memcmp-ordered byte strings of sort/keys.py,
    persisted verbatim so the merge phase never re-extracts or unpickles
    (the reference serializes keys into spill runs the same way, keys.rs:57).
    Frame header: <II> (compressed size, uncompressed size).
    """

    def __init__(self, tmp_dir):
        fd, self.path = tempfile.mkstemp(dir=tmp_dir, suffix=".run")
        self._f = os.fdopen(fd, "wb")

    def write(self, entries):
        frame = bytearray()
        for key, ordinal, data in entries:
            frame += struct.pack("<HQI", len(key), ordinal, len(data))
            frame += key
            frame += data
            if len(frame) >= _FRAME_BYTES:
                self._write_frame(frame)
                frame = bytearray()
        if frame:
            self._write_frame(frame)
        self._f.close()

    def _write_frame(self, frame):
        from ..native import zlib_compress

        payload = zlib_compress(bytes(frame), 1)
        if payload is None:
            payload = zlib.compress(frame, 1)
        self._f.write(struct.pack("<II", len(payload), len(frame)))
        self._f.write(payload)

    def _read_raw_frames(self):
        """(compressed payload, uncompressed size) frames off disk."""
        with open(self.path, "rb") as f:
            while True:
                size_b = f.read(8)
                if len(size_b) < 8:
                    break
                size, usize = struct.unpack("<II", size_b)
                yield f.read(size), usize

    @staticmethod
    def _decode_frame(payload, usize):
        from ..native import zlib_decompress
        from ..observe.metrics import METRICS

        t0 = time.monotonic()
        frame = zlib_decompress(payload, usize)
        if frame is None:
            frame = zlib.decompress(payload)
        # phase-2 merge frame decode latency: the tail of this histogram is
        # what the merge heap stalls on when the prefetch pool falls behind
        METRICS.observe("sort.merge_frame_s", time.monotonic() - t0)
        return frame

    def frames(self, executor=None):
        """Decompressed frame buffers in run order. With ``executor`` the
        NEXT frame's decompression runs on the pool while the caller
        consumes the current one (the phase-2 merge prefetch,
        fgumi-sort/src/worker_pool.rs:25-31 analog) — frame order, and
        hence the k-way merge's heap order, is unchanged."""
        raw = self._read_raw_frames()
        if executor is None:
            for payload, usize in raw:
                yield self._decode_frame(payload, usize)
            return
        pending = None
        for payload, usize in raw:
            fut = executor.submit(self._decode_frame, payload, usize)
            if pending is not None:
                yield pending.result()
            pending = fut
        if pending is not None:
            yield pending.result()

    def entries(self, executor=None):
        """(key, ordinal, record bytes) entries, optionally frame-prefetched."""
        for frame in self.frames(executor):
            off = 0
            end = len(frame)
            while off < end:
                klen, ordinal, rlen = struct.unpack_from("<HQI", frame, off)
                off += 14
                key = frame[off:off + klen]
                off += klen
                yield (key, ordinal, frame[off:off + rlen])
                off += rlen

    def __iter__(self):
        return self.entries()

    def unlink(self):
        try:
            self._f.close()  # a run that died mid-write still holds it open
        except OSError:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass


class ExternalSorter:
    """Accumulate -> sort -> spill -> k-way merge (RawExternalSorter analog).

    `key_fn` must return packed bytes (sort/keys.py); the memory budget is
    byte-based (`max_bytes`, keys + records + bookkeeping), matching the
    reference's byte-accounted RecordBuffer (external.rs Phase 1) rather than
    a record count. Use as a context manager (or call close()) to guarantee
    spill cleanup; the temp directory is created lazily on first spill.
    """

    def __init__(self, key_fn, max_bytes: int = 256 << 20, tmp_dir=None,
                 max_records: int = None, spill_workers: int = 0):
        self.key_fn = key_fn
        self.max_bytes = max_bytes
        self.max_records = max_records  # optional extra cap (tests)
        self._tmp_dir_arg = tmp_dir
        self._tmp_dir = None
        self._own_tmp_dir = False
        self._disk_token = None
        self._chunk = []
        self._chunk_bytes = 0
        self._runs = []
        self.n_records = 0
        # merge-phase frame prefetch pool size (phase 1 spills run inline
        # in this pure-Python engine; the native engine overlaps both)
        self._spill_workers = max(int(spill_workers), 0)

    def add(self, rec: RawRecord):
        self.add_entry(self.key_fn(rec), rec.data)

    def add_entry(self, key: bytes, data: bytes):
        self._chunk.append((key, self.n_records, data))
        self.n_records += 1
        self._chunk_bytes += len(key) + len(data) + _ENTRY_OVERHEAD
        if self._chunk_bytes >= self.max_bytes or (
                self.max_records is not None
                and len(self._chunk) >= self.max_records):
            self._spill()
        elif GOVERNOR.state != "ok" \
                and self._chunk_bytes >= _pressure_spill_floor(self.max_bytes):
            # soft memory pressure: get bytes out of RAM early (hard
            # pressure fails cleanly at the next check site instead)
            GOVERNOR.check_hard()
            self._spill()

    def _spill(self):
        from ..observe.metrics import METRICS
        from ..observe.trace import span
        from ..utils import faults

        if self._tmp_dir is None:
            if self._tmp_dir_arg is not None:
                self._tmp_dir = self._tmp_dir_arg
            else:
                self._tmp_dir = tempfile.mkdtemp(prefix="fgumi_sort_")
                self._own_tmp_dir = True
            self._disk_token = GOVERNOR.watch_path("spill", self._tmp_dir)
        METRICS.inc("sort.spills")
        METRICS.inc("sort.spill_records", len(self._chunk))
        t0 = time.monotonic()
        with span("sort.spill", records=len(self._chunk)):
            self._chunk.sort()
            try:
                faults.fire("sort.spill")
                run = _SpillRun(self._tmp_dir)
                # registered BEFORE write, like the native engine's
                # fixed-at-submission slot: a run that dies mid-write (real
                # ENOSPC) must still be swept by close()
                self._runs.append(run)
                run.write(iter(self._chunk))
            except OSError as e:
                # a full disk mid-spill becomes the clean-failure contract
                # (ResourceExhausted -> exit 4, temps swept by close())
                reraise_enospc(e, "sort.spill", path=self._tmp_dir)
                raise
        METRICS.observe("sort.spill_s", time.monotonic() - t0)
        self._chunk = []
        self._chunk_bytes = 0

    def sorted_records(self):
        """Yield record bytes in sorted order."""
        if not self._runs:
            # in-memory fast path (external.rs single-chunk analog)
            self._chunk.sort()
            for _, _, data in self._chunk:
                yield data
            self._chunk = []
            return
        self._spill()
        # global ingest ordinals make (key, ordinal) a total order, so the merged
        # stream is identical to what a single in-memory sort would produce —
        # with spill workers the next frame of each run decompresses on the
        # pool while the heap consumes the current one (bounded by the
        # governor's merge-prefetch budget; order unchanged)
        n_pf = 0
        if self._spill_workers >= 2 and len(self._runs) > 1:
            from ..utils.governor import merge_prefetch_bytes

            n_pf = min(len(self._runs),
                       int(merge_prefetch_bytes() // _FRAME_BYTES))
        if n_pf:
            from concurrent.futures import ThreadPoolExecutor

            ex = ThreadPoolExecutor(
                max_workers=min(self._spill_workers, n_pf),
                thread_name_prefix="fgumi-merge-pf")
            try:
                streams = [r.entries(ex if i < n_pf else None)
                           for i, r in enumerate(self._runs)]
                for _, _, data in heapq.merge(*streams):
                    yield data
            finally:
                ex.shutdown(wait=True)
            return
        for _, _, data in heapq.merge(*self._runs):
            yield data

    def close(self):
        GOVERNOR.unwatch_path(self._disk_token)
        self._disk_token = None
        for run in self._runs:
            run.unlink()
        self._runs = []
        if self._own_tmp_dir and self._tmp_dir is not None:
            try:
                os.rmdir(self._tmp_dir)
            except OSError:
                pass
            self._tmp_dir = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def merge_sorted(readers, key_fn):
    """K-way merge of already-sorted record streams (fgumi merge, merge.rs:1-8)."""
    streams = (
        ((key_fn(rec), idx, rec.data) for rec in reader)
        for idx, reader in enumerate(readers)
    )
    for _, _, data in heapq.merge(*streams):
        yield data


def merge_keyed_streams(streams):
    """Public stable k-way merge of pre-keyed ``(key, value)`` streams.

    The module's merge machinery (``merge_sorted`` above, the sorters'
    spill-run merges) was only reachable through sorter objects or took
    whole record readers; consumers that already hold ``(key, value)``
    pairs — the scatter/gather stage merging shard manifests by family
    ordinal (serve/scatter.py), future partial-sort consumers — get this
    entry instead of reaching into internals.

    Contract:

    - every input stream must be non-decreasing in ``key`` (keys need
      only be mutually comparable; values are NEVER compared);
    - the merge is **stable**: equal keys yield in stream-index order,
      and within one stream in arrival order — enforced by a per-stream
      sequence number, so unlike a bare ``heapq.merge`` of value tuples
      no tie ever falls through to comparing payloads.

    Yields ``(key, value)`` pairs; lazy over the inputs (streaming k-way
    heap, O(k) open streams)."""
    def decorate(s_idx, stream):
        # bound through arguments, not the enclosing loop: a nested
        # genexp would late-bind s_idx to the LAST stream index and
        # break the stream-order tie rule
        return ((key, s_idx, seq, value)
                for seq, (key, value) in enumerate(stream))

    decorated = [decorate(i, s) for i, s in enumerate(streams)]
    for key, _s, _q, value in heapq.merge(*decorated):
        yield key, value


class NativeExternalSorter:
    """ExternalSorter with native phase internals (VERDICT r2 item 4).

    Same external contract as ExternalSorter, but records/keys accumulate in
    two contiguous byte pools with span tables and the hot phases run in C++
    (fgumi_native.cc sort engine): argsort by (memcmp, ingest order) over
    spans, permutation gather, framed deflate-1 spill runs written natively,
    and a heap k-way merge streaming wire chunks back (the analog of
    radix_sort_record_refs + LoserTree, fgumi-sort/src/inline.rs:1642,
    loser_tree.rs:34). Records are stored block_size-prefixed (BAM wire
    form), so sorted output can go straight to BamWriter.write_serialized.

    `add_batch` appends a whole RecordBatch in two memcpys; `add_entry`
    remains for per-record callers. sorted_records() yields per-record bytes
    (prefix stripped) for compatibility; sorted_wire_chunks() yields large
    concatenated wire blobs and per-record lengths.
    """

    _GATHER_CHUNK = 8 << 20  # target bytes per emitted wire blob

    def __init__(self, key_fn, max_bytes: int = 256 << 20, tmp_dir=None,
                 max_records: int = None, spill_workers: int = 0):
        """spill_workers > 0 overlaps Phase 1: completed pools are sorted,
        compressed, and written by background threads (the native calls
        release the GIL) while the caller keeps ingesting into fresh pools —
        the fixed-role analog of the reference's phase-aware worker pool
        (fgumi-sort/src/worker_pool.rs:1-35,669: DecompressInput >
        ReadInputBlocks > CompressSpill). In-flight spills are bounded by
        the worker count so memory stays ~ (1 + workers) * max_bytes. Tie
        determinism is preserved: each spill is assigned its run slot at
        submission, so the k-way merge sees runs in ingest order no matter
        which worker finishes first. On a single-core host this only
        overlaps I/O waits — wall-clock scaling needs real cores
        (docs/performance-tuning.md)."""
        import numpy as np

        from ..native import get_lib

        self._np = np
        self._lib = get_lib()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self.key_fn = key_fn
        self.max_bytes = max_bytes
        self.max_records = max_records
        self._tmp_dir_arg = tmp_dir
        self._tmp_dir = None
        self._own_tmp_dir = False
        self._disk_token = None
        self._reset_pools()
        self._run_paths = []
        self.n_records = 0
        self._spill_workers = max(int(spill_workers), 0)
        self._executor = None
        self._futures = []

    def _reset_pools(self):
        self._keys = bytearray()
        self._recs = bytearray()
        # span chunks: (koff i64, klen i32, roff i64, rlen i32) absolute
        self._chunks = []
        self._ent_keys = []  # pending per-record add_entry spans
        self._chunk_records = 0
        self._chunk_bytes = 0

    # ------------------------------------------------------------------ add

    def add(self, rec: RawRecord):
        self.add_entry(self.key_fn(rec), rec.data)

    def add_entry(self, key: bytes, data: bytes):
        ko = len(self._keys)
        self._keys += key
        ro = len(self._recs)
        self._recs += struct.pack("<I", len(data))
        self._recs += data
        self._ent_keys.append((ko, len(key), ro, 4 + len(data)))
        self._after_add(1, len(key) + len(data) + 36)

    def add_batch(self, keys_blob, key_off, key_len, wire, rec_off, rec_len):
        """Append a whole batch: key spans from make_batch_keys_fn (blob +
        off/len tables), `wire` the contiguous block_size-prefixed record
        region, rec_off/rec_len spans relative to `wire`."""
        np = self._np
        base_k = len(self._keys)
        self._keys += keys_blob
        base_r = len(self._recs)
        # memoryview: numpy slices append through the buffer protocol (a
        # plain += would dispatch to ndarray broadcasting)
        self._recs += memoryview(wire)
        koff = key_off.astype(np.int64) + base_k
        klen = np.asarray(key_len, dtype=np.int32)
        roff = rec_off.astype(np.int64) + base_r
        rlen = np.asarray(rec_len, dtype=np.int32)
        self._chunks.append((koff, klen, roff, rlen))
        n = len(klen)
        self._after_add(n, len(keys_blob) + len(wire) + 32 * n)

    def add_record_batch(self, batch, batch_keys_fn):
        """Append one decoded RecordBatch: native key extraction + two pool
        memcpys (the whole-batch fast path for cmd_sort)."""
        if batch.n == 0:
            return
        blob, koff, klen = batch_keys_fn(batch)
        base = int(batch.rec_off[0])
        wire = batch.buf[base:int(batch.data_end[-1])]
        self.add_batch(blob, koff, klen, wire,
                       batch.rec_off - base,
                       (batch.data_end - batch.rec_off))

    def ingest_batches(self, batches, batch_keys_fn, on_batch=None):
        """Phase-1 ingest from any RecordBatch iterable — a file reader or a
        fused-chain channel (``pipeline_chain.ChannelBatchReader``).

        Batches are keyed and pooled as they arrive, so with spill workers
        the sort/compress/write of completed pools overlaps the *producer*
        (in the fused chain: extract emits while sort spills — the sort
        merge is the chain's natural barrier). ``on_batch(n)`` fires per
        batch for progress reporting."""
        for b in batches:
            self.add_record_batch(b, batch_keys_fn)
            if on_batch is not None:
                on_batch(b.n)

    def _after_add(self, n: int, nbytes: int):
        self.n_records += n
        self._chunk_records += n
        self._chunk_bytes += nbytes
        if self._chunk_bytes >= self.max_bytes or (
                self.max_records is not None
                and self._chunk_records >= self.max_records):
            self._spill()
        elif GOVERNOR.state != "ok" \
                and self._chunk_bytes >= _pressure_spill_floor(self.max_bytes):
            # soft watermark: spill early so accumulated pools stop
            # competing with the rest of the process for RAM
            GOVERNOR.check_hard()
            self._spill()

    # ---------------------------------------------------------------- phases

    def _spans(self):
        """Concatenated span arrays for the current pools."""
        np = self._np
        chunks = list(self._chunks)
        if self._ent_keys:
            arr = np.asarray(self._ent_keys, dtype=np.int64)
            chunks.append((arr[:, 0], arr[:, 1].astype(np.int32),
                           arr[:, 2], arr[:, 3].astype(np.int32)))
        if not chunks:
            z64 = np.zeros(0, np.int64)
            z32 = np.zeros(0, np.int32)
            return z64, z32, z64, z32
        koff = np.ascontiguousarray(np.concatenate([c[0] for c in chunks]))
        klen = np.ascontiguousarray(np.concatenate([c[1] for c in chunks]))
        roff = np.ascontiguousarray(np.concatenate([c[2] for c in chunks]))
        rlen = np.ascontiguousarray(np.concatenate([c[3] for c in chunks]))
        return koff, klen, roff, rlen

    def _sort_perm(self, koff, klen):
        np = self._np
        n = len(klen)
        perm = np.empty(n, dtype=np.int64)
        keys = np.frombuffer(self._keys, dtype=np.uint8)
        self._lib.fgumi_sort_spans(keys.ctypes.data, koff.ctypes.data,
                                   klen.ctypes.data, n, perm.ctypes.data)
        return perm

    def _ensure_tmp_dir(self):
        if self._tmp_dir is None:
            if self._tmp_dir_arg is not None:
                self._tmp_dir = self._tmp_dir_arg
            else:
                self._tmp_dir = tempfile.mkdtemp(prefix="fgumi_sort_")
                self._own_tmp_dir = True
            self._disk_token = GOVERNOR.watch_path("spill", self._tmp_dir)

    def _build_run(self, path, keys_b, recs_b, spans):
        """Sort + compress + write one frozen pool to `path` (runs on a
        spill worker or inline; touches no mutable sorter state)."""
        from ..observe.metrics import METRICS
        from ..observe.trace import span
        from ..utils import faults

        n = len(spans[1])
        METRICS.inc("sort.spills")
        METRICS.inc("sort.spill_records", n)
        t0 = time.monotonic()
        with span("sort.spill", records=n):
            try:
                faults.fire("sort.spill")
                out = self._build_run_traced(path, keys_b, recs_b, spans, n)
            except OSError as e:
                reraise_enospc(e, "sort.spill", path=self._tmp_dir)
                raise
        METRICS.observe("sort.spill_s", time.monotonic() - t0)
        return out

    def _build_run_traced(self, path, keys_b, recs_b, spans, n):
        np = self._np
        koff, klen, roff, rlen = spans
        perm = np.empty(n, dtype=np.int64)
        keys = np.frombuffer(keys_b, dtype=np.uint8)
        recs = np.frombuffer(recs_b, dtype=np.uint8)
        self._lib.fgumi_sort_spans(keys.ctypes.data, koff.ctypes.data,
                                   klen.ctypes.data, n, perm.ctypes.data)
        rc = self._lib.fgumi_write_run(
            path.encode(), keys.ctypes.data, koff.ctypes.data,
            klen.ctypes.data, recs.ctypes.data, roff.ctypes.data,
            rlen.ctypes.data, perm.ctypes.data, n, _FRAME_BYTES, 1)
        if rc != 0:
            # the native writer reports -errno for I/O failures (so a full
            # disk maps onto the ENOSPC clean-failure contract); any other
            # negative value is a compression/internal failure
            err = -int(rc)
            if 0 < err < 256:
                raise OSError(err, f"native spill write failed: "
                              f"{os.strerror(err)}", path)
            raise OSError(f"native spill write failed: {path}")

    def _spill(self):
        if self._chunk_records == 0:
            return
        self._ensure_tmp_dir()
        spans = self._spans()
        keys_b, recs_b = self._keys, self._recs
        fd, path = tempfile.mkstemp(dir=self._tmp_dir, suffix=".run")
        os.close(fd)
        self._run_paths.append(path)  # slot fixed at submission: ingest-order
        self._reset_pools()
        if self._spill_workers:
            from concurrent.futures import ThreadPoolExecutor

            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    max_workers=self._spill_workers,
                    thread_name_prefix="fgumi-spill")
            # bound in-flight pools: wait for the oldest when every worker
            # is busy (memory ~ (1 + workers) * max_bytes)
            while len(self._futures) >= self._spill_workers:
                self._futures.pop(0).result()
            self._futures.append(self._executor.submit(
                self._build_run, path, keys_b, recs_b, spans))
        else:
            self._build_run(path, keys_b, recs_b, spans)

    def _drain_spills(self):
        """Complete every in-flight spill (first exception wins)."""
        while self._futures:
            self._futures.pop(0).result()

    def _chunked(self, with_lens, as_bytes=True):
        """Yield sorted output as (wire blob, rec_lens|None) chunks.

        ``as_bytes=False`` yields writable uint8 arrays instead of bytes:
        the in-memory path hands over its freshly gathered buffer with no
        extra copy, the merge path copies out of its reused read buffer
        (same cost as the ``tobytes`` it replaces). The fused chain uses
        this so downstream batches can mutate records in place."""
        np = self._np
        if not self._run_paths:
            koff, klen, roff, rlen = self._spans()
            perm = self._sort_perm(koff, klen)
            recs = np.frombuffer(self._recs, dtype=np.uint8)
            lens_sorted = rlen[perm]
            n = len(perm)
            # chunk boundaries in one vectorized pass: first index where the
            # cumulative size clears each successive _GATHER_CHUNK multiple
            csum = np.cumsum(lens_sorted, dtype=np.int64)
            total_bytes = int(csum[-1]) if n else 0
            targets = np.arange(self._GATHER_CHUNK, total_bytes,
                                self._GATHER_CHUNK, dtype=np.int64)
            bounds = np.concatenate((
                [0], np.searchsorted(csum, targets, side="left") + 1, [n]))
            bounds = np.unique(bounds)
            for i, j in zip(bounds[:-1], bounds[1:]):
                i, j = int(i), int(j)
                out = np.empty(int(csum[j - 1] - (csum[i - 1] if i else 0)),
                               dtype=np.uint8)
                self._lib.fgumi_gather_spans(
                    recs.ctypes.data, roff.ctypes.data, rlen.ctypes.data,
                    perm[i:j].ctypes.data, j - i, out.ctypes.data)
                yield ((out.tobytes() if as_bytes else out),
                       (lens_sorted[i:j] if with_lens else None))
            self._reset_pools()
            return
        self._spill()
        self._drain_spills()
        import ctypes as ct

        paths = b"\n".join(p.encode() for p in self._run_paths)
        # phase-2 merge prefetch: the spill-worker pool's thread count now
        # reads+decompresses each run's next frame while the heap drains
        # the current one (worker_pool.rs:25-31 analog), holding at most
        # merge-prefetch-budget / frame-size decoded frames beyond the
        # per-run current ones. Deterministic: heap order is untouched.
        # >= 2 workers: with one, the merge thread steals most frames back
        # and pays pure coordination (measured ~0.8x; >=2 measured ~1.2x
        # on 2 cores, more with real core counts)
        pf_threads = pf_frames = 0
        if self._spill_workers >= 2 and len(self._run_paths) > 1:
            from ..utils.governor import merge_prefetch_bytes

            pf_frames = int(merge_prefetch_bytes() // _FRAME_BYTES)
            pf_threads = min(self._spill_workers, len(self._run_paths))
        if pf_frames > 0:
            h = self._lib.fgumi_merge_open2(paths, len(paths),
                                            len(self._run_paths),
                                            pf_threads, pf_frames)
        else:
            h = self._lib.fgumi_merge_open(paths, len(paths),
                                           len(self._run_paths))
        if not h:
            raise OSError("native merge open failed")
        try:
            cap = self._GATHER_CHUNK
            max_recs = max(cap // 64, 1024)
            out = np.empty(cap, dtype=np.uint8)
            lens = np.empty(max_recs, dtype=np.int32)
            n_out = ct.c_long(0)
            while True:
                n_bytes = self._lib.fgumi_merge_next(
                    h, out.ctypes.data, cap, lens.ctypes.data, max_recs,
                    ct.byref(n_out))
                if n_bytes < 0:
                    raise OSError("corrupt spill run during merge")
                if n_bytes == 0:
                    break
                yield ((out[:n_bytes].tobytes() if as_bytes
                        else out[:n_bytes].copy()),
                       (lens[:n_out.value].copy() if with_lens else None))
        finally:
            self._lib.fgumi_merge_close(h)

    def sorted_wire_chunks(self):
        """Yield large blobs of block_size-prefixed records in sorted order
        (feed straight to BamWriter.write_serialized)."""
        for blob, _ in self._chunked(with_lens=False):
            yield blob

    def iter_sorted_wire(self):
        """Sorted wire chunks as WRITABLE uint8 arrays (the fused-chain
        output path: downstream RecordBatches mutate seq/qual in place, and
        the in-memory sort path hands its buffers over with no copy)."""
        for arr, _ in self._chunked(with_lens=False, as_bytes=False):
            yield arr

    def sorted_chunks_with_lens(self):
        """(wire blob, int32 per-record wire lengths) chunks in sorted order
        (the BAI path needs record boundaries for virtual offsets)."""
        return self._chunked(with_lens=True)

    def sorted_records(self):
        """Per-record bytes (no block_size prefix) in sorted order."""
        for blob, lens in self._chunked(with_lens=True):
            off = 0
            for ln in lens:
                yield blob[off + 4:off + int(ln)]
                off += int(ln)

    def close(self):
        GOVERNOR.unwatch_path(self._disk_token)
        self._disk_token = None
        try:
            self._drain_spills()
        except Exception:  # noqa: BLE001 - close() must still clean up
            pass
        if self._executor is not None:
            self._executor.shutdown(wait=True)
            self._executor = None
        for path in self._run_paths:
            try:
                os.unlink(path)
            except OSError:
                pass
        self._run_paths = []
        if self._own_tmp_dir and self._tmp_dir is not None:
            try:
                os.rmdir(self._tmp_dir)
            except OSError:
                pass
            self._tmp_dir = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def create_sorter(key_fn, max_bytes: int = 256 << 20, tmp_dir=None,
                  max_records: int = None, spill_workers: int = 0):
    """NativeExternalSorter when the native library is available, else the
    pure-Python ExternalSorter (identical output contract; tested against
    each other in tests/test_sort_v2.py). spill_workers overlaps Phase-1
    spills (native engine) and Phase-2 merge frame prefetch (both)."""
    from ..native import get_lib

    if get_lib() is not None:
        return NativeExternalSorter(key_fn, max_bytes=max_bytes,
                                    tmp_dir=tmp_dir, max_records=max_records,
                                    spill_workers=spill_workers)
    return ExternalSorter(key_fn, max_bytes=max_bytes, tmp_dir=tmp_dir,
                          max_records=max_records,
                          spill_workers=spill_workers)
