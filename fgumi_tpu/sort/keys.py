"""Packed binary sort keys: memcmp order == semantic order.

The reference packs sort keys into fixed-width integers so an unstable radix
sort becomes total (/root/reference/crates/fgumi-sort/src/keys.rs, radix.rs:35).
Python's analog of that discipline is byte-string keys whose lexicographic
(memcmp) comparison reproduces the tuple-key semantics of sort/external.py:
bytes compare in C, spill frames carry the key verbatim (no pickling), and the
merge phase never re-extracts.

Encodings (all big-endian so memcmp == numeric):
- coordinate: tid(4) pos+1(4); unmapped tid -> 0x80000000 (above any real
  int32 tid, sorts last; matches external._UNMAPPED_SENTINEL).
- natural queryname: per element, digit runs as 0x01 + u8 digit-count +
  stripped digits (fewer digits = smaller number; same count compares
  lexicographically == numerically), text runs as 0x02 + text + 0x00; a name
  that is a prefix of another terminates first and sorts first (tags > 0x00).
- lexicographic queryname: raw name + 0x00 terminator (QNAME has no NUL).
- template-coordinate: tid1(4) tid2(4) pos1(4) pos2(4) !neg1 !neg2 lib(2)
  mi-value(8) mi-sub name-natural 0x00 is_upper — the TemplateKey field order
  (fgumi-sort/src/inline.rs:620-694).
"""

import re
import struct

from ..core.overlap import parse_soft_clips_and_ref_len
from ..core.template import unclipped_5prime
from ..io.bam import (FLAG_FIRST, FLAG_MATE_REVERSE, FLAG_MATE_UNMAPPED,
                      FLAG_PAIRED, FLAG_REVERSE, FLAG_SECONDARY,
                      FLAG_SUPPLEMENTARY, FLAG_UNMAPPED, RawRecord)

_DIGITS = re.compile(rb"(\d+)")

# Bias keeping template-coordinate positions non-negative in u32: unclipped
# starts can go below zero on heavily clipped leading alignments.
_POS_BIAS = 0x4000_0000
# above any real reference id (tids are int32 < 2^31); matches
# external._UNMAPPED_SENTINEL so packed and tuple keys order identically
_TID_UNMAPPED = 1 << 31
_POS_SENTINEL = 0x7FFF_FFFF


def coordinate_key_bytes(rec: RawRecord) -> bytes:
    """samtools coordinate order: mapped by (tid, pos), unmapped (tid<0) last."""
    tid = rec.ref_id
    return struct.pack(">II", _TID_UNMAPPED if tid < 0 else tid, rec.pos + 1)


def encode_natural_name(name: bytes) -> bytes:
    """Byte-comparable natural (digit-aware) name encoding."""
    out = bytearray()
    for part in _DIGITS.split(name):
        if not part:
            continue
        if part.isdigit():
            sig = part.lstrip(b"0")
            out += b"\x01" + bytes([len(sig)]) + sig
        else:
            out += b"\x02" + part + b"\x00"
    return bytes(out)


def _rank_bytes(flag: int) -> bytes:
    """Sub-order within one template: primaries first, R1 before R2, then flag."""
    sec = 1 if flag & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY) else 0
    r12 = 0 if not flag & FLAG_PAIRED else (1 if flag & FLAG_FIRST else 2)
    return struct.pack(">BBH", sec, r12, flag)


def queryname_key_bytes(rec: RawRecord, lexicographic: bool = False) -> bytes:
    name = rec.name
    enc = (name + b"\x00") if lexicographic else (encode_natural_name(name)
                                                 + b"\x00")
    return enc + _rank_bytes(rec.flag)


def _own_end(rec: RawRecord, flag: int):
    if flag & FLAG_UNMAPPED:
        return (_TID_UNMAPPED, _POS_SENTINEL, False)
    return (rec.ref_id, unclipped_5prime(rec) + 1, bool(flag & FLAG_REVERSE))


def _mate_end(rec: RawRecord, flag: int):
    if not flag & FLAG_PAIRED or flag & FLAG_MATE_UNMAPPED \
            or rec.next_ref_id < 0:
        return (_TID_UNMAPPED, _POS_SENTINEL, False)
    mate_rev = bool(flag & FLAG_MATE_REVERSE)
    mate_pos = rec.next_pos + 1  # 1-based
    mc = rec.get_str(b"MC")
    leading = ref_len = trailing = 0
    if mc is not None:
        parsed = parse_soft_clips_and_ref_len(mc)
        if parsed is not None:
            leading, ref_len, trailing = parsed
    if mate_rev:
        pos = mate_pos - 1 + max(ref_len, 1) - 1 + trailing + 1
    else:
        pos = mate_pos - leading
    return (rec.next_ref_id, pos, mate_rev)


def template_coordinate_key_bytes(rec: RawRecord, library_ord: int,
                                  mi: tuple) -> bytes:
    """TemplateKey analog: earlier end first; reverse strand sorts before
    forward (inverted flag); the lower-end record sorts before its mate."""
    flag = rec.flag
    own = _own_end(rec, flag)
    mate = _mate_end(rec, flag)
    if own <= mate:
        lo, hi, is_upper = own, mate, 0
    else:
        lo, hi, is_upper = mate, own, 1
    tid1, pos1, neg1 = lo
    tid2, pos2, neg2 = hi
    return (struct.pack(">IIII", tid1, tid2, pos1 + _POS_BIAS,
                        pos2 + _POS_BIAS)
            + bytes([0 if neg1 else 1, 0 if neg2 else 1])
            + struct.pack(">HQB", library_ord,
                          max(0, min(mi[0], 0xFFFF_FFFF_FFFF_FFFF)), mi[1])
            # raw name bytes: template-coordinate name order only needs to be
            # deterministic grouping (the reference hashes names here,
            # inline.rs TemplateKey name_hash_upper)
            + rec.name + b"\x00" + bytes([is_upper]))


def make_batch_keys_fn(order: str, header, subsort: str = "natural"):
    """Whole-RecordBatch packed-key extraction: fn(batch) -> (blob, off, len).

    The native analog of make_key_bytes_fn: key semantics are identical
    byte-for-byte (tested in tests/test_sort_v2.py), but extraction runs one
    native pass per batch instead of Python per record, and the keys stay in
    one blob with int64 offset / int32 length span tables — record i's key
    is blob[off[i]:off[i]+len[i]] (spans may carry allocation gaps) — so the
    native sorter ingests them without materializing per-record bytes
    objects. Returns None when the native layer is unavailable (callers
    fall back to the per-record path).
    """
    import numpy as np

    from ..native import batch as nb

    if not nb.available():
        return None

    if order == "coordinate":

        def coord_keys(batch):
            arr = np.empty((batch.n, 2), dtype=">u4")
            tid = batch.ref_id.astype(np.int64)
            arr[:, 0] = np.where(tid < 0, _TID_UNMAPPED, tid)
            arr[:, 1] = batch.pos.astype(np.int64) + 1
            off = np.arange(batch.n, dtype=np.int64) * 8
            return arr.tobytes(), off, np.full(batch.n, 8, dtype=np.int32)

        return coord_keys

    if order == "queryname":
        if subsort == "lex":

            def lex_keys(batch):
                buf = batch.buf
                name_off = batch.data_off + 32
                name_len = batch.l_read_name - 1
                parts = [
                    buf[name_off[i]:name_off[i] + name_len[i]].tobytes()
                    + b"\x00" + _rank_bytes(int(batch.flag[i]))
                    for i in range(batch.n)]
                lens = np.array([len(p) for p in parts], dtype=np.int32)
                off = np.zeros(batch.n, dtype=np.int64)
                np.cumsum(lens[:-1], out=off[1:])
                return b"".join(parts), off, lens

            return lex_keys

        def natural_keys(batch):
            out, out_off, out_len = nb.natural_name_keys(batch)
            return out.tobytes(), out_off, out_len

        return natural_keys

    if order == "template-coordinate":
        from .external import SortContext

        ctx = SortContext(header)
        unknown_ord = ctx._lib_ord["unknown"]

        def tc_keys(batch):
            # one fused aux scan for everything this key fn + the native
            # key extractor read
            batch.prefetch_tags([b"RG", b"MC", b"MI"])
            # vectorized RG -> library ordinal: resolve each distinct RG
            # value once (hash-deduplicated, byte-verified)
            rg_off, rg_len, _ = batch.tag_locs_str(b"RG")
            lib_ord = np.full(batch.n, unknown_ord, dtype=np.int32)
            present = rg_off >= 0
            if present.any():
                hashes = nb.hash_ranges(batch.buf, rg_off, rg_len)
                uniq, first_idx, inv = np.unique(
                    hashes, return_index=True, return_inverse=True)
                # hash-collision guard: every row must byte-match its
                # representative, else fall back to exact per-record lookup
                reps = first_idx[inv]
                eq = nb.ranges_equal(batch.buf, rg_off, rg_len, rg_off[reps],
                                     rg_len[reps])
                if eq[present].all():
                    ords = np.empty(len(uniq), dtype=np.int32)
                    for u, fi in enumerate(first_idx):
                        if rg_off[fi] < 0:
                            ords[u] = unknown_ord
                            continue
                        rg = batch.buf[rg_off[fi]:rg_off[fi] + rg_len[fi]] \
                            .tobytes().decode(errors="replace")
                        ords[u] = ctx._rg_to_ord.get(rg, unknown_ord)
                    lib_ord = ords[inv]
                    lib_ord[~present] = unknown_ord
                else:  # astronomically rare: exact per-record resolution
                    for i in np.nonzero(present)[0]:
                        rg = batch.buf[rg_off[i]:rg_off[i] + rg_len[i]] \
                            .tobytes().decode(errors="replace")
                        lib_ord[i] = ctx._rg_to_ord.get(rg, unknown_ord)
            out, out_off = nb.template_coord_keys(batch, lib_ord)
            return (out.tobytes(), out_off[:-1],
                    np.diff(out_off).astype(np.int32))

        return tc_keys

    raise ValueError(f"unknown sort order: {order}")


def iter_keyed_records(path_or_obj, batch_keys_fn, on_batch=None):
    """(packed key bytes, record wire bytes) per record, batch-extracted.

    The per-record consumer loop for the k-way merge and the pure-Python
    sorter fallback; `on_batch(n)` fires once per decoded batch (progress
    reporting). The native sorter bypasses this via add_record_batch.
    """
    from ..io.batch_reader import BamBatchReader

    with BamBatchReader(path_or_obj) as br:
        for batch in br:
            blob, koff, klen = batch_keys_fn(batch)
            buf = batch.buf
            do, de = batch.data_off, batch.data_end
            if on_batch is not None:
                on_batch(batch.n)
            for i in range(batch.n):
                yield blob[koff[i]:koff[i] + klen[i]], bytes(buf[do[i]:de[i]])


def make_key_bytes_fn(order: str, header, subsort: str = "natural"):
    """Packed-key function for coordinate|queryname|template-coordinate."""
    from .external import SortContext, _mi_key

    if order == "coordinate":
        return coordinate_key_bytes
    if order == "queryname":
        lex = subsort == "lex"
        return lambda rec: queryname_key_bytes(rec, lexicographic=lex)
    if order == "template-coordinate":
        ctx = SortContext(header)
        return lambda rec: template_coordinate_key_bytes(
            rec, ctx.library_ordinal(rec), _mi_key(rec))
    raise ValueError(f"unknown sort order: {order}")
