"""Optional XLA device profile capture (``--xla-profile DIR``).

``fgumi-tpu --xla-profile DIR <command>`` (or
``FGUMI_TPU_XLA_PROFILE=DIR``) arms a one-shot ``jax.profiler`` trace
around the Nth device dispatch (``FGUMI_TPU_XLA_PROFILE_NTH``, default 1
— the first dispatch carries the XLA compile, so profiling a warm
dispatch usually wants N=2). The capture lands in DIR in TensorBoard /
``xprof`` format and the run report records the directory
(``xla_profile_dir``), so a perf investigation can jump from "this run's
device time regressed" straight to the XLA op-level timeline.

Deliberately one-shot: a per-dispatch profile of a million-dispatch run
would be gigabytes of xplane protos and a constant host tax. Zero
overhead when off: the kernel's dispatch path checks one module flag.
All failures are soft — a missing/old profiler API or an unwritable DIR
logs a warning and disarms; it never fails the dispatch.
"""

import logging
import threading

log = logging.getLogger("fgumi_tpu")

_lock = threading.Lock()
_dir = None          # capture target; None = feature off
_nth = 1             # which dispatch to profile (1-based)
_seen = 0            # dispatches observed so far
_active = False      # a jax.profiler trace is running
_captured = None     # DIR once a capture completed (also: re-arm guard)


def configure(profile_dir: str, nth: int = 1):
    """Arm capture of the ``nth`` dispatch into ``profile_dir`` (CLI
    entry, once per command). None disarms."""
    global _dir, _nth, _seen, _active, _captured
    with _lock:
        _dir = profile_dir or None
        _nth = max(int(nth), 1)
        _seen = 0
        _active = False
        _captured = None


def armed() -> bool:
    """Cheap gate for the dispatch hot path (no lock: a stale read costs
    one extra function call, never a wrong capture)."""
    return _dir is not None and _captured is None


def on_dispatch_begin():
    """Called as a device dispatch is submitted; starts the profiler when
    this is the Nth one."""
    global _seen, _active, _dir
    with _lock:
        if _dir is None or _captured is not None or _active:
            return
        _seen += 1
        if _seen != _nth:
            return
        profile_dir = _dir
        try:
            import jax

            jax.profiler.start_trace(profile_dir)
        except Exception as e:  # noqa: BLE001 - profiling is best-effort
            log.warning("xla-profile: cannot start device trace in %s: %s",
                        profile_dir, e)
            _dir = None
            return
        _active = True
        log.info("xla-profile: capturing dispatch %d into %s", _seen,
                 profile_dir)


def on_dispatch_end():
    """Called after a dispatch's result was fetched; stops a running
    capture (the profile then spans upload + compute + fetch)."""
    global _active, _captured, _dir
    with _lock:
        if not _active:
            return
        profile_dir = _dir
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            log.warning("xla-profile: stop_trace failed: %s", e)
            _active = False
            _dir = None
            return
        _active = False
        _captured = profile_dir
        log.info("xla-profile: device profile written to %s", profile_dir)


def captured_dir():
    """The completed capture's directory (run-report rider), or None."""
    return _captured
