"""Periodic one-line progress heartbeat on the standard log stream.

The stall watchdog (:class:`fgumi_tpu.pipeline._Watchdog`) only speaks when
nothing moves; operators of long runs also want the inverse — a regular
"still alive, here's where I am" line. The heartbeat folds the watchdog's
view (pipeline counters, queue depths) together with device activity and
record totals into one INFO line every ``interval`` seconds::

    heartbeat: +120s read=48 processed=47 written=45 q_in=2/4 q_out=1/8 \
device(dispatches=47 in-flight=1 retries=0) records=4700000 rss=812MB

Components publish live state by registering a gauge callable returning a
``{label: value}`` dict (:func:`register_gauge`); run_stages registers its
counters/queues for the duration of the pipeline and unregisters in its
``finally``. Enabled by ``--heartbeat SECONDS`` / ``FGUMI_TPU_HEARTBEAT_S``;
off (0) by default — no thread starts.
"""

import logging
import threading
import time

log = logging.getLogger("fgumi_tpu")

_lock = threading.Lock()
_gauges = {}  # token -> callable() -> {label: value}
_next_token = [0]
#: expected total records for the ETA column (None = unknown). Set by
#: whoever knows the workload size upfront (simulate's generators,
#: ProgressTracker(total=...)); the beat divides remaining by the
#: records/s EWMA.
_goal = [None]

#: EWMA smoothing for the records/s estimate (per beat).
RATE_ALPHA = 0.3

#: gauge keys treated as "records so far", best first (the most-downstream
#: counter is the honest progress number).
_RECORD_KEYS = ("written", "processed", "read", "records")


def register_gauge(fn):
    """Register a live-state callable; returns a token for unregister."""
    with _lock:
        _next_token[0] += 1
        token = _next_token[0]
        _gauges[token] = fn
    return token


def unregister_gauge(token):
    with _lock:
        _gauges.pop(token, None)


def set_goal(total_records, token, gauge_token=None):
    """Declare the expected record total so beats can print an ETA.

    First claimant wins: ``token`` (any hashable owner id) must clear the
    goal before another can arm one — two concurrent goal-declaring
    commands in one process (serve daemon workers) would otherwise
    clobber each other into nonsense ETAs. ``gauge_token`` names the
    owner's OWN record gauge (register_gauge return value): the ETA is
    computed against that gauge only, never against whatever unrelated
    counter another concurrent command happens to publish. Returns True
    when armed."""
    if not total_records:
        return False
    with _lock:
        if _goal[0] is not None and _goal[0][0] != token:
            return False
        _goal[0] = (token, int(total_records), gauge_token)
        return True


def clear_goal(token):
    with _lock:
        if _goal[0] is not None and _goal[0][0] == token:
            _goal[0] = None


def _goal_info():
    """``(total, gauge_token)`` of the armed goal, or ``(None, None)``."""
    with _lock:
        if _goal[0] is None:
            return None, None
        return _goal[0][1], _goal[0][2]


def _goal_total():
    return _goal_info()[0]


def _gauge_states() -> list:
    """Live ``(token, {label: value})`` state of every registered gauge,
    registration order. A list, not a merged dict: concurrent gauges
    (fused-pipeline stages) publish identical keys, and last-wins merging
    would hide all but one stage's progress."""
    with _lock:
        fns = list(_gauges.items())
    out = []
    for token, fn in fns:
        try:
            out.append((token, fn()))
        except Exception:  # noqa: BLE001 - a gauge must never kill the beat
            continue
    return out


def _records_from(states: list):
    """``(token, value)`` of the record counter to pace the rate EWMA by:
    the FIRST-registered gauge exposing a record key, most-downstream key
    first. Pinning to one stable gauge (not a merged view) keeps the EWMA
    from flipping between unrelated stage counters mid-run."""
    for token, state in states:
        for key in _RECORD_KEYS:
            v = state.get(key)
            if isinstance(v, int) and not isinstance(v, bool):
                return token, v
    return None, None


def _rss_mb():
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    return int(line.split()[1]) // 1024
    except (OSError, ValueError, IndexError):
        pass
    return None


class Heartbeat:
    """Daemon timer logging one progress line every ``interval`` seconds."""

    def __init__(self, interval: float):
        self.interval = interval
        self._t0 = time.monotonic()
        self._stop = threading.Event()
        self._t = None
        # records/s EWMA state (fed per beat from ONE record gauge,
        # re-baselined whenever the source gauge changes)
        self.rate_ewma = None
        self._last_records = None
        self._last_records_t = None
        self._rate_source = None
        self.last_eta_s = None
        if interval > 0:
            # carry the caller's telemetry scope so the beat reads the
            # owning command's DeviceStats, not the process-global fallback
            from .scope import spawn_thread

            self._t = spawn_thread(self._loop, name="fgumi-heartbeat")
            self._t.start()

    def _loop(self):
        while not self._stop.wait(self.interval):
            self.beat()

    def _update_rate(self, states: list):
        """Advance the records/s EWMA from this beat's record gauge;
        returns (rate, eta_s) — either may be None."""
        goal, goal_gauge = _goal_info()
        if goal_gauge is not None:
            # a goal owner paces BOTH the rate and the ETA by its own
            # gauge; an unrelated concurrent command's counter must not
            # cross-contaminate either number
            own = [(t, s) for t, s in states if t == goal_gauge]
            source, records = _records_from(own)
            if records is None:
                goal = None  # owner's gauge gone: no ETA this beat
        else:
            source, records = _records_from(states)
        now = time.monotonic()
        if records is not None:
            if source != self._rate_source:
                # the pacing gauge changed (a stage finished, another
                # registered): re-baseline instead of computing a bogus
                # delta across unrelated counters
                self._rate_source = source
                self._last_records = None
            if self._last_records is not None:
                dt = now - self._last_records_t
                if dt > 0:
                    inst = max(records - self._last_records, 0) / dt
                    self.rate_ewma = inst if self.rate_ewma is None else \
                        (1.0 - RATE_ALPHA) * self.rate_ewma \
                        + RATE_ALPHA * inst
            self._last_records = records
            self._last_records_t = now
        eta = None
        if goal and records is not None and self.rate_ewma:
            eta = max(goal - records, 0) / self.rate_ewma
            self.last_eta_s = eta
        return self.rate_ewma, eta

    def beat(self):
        """Log one heartbeat line (also callable directly from tests)."""
        from .metrics import METRICS
        from .report import _device_stats

        parts = [f"heartbeat: +{time.monotonic() - self._t0:.0f}s"]
        states = _gauge_states()
        for _token, state in states:
            if state:
                parts.append(" ".join(f"{k}={v}" for k, v in state.items()))
        rate, eta = self._update_rate(states)
        if rate is not None:
            parts.append(f"rate={rate:.0f}/s")
        if eta is not None:
            parts.append(f"eta={eta:.0f}s")
        stats = _device_stats()  # None while ops.kernel is unimported
        snap = stats.snapshot() if stats is not None else {}
        if snap.get("dispatches"):
            parts.append(
                f"device(dispatches={snap['dispatches']}"
                f" in-flight={stats.in_flight_count()}"
                f" retries={snap.get('dispatch_retries', 0)}"
                f" host-fallbacks={snap.get('host_fallbacks', 0)})")
        # live accelerator memory (None on CPU backends): logged AND kept
        # as gauges so the run report / stats op / scrape carry the same
        # figures the heartbeat printed
        from .flight import device_memory_snapshot

        mem = device_memory_snapshot()
        if mem is not None:
            METRICS.set("device.memory.bytes_in_use", mem["bytes_in_use"])
            METRICS.set("device.memory.peak_bytes", mem["peak_bytes"])
            parts.append(f"devmem={mem['bytes_in_use'] / 1e6:.0f}MB"
                         f"(peak {mem['peak_bytes'] / 1e6:.0f}MB)")
        # tail visibility: the p99 dispatch wall straight from the latency
        # histogram (the counter above says how MUCH, this says how SLOW)
        wall = METRICS.histogram("device.dispatch.wall_s")
        if wall is not None and wall.count:
            parts.append(f"p99-dispatch={wall.quantile(0.99) * 1e3:.0f}ms")
        rss = _rss_mb()
        if rss is not None:
            parts.append(f"rss={rss}MB")
        # resource pressure (utils/governor.py): only worth a column when
        # the run is actually degrading
        import sys

        gov = sys.modules.get("fgumi_tpu.utils.governor")
        if gov is not None and gov.GOVERNOR.state != "ok":
            parts.append(f"pressure={gov.GOVERNOR.state}")
        log.info(" ".join(parts))

    def stop(self):
        """Stop AND join (same discipline as the watchdog: a finished
        command must not leave a daemon timer logging behind it). The
        final rate/ETA estimates fold into the run report's metrics."""
        self._stop.set()
        if self._t is not None:
            self._t.join(timeout=5)
            self._t = None
        if self.rate_ewma is not None:
            from .metrics import METRICS

            METRICS.set("heartbeat.records_per_s", round(self.rate_ewma, 3))
            if self.last_eta_s is not None:
                METRICS.set("heartbeat.last_eta_s",
                            round(self.last_eta_s, 1))
