"""Periodic one-line progress heartbeat on the standard log stream.

The stall watchdog (:class:`fgumi_tpu.pipeline._Watchdog`) only speaks when
nothing moves; operators of long runs also want the inverse — a regular
"still alive, here's where I am" line. The heartbeat folds the watchdog's
view (pipeline counters, queue depths) together with device activity and
record totals into one INFO line every ``interval`` seconds::

    heartbeat: +120s read=48 processed=47 written=45 q_in=2/4 q_out=1/8 \
device(dispatches=47 in-flight=1 retries=0) records=4700000 rss=812MB

Components publish live state by registering a gauge callable returning a
``{label: value}`` dict (:func:`register_gauge`); run_stages registers its
counters/queues for the duration of the pipeline and unregisters in its
``finally``. Enabled by ``--heartbeat SECONDS`` / ``FGUMI_TPU_HEARTBEAT_S``;
off (0) by default — no thread starts.
"""

import logging
import threading
import time

log = logging.getLogger("fgumi_tpu")

_lock = threading.Lock()
_gauges = {}  # token -> callable() -> {label: value}
_next_token = [0]


def register_gauge(fn):
    """Register a live-state callable; returns a token for unregister."""
    with _lock:
        _next_token[0] += 1
        token = _next_token[0]
        _gauges[token] = fn
    return token


def unregister_gauge(token):
    with _lock:
        _gauges.pop(token, None)


def _gauge_text():
    with _lock:
        fns = list(_gauges.values())
    parts = []
    for fn in fns:
        try:
            state = fn()
        except Exception:  # noqa: BLE001 - a gauge must never kill the beat
            continue
        parts.extend(f"{k}={v}" for k, v in state.items())
    return " ".join(parts)


def _rss_mb():
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    return int(line.split()[1]) // 1024
    except (OSError, ValueError, IndexError):
        pass
    return None


class Heartbeat:
    """Daemon timer logging one progress line every ``interval`` seconds."""

    def __init__(self, interval: float):
        self.interval = interval
        self._t0 = time.monotonic()
        self._stop = threading.Event()
        self._t = None
        if interval > 0:
            # carry the caller's telemetry scope so the beat reads the
            # owning command's DeviceStats, not the process-global fallback
            from .scope import spawn_thread

            self._t = spawn_thread(self._loop, name="fgumi-heartbeat")
            self._t.start()

    def _loop(self):
        while not self._stop.wait(self.interval):
            self.beat()

    def beat(self):
        """Log one heartbeat line (also callable directly from tests)."""
        from .report import _device_stats

        parts = [f"heartbeat: +{time.monotonic() - self._t0:.0f}s"]
        gauges = _gauge_text()
        if gauges:
            parts.append(gauges)
        stats = _device_stats()  # None while ops.kernel is unimported
        snap = stats.snapshot() if stats is not None else {}
        if snap.get("dispatches"):
            parts.append(
                f"device(dispatches={snap['dispatches']}"
                f" in-flight={stats.in_flight_count()}"
                f" retries={snap.get('dispatch_retries', 0)}"
                f" host-fallbacks={snap.get('host_fallbacks', 0)})")
        rss = _rss_mb()
        if rss is not None:
            parts.append(f"rss={rss}MB")
        # resource pressure (utils/governor.py): only worth a column when
        # the run is actually degrading
        import sys

        gov = sys.modules.get("fgumi_tpu.utils.governor")
        if gov is not None and gov.GOVERNOR.state != "ok":
            parts.append(f"pressure={gov.GOVERNOR.state}")
        log.info(" ".join(parts))

    def stop(self):
        """Stop AND join (same discipline as the watchdog: a finished
        command must not leave a daemon timer logging behind it)."""
        self._stop.set()
        if self._t is not None:
            self._t.join(timeout=5)
            self._t = None
