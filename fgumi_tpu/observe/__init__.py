"""Unified telemetry: span tracing, metrics registry, run reports, heartbeat.

The reference fgumi is obsessive about operator visibility — per-step
pipeline timers and queue-occupancy history (base.rs:2853-3379), progress
heartbeats, per-command metric files. This package is that discipline for
fgumi-tpu, as one layer with a zero-overhead-when-disabled contract:

- :mod:`.trace` — thread-aware ``span("name", **attrs)`` context manager
  recording begin/end events across the pipeline stages, BGZF/prefetch
  workers, external-sort spills, and device dispatch/fetch; exported as
  Chrome trace-event JSON loadable in Perfetto (``--trace`` /
  ``FGUMI_TPU_TRACE``).
- :mod:`.metrics` — a process-wide :class:`MetricsRegistry` aggregating the
  scattered ``DeviceStats``, ``StageTimes``, fault/retry counters, and I/O
  byte counts under stable dotted names.
- :mod:`.report` — a schema-versioned machine-readable run report emitted
  atomically at the end of every command (``--run-report`` /
  ``FGUMI_TPU_RUN_REPORT``).
- :mod:`.heartbeat` — a periodic one-line progress heartbeat on the
  standard log stream (``--heartbeat`` / ``FGUMI_TPU_HEARTBEAT_S``).
- :mod:`.logs` — ``--log-level`` logging setup with elapsed time and
  thread name, so multi-threaded stage logs are attributable.
- :mod:`.scope` — job-scoped telemetry: a contextvar-resolved
  :class:`TelemetryScope` gives every top-level command (and every serve-
  daemon job) its own metrics/DeviceStats/tracer, propagated through the
  pipeline's helper threads; replaces the old per-command global reset.
- :mod:`.compilewatch` — folds jax compile/cache-hit monitoring events
  into the owning scope's metrics (``device.backend_compiles``), the
  warm-kernel evidence the serve smoke gate asserts on.

Disabled is the default and costs nothing on the hot path: ``span`` returns
a shared no-op context manager, metric folding happens once per command at
report time, and no background thread starts unless asked for.
"""

from .metrics import METRICS, MetricsRegistry  # noqa: F401
from .trace import (NULL_SPAN, instant, span, start_trace, stop_trace,  # noqa: F401
                    tracing_enabled, write_trace)
