"""Always-on flight recorder: a bounded ring of recent events plus a
crash-time black-box dump.

The round-5 bench lost its device win to two 600 s timeouts nobody could
diagnose after the fact — the process died (or was killed) with all of its
state in RAM. This module is the black box a production stack carries: a
small, always-on ring buffer of recent *interesting* events (resolved
device dispatches, breaker/governor/scheduler/feeder state transitions,
every WARNING+ log line, span ends when tracing is armed), costing one
deque append each in steady state, and a single
:meth:`FlightRecorder.dump` that freezes everything — ring contents,
all-thread stacks, metrics + latency summaries, DeviceStats timeline,
breaker/governor snapshots — into one schema'd JSON file when something
goes wrong.

Dump triggers (each fires at most once per reason per process, bounded by
:data:`MAX_DUMPS` total so a failure storm cannot fill a disk):

- an unhandled exception escaping a CLI command (cli.py);
- ``ResourceExhausted`` — the governor's hard-pressure clean failure;
- a dispatch-deadline overrun (ops/kernel.py — the wedge signature);
- the device circuit breaker tripping open (ops/breaker.py);
- a fatal signal (SIGTERM, via :func:`install_signal_dump`; the serve
  daemon's own SIGTERM drain handler supersedes this one on purpose —
  a drained daemon is a clean exit, not a crash).

Dumps are written only when a destination is configured
(``--flight-dump-dir`` / ``FGUMI_TPU_FLIGHT``); the ring itself always
records, so enabling dumps changes *where* evidence lands, never what was
collected. A clean exit writes nothing.
"""

import json
import logging
import os
import sys
import threading
import time
import traceback
from collections import deque

log = logging.getLogger("fgumi_tpu")

SCHEMA_VERSION = 1

#: Ring capacity (events). Small on purpose: the ring answers "what were
#: the last few hundred interesting things", not "everything that happened"
#: — that is the trace's job. Override with FGUMI_TPU_FLIGHT_EVENTS.
DEFAULT_EVENTS = 512

#: Hard cap on black boxes per process: a wedge that re-fires per batch
#: must not turn the dump dir into a disk-pressure incident of its own.
MAX_DUMPS = 8

#: How many trailing DeviceStats timeline entries ride in a dump.
TIMELINE_TAIL = 16


# ---------------------------------------------------------------------------
# shared lazily-imported-singleton snapshots: one definition serves both the
# flight dump's sections and the serve stats/metrics surfaces
# (serve/introspect.py) so they cannot diverge


def live_device_stats():
    """The process-global DeviceStats, or None before ops.kernel loads."""
    kern = sys.modules.get("fgumi_tpu.ops.kernel")
    return getattr(kern, "DEVICE_STATS", None)


def breaker_snapshot():
    breaker = sys.modules.get("fgumi_tpu.ops.breaker")
    return breaker.BREAKER.snapshot() if breaker is not None else None


def governor_snapshot():
    gov = sys.modules.get("fgumi_tpu.utils.governor")
    return gov.GOVERNOR.snapshot() if gov is not None else None


def router_snapshot():
    router = sys.modules.get("fgumi_tpu.ops.router")
    return router.ROUTER.snapshot() if router is not None else None


def audit_snapshot():
    """The silent-corruption sentinel's scoreboard (ops/sentinel.py), or
    None before it loads / while it has seen nothing."""
    sentinel = sys.modules.get("fgumi_tpu.ops.sentinel")
    if sentinel is None or not sentinel.SENTINEL.has_activity():
        return None
    return sentinel.SENTINEL.snapshot()


def coalesce_snapshot():
    """The cross-job dispatch coalescer's scoreboard (ops/coalesce.py),
    or None before it loads / while it has merged nothing and is not
    armed."""
    coal = sys.modules.get("fgumi_tpu.ops.coalesce")
    if coal is None:
        return None
    if not (coal.COALESCER.has_activity() or coal.COALESCER.armed()):
        return None
    return coal.COALESCER.snapshot()


def mesh_snapshot():
    """The active production mesh's {dp, sp, devices, platform}, or None
    when no mesh was built this process (single-device / host-only)."""
    pm = sys.modules.get("fgumi_tpu.parallel.mesh")
    return getattr(pm, "LAST_MESH_SNAPSHOT", None) if pm is not None \
        else None


def device_memory_snapshot():
    """Live accelerator memory, summed over local devices:
    ``{bytes_in_use, peak_bytes}`` from each device's ``memory_stats()``.

    None on CPU (the CPU backend reports no memory stats), before the
    kernel module initialized jax, or on backends predating the API —
    so every consumer (heartbeat, stats op, /metrics, flight dumps) shows
    the section only where it means something. Gated on the kernel's own
    jax-ready flag: merely *asking* jax for devices would otherwise
    initialize the backend from a telemetry path."""
    kern = sys.modules.get("fgumi_tpu.ops.kernel")
    if kern is None or not getattr(kern, "_jax_ready", False):
        return None
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return None
    try:
        devices = jax_mod.local_devices()
    except Exception:  # noqa: BLE001 - telemetry never raises
        return None
    in_use = peak = 0
    seen = False
    for d in devices:
        ms = getattr(d, "memory_stats", None)
        if ms is None:
            continue
        try:
            stats = ms()
        except Exception:  # noqa: BLE001
            continue
        if not stats:
            continue  # CPU devices answer None/{}: no section
        seen = True
        in_use += int(stats.get("bytes_in_use", 0) or 0)
        peak += int(stats.get("peak_bytes_in_use",
                              stats.get("bytes_in_use", 0)) or 0)
    if not seen:
        return None
    return {"bytes_in_use": in_use, "peak_bytes": peak}


def _ring_capacity() -> int:
    try:
        n = int(os.environ.get("FGUMI_TPU_FLIGHT_EVENTS",
                               str(DEFAULT_EVENTS)))
    except ValueError:
        n = DEFAULT_EVENTS
    return max(n, 16)


class FlightRecorder:
    """The process-wide ring + dump machinery (singleton :data:`FLIGHT`)."""

    def __init__(self, capacity: int = None):
        self._lock = threading.Lock()
        self._ring = deque(maxlen=capacity or _ring_capacity())
        self._t0 = time.monotonic()
        self._dump_dir = None          # explicit --flight-dump-dir override
        self._dumped_reasons = set()   # first dump per reason wins
        self._dump_paths = []
        self.events_noted = 0

    # ------------------------------------------------------------ recording

    def note(self, kind: str, **attrs) -> None:
        """Append one event to the ring. Always on, deliberately cheap:
        one dict build + one bounded deque append under a short lock."""
        ev = {"t": round(time.monotonic() - self._t0, 4), "kind": kind,
              "thread": threading.current_thread().name}
        if attrs:
            ev.update(attrs)
        with self._lock:
            self._ring.append(ev)
            self.events_noted += 1

    def events(self) -> list:
        with self._lock:
            return list(self._ring)

    # ---------------------------------------------------------- destination

    def configure(self, dump_dir) -> None:
        """Set (or clear, with None) the explicit dump destination; the
        ``FGUMI_TPU_FLIGHT`` environment is the fallback."""
        self._dump_dir = dump_dir

    def dump_dir(self):
        return self._dump_dir or os.environ.get("FGUMI_TPU_FLIGHT") or None

    def dump_paths(self) -> list:
        """Paths of every black box written so far (run-report carriage)."""
        with self._lock:
            return list(self._dump_paths)

    def reset(self) -> None:
        """Test hook: clear the ring and the per-reason dump dedupe."""
        with self._lock:
            self._ring.clear()
            self._dumped_reasons.clear()
            self._dump_paths.clear()
            self.events_noted = 0
        self._dump_dir = None

    # ------------------------------------------------------------- dumping

    def dump(self, reason: str, exc: BaseException = None, **attrs):
        """Write one black box; returns its path, or None when no dump dir
        is configured / this reason already dumped / the cap is reached.

        Never raises: a failing dump must not worsen the failure it is
        documenting. Must NOT be called while holding a lock the snapshot
        sections below also take (breaker/governor/DeviceStats locks)."""
        d = self.dump_dir()
        if not d:
            return None
        with self._lock:
            if reason in self._dumped_reasons \
                    or len(self._dump_paths) >= MAX_DUMPS:
                return None
            self._dumped_reasons.add(reason)
            seq = len(self._dump_paths)
        try:
            obj = self._build(reason, exc, attrs)
            safe = "".join(c if c.isalnum() or c in "-_" else "-"
                           for c in reason)
            path = os.path.join(d, f"flight-{os.getpid()}-{seq}-{safe}.json")
            os.makedirs(d, exist_ok=True)
            from ..utils.atomic import discard_output, open_output

            out = open_output(path, "w")
            try:
                json.dump(obj, out, indent=1, default=str)
                out.write("\n")
            except BaseException:
                discard_output(out)
                raise
            out.close()
        except Exception as e:  # noqa: BLE001 - evidence loss != new crash
            log.error("flight recorder: could not write black box (%s: %s)",
                      type(e).__name__, e)
            # a FAILED write must not consume the reason: the classic case
            # is resource-exhausted firing while the dump dir's filesystem
            # is the full one — a retrigger after space frees up (temps
            # swept) should still get its black box
            with self._lock:
                self._dumped_reasons.discard(reason)
            return None
        with self._lock:
            self._dump_paths.append(path)
        log.warning("flight recorder: black box -> %s (%s)", path, reason)
        return path

    def _build(self, reason: str, exc, attrs) -> dict:
        obj = {
            "schema_version": SCHEMA_VERSION,
            "tool": "fgumi-tpu",
            "reason": reason,
            "unix": round(time.time(), 3),
            "pid": os.getpid(),
            "argv": sys.argv,
            "events": self.events(),
            "threads": self._thread_stacks(),
        }
        if attrs:
            obj["attrs"] = dict(attrs)
        # a dump raised inside a daemon job names the job and its trace so
        # the black box joins the merged timeline / journal record
        try:
            from .scope import current_scope

            scope = current_scope()
            if scope is not None:
                for key in ("job_id", "trace_id"):
                    val = getattr(scope, key, None)
                    if val:
                        obj[key] = val
        except Exception:  # noqa: BLE001 - identity is optional
            pass
        if exc is not None:
            obj["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc),
                "traceback": traceback.format_exception(
                    type(exc), exc, exc.__traceback__),
            }
        # every section below is best-effort: a half-initialized module
        # must not take the black box down with it
        for name, fn in (("metrics", self._metrics_section),
                         ("device", self._device_section),
                         ("device_memory", device_memory_snapshot),
                         ("mesh", mesh_snapshot),
                         ("breaker", breaker_snapshot),
                         ("governor", governor_snapshot),
                         ("audit", audit_snapshot)):
            try:
                obj[name] = fn()
            except Exception as e:  # noqa: BLE001 - keep the rest
                obj[name] = {"error": f"{type(e).__name__}: {e}"}
        return obj

    @staticmethod
    def _thread_stacks() -> dict:
        """Current stack of every live thread, newest frame last."""
        names = {t.ident: t.name for t in threading.enumerate()}
        out = {}
        for tid, frame in sys._current_frames().items():
            label = f"{names.get(tid, 'unknown')}-{tid}"
            out[label] = [ln.rstrip("\n") for ln in
                          traceback.format_stack(frame)][-40:]
        return out

    @staticmethod
    def _metrics_section() -> dict:
        from .metrics import METRICS

        return {"values": METRICS.snapshot(), "latency": METRICS.summaries()}

    @staticmethod
    def _device_section():
        stats = live_device_stats()
        if stats is None:
            return None
        tail = stats.timeline_snapshot()  # entries carry their true slot
        tail = tail[-TIMELINE_TAIL:]
        # a dispatch with no t_fetched stamp at dump time is still (or was,
        # when abandoned) in flight: the wedge suspect list
        wedged = [t for t in tail if "t_fetched" not in t]
        out = {"snapshot": stats.snapshot(), "timeline_tail": tail,
               "wedged_dispatches": wedged}
        routing = router_snapshot()
        if routing is not None:
            out["routing"] = routing
        return out



#: Process-wide singleton. Flight evidence is a per-process fact: the ring
#: deliberately spans every scope/job so a daemon dump shows the neighbour
#: activity that a per-scope ring would hide.
FLIGHT = FlightRecorder()


def install_signal_dump() -> None:
    """Dump a black box on SIGTERM before dying with the default action.

    Installed by the CLI (main thread, depth-0) only when a dump dir is
    configured. The serve daemon replaces this handler with its own drain
    handler afterwards — a drained daemon is a clean exit, not a crash.
    No-op off the main thread (in-process test harnesses)."""
    if not FLIGHT.dump_dir():
        return
    import signal

    def _on_fatal(signum, frame):
        # the handler runs ON the interrupted thread, which may hold one
        # of the (non-reentrant) locks the dump's snapshot sections take
        # (metrics registry, the ring itself, DeviceStats) — dumping
        # inline could deadlock and turn SIGTERM into a hang. A helper
        # thread + bounded join keeps termination guaranteed: evidence is
        # best-effort, dying is not. The thread runs under a COPY of the
        # interrupted thread's context so the telemetry-scope proxies
        # (METRICS/DEVICE_STATS) resolve to the running command's
        # registries, not the process-global fallbacks.
        import contextvars

        ctx = contextvars.copy_context()
        t = threading.Thread(
            target=ctx.run, args=(FLIGHT.dump, "fatal-signal"),
            kwargs={"signal": signal.Signals(signum).name},
            name="fgumi-flight-dump", daemon=True)
        t.start()
        t.join(timeout=10)
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)

    try:
        signal.signal(signal.SIGTERM, _on_fatal)
    except (ValueError, OSError):
        pass


# ---------------------------------------------------------------------------
# dump validation (tests + the telemetry smoke gate)

_REQUIRED = {
    "schema_version": int,
    "tool": str,
    "reason": str,
    "unix": (int, float),
    "pid": int,
    "argv": list,
    "events": list,
    "threads": dict,
}


def validate_dump(obj) -> list:
    """Structural validation of a black box; returns human-readable
    violations (empty == valid), mirroring report.validate_report."""
    errors = []
    if not isinstance(obj, dict):
        return ["flight dump is not a JSON object"]
    for key, typ in _REQUIRED.items():
        if key not in obj:
            errors.append(f"missing required field {key!r}")
        elif not isinstance(obj[key], typ):
            errors.append(f"field {key!r} has type {type(obj[key]).__name__}")
    if isinstance(obj.get("schema_version"), int) \
            and obj["schema_version"] != SCHEMA_VERSION:
        errors.append(f"schema_version {obj['schema_version']} != "
                      f"{SCHEMA_VERSION}")
    for ev in obj.get("events", []) if isinstance(obj.get("events"), list) \
            else []:
        if not isinstance(ev, dict) or "kind" not in ev or "t" not in ev:
            errors.append(f"malformed ring event: {ev!r}")
            break
    if isinstance(obj.get("threads"), dict):
        for name, stack in obj["threads"].items():
            if not isinstance(stack, list):
                errors.append(f"thread {name!r} stack is not a list")
                break
    return errors
