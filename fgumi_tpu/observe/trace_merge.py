"""Stitch per-process Perfetto trace files into one fleet timeline.

``fgumi-tpu trace-merge client.json bal.json job.json -o merged.json``
takes the Chrome trace-event files a fleet-routed job left behind — the
submitting client's (``--trace`` on the submit), the balancer's, and the
backend job's (``submit --trace``) — and produces ONE file Perfetto opens
as a single timeline with a labelled track group per process.

Alignment: every fgumi-tpu trace export carries a clock anchor
(``otherData.clock.t_zero_unix`` — the wall-clock instant of the file's
monotonic zero, see observe/trace.py). The merge shifts each file's
timestamps so the anchors agree on one wall clock; a file that also
carries ``clock.offset_estimate_s`` (the serve-handshake clock-offset
estimate, recorded when the tracing process handshook a TCP daemon) is
first corrected onto the server's clock, so cross-host skew cancels to
within half the handshake round trip. ``--shift FILE=SECONDS`` overrides
the estimate per file when an operator knows better (e.g. from ptp/ntp
telemetry).

Causality: files carry ``otherData.trace_context`` (trace-id +
parent-span-id). The merge groups by trace-id — mixing files from
different traces is almost always an operator mistake, so differing ids
are an error unless ``--trace-id`` picks one (then non-matching files are
skipped with a note) or ``--force`` keeps them all.
"""

import json
import os

#: synthetic pid namespace for colliding input files: two processes on
#: different hosts can share an OS pid, and Perfetto would fold their
#: tracks together — remapped pids start here (real pids stay put).
_REMAP_BASE = 1 << 22


class MergeError(ValueError):
    """A merge input is unusable (not a trace, unreadable, id mismatch)."""


def _load(path: str) -> dict:
    try:
        with open(path, encoding="utf-8") as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        raise MergeError(f"{path}: cannot read trace: {e}") from None
    if not isinstance(obj, dict) or not isinstance(
            obj.get("traceEvents"), list):
        raise MergeError(f"{path}: not a Chrome trace-event file "
                         "(no traceEvents array)")
    return obj


def _file_meta(path: str, obj: dict) -> dict:
    """Anchor, process identity, and trace context of one input file."""
    other = obj.get("otherData") if isinstance(obj.get("otherData"),
                                               dict) else {}
    clock = other.get("clock") if isinstance(other.get("clock"),
                                             dict) else {}
    process = other.get("process") if isinstance(other.get("process"),
                                                 dict) else {}
    ctx = other.get("trace_context") \
        if isinstance(other.get("trace_context"), dict) else {}
    anchor = clock.get("t_zero_unix")
    if not isinstance(anchor, (int, float)) or isinstance(anchor, bool):
        anchor = None
    offset = clock.get("offset_estimate_s")
    if not isinstance(offset, (int, float)) or isinstance(offset, bool):
        offset = 0.0
    pids = {ev.get("pid") for ev in obj["traceEvents"]
            if isinstance(ev.get("pid"), int)}
    return {
        "path": path,
        "anchor_unix": anchor,
        "offset_s": float(offset),
        "pid": process.get("pid") if isinstance(process.get("pid"), int)
        else (sorted(pids)[0] if pids else 0),
        "label": process.get("label") or None,
        "trace_id": ctx.get("trace_id"),
        "parent_span_id": ctx.get("parent_span_id"),
    }


def parse_shift_specs(specs) -> dict:
    """``["bal.json=0.25", ...]`` -> {basename-or-path: seconds}."""
    out = {}
    for spec in specs or ():
        name, eq, val = spec.partition("=")
        if not eq or not name:
            raise MergeError(f"--shift {spec!r} is not FILE=SECONDS")
        try:
            out[name] = float(val)
        except ValueError:
            raise MergeError(
                f"--shift {spec!r}: {val!r} is not a number") from None
    return out


def _user_shift(path: str, shifts: dict) -> float:
    if path in shifts:
        return shifts[path]
    return shifts.get(os.path.basename(path), 0.0)


def merge_traces(paths, trace_id: str = None, shifts: dict = None,
                 force: bool = False) -> dict:
    """Merge trace files into one Chrome trace-event object.

    Returns the merged object; raises :class:`MergeError` on unusable
    inputs or conflicting trace ids (unless ``force``). ``trace_id``
    keeps only files stamped with that id (others are skipped, recorded
    under ``otherData.skipped``); ``shifts`` maps file path/basename to
    extra seconds added to that file's timeline."""
    if not paths:
        raise MergeError("no trace files to merge")
    shifts = shifts or {}
    loaded = []
    skipped = []
    for path in paths:
        obj = _load(path)
        meta = _file_meta(path, obj)
        if trace_id is not None and meta["trace_id"] != trace_id:
            skipped.append({"path": path,
                            "trace_id": meta["trace_id"],
                            "reason": "trace_id mismatch"})
            continue
        loaded.append((meta, obj))
    if not loaded:
        raise MergeError("no input file matches trace id "
                         f"{trace_id!r}" if trace_id is not None
                         else "no trace files to merge")
    ids = {m["trace_id"] for m, _ in loaded if m["trace_id"]}
    if len(ids) > 1 and not force:
        raise MergeError(
            "inputs span multiple trace ids "
            f"{sorted(ids)}; pick one with --trace-id or pass --force")
    # the reference clock: the earliest corrected anchor, so every merged
    # timestamp is >= 0 (Perfetto dislikes negative ts). Files with no
    # anchor (foreign traces) align at the reference as-is.
    anchored = [m["anchor_unix"] - m["offset_s"]
                + _user_shift(m["path"], shifts)
                for m, _ in loaded if m["anchor_unix"] is not None]
    ref = min(anchored) if anchored else 0.0
    events = []
    merged_from = []
    used_pids = set()
    next_remap = _REMAP_BASE
    for meta, obj in loaded:
        if meta["anchor_unix"] is None:
            shift_us = round(_user_shift(meta["path"], shifts) * 1e6, 1)
        else:
            corrected = (meta["anchor_unix"] - meta["offset_s"]
                         + _user_shift(meta["path"], shifts))
            shift_us = round((corrected - ref) * 1e6, 1)
        # per-file pid remap: keep the real pid unless another file
        # already claimed it (same pid on two hosts, or a restarted
        # process), else move the whole file to a synthetic pid
        pid_map = {}

        def mapped(pid):
            nonlocal next_remap
            if pid in pid_map:
                return pid_map[pid]
            new = pid
            while new in used_pids:
                new = next_remap
                next_remap += 1
            used_pids.add(new)
            pid_map[pid] = new
            return new

        saw_process_name = False
        for ev in obj["traceEvents"]:
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            if isinstance(ev.get("pid"), int):
                ev["pid"] = mapped(ev["pid"])
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    saw_process_name = True
            elif isinstance(ev.get("ts"), (int, float)):
                ev["ts"] = round(ev["ts"] + shift_us, 1)
            events.append(ev)
        file_pid = mapped(meta["pid"])
        if not saw_process_name:
            # label the track group even when the source never did —
            # fall back to the file name so the merged view stays legible
            events.append({
                "name": "process_name", "ph": "M", "pid": file_pid,
                "tid": 0,
                "args": {"name": meta["label"]
                         or os.path.basename(meta["path"])}})
        merged_from.append({
            "path": meta["path"],
            "pid": file_pid,
            "label": meta["label"],
            "trace_id": meta["trace_id"],
            "parent_span_id": meta["parent_span_id"],
            "shift_s": round(shift_us / 1e6, 6),
            "clock_offset_s": round(meta["offset_s"], 6),
        })
    other = {"clock": {"t_zero_unix": round(ref, 6)},
             "merged_from": merged_from}
    if len(ids) == 1:
        other["trace_context"] = {"trace_id": next(iter(ids))}
    if skipped:
        other["skipped"] = skipped
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": other}


def write_merged(obj: dict, path: str):
    """Commit the merged trace atomically (like every other output)."""
    from ..utils.atomic import discard_output, open_output

    out = open_output(path, "w")
    try:
        json.dump(obj, out, separators=(",", ":"))
    except BaseException:
        discard_output(out)
        raise
    out.close()
