"""Thread-aware span tracing with Chrome trace-event JSON export.

``span("stage.process", batch=3)`` times a region of one thread and records
it as a Chrome trace-event *complete* event (``ph: "X"``), so a run traced
with ``--trace out.json`` opens directly in Perfetto (or
``chrome://tracing``) with one timeline row per thread — a pipeline stall
is visible as a gap, a device round trip as a block on the feeder row.

Design constraints (the acceptance contract of the telemetry layer):

- **Zero overhead when disabled.** ``span()`` with tracing off returns one
  shared no-op context manager — no allocation, no lock, no time call.
  Hot loops that want even the dict-build of attrs gone should hoist
  ``tracing_enabled()`` once and skip their span calls entirely (the
  pipeline does this).
- **Thread attribution.** Events carry the OS thread id and the trace
  names each thread once via ``thread_name`` metadata events, so the
  fgumi-reader / fgumi-writer / fgumi-worker-N / fgumi-device-feeder rows
  are labelled.
- **Bounded memory.** The event buffer is capped (:data:`MAX_EVENTS`,
  override ``FGUMI_TPU_TRACE_MAX_EVENTS``); overflow drops further spans
  and reports the dropped count in the export rather than growing without
  bound on a long run.
- **Cross-process linkage.** A W3C-style trace context (32-hex trace-id +
  16-hex parent-span-id, carried as a ``traceparent`` string) can be
  attached to a tracer; the export then stamps it into ``otherData`` and a
  ``process_labels`` metadata event so ``fgumi-tpu trace-merge`` can stitch
  per-process files from one fleet-routed job into a single timeline.
  Every export also records a wall-clock anchor (``t_zero_unix`` paired
  with the monotonic ``t_zero``) — the merge tool aligns per-process
  timelines on these anchors (docs/observability.md "Fleet tracing").
"""

import json
import os
import threading
import time

# ---------------------------------------------------------------------------
# W3C-style trace context (trace-id + parent-span-id)

#: traceparent wire format, a strict subset of W3C Trace Context:
#: ``00-<32 hex trace-id>-<16 hex span-id>-01``. Malformed values are
#: IGNORED by every consumer (dropped, never rejected) so a buggy or
#: future-version peer can't fail a submission over telemetry garnish.
_TRACEPARENT_VERSION = "00"


def mint_trace_id() -> str:
    """A fresh 32-hex trace id (random, collision-safe across the fleet)."""
    return os.urandom(16).hex()


def mint_span_id() -> str:
    """A fresh 16-hex span id."""
    return os.urandom(8).hex()


def format_traceparent(trace_id: str, span_id: str) -> str:
    """``00-<trace-id>-<span-id>-01`` (sampled flag always set: fgumi-tpu
    traces are explicitly requested, never probabilistically sampled)."""
    return f"{_TRACEPARENT_VERSION}-{trace_id}-{span_id}-01"


def _is_hex(s: str, n: int) -> bool:
    if len(s) != n:
        return False
    try:
        int(s, 16)
    except ValueError:
        return False
    return True


def parse_traceparent(value):
    """``(trace_id, span_id)`` for a well-formed traceparent, else None.

    None for anything malformed — wrong type, wrong field count, non-hex,
    all-zero ids — per the propagation contract: telemetry context is
    best-effort garnish and must never fail a request."""
    if not isinstance(value, str):
        return None
    parts = value.split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if not (_is_hex(version, 2) and _is_hex(trace_id, 32)
            and _is_hex(span_id, 16) and _is_hex(flags, 2)):
        return None
    if version == "ff" or set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None
    return trace_id, span_id


# ---------------------------------------------------------------------------
# no-op fast path


class _NullSpan:
    """Shared do-nothing context manager returned while tracing is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        """No-op attr update (mirrors the live span's API)."""


NULL_SPAN = _NullSpan()

_tracer = None  # process-global _Tracer, or None (used when no scope active)


def _current_tracer():
    """The tracer spans should record into: the active telemetry scope's
    (one per daemon job) when inside one, else the process-global tracer.
    A scope with tracing off shades the global tracer on purpose — job A
    tracing must not collect job B's spans."""
    from .scope import current_scope

    scope = current_scope()
    if scope is not None:
        return scope.tracer
    return _tracer


def tracing_enabled() -> bool:
    return _current_tracer() is not None


# ---------------------------------------------------------------------------
# live tracer

MAX_EVENTS = 500_000


class _Span:
    """One in-flight span: records a complete ("X") event on exit."""

    __slots__ = ("_tracer", "name", "_t0", "args")

    def __init__(self, tracer, name, args):
        self._tracer = tracer
        self.name = name
        self.args = args
        self._t0 = time.monotonic()

    def set(self, **attrs):
        """Attach attrs discovered mid-span (recorded at exit)."""
        if self.args is None:
            self.args = attrs
        else:
            self.args.update(attrs)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.monotonic()
        self._tracer._complete(self.name, self._t0, t1, self.args,
                               error=exc_type.__name__ if exc_type else None)
        return False


class _Tracer:
    def __init__(self, max_events: int = None):
        if max_events is None:
            try:
                max_events = int(os.environ.get(
                    "FGUMI_TPU_TRACE_MAX_EVENTS", str(MAX_EVENTS)))
            except ValueError:
                max_events = MAX_EVENTS
        self.max_events = max_events
        # the clock anchor pair: one monotonic zero for in-file timestamps
        # and the wall-clock instant it corresponds to, captured
        # back-to-back. trace-merge aligns per-process files by shifting
        # each timeline so the anchors agree (the residual error is the
        # few-ns gap between these two calls plus any host clock skew,
        # correctable with the handshake offset estimate).
        self.t_zero = time.monotonic()
        self.t_zero_unix = time.time()
        #: W3C-style trace context (set via :meth:`set_context` when this
        #: process's work is part of a fleet-routed job); exported so
        #: trace-merge can group per-process files under one trace-id
        self.trace_id = None
        self.parent_span_id = None
        #: human label for this process's track group in a merged timeline
        #: (e.g. "client", "balancer", "backend j-3")
        self.process_label = None
        #: estimated local-minus-server wall clock skew (seconds), from
        #: the serve handshake round trip; trace-merge subtracts it from
        #: the anchor so cross-host timelines line up on the server clock
        self.clock_offset_s = None
        self.dropped = 0
        self._lock = threading.Lock()
        self._events = []
        self._named_tids = set()

    def set_context(self, trace_id: str = None, parent_span_id: str = None,
                    process_label: str = None):
        """Attach the fleet trace context (any subset; idempotent)."""
        if trace_id is not None:
            self.trace_id = trace_id
        if parent_span_id is not None:
            self.parent_span_id = parent_span_id
        if process_label is not None:
            self.process_label = process_label

    def _thread_meta_locked(self):
        """Emit a thread_name metadata event for the calling thread once."""
        tid = threading.get_ident()
        if tid not in self._named_tids:
            self._named_tids.add(tid)
            self._events.append({
                "name": "thread_name", "ph": "M", "pid": os.getpid(),
                "tid": tid,
                "args": {"name": threading.current_thread().name}})
        return tid

    def _complete(self, name, t0, t1, args, error=None):
        # span ends also feed the always-on flight recorder's ring (the
        # black box shows the last few hundred spans even when the trace
        # buffer overflowed or was never exported)
        from .flight import FLIGHT

        FLIGHT.note("span", name=name, dur_ms=round((t1 - t0) * 1e3, 3),
                    **({"error": error} if error else {}))
        ev = {"name": name, "ph": "X", "pid": os.getpid(),
              "ts": round((t0 - self.t_zero) * 1e6, 1),
              "dur": round((t1 - t0) * 1e6, 1)}
        if error is not None:
            args = dict(args or ())
            args["error"] = error
        if args:
            ev["args"] = args
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            ev["tid"] = self._thread_meta_locked()
            self._events.append(ev)

    def instant(self, name, args=None):
        ev = {"name": name, "ph": "i", "s": "t", "pid": os.getpid(),
              "ts": round((time.monotonic() - self.t_zero) * 1e6, 1)}
        if args:
            ev["args"] = args
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            ev["tid"] = self._thread_meta_locked()
            self._events.append(ev)

    def snapshot(self):
        with self._lock:
            return list(self._events)

    def to_json_obj(self):
        events = self.snapshot()
        if self.dropped:
            # an explicit truncation marker INSIDE the timeline: a human in
            # Perfetto sees where recording stopped instead of silently
            # reading a gap as "nothing happened after this"
            events.append({
                "name": "trace.truncated", "ph": "i", "s": "g",
                "pid": os.getpid(), "tid": 0,
                "ts": round((time.monotonic() - self.t_zero) * 1e6, 1),
                "args": {"dropped_events": self.dropped,
                         "max_events": self.max_events}})
        if self.process_label:
            # a process_name metadata event labels this pid's track group
            # when the file is merged with other processes' timelines
            events.append({"name": "process_name", "ph": "M",
                           "pid": os.getpid(), "tid": 0,
                           "args": {"name": self.process_label}})
        obj = {"traceEvents": events, "displayTimeUnit": "ms"}
        clock = {"t_zero_unix": round(self.t_zero_unix, 6)}
        if self.clock_offset_s is not None:
            clock["offset_estimate_s"] = round(self.clock_offset_s, 6)
        other = {"clock": clock,
                 "process": {"pid": os.getpid(),
                             "label": self.process_label}}
        if self.trace_id:
            other["trace_context"] = {"trace_id": self.trace_id,
                                      "parent_span_id": self.parent_span_id}
        if self.dropped:
            other["dropped_events"] = self.dropped
        obj["otherData"] = other
        return obj


# ---------------------------------------------------------------------------
# module API


def span(name: str, **attrs):
    """Time a region of the current thread as a named trace span.

    With tracing disabled this returns the shared :data:`NULL_SPAN` (no
    allocation); enabled, a complete event is recorded when the context
    exits, tagged with ``attrs`` and the thread's id/name. Exceptions
    propagate (the span records ``error: <type>``)."""
    t = _current_tracer()
    if t is None:
        return NULL_SPAN
    return _Span(t, name, attrs or None)


def instant(name: str, **attrs):
    """Record a zero-duration instant event (a timeline marker)."""
    t = _current_tracer()
    if t is not None:
        t.instant(name, attrs or None)


def set_trace_context(trace_id: str = None, parent_span_id: str = None,
                      process_label: str = None):
    """Attach the fleet trace context to the active tracer (no-op when
    tracing is off — context is garnish, never a reason to allocate)."""
    t = _current_tracer()
    if t is not None:
        t.set_context(trace_id, parent_span_id, process_label)


def set_clock_offset(offset_s: float):
    """Record the handshake clock-offset estimate on the active tracer
    (no-op when tracing is off)."""
    t = _current_tracer()
    if t is not None:
        t.clock_offset_s = float(offset_s)


def start_trace(max_events: int = None):
    """Enable tracing for the active telemetry scope (one per daemon job),
    or process-wide when no scope is entered. Idempotent (keeps the active
    tracer)."""
    global _tracer
    from .scope import current_scope

    scope = current_scope()
    if scope is not None:
        if scope.tracer is None:
            scope.tracer = _Tracer(max_events)
        return scope.tracer
    if _tracer is None:
        _tracer = _Tracer(max_events)
    return _tracer


def stop_trace():
    """Disable tracing (scope-local when inside a scope) and return the
    tracer (caller may still export it)."""
    global _tracer
    from .scope import current_scope

    scope = current_scope()
    if scope is not None:
        t, scope.tracer = scope.tracer, None
        return t
    t, _tracer = _tracer, None
    return t


def write_trace(path: str, tracer=None):
    """Export the trace as Chrome trace-event JSON, committed atomically.

    Writes the active tracer by default; pass the object returned by
    :func:`stop_trace` to export after disabling."""
    t = tracer if tracer is not None else _tracer
    if t is None:
        return
    if t.dropped:
        # overflow is an observability *defect* worth a counter: the run
        # report says how much of the timeline is missing
        from .metrics import METRICS

        METRICS.inc("trace.dropped_events", t.dropped)
    from ..utils.atomic import discard_output, open_output

    out = open_output(path, "w")
    try:
        json.dump(t.to_json_obj(), out, separators=(",", ":"))
    except BaseException:
        discard_output(out)
        raise
    out.close()
