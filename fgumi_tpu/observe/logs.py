"""Logging setup: ``--log-level`` / ``FGUMI_TPU_LOG`` with a consistent
format carrying elapsed time and thread name.

Supersedes the bare ``-v`` flag (kept as an alias for ``--log-level
debug``): multi-threaded stage logs were unattributable with the old
``asctime level name: message`` format — a stall warning from the watchdog
thread and a retry warning from a resolve worker looked identical. Every
line now reads::

    12:34:56 INFO fgumi_tpu [fgumi-writer +3.214s]: ...

where ``+3.214s`` is seconds since logging setup (process-relative, so
stage lines across a chained ``pipeline`` run share one clock).
"""

import logging
import os
import time

LEVELS = ("debug", "info", "warning", "error")

_FORMAT = "%(asctime)s %(levelname)s %(name)s [%(threadName)s %(elapsed)s]: %(message)s"


class ElapsedFormatter(logging.Formatter):
    """Formatter injecting ``%(elapsed)s`` = +seconds since construction."""

    default_time_format = "%H:%M:%S"
    default_msec_format = None

    def __init__(self, fmt=_FORMAT):
        super().__init__(fmt)
        self._t0 = time.monotonic()

    def format(self, record):
        record.elapsed = f"+{time.monotonic() - self._t0:.3f}s"
        return super().format(record)


def resolve_level(log_level: str = None, verbose: bool = False) -> int:
    """Effective logging level: explicit --log-level wins, then the
    FGUMI_TPU_LOG environment, then -v (debug), else info. Unknown env
    values fall back to info (loudly, once logging is up)."""
    name = log_level or os.environ.get("FGUMI_TPU_LOG", "").strip().lower()
    if name not in LEVELS:
        if name:
            logging.getLogger("fgumi_tpu").warning(
                "FGUMI_TPU_LOG=%s: unknown level (expected one of %s); "
                "using info", name, "/".join(LEVELS))
        name = "debug" if verbose else "info"
    return getattr(logging, name.upper())


class FlightLogHandler(logging.Handler):
    """Feeds every WARNING+ log record into the flight recorder's ring.

    Always installed (the ring is always-on); costs one handler dispatch
    per WARNING+ record — by definition not the hot path."""

    def __init__(self):
        super().__init__(level=logging.WARNING)

    def emit(self, record):
        try:
            from .flight import FLIGHT

            FLIGHT.note("log", level=record.levelname, logger=record.name,
                        msg=record.getMessage()[:300])
        except Exception:  # noqa: BLE001 - evidence must never crash logging
            pass


def _install_flight_handler(root):
    if not any(isinstance(h, FlightLogHandler) for h in root.handlers):
        root.addHandler(FlightLogHandler())


def setup_logging(log_level: str = None, verbose: bool = False) -> int:
    """Install the elapsed/thread-aware format on the root logger.

    Safe to call repeatedly in one process (the chained ``pipeline``
    command re-enters main() per stage): the handler is installed once and
    the level is updated each call. Returns the effective level."""
    level = resolve_level(log_level, verbose)
    root = logging.getLogger()
    _install_flight_handler(root)
    handler = None
    for h in root.handlers:
        if getattr(h, "_fgumi_observe", False):
            handler = h
            break
    if handler is None:
        # the flight handler is ours and writes nowhere visible — only
        # FOREIGN handlers mean someone else owns the logging config
        if any(not isinstance(h, FlightLogHandler)
               for h in root.handlers):
            # e.g. pytest or an embedding app configured logging first:
            # respect their handlers, only adjust the level
            root.setLevel(min(root.level or level, level))
            logging.getLogger("fgumi_tpu").setLevel(level)
            return level
        handler = logging.StreamHandler()
        handler.setFormatter(ElapsedFormatter())
        handler._fgumi_observe = True
        root.addHandler(handler)
    root.setLevel(level)
    return level
