"""Fold XLA compilation activity into the metrics registry.

The serve daemon's whole value proposition is that the second job on a warm
process *does not compile anything* — but "it felt faster" is not evidence.
jax publishes monitoring events for exactly this: every real backend compile
records ``/jax/core/compile/backend_compile_duration`` and every persistent
compile-cache load records ``/jax/compilation_cache/cache_hits``; an
in-memory jit cache hit records neither. A process-wide listener (installed
once, at first jax use) forwards those events into ``METRICS`` under::

    device.backend_compiles      count of real XLA compilations
    device.backend_compile_s     seconds spent in them
    device.compile_cache_hits    executables loaded from the persistent cache

``METRICS`` is the scope-resolving proxy, and the listener fires on the
thread that triggered the compile (the job thread or its context-carrying
device feeder), so in the daemon these counters land in the *owning job's*
registry — ``tools/serve_smoke.py`` and the run reports assert warm-kernel
behaviour from them: job 1 reports ``backend_compiles > 0``, the identical
job 2 reports none.

Failure tolerant by design: an old jax without ``jax.monitoring`` simply
means no compile telemetry.
"""

import logging

log = logging.getLogger("fgumi_tpu")

_installed = False

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"


def _on_duration(event: str, duration: float, **_kw):
    if event == _BACKEND_COMPILE_EVENT:
        from .metrics import METRICS

        METRICS.inc("device.backend_compiles")
        METRICS.inc("device.backend_compile_s", round(duration, 4))
        # shape-bucket attribution: the dispatch machinery flags (via a
        # contextvar that rides the feeder's context copy) dispatches
        # whose bucketed shape is new this process; a real backend
        # compile landing inside one is a shape-ladder recompile, which
        # is what device.shape_bucket.recompiles counts (ops/datapath.py)
        try:
            from ..ops.datapath import compile_is_shape_miss

            if compile_is_shape_miss():
                METRICS.inc("device.shape_bucket.recompiles")
        except Exception:  # pragma: no cover - attribution is best-effort
            pass


def _on_event(event: str, **_kw):
    if event == _CACHE_HIT_EVENT:
        from .metrics import METRICS

        METRICS.inc("device.compile_cache_hits")


def install() -> bool:
    """Register the jax monitoring listeners (idempotent).

    Called from ``ops.kernel._ensure_jax`` so any code path that can compile
    has the watch in place first. Returns True when listening."""
    global _installed
    if _installed:
        return True
    try:
        from jax import monitoring
    except Exception as e:  # pragma: no cover - jax without monitoring
        log.debug("compile watch unavailable: %s", e)
        return False
    try:
        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
    except Exception as e:  # pragma: no cover - API drift tolerated
        log.debug("compile watch not installed: %s", e)
        return False
    _installed = True
    return True
