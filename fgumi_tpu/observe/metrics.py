"""Process-wide metrics registry under stable dotted names.

PR 1 grew rich internal counters — ``DeviceStats`` dispatch/retry/fallback
tallies, ``StageTimes`` busy/blocked/queue samples, fault-injection fire
counts, BGZF byte offsets — but each lived inside its owning object. The
registry is the single aggregation point: components fold their counters in
(cheaply, at end-of-run or close time, never per record) and the run report
/ telemetry smoke read one flat ``{dotted.name: number}`` mapping.

Naming convention (stable API — the run-report schema and CI smoke rely on
these prefixes):

- ``pipeline.stage.<stage>.busy_s`` / ``.blocked_s`` — run_stages timings
- ``pipeline.stage.<name>.wall_s`` — the `pipeline` command's per-stage
  wall clock (extract/sort/group/simplex/filter), both drivers
- ``pipeline.chain.fused`` — 1 when the fused in-memory chain ran;
  ``pipeline.chain.<producer>.<consumer>.{batches,bytes,peak_bytes,
  put_wait_s,get_wait_s,copies}`` — per-channel handoff traffic and
  backpressure of the fused chain (pipeline_chain.py; the CI gate
  ``tools/chain_smoke.py`` reads these)
- ``pipeline.queue.{in,out}.{mean,max}``, ``pipeline.queue.samples``
- ``device.*`` — DeviceStats snapshot (dispatches, retries, batch_splits,
  host_fallbacks, bytes_uploaded, bytes_fetched, fetch_wait_s,
  upload_overlap_s, feeder_queue_depth, const_uploads/const_hits, ...)
- ``device.shape_bucket.{hits,misses,recompiles,shapes}`` — bucketed
  shape-registry lookups (ops/datapath.py): hit = padded shape already
  seen this process (guaranteed jit-cache hit), miss = first sighting,
  recompile = a miss whose dispatch triggered a real XLA backend compile
  (persistent-cache miss too), shapes = distinct-shape gauge
- ``device.const_cache.{hits,misses,bytes_uploaded}`` — device-resident
  constant-table cache traffic (quality tables / wire dictionaries are
  uploaded once per (device, content), not per dispatch)
- ``device.breaker.state`` (gauge: closed/open/half-open),
  ``device.breaker.{transitions,opened}``, ``device.canary.{ok,failed}``
  — wedge circuit breaker + health canary (ops/breaker.py);
  ``device.deadline_fallbacks`` folds in from DeviceStats when a
  dispatch was abandoned at its deadline
- ``serve.journal.{replayed,requeued,truncated_bytes}`` — crash-recovery
  accounting from the serve daemon's journal replay (serve/journal.py)
- ``io.bytes_read`` / ``io.bytes_written`` — compressed bytes through the
  BGZF reader/writer (and raw bytes for plain streams)
- ``records.<label>`` — ProgressTracker totals per command label
- ``faults.<point>`` — injected-fault fire counts
"""

import threading


class MetricsRegistry:
    """Thread-safe flat registry of numeric metrics under dotted names."""

    def __init__(self):
        self._lock = threading.Lock()
        self._values = {}

    def inc(self, name: str, n=1):
        """Add ``n`` to a counter (creating it at 0)."""
        with self._lock:
            self._values[name] = self._values.get(name, 0) + n

    def set(self, name: str, value):
        """Set a gauge to ``value`` (last write wins)."""
        with self._lock:
            self._values[name] = value

    def max(self, name: str, value):
        """Raise a high-water-mark gauge to ``value`` if larger."""
        with self._lock:
            if value > self._values.get(name, value - 1):
                self._values[name] = value

    def update(self, mapping, prefix: str = ""):
        """Fold a ``{name: number}`` mapping in under an optional prefix.

        Numeric values accumulate (so two pipeline stages or two CLI
        sub-stages of one chained command sum rather than clobber);
        non-numeric values overwrite."""
        p = prefix + "." if prefix and not prefix.endswith(".") else prefix
        with self._lock:
            for k, v in mapping.items():
                key = p + k
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    self._values[key] = v
                else:
                    self._values[key] = self._values.get(key, 0) + v

    def get(self, name: str, default=None):
        with self._lock:
            return self._values.get(name, default)

    def snapshot(self) -> dict:
        """Name-sorted copy of every metric."""
        with self._lock:
            return dict(sorted(self._values.items()))

    def reset(self):
        with self._lock:
            self._values.clear()

    def replace(self, mapping: dict):
        """Overwrite this registry's whole content (scope publishing)."""
        with self._lock:
            self._values = dict(mapping)


#: Fallback registry used when no telemetry scope is active (library use,
#: tests, plain single-command CLI runs).
_GLOBAL_REGISTRY = MetricsRegistry()


def current_registry() -> MetricsRegistry:
    """The registry writes should land in: the active scope's (observe.scope)
    when one is entered — one per daemon job / top-level command — else the
    process-global fallback."""
    from .scope import current_scope

    scope = current_scope()
    return scope.metrics if scope is not None else _GLOBAL_REGISTRY


class _RegistryProxy:
    """Drop-in stand-in for the old module-global registry: every call
    resolves the active scope first, so ``from ..observe.metrics import
    METRICS`` keeps working at every existing fold site while two scoped
    jobs in one process stay isolated."""

    __slots__ = ()

    def inc(self, name: str, n=1):
        current_registry().inc(name, n)

    def set(self, name: str, value):
        current_registry().set(name, value)

    def max(self, name: str, value):
        current_registry().max(name, value)

    def update(self, mapping, prefix: str = ""):
        current_registry().update(mapping, prefix)

    def get(self, name: str, default=None):
        return current_registry().get(name, default)

    def snapshot(self) -> dict:
        return current_registry().snapshot()

    def reset(self):
        current_registry().reset()


#: The registry every component folds into (scope-resolving proxy).
METRICS = _RegistryProxy()


def record_stage_times(stats) -> None:
    """Fold a :class:`fgumi_tpu.pipeline.StageTimes` into :data:`METRICS`.

    Called once per run_stages completion (success or failure path), so
    every command that ran a pipeline contributes its per-stage busy/blocked
    seconds and queue-occupancy statistics to the run report."""
    for stage, dt in stats.busy.items():
        METRICS.inc(f"pipeline.stage.{stage}.busy_s", round(dt, 6))
    for stage, dt in stats.blocked.items():
        METRICS.inc(f"pipeline.stage.{stage}.blocked_s", round(dt, 6))
    if stats.q_samples:
        METRICS.inc("pipeline.queue.samples", stats.q_samples)
        METRICS.inc("pipeline.queue.in.sum", stats.q_in_sum)
        METRICS.inc("pipeline.queue.out.sum", stats.q_out_sum)
        METRICS.max("pipeline.queue.in.max", stats.q_in_max)
        METRICS.max("pipeline.queue.out.max", stats.q_out_max)
    peak = getattr(stats, "peak_in_flight_bytes", None)
    if peak:
        METRICS.max("pipeline.peak_in_flight_bytes", peak)
