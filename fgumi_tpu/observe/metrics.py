"""Process-wide metrics registry under stable dotted names.

PR 1 grew rich internal counters — ``DeviceStats`` dispatch/retry/fallback
tallies, ``StageTimes`` busy/blocked/queue samples, fault-injection fire
counts, BGZF byte offsets — but each lived inside its owning object. The
registry is the single aggregation point: components fold their counters in
(cheaply, at end-of-run or close time, never per record) and the run report
/ telemetry smoke read one flat ``{dotted.name: number}`` mapping.

Naming convention (stable API — the run-report schema and CI smoke rely on
these prefixes):

- ``pipeline.stage.<stage>.busy_s`` / ``.blocked_s`` — run_stages timings
- ``pipeline.stage.<name>.wall_s`` — the `pipeline` command's per-stage
  wall clock (extract/sort/group/simplex/filter), both drivers
- ``pipeline.chain.fused`` — 1 when the fused in-memory chain ran;
  ``pipeline.chain.<producer>.<consumer>.{batches,bytes,peak_bytes,
  put_wait_s,get_wait_s,copies}`` — per-channel handoff traffic and
  backpressure of the fused chain (pipeline_chain.py; the CI gate
  ``tools/chain_smoke.py`` reads these)
- ``pipeline.queue.{in,out}.{mean,max}``, ``pipeline.queue.samples``
- ``device.*`` — DeviceStats snapshot (dispatches, retries, batch_splits,
  host_fallbacks, bytes_uploaded, bytes_fetched, fetch_wait_s,
  upload_overlap_s, feeder_queue_depth, const_uploads/const_hits, ...)
- ``device.shape_bucket.{hits,misses,recompiles,shapes}`` — bucketed
  shape-registry lookups (ops/datapath.py): hit = padded shape already
  seen this process (guaranteed jit-cache hit), miss = first sighting,
  recompile = a miss whose dispatch triggered a real XLA backend compile
  (persistent-cache miss too), shapes = distinct-shape gauge
- ``device.const_cache.{hits,misses,bytes_uploaded}`` — device-resident
  constant-table cache traffic (quality tables / wire dictionaries are
  uploaded once per (device, content), not per dispatch)
- ``device.breaker.state`` (gauge: closed/open/half-open),
  ``device.breaker.{transitions,opened}``, ``device.canary.{ok,failed}``
  — wedge circuit breaker + health canary (ops/breaker.py);
  ``device.deadline_fallbacks`` folds in from DeviceStats when a
  dispatch was abandoned at its deadline
- ``serve.journal.{replayed,requeued,truncated_bytes}`` — crash-recovery
  accounting from the serve daemon's journal replay (serve/journal.py)
- ``io.bytes_read`` / ``io.bytes_written`` — compressed bytes through the
  BGZF reader/writer (and raw bytes for plain streams)
- ``records.<label>`` — ProgressTracker totals per command label
- ``faults.<point>`` — injected-fault fire counts

Latency histograms (``METRICS.observe(name, seconds)``) live next to the
counters under the same dotted names and fold into the run report's
``latency`` section (schema v2) as ``{count, sum, p50, p90, p99, max}``
summaries:

- ``device.dispatch.{pack_s,upload_s,compute_s,fetch_s,wall_s}`` — per
  dispatch, from the DeviceStats timeline at resolve time
- ``device.router.pred_err_s`` — |predicted − actual| dispatch wall of the
  offload cost model (ops/router.py), per stamped dispatch
- ``pipeline.chain.{put_wait_s,get_wait_s}`` — per-blob backpressure waits
  of the fused chain's channels (pipeline_chain.py)
- ``governor.budget.wait_s`` — blocking DynamicBudget.acquire waits
- ``sort.{spill_s,merge_frame_s}`` — external-sort spill runs and phase-2
  merge frame decompressions
- ``io.bgzf.{compress_s,decompress_s}`` — per BGZF (de)compress call
- ``serve.job.{queue_wait_s,run_s,total_s}`` — daemon job latencies
  (queued→running, running→terminal, submit→terminal)
"""

import bisect
import math
import threading

# ---------------------------------------------------------------------------
# histograms

#: Geometric bucket growth: 4 buckets per octave (~19% wide), deterministic
#: for a given value — the same observation always lands in the same bucket
#: on every platform, so summaries are reproducible across runs.
HIST_GROWTH = 2.0 ** 0.25
#: Lowest bucket upper edge (1 µs) and bucket count: edges span ~1 µs to
#: ~1e6 s, far past any real latency; values beyond either end clamp to the
#: boundary buckets.
HIST_MIN = 1e-6
HIST_BUCKETS = 164

#: Inclusive upper edges of every bucket, precomputed once.
HIST_EDGES = tuple(HIST_MIN * HIST_GROWTH ** i for i in range(HIST_BUCKETS))


class Histogram:
    """Deterministic log-bucketed latency histogram.

    Observations land in geometric buckets (:data:`HIST_EDGES`); quantiles
    are read as the upper edge of the bucket holding the quantile rank,
    clamped to the exact observed max — so ``p50 <= p90 <= p99 <= max``
    holds by construction and the error of any quantile is bounded by one
    bucket width (~19%). Not thread-safe on its own; the owning
    :class:`MetricsRegistry` serializes access."""

    __slots__ = ("count", "total", "max", "_buckets")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self._buckets = {}  # bucket index -> observation count (sparse)

    @staticmethod
    def bucket_index(value: float) -> int:
        """The (deterministic) bucket a value lands in."""
        idx = bisect.bisect_left(HIST_EDGES, float(value))
        return min(idx, HIST_BUCKETS - 1)

    def observe(self, value) -> None:
        v = float(value)
        if v < 0.0 or math.isnan(v):
            return  # a backwards clock must not poison the distribution
        idx = self.bucket_index(v)
        self._buckets[idx] = self._buckets.get(idx, 0) + 1
        self.count += 1
        self.total += v
        if v > self.max:
            self.max = v

    def merge(self, other: "Histogram") -> None:
        """Fold another histogram in (scope-exit publishing, shard joins)."""
        for idx, n in other._buckets.items():
            self._buckets[idx] = self._buckets.get(idx, 0) + n
        self.count += other.count
        self.total += other.total
        if other.max > self.max:
            self.max = other.max

    def copy(self) -> "Histogram":
        h = Histogram()
        h.count = self.count
        h.total = self.total
        h.max = self.max
        h._buckets = dict(self._buckets)
        return h

    def quantile(self, q: float) -> float:
        """The q-quantile (0 < q <= 1) as a bucket upper edge, clamped to
        the observed max."""
        if not self.count:
            return 0.0
        rank = q * self.count
        seen = 0
        for idx in sorted(self._buckets):
            seen += self._buckets[idx]
            if seen >= rank:
                return min(HIST_EDGES[idx], self.max)
        return self.max

    def buckets(self):
        """``[(upper_edge_s, cumulative_count), ...]`` over non-empty
        buckets, cumulative — the Prometheus ``le`` series shape."""
        out = []
        seen = 0
        for idx in sorted(self._buckets):
            seen += self._buckets[idx]
            out.append((HIST_EDGES[idx], seen))
        return out

    def summary(self) -> dict:
        """The run-report summary: count, sum, p50/p90/p99, max."""
        return {
            "count": self.count,
            "sum": round(self.total, 6),
            "p50": round(self.quantile(0.50), 6),
            "p90": round(self.quantile(0.90), 6),
            "p99": round(self.quantile(0.99), 6),
            "max": round(self.max, 6),
        }


class MetricsRegistry:
    """Thread-safe flat registry of numeric metrics under dotted names."""

    def __init__(self):
        self._lock = threading.Lock()
        self._values = {}
        self._hists = {}  # dotted name -> Histogram

    def inc(self, name: str, n=1):
        """Add ``n`` to a counter (creating it at 0)."""
        with self._lock:
            self._values[name] = self._values.get(name, 0) + n

    def set(self, name: str, value):
        """Set a gauge to ``value`` (last write wins)."""
        with self._lock:
            self._values[name] = value

    def max(self, name: str, value):
        """Raise a high-water-mark gauge to ``value`` if larger."""
        with self._lock:
            if value > self._values.get(name, value - 1):
                self._values[name] = value

    def update(self, mapping, prefix: str = ""):
        """Fold a ``{name: number}`` mapping in under an optional prefix.

        Numeric values accumulate (so two pipeline stages or two CLI
        sub-stages of one chained command sum rather than clobber);
        non-numeric values overwrite."""
        p = prefix + "." if prefix and not prefix.endswith(".") else prefix
        with self._lock:
            for k, v in mapping.items():
                key = p + k
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    self._values[key] = v
                else:
                    self._values[key] = self._values.get(key, 0) + v

    def get(self, name: str, default=None):
        with self._lock:
            return self._values.get(name, default)

    def observe(self, name: str, value) -> None:
        """Record one latency observation into the named histogram
        (created on first use)."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.observe(value)

    def histogram(self, name: str):
        """A copy of one histogram, or None."""
        with self._lock:
            h = self._hists.get(name)
            return h.copy() if h is not None else None

    def histograms(self) -> dict:
        """Name-sorted ``{name: Histogram}`` copies of every histogram."""
        with self._lock:
            return {k: self._hists[k].copy() for k in sorted(self._hists)}

    def summaries(self) -> dict:
        """Name-sorted ``{name: {count,sum,p50,p90,p99,max}}`` — the run
        report's ``latency`` section."""
        with self._lock:
            return {k: self._hists[k].summary() for k in sorted(self._hists)}

    def merge_histograms(self, hists: dict) -> None:
        """Fold ``{name: Histogram}`` in (scope-exit publishing: the
        process-global registry accumulates every finished scope's
        distributions, which is exactly the cumulative-since-start view a
        long-lived daemon's /metrics endpoint wants)."""
        with self._lock:
            for name, h in hists.items():
                mine = self._hists.get(name)
                if mine is None:
                    self._hists[name] = h.copy()
                else:
                    mine.merge(h)

    def snapshot(self) -> dict:
        """Name-sorted copy of every metric."""
        with self._lock:
            return dict(sorted(self._values.items()))

    def reset(self):
        with self._lock:
            self._values.clear()
            self._hists.clear()

    def replace(self, mapping: dict):
        """Overwrite this registry's counter/gauge content (scope
        publishing; histograms merge separately via
        :meth:`merge_histograms`)."""
        with self._lock:
            self._values = dict(mapping)


#: Fallback registry used when no telemetry scope is active (library use,
#: tests, plain single-command CLI runs).
_GLOBAL_REGISTRY = MetricsRegistry()


def current_registry() -> MetricsRegistry:
    """The registry writes should land in: the active scope's (observe.scope)
    when one is entered — one per daemon job / top-level command — else the
    process-global fallback."""
    from .scope import current_scope

    scope = current_scope()
    return scope.metrics if scope is not None else _GLOBAL_REGISTRY


class _RegistryProxy:
    """Drop-in stand-in for the old module-global registry: every call
    resolves the active scope first, so ``from ..observe.metrics import
    METRICS`` keeps working at every existing fold site while two scoped
    jobs in one process stay isolated."""

    __slots__ = ()

    def inc(self, name: str, n=1):
        current_registry().inc(name, n)

    def set(self, name: str, value):
        current_registry().set(name, value)

    def max(self, name: str, value):
        current_registry().max(name, value)

    def update(self, mapping, prefix: str = ""):
        current_registry().update(mapping, prefix)

    def get(self, name: str, default=None):
        return current_registry().get(name, default)

    def observe(self, name: str, value):
        current_registry().observe(name, value)

    def histogram(self, name: str):
        return current_registry().histogram(name)

    def histograms(self) -> dict:
        return current_registry().histograms()

    def summaries(self) -> dict:
        return current_registry().summaries()

    def snapshot(self) -> dict:
        return current_registry().snapshot()

    def reset(self):
        current_registry().reset()


#: The registry every component folds into (scope-resolving proxy).
METRICS = _RegistryProxy()


def record_stage_times(stats) -> None:
    """Fold a :class:`fgumi_tpu.pipeline.StageTimes` into :data:`METRICS`.

    Called once per run_stages completion (success or failure path), so
    every command that ran a pipeline contributes its per-stage busy/blocked
    seconds and queue-occupancy statistics to the run report."""
    for stage, dt in stats.busy.items():
        METRICS.inc(f"pipeline.stage.{stage}.busy_s", round(dt, 6))
    for stage, dt in stats.blocked.items():
        METRICS.inc(f"pipeline.stage.{stage}.blocked_s", round(dt, 6))
    if stats.q_samples:
        METRICS.inc("pipeline.queue.samples", stats.q_samples)
        METRICS.inc("pipeline.queue.in.sum", stats.q_in_sum)
        METRICS.inc("pipeline.queue.out.sum", stats.q_out_sum)
        METRICS.max("pipeline.queue.in.max", stats.q_in_max)
        METRICS.max("pipeline.queue.out.max", stats.q_out_max)
    peak = getattr(stats, "peak_in_flight_bytes", None)
    if peak:
        METRICS.max("pipeline.peak_in_flight_bytes", peak)
