"""Job-scoped telemetry: per-command registries resolved through a contextvar.

PR 2 gave every top-level CLI command clean counters by *resetting* the
process-global ``METRICS``/``DEVICE_STATS`` singletons at command entry.
That is correct for one command at a time but wrong the moment two commands
share a process concurrently — the serve daemon runs jobs on a worker pool,
and one job's reset would zero a neighbour's live counters mid-run.

This module replaces the reset with scoping: a :class:`TelemetryScope`
bundles one ``MetricsRegistry``, one ``DeviceStats``, and (optionally) one
tracer, and a :data:`contextvars.ContextVar` names the active scope. The
singletons in ``observe.metrics`` / ``ops.kernel`` / ``observe.trace``
become thin proxies that resolve the active scope on every call and fall
back to the old process-global objects when none is active — so library
users, tests, and single-command CLI runs see exactly the old behaviour,
while the daemon gets per-job isolation by entering one scope per job.

Contextvars do not cross ``threading.Thread`` boundaries on their own, so
every helper thread that contributes telemetry (pipeline reader/writer/
workers, BGZF prefetch, the device feeder, the heartbeat) is spawned
through :func:`spawn_thread` / a captured :func:`contextvars.copy_context`
— a job's counters follow its whole thread tree, not just the submitting
thread.
"""

import contextvars
import threading

_SCOPE = contextvars.ContextVar("fgumi_tpu_telemetry_scope", default=None)
#: Effective command line (argv list) override for output provenance (@PG
#: CL lines). The serve daemon sets this to the *client's* command line so a
#: job's outputs are byte-identical to the same command run standalone.
_ARGV = contextvars.ContextVar("fgumi_tpu_command_argv", default=None)
#: Pending job context for the NEXT telemetry scope created underneath: the
#: serve daemon re-enters ``cli.main`` per job, and main() builds the job's
#: scope itself — this is how the daemon hands the job id, the propagated
#: W3C-style trace context, and the upstream hop timestamps across that
#: re-entry (same pattern as :class:`command_argv`).
_JOB_CTX = contextvars.ContextVar("fgumi_tpu_job_context", default=None)


class TelemetryScope:
    """One command's telemetry world: metrics + device stats + tracer.

    Registries are created lazily: the ``DeviceStats`` in particular lives
    in ``ops.kernel`` and is only materialized when a kernel actually
    touches it, so numpy-free commands never pay that import."""

    __slots__ = ("label", "metrics", "tracer", "_device_stats", "_lock",
                 "trace_id", "parent_span_id", "job_id", "hops")

    def __init__(self, label: str = None):
        from .metrics import MetricsRegistry

        self.label = label
        self.metrics = MetricsRegistry()
        self.tracer = None  # set by trace.start_trace inside the scope
        self._device_stats = None
        self._lock = threading.Lock()
        #: fleet trace context (W3C-style ids propagated over the serve
        #: protocol): set by the daemon before running a job so the run
        #: report, the per-job trace, and every flight dump written inside
        #: this scope carry the client-visible correlation ids
        self.trace_id = None
        self.parent_span_id = None
        self.job_id = None
        #: upstream hop wall-clock timestamps for end-to-end latency
        #: attribution (client_sent_unix / balancer_recv_unix /
        #: balancer_sent_unix / admitted_unix / started_unix as available)
        self.hops = None

    def device_stats(self, factory):
        """This scope's DeviceStats, created on first use via ``factory``
        (the class object, passed in to avoid an import cycle with
        ops.kernel)."""
        with self._lock:
            if self._device_stats is None:
                self._device_stats = factory()
            return self._device_stats

    def device_stats_if_any(self):
        with self._lock:
            return self._device_stats


def current_scope():
    """The active :class:`TelemetryScope`, or None (process-global mode)."""
    return _SCOPE.get()


class scoped_telemetry:
    """Context manager entering a fresh (or given) telemetry scope.

    ``with scoped_telemetry("simplex"):`` gives the body — and every thread
    it spawns through :func:`spawn_thread` — its own metrics/device/trace
    registries, isolated from any other scope and from the process globals.
    """

    def __init__(self, label: str = None, scope: TelemetryScope = None):
        self.scope = scope if scope is not None else TelemetryScope(label)
        self._token = None

    def __enter__(self):
        self._token = _SCOPE.set(self.scope)
        return self.scope

    def __exit__(self, *exc):
        _SCOPE.reset(self._token)
        return False


class command_argv:
    """Context manager overriding the provenance command line (@PG CL).

    Outputs written inside the context record ``" ".join(argv)`` instead of
    the process's ``sys.argv`` — how a daemon job reproduces the exact
    header bytes of a standalone invocation."""

    def __init__(self, argv):
        self._argv = list(argv)
        self._token = None

    def __enter__(self):
        self._token = _ARGV.set(self._argv)
        return self._argv

    def __exit__(self, *exc):
        _ARGV.reset(self._token)
        return False


class job_context:
    """Context manager naming the fleet job context for scopes created
    inside it (the serve daemon wraps each job's ``cli.main`` re-entry).

    ``trace_id``/``parent_span_id`` are the propagated W3C-style ids (or
    None), ``hops`` the upstream wall-clock timestamps for end-to-end
    latency attribution (``client_sent_unix`` / ``balancer_recv_unix`` /
    ``balancer_sent_unix`` / ``admitted_unix`` / ``started_unix``)."""

    def __init__(self, job_id: str = None, trace_id: str = None,
                 parent_span_id: str = None, hops: dict = None):
        self._ctx = {"job_id": job_id, "trace_id": trace_id,
                     "parent_span_id": parent_span_id,
                     "hops": dict(hops) if hops else None}
        self._token = None

    def __enter__(self):
        self._token = _JOB_CTX.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        _JOB_CTX.reset(self._token)
        return False


def adopt_job_context(scope: TelemetryScope):
    """Stamp any pending :class:`job_context` onto a fresh scope (called
    by ``cli.main`` right after it creates the per-command scope)."""
    ctx = _JOB_CTX.get()
    if ctx is None:
        return
    scope.job_id = ctx["job_id"]
    scope.trace_id = ctx["trace_id"]
    scope.parent_span_id = ctx["parent_span_id"]
    scope.hops = ctx["hops"]


def current_argv():
    """The effective command line for provenance: the override set by
    :class:`command_argv` when inside one, else ``sys.argv``."""
    override = _ARGV.get()
    if override is not None:
        return override
    import sys

    return sys.argv


def publish_to_global(scope: TelemetryScope):
    """Copy a finished scope's counters onto the process-global fallbacks.

    The CLI calls this as each top-level command exits so the legacy
    inspection surface — ``METRICS`` / ``DEVICE_STATS`` read *after*
    ``cli_main`` returns by bench harnesses, probes, and tests — shows the
    finished command's numbers exactly as the old reset-at-entry globals
    did. Concurrent daemon jobs race here by design (last finisher wins):
    the per-job truth lives in each job's own scope and run report."""
    from . import metrics as _metrics

    _metrics._GLOBAL_REGISTRY.replace(scope.metrics.snapshot())
    # histograms MERGE instead of replacing: the global surface is the
    # cumulative-since-process-start view (Prometheus semantics — the serve
    # daemon's /metrics endpoint reads it), while each scope's run report
    # still carries only its own distributions
    _metrics._GLOBAL_REGISTRY.merge_histograms(scope.metrics.histograms())
    import sys

    kern = sys.modules.get("fgumi_tpu.ops.kernel")
    if kern is not None:
        stats = scope.device_stats_if_any()
        if stats is not None:
            kern._GLOBAL_DEVICE_STATS.load_from(stats)
        else:
            # the command never touched the device: the legacy surface must
            # read zero, exactly like the old reset-at-entry did — leaving a
            # previous command's dispatches visible would misattribute them
            kern._GLOBAL_DEVICE_STATS.reset()


def spawn_thread(target, *, name=None, daemon=True, args=()):
    """A ``threading.Thread`` whose target runs in a copy of the caller's
    context — the one-line way to keep a job's telemetry scope attached to
    its helper threads. Returned un-started (call ``.start()``)."""
    ctx = contextvars.copy_context()
    return threading.Thread(target=lambda: ctx.run(target, *args),
                            name=name, daemon=daemon)
