"""Schema-versioned machine-readable run report.

One JSON artifact per command (``--run-report out.json`` /
``FGUMI_TPU_RUN_REPORT``), committed atomically via ``utils/atomic`` at
command exit — success or failure — so a benchmark harness or CI gate can
answer "where did the time go, and did the device degrade?" without parsing
logs: wall time, per-stage busy/blocked seconds, queue occupancy mean/max,
device dispatches/retries/batch-splits/host-fallbacks, upload-pipeline
overlap + constant-cache traffic (``device.upload_overlap_s``,
``device.const_*``, ``device.shape_bucket.*`` — the data-path counters
``tools/perf_smoke.py`` gates on), bytes in/out, records processed, and
exit status.

The schema is versioned (:data:`SCHEMA_VERSION`) and validated structurally
by :func:`validate_report` — the same function the golden-file test and
``tools/telemetry_smoke.py`` gate on, so the shape cannot drift silently.
"""

import json
import os
import sys
import time

#: v2 (ISSUE 9): adds the optional ``latency`` section — per-histogram
#: ``{count, sum, p50, p90, p99, max}`` summaries from the latency
#: histograms (observe/metrics.py) — and optional ``flight_dumps`` (paths
#: of black boxes the flight recorder wrote during the run).
#: v3 (ISSUE 11): the ``device`` section may carry the device-resident
#: pipeline counters — ``donated_uploads``, ``resident_bytes_peak`` (+
#: live ``resident_bytes`` when nonzero at exit), and the routing
#: snapshot's ``filter_keep_rate`` — and the latency section gains the
#: ``device.dispatch.fetch_bytes`` histogram, making the fused-filter
#: bytes-fetched claim machine-readable from any run.
#: v4 (ISSUE 14): optional ``audit`` section — the silent-corruption
#: sentinel's scoreboard (sampled/clean/divergent/dropped counts,
#: per-device attribution map, bounded ``divergence`` evidence records
#: carrying both result buffers' sha256 digests, and the
#: ``--audit-output`` pre-commit verification verdicts). A run whose
#: ``audit.divergence`` is non-empty produced at least one device result
#: the f64 oracle refutes — callers must treat that output as suspect
#: (in sampled mode the corrupt batch was already consumed).
#: v5 (ISSUE 17): optional ``trace_context`` (the fleet trace id / parent
#: span / job id this run executed under, when it was a routed serve job),
#: ``latency_decomposition`` (end-to-end attribution of where the time
#: went — client->balancer, balancer->admit, queue, coalesce hold, device,
#: commit, host-complete residual — components never summing past
#: ``total_s``), and ``xla_profile_dir`` (the --xla-profile capture
#: directory, when one was taken).
#: v6 (ISSUE 19): the ``device`` section may carry the kernel-backend
#: counters ``kernel_pallas`` / ``kernel_xla`` (wire dispatches executed
#: by the hand-tiled Pallas kernel vs the XLA-lowered oracle; absent when
#: no wire dispatch ran), and DeviceStats timeline entries (flight dumps,
#: ``--stats`` report) gain a per-dispatch ``kernel_backend`` stamp.
#: v7 (ISSUE 20): ``device.routing`` gains ``prior_source`` ("cold" /
#: "profile" / "snapshot" — where the cost model's starting EWMAs came
#: from, so first-batch routing is attributable), the metrics section may
#: carry ``tune.*`` gauges, and the optional top-level ``profile``
#: section records the applied deployment profile (path, knobs applied /
#: skipped by explicit overrides, fingerprint mismatches, whether router
#: priors were seeded — tune/profile.py).
SCHEMA_VERSION = 7


def _device_stats():
    """The module-wide DeviceStats, or None when ops.kernel was never
    imported this run — an unimported kernel has nothing to report, and
    importing it here would tax numpy-free commands (sort, fastq, ...)
    with the kernel import at exit. getattr-with-default also covers a
    *partially initialized* module: the heartbeat thread can observe
    sys.modules mid-import while another stage thread (fused chain,
    serve job) is still executing the kernel module body."""
    kern = sys.modules.get("fgumi_tpu.ops.kernel")
    return getattr(kern, "DEVICE_STATS", None)

#: Structural schema: top-level field -> required type (None = any JSON).
#: Sections marked optional may be absent when the command produced no such
#: activity (e.g. no device dispatch, no threaded pipeline).
_REQUIRED = {
    "schema_version": int,
    "tool": str,
    "command": str,
    "argv": list,
    "started_unix": (int, float),
    "wall_s": (int, float),
    "exit_status": int,
    "pid": int,
    "metrics": dict,
}
_OPTIONAL = {
    "stages": dict,     # stage -> {"busy_s": f, "blocked_s": f}
    "queues": dict,     # {"in_mean","in_max","out_mean","out_max","samples"}
    "device": dict,     # DeviceStats.snapshot()
    "io": dict,         # {"bytes_read","bytes_written"}
    "records": dict,    # progress label -> count
    "faults": dict,     # fault point -> fired count
    "resource": dict,   # governor snapshot: pressure state, events
                        # (enospc/watermarks), budget rebalancing counters
                        # (utils/governor.py)
    "latency": dict,    # histogram name -> {count,sum,p50,p90,p99,max}
                        # (observe/metrics.py latency histograms; v2)
    "audit": dict,      # silent-corruption sentinel scoreboard + output
                        # verification verdicts (ops/sentinel.py; v4)
    "flight_dumps": list,  # black-box paths the flight recorder wrote
                           # during this run (observe/flight.py; v2)
    "trace_path": str,
    "hostname": str,
    "trace_context": dict,  # fleet trace id / parent span / job id this
                            # run executed under (observe/trace.py; v5)
    "latency_decomposition": dict,  # end-to-end attribution: hop/queue/
                                    # device/commit components + residual,
                                    # summing <= total_s (v5)
    "xla_profile_dir": str,  # --xla-profile capture directory (v5)
    "profile": dict,  # applied deployment profile: path, knobs applied/
                      # skipped_explicit, fingerprint mismatches, whether
                      # router priors were seeded (tune/profile.py; v7)
}

#: Components a ``latency_decomposition`` section may carry besides
#: ``total_s`` (any subset; what was measurable for this run).
_DECOMP_COMPONENTS = (
    "client_to_balancer_s", "balancer_to_admit_s", "client_to_admit_s",
    "queue_s", "coalesce_hold_s", "device_s", "commit_s",
    "host_complete_s",
)

#: Required numeric fields of one ``latency`` summary entry, in the order
#: the quantile-monotonicity check walks them.
_LATENCY_FIELDS = ("count", "sum", "p50", "p90", "p99", "max")

#: Required integer counters of the ``audit`` section (v4).
_AUDIT_COUNTERS = ("sampled", "clean", "divergent", "dropped")


def validate_report(obj) -> list:
    """Return a list of human-readable schema violations (empty == valid)."""
    errors = []
    if not isinstance(obj, dict):
        return ["report is not a JSON object"]
    for key, typ in _REQUIRED.items():
        if key not in obj:
            errors.append(f"missing required field {key!r}")
        elif not isinstance(obj[key], typ):
            errors.append(f"field {key!r} has type {type(obj[key]).__name__}")
    for key, typ in _OPTIONAL.items():
        if key in obj and not isinstance(obj[key], typ):
            errors.append(f"field {key!r} has type {type(obj[key]).__name__}")
    unknown = set(obj) - set(_REQUIRED) - set(_OPTIONAL)
    if unknown:
        errors.append(f"unknown fields: {sorted(unknown)}")
    if isinstance(obj.get("schema_version"), int) \
            and obj["schema_version"] != SCHEMA_VERSION:
        errors.append(f"schema_version {obj['schema_version']} != "
                      f"{SCHEMA_VERSION}")
    if isinstance(obj.get("metrics"), dict):
        for k in obj["metrics"]:
            if not isinstance(k, str) or not k:
                errors.append(f"metrics key {k!r} is not a dotted name")
    if isinstance(obj.get("latency"), dict):
        for name, summ in obj["latency"].items():
            if not isinstance(summ, dict):
                errors.append(f"latency entry {name!r} is not an object")
                continue
            missing = [f for f in _LATENCY_FIELDS if not isinstance(
                summ.get(f), (int, float)) or isinstance(summ.get(f), bool)]
            if missing:
                errors.append(f"latency entry {name!r} missing numeric "
                              f"fields {missing}")
                continue
            if not (summ["p50"] <= summ["p90"] <= summ["p99"]
                    <= summ["max"]):
                errors.append(f"latency entry {name!r} quantiles are not "
                              "ordered (p50 <= p90 <= p99 <= max)")
    if isinstance(obj.get("audit"), dict):
        audit = obj["audit"]
        for f in _AUDIT_COUNTERS:
            v = audit.get(f)
            if not isinstance(v, int) or isinstance(v, bool):
                errors.append(f"audit field {f!r} is not an integer")
        if audit.get("divergent", 0) and not audit.get("divergence"):
            errors.append("audit.divergent > 0 but no divergence records")
        if "divergence" in audit and not isinstance(audit["divergence"],
                                                    list):
            errors.append("audit.divergence is not a list")
        if "output" in audit and not isinstance(audit["output"], list):
            errors.append("audit.output is not a list")
        if "devices" in audit and not isinstance(audit["devices"], dict):
            errors.append("audit.devices is not an object")
    if isinstance(obj.get("trace_context"), dict):
        tc = obj["trace_context"]
        for f in ("trace_id", "parent_span_id", "job_id"):
            if f in tc and not isinstance(tc[f], str):
                errors.append(f"trace_context field {f!r} is not a string")
        unknown = set(tc) - {"trace_id", "parent_span_id", "job_id"}
        if unknown:
            errors.append(f"trace_context unknown fields {sorted(unknown)}")
    if isinstance(obj.get("latency_decomposition"), dict):
        dec = obj["latency_decomposition"]
        total = dec.get("total_s")
        if not isinstance(total, (int, float)) or isinstance(total, bool) \
                or total < 0:
            errors.append("latency_decomposition.total_s is not a "
                          "non-negative number")
            total = None
        comp_sum = 0.0
        for name, v in dec.items():
            if name == "total_s":
                continue
            if name not in _DECOMP_COMPONENTS:
                errors.append("latency_decomposition unknown component "
                              f"{name!r}")
            elif not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v < 0:
                errors.append(f"latency_decomposition component {name!r} "
                              "is not a non-negative number")
            else:
                comp_sum += v
        # the attribution invariant (small epsilon for per-field rounding)
        if total is not None and comp_sum > total + 0.005:
            errors.append("latency_decomposition components sum "
                          f"{comp_sum:.6f} past total_s {total:.6f}")
    return errors


def _stage_sections(metrics: dict):
    """Derive the stages/queues sections from the flat dotted metrics."""
    stages = {}
    for name, v in metrics.items():
        if name.startswith("pipeline.stage.") and name.count(".") >= 3:
            _, _, stage, field = name.split(".", 3)
            stages.setdefault(stage, {})[field] = v
    queues = None
    samples = metrics.get("pipeline.queue.samples")
    if samples:
        queues = {
            "samples": samples,
            "in_mean": round(metrics.get("pipeline.queue.in.sum", 0)
                             / samples, 3),
            "in_max": metrics.get("pipeline.queue.in.max", 0),
            "out_mean": round(metrics.get("pipeline.queue.out.sum", 0)
                              / samples, 3),
            "out_max": metrics.get("pipeline.queue.out.max", 0),
        }
    return stages, queues


def _latency_decomposition(latency: dict, wall_s: float, scope) -> dict:
    """The v5 end-to-end attribution: where did submit-to-bytes-published
    go? Hop legs come from the propagated wall-clock timestamps on the
    telemetry scope (client_sent / balancer_recv / balancer_sent /
    admitted / started — a fleet-routed job has all five, a direct submit
    three, a plain CLI run none); in-process components are histogram sums
    (coalesce hold, device wall, output commit); ``host_complete_s`` is
    the residual. ``total_s`` spans client send to now when the client
    stamped its send time, else the command wall.

    Components are CAPPED in order so they can never sum past ``total_s``
    — this section is an *attribution* of the total (shares), not a raw
    measurement (raw sums stay in ``latency``); host clock skew or
    overlapped device work therefore shrinks later components instead of
    fabricating > 100% accounting. None when nothing was measurable
    (no hops and no timed component)."""
    hops = dict(scope.hops) if scope is not None and scope.hops else {}

    def hist_sum(name):
        summ = latency.get(name)
        return float(summ["sum"]) if isinstance(summ, dict) else 0.0

    cs = hops.get("client_sent_unix")
    br = hops.get("balancer_recv_unix")
    bs = hops.get("balancer_sent_unix")
    ad = hops.get("admitted_unix")
    st = hops.get("started_unix")
    measured = []
    if cs and br:
        measured.append(("client_to_balancer_s", br - cs))
    if bs and ad:
        measured.append(("balancer_to_admit_s", ad - bs))
    elif cs and ad and not br:
        measured.append(("client_to_admit_s", ad - cs))
    if ad and st:
        measured.append(("queue_s", st - ad))
    measured.append(("coalesce_hold_s",
                     hist_sum("device.coalesce.window_wait_s")))
    measured.append(("device_s", hist_sum("device.dispatch.wall_s")))
    measured.append(("commit_s", hist_sum("io.commit_s")))
    if not hops and not any(v > 0 for _, v in measured):
        return None
    total = (time.time() - cs) if cs else float(wall_s)
    if total <= 0:  # client clock ahead of ours: fall back to our wall
        total = max(float(wall_s), 0.0)
    out = {"total_s": round(total, 6)}
    spent = 0.0
    for name, v in measured:
        v = min(max(float(v), 0.0), max(total - spent, 0.0))
        if v <= 0 and name in ("coalesce_hold_s", "device_s", "commit_s"):
            continue  # component never armed this run: omit, not zero
        out[name] = round(v, 6)
        spent += v
    out["host_complete_s"] = round(max(total - spent, 0.0), 6)
    return out


def build_report(command: str, argv, started_unix: float, wall_s: float,
                 exit_status: int, trace_path: str = None) -> dict:
    """Assemble the report dict from the global registries.

    Reads :data:`fgumi_tpu.observe.metrics.METRICS`, the module-wide
    ``DEVICE_STATS`` (when the kernel module is loaded), and the fault
    registry; pure read — folding raw counters into METRICS is each
    component's job."""
    from ..utils import faults
    from .metrics import METRICS

    metrics = METRICS.snapshot()
    report = {
        "schema_version": SCHEMA_VERSION,
        "tool": "fgumi-tpu",
        "command": command,
        "argv": list(argv),
        "started_unix": round(started_unix, 3),
        "wall_s": round(wall_s, 4),
        "exit_status": int(exit_status),
        "pid": os.getpid(),
        "metrics": metrics,
    }
    try:
        import socket

        report["hostname"] = socket.gethostname()
    except OSError:
        pass
    stages, queues = _stage_sections(metrics)
    if stages:
        report["stages"] = stages
    if queues:
        report["queues"] = queues
    stats = _device_stats()
    dev = stats.snapshot() if stats is not None else {}
    # active production mesh (parallel/mesh.py publish_mesh): the device
    # section names the (dp, sp, devices) shape so a sharded run's artifact
    # is distinguishable from a single-device one at a glance (ISSUE 10).
    # Keyed off THIS scope's gauges — the process-global snapshot alone
    # would leak one daemon job's mesh into every later job's report; it
    # only contributes the platform label when it matches.
    m_dp = metrics.get("device.mesh.dp")
    if m_dp:
        mesh_sec = {"dp": m_dp, "sp": metrics.get("device.mesh.sp", 1),
                    "devices": metrics.get("device.mesh.devices", m_dp)}
        pm = sys.modules.get("fgumi_tpu.parallel.mesh")
        snap = getattr(pm, "LAST_MESH_SNAPSHOT", None) if pm else None
        if snap and snap.get("dp") == m_dp:
            mesh_sec["platform"] = snap.get("platform")
        dev["mesh"] = mesh_sec
    # offload cost-model state (link/host EWMAs + last decision) rides
    # along whenever batches were routed, so a wrong crossover is
    # diagnosable from the report alone (ISSUE 6 satellite) — including
    # the all-host case, where dispatches stays 0 but route_host > 0,
    # and the seeded-but-idle case (v7: a profile/snapshot-seeded router
    # must stamp prior_source even before its first routed batch)
    router = sys.modules.get("fgumi_tpu.ops.router")
    if router is not None and (dev.get("route_device")
                               or dev.get("route_host")
                               or router.ROUTER.prior_source != "cold"):
        dev["routing"] = router.ROUTER.snapshot()
    # wedge circuit breaker (ops/breaker.py): anything beyond pristine
    # closed rides along, so a degraded run's artifact explains itself —
    # the ISSUE 7 acceptance reads device.breaker.state transitions +
    # deadline_fallbacks straight out of the report
    breaker = sys.modules.get("fgumi_tpu.ops.breaker")
    if breaker is not None:
        bsnap = breaker.BREAKER.snapshot()
        if bsnap["transitions"] or bsnap["state"] != "closed" \
                or bsnap["deadline_overruns"]:
            dev["breaker"] = bsnap
    if dev.get("dispatches") or dev.get("route_host") \
            or dev.get("breaker") or dev.get("mesh") or dev.get("routing"):
        report["device"] = dev
    io_sec = {k.split(".", 1)[1]: v for k, v in metrics.items()
              if k.startswith("io.")}
    if io_sec:
        report["io"] = io_sec
    records = {k.split(".", 1)[1]: v for k, v in metrics.items()
               if k.startswith("records.")}
    if records:
        report["records"] = records
    fired = {p: n for p, n in faults.snapshot().items() if n}
    if fired:
        report["faults"] = fired
    # resource governance: anything beyond a quiet run — a pressure
    # transition, an ENOSPC event, admission sheds, budget rebalancing —
    # rides along so a degraded or resource-failed run's artifact explains
    # itself (the ISSUE 8 acceptance reads the `resource` section straight
    # out of the report of an injected disk-full run)
    gov = sys.modules.get("fgumi_tpu.utils.governor")
    if gov is not None and gov.GOVERNOR.has_activity():
        report["resource"] = gov.GOVERNOR.snapshot()
    # silent-corruption sentinel (schema v4): anything beyond a quiet run
    # — sampled shadow audits, dropped samples, divergences, output-audit
    # verdicts — rides along, so an SDC-touched run's artifact names the
    # corrupt dispatch and which output to distrust (ops/sentinel.py)
    sentinel = sys.modules.get("fgumi_tpu.ops.sentinel")
    if sentinel is not None and sentinel.SENTINEL.has_activity():
        report["audit"] = sentinel.SENTINEL.snapshot()
    # latency histogram summaries (schema v2): every instrumented hot path
    # that observed at least one sample this run — the "how slow was the
    # tail" counterpart of the flat counters above
    latency = METRICS.summaries()
    if latency:
        report["latency"] = latency
    # fleet trace context + end-to-end attribution (schema v5): a daemon
    # job adopted its job id / trace context / hop timestamps onto the
    # telemetry scope at entry (observe/scope.py adopt_job_context); the
    # report is where they become a queryable artifact
    from .scope import current_scope

    scope = current_scope()
    if scope is not None and (scope.trace_id or scope.job_id):
        tc = {}
        if scope.trace_id:
            tc["trace_id"] = scope.trace_id
        if scope.parent_span_id:
            tc["parent_span_id"] = scope.parent_span_id
        if scope.job_id:
            tc["job_id"] = scope.job_id
        report["trace_context"] = tc
    decomposition = _latency_decomposition(latency, wall_s, scope)
    if decomposition:
        report["latency_decomposition"] = decomposition
    # black boxes written during this run (flight recorder): the report is
    # the breadcrumb from "this run degraded" to the full evidence file
    flight = sys.modules.get("fgumi_tpu.observe.flight")
    if flight is not None:
        dumps = flight.FLIGHT.dump_paths()
        if dumps:
            report["flight_dumps"] = dumps
    if trace_path:
        report["trace_path"] = trace_path
    # one-shot XLA device profile (--xla-profile): the capture directory
    # rides along so "device time regressed" links straight to the
    # op-level xprof timeline (observe/xprof.py; v5)
    xprof = sys.modules.get("fgumi_tpu.observe.xprof")
    if xprof is not None:
        captured = xprof.captured_dir()
        if captured:
            report["xla_profile_dir"] = captured
    # applied deployment profile (tune/profile.py; v7): which knobs the
    # profile filled vs explicit overrides, fingerprint mismatches, and
    # whether router priors were seeded — pairs with
    # device.routing.prior_source to make first-batch routing attributable
    tune_prof = sys.modules.get("fgumi_tpu.tune.profile")
    if tune_prof is not None:
        applied = tune_prof.applied_info()
        if applied:
            report["profile"] = {
                "path": applied["path"],
                "knobs_applied": list(applied["applied"]),
                "knobs_skipped_explicit":
                    list(applied["skipped_explicit"]),
                "fingerprint_mismatch":
                    list(applied["fingerprint_mismatch"]),
                "seeded_router": bool(applied["seeded_router"]),
                "seeded_choosers": list(applied["seeded_choosers"]),
            }
    return report


def write_report(path: str, report: dict):
    """Commit the report atomically (crash-safe like every other output)."""
    from ..utils.atomic import discard_output, open_output

    out = open_output(path, "w")
    try:
        json.dump(report, out, indent=1, sort_keys=False)
        out.write("\n")
    except BaseException:
        discard_output(out)
        raise
    out.close()


def emit(path: str, command: str, argv, started_unix: float, wall_s: float,
         exit_status: int, trace_path: str = None) -> dict:
    """Build + write in one step; never raises out of an exiting command
    (a telemetry failure must not turn a successful run into a failed one —
    it logs and returns None instead)."""
    import logging

    try:
        report = build_report(command, argv, started_unix, wall_s,
                              exit_status, trace_path)
        write_report(path, report)
        return report
    except Exception:
        logging.getLogger("fgumi_tpu").exception(
            "failed to write run report %s", path)
        return None


def fold_device_stats():
    """Fold the module-wide DeviceStats into METRICS under ``device.*``.

    Called once at command exit (before the report is built) so the flat
    metrics view carries the same numbers as the ``device`` section."""
    from .metrics import METRICS

    stats = _device_stats()
    snap = stats.snapshot() if stats is not None else {}
    if snap.get("dispatches"):
        METRICS.update(snap, prefix="device")
