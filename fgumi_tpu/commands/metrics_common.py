"""Shared infrastructure for the duplex-metrics / simplex-metrics commands.

Mirrors /root/reference/src/lib/commands/shared_metrics.rs: template streaming
from a grouped BAM, ReadInfoKey coordinate grouping, interval filtering
(BED / Picard interval list), deterministic Murmur3 downsampling scores, and
the 20-level downsampling fraction ladder.
"""

import logging
from dataclasses import dataclass
from typing import Optional

from ..core.template import iter_name_groups, unclipped_5prime
from ..io.bam import (FLAG_FIRST, FLAG_LAST, FLAG_PAIRED, FLAG_REVERSE,
                      FLAG_SECONDARY, FLAG_SUPPLEMENTARY, FLAG_UNMAPPED,
                      FLAG_MATE_UNMAPPED, BamReader, RawRecord)
from ..metrics import compute_hash_fraction

log = logging.getLogger("fgumi_tpu")

# 5%, 10%, ..., 100% (shared_metrics.rs:24-28)
DOWNSAMPLING_FRACTIONS = [round(0.05 * i, 2) for i in range(1, 21)]


@dataclass
class Interval:
    """0-based half-open genomic interval (shared_metrics.rs:33-42)."""

    ref_name: str
    start: int
    end: int


@dataclass
class TemplateInfo:
    """Per-template info for grouping + downsampling (shared_metrics.rs:45-62)."""

    mi: str
    rx: str
    ref_name: Optional[str]
    position: Optional[int]  # 1-based insert start
    end_position: Optional[int]  # 1-based inclusive insert end
    r1_positive: bool
    hash_fraction: float


@dataclass
class TemplateMetadata:
    """MI parsed into base UMI + strand (shared_metrics.rs:91-103, 434-448)."""

    template: TemplateInfo
    base_umi: str
    is_a_strand: bool
    is_b_strand: bool


def compute_template_metadata(group) -> list:
    out = []
    for t in group:
        if t.mi.endswith("/A"):
            out.append(TemplateMetadata(t, t.mi[:-2], True, False))
        elif t.mi.endswith("/B"):
            out.append(TemplateMetadata(t, t.mi[:-2], False, True))
        else:
            out.append(TemplateMetadata(t, t.mi, False, False))
    return out


def parse_intervals(path: str) -> list:
    """BED (0-based half-open) or Picard interval list (1-based closed),
    auto-detected by '@' header lines (shared_metrics.rs:213-272)."""
    intervals = []
    is_interval_list = False
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if line.startswith("@"):
                is_interval_list = True
                continue
            parts = line.split("\t", 3)
            if len(parts) < 3:
                raise ValueError(
                    f"Invalid {'interval list' if is_interval_list else 'BED'} "
                    f"line (needs at least 3 fields): {line}")
            ref_name, start_s, end_s = parts[0], parts[1], parts[2]
            start = int(start_s)
            end = int(end_s)
            if is_interval_list:
                start -= 1  # 1-based closed -> 0-based half-open
            intervals.append(Interval(ref_name, start, end))
    return intervals


def overlaps_intervals(template: TemplateInfo, intervals: list) -> bool:
    """Insert-vs-interval overlap (shared_metrics.rs:276-303)."""
    if not intervals:
        return True
    if template.ref_name is None or template.position is None \
            or template.end_position is None:
        return False
    start, end = template.position, template.end_position
    return any(iv.ref_name == template.ref_name
               and start <= iv.end and iv.start < end
               for iv in intervals)


def validate_not_consensus_bam(path: str):
    """Reject consensus BAM input by checking the first primary paired R1 for
    consensus tags (shared_metrics.rs:316-360)."""
    with BamReader(path) as reader:
        for rec in reader:
            flg = rec.flag
            if not flg & FLAG_PAIRED or not flg & FLAG_FIRST:
                continue
            if flg & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY):
                continue
            for tag in (b"cD", b"cM", b"cE", b"aD", b"bD"):
                if rec.find_tag(tag) is not None:
                    raise ValueError(
                        "input appears to be a consensus BAM (found "
                        f"{tag.decode()} tag); metrics tools take grouped raw "
                        "reads, not consensus output")
            return


def _template_filter(rec: RawRecord, want_first: bool) -> bool:
    """fgbio R1/R2 filter: paired, both mapped, primary (shared_metrics.rs:499-509)."""
    flg = rec.flag
    seg = FLAG_FIRST if want_first else FLAG_LAST
    return bool(flg & FLAG_PAIRED) and not flg & FLAG_UNMAPPED \
        and not flg & FLAG_MATE_UNMAPPED and bool(flg & seg) \
        and not flg & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY)


def _library_index(header_text: str) -> dict:
    """RG id -> LB library string from the header (read_info.rs LibraryIndex)."""
    out = {}
    for line in header_text.splitlines():
        if not line.startswith("@RG"):
            continue
        rg_id = lb = None
        for field in line.split("\t")[1:]:
            if field.startswith("ID:"):
                rg_id = field[3:]
            elif field.startswith("LB:"):
                lb = field[3:]
        if rg_id is not None:
            out[rg_id] = lb or ""
    return out


def process_templates_from_bam(path: str, intervals: list, num_fractions: int,
                               process_group):
    """Stream templates, group by ReadInfo coordinate key, dispatch each group.

    `process_group(group: [TemplateInfo], fraction_counts: [int])` is called
    once per coordinate group. Returns (total_templates, fraction_counts).
    Mirrors shared_metrics.rs:473-620.
    """
    total = 0
    fraction_counts = [0] * num_fractions
    with BamReader(path) as reader:
        libraries = _library_index(reader.header.text)
        ref_names = reader.header.ref_names
        current_key = None
        current_group = []

        for _name, records in iter_name_groups(reader):
            if len(records) < 2:
                continue
            r1 = next((r for r in records if _template_filter(r, True)), None)
            r2 = next((r for r in records if _template_filter(r, False)), None)
            if r1 is None or r2 is None:
                continue
            mi = r1.get_str(b"MI")
            rx = r1.get_str(b"RX")
            if mi is None or rx is None:
                missing = "MI" if mi is None else "RX"
                raise ValueError(
                    f"record {r1.name!r} missing required {missing} tag")
            if r1.ref_id < 0 or r2.ref_id < 0:
                continue

            s1, s2 = unclipped_5prime(r1), unclipped_5prime(r2)
            strand1 = bool(r1.flag & FLAG_REVERSE)
            strand2 = bool(r2.flag & FLAG_REVERSE)
            rg = r1.get_str(b"RG")
            library = libraries.get(rg, "") if rg else ""
            cb = r1.get_str(b"CB")

            # order the two ends so the earlier-mapping one comes first
            end1 = (r1.ref_id, s1, strand1)
            end2 = (r2.ref_id, s2, strand2)
            key = (*min(end1, end2), *max(end1, end2), library, cb)

            same_ref = r1.ref_id == r2.ref_id
            r1_start, r2_start = r1.pos + 1, r2.pos + 1
            r1_end = r1.pos + r1.reference_length()
            r2_end = r2.pos + r2.reference_length()
            if same_ref:
                position = min(r1_start, r2_start)
                end_position = max(r1_end, r2_end)
            else:
                position, end_position = r1_start, r1_end

            info = TemplateInfo(
                mi=mi, rx=rx,
                ref_name=ref_names[r1.ref_id] if r1.ref_id < len(ref_names) else None,
                position=position, end_position=end_position,
                r1_positive=not strand1,
                hash_fraction=compute_hash_fraction(r1.name.decode()),
            )
            if not overlaps_intervals(info, intervals):
                continue
            total += 1

            if key != current_key:
                if current_group:
                    process_group(current_group, fraction_counts)
                current_key = key
                current_group = []
            current_group.append(info)

        if current_group:
            process_group(current_group, fraction_counts)
    return total, fraction_counts
