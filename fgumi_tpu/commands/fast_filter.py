"""Vectorized consensus-filter path over RecordBatch inputs.

The filter-command analog of consensus/fast.py: read-level thresholds,
per-base mask computation (cd/ce and ad/ae/bd/be tag matrices gathered
natively), in-place N/Q2 masking, the no-call check, and template verdicts
all run as whole-batch array passes; kept/rejected records emit as
contiguous slices of the (mutated in place) batch buffer.

Semantics contract: identical output records, statistics, and rejection
reasons to commands/filter.py::run_filter on the same stream (tested in
tests/test_fast_filter.py). Engages only for the configurations the arrays
can express: no reference (unmapped-only input, enforced with the same
error as the classic path), no per-base tag reversal, no single-strand
agreement check. An unexpected per-base tag subtype anywhere in the input
aborts the fast pass and the command re-runs entirely on the classic
per-record engine (cli.py catches _OddSubtype before any output commits
beyond what the rerun rewrites).
"""

import numpy as np

from ..consensus.filter import (EXCESSIVE_ERROR_RATE, INSUFFICIENT_READS,
                                LOW_QUALITY, PASS, TOO_MANY_NO_CALLS,
                                FilterConfig, duplex_base_mask_arrays,
                                simplex_base_mask_arrays)
from ..io.bam import FLAG_SECONDARY, FLAG_SUPPLEMENTARY, FLAG_UNMAPPED
from ..native import batch as nb
from .filter import FilterStats, _process_one

_R_PASS, _R_INSUF, _R_ERR, _R_LOWQ, _R_NOCALL = 0, 1, 2, 3, 4
_RESULT_STR = {_R_PASS: PASS, _R_INSUF: INSUFFICIENT_READS,
               _R_ERR: EXCESSIVE_ERROR_RATE, _R_LOWQ: LOW_QUALITY,
               _R_NOCALL: TOO_MANY_NO_CALLS}
_INT_TYPES = (("c", 1, True), ("C", 1, False), ("s", 2, True),
              ("S", 2, False), ("i", 4, True), ("I", 4, False))


def int_tag_values(batch, tag: bytes):
    """(values int64[n], present bool[n]) for an integer-typed tag
    (RawRecord.get_int semantics: non-integer types read as absent)."""
    vo, vl, vt = batch.tag_locs(tag)
    buf = batch.buf
    val = np.zeros(batch.n, dtype=np.int64)
    present = np.zeros(batch.n, dtype=bool)
    for code, width, signed in _INT_TYPES:
        m = (vt == ord(code)) & (vo >= 0)
        if not m.any():
            continue
        offs = vo[m]
        v = np.zeros(len(offs), dtype=np.int64)
        for j in range(width):
            v |= buf[offs + j].astype(np.int64) << (8 * j)
        if signed:
            sign_bit = np.int64(1) << (8 * width - 1)
            v = (v ^ sign_bit) - sign_bit
        val[m] = v
        present |= m
    return val, present


def float_tag_values(batch, tag: bytes):
    """(values float64[n], present bool[n]) for an f-typed tag."""
    vo, vl, vt = batch.tag_locs(tag)
    buf = batch.buf
    val = np.zeros(batch.n, dtype=np.float64)
    m = (vt == ord("f")) & (vo >= 0)
    if m.any():
        offs = vo[m]
        raw = np.zeros(len(offs), dtype=np.uint32)
        for j in range(4):
            raw |= buf[offs + j].astype(np.uint32) << (8 * j)
        val[m] = raw.view(np.float32).astype(np.float64)
    return val, m


class FastFilter:
    """Batch filter engine. Feed RecordBatches; collect wire chunks."""

    def __init__(self, config: FilterConfig, *, filter_by_template=True):
        self.config = config
        self.filter_by_template = filter_by_template
        self.stats = FilterStats()
        self._carry = []        # (record bytes,) of the open name group

    def process_batch(self, batch, emit, emit_reject):
        """Filter one batch; emit(buf_slice_bytes) per kept wire chunk."""
        n = batch.n
        if n == 0:
            return
        buf = batch.buf
        if ((batch.flag & FLAG_UNMAPPED) == 0).any():
            raise ValueError(
                "--ref is required when filtering mapped reads to keep "
                "NM/UQ/MD tags consistent")

        # name-group bounds; the last group may continue into the next batch
        name_off = batch.data_off + 32
        name_len = (batch.l_read_name - 1).astype(np.int32)
        tstarts = nb.group_starts(buf, np.ascontiguousarray(name_off),
                                  name_len)
        tbounds = np.append(tstarts, n)
        nT = len(tbounds) - 1

        # merge a split name group into the carry
        t0 = 0
        if self._carry and buf[name_off[0]:name_off[0] + name_len[0]] \
                .tobytes() == self._carry_name:
            self._carry.extend(
                bytes(buf[batch.data_off[i]:batch.data_end[i]])
                for i in range(tbounds[0], tbounds[1]))
            t0 = 1
        if t0 >= nT:
            return  # the whole batch merged into the (still open) carry
        if self._carry:
            self._emit_carry(emit, emit_reject)

        # hold back the last (possibly split) name group; filter the rest
        lo, hi = int(tbounds[t0]), int(tbounds[nT - 1])
        if hi > lo:
            rows = np.arange(lo, hi)
            self._filter_rows(batch, rows, tbounds[t0:nT].astype(np.int64),
                              emit, emit_reject)
        self._carry = [bytes(buf[batch.data_off[i]:batch.data_end[i]])
                       for i in range(tbounds[nT - 1], tbounds[nT])]
        self._carry_name = buf[
            name_off[tbounds[nT - 1]]:name_off[tbounds[nT - 1]]
            + name_len[tbounds[nT - 1]]].tobytes()

    def _filter_rows(self, batch, rows, tbounds, emit, emit_reject):
        cfg = self.config
        buf = batch.buf
        n = len(rows)
        lo = rows[0]
        # every tag this pass reads, one native aux scan for all of them
        batch.prefetch_tags([b"cD", b"cE", b"aD", b"aM", b"bD", b"bM",
                             b"aE", b"bE", b"cd", b"ce", b"ad", b"ae",
                             b"bd", b"be"])
        l_seq = batch.l_seq[rows].astype(np.int64)
        L = max(int(l_seq.max()), 1) if n else 1

        cD, cD_p = int_tag_values(batch, b"cD")
        cE, cE_p = float_tag_values(batch, b"cE")
        cD, cD_p, cE, cE_p = cD[rows], cD_p[rows], cE[rows], cE_p[rows]
        if not (cD_p.all() and cE_p.all()):
            raise ValueError(
                "read does not appear to have consensus calling tags (cD/cE) "
                "present; filter requires reads produced by consensus calling")
        aD, aD_p = int_tag_values(batch, b"aD")
        aM, aM_p = int_tag_values(batch, b"aM")
        bD, bD_p = int_tag_values(batch, b"bD")
        bM, bM_p = int_tag_values(batch, b"bM")
        aE, aE_p = float_tag_values(batch, b"aE")
        bE, bE_p = float_tag_values(batch, b"bE")
        # duplex detection is by tag PRESENCE of any type
        # (is_duplex_consensus / find_tag), not integer-typedness
        aD_vo = batch.tag_locs(b"aD")[0]
        bD_vo = batch.tag_locs(b"bD")[0]
        duplex = (aD_vo[rows] >= 0) & (bD_vo[rows] >= 0)

        # ---- read-level verdicts (filter_read / filter_duplex_read)
        res = np.full(n, _R_PASS, dtype=np.int8)
        t = cfg.single_strand
        cc = cfg.cc
        thr_min = np.where(duplex, cc.min_reads, t.min_reads)
        thr_err = np.where(duplex, cc.max_read_error_rate,
                           t.max_read_error_rate)
        res[(res == _R_PASS) & (cE > thr_err)] = _R_ERR
        res[cD < thr_min] = _R_INSUF  # depth outranks error rate
        if duplex.any():
            d = np.nonzero(duplex & (res == _R_PASS))[0]
            adp = np.where(aD_p[rows][d], aD[rows][d],
                           np.where(aM_p[rows][d], aM[rows][d], -1))
            bdp = np.where(bD_p[rows][d], bD[rows][d],
                           np.where(bM_p[rows][d], bM[rows][d], -1))
            has_a, has_b = adp >= 0, bdp >= 0
            any_ss = has_a | has_b
            best = np.maximum(np.where(has_a, adp, np.int64(-1 << 40)),
                              np.where(has_b, bdp, np.int64(-1 << 40)))
            worst = np.where(has_a & has_b, np.minimum(adp, bdp), 0)
            ae = np.where(aE_p[rows][d], aE[rows][d], np.nan)
            be = np.where(bE_p[rows][d], bE[rows][d], np.nan)
            errs = np.stack([ae, be])
            with np.errstate(invalid="ignore"):
                best_err = np.where(np.isnan(errs).all(axis=0), 0.0,
                                    np.nanmin(errs, axis=0))
                worst_err = np.where(np.isnan(errs).all(axis=0), 0.0,
                                     np.nanmax(errs, axis=0))
            dres = np.full(len(d), _R_PASS, dtype=np.int8)
            dres[worst_err > cfg.ba.max_read_error_rate] = _R_ERR
            dres[worst < cfg.ba.min_reads] = _R_INSUF
            dres[best_err > cfg.ab.max_read_error_rate] = _R_ERR
            dres[best < cfg.ab.min_reads] = _R_INSUF
            dres[~any_ss] = _R_PASS
            res[d] = dres

        # ---- mean base quality over the full read, pre-mask
        if cfg.min_mean_base_quality is not None:
            sums = nb.qual_scores(batch, 0, 1 << 30).astype(np.float64)[rows]
            mean = np.where(l_seq > 0, sums / np.maximum(l_seq, 1), 0.0)
            res[(res == _R_PASS)
                & (mean < cfg.min_mean_base_quality)] = _R_LOWQ

        # ---- per-base masks
        in_len = np.arange(L)[None, :] < l_seq[:, None]
        quals = self._qual_matrix(batch, rows, L)

        def per_base(tag):
            """(float64 (n, L) matrix, present mask) for a B:s/B:S tag;
            non-B types read as absent (_per_base_padded semantics)."""
            vo, vl, vt = batch.tag_locs(tag)
            vo = np.where(vt == ord("B"), vo, -1)[rows]
            vals, counts = nb.gather_u16_arrays(buf, vo, L)
            if (counts == -2).any():
                raise _OddSubtype()
            present = counts >= 0
            # subtype decides signedness: B:s values are int16, B:S uint16
            f = vals.astype(np.float64)
            signed = present & (buf[np.maximum(vo, 0)] == ord("s"))
            if signed.any():
                f[signed] = vals[signed].view(np.int16)
            return f, present

        cd, cd_p = per_base(b"cd")
        ce, ce_p = per_base(b"ce")
        simplex_pb = ~duplex & cd_p & ce_p
        # one shared numeric core with the device-resident fused filter
        # stage (consensus/filter.py array twins): quality mask everywhere,
        # simplex depth/error masks only where per-base evidence exists
        mask = simplex_base_mask_arrays(
            cd, ce, quals, in_len, cfg.single_strand, cfg.min_base_quality,
            has_per_base=simplex_pb)
        if duplex.any():
            ad, _ = per_base(b"ad")
            ae_b, _ = per_base(b"ae")
            bd, _ = per_base(b"bd")
            be_b, _ = per_base(b"be")
            dmask = duplex_base_mask_arrays(ad, ae_b, bd, be_b, cfg.cc,
                                            cfg.ab, cfg.ba)
            mask |= duplex[:, None] & dmask & in_len

        # EM-Seq/TAPS depth masking (filter.rs:952-1043): cu+ct below the
        # first threshold; duplex rows additionally au+at / bu+bt. Rows
        # without any cu/ct tag are untouched (no-tags no-op).
        mdt = cfg.methylation_depth
        simplex_meth = None
        if mdt is not None:
            cu, cu_p = per_base(b"cu")
            ct, ct_p = per_base(b"ct")
            has_meth = (cu_p | ct_p)[:, None] & in_len
            meth_mask = has_meth & ((cu + ct) < mdt.duplex)
            if duplex.any():
                au, _ = per_base(b"au")
                at, _ = per_base(b"at")
                bu, _ = per_base(b"bu")
                bt, _ = per_base(b"bt")
                meth_mask |= has_meth & duplex[:, None] \
                    & (((au + at) < mdt.ab) | ((bu + bt) < mdt.ba))
            # duplex rows ride the skip-N pass below; simplex rows get a
            # SECOND skip-N pass after the base mask (the reference's
            # methylation masking always skips already-N positions,
            # filter.rs:969-971, while simplex base masking does not)
            mask |= meth_mask & duplex[:, None]
            simplex_meth = meth_mask & ~duplex[:, None]

        skip_n = duplex  # duplex masking skips already-N positions
        newly = np.empty(n, dtype=np.int32)
        n_after = np.empty(n, dtype=np.int32)
        for group, skip in ((np.nonzero(~duplex)[0], False),
                            (np.nonzero(duplex)[0], True)):
            if len(group):
                nw, na = nb.apply_masks(batch, rows[group], mask[group], skip)
                newly[group] = nw
                n_after[group] = na
        if simplex_meth is not None and simplex_meth.any():
            g = np.nonzero(~duplex)[0]
            if len(g):
                nw2, na2 = nb.apply_masks(batch, rows[g], simplex_meth[g],
                                          True)
                newly[g] += nw2
                n_after[g] = na2
        # simplex semantics: only mask when any bit set (mask_bases returns
        # early otherwise) — apply_masks is equivalent since no-bit rows
        # write nothing

        # ---- post-mask no-call check: < 1.0 is a fraction of read
        # length, >= 1.0 an absolute N count (no_call_check semantics)
        if cfg.max_no_call_fraction < 1.0:
            frac = np.where(l_seq > 0, n_after / np.maximum(l_seq, 1), 0.0)
            too_many = (l_seq > 0) & (frac > cfg.max_no_call_fraction)
        else:
            too_many = n_after > cfg.max_no_call_fraction
        res[(res == _R_PASS) & too_many] = _R_NOCALL

        # ---- template verdicts + emit (run_filter.emit_template)
        stats = self.stats
        flag = batch.flag[rows]
        secsup = (flag & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY)) != 0
        ok = res == _R_PASS
        if self.filter_by_template:
            # template passes iff all primaries pass (template_passes)
            t_of = np.repeat(np.arange(len(tbounds) - 1),
                             np.diff(tbounds))
            fail = ~ok & ~secsup
            any_prim = np.zeros(len(tbounds) - 1, dtype=bool)
            np.logical_or.at(any_prim, t_of, ~secsup)
            t_fail = np.zeros(len(tbounds) - 1, dtype=bool)
            np.logical_or.at(t_fail, t_of, fail)
            # a template with no primaries fails (template_passes)
            tpl_pass = ~t_fail & any_prim
            keep = np.where(secsup, tpl_pass[t_of] & ok, tpl_pass[t_of])
        else:
            keep = ok

        stats.total_records += n
        kept = int(keep.sum())
        stats.passed_records += kept
        stats.failed_records += n - kept
        stats.bases_masked += int(newly[keep & ~secsup].sum())
        for i in np.nonzero(~keep)[0]:
            reason = _RESULT_STR[res[i]] if res[i] != _R_PASS \
                else "template_failed"
            stats.rejection_reasons[reason] += 1

        self._emit_runs(batch, rows, keep, emit)
        if emit_reject is not None:
            self._emit_runs(batch, rows, ~keep, emit_reject)

    def _qual_matrix(self, batch, rows, L):
        """Dense (n, L) qualities (zero-padded); per-row gather."""
        buf = batch.buf
        n = len(rows)
        out = np.zeros((n, L), dtype=np.uint8)
        q_off = batch.qual_off[rows]
        l_seq = batch.l_seq[rows]
        # gather via flat fancy indexing: offsets matrix clipped to range
        idx = q_off[:, None] + np.arange(L)[None, :]
        valid = np.arange(L)[None, :] < l_seq[:, None]
        np.copyto(out, buf[np.minimum(idx, len(buf) - 1)], where=valid)
        return out

    def _emit_runs(self, batch, rows, keep, emit):
        """Contiguous kept records emit as single buffer slices (records are
        adjacent on the wire, each preceded by its block_size prefix)."""
        if not keep.any():
            return
        buf = batch.buf
        k = np.nonzero(keep)[0]
        run_starts = np.nonzero(np.concatenate(
            ([True], np.diff(k) > 1)))[0]
        bounds = np.append(run_starts, len(k))
        for ri in range(len(run_starts)):
            a = rows[k[bounds[ri]]]
            b = rows[k[bounds[ri + 1] - 1]]
            emit(bytes(buf[batch.data_off[a] - 4:batch.data_end[b]]))

    # ------------------------------------------------------------------ carry

    def _emit_carry(self, emit, emit_reject):
        """The completed carried name group runs the classic per-record
        path (identical semantics; group sizes are tiny)."""
        from ..io.bam import RawRecord
        from .filter import template_passes

        records = self._carry
        self._carry = []
        processed = [_process_one(data, self.config, False, None, ())
                     for data in records]
        recs = [RawRecord(d) for d, _, _ in processed]
        results = [r for _, r, _ in processed]
        masked = [m for _, _, m in processed]
        stats = self.stats
        pass_flags = [r == PASS for r in results]
        tpl_pass = template_passes(recs, pass_flags) \
            if self.filter_by_template else True
        for rec, okf, result, mk in zip(recs, pass_flags, results, masked):
            stats.total_records += 1
            is_sec = bool(rec.flag & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY))
            if not self.filter_by_template:
                kp = okf
            elif is_sec:
                kp = tpl_pass and okf
            else:
                kp = tpl_pass
            chunk = len(rec.data).to_bytes(4, "little") + rec.data
            if kp:
                stats.passed_records += 1
                stats.bases_masked += 0 if is_sec else mk
                emit(chunk)
            else:
                stats.failed_records += 1
                reason = result if result != PASS else "template_failed"
                stats.rejection_reasons[reason] += 1
                if emit_reject is not None:
                    emit_reject(chunk)

    def flush(self, emit, emit_reject):
        if self._carry:
            self._emit_carry(emit, emit_reject)


class _OddSubtype(Exception):
    """A per-base tag with a non-16-bit subtype: classic fallback needed."""
