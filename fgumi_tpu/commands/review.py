"""review: extract consensus + raw reads supporting variant calls.

Mirrors /root/reference/src/lib/commands/review.rs (fgbio
ReviewConsensusVariants): builds a SNP variant list from a VCF (with optional
sample genotype/MAF gating) or an interval list + reference FASTA, extracts
every consensus read with a non-reference allele (alt, third allele, no-call,
or spanning deletion) at any variant site into <output>.consensus.bam, the raw
grouped reads of the same source molecules into <output>.grouped.bam, and
writes a per-variant per-consensus-read TSV <output>.txt with consensus and
raw-read base counts (variant_review.rs ConsensusVariantReviewInfo columns).

Reads are correlated by the MI tag truncated at the last '/'
(review.rs:30-42 to_mi). Consensus-read selection uses BAI/CSI random
access over the variant windows when an index exists next to the consensus
BAM (io/bam.py BamIndexedReader — the indexed_reader.rs analog; a sparse
variant list touches only candidate BGZF blocks), falling back to one
streaming pass otherwise. The grouped-BAM pass always streams: it selects
by molecule id over the whole file (every read of a selected molecule is
extracted, wherever it maps), which no coordinate index can answer.
"""

import logging
from dataclasses import dataclass
from typing import Optional

from ..core.cigar import read_pos_at_ref_pos
from ..io.bam import (FLAG_FIRST, FLAG_LAST, FLAG_MATE_REVERSE,
                      FLAG_MATE_UNMAPPED, FLAG_PAIRED, FLAG_REVERSE,
                      FLAG_UNMAPPED, BamReader, BamWriter, RawRecord)

log = logging.getLogger("fgumi_tpu")

REVIEW_COLUMNS = ["chrom", "pos", "ref", "genotype", "filters",
                  "A", "C", "G", "T", "N",
                  "consensus_read", "consensus_insert", "consensus_call",
                  "consensus_qual", "a", "c", "g", "t", "n"]


@dataclass
class Variant:
    """One SNP site under review (variant_review.rs:159-184)."""

    chrom: str
    pos: int  # 1-based
    ref_base: str
    genotype: Optional[str] = None
    filters: Optional[str] = None


class BaseCounts:
    """A/C/G/T/N counts at a position (variant_review.rs:186-212)."""

    __slots__ = ("a", "c", "g", "t", "n")

    def __init__(self):
        self.a = self.c = self.g = self.t = self.n = 0

    def add(self, base: str):
        base = base.upper()
        if base == "A":
            self.a += 1
        elif base == "C":
            self.c += 1
        elif base == "G":
            self.g += 1
        elif base == "T":
            self.t += 1
        elif base == "N":
            self.n += 1


def extract_mi_base(mi: str) -> str:
    """MI truncated at the last '/' ('1/A' -> '1'; review.rs:30-42)."""
    idx = mi.rfind("/")
    return mi[:idx] if idx >= 0 else mi


def read_number_suffix(rec: RawRecord) -> str:
    """'/2' only for paired second-of-pair reads (variant_review.rs:214-224)."""
    flg = rec.flag
    return "/2" if (flg & FLAG_PAIRED and flg & FLAG_LAST) else "/1"


def format_insert_string(rec: RawRecord, ref_names: list) -> str:
    """'chr:start-end | F1R2' for mapped FR pairs, else 'NA'
    (variant_review.rs:231-320)."""
    flg = rec.flag
    if not flg & FLAG_PAIRED or flg & (FLAG_UNMAPPED | FLAG_MATE_UNMAPPED):
        return "NA"
    if rec.ref_id < 0 or rec.next_ref_id < 0 or rec.ref_id != rec.next_ref_id:
        return "NA"
    is_reverse = bool(flg & FLAG_REVERSE)
    if is_reverse == bool(flg & FLAG_MATE_REVERSE):
        return "NA"
    tlen = rec.tlen
    if tlen == 0 or (not is_reverse and tlen < 0) or (is_reverse and tlen > 0):
        return "NA"
    if rec.ref_id >= len(ref_names):
        return "NA"
    ref_name = ref_names[rec.ref_id]
    outer = (rec.pos + rec.reference_length()) if is_reverse else (rec.pos + 1)
    other = outer + tlen + (1 if tlen < 0 else -1)
    start, end = (outer, other) if outer < other else (other, outer)
    is_first = bool(flg & FLAG_FIRST)
    pairing = "F1R2" if is_first == (start == outer) else "F2R1"
    return f"{ref_name}:{start}-{end} | {pairing}"


def _base_at_position(rec: RawRecord, ref_pos: int):
    """(ASCII base, qual) at 1-based ref_pos, or None when not covered
    (deletion / outside; review.rs get_base_at_position)."""
    offset = read_pos_at_ref_pos(rec.cigar(), rec.pos + 1, ref_pos, False)
    if offset is None:
        return None
    idx = offset - 1
    seq = rec.seq_bytes()
    if idx >= len(seq):
        return None
    return chr(seq[idx]), int(rec.quals()[idx])


def _normalize(base: str, ref_base: str) -> str:
    """BAM '=' means the reference base (review.rs normalize_base_for_variant)."""
    return ref_base.upper() if base == "=" else base.upper()


# ------------------------------------------------------------------ variants

def format_genotype(gt: str, ref: str, alts: list) -> str:
    """htsjdk Genotype.getGenotypeString: allele bases in genotype order,
    '|' only when fully phased (review.rs:44-76)."""
    phased = "/" not in gt
    sep = "|" if phased and "|" in gt else "/"
    parts = gt.replace("|", "/").split("/")
    bases = []
    for p in parts:
        if p == ".":
            bases.append(".")
        elif p == "0":
            bases.append(ref)
        else:
            i = int(p) - 1
            bases.append(alts[i] if i < len(alts) else ".")
    return sep.join(bases)


def _maf_from_fields(fields: dict):
    """fgbio mafFromGenotype: AF first, then 1 - AD[0]/sum(AD); None when
    neither is usable (review.rs:665-713). A zero-AD sum yields NaN."""
    af = fields.get("AF")
    if af and af != ".":
        try:
            return float(af.split(",")[0])
        except ValueError:
            pass
    ad = fields.get("AD")
    if ad and ad != ".":
        try:
            # a missing ('.') AD entry counts as 0, matching the reference
            counts = [0 if x == "." else int(x) for x in ad.split(",")]
        except ValueError:
            return None
        total = sum(counts)
        if total == 0:
            return float("nan")
        return 1.0 - counts[0] / total
    return None


def _open_text(path: str):
    if path.lower().endswith(".gz"):
        import gzip

        return gzip.open(path, "rt")
    return open(path)


def load_variants_from_vcf(path: str, sample: Optional[str],
                           maf_threshold: float) -> list:
    """SNPs from a VCF (plain or gzipped); genotype/filters from the chosen
    sample; variants whose AF/AD-derived MAF exceeds the threshold (or is NaN)
    are dropped (review.rs:412-517)."""
    variants = []
    sample_names = []
    with _open_text(path) as fh:
        for line in fh:
            line = line.rstrip("\r\n")
            if line.startswith("##") or not line:
                continue
            if line.startswith("#CHROM"):
                cols = line.split("\t")
                sample_names = cols[9:] if len(cols) > 9 else []
                continue
            cols = line.split("\t")
            if len(cols) < 8:
                continue
            chrom, pos_s, _id, ref, alt = cols[0], cols[1], cols[2], cols[3], cols[4]
            # SNPs only: single-base ACGT ref with at least one single-base alt
            if len(ref) != 1 or ref.upper() not in "ACGT":
                continue
            alts = [a for a in alt.split(",") if a != "."]
            if not alts or not all(len(a) == 1 for a in alts):
                continue
            filters = cols[6]
            v = Variant(chrom=chrom, pos=int(pos_s), ref_base=ref.upper(),
                        filters=None if filters in (".", "PASS", "") else filters)

            if len(cols) > 9 and sample_names:
                if sample is not None:
                    if sample not in sample_names:
                        raise ValueError(
                            f"sample {sample!r} not found in VCF (has "
                            f"{sample_names})")
                    s_idx = sample_names.index(sample)
                elif len(sample_names) == 1:
                    s_idx = 0
                else:
                    s_idx = None
                if s_idx is not None:
                    fmt = cols[8].split(":")
                    vals = cols[9 + s_idx].split(":")
                    fields = dict(zip(fmt, vals))
                    gt = fields.get("GT")
                    if gt:
                        v.genotype = format_genotype(gt, ref.upper(), alts)
                    maf = _maf_from_fields(fields)
                    # keep only when MAF is absent or <= threshold; a NaN
                    # MAF fails the comparison and drops the variant
                    # (fgbio forall(_ <= maf) semantics)
                    if maf is not None and not maf <= maf_threshold:
                        continue
            variants.append(v)
    return variants


def load_variants_from_intervals(path: str, reference) -> list:
    """One variant per position per interval (1-based closed), ref base from
    the FASTA (review.rs:519-559)."""
    variants = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith(("@", "#")):
                continue
            fields = line.split("\t")
            if len(fields) < 3:
                continue
            chrom, start, end = fields[0], int(fields[1]), int(fields[2])
            seq = reference.fetch(chrom, start - 1, end).decode().upper()
            for i, pos in enumerate(range(start, end + 1)):
                ref_base = seq[i] if i < len(seq) else "N"
                variants.append(Variant(chrom, pos, ref_base))
    return variants


# ------------------------------------------------------------------ main flow

def _open_indexed(path: str):
    """BamIndexedReader over `path` when a .bai/.csi sits next to it, else
    None (streaming fallback). Tagged with index_kind for the log line."""
    import os

    from ..io.bam import BamIndexedReader

    for ext in (".bai", ".csi"):
        ipath = path + ext
        if os.path.exists(ipath):
            try:
                if os.path.getmtime(ipath) < os.path.getmtime(path):
                    # stale index (BAM rewritten after indexing): virtual
                    # offsets would silently fetch garbage — try the next
                    # flavor, else stream
                    log.warning("review: %s is older than %s; ignoring the "
                                "stale index", ipath, path)
                    continue
                r = BamIndexedReader(path, ipath)
            except (OSError, ValueError) as e:
                log.warning("review: index %s unusable (%s)", ipath, e)
                continue
            r.index_kind = ext[1:]
            return r
    return None


def _index_variants(variants) -> dict:
    """chrom -> (sorted positions array, variants sorted by pos)."""
    by_chrom = {}
    for v in variants:
        by_chrom.setdefault(v.chrom, []).append(v)
    out = {}
    for chrom, vs in by_chrom.items():
        vs.sort(key=lambda v: v.pos)
        out[chrom] = ([v.pos for v in vs], vs)
    return out


def _variants_overlapping(variant_index, rec: RawRecord, ref_names):
    """Variants within the record's reference span, via bisect over the
    per-chromosome sorted position list."""
    import bisect

    if rec.flag & FLAG_UNMAPPED or rec.ref_id < 0 or rec.ref_id >= len(ref_names):
        return []
    entry = variant_index.get(ref_names[rec.ref_id])
    if entry is None:
        return []
    positions, chrom_variants = entry
    start = rec.pos + 1
    end = rec.pos + rec.reference_length()
    lo = bisect.bisect_left(positions, start)
    hi = bisect.bisect_right(positions, end)
    return chrom_variants[lo:hi]


def run_review(args) -> int:
    from ..metrics import write_metrics

    lower = args.input.lower()
    try:
        if lower.endswith((".vcf", ".vcf.gz")):
            variants = load_variants_from_vcf(args.input, args.sample, args.maf)
        else:
            if args.ref is None:
                log.error("--ref is required for interval-list input")
                return 2
            from ..core.reference import ReferenceReader

            variants = load_variants_from_intervals(args.input,
                                                    ReferenceReader(args.ref))
    except (ValueError, OSError) as e:
        log.error("%s", e)
        return 2

    log.info("review: %d variant sites loaded", len(variants))

    # Pass 1: consensus BAM — select non-reference reads per variant, and
    # pileup site base counts over ALL consensus reads covering each variant
    # (dedup by (base, read name), review.rs:989-1002 / REV3-02).
    site_seen = set()
    selected_mis = set()
    n_consensus_out = 0
    with BamReader(args.consensus_bam) as reader:
        ref_names = reader.header.ref_names
        header = reader.header
        # reference parity (review.rs:283-298, fgumi issue #497): variants
        # process — and TSV rows emit — in sequence-dictionary coordinate
        # order regardless of the input file's order; a variant on a contig
        # absent from the dictionary is an error, as in fgbio
        dict_order = {n.decode() if isinstance(n, bytes) else n: i
                      for i, n in enumerate(ref_names)}
        missing = sorted({v.chrom for v in variants
                          if v.chrom not in dict_order})
        if missing:
            log.error("review: variant contig(s) %s not in the BAM "
                      "sequence dictionary", ", ".join(missing))
            return 2
        variants.sort(key=lambda v: (dict_order[v.chrom], v.pos))
        variant_index = _index_variants(variants)
        per_variant_consensus = {id(v): [] for v in variants}
        consensus_site_counts = {id(v): BaseCounts() for v in variants}

        class _MissingMi(Exception):
            pass

        def visit(rec, writer):
            """Shared per-record selection for both access paths."""
            nonlocal n_consensus_out
            overlapping = _variants_overlapping(variant_index, rec,
                                                ref_names)
            if not overlapping:
                return
            hits = []
            for v in overlapping:
                got = _base_at_position(rec, v.pos)
                if got is not None:
                    base = _normalize(got[0], v.ref_base)
                    key = (id(v), base, rec.name)
                    if key not in site_seen:
                        site_seen.add(key)
                        consensus_site_counts[id(v)].add(base)
                    non_ref = base != v.ref_base and \
                        not (args.ignore_ns and base == "N")
                    detail = (base, got[1])  # drives the TSV row later
                else:
                    non_ref = True  # spanning deletion
                    detail = None  # extracted, but no detail row
                if non_ref:
                    hits.append((v, detail))
            if not hits:
                return
            mi = rec.get_str(b"MI")
            if mi is None:
                raise _MissingMi(rec.name.decode(errors="replace"))
            mi_base = extract_mi_base(mi)
            selected_mis.add(mi_base)
            writer.write_record(rec)
            n_consensus_out += 1
            for v, detail in hits:
                per_variant_consensus[id(v)].append((rec, detail))

        # a dense variant list touches essentially every block, where
        # per-variant queries would re-decompress shared BGZF chunks — the
        # index only wins when the list is sparse
        indexed = _open_indexed(args.consensus_bam) \
            if len(variants) <= 20000 else None
        try:
            with BamWriter(args.output + ".consensus.bam", header) as writer:
                if indexed is not None:
                    # BAI/CSI fast path: only blocks overlapping variant
                    # windows are touched. A read spanning several variants
                    # appears in several queries; dedup keeps the first
                    # (lowest-coordinate) visit so record handling matches
                    # the streaming order.
                    with indexed:
                        visited = set()
                        for v in variants:
                            tid = dict_order[v.chrom]
                            for rec in indexed.query(tid, v.pos - 1, v.pos):
                                rkey = (rec.name, rec.flag, rec.ref_id,
                                        rec.pos)
                                if rkey in visited:
                                    continue
                                visited.add(rkey)
                                visit(rec, writer)
                    log.info("review: consensus pass used the %s index",
                             "CSI" if indexed.index_kind == "csi" else "BAI")
                else:
                    for rec in reader:
                        visit(rec, writer)
        except _MissingMi as e:
            log.error("consensus read %s has no MI tag", e)
            return 2

    # Pass 2: grouped BAM — extract raw reads of the selected molecules and
    # accumulate per-(variant, mi, read-number) base counts.
    raw_counts = {}
    n_grouped_out = 0
    with BamReader(args.grouped_bam) as reader:
        g_ref_names = reader.header.ref_names
        with BamWriter(args.output + ".grouped.bam", reader.header) as writer:
            seen = set()
            for rec in reader:
                mi = rec.get_str(b"MI")
                if mi is None:
                    continue
                mi_base = extract_mi_base(mi)
                if mi_base not in selected_mis:
                    continue
                writer.write_record(rec)
                n_grouped_out += 1
                suffix = read_number_suffix(rec)
                for v in _variants_overlapping(variant_index, rec,
                                               g_ref_names):
                    dedup_key = (id(v), rec.name, suffix)
                    if dedup_key in seen:
                        continue
                    seen.add(dedup_key)
                    got = _base_at_position(rec, v.pos)
                    if got is None:
                        continue
                    key = (id(v), mi_base, suffix)
                    counts = raw_counts.get(key)
                    if counts is None:
                        counts = raw_counts[key] = BaseCounts()
                    counts.add(_normalize(got[0], v.ref_base))

    # Review TSV: one row per (variant, non-reference consensus read).
    cons_ref_names = ref_names
    rows = []
    for v in variants:
        cons_reads = per_variant_consensus[id(v)]
        if not cons_reads:
            continue
        consensus_counts = consensus_site_counts[id(v)]

        variant_rows = []
        for rec, detail in cons_reads:
            if detail is None:
                continue  # spanning deletion: extracted but no detail row
            base, qual = detail
            mi_base = extract_mi_base(rec.get_str(b"MI"))
            suffix = read_number_suffix(rec)
            rc = raw_counts.get((id(v), mi_base, suffix), BaseCounts())
            variant_rows.append((mi_base + suffix, {
                "chrom": v.chrom, "pos": v.pos, "ref": v.ref_base,
                "genotype": v.genotype or "NA",
                "filters": v.filters or "PASS",
                "A": consensus_counts.a, "C": consensus_counts.c,
                "G": consensus_counts.g, "T": consensus_counts.t,
                "N": consensus_counts.n,
                "consensus_read": rec.name.decode(errors="replace") + suffix,
                "consensus_insert": format_insert_string(rec, cons_ref_names),
                "consensus_call": base, "consensus_qual": qual,
                "a": rc.a, "c": rc.c, "g": rc.g, "t": rc.t, "n": rc.n,
            }))
        variant_rows.sort(key=lambda t: t[0])
        rows.extend(r for _, r in variant_rows)

    write_metrics(args.output + ".txt", rows, REVIEW_COLUMNS)
    log.info("review: %d consensus reads, %d raw reads extracted; %d detail "
             "rows -> %s.{consensus.bam,grouped.bam,txt}",
             n_consensus_out, n_grouped_out, len(rows), args.output)
    return 0
