"""duplex-metrics: CollectDuplexSeqMetrics analog.

Mirrors /root/reference/src/lib/commands/duplex_metrics.rs +
crates/fgumi-metrics/src/duplex.rs: 20-level deterministic downsampling
(Murmur3 read-name hashing), CS / SS / DS family size distributions, AB/BA
duplex family sizes with 2D cumulative fractions, UMI count metrics with
within-family consensus correction, duplex yield metrics with a binomial
ideal-duplex model, and optional interval filtering.

Outputs: <output>.family_sizes.txt, <output>.duplex_family_sizes.txt,
<output>.duplex_yield_metrics.txt, <output>.umi_counts.txt, and (with
--duplex-umi-counts) <output>.duplex_umi_counts.txt. (The reference's optional
R-based PDF plots are not produced — no R in this environment.)
"""

import logging

from ..consensus.simple_umi import consensus_umis
from ..metrics import (UmiCountTracker, binomial_cdf, family_size_rows, frac,
                       write_metrics)
from .metrics_common import (DOWNSAMPLING_FRACTIONS, compute_template_metadata,
                             parse_intervals, process_templates_from_bam,
                             validate_not_consensus_bam)

log = logging.getLogger("fgumi_tpu")

FAMILY_SIZE_FIELDS = [
    "family_size", "cs_count", "cs_fraction", "cs_fraction_gt_or_eq_size",
    "ss_count", "ss_fraction", "ss_fraction_gt_or_eq_size",
    "ds_count", "ds_fraction", "ds_fraction_gt_or_eq_size"]
DUPLEX_FAMILY_FIELDS = ["ab_size", "ba_size", "count", "fraction",
                        "fraction_gt_or_eq_size"]
YIELD_FIELDS = ["fraction", "read_pairs", "cs_families", "ss_families",
                "ds_families", "ds_duplexes", "ds_fraction_duplexes",
                "ds_fraction_duplexes_ideal"]
UMI_FIELDS = ["umi", "raw_observations", "raw_observations_with_errors",
              "unique_observations", "fraction_raw_observations",
              "fraction_unique_observations"]
DUPLEX_UMI_FIELDS = UMI_FIELDS + ["fraction_unique_observations_expected"]


class DuplexMetricsCollector:
    """Per-fraction accumulator (fgumi-metrics duplex.rs:246-500)."""

    def __init__(self, collect_duplex_umi_counts: bool = False):
        self.collect_duplex_umi_counts = collect_duplex_umi_counts
        self.cs_family_sizes = {}
        self.ss_family_sizes = {}
        self.ds_family_sizes = {}
        self.duplex_family_sizes = {}
        self.umi_counts = UmiCountTracker()
        self.duplex_umi_counts = UmiCountTracker()

    def record_cs_family(self, size: int):
        self.cs_family_sizes[size] = self.cs_family_sizes.get(size, 0) + 1

    def record_ss_family(self, size: int):
        self.ss_family_sizes[size] = self.ss_family_sizes.get(size, 0) + 1

    def record_ds_family(self, size: int):
        self.ds_family_sizes[size] = self.ds_family_sizes.get(size, 0) + 1

    def record_duplex_family(self, ab_size: int, ba_size: int):
        key = (max(ab_size, ba_size), min(ab_size, ba_size))
        self.duplex_family_sizes[key] = self.duplex_family_sizes.get(key, 0) + 1

    def record_umi(self, umi: str, raw_count: int, error_count: int,
                   is_unique: bool):
        self.umi_counts.record(umi, raw_count, error_count, is_unique)

    def family_size_metrics(self) -> list:
        """One sparse row per observed size, ascending, with cumulative >=size
        fractions (duplex.rs:333-388)."""
        return family_size_rows({"cs": self.cs_family_sizes,
                                 "ss": self.ss_family_sizes,
                                 "ds": self.ds_family_sizes})

    def duplex_family_size_metrics(self) -> list:
        """(ab, ba)-sorted rows with sparse 2D cumulative fractions
        (duplex.rs:390-442)."""
        total = sum(self.duplex_family_sizes.values())
        entries = sorted(self.duplex_family_sizes.items())
        rows = []
        for (ab, ba), count in entries:
            cumulative = sum(c for (a, b), c in entries if a >= ab and b >= ba)
            rows.append({
                "ab_size": ab, "ba_size": ba, "count": count,
                "fraction": frac(count, total),
                "fraction_gt_or_eq_size": frac(cumulative, total),
            })
        return rows

    def umi_metrics(self) -> list:
        return self.umi_counts.to_metrics()

    def duplex_umi_metrics(self, umi_metrics: list) -> list:
        if not self.collect_duplex_umi_counts:
            return []
        single_fractions = {m["umi"]: m["fraction_unique_observations"]
                            for m in umi_metrics}
        total_raw = self.duplex_umi_counts.total_raw()
        total_unique = self.duplex_umi_counts.total_unique()
        rows = []
        for umi in sorted(self.duplex_umi_counts.counts):
            raw, errors, unique = self.duplex_umi_counts.counts[umi]
            if "-" in umi:
                u1, u2 = umi.split("-", 1)
                expected = (single_fractions.get(u1, 0.0)
                            * single_fractions.get(u2, 0.0))
            else:
                expected = 0.0
            rows.append({
                "umi": umi, "raw_observations": raw,
                "raw_observations_with_errors": errors,
                "unique_observations": unique,
                "fraction_raw_observations": frac(raw, total_raw),
                "fraction_unique_observations": frac(unique, total_unique),
                "fraction_unique_observations_expected": expected,
            })
        return rows


def _safe_consensus(umis: list) -> str:
    try:
        return consensus_umis(umis)
    except ValueError:
        # ragged UMI lengths: fall back to the most common observation
        from collections import Counter

        return Counter(umis).most_common(1)[0][0]


def _update_umi_metrics(collector, group_pairs, duplex_umi_counts):
    """Per-DS-family UMI consensus + observation counting
    (duplex_metrics.rs:564-668): RX halves oriented F1R2 by the R1 strand.
    `group_pairs` is already one base-UMI family (built per ds_groups entry)."""
    umi1s, umi2s = [], []
    for mi, rx, r1_positive in group_pairs:
        parts = rx.split("-")
        if len(parts) != 2:
            raise ValueError(
                f"Duplex UMI did not contain 2 segments delimited by '-': "
                f"{rx!r} (MI {mi!r})")
        if r1_positive:
            umi1s.append(parts[0])
            umi2s.append(parts[1])
        else:
            umi1s.append(parts[1])
            umi2s.append(parts[0])

    consensus = []
    for umis in (umi1s, umi2s):
        if not umis:
            continue
        cons = _safe_consensus(umis)
        errors = sum(1 for u in umis if u != cons)
        collector.record_umi(cons, len(umis), errors, True)
        consensus.append(cons)

    if duplex_umi_counts and len(consensus) == 2:
        duplex_umi = f"{consensus[0]}-{consensus[1]}"
        expected = {duplex_umi, f"{consensus[1]}-{consensus[0]}"}
        errors = sum(1 for _mi, rx, _pos in group_pairs if rx not in expected)
        collector.duplex_umi_counts.record(duplex_umi, len(umi1s), errors, True)


def _ideal_duplex_fraction(family_rows: list, min_ab: int, min_ba: int) -> float:
    """Binomial(n, 0.5) ideal model weighted by per-size DS counts
    (duplex_metrics.rs:498-556)."""
    total = sum(r["ds_count"] for r in family_rows)
    if total == 0:
        return 0.0
    ideal = 0.0
    for row in family_rows:
        ds_count = row["ds_count"]
        size = row["family_size"]
        if ds_count == 0 or size < min_ab + min_ba:
            continue
        upper = size - min_ba
        lower = min_ab
        if upper >= lower:
            prob = binomial_cdf(upper, size) - \
                (binomial_cdf(lower - 1, size) if lower > 0 else 0.0)
        else:
            prob = 0.0
        ideal += prob * ds_count
    return ideal / total


def _yield_metric(collector, fraction, read_pairs, min_ab, min_ba):
    """DuplexYieldMetric for one fraction (duplex_metrics.rs:420-496)."""
    family_rows = collector.family_size_metrics()
    duplex_rows = collector.duplex_family_size_metrics()
    ds_families = sum(r["ds_count"] for r in family_rows)
    ds_duplexes = sum(r["count"] for r in duplex_rows
                      if r["ab_size"] >= min_ab and r["ba_size"] >= min_ba)
    cs_families = sum(r["cs_count"] for r in family_rows)
    ss_families = sum(
        ((1 if r["ab_size"] > 0 else 0) + (1 if r["ba_size"] > 0 else 0))
        * r["count"] for r in duplex_rows)
    return {
        "fraction": fraction, "read_pairs": read_pairs,
        "cs_families": cs_families, "ss_families": ss_families,
        "ds_families": ds_families, "ds_duplexes": ds_duplexes,
        "ds_fraction_duplexes": frac(ds_duplexes, ds_families),
        "ds_fraction_duplexes_ideal":
            _ideal_duplex_fraction(family_rows, min_ab, min_ba),
    }


def run_duplex_metrics(args) -> int:
    if args.min_ab_reads < 1 or args.min_ba_reads < 1:
        log.error("--min-ab-reads/--min-ba-reads must be >= 1")
        return 2
    if args.min_ba_reads > args.min_ab_reads:
        log.error("--min-ba-reads must be <= --min-ab-reads")
        return 2
    try:
        validate_not_consensus_bam(args.input)
        intervals = parse_intervals(args.intervals) if args.intervals else []
    except (ValueError, OSError) as e:
        log.error("%s", e)
        return 2

    fractions = DOWNSAMPLING_FRACTIONS
    collectors = [DuplexMetricsCollector(args.duplex_umi_counts)
                  for _ in fractions]
    last_idx = len(fractions) - 1

    def process_group(group, fraction_counts):
        metadata = compute_template_metadata(group)
        for idx, fraction in enumerate(fractions):
            downsampled = [m for m in metadata
                           if m.template.hash_fraction <= fraction]
            if not downsampled:
                continue
            fraction_counts[idx] += len(downsampled)
            collectors[idx].record_cs_family(len(downsampled))
            is_full = idx == last_idx

            ss_groups = {}
            for m in downsampled:
                ss_groups[m.template.mi] = ss_groups.get(m.template.mi, 0) + 1
            for size in ss_groups.values():
                collectors[idx].record_ss_family(size)

            ds_groups = {}
            for m in downsampled:
                entry = ds_groups.setdefault(m.base_umi, [0, 0, []])
                if m.is_b_strand:
                    entry[1] += 1
                else:
                    entry[0] += 1  # /A or unsuffixed counts toward AB
                if is_full:
                    entry[2].append((m.template.mi, m.template.rx,
                                     m.template.r1_positive))
            for base_umi, (a_count, b_count, pairs) in ds_groups.items():
                collectors[idx].record_ds_family(a_count + b_count)
                collectors[idx].record_duplex_family(a_count, b_count)
                if is_full:
                    _update_umi_metrics(collectors[idx], pairs,
                                        args.duplex_umi_counts)

    try:
        total, fraction_counts = process_templates_from_bam(
            args.input, intervals, len(fractions), process_group)
    except ValueError as e:
        log.error("%s", e)
        return 2

    full = collectors[last_idx]
    write_metrics(f"{args.output}.family_sizes.txt",
                  full.family_size_metrics(), FAMILY_SIZE_FIELDS)
    write_metrics(f"{args.output}.duplex_family_sizes.txt",
                  full.duplex_family_size_metrics(), DUPLEX_FAMILY_FIELDS)
    yields = [_yield_metric(c, f, n, args.min_ab_reads, args.min_ba_reads)
              for c, f, n in zip(collectors, fractions, fraction_counts)]
    write_metrics(f"{args.output}.duplex_yield_metrics.txt", yields,
                  YIELD_FIELDS)
    umi_rows = full.umi_metrics()
    write_metrics(f"{args.output}.umi_counts.txt", umi_rows, UMI_FIELDS)
    if args.duplex_umi_counts:
        write_metrics(f"{args.output}.duplex_umi_counts.txt",
                      full.duplex_umi_metrics(umi_rows), DUPLEX_UMI_FIELDS)

    log.info("duplex-metrics: %d templates -> %s.{family_sizes,"
             "duplex_family_sizes,duplex_yield_metrics,umi_counts}.txt",
             total, args.output)
    return 0
