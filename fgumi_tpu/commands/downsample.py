"""downsample: uniform per-UMI-family sampling in one streaming pass.

Mirrors the reference's downsample command (/root/reference/src/lib/commands/
downsample.rs): groups consecutive records sharing an MI tag, draws once per
family, and keeps or rejects the whole family. Requires group-produced
template-coordinate input; --seed makes runs reproducible (one sequential
draw per family, order-dependent by design — NOT Picard DownsampleSam).
"""

import math
import random
from collections import Counter
from dataclasses import dataclass, field


@dataclass
class DownsampleStats:
    families_total: int = 0
    families_kept: int = 0
    records_total: int = 0
    records_kept: int = 0
    kept_sizes: Counter = field(default_factory=Counter)
    rejected_sizes: Counter = field(default_factory=Counter)


def validate_fraction(fraction: float):
    """(0.0, 1.0]; NaN/inf rejected (downsample.rs:116-126)."""
    if math.isnan(fraction) or math.isinf(fraction) or not 0.0 < fraction <= 1.0:
        raise ValueError(
            f"--fraction must be in (0.0, 1.0], got {fraction}")


def _mi_value(rec) -> str:
    got = rec.find_tag(b"MI")
    if got is None:
        raise ValueError(
            f"record '{rec.name.decode(errors='replace')}' has no MI tag; "
            "downsample requires group-produced input")
    typ, val = got
    if typ == "Z":
        return val
    if typ in "cCsSiI":
        return str(val)
    raise ValueError(f"MI tag has unsupported type '{typ}'")


def iter_mi_families(records):
    """Yield (mi, [records]) for consecutive records sharing an MI value."""
    current_mi = None
    current = []
    for rec in records:
        mi = _mi_value(rec)
        if current and mi != current_mi:
            yield current_mi, current
            current = []
        current_mi = mi
        current.append(rec)
    if current:
        yield current_mi, current


def run_downsample(reader, writer, fraction: float, *, seed=None,
                   rejects_writer=None, validate_mi_order: bool = True
                   ) -> DownsampleStats:
    validate_fraction(fraction)
    rng = random.Random(seed)
    stats = DownsampleStats()
    seen = set()
    for mi, records in iter_mi_families(reader):
        if validate_mi_order:
            if mi in seen:
                raise ValueError(
                    f"MI tag '{mi}' appears in non-consecutive blocks; input "
                    "must be grouped (template-coordinate order from group)")
            seen.add(mi)
        stats.families_total += 1
        stats.records_total += len(records)
        if rng.random() < fraction:
            stats.families_kept += 1
            stats.records_kept += len(records)
            stats.kept_sizes[len(records)] += 1
            for rec in records:
                writer.write_record_bytes(rec.data)
        else:
            stats.rejected_sizes[len(records)] += 1
            if rejects_writer is not None:
                for rec in records:
                    rejects_writer.write_record_bytes(rec.data)
    return stats


def write_histogram(sizes: Counter, path: str):
    """family_size -> count TSV (downsample.rs:286-297)."""
    from ..utils.atomic import open_output

    with open_output(path, "w") as f:
        f.write("family_size\tcount\n")
        for size in sorted(sizes):
            f.write(f"{size}\t{sizes[size]}\n")
