"""filter: mask and filter consensus reads by quality/depth/error thresholds.

Command-level flow mirrors the reference (/root/reference/src/lib/commands/
filter.rs): base-level masking (only when per-base tags are present) then
read-level filtering; with --filter-by-template (default) all primary records
of a QNAME must pass or the whole template is dropped, while secondary/
supplementary records are filtered independently (filter.rs:60-75).

With --ref, NM/UQ/MD are regenerated against the reference FASTA after
masking (filter.rs:881-883); without it, filtering MAPPED reads fails fast,
matching the reference (filter.rs:777-785), since masking would leave stale
NM/UQ/MD tags.
"""

from collections import Counter
from dataclasses import dataclass, field

from ..consensus.filter import (
    EXCESSIVE_ERROR_RATE, FilterConfig, INSUFFICIENT_READS, LOW_QUALITY, PASS,
    TOO_MANY_NO_CALLS, filter_duplex_read, filter_read, is_duplex_consensus,
    mask_bases, mask_duplex_bases, mean_base_quality_full_length,
    no_call_check, template_passes)
from ..core.tag_reversal import reverse_per_base_tags
from ..core.template import iter_name_groups
from ..io.bam import (FLAG_SECONDARY, FLAG_SUPPLEMENTARY, FLAG_UNMAPPED,
                      RawRecord)


@dataclass
class FilterStats:
    total_records: int = 0
    passed_records: int = 0
    failed_records: int = 0
    bases_masked: int = 0
    rejection_reasons: Counter = field(default_factory=Counter)


def _process_one(data: bytes, config: FilterConfig, reverse_tags: bool,
                 reference=None, ref_names=()):
    """Mask + judge one record. Returns (new_bytes, result_str, masked_count)."""
    buf = bytearray(data)
    # Fail fast on mapped reads without --ref: masking would invalidate
    # NM/UQ/MD with no way to regenerate them (filter.rs:774-785).
    flag = int.from_bytes(buf[14:16], "little")
    if reference is None and not flag & FLAG_UNMAPPED:
        raise ValueError(
            "--ref is required when filtering mapped reads to keep "
            "NM/UQ/MD tags consistent")
    if reverse_tags:
        reverse_per_base_tags(buf)
    rec = RawRecord(bytes(buf))  # one parse; masking mutates only seq/qual
    duplex = is_duplex_consensus(rec)

    # Read-level thresholds on the pre-masking record.
    if duplex:
        result = filter_duplex_read(rec, config.cc, config.ab, config.ba)
    else:
        result = filter_read(rec, config.single_strand)

    # Mean quality over the full read, prior to masking (filter.rs:668-678).
    if result == PASS and config.min_mean_base_quality is not None:
        if mean_base_quality_full_length(buf) < config.min_mean_base_quality:
            result = LOW_QUALITY

    # Base-level masking (always applied so rejected reads in the rejects file
    # carry the same masking the kept ones would).
    if duplex:
        masked = mask_duplex_bases(buf, config.cc, config.ab, config.ba,
                                   config.min_base_quality,
                                   config.require_ss_agreement, rec=rec)
    else:
        masked = mask_bases(buf, config.single_strand,
                            config.min_base_quality, rec=rec)

    # EM-Seq/TAPS masking (filter.rs:827-880): depth first, then the
    # reference-dependent CpG strand-agreement (duplex only)
    if config.methylation_depth is not None:
        from ..consensus.filter import mask_methylation_depth
        masked += mask_methylation_depth(buf, rec, config.methylation_depth,
                                         duplex)
    ref_codes = None
    needs_ref_codes = ((config.require_strand_methylation_agreement and duplex)
                       or config.min_conversion_fraction is not None)
    if needs_ref_codes and reference is not None:
        from ..consensus.filter import resolve_ref_codes
        ref_codes = resolve_ref_codes(rec, reference, ref_names)
    if config.require_strand_methylation_agreement and duplex:
        from ..consensus.filter import mask_strand_methylation_agreement
        masked += mask_strand_methylation_agreement(buf, rec, ref_codes)

    if result == PASS:
        result = no_call_check(buf, config.max_no_call_fraction)
    # read-level conversion-fraction filter (filter.rs:915-930)
    if result == PASS and config.min_conversion_fraction is not None:
        from ..consensus.filter import check_conversion_fraction
        if not check_conversion_fraction(rec, config.min_conversion_fraction,
                                         ref_codes,
                                         config.methylation_mode):
            result = "low_conversion"
    if reference is not None:
        # regenerate NM/UQ/MD after masking (filter.rs:881-883)
        from ..core.alignment_tags import regenerate_alignment_tags
        from ..core.clipper import MutableRecord
        m = MutableRecord.from_raw(RawRecord(bytes(buf)))
        regenerate_alignment_tags(m, ref_names, reference)
        return m.encode(), result, masked
    return bytes(buf), result, masked


def run_filter(reader, writer, config: FilterConfig, *,
               filter_by_template: bool = True,
               reverse_per_base: bool = False,
               rejects_writer=None, reference=None) -> FilterStats:
    """Stream records, filtering per template (or per record)."""
    stats = FilterStats()
    ref_names = reader.header.ref_names if reference is not None else ()

    def emit_template(records, results, masked_counts):
        """records: [RawRecord], results: [str] parallel."""
        pass_flags = [r == PASS for r in results]
        if filter_by_template:
            tpl_pass = template_passes(records, pass_flags)
        else:
            tpl_pass = True  # records judged independently
        for rec, ok, result, masked in zip(records, pass_flags, results,
                                           masked_counts):
            stats.total_records += 1
            is_secondary = bool(rec.flag & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY))
            # Non-primaries need the template to pass AND their own filters
            # (filter.rs:703-708); primaries ride the template verdict.
            if not filter_by_template:
                keep = ok
            elif is_secondary:
                keep = tpl_pass and ok
            else:
                keep = tpl_pass
            if keep:
                stats.passed_records += 1
                stats.bases_masked += 0 if is_secondary else masked
                writer.write_record_bytes(rec.data)
            else:
                stats.failed_records += 1
                reason = result if result != PASS else "template_failed"
                stats.rejection_reasons[reason] += 1
                if rejects_writer is not None:
                    rejects_writer.write_record_bytes(rec.data)

    if not filter_by_template:
        for rec in reader:
            data, result, masked = _process_one(rec.data, config,
                                                reverse_per_base,
                                                reference, ref_names)
            emit_template([RawRecord(data)], [result], [masked])
        return stats
    for _name, group in iter_name_groups(reader):
        processed = [_process_one(rec.data, config, reverse_per_base,
                                  reference, ref_names)
                     for rec in group]
        emit_template([RawRecord(d) for d, _, _ in processed],
                      [r for _, r, _ in processed],
                      [m for _, _, m in processed])
    return stats
