"""compare: BAM / metrics equivalence checking (test infrastructure).

Analog of the reference's feature-gated `compare` tool
(/root/reference/src/lib/commands/compare/): `compare bams` checks two BAMs for
functional equivalence — core SAM fields plus tag values irrespective of tag
order (bams.rs:1-14) — with a `content` mode (exact, order-honest; optionally
order-insensitive multiset compare) and a `grouping` mode that matches
molecules by an MI-invariant canonical id (the lexicographically smallest read
name in the molecule) and checks membership, content-minus-MI, and duplex
/A-/B strand-partition equivalence up to a global swap
(engines/molecule_join.rs semantics). `compare metrics` diffs TSVs with float
tolerance. Exit code 1 on mismatch (mod.rs:32-41 CompareMismatch contract),
0 on match.
"""

import logging
import math

import numpy as np

from ..io.bam import (FLAG_FIRST, FLAG_LAST, BamReader, RawRecord,
                      _read_tag_value)

log = logging.getLogger("fgumi_tpu")

MAX_REPORTED = 10


def _normalize_tag(typ: str, val):
    """Width/representation-independent tag value (value compare, bams.rs:3-5)."""
    if typ in "cCsSiI":
        return ("i", int(val))
    if typ == "f":
        return ("f", float(np.float32(val)))
    if typ == "A":
        return ("A", val)
    if typ in "ZH":
        return ("Z", val)
    if typ == "B":
        arr = np.asarray(val)
        if arr.dtype.kind == "f":
            return ("Bf", tuple(float(np.float32(x)) for x in arr))
        return ("Bi", tuple(int(x) for x in arr))
    return (typ, val)


def record_tags(rec: RawRecord, ignore_tags=frozenset()):
    """{tag: normalized value}, order-independent."""
    out = {}
    for t, typ, off in rec._iter_tags():
        if t in ignore_tags:
            continue
        out[t] = _normalize_tag(chr(typ), _read_tag_value(rec.data, typ, off))
    return out


def record_fingerprint(rec: RawRecord, ignore_tags=frozenset()):
    """Hashable identity of all compared content of one record."""
    return (rec.name, rec.flag, rec.ref_id, rec.pos, rec.mapq,
            tuple(rec.cigar()), rec.next_ref_id, rec.next_pos, rec.tlen,
            rec.seq_bytes(), rec.quals().tobytes(),
            tuple(sorted(record_tags(rec, ignore_tags).items())))


def _describe(rec: RawRecord) -> str:
    return (f"{rec.name.decode(errors='replace')} flag={rec.flag} "
            f"ref={rec.ref_id} pos={rec.pos}")


def compare_headers(ha, hb) -> list:
    """@SQ compatibility: same reference names and lengths, same order
    (engines/header.rs semantics)."""
    problems = []
    if ha.ref_names != hb.ref_names:
        problems.append(f"reference names differ: {ha.ref_names[:3]}... vs "
                        f"{hb.ref_names[:3]}...")
    elif ha.ref_lengths != hb.ref_lengths:
        problems.append("reference lengths differ")
    return problems


def _diff_records(a: RawRecord, b: RawRecord, ignore_tags) -> list:
    """Field-level differences between two paired records."""
    diffs = []
    for field in ("name", "flag", "ref_id", "pos", "mapq", "next_ref_id",
                  "next_pos", "tlen"):
        va, vb = getattr(a, field), getattr(b, field)
        if va != vb:
            diffs.append(f"{field}: {va!r} != {vb!r}")
    if a.cigar() != b.cigar():
        diffs.append("cigar differs")
    if a.seq_bytes() != b.seq_bytes():
        diffs.append("sequence differs")
    if a.quals().tobytes() != b.quals().tobytes():
        diffs.append("qualities differ")
    ta, tb = record_tags(a, ignore_tags), record_tags(b, ignore_tags)
    for tag in sorted(set(ta) | set(tb)):
        if ta.get(tag) != tb.get(tag):
            diffs.append(f"tag {tag.decode()}: {ta.get(tag)!r} != {tb.get(tag)!r}")
    return diffs


def compare_bams_content(path_a: str, path_b: str, ignore_order: bool = False,
                         ignore_tags=frozenset()) -> list:
    """Content engine: exact record-by-record (order-honest) or multiset compare.

    Returns mismatch description lines (empty = equal).
    """
    mismatches = []
    with BamReader(path_a) as ra, BamReader(path_b) as rb:
        mismatches.extend(compare_headers(ra.header, rb.header))
        if ignore_order:
            from collections import Counter

            ca = Counter(record_fingerprint(r, ignore_tags) for r in ra)
            cb = Counter(record_fingerprint(r, ignore_tags) for r in rb)
            only_a = ca - cb
            only_b = cb - ca
            for fp, n in list(only_a.items())[:MAX_REPORTED]:
                mismatches.append(
                    f"record only in A (x{n}): {fp[0].decode(errors='replace')} "
                    f"flag={fp[1]} pos={fp[3]}")
            for fp, n in list(only_b.items())[:MAX_REPORTED]:
                mismatches.append(
                    f"record only in B (x{n}): {fp[0].decode(errors='replace')} "
                    f"flag={fp[1]} pos={fp[3]}")
            hidden = (len(only_a) - min(len(only_a), MAX_REPORTED)
                      + len(only_b) - min(len(only_b), MAX_REPORTED))
            if hidden:
                mismatches.append(f"... and {hidden} more differing records")
        else:
            n_a = n_b = 0
            ib = iter(rb)
            for i, a in enumerate(ra):
                n_a += 1
                b = next(ib, None)
                if b is None:
                    continue
                n_b += 1
                if record_fingerprint(a, ignore_tags) != \
                        record_fingerprint(b, ignore_tags):
                    if len(mismatches) < MAX_REPORTED:
                        diffs = _diff_records(a, b, ignore_tags)
                        mismatches.append(
                            f"record {i} ({_describe(a)}): " + "; ".join(diffs[:4]))
                    else:
                        mismatches.append(None)
            for b in ib:
                n_b += 1
            if n_a != n_b:
                mismatches.append(f"record counts differ: {n_a} vs {n_b}")
        n_hidden = sum(1 for m in mismatches if m is None)
        mismatches = [m for m in mismatches if m is not None]
        if n_hidden:
            mismatches.append(f"... and {n_hidden} more record mismatches")
    return mismatches


def _mi_of(rec, tag: bytes):
    """Group-tag value as a string: string aux, or the integer aux form some
    tools emit (reference record_key.rs get_mi_tag_raw parses both)."""
    mi = rec.get_str(tag)
    if mi is None:
        v = rec.get_int(tag)
        if v is not None:
            return str(v)
    return mi


def _iter_molecules(reader, tag: bytes):
    """Yield (records,) runs of consecutive equal group-tag values."""
    current = None
    run = []
    for rec in reader:
        mi = _mi_of(rec, tag)
        if mi is None:
            raise ValueError(f"record {rec.name!r} missing {tag.decode()} tag")
        base = mi[:-2] if mi.endswith(("/A", "/B")) else mi
        if base != current:
            if run:
                yield run
            current = base
            run = []
        run.append(rec)
    if run:
        yield run


def _molecule_summary(records, ignore_tags, tag: bytes):
    """(canonical_id, membership, content_multiset, strand_partition).

    canonical id = lexicographically smallest read name (grouping-tag-invariant,
    molecule_join.rs); membership = sorted (name, R1/R2-identity); content
    excludes the grouping tag; strand partition maps name -> 'A'/'B'/None.
    """
    from collections import Counter

    canonical = min(r.name for r in records)
    membership = tuple(sorted(
        (r.name, r.flag & (FLAG_FIRST | FLAG_LAST)) for r in records))
    ignore = frozenset(ignore_tags) | {tag}
    content = Counter(record_fingerprint(r, ignore) for r in records)
    strands = {}
    for r in records:
        mi = _mi_of(r, tag) or ""
        strand = mi[-1] if mi.endswith(("/A", "/B")) else None
        strands[(r.name, r.flag & (FLAG_FIRST | FLAG_LAST))] = strand
    return canonical, membership, content, strands


def compare_bams_grouping(path_a: str, path_b: str, tag: bytes = b"MI",
                          ignore_tags=frozenset()) -> list:
    """Grouping engine: MI-numbering-invariant molecule equivalence
    (molecule_join.rs semantics; requires grouped inputs)."""
    mismatches = []
    with BamReader(path_a) as ra, BamReader(path_b) as rb:
        mismatches.extend(compare_headers(ra.header, rb.header))
        mols_a = {}
        for records in _iter_molecules(ra, tag):
            cid, membership, content, strands = _molecule_summary(records, ignore_tags, tag)
            if cid in mols_a:
                mismatches.append(f"A: molecule id {cid!r} not unique "
                                  "(input not grouped?)")
            mols_a[cid] = (membership, content, strands)
        seen_b = set()
        for records in _iter_molecules(rb, tag):
            cid, membership, content, strands = _molecule_summary(records, ignore_tags, tag)
            seen_b.add(cid)
            got = mols_a.get(cid)
            if got is None:
                if len(mismatches) < MAX_REPORTED:
                    mismatches.append(f"molecule {cid!r} only in B")
                continue
            m_a, c_a, s_a = got
            if m_a != membership:
                if len(mismatches) < MAX_REPORTED:
                    mismatches.append(f"molecule {cid!r}: membership differs")
                continue
            if c_a != content:
                if len(mismatches) < MAX_REPORTED:
                    mismatches.append(f"molecule {cid!r}: record content differs "
                                      "(ignoring MI)")
                continue
            # duplex strand partition equivalence up to a global A/B swap
            pairs = {(s_a[k], strands[k]) for k in strands}
            consistent = (pairs <= {("A", "A"), ("B", "B"), (None, None)}
                          or pairs <= {("A", "B"), ("B", "A"), (None, None)})
            if not consistent:
                if len(mismatches) < MAX_REPORTED:
                    mismatches.append(f"molecule {cid!r}: strand partition differs")
        for cid in set(mols_a) - seen_b:
            if len(mismatches) < MAX_REPORTED:
                mismatches.append(f"molecule {cid!r} only in A")
    return mismatches


def compare_metrics(path_a: str, path_b: str, float_tolerance: float = 1e-5) -> list:
    """TSV metric compare: same columns and rows; numeric cells within relative
    tolerance (metrics.rs semantics)."""
    mismatches = []
    with open(path_a) as fa, open(path_b) as fb:
        lines_a = [l.rstrip("\n") for l in fa if not l.startswith("#")]
        lines_b = [l.rstrip("\n") for l in fb if not l.startswith("#")]
    if not lines_a or not lines_b:
        if bool(lines_a) != bool(lines_b):
            mismatches.append("one file is empty")
        return mismatches
    head_a, head_b = lines_a[0].split("\t"), lines_b[0].split("\t")
    if head_a != head_b:
        mismatches.append(f"columns differ: {head_a} vs {head_b}")
        return mismatches
    if len(lines_a) != len(lines_b):
        mismatches.append(f"row counts differ: {len(lines_a) - 1} vs {len(lines_b) - 1}")
    for i, (la, lb) in enumerate(zip(lines_a[1:], lines_b[1:]), start=1):
        if la == lb:
            continue
        ca, cb = la.split("\t"), lb.split("\t")
        if len(ca) != len(cb):
            mismatches.append(f"row {i}: cell counts differ")
            continue
        for col, (va, vb) in zip(head_a, zip(ca, cb)):
            if va == vb:
                continue
            try:
                fa_, fb_ = float(va), float(vb)
                if math.isclose(fa_, fb_, rel_tol=float_tolerance,
                                abs_tol=float_tolerance):
                    continue
            except ValueError:
                pass
            if len(mismatches) < MAX_REPORTED:
                mismatches.append(f"row {i} col {col}: {va!r} != {vb!r}")
    return mismatches


def verify_sort_order(path: str) -> list:
    """Check that records actually satisfy the header's DECLARED sort order
    (the in-pipeline sort-verification engine of the reference's compare,
    engines/sort_verify.rs:810-870): coordinate, queryname
    (natural/lexicographical sub-sort), or template-coordinate, via the
    packed byte keys (memcmp order == semantic order, sort/keys.py). Headers
    declaring no verifiable order produce no findings."""
    from ..core.template import _hd_fields
    from ..sort.keys import make_batch_keys_fn, make_key_bytes_fn

    mismatches = []
    with BamReader(path) as reader:
        header = reader.header
        hd = _hd_fields(header.text)
    so = hd.get("SO", "")
    ss = hd.get("SS", "")
    if so == "coordinate":
        order, subsort = "coordinate", "natural"
    elif so == "queryname":
        order = "queryname"
        subsort = "lex" if ss.endswith("lexicographical") else "natural"
    elif ss.endswith("template-coordinate"):
        order, subsort = "template-coordinate", "natural"
    else:
        return []

    def report(i, prev_i):
        if len(mismatches) < MAX_REPORTED:
            mismatches.append(f"{path}: record {i} out of declared {order} "
                              f"order (violates record {prev_i})")

    prev = b""
    prev_i = -1
    batch_fn = make_batch_keys_fn(order, header, subsort)
    if batch_fn is not None:
        from ..io.batch_reader import BamBatchReader

        i = 0
        with BamBatchReader(path) as br:
            for batch in br:
                blob, koff, klen = batch_fn(batch)
                for j in range(batch.n):
                    key = blob[koff[j]:koff[j] + klen[j]]
                    if key < prev:
                        report(i + j, prev_i)
                    else:
                        prev, prev_i = key, i + j
                i += batch.n
    else:
        key_fn = make_key_bytes_fn(order, header, subsort)
        with BamReader(path) as reader:
            for i, rec in enumerate(reader):
                key = key_fn(rec)
                if key < prev:
                    report(i, prev_i)
                else:
                    prev, prev_i = key, i
    return mismatches


# ------------------------------------------------------------------ CLI glue

# --command preset -> (mode, ignore_order, also_verify_sort): canonical
# comparison settings per pipeline stage (reference compare/bams.rs
# CommandPreset::resolve, bams.rs:178-206). group is the only preset that
# verifies grouping equivalence instead of positional content; sort verifies
# each input's declared order and compares content as a multiset (tie
# reordering within equal sort keys is legitimate); every other stage is
# deterministic exact content.
_PRESETS = {
    "extract": ("content", False, False),
    "zipper": ("content", False, False),
    "correct": ("content", False, False),
    "dedup": ("content", False, False),
    "clip": ("content", False, False),
    "filter": ("content", False, False),
    "simplex": ("content", False, False),
    "duplex": ("content", False, False),
    "codec": ("content", False, False),
    "group": ("grouping", True, False),
    "sort": ("content", True, True),
}


def run_compare_bams(args) -> int:
    preset = getattr(args, "preset", None)
    if preset is not None:
        p_mode, p_ignore, p_verify = _PRESETS[preset]
        if args.mode is None:
            args.mode = p_mode
        if args.ignore_order is None:
            args.ignore_order = p_ignore
        if p_verify:
            args.verify_sort = True
    if args.mode is None:
        args.mode = "content"
    if args.ignore_order is None:
        args.ignore_order = False
    ignore_tags = frozenset(t.encode() for t in (args.ignore_tags or []))
    if getattr(args, "verify_sort", False):
        sort_mismatches = []
        for path in (args.a, args.b):
            sort_mismatches.extend(verify_sort_order(path))
        if sort_mismatches:
            for m in sort_mismatches:
                log.error("compare: %s", m)
            log.error("compare: declared sort order VIOLATED "
                      "(%d findings)", len(sort_mismatches))
            return 1
    if args.mode == "grouping":
        try:
            mismatches = compare_bams_grouping(args.a, args.b, tag=args.tag.encode(),
                                               ignore_tags=ignore_tags)
        except ValueError as e:
            # a structural error (e.g. ungrouped input) is not a mismatch: exit 2
            log.error("compare: %s", e)
            return 2
    else:
        mismatches = compare_bams_content(args.a, args.b,
                                          ignore_order=args.ignore_order,
                                          ignore_tags=ignore_tags)
    if mismatches:
        for m in mismatches:
            log.error("compare: %s", m)
        log.error("compare: files DIFFER (%d mismatch lines)", len(mismatches))
        return 1
    log.info("compare: files match")
    return 0


def run_compare_metrics(args) -> int:
    mismatches = compare_metrics(args.a, args.b,
                                 float_tolerance=args.float_tolerance)
    if mismatches:
        for m in mismatches:
            log.error("compare: %s", m)
        log.error("compare: metrics DIFFER")
        return 1
    log.info("compare: metrics match")
    return 0
