"""UMI-aware duplicate marking (fgumi dedup).

Mirrors /root/reference/src/lib/commands/dedup.rs:
- template-coordinate sorted input required, with `tc` tags on secondary/
  supplementary reads from zipper (dedup.rs:1196-1210);
- position groups (secondary/supplementary included), template filtering shared
  with group but with both-unmapped templates either discarded or — under
  --include-unmapped — passed through verbatim (dedup.rs:455-480,800-815);
- UMI clustering per group via the standard strategies; non-paired strategies
  split by strand of origin unless --no-umi, which groups orientation-
  agnostically like Picard MarkDuplicates (splits_by_strand_of_origin,
  dedup.rs:640-660);
- cell-barcode partitioning: reads at one position are split by the CB tag so
  different cells never dedup against each other (dedup.rs "Cell Barcodes");
- Picard SUM_OF_BASE_QUALITIES scoring: per primary read, sum of quals >= 15,
  capped at Short.MAX_VALUE/2 per read, QC-fail discounted Short.MIN_VALUE/2
  (score_template, dedup.rs:222-290);
- the highest-scoring template per UMI family is the representative; all other
  templates get the 0x400 flag on every record, or are dropped entirely under
  --remove-duplicates (mark_duplicates_in_family, dedup.rs:700-775);
- MI:Z tags minted from the assigners' cumulative counters in stream order
  (deterministic-MI-numbering contract), written on all records of assigned
  templates (dedup.rs serialize_fn);
- metrics: template/read totals, duplicate rate, secondary/supplementary
  counts, missing-tc-tag count, family-size histogram (DedupMetricsOutput,
  dedup.rs:119-152).
"""

import logging
import struct
from dataclasses import dataclass, field

import numpy as np

from ..core.template import iter_templates, library_lookup_from_header
from ..io.bam import (FLAG_DUPLICATE, FLAG_MATE_UNMAPPED, FLAG_PAIRED,
                      FLAG_QC_FAIL, FLAG_SECONDARY, FLAG_SUPPLEMENTARY,
                      FLAG_UNMAPPED, RawRecord)
from ..umi.assigners import make_assigner
from .group import FilterMetrics, assign_group, iter_position_groups

log = logging.getLogger("fgumi_tpu.dedup")

# Picard/HTSJDK DuplicateScoringStrategy constants (dedup.rs:222-245): the 15 is
# a threshold (full value counted above it, not a cap), the per-read cap keeps
# two mates' scores summable in a short, and the QC-fail discount guarantees a
# QC-fail read never wins representative selection.
PICARD_MIN_BASE_QUALITY = 15
PICARD_MAX_SCORE_PER_READ = 32767 // 2
PICARD_QC_FAIL_DISCOUNT = -32768 // 2


@dataclass
class DedupMetrics:
    total_templates: int = 0
    unique_templates: int = 0
    duplicate_templates: int = 0
    total_reads: int = 0
    unique_reads: int = 0
    duplicate_reads: int = 0
    secondary_reads: int = 0
    supplementary_reads: int = 0
    missing_tc_tag: int = 0
    filter: FilterMetrics = field(default_factory=FilterMetrics)

    def duplicate_rate(self) -> float:
        if self.total_templates == 0:
            return 0.0
        return self.duplicate_templates / self.total_templates


def score_template(t) -> int:
    """Picard SUM_OF_BASE_QUALITIES over the primary reads (dedup.rs:246-290)."""
    score = 0
    for rec in (t.r1, t.r2, t.fragment):
        if rec is None:
            continue
        quals = rec.quals()
        read_sum = int(quals[quals >= PICARD_MIN_BASE_QUALITY].sum(dtype=np.int64))
        read_score = min(read_sum, PICARD_MAX_SCORE_PER_READ)
        if rec.flag & FLAG_QC_FAIL:
            read_score += PICARD_QC_FAIL_DISCOUNT
        score += read_score
    return score


def filter_dedup_template(t, *, umi_tag: bytes, min_mapq: int,
                          include_non_pf: bool, min_umi_length, no_umi: bool,
                          metrics: FilterMetrics) -> bool:
    """filter_template (dedup.rs:330-450): like group's filter, counted per
    template (not per read), both-unmapped always fails here — the
    --include-unmapped pass-through is split off before filtering."""
    metrics.total_templates += 1
    primaries = [r for r in (t.r1, t.r2, t.fragment) if r is not None]
    if not primaries:
        metrics.poor_alignment += 1
        return False
    if all(r.flag & FLAG_UNMAPPED for r in primaries):
        metrics.poor_alignment += 1
        return False
    for r in primaries:
        if not include_non_pf and r.flag & FLAG_QC_FAIL:
            metrics.non_pf += 1
            return False
        if not r.flag & FLAG_UNMAPPED and r.mapq < min_mapq:
            metrics.poor_alignment += 1
            return False
    for r in primaries:
        if r.flag & FLAG_PAIRED and not r.flag & FLAG_MATE_UNMAPPED:
            mq = r.get_int(b"MQ")
            # signed compare so MQ:c:-1 fails rather than wrapping (dedup.rs:412-420)
            if mq is not None and mq < min_mapq:
                metrics.poor_alignment += 1
                return False
        if no_umi:
            continue
        umi = r.get_str(umi_tag)
        if umi is None:
            metrics.poor_alignment += 1
            return False
        if "N" in umi.upper():
            metrics.ns_in_umi += 1
            return False
        if min_umi_length is not None:
            bases = sum(len(seg) for seg in umi.split("-"))
            if bases < min_umi_length:
                metrics.umi_too_short += 1
                return False
    metrics.accepted += 1
    return True


def is_unmapped_passthrough(t) -> bool:
    """template_is_unmapped_passthrough (dedup.rs:455-480): no mapped primary."""
    primaries = [r for r in (t.r1, t.r2, t.fragment) if r is not None]
    if not primaries:
        return False
    return all(r.flag & FLAG_UNMAPPED for r in primaries)


def _family_key(mi):
    """Sort/group key for an assigned MoleculeId: /A and /B strands are separate
    families (dedup.rs to_vec_index ordering)."""
    return (mi.id, mi.kind)


def _record_with_flag_and_mi(rec: RawRecord, is_dup: bool, mi_str,
                             assigned_tag: bytes) -> bytes:
    flag = (rec.flag & ~FLAG_DUPLICATE) | (FLAG_DUPLICATE if is_dup else 0)
    if mi_str is None:
        data = bytearray(rec.data)
    else:
        data = bytearray(rec.data_without_tag(assigned_tag))
        data += assigned_tag + b"Z" + mi_str.encode() + b"\x00"
    struct.pack_into("<H", data, 14, flag)
    return bytes(data)


def _cell_partitions(templates):
    """Partition a position group's templates by CB cell barcode (deterministic
    order: barcode-sorted, barcodeless group first)."""
    by_cell = {}
    for t in templates:
        r = t.primary_r1 or t.r2
        cb = r.get_str(b"CB") if r is not None else None
        by_cell.setdefault(cb or "", []).append(t)
    return [by_cell[k] for k in sorted(by_cell)]


def process_group(templates, assigner, *, umi_tag: bytes, min_umi_length,
                  no_umi: bool, metrics: DedupMetrics):
    """Assign UMIs + mark duplicates in one position group, in place
    (process_position_group, dedup.rs:780-940). Returns family-size counts."""
    family_sizes = {}
    for cell_templates in _cell_partitions(templates):
        if no_umi:
            # orientation-agnostic identity grouping (Picard semantics):
            # bypass assign_group's strand-of-origin split entirely
            assignments = assigner.assign([""] * len(cell_templates))
            for t, mi in zip(cell_templates, assignments):
                t.mi = mi
        else:
            assign_group(cell_templates, assigner, umi_tag, min_umi_length, False)
        ordered = sorted(cell_templates, key=lambda t: (_family_key(t.mi), t.name))
        i = 0
        while i < len(ordered):
            j = i
            while j < len(ordered) and _family_key(ordered[j].mi) == _family_key(ordered[i].mi):
                j += 1
            family = ordered[i:j]
            family_sizes[len(family)] = family_sizes.get(len(family), 0) + 1
            if len(family) == 1:
                # singleton fast path: no scoring needed (dedup.rs:707-712)
                best = 0
            else:
                scores = [score_template(t) for t in family]
                best = max(range(len(family)), key=lambda k: (scores[k], -k))
            for k, t in enumerate(family):
                t.is_duplicate = k != best
                metrics.total_templates += 1
                if t.is_duplicate:
                    metrics.duplicate_templates += 1
                else:
                    metrics.unique_templates += 1
            i = j
    return family_sizes


def run_dedup(reader, writer, *, strategy: str = "adjacency", edits: int = 1,
              umi_tag: bytes = b"RX", assigned_tag: bytes = b"MI",
              min_mapq: int = 0, include_non_pf: bool = False,
              min_umi_length=None, no_umi: bool = False,
              include_unmapped: bool = False, remove_duplicates: bool = False):
    """Stream reader -> writer marking/removing duplicates. Returns metrics."""
    if no_umi and strategy == "paired":
        raise ValueError("--no-umi cannot be used with --strategy paired")
    if min_umi_length is not None and strategy == "paired":
        raise ValueError("Paired strategy cannot be used with --min-umi-length")
    if no_umi:
        strategy, edits = "identity", 0
    assigner = make_assigner(strategy, edits)
    library_of = library_lookup_from_header(reader.header.text)
    metrics = DedupMetrics()
    family_sizes = {}

    def count_read(rec, is_dup: bool):
        metrics.total_reads += 1
        if is_dup:
            metrics.duplicate_reads += 1
        sec = rec.flag & FLAG_SECONDARY
        sup = rec.flag & FLAG_SUPPLEMENTARY
        if sec:
            metrics.secondary_reads += 1
        if sup:
            metrics.supplementary_reads += 1
        if (sec or sup) and rec.find_tag(b"tc") is None:
            metrics.missing_tc_tag += 1

    for group in iter_position_groups(iter_templates(reader), library_of):
        passthrough, candidates = [], group
        if include_unmapped:
            passthrough, candidates = [], []
            for t in group:
                (passthrough if is_unmapped_passthrough(t) else candidates).append(t)
        kept = [t for t in candidates
                if filter_dedup_template(t, umi_tag=umi_tag, min_mapq=min_mapq,
                                         include_non_pf=include_non_pf,
                                         min_umi_length=min_umi_length,
                                         no_umi=no_umi, metrics=metrics.filter)]
        if kept:
            sizes = process_group(kept, assigner, umi_tag=umi_tag,
                                  min_umi_length=min_umi_length, no_umi=no_umi,
                                  metrics=metrics)
            for size, count in sizes.items():
                family_sizes[size] = family_sizes.get(size, 0) + count
        for t in kept:
            mi_str = t.mi.render() if t.mi is not None else None
            for rec in t.all_records():
                count_read(rec, t.is_duplicate)
                if remove_duplicates and t.is_duplicate:
                    continue
                writer.write_record_bytes(
                    _record_with_flag_and_mi(rec, t.is_duplicate, mi_str,
                                             assigned_tag))
        # pass-through templates are written verbatim: never marked, never
        # MI-tagged, counted as unique (dedup.rs:915-935)
        for t in passthrough:
            metrics.total_templates += 1
            metrics.unique_templates += 1
            for rec in t.all_records():
                count_read(rec, False)
                writer.write_record_bytes(rec.data)
    metrics.unique_reads = metrics.total_reads - metrics.duplicate_reads
    return metrics, dict(sorted(family_sizes.items()))


_METRIC_COLUMNS = [
    "total_templates", "unique_templates", "duplicate_templates",
    "duplicate_rate", "total_reads", "unique_reads", "duplicate_reads",
    "secondary_reads", "supplementary_reads", "missing_tc_tag",
]


def write_metrics(metrics: DedupMetrics, path: str):
    """DedupMetricsOutput TSV (dedup.rs:119-152)."""
    row = {c: getattr(metrics, c) for c in _METRIC_COLUMNS if c != "duplicate_rate"}
    row["duplicate_rate"] = f"{metrics.duplicate_rate():.6f}"
    from ..utils.atomic import open_output

    with open_output(path, "w") as f:
        f.write("\t".join(_METRIC_COLUMNS) + "\n")
        f.write("\t".join(str(row[c]) for c in _METRIC_COLUMNS) + "\n")


def write_family_size_histogram(family_sizes: dict, path: str):
    from ..utils.atomic import open_output

    with open_output(path, "w") as f:
        f.write("family_size\tcount\n")
        for size, count in family_sizes.items():
            f.write(f"{size}\t{count}\n")
