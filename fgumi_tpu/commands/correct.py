"""correct: error-correct UMIs (or sample barcodes) to a fixed whitelist.

Mirrors /root/reference/src/lib/commands/correct.rs:
- whitelist from --umis and/or --umi-files, uppercased, deduped, sorted,
  uniform length required (load_umi_sequences, correct.rs:563-595);
- ambiguity warning for whitelist pairs within min-distance-diff - 1
  (check_umi_distances, correct.rs:600-624; --min-distance 0 reports nothing,
  matching fgbio's signed arithmetic);
- per template: one consistent UMI across all records (mismatched UMIs or
  inconsistent presence is an error; non-Z tag type is an error;
  extract_and_validate_template_umi_raw, correct.rs:770-835);
- matching: per '-'-separated segment, nearest whitelist entry by Hamming
  distance; accept when best <= max-mismatches AND second_best - best >=
  min-distance-diff (find_best_match_encoded, correct.rs:1578-1643) — the
  whole-whitelist distance sweep is vectorized over a byte matrix;
- --revcomp reverse-complements each segment and reverses segment order
  before matching (correct.rs:639-643);
- accepted templates: sequence tag updated, original stashed in the original
  tag when there were actual mismatches (unless --dont-store-original);
  rejected templates: dropped from the main output, optionally routed to a
  --rejects BAM (correct.rs:1037-1085);
- per-UMI metrics credited per segment for every correct-length template
  BEFORE the accept/reject decision; unmatched segments credit the all-N
  bucket; missing-UMI and wrong-length templates credit nothing
  (credit_umi_metrics, correct.rs:735-765);
- --min-corrected: fail the run when kept/total falls below the threshold
  (correct.rs:1220-1229);
- --target umi reads/writes RX with original in OX; --target barcode
  reads/writes BC with original in the fgumi-local ob tag (Target,
  correct.rs:100-131).
"""

import logging
from collections import OrderedDict
from dataclasses import dataclass, field

import numpy as np

from ..constants import reverse_complement_bytes
from ..core.template import iter_name_groups
from ..io.bam import RawRecord

log = logging.getLogger("fgumi_tpu.correct")

TARGET_TAGS = {
    "umi": (b"RX", b"OX"),
    "barcode": (b"BC", b"ob"),
}


def load_umi_sequences(umis=(), umi_files=()):
    """(sorted unique uppercased UMIs, length); uniform length required."""
    umi_set = {u.upper() for u in umis}
    for path in umi_files:
        with open(path) as f:
            for line in f:
                u = line.strip().upper()
                if u:
                    umi_set.add(u)
    if not umi_set:
        raise ValueError("At least one UMI or UMI file must be provided.")
    seqs = sorted(umi_set)
    length = len(seqs[0])
    if any(len(u) != length for u in seqs):
        raise ValueError("All UMIs must have the same length.")
    return seqs, length


def find_umi_pairs_within_distance(umis, distance):
    """All whitelist pairs within `distance` mismatches (correct.rs:1668-1683).
    One row of the distance matrix at a time keeps memory at O(N*L) even for
    barcode whitelists with tens of thousands of entries."""
    pairs = []
    mat = np.frombuffer("".join(umis).encode(), dtype=np.uint8)
    mat = mat.reshape(len(umis), -1)
    for i in range(len(umis) - 1):
        dists = (mat[i + 1:] != mat[i][None, :]).sum(axis=1)
        for off in np.nonzero(dists <= distance)[0]:
            j = i + 1 + int(off)
            pairs.append((umis[i], umis[j], int(dists[off])))
    return pairs


class UmiMatcher:
    """Nearest-whitelist matching with an LRU cache over observed segments.

    The per-observation sweep compares the observed segment against the whole
    whitelist at once as a numpy byte-matrix reduction (the vectorized
    equivalent of the reference's BitEnc XOR/popcount loop).
    """

    def __init__(self, umis, max_mismatches: int, min_distance_diff: int,
                 cache_size: int = 100_000):
        self.umis = umis
        self.matrix = np.frombuffer("".join(umis).encode(), dtype=np.uint8)
        self.matrix = self.matrix.reshape(len(umis), -1)
        self.max_mismatches = max_mismatches
        self.min_distance_diff = min_distance_diff
        self.cache_size = cache_size
        self._cache = OrderedDict()

    def find_best(self, observed: bytes):
        """(matched, best_umi, mismatches) for one uppercased segment."""
        hit = self._cache.get(observed)
        if hit is not None:
            self._cache.move_to_end(observed)
            return hit
        obs = np.frombuffer(observed, dtype=np.uint8)
        dists = (self.matrix != obs[None, :]).sum(axis=1)
        best_i = int(dists.argmin())
        best = int(dists[best_i])
        if len(dists) > 1:
            second = int(np.partition(dists, 1)[1])
        else:
            second = np.iinfo(np.int64).max
        matched = best <= self.max_mismatches and (second - best) >= self.min_distance_diff
        result = (matched, self.umis[best_i], best)
        if self.cache_size > 0:
            self._cache[observed] = result
            if len(self._cache) > self.cache_size:
                self._cache.popitem(last=False)
        return result


@dataclass
class TemplateCorrection:
    matched: bool
    corrected_umi: str | None
    original_umi: str
    needs_correction: bool
    has_mismatches: bool
    matches: list
    rejection: str  # '' | 'wrong_length' | 'mismatched'


def compute_template_correction(umi: str, umi_length: int, revcomp: bool,
                                matcher: UmiMatcher) -> TemplateCorrection:
    """correct.rs:627-717."""
    if revcomp:
        segments = [reverse_complement_bytes(s.encode()).decode()
                    for s in umi.split("-")][::-1]
    else:
        segments = umi.split("-")
    if any(len(s) != umi_length for s in segments):
        return TemplateCorrection(False, None, umi, False, False, [], "wrong_length")
    matches = [matcher.find_best(s.upper().encode()) for s in segments]
    all_matched = all(m[0] for m in matches)
    has_mismatches = any(m[2] > 0 for m in matches)
    if all_matched:
        corrected = "-".join(m[1] for m in matches)
        return TemplateCorrection(True, corrected, umi,
                                  has_mismatches or revcomp, has_mismatches,
                                  matches, "")
    return TemplateCorrection(False, None, umi, False, False, matches, "mismatched")


def extract_template_umi(records, umi_tag: bytes):
    """One consistent UMI per template or None (correct.rs:770-835)."""
    first = None
    first_present = None
    for rec in records:
        got = rec.find_tag(umi_tag)
        if got is not None and got[0] != "Z":
            raise ValueError(
                f"UMI tag {umi_tag.decode()} exists but has non-string type "
                f"{got[0]!r}, expected 'Z'")
        umi = got[1] if got is not None else None
        if first_present is None:
            first, first_present = umi, umi is not None
        else:
            if (umi is not None) != first_present:
                raise ValueError(
                    "Template has inconsistent UMI presence across records")
            if umi is not None and umi != first:
                raise ValueError(
                    f"Template has mismatched UMIs: first={first!r}, "
                    f"current={umi!r}")
    return first


def apply_correction(rec: RawRecord, correction: TemplateCorrection,
                     umi_tag: bytes, original_tag: bytes,
                     store_original: bool) -> bytes:
    if not correction.needs_correction:
        return rec.data
    data = rec.data_without_tag(umi_tag)
    if store_original and correction.has_mismatches:
        data = RawRecord(data).data_without_tag(original_tag)
        data += original_tag + b"Z" + correction.original_umi.encode() + b"\x00"
    data += umi_tag + b"Z" + correction.corrected_umi.encode() + b"\x00"
    return data


@dataclass
class CorrectStats:
    templates: int = 0
    records_written: int = 0
    missing_umis: int = 0
    wrong_length: int = 0
    mismatched: int = 0
    umi_metrics: dict = field(default_factory=dict)  # umi -> [total, m0, m1, m2, m3+]


def _credit(metrics: dict, matches, num_records: int, unmatched_umi: str):
    """credit_umi_metrics (correct.rs:735-765)."""
    for matched, umi, mismatches in matches:
        if matched:
            row = metrics.setdefault(umi, [0, 0, 0, 0, 0])
            row[0] += num_records
            row[min(mismatches, 3) + 1] += num_records
        else:
            metrics.setdefault(unmatched_umi, [0, 0, 0, 0, 0])[0] += num_records


def run_correct(reader, writer, matcher: UmiMatcher, umi_length: int, *,
                target: str = "umi", revcomp: bool = False,
                store_original: bool = True, rejects_writer=None) -> CorrectStats:
    """Stream reader -> writer correcting template UMIs."""
    umi_tag, original_tag = TARGET_TAGS[target]
    stats = CorrectStats()
    unmatched_umi = "N" * umi_length
    for _name, records in iter_name_groups(reader):
        stats.templates += 1
        umi = extract_template_umi(records, umi_tag)
        if umi is None:
            # missing UMIs never credit the all-N metric bucket
            # (CorrectUmis.scala:199-202 via correct.rs:1018-1024)
            stats.missing_umis += len(records)
            if rejects_writer is not None:
                for rec in records:
                    rejects_writer.write_record_bytes(rec.data)
            continue
        correction = compute_template_correction(umi, umi_length, revcomp, matcher)
        if correction.matches:
            _credit(stats.umi_metrics, correction.matches, len(records),
                    unmatched_umi)
        if correction.matched:
            for rec in records:
                writer.write_record_bytes(
                    apply_correction(rec, correction, umi_tag, original_tag,
                                     store_original))
                stats.records_written += 1
        else:
            if correction.rejection == "wrong_length":
                stats.wrong_length += len(records)
            else:
                stats.mismatched += len(records)
            if rejects_writer is not None:
                for rec in records:
                    rejects_writer.write_record_bytes(rec.data)
    return stats


_METRIC_COLUMNS = ["umi", "total_matches", "perfect_matches",
                   "one_mismatch_matches", "two_mismatch_matches",
                   "other_matches", "fraction_of_matches", "representation"]


def write_correction_metrics(stats: CorrectStats, umi_length: int, path: str):
    """UmiCorrectionMetrics TSV, fraction/representation semantics matching
    finalize_metrics (correct.rs:867-900): NaN/inf allowed when empty."""
    unmatched = "N" * umi_length
    metrics = stats.umi_metrics
    total = sum(row[0] for row in metrics.values())
    matched_total = sum(row[0] for umi, row in metrics.items() if umi != unmatched)
    umi_count = sum(1 for umi in metrics if umi != unmatched)
    mean = matched_total / umi_count if umi_count else float("nan")
    from ..utils.atomic import open_output

    with open_output(path, "w") as f:
        f.write("\t".join(_METRIC_COLUMNS) + "\n")
        for umi in sorted(metrics):
            row = metrics[umi]
            frac = row[0] / total if total else float("nan")
            rep = row[0] / mean if mean else float("nan")
            f.write("\t".join([umi, str(row[0]), str(row[1]), str(row[2]),
                               str(row[3]), str(row[4]), f"{frac:.6f}",
                               f"{rep:.6f}"]) + "\n")
