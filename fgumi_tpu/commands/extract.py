"""extract: FASTQ(.gz) -> unmapped BAM with UMI extraction.

Behavioral parity with the reference's extract command
(/root/reference/src/lib/commands/extract.rs): fgbio read structures allocate
bases to template / sample-barcode / molecular-barcode / cell-barcode / skip
segments; molecular segments land in RX (joined '-'), their qualities in QX
(joined ' ', raw ASCII); read-name UMIs (8+ colon fields, 'r'-revcomp prefix,
'+'->'-') can be prepended; quality encoding (Phred+33 vs +64) is detected by
pooling the heads of all inputs (extract.rs:210-338).
"""

import re
from dataclasses import dataclass, field

import numpy as np

from ..core.read_structure import ReadStructure, TEMPLATE
from ..io.bam import FLAG_FIRST, FLAG_LAST, FLAG_MATE_UNMAPPED, FLAG_PAIRED, \
    FLAG_UNMAPPED, BamHeader, BamWriter, RecordBuilder
from ..io.fastq import FastqReader, strip_read_suffix

QUALITY_DETECTION_SAMPLE_SIZE = 400

# Complement preserving unknowns (dna.rs reverse_complement: ACGT<->TGCA, U->A,
# N->N, others pass through and are rejected by UMI validation downstream).
_COMP = bytes.maketrans(b"ACGTUacgtu", b"TGCATtgcat")

_VALID_UMI = re.compile(rb"^[ACGTN-]*$")


def _revcomp_loose(seq: bytes) -> bytes:
    return seq.translate(_COMP)[::-1]


class ExtractError(ValueError):
    pass


def detect_quality_encoding(paths, sample_size=QUALITY_DETECTION_SAMPLE_SIZE):
    """Return the Phred offset (33 or 64) from pooled input heads.

    Decision table mirrors extract.rs:275-338: any byte outside [33,126] is an
    error; min<59 -> 33; min>=64 and max>=75 -> 64; otherwise 33.
    """
    min_q, max_q = 255, 0
    total_bases = 0
    num_records = 0
    for path in paths:
        with FastqReader(path) as reader:
            for i, rec in enumerate(reader):
                if i >= sample_size:
                    break
                num_records += 1
                if rec.quals:
                    min_q = min(min_q, min(rec.quals))
                    max_q = max(max_q, max(rec.quals))
                    total_bases += len(rec.quals)
    if num_records == 0:
        raise ExtractError("Cannot detect quality encoding: no records provided")
    if total_bases == 0:
        return 33
    if min_q < 33 or max_q > 126:
        raise ExtractError(
            f"Invalid quality scores detected: range [{min_q}, {max_q}]. "
            "Quality scores must be in the printable ASCII range (33-126)")
    if min_q < 59:
        return 33
    if min_q >= 64 and max_q >= 75:
        return 64
    return 33


def normalize_read_name_umi(raw: bytes) -> bytes:
    """Normalize a read-name UMI (extract.rs:838-885 / fgbio Umis.scala:85-126).

    Reverse-complements 'r'-prefixed segments, translates the '+' dual-UMI
    delimiter to '-', upper-cases, and rejects characters outside ACGTN-.
    """
    has_r = b"r" in raw
    plus_at = raw.find(b"+")
    has_delim = plus_at > 0  # a leading '+' is not a delimiter
    if has_r and has_delim:
        parts = []
        for seg in raw.split(b"+"):
            if seg.startswith(b"r"):
                parts.append(_revcomp_loose(seg[1:]))
            else:
                parts.append(seg)
        out = b"-".join(parts)
    elif has_r:
        out = _revcomp_loose(raw[1:] if raw.startswith(b"r") else raw)
    elif has_delim:
        out = raw.replace(b"+", b"-")
    else:
        out = raw
    out = out.upper()
    if not _VALID_UMI.match(out):
        bad = next(chr(b) for b in out if not _VALID_UMI.match(bytes([b])))
        raise ExtractError(
            f"Invalid UMI '{out.decode(errors='replace')}' extracted from read "
            f"name (illegal character '{bad}')")
    return out


def extract_read_name_umi(name: bytes) -> bytes | None:
    """The last ':'-field of an 8+-field read name, normalized; else None."""
    parts = name.split(b":")
    if len(parts) >= 8 and parts[-1]:
        return normalize_read_name_umi(parts[-1])
    return None


@dataclass
class ExtractOptions:
    read_structures: list = field(default_factory=list)  # strings
    sample: str = "sample"
    library: str = "library"
    read_group_id: str = "A"
    store_umi_quals: bool = False
    store_cell_quals: bool = False
    store_sample_barcode_quals: bool = False
    extract_umis_from_read_names: bool = False
    annotate_read_names: bool = False
    single_tag: str | None = None
    barcode: str | None = None
    platform: str = "illumina"
    platform_unit: str | None = None
    platform_model: str | None = None
    sequencing_center: str | None = None
    predicted_insert_size: int | None = None
    description: str | None = None
    run_date: str | None = None
    comments: list = field(default_factory=list)
    command_line: str = "fgumi-tpu extract"


# Tags extract itself emits; --single-tag must not collide with these
# (extract.rs:644-649 RESERVED_OUTPUT_TAGS).
_RESERVED_OUTPUT_TAGS = {"RX", "QX", "CB", "CY", "BC", "QT", "RG"}

_SAM_TAG = re.compile(r"^[A-Za-z][A-Za-z0-9]$")


def build_header(opts: ExtractOptions) -> BamHeader:
    """Unmapped-BAM header: @HD SO:unsorted GO:query + one @RG (extract.rs:680-715)."""
    rg = [("ID", opts.read_group_id), ("SM", opts.sample), ("LB", opts.library)]
    if opts.barcode:
        rg.append(("BC", opts.barcode))
    rg.append(("PL", opts.platform))
    for tag, val in (("PU", opts.platform_unit), ("PM", opts.platform_model),
                     ("CN", opts.sequencing_center),
                     ("PI", opts.predicted_insert_size),
                     ("DS", opts.description), ("DT", opts.run_date)):
        if val is not None:
            rg.append((tag, val))
    lines = ["@HD\tVN:1.6\tSO:unsorted\tGO:query",
             "@RG\t" + "\t".join(f"{t}:{v}" for t, v in rg),
             "@PG\tID:fgumi-tpu\tPN:fgumi-tpu\tCL:" + opts.command_line]
    lines += [f"@CO\t{c}" for c in opts.comments]
    return BamHeader(text="\n".join(lines) + "\n", ref_names=[], ref_lengths=[])


def _join(parts, sep: bytes) -> bytes:
    return sep.join(parts) if parts else b""


class Extractor:
    """Stateless per-readset record maker (extract.rs make_raw_records:980-1115)."""

    def __init__(self, structures, opts: ExtractOptions, qual_offset: int):
        self.structures = structures
        self.opts = opts
        self.qual_offset = qual_offset
        self._builder = RecordBuilder()
        template_count = sum(
            sum(1 for s in rs.segments if s.kind == TEMPLATE) for rs in structures)
        if not 1 <= template_count <= 2:
            raise ExtractError(
                f"Read structures must contain 1-2 template segments total, "
                f"found {template_count}")
        if opts.single_tag:
            if not _SAM_TAG.match(opts.single_tag):
                raise ExtractError(
                    f"Single tag must be a two-character SAM tag: {opts.single_tag}")
            if opts.single_tag in _RESERVED_OUTPUT_TAGS:
                raise ExtractError(
                    f"Single tag cannot be one of the tags extract already emits "
                    f"(RX, QX, CB, CY, BC, QT, RG): {opts.single_tag}")
        if opts.extract_umis_from_read_names and opts.store_umi_quals:
            raise ExtractError(
                "--store-umi-quals conflicts with --extract-umis-from-read-names "
                "(read-name UMIs have no qualities)")

    def make_records(self, reads):
        """reads: one FastqRead per input. Yields raw BAM record bytes."""
        opts = self.opts
        # read names must agree across all inputs (extract.rs:887-920)
        name0 = strip_read_suffix(reads[0].name)
        for i, r in enumerate(reads[1:], 1):
            ni = strip_read_suffix(r.name)
            if ni != name0:
                raise ExtractError(
                    f"Read names do not match across FASTQs: "
                    f"'{name0.decode(errors='replace')}' vs "
                    f"'{ni.decode(errors='replace')}' (FASTQ index 0 vs {i})")

        segments = []  # (kind, seq, quals) across all reads, in order
        for r, rs in zip(reads, self.structures):
            err = rs.check_read_length(len(r.seq))
            if err:
                raise ExtractError(
                    f"read '{r.name.decode(errors='replace')}': {err}")
            segments.extend(rs.extract(r.seq, r.quals))

        def seqs(kind):
            return [s for k, s, _ in segments if k == kind and s]

        def qs(kind):
            return [q for k, s, q in segments if k == kind and s]

        cell_bc = _join(seqs("C"), b"-")
        cell_quals = _join(qs("C"), b" ")
        sample_bc = _join(seqs("B"), b"-")
        sample_quals = _join(qs("B"), b" ")
        umi = _join(seqs("M"), b"-")
        umi_quals = _join(qs("M"), b" ")

        umi_from_name = (extract_read_name_umi(name0)
                         if opts.extract_umis_from_read_names else None)
        if umi_from_name and umi:
            final_umi = umi_from_name + b"-" + umi
        else:
            final_umi = umi_from_name or umi

        templates = [(s, q) for k, s, q in segments if k == TEMPLATE]
        num_templates = len(templates)
        name = name0
        if opts.annotate_read_names and final_umi:
            name = name0 + b"+" + final_umi

        for index, (seq, quals) in enumerate(templates):
            flag = FLAG_UNMAPPED
            if num_templates == 2:
                flag |= FLAG_PAIRED | FLAG_MATE_UNMAPPED
                flag |= FLAG_FIRST if index == 0 else FLAG_LAST
            if seq:
                # saturating subtract (to_standard_numeric, extract.rs:256-261):
                # a sub-offset byte past the detection sample clamps to Q0.
                off = self.qual_offset
                qarr = np.frombuffer(quals, dtype=np.uint8)
                numeric = np.where(qarr >= off, qarr - off, 0).astype(np.uint8)
            else:
                # empty template segment -> single N @ Q2 (extract.rs:947-948)
                seq, numeric = b"N", bytearray([2])
            b = self._builder.start_unmapped(name, flag, seq, numeric)
            b.tag_str(b"RG", opts.read_group_id.encode())
            if cell_bc:
                b.tag_str(b"CB", cell_bc)
                if cell_quals and opts.store_cell_quals:
                    b.tag_str(b"CY", cell_quals)
            if sample_bc:
                b.tag_str(b"BC", sample_bc)
                if sample_quals and opts.store_sample_barcode_quals:
                    b.tag_str(b"QT", sample_quals)
            if final_umi:
                b.tag_str(b"RX", final_umi)
                if opts.single_tag:
                    b.tag_str(opts.single_tag.encode(), final_umi)
                if umi_from_name is None and umi_quals and opts.store_umi_quals:
                    b.tag_str(b"QX", umi_quals)
            yield b.finish()


_SEG_KIND_CODE = {TEMPLATE: 0, "M": 1, "S": 2}


def _fast_extract_ok(structures, opts) -> bool:
    """The native batch path covers the common option surface: T/M/S segments
    with any '+' only in last position, and none of the exotic output options
    (cell/sample barcodes, single-tag, name annotation, read-name UMIs)."""
    from ..native import batch as nb

    if not nb.available():
        return False
    if (opts.extract_umis_from_read_names or opts.annotate_read_names
            or opts.single_tag):
        return False
    for rs in structures:
        # every structure must END with a '+' segment: a fully-fixed
        # structure errors on over-long reads in the Python path, which the
        # native walk cannot reproduce
        if rs.segments[-1].length is not None:
            return False
        for i, seg in enumerate(rs.segments):
            if seg.kind not in _SEG_KIND_CODE:
                return False
            if seg.length is None and i != len(rs.segments) - 1:
                return False
            # UMI segments must be fixed-length (bounded native join buffer)
            if seg.kind == "M" and seg.length is None:
                return False
    umi_total = sum((seg.length or 0) + 1 for rs in structures
                    for seg in rs.segments if seg.kind == "M")
    return umi_total < 1000  # native join buffer is 1024 bytes


def _run_extract_fast(inputs, output, structures, opts, offset, header,
                      sink=None):
    """Batched native extraction (fgumi_extract_records): vectorized FASTQ
    lexing + C record assembly, byte-identical to make_records on the
    supported option surface (tests/test_extract_fast.py)."""
    from ..io.fastq import FastqBatchReader
    from ..native import batch as nb

    segments = []
    for k, rs in enumerate(structures):
        for seg in rs.segments:
            segments.append((k, _SEG_KIND_CODE[seg.kind],
                             -1 if seg.length is None else seg.length))
    rg = opts.read_group_id.encode()

    from ..utils.progress import ProgressTracker

    progress = ProgressTracker("extract read sets")
    n_records = 0
    n_sets = 0
    readers = [FastqBatchReader(p) for p in inputs]
    try:
        with (BamWriter(output, header) if sink is None
              else sink(header)) as writer:
            iters = [iter(r) for r in readers]
            cur = [None] * len(readers)  # (arrays tuple, consumed)
            while True:
                for i, it in enumerate(iters):
                    if cur[i] is None or cur[i][1] >= len(cur[i][0][1]):
                        nxt = next(it, None)
                        cur[i] = (nxt, 0) if nxt is not None else None
                if all(c is None for c in cur):
                    break
                if any(c is None for c in cur):
                    short = [inputs[i] for i, c in enumerate(cur) if c is None]
                    raise ExtractError(
                        f"FASTQ inputs have differing record counts; "
                        f"{short} ended early")
                take = min(len(c[0][1]) - c[1] for c in cur)
                bufs = []
                name_off = []
                name_len = []
                seq_off = []
                seq_len = []
                qual_off = []
                for i, (batch, pos) in enumerate(cur):
                    buf, no, nl, so, sl, qo = batch
                    bufs.append(buf)
                    name_off.append(no[pos:pos + take])
                    name_len.append(nl[pos:pos + take])
                    seq_off.append(so[pos:pos + take])
                    seq_len.append(sl[pos:pos + take])
                    qual_off.append(qo[pos:pos + take])
                    cur[i] = (batch, pos + take)
                try:
                    blob = nb.extract_records(
                        bufs, np.stack(name_off), np.stack(name_len),
                        np.stack(seq_off), np.stack(seq_len),
                        np.stack(qual_off), segments, offset, rg,
                        opts.store_umi_quals)
                except nb.NativeExtractError as e:
                    # canonical error path: rebuild the offending record as
                    # FastqReads and let make_records raise its ExtractError
                    from ..io.fastq import FastqRead

                    r = e.record_index
                    reads = []
                    for i, buf in enumerate(bufs):
                        bb = buf.tobytes()
                        reads.append(FastqRead(
                            bb[name_off[i][r]:name_off[i][r] + name_len[i][r]],
                            bb[seq_off[i][r]:seq_off[i][r] + seq_len[i][r]],
                            bb[qual_off[i][r]:qual_off[i][r] + seq_len[i][r]]))
                    extractor = Extractor(structures, opts, offset)
                    list(extractor.make_records(reads))
                    raise ExtractError(str(e))  # native-only failure
                writer.write_serialized(blob)
                n_sets += take
                progress.add(take)
    finally:
        for r in readers:
            r.close()
    progress.finish()
    # each read set emits exactly one record per template segment
    n_templates = sum(1 for s in segments if s[1] == 0)
    n_records = n_sets * n_templates
    return n_records, n_sets


def run_extract(inputs, output, opts: ExtractOptions, sink=None):
    """Full extract: detect encoding, zip FASTQs, write unmapped BAM.

    ``sink`` (optional) replaces the file output: a callable taking the
    output BamHeader and returning a BamWriter-compatible context manager —
    the fused pipeline chain passes a channel-backed writer here so
    extract's records stream straight into sort with no intermediate file.

    Returns (records_written, read_pairs_processed).
    """
    if opts.read_structures:
        if len(opts.read_structures) != len(inputs):
            raise ExtractError(
                f"Number of read structures ({len(opts.read_structures)}) must "
                f"match number of inputs ({len(inputs)})")
        structures = [ReadStructure.parse(rs) for rs in opts.read_structures]
    elif 1 <= len(inputs) <= 2:
        structures = [ReadStructure.parse("+T")] * len(inputs)
    else:
        raise ExtractError(
            "Read structures are required for more than 2 input FASTQs")

    offset = detect_quality_encoding(inputs)
    extractor = Extractor(structures, opts, offset)
    header = build_header(opts)

    if _fast_extract_ok(structures, opts):
        return _run_extract_fast(inputs, output, structures, opts, offset,
                                 header, sink=sink)

    n_records = 0
    n_sets = 0
    readers = [FastqReader(p) for p in inputs]
    try:
        with (BamWriter(output, header) if sink is None
              else sink(header)) as writer:
            iters = [iter(r) for r in readers]
            while True:
                reads = []
                for i, it in enumerate(iters):
                    rec = next(it, None)
                    reads.append(rec)
                if all(r is None for r in reads):
                    break
                if any(r is None for r in reads):
                    short = [inputs[i] for i, r in enumerate(reads) if r is None]
                    raise ExtractError(
                        f"FASTQ inputs have differing record counts; "
                        f"{short} ended early")
                n_sets += 1
                for rec_bytes in extractor.make_records(reads):
                    writer.write_record_bytes(rec_bytes)
                    n_records += 1
    finally:
        for r in readers:
            r.close()
    return n_records, n_sets
