"""Vectorized GroupReadsByUmi host path over RecordBatch inputs.

The group-command analog of consensus/fast.py: template formation, position
keys, filtering, and MI-tag record rewriting happen in whole-batch array
passes (native ops from fgumi_tpu.native.batch); only the per-position-group
UMI assignment (strings + the strategy assigner) remains Python, matching
the reference's split where assigners are the algorithmic core
(/root/reference/src/lib/commands/group.rs:505-560) and everything around
them is raw-byte plumbing.

Semantics contract: byte-identical output records, identical filter metrics
and family-size histograms to commands/group.py::run_group on the same
stream (tested in tests/test_fast_group.py). The position group spanning a
batch boundary is carried as Python Templates and runs the per-template
reference path, sharing the assigner (and so the global molecule counter).
"""

import numpy as np

from ..core.template import (UNKNOWN_POS, UNKNOWN_REF, UNKNOWN_STRAND,
                             classify, library_lookup_from_header,
                             read_info_key)
from ..io.bam import (FLAG_FIRST, FLAG_LAST, FLAG_MATE_UNMAPPED, FLAG_PAIRED,
                      FLAG_QC_FAIL, FLAG_REVERSE, FLAG_SECONDARY,
                      FLAG_SUPPLEMENTARY, FLAG_UNMAPPED)
from ..native import batch as nb
from .group import (FilterMetrics, append_mi_tag, assign_group, extract_umi,
                    filter_template, pair_orientation)

_ACCEPT, _POOR, _NONPF, _NS, _SHORT = 0, 1, 2, 3, 4


class _PySeg:
    """Carried-group segment of python Templates (tail merges, weird UMIs);
    filtered and tallied at group closure."""

    __slots__ = ("templates",)

    def __init__(self, templates):
        self.templates = templates


class _ArrSeg:
    """Carried-group segment backed by a retained RecordBatch: templates
    were filtered/tallied at batch time; closure only assigns + rewrites."""

    __slots__ = ("batch", "umis", "okeys", "out_rows")

    def __init__(self, batch, umis, okeys, out_rows):
        self.batch = batch
        self.umis = umis        # list[str], kept templates in order
        self.okeys = okeys      # list[orientation key | None]
        self.out_rows = out_rows  # (rows_flat int64[], counts int64[])


class FastGrouper:
    """Batch GroupReadsByUmi engine. Feed RecordBatches; collect wire chunks."""

    # per-batch tags beyond umi_tag fetched in ONE fused aux scan;
    # subclasses extend with their own lookups
    _PREFETCH_TAGS = [b"RG", b"MQ"]

    def __init__(self, header, assigner, *, umi_tag=b"RX", assigned_tag=b"MI",
                 min_mapq=1, include_non_pf=False, min_umi_length=None,
                 no_umi=False, allow_unmapped=False):
        self.assigner = assigner
        self.umi_tag = umi_tag
        self.assigned_tag = assigned_tag
        self.min_mapq = min_mapq
        self.include_non_pf = include_non_pf
        self.min_umi_length = min_umi_length
        self.no_umi = no_umi
        self.allow_unmapped = allow_unmapped
        self.library_of = library_lookup_from_header(header.text)
        libs = sorted(set(self.library_of.values()) | {"unknown"})
        self._lib_ord = {lib: i for i, lib in enumerate(libs)}
        self._rg_to_ord = {rg: self._lib_ord[lib]
                           for rg, lib in self.library_of.items()}
        self.metrics = FilterMetrics()
        self.family_sizes = {}
        self.position_group_sizes = {}
        self.records_out = 0
        self._carry = []        # python Templates of the open position group
        self._carry_key = None  # their read_info_key
        self._tail = None       # the held-back, possibly-split last template

    # ------------------------------------------------------------------ slow

    def _template_key(self, t):
        r = t.primary_r1 or t.r2
        rg = r.get_str(b"RG") if r is not None else None
        return read_info_key(t, self.library_of.get(rg, "unknown"))

    def _emit_slow_group(self, templates):
        """One position group through the reference per-template path."""
        m = self.metrics
        kept = [t for t in templates
                if filter_template(t, umi_tag=self.umi_tag,
                                   min_mapq=self.min_mapq,
                                   include_non_pf=self.include_non_pf,
                                   min_umi_length=self.min_umi_length,
                                   no_umi=self.no_umi,
                                   allow_unmapped=self.allow_unmapped,
                                   metrics=m)]
        if not kept:
            return []
        m.accepted += sum(len(t.primary_records()) for t in kept)
        assign_group(kept, self.assigner, self.umi_tag, self.min_umi_length,
                     self.no_umi)
        self._tally(kept)
        out = bytearray()
        for t in kept:
            mi = t.mi.render()
            for rec in t.primary_records():
                data = append_mi_tag(rec, mi, self.assigned_tag)
                out += len(data).to_bytes(4, "little") + data
                self.records_out += 1
        return [bytes(out)] if out else []

    def _tally(self, kept):
        sizes = {}
        for t in kept:
            key = t.mi.render()
            sizes[key] = sizes.get(key, 0) + 1
        for size in sizes.values():
            self.family_sizes[size] = self.family_sizes.get(size, 0) + 1
        pg = sum(sizes.values())
        self.position_group_sizes[pg] = \
            self.position_group_sizes.get(pg, 0) + 1

    def _resolve_tail(self):
        """The held-back template is now known complete: join the open group
        or close it and start a new one."""
        if self._tail is None:
            return []
        tail, self._tail = self._tail, None
        tk = self._template_key(tail)
        if self._carry and tk == self._carry_key:
            self._carry.append(_PySeg([tail]))
            return []
        out = self._flush_carry()
        self._carry = [_PySeg([tail])]
        self._carry_key = tk
        return out

    def _flush_carry(self):
        """Close the open position group: one assignment over every carried
        segment's templates, then per-segment emission (native rewrite for
        array segments). Groups spanning many batches — the degenerate
        all-unmapped single-group input the reference's parallel assigners
        exist for (group.rs:366-498) — stay vectorized end to end."""
        segs, self._carry, self._carry_key = self._carry, [], None
        if not segs:
            return []
        # per-template entries in stream order: (umi, okey, emitter info)
        umis = []
        okeys = []
        emit_plan = []  # per seg: ("arr", seg) | ("py", kept templates)
        m = self.metrics
        for seg in segs:
            if isinstance(seg, _PySeg):
                kept = [t for t in seg.templates
                        if filter_template(
                            t, umi_tag=self.umi_tag, min_mapq=self.min_mapq,
                            include_non_pf=self.include_non_pf,
                            min_umi_length=self.min_umi_length,
                            no_umi=self.no_umi,
                            allow_unmapped=self.allow_unmapped, metrics=m)]
                m.accepted += sum(len(t.primary_records()) for t in kept)
                for t in kept:
                    if self.no_umi:
                        umis.append("")
                    else:
                        umis.append(extract_umi(t, self.umi_tag,
                                                self.assigner))
                    okeys.append(pair_orientation(t)
                                 if self.assigner.split_by_orientation()
                                 else None)
                emit_plan.append(("py", kept))
            else:
                umis.extend(seg.umis)
                okeys.extend(seg.okeys)
                emit_plan.append(("arr", seg))
        total = len(umis)
        if total == 0:
            return []

        # orientation subgrouping + truncation + assignment (assign_group)
        from ..umi.assigners import render_mis_array

        rendered = render_mis_array(self._assign_umis(umis, okeys))
        self._tally_family_sizes(rendered)
        self.position_group_sizes[total] = \
            self.position_group_sizes.get(total, 0) + 1

        out = []
        pos = 0
        for plan in emit_plan:
            if plan[0] == "py":
                blob = bytearray()
                for t in plan[1]:
                    mi = rendered[pos].decode()
                    pos += 1
                    for rec in t.primary_records():
                        data = append_mi_tag(rec, mi, self.assigned_tag)
                        blob += len(data).to_bytes(4, "little") + data
                        self.records_out += 1
                if blob:
                    out.append(bytes(blob))
            else:
                seg = plan[1]
                rows_flat, counts = seg.out_rows
                k = len(seg.umis)
                # one repeat expands template values to record values
                values = np.repeat(rendered[pos:pos + k],
                                   np.asarray(counts, dtype=np.int64))
                pos += k
                out.extend(self._flush_pending(seg.batch, rows_flat,
                                               values))
        return out

    def flush(self):
        """End of stream: resolve the held template and close the open group."""
        out = self._resolve_tail()
        out.extend(self._flush_carry())
        return out

    # ----------------------------------------------------------------- driver

    def process_batch(self, batch):
        """The last template of a batch may be SPLIT across the batch
        boundary, making its position key unreliable; it is held back
        (`_tail`) until the next batch proves it complete, and the last
        complete position group stays open (`_carry`) since the tail may
        belong to it. Both run the reference per-template path; call
        flush() after the last batch."""
        n = batch.n
        if n == 0:
            return []
        buf = batch.buf
        # one native aux scan covers every tag the phases of this engine
        # read (FastDedup extends the list with its tc/CB lookups)
        batch.prefetch_tags([self.umi_tag] + self._PREFETCH_TAGS)
        name_off = batch.data_off + 32
        name_len = (batch.l_read_name - 1).astype(np.int32)
        tstarts = nb.group_starts(buf, np.ascontiguousarray(name_off),
                                  name_len)
        tbounds = np.append(tstarts, n)
        nT = len(tbounds) - 1

        # merge a template split across the batch boundary into the tail
        t0 = 0
        if self._tail is not None and buf[
                name_off[0]:name_off[0] + name_len[0]] \
                .tobytes() == self._tail.name:
            merged = classify(self._tail.all_records()
                              + [batch.raw_record(int(i))
                                 for i in range(tbounds[0], tbounds[1])])
            self._tail = merged
            t0 = 1
        if t0 >= nT:
            return []  # the whole batch merged into the (still open) tail

        # the tail is complete now (a later template exists in this batch)
        out = self._resolve_tail()

        keys = self._template_keys(batch, tbounds, nT)
        nC = nT - 1  # complete templates; the last may continue

        # absorb batch-leading templates continuing the open group
        if self._carry and t0 < nC \
                and self._python_key(batch, tbounds, keys, t0) \
                == self._carry_key:
            diffs = np.nonzero(
                (keys[t0 + 1:nC] != keys[t0:nC - 1]).any(axis=1))[0]
            run_end = (t0 + 1 + int(diffs[0])) if len(diffs) else nC
            self._defer_templates(batch, tbounds,
                                  np.arange(t0, run_end, dtype=np.int64))
            t0 = run_end
        if self._carry and t0 < nC:
            out.extend(self._flush_carry())  # a differing template follows

        if t0 < nC:
            # position-group boundaries among complete templates [t0, nC)
            diff = (keys[t0 + 1:nC] != keys[t0:nC - 1]).any(axis=1)
            gb = [t0] + (np.nonzero(diff)[0] + t0 + 1).tolist() + [nC]
            # the last complete group becomes the new open group
            if len(gb) > 2:
                out.extend(self._process_groups(batch, tbounds, keys,
                                                gb[:-1]))
            last_start = gb[-2]
            assert not self._carry
            self._defer_templates(batch, tbounds,
                                  np.arange(last_start, nC, dtype=np.int64))
            self._carry_key = self._python_key(batch, tbounds, keys,
                                               last_start)

        self._tail = self._materialize(batch, tbounds, nT - 1)
        return out

    def _defer_templates(self, batch, tbounds, ts):
        """Append templates of the open group to the carry: filter + tally
        now (vectorized), carry only the kept templates' UMI strings and
        output rows; non-ASCII-UMI templates carry as python Templates,
        interleaved in stream order (MI numbering is order-sensitive)."""
        if not len(ts):
            return
        cat, weird = self._filter_codes_cached(batch, tbounds)
        cat, weird = cat[ts], weird[ts]
        m = self.metrics
        n_prim = np.zeros(len(ts), dtype=np.int64)
        for sel in (self._r1_of, self._r2_of, self._fr_of):
            n_prim += sel[ts] >= 0
        ok = ~weird
        m.total_templates += int(n_prim[ok].sum())
        for code, attr in ((_POOR, "poor_alignment"), (_NONPF, "non_pf"),
                           (_NS, "ns_in_umi"), (_SHORT, "umi_too_short")):
            c = int(n_prim[ok & (cat == code)].sum())
            if c:
                setattr(m, attr, getattr(m, attr) + c)
        keep = ok & (cat == _ACCEPT)
        m.accepted += int(n_prim[keep].sum())

        def flush_run(run):
            if not run:
                return
            kept_t = np.asarray(run, dtype=np.int64)
            umis, okeys = self._umi_strings(batch, kept_t)
            picks = np.stack([self._fr_of[kept_t], self._r1_of[kept_t],
                              self._r2_of[kept_t]], axis=1)
            rows_flat = picks.ravel()
            rows_flat = rows_flat[rows_flat >= 0]
            counts = (picks >= 0).sum(axis=1)
            self._carry.append(_ArrSeg(batch, umis, okeys,
                                       (rows_flat, counts)))

        run = []
        for li, t in enumerate(ts):
            if weird[li]:
                flush_run(run)
                run = []
                self._carry.append(
                    _PySeg([self._materialize(batch, tbounds, int(t))]))
            elif keep[li]:
                run.append(int(t))
        flush_run(run)

    def _filter_codes_cached(self, batch, tbounds):
        """Full-batch filter categories, computed once per batch (both the
        group processor and the defer path consume slices)."""
        if getattr(self, "_fc_batch", None) is not batch:
            nT = len(tbounds) - 1
            self._fc = self._filter_codes(batch, tbounds, nT, 0, nT)
            self._fc_batch = batch
        return self._fc

    def _umi_strings(self, batch, kept_t):
        """(umis, okeys) for kept templates: the strings assign_group would
        hand the assigner (uppercased; paired-prefix applied), plus the
        orientation subgroup key (None for the paired strategy)."""
        assigner = self.assigner
        uo, ul, _ = batch.tag_locs_str(self.umi_tag)
        buf = batch.buf
        flag = batch.flag

        # representative row per kept template (r1 > fragment > r2) and one
        # blob gather + single upper/decode for every UMI string — the
        # per-template slice/tobytes/decode/upper loop here was ~20% of
        # group wall time
        kt = np.asarray(kept_t, dtype=np.int64)
        r1s, r2s, frs = self._r1_of[kt], self._r2_of[kt], self._fr_of[kt]
        rep = np.where(r1s >= 0, r1s, np.where(frs >= 0, frs, r2s))
        offs = uo[rep]
        lens = np.where(offs >= 0, ul[rep], 0).astype(np.int64)
        if self.no_umi:
            all_umis = [""] * len(kt)
        else:
            from ..native import batch as _nb

            blob, boff = _nb.concat_spans(
                [buf], np.zeros(len(kt), np.int32), offs, lens)
            s = blob.tobytes().upper().decode()
            bo = boff.tolist()
            all_umis = [s[bo[i]:bo[i + 1]] for i in range(len(kt))]

        if assigner.split_by_orientation():
            ok1 = (r1s < 0) | ((flag[np.maximum(r1s, 0)] & FLAG_REVERSE) == 0)
            ok2 = (r2s < 0) | ((flag[np.maximum(r2s, 0)] & FLAG_REVERSE) == 0)
            okeys = list(zip(ok1.tolist(), ok2.tolist()))
            return all_umis, okeys
        umis = []
        okeys = []
        u5 = self._u5_cache(batch)
        lo_p, hi_p = assigner.lower_prefix, assigner.higher_prefix
        for i, t in enumerate(kept_t):
            umi = all_umis[i]
            parts = umi.split("-")
            if len(parts) != 2:
                raise ValueError(
                    "Paired strategy used but UMI did not contain 2 segments "
                    f"delimited by '-': {umi}")
            r1, r2 = self._r1_of[t], self._r2_of[t]
            if r1 >= 0 and r2 >= 0:
                if batch.ref_id[r1] != batch.ref_id[r2]:
                    r1_earlier = batch.ref_id[r1] < batch.ref_id[r2]
                elif u5[r1] != u5[r2]:
                    r1_earlier = u5[r1] < u5[r2]
                else:
                    r1_earlier = not flag[r1] & FLAG_REVERSE
            else:
                r1_earlier = True
            if r1_earlier:
                umis.append(f"{lo_p}:{parts[0]}-{hi_p}:{parts[1]}")
            else:
                umis.append(f"{hi_p}:{parts[0]}-{lo_p}:{parts[1]}")
            okeys.append(None)
        return umis, okeys

    def _materialize(self, batch, tbounds, t):
        return classify(batch.raw_records(
            np.arange(tbounds[t], tbounds[t + 1])))

    # ------------------------------------------------------------------- keys

    def _template_keys(self, batch, tbounds, nT):
        """Per-template position-key fields, (nT, 7) int64:
        lib_ord, a_tid, a_pos, a_strand, b_tid, b_pos, b_strand."""
        n = batch.n
        flag = batch.flag
        secsup = (flag & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY)) != 0
        paired = (flag & FLAG_PAIRED) != 0
        first = (flag & FLAG_FIRST) != 0
        last = (flag & FLAG_LAST) != 0
        role_r1 = ~secsup & paired & first            # classify() elif order
        role_r2 = ~secsup & paired & ~first & last
        role_fr = ~secsup & ~paired
        t_of = np.repeat(np.arange(nT), np.diff(tbounds))

        # last-wins role selection (classify overwrites on duplicates)
        def pick(mask):
            sel = np.full(nT, -1, dtype=np.int64)
            rows = np.nonzero(mask)[0]
            sel[t_of[rows]] = rows  # ascending rows: later assignment wins
            return sel

        self._r1_of = pick(role_r1)
        self._r2_of = pick(role_r2)
        self._fr_of = pick(role_fr)
        self._t_of = t_of

        u5 = self._u5_cache(batch)
        unmapped = (flag & FLAG_UNMAPPED) != 0
        rev = ((flag & FLAG_REVERSE) != 0).astype(np.int64)

        def end_of(sel):
            """(tid, pos, strand) per template for one role; sentinel when
            the role is absent or the read unmapped."""
            has = sel >= 0
            idx = np.where(has, sel, 0)
            ok = has & ~unmapped[idx]
            tid = np.where(ok, batch.ref_id[idx], UNKNOWN_REF)
            pos = np.where(ok, u5[idx], UNKNOWN_POS)
            strand = np.where(ok, rev[idx], UNKNOWN_STRAND)
            return np.stack([tid, pos, strand], axis=1).astype(np.int64), has

        e1, has1 = end_of(self._r1_of)
        e2, has2 = end_of(self._r2_of)
        ef, _ = end_of(self._fr_of)
        # read_info_key: r1/r2 when either exists, else the fragment
        use_frag = ~has1 & ~has2
        e1 = np.where(use_frag[:, None], ef, e1)
        unknown = np.array([UNKNOWN_REF, UNKNOWN_POS, UNKNOWN_STRAND],
                           dtype=np.int64)
        e2 = np.where(use_frag[:, None], unknown[None, :], e2)
        # order ends: lower tuple first (sentinels already sort last)
        swap = ((e1[:, 0] > e2[:, 0])
                | ((e1[:, 0] == e2[:, 0]) & (e1[:, 1] > e2[:, 1]))
                | ((e1[:, 0] == e2[:, 0]) & (e1[:, 1] == e2[:, 1])
                   & (e1[:, 2] > e2[:, 2])))
        a = np.where(swap[:, None], e2, e1)
        b = np.where(swap[:, None], e1, e2)

        # library ordinal from the primary r1 (or fragment, or r2)'s RG
        key_read = np.where(self._r1_of >= 0, self._r1_of,
                            np.where(self._fr_of >= 0, self._fr_of,
                                     self._r2_of))
        lib = np.full(nT, self._lib_ord["unknown"], dtype=np.int64)
        rg_off, rg_len, _ = batch.tag_locs_str(b"RG")
        kr = np.where(key_read >= 0, key_read, 0)
        ro = np.where(key_read >= 0, rg_off[kr], -1)
        rl = rg_len[kr]
        present = ro >= 0
        if present.any():
            hashes = nb.hash_ranges(batch.buf, ro, rl)
            uniq, first_idx, inv = np.unique(hashes, return_index=True,
                                             return_inverse=True)
            reps = first_idx[inv]
            eq = nb.ranges_equal(batch.buf, ro, rl, ro[reps], rl[reps])
            if eq[present].all():
                ords = np.empty(len(uniq), dtype=np.int64)
                for u, fi in enumerate(first_idx):
                    if ro[fi] < 0:
                        ords[u] = self._lib_ord["unknown"]
                        continue
                    rg = batch.buf[ro[fi]:ro[fi] + rl[fi]].tobytes() \
                        .decode(errors="replace")
                    ords[u] = self._rg_to_ord.get(rg,
                                                  self._lib_ord["unknown"])
                lib = ords[inv].copy()
                lib[~present] = self._lib_ord["unknown"]
            else:
                for t in np.nonzero(present)[0]:
                    rg = batch.buf[ro[t]:ro[t] + rl[t]].tobytes() \
                        .decode(errors="replace")
                    lib[t] = self._rg_to_ord.get(rg,
                                                 self._lib_ord["unknown"])
        return np.concatenate([lib[:, None], a, b], axis=1)

    def _python_key(self, batch, tbounds, keys, t):
        """The canonical python read_info_key of template t (for cross-batch
        carry comparisons; within-batch equality uses the int key rows)."""
        return self._template_key(self._materialize(batch, tbounds, t))

    # ----------------------------------------------------------------- filter

    def _filter_codes(self, batch, tbounds, nT, t_lo, t_hi):
        """Per-template accept/reject category, replicating the reference's
        first-failing-check attribution (filter_template evaluation order)."""
        flag = batch.flag
        m = self.min_mapq

        def arr(sel, field, default):
            idx = np.where(sel >= 0, sel, 0)
            return np.where(sel >= 0, field[idx], default)

        roles = [self._r1_of, self._r2_of, self._fr_of]
        # reads order in filter_template: r1, r2, fragment
        unmapped = (flag & FLAG_UNMAPPED) != 0
        qcfail = (flag & FLAG_QC_FAIL) != 0
        paired = (flag & FLAG_PAIRED) != 0
        mate_unmapped = (flag & FLAG_MATE_UNMAPPED) != 0

        mq_val = self._mq_values(batch)
        uo, ul, _ = batch.tag_locs_str(self.umi_tag)
        has_n, bases, ascii_ok = nb.umi_scan(batch.buf, uo, ul)

        conds = []
        codes = []

        def add(cond, code):
            conds.append(cond)
            codes.append(code)

        # primaries empty -> poor (no primary records at all)
        n_prim = np.zeros(nT, dtype=np.int64)
        for sel in roles:
            n_prim += sel >= 0
        add(n_prim == 0, _POOR)

        # both_unmapped (over present reads) and not allow_unmapped
        if not self.allow_unmapped:
            all_unmapped = np.ones(nT, dtype=bool)
            for sel in roles:
                r_unmapped = arr(sel, unmapped, True)
                all_unmapped &= np.where(sel >= 0, r_unmapped, True)
            add((n_prim > 0) & all_unmapped, _POOR)

        # loop 1 per read: qc-fail then mapq
        for sel in roles:
            present = sel >= 0
            if not self.include_non_pf:
                add(present & arr(sel, qcfail, False), _NONPF)
            r_unmapped = arr(sel, unmapped, True)
            mapq = arr(sel, batch.mapq.astype(np.int64), m)
            add(present & ~r_unmapped & (mapq < m), _POOR)

        # loop 2 per read: MQ tag, then UMI checks
        for sel in roles:
            present = sel >= 0
            r_paired = arr(sel, paired, False)
            r_mu = arr(sel, mate_unmapped, True)
            mq = arr(sel, mq_val, np.int64(1 << 40))
            add(present & r_paired & ~r_mu & (mq < m), _POOR)
            if not self.no_umi:
                u_off = arr(sel, uo, -1)
                add(present & (u_off < 0), _POOR)
                add(present & arr(sel, has_n.astype(bool), False), _NS)
                if self.min_umi_length is not None:
                    add(present
                        & (arr(sel, bases.astype(np.int64), 1 << 40)
                           < self.min_umi_length), _SHORT)

        cat = np.select(conds, codes, default=_ACCEPT)[t_lo:t_hi]

        # non-ASCII UMI bytes route the group through the python path (their
        # decoded character count can differ from the byte count)
        weird = np.zeros(nT, dtype=bool)
        if not self.no_umi:
            for sel in roles:
                weird |= (sel >= 0) & ~arr(sel, ascii_ok.astype(bool), True)
        return cat, weird[t_lo:t_hi]

    def _mq_values(self, batch):
        """Per-record MQ tag as int64 (absent/non-integer -> huge sentinel,
        which never fails the < min_mapq check — get_int None semantics)."""
        vo, vl, vt = batch.tag_locs(b"MQ")
        buf = batch.buf
        val = np.full(batch.n, 1 << 40, dtype=np.int64)
        for code, width, signed in (("c", 1, True), ("C", 1, False),
                                    ("s", 2, True), ("S", 2, False),
                                    ("i", 4, True), ("I", 4, False)):
            mask = (vt == ord(code)) & (vo >= 0)
            if not mask.any():
                continue
            offs = vo[mask]
            v = np.zeros(len(offs), dtype=np.int64)
            for j in range(width):
                v |= buf[offs + j].astype(np.int64) << (8 * j)
            if signed:
                sign_bit = np.int64(1) << (8 * width - 1)
                v = (v ^ sign_bit) - sign_bit
            val[mask] = v
        return val

    # ----------------------------------------------------------------- groups

    def _process_groups(self, batch, tbounds, keys, gb):
        """Vectorized filter + python assignment + native MI rewrite for
        complete groups gb[0]..gb[-1]."""
        m = self.metrics
        t_lo, t_hi = gb[0], gb[-1]
        cat, weird = self._filter_codes_cached(batch, tbounds)
        cat, weird = cat[t_lo:t_hi], weird[t_lo:t_hi]
        sizes_prim = np.zeros(t_hi - t_lo, dtype=np.int64)
        for sel in (self._r1_of, self._r2_of, self._fr_of):
            sizes_prim += sel[t_lo:t_hi] >= 0

        out = []
        # accumulated fast-group output, emitted in one vectorized pass:
        # assignment stays per group (the algorithm is per position group)
        # but rendering, family tallies, and row/value expansion run ONCE
        # over the whole accumulation (render_mis_array) — the per-template
        # render/encode/append loop was ~0.25 s/run of pure Python
        acc_mols = []  # MoleculeIds, template order across fast groups
        acc_kept = []  # kept template-index arrays

        def flush_fast():
            if not acc_mols:
                return []
            from ..umi.assigners import render_mis_array

            rend = render_mis_array(acc_mols)
            # MI values are globally unique per family (the deterministic
            # counter), so one tally covers every group in the accumulation
            self._tally_family_sizes(rend)
            kept_all = np.concatenate(acc_kept)
            acc_mols.clear()
            acc_kept.clear()
            sels = np.stack([self._fr_of[kept_all], self._r1_of[kept_all],
                             self._r2_of[kept_all]], axis=1)
            valid = sels >= 0
            rows = sels[valid]
            values = np.repeat(rend, valid.sum(axis=1))
            return self._flush_pending(batch, rows, values)

        for gi in range(len(gb) - 1):
            lo, hi = gb[gi] - t_lo, gb[gi + 1] - t_lo
            g_cat = cat[lo:hi]
            if weird[lo:hi].any():
                # rare: python path for the whole group, after flushing the
                # pending fast output to preserve stream order
                out.extend(flush_fast())
                out.extend(self._emit_slow_group(
                    [self._materialize(batch, tbounds, t)
                     for t in range(gb[gi], gb[gi + 1])]))
                continue
            # metrics: total per template; category counters
            g_sizes = sizes_prim[lo:hi]
            m.total_templates += int(g_sizes.sum())
            for code, attr in ((_POOR, "poor_alignment"), (_NONPF, "non_pf"),
                               (_NS, "ns_in_umi"), (_SHORT, "umi_too_short")):
                c = int(g_sizes[g_cat == code].sum())
                if c:
                    setattr(m, attr, getattr(m, attr) + c)
            kept_t = np.nonzero(g_cat == _ACCEPT)[0] + gb[gi]
            if not len(kept_t):
                continue
            m.accepted += int(g_sizes[g_cat == _ACCEPT].sum())

            mols = self._assign_light(batch, kept_t)
            self.position_group_sizes[len(mols)] = \
                self.position_group_sizes.get(len(mols), 0) + 1
            acc_mols.extend(mols)
            acc_kept.append(kept_t)

        out.extend(flush_fast())
        return out

    def _tally_family_sizes(self, rendered):
        """Family multiplicities from rendered MI values: two unique passes
        (vectorized Counter-of-Counter). Safe across position groups — MI
        values are globally unique per family."""
        _, fam_counts = np.unique(rendered, return_counts=True)
        for size, cnt in zip(*np.unique(fam_counts, return_counts=True)):
            self.family_sizes[int(size)] = \
                self.family_sizes.get(int(size), 0) + int(cnt)

    def _flush_pending(self, batch, rows, values):
        if len(rows) == 0:
            return []
        try:
            blob = nb.rewrite_tag_records(
                batch, np.asarray(rows, dtype=np.int64), self.assigned_tag,
                values)
        except ValueError:
            # malformed aux region somewhere in the run: per-record python
            # editor (identical output, tolerant TLV walk)
            parts = []
            for r, v in zip(rows, values):
                data = append_mi_tag(batch.raw_record(int(r)),
                                     v.decode(), self.assigned_tag)
                parts.append(len(data).to_bytes(4, "little") + data)
            blob = b"".join(parts)
        self.records_out += len(rows)
        return [blob]

    def _assign_light(self, batch, kept_t):
        """UMI extraction + strategy assignment for one group's kept
        templates; returns MoleculeIds in template order."""
        umis, okeys = self._umi_strings(batch, kept_t)
        return self._assign_umis(umis, okeys)

    def _assign_umis(self, umis, okeys):
        """assign_group's subgroup/truncate/assign tail over prepared UMI
        strings; returns MoleculeIds in entry order."""
        assigner = self.assigner
        if not assigner.split_by_orientation():
            return assigner.assign(self._truncate(umis))
        # okeys are (r1_positive, r2_positive) bool pairs over (possibly)
        # hundreds of thousands of templates: one numpy unique+argsort beats
        # a per-template dict walk. Encoding the pair as r1*2+r2 preserves
        # tuple lexicographic order (False < True), so the subgroup
        # assignment order matches the scalar sorted(subgroups.items())
        ok_arr = np.asarray(okeys, dtype=bool)
        inv_raw = (ok_arr[:, 0].astype(np.int8) << 1) | ok_arr[:, 1]
        uniq_ok, inv_ok = np.unique(inv_raw, return_inverse=True)
        mids = [None] * len(umis)
        if len(uniq_ok) == 1:
            sub = umis if self.no_umi else self._truncate(umis)
            for i, mi in enumerate(assigner.assign(sub)):
                mids[i] = mi
            return mids
        order = np.argsort(inv_ok, kind="stable")
        bounds = np.searchsorted(inv_ok[order], np.arange(len(uniq_ok) + 1))
        for g in range(len(uniq_ok)):
            idxs = order[bounds[g]:bounds[g + 1]]
            sub = [umis[i] for i in idxs]
            if not self.no_umi:
                sub = self._truncate(sub)
            for i, mi in zip(idxs, assigner.assign(sub)):
                mids[int(i)] = mi
        return mids

    def _truncate(self, umis):
        if self.min_umi_length is None:
            return umis
        shortest = min((len(u) for u in umis), default=0)
        if shortest < self.min_umi_length:
            raise ValueError(
                f"UMI found that had shorter length than expected "
                f"({shortest} < {self.min_umi_length})")
        return [u[:self.min_umi_length] for u in umis]

    def _u5_cache(self, batch):
        if getattr(self, "_u5_batch", None) is not batch:
            self._u5_arr = nb.unclipped_5prime(batch)
            self._u5_batch = batch
        return self._u5_arr

    def result(self):
        return {
            "records_out": self.records_out,
            "filter": self.metrics.as_dict(),
            "family_sizes": dict(sorted(self.family_sizes.items())),
            "position_group_sizes": dict(
                sorted(self.position_group_sizes.items())),
        }


class FastDedup(FastGrouper):
    """Batch dedup engine (commands/dedup.py semantics over RecordBatches).

    Reuses the grouper's template/key/filter machinery; differs in
    per-template metric counting, the unmapped pass-through split, Picard
    best-template selection, duplicate-flag + MI record rewriting over ALL
    records (incl. secondary/supplementary), and per-read output metrics.
    Groups with CB cell barcodes or --no-umi run the reference per-template
    path (rare); so does the batch-boundary carry.
    """

    # the dedup phases additionally read tc (template-coordinate keys from
    # zipper) and CB (cell partitions) — same fused scan
    _PREFETCH_TAGS = FastGrouper._PREFETCH_TAGS + [b"tc", b"CB"]

    def __init__(self, header, assigner, *, umi_tag=b"RX", assigned_tag=b"MI",
                 min_mapq=0, include_non_pf=False, min_umi_length=None,
                 no_umi=False, include_unmapped=False,
                 remove_duplicates=False):
        from .dedup import DedupMetrics

        super().__init__(header, assigner, umi_tag=umi_tag,
                         assigned_tag=assigned_tag, min_mapq=min_mapq,
                         include_non_pf=include_non_pf,
                         min_umi_length=min_umi_length, no_umi=no_umi,
                         allow_unmapped=False)
        self.include_unmapped = include_unmapped
        self.remove_duplicates = remove_duplicates
        self.dmetrics = DedupMetrics()
        self.metrics = self.dmetrics.filter  # FilterMetrics slot

    # ------------------------------------------------------------------ slow

    def _defer_templates(self, batch, tbounds, ts):
        for t in ts:
            self._carry.append(
                _PySeg([self._materialize(batch, tbounds, int(t))]))

    def _flush_carry(self):
        segs, self._carry, self._carry_key = self._carry, [], None
        templates = [t for seg in segs for t in seg.templates]
        return self._emit_slow_group(templates) if templates else []

    def _emit_slow_group(self, templates):
        from .dedup import (_record_with_flag_and_mi, filter_dedup_template,
                            is_unmapped_passthrough, process_group)

        dm = self.dmetrics
        passthrough, candidates = [], templates
        if self.include_unmapped:
            passthrough, candidates = [], []
            for t in templates:
                (passthrough if is_unmapped_passthrough(t)
                 else candidates).append(t)
        kept = [t for t in candidates
                if filter_dedup_template(t, umi_tag=self.umi_tag,
                                         min_mapq=self.min_mapq,
                                         include_non_pf=self.include_non_pf,
                                         min_umi_length=self.min_umi_length,
                                         no_umi=self.no_umi,
                                         metrics=dm.filter)]
        if kept:
            sizes = process_group(kept, self.assigner, umi_tag=self.umi_tag,
                                  min_umi_length=self.min_umi_length,
                                  no_umi=self.no_umi, metrics=dm)
            for size, count in sizes.items():
                self.family_sizes[size] = \
                    self.family_sizes.get(size, 0) + count
        out = bytearray()

        def emit(data):
            out.extend(len(data).to_bytes(4, "little") + data)
            self.records_out += 1

        for t in kept:
            mi_str = t.mi.render() if t.mi is not None else None
            for rec in t.all_records():
                self._count_read_slow(rec, t.is_duplicate)
                if self.remove_duplicates and t.is_duplicate:
                    continue
                emit(_record_with_flag_and_mi(rec, t.is_duplicate, mi_str,
                                              self.assigned_tag))
        for t in passthrough:
            dm.total_templates += 1
            dm.unique_templates += 1
            for rec in t.all_records():
                self._count_read_slow(rec, False)
                emit(rec.data)
        return [bytes(out)] if out else []

    def _count_read_slow(self, rec, is_dup):
        dm = self.dmetrics
        dm.total_reads += 1
        if is_dup:
            dm.duplicate_reads += 1
        sec = rec.flag & FLAG_SECONDARY
        sup = rec.flag & FLAG_SUPPLEMENTARY
        if sec:
            dm.secondary_reads += 1
        if sup:
            dm.supplementary_reads += 1
        if (sec or sup) and rec.find_tag(b"tc") is None:
            dm.missing_tc_tag += 1

    # ----------------------------------------------------------------- groups

    def _process_groups(self, batch, tbounds, keys, gb):
        from .dedup import (PICARD_MAX_SCORE_PER_READ, PICARD_MIN_BASE_QUALITY,
                            PICARD_QC_FAIL_DISCOUNT, _family_key)
        from ..io.bam import FLAG_DUPLICATE

        dm = self.dmetrics
        m = dm.filter
        t_lo, t_hi = gb[0], gb[-1]
        cat, weird = self._filter_codes_cached(batch, tbounds)
        cat, weird = cat[t_lo:t_hi], weird[t_lo:t_hi]
        flag = batch.flag
        unmapped = (flag & FLAG_UNMAPPED) != 0
        qcfail = (flag & FLAG_QC_FAIL) != 0
        tc_off, _tc_len, _ = batch.tag_locs(b"tc")
        cb_off, _cb_len, _ = batch.tag_locs_str(b"CB")

        # per-template passthrough mask: has primaries and all unmapped
        nT = len(tbounds) - 1
        n_prim = np.zeros(nT, dtype=np.int64)
        all_unm = np.ones(nT, dtype=bool)
        for sel in (self._r1_of, self._r2_of, self._fr_of):
            has = sel >= 0
            n_prim += has
            idx = np.where(has, sel, 0)
            all_unm &= np.where(has, unmapped[idx], True)
        passthrough_t = (n_prim > 0) & all_unm if self.include_unmapped \
            else np.zeros(nT, dtype=bool)

        scores = None  # computed lazily: slow-routed batches never need it
        name_off = batch.data_off + 32
        name_len = batch.l_read_name - 1

        out = []
        pending_rows = []
        pending_flags = []
        pending_values = []

        def flush_pending():
            if not pending_rows:
                return
            blob = self._rewrite(batch, pending_rows, pending_values,
                                 pending_flags)
            out.append(blob)
            pending_rows.clear()
            pending_flags.clear()
            pending_values.clear()

        for gi in range(len(gb) - 1):
            g_ts = np.arange(gb[gi], gb[gi + 1])
            cand = g_ts[~passthrough_t[g_ts]]
            # CB barcodes present -> reference path for the whole group
            cb_present = False
            for t in cand:
                r = self._r1_of[t] if self._r1_of[t] >= 0 else (
                    self._fr_of[t] if self._fr_of[t] >= 0 else self._r2_of[t])
                if r >= 0 and cb_off[r] >= 0:
                    cb_present = True
                    break
            if cb_present or self.no_umi \
                    or weird[gb[gi] - t_lo:gb[gi + 1] - t_lo].any():
                flush_pending()
                out.extend(self._emit_slow_group(
                    [self._materialize(batch, tbounds, t) for t in g_ts]))
                continue

            g_cat = cat[gb[gi] - t_lo:gb[gi + 1] - t_lo].copy()
            g_cat[passthrough_t[g_ts]] = -1  # split off before filtering
            n_cand = int((g_cat >= 0).sum())
            m.total_templates += n_cand
            for code, attr in ((_POOR, "poor_alignment"), (_NONPF, "non_pf"),
                               (_NS, "ns_in_umi"), (_SHORT, "umi_too_short")):
                c = int((g_cat == code).sum())
                if c:
                    setattr(m, attr, getattr(m, attr) + c)
            kept_t = g_ts[g_cat == _ACCEPT]
            m.accepted += len(kept_t)

            is_dup = {}
            if len(kept_t):
                mids = self._assign_light(batch, kept_t)
                # family grouping by (mi.id, mi.kind), name-ordered within
                fams = {}
                for k, t in enumerate(kept_t):
                    fams.setdefault(_family_key(mids[k]), []).append((k, t))
                for fam in fams.values():
                    fam.sort(key=lambda kt: batch.buf[
                        name_off[tbounds[kt[1]]]:
                        name_off[tbounds[kt[1]]]
                        + name_len[tbounds[kt[1]]]].tobytes())
                    self.family_sizes[len(fam)] = \
                        self.family_sizes.get(len(fam), 0) + 1
                    if len(fam) == 1:
                        best = 0
                    else:
                        if scores is None:
                            scores = nb.qual_scores(
                                batch, PICARD_MIN_BASE_QUALITY,
                                PICARD_MAX_SCORE_PER_READ)
                        best_score = None
                        best = 0
                        for j, (k, t) in enumerate(fam):
                            s = 0
                            for sel in (self._r1_of, self._r2_of,
                                        self._fr_of):
                                r = sel[t]
                                if r >= 0:
                                    rs = int(scores[r])
                                    if qcfail[r]:
                                        rs += PICARD_QC_FAIL_DISCOUNT
                                    s += rs
                            if best_score is None or s > best_score:
                                best_score = s
                                best = j
                    for j, (k, t) in enumerate(fam):
                        dup = j != best
                        is_dup[int(t)] = dup
                        dm.total_templates += 1
                        if dup:
                            dm.duplicate_templates += 1
                        else:
                            dm.unique_templates += 1

                mi_strs = {int(t): mids[k].render()
                           for k, t in enumerate(kept_t)}
                for t in kept_t:
                    t = int(t)
                    dup = is_dup[t]
                    mi_b = mi_strs[t].encode()
                    rows = self._template_rows(batch, tbounds, t)
                    self._count_rows(rows, dup, flag, tc_off)
                    if self.remove_duplicates and dup:
                        continue
                    for r in rows:
                        pending_rows.append(r)
                        pending_values.append(mi_b)
                        f = (int(flag[r]) & ~FLAG_DUPLICATE) \
                            | (FLAG_DUPLICATE if dup else 0)
                        pending_flags.append(f)

            # pass-through templates: verbatim records after the kept ones
            pts = g_ts[passthrough_t[g_ts]]
            if len(pts):
                flush_pending()
                blob = bytearray()
                for t in pts:
                    dm.total_templates += 1
                    dm.unique_templates += 1
                    rows = self._template_rows(batch, tbounds, int(t))
                    self._count_rows(rows, False, flag, tc_off)
                    for r in rows:
                        data = batch.buf[batch.data_off[r]:
                                         batch.data_end[r]].tobytes()
                        blob += len(data).to_bytes(4, "little") + data
                        self.records_out += 1
                if blob:
                    out.append(bytes(blob))

        flush_pending()
        return out

    def _template_rows(self, batch, tbounds, t):
        """Record rows of template t in all_records() order: picked primaries
        (fragment, r1, r2) then the remaining rows in file order."""
        picks = [int(sel[t]) for sel in (self._fr_of, self._r1_of,
                                         self._r2_of) if sel[t] >= 0]
        pick_set = set(picks)
        rows = picks[:]
        flag = batch.flag
        for r in range(int(tbounds[t]), int(tbounds[t + 1])):
            if r in pick_set:
                continue
            f = int(flag[r])
            if f & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY) \
                    or (f & FLAG_PAIRED and not f & FLAG_FIRST
                        and not f & FLAG_LAST):
                rows.append(r)
            # overwritten duplicate-role primaries are dropped (classify
            # last-wins keeps only the pick)
        return rows

    def _count_rows(self, rows, is_dup, flag, tc_off):
        dm = self.dmetrics
        dm.total_reads += len(rows)
        if is_dup:
            dm.duplicate_reads += len(rows)
        for r in rows:
            f = int(flag[r])
            if f & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY):
                if f & FLAG_SECONDARY:
                    dm.secondary_reads += 1
                if f & FLAG_SUPPLEMENTARY:
                    dm.supplementary_reads += 1
                if tc_off[r] < 0:
                    dm.missing_tc_tag += 1

    def _rewrite(self, batch, rows, values, flags):
        from ..io.bam import FLAG_DUPLICATE

        try:
            blob = nb.rewrite_tag_records(
                batch, np.asarray(rows, dtype=np.int64), self.assigned_tag,
                values, new_flags=np.asarray(flags, dtype=np.int32))
        except ValueError:
            from .dedup import _record_with_flag_and_mi

            parts = []
            for r, v, f in zip(rows, values, flags):
                data = _record_with_flag_and_mi(
                    batch.raw_record(int(r)), bool(f & FLAG_DUPLICATE),
                    v.decode(), self.assigned_tag)
                parts.append(len(data).to_bytes(4, "little") + data)
            blob = b"".join(parts)
        self.records_out += len(rows)
        return blob

    def result(self):
        dm = self.dmetrics
        dm.unique_reads = dm.total_reads - dm.duplicate_reads
        return dm, dict(sorted(self.family_sizes.items()))
