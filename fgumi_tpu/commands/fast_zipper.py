"""Batch zipper engine over RecordBatch pairs.

The classic per-template zipper (commands/zipper.py, the semantic oracle —
reference /root/reference/src/lib/commands/zipper.rs merge_raw:397-545) spends
its time in per-tag Python: TagEditor walks, raw_tag_entries, per-record
RawRecord round trips. This engine processes the overwhelmingly common
template shapes — a fully mapped primary pair, or an unpaired fragment, with
no secondary/supplementary records and no tag-name collisions — as whole-batch
array passes plus three native ops:

- field patches (mate ref/pos/flags, TLEN, QC transfer) as vectorized writes
  into the batch buffer;
- the per-record append region (MQ/MC/ms entries, the unmapped record's aux
  bytes, normalized AS/XS) assembled by fgumi_concat_spans from a span table
  built entirely in numpy;
- record rebuild (prefix + surviving aux + appends) by
  fgumi_rebuild_aux_records, whose output order IS TagEditor.finish's order:
  surviving originals in place, appends at the end in staged order.

Secondary/supplementary rows vectorize too (round 5): supplementaries get
the opposite primary's mate pointers/MQ/MC/ms and the same-side tlen,
secondaries keep their mate fields, and both get the `tc`
template-coordinate B:i tag from the primaries' unclipped 5' coordinates
(zipper.rs:281-357, template.rs:459-605). Aligner-dropped templates queue
INSIDE the window so scattered passthroughs cannot fragment it.

Anything else — templates spanning batch buffers, half-mapped or unmapped
pairs, tag-name collisions with MQ/MC/ms/AS/XS/tc, active reverse/revcomp
tag sets on negative-strand reads — falls back to the classic engine per
template, preserving byte-exact semantics (tests/test_zipper.py parity
suite runs both engines on adversarial inputs).
"""

import numpy as np

from ..io.bam import (FLAG_FIRST, FLAG_MATE_REVERSE, FLAG_MATE_UNMAPPED,
                      FLAG_PAIRED, FLAG_QC_FAIL, FLAG_REVERSE, FLAG_SECONDARY,
                      FLAG_SUPPLEMENTARY, FLAG_UNMAPPED)
from ..native import batch as nb
from .zipper import MappedTemplate, merge_template

_SEC_SUPP = FLAG_SECONDARY | FLAG_SUPPLEMENTARY
# tag names whose presence on the unmapped record collides with the staged
# MQ/MC/ms/tc appends or the AS/XS normalization ordering -> classic fallback
_RESERVED_U_TAGS = {b"MQ", b"MC", b"ms", b"AS", b"XS", b"tc"}
_INT_TYPES = frozenset(b"cCsSiI")


def _tag16(tag: bytes) -> int:
    return tag[0] | (tag[1] << 8)


def iter_template_windows(reader):
    """Yield ("batch", batch, bounds) for complete name groups within one
    RecordBatch (templates are bounds[j]..bounds[j+1] rows), and
    ("py", name, [RawRecord]) for groups spanning batch buffers (including
    the final group). Order is stream order."""
    carry = None  # (name, [RawRecord])
    for batch in reader:
        if batch.n == 0:
            continue
        name_off = batch.data_off + 32
        name_len = (batch.l_read_name - 1).astype(np.int64)
        starts = nb.group_starts(batch.buf, np.ascontiguousarray(name_off),
                                 name_len)
        bounds = np.append(starts, batch.n)
        n_groups = len(bounds) - 1
        first_name = bytes(batch.buf[name_off[0]:name_off[0] + name_len[0]])
        gi = 0
        if carry is not None and carry[0] == first_name:
            carry[1].extend(batch.raw_records(
                np.arange(bounds[0], bounds[1])))
            gi = 1
            if n_groups == 1:
                continue  # the whole batch is one open template
            yield ("py", carry[0], carry[1])
            carry = None
        elif carry is not None:
            yield ("py", carry[0], carry[1])
            carry = None
        if gi < n_groups - 1:
            yield ("batch", batch, bounds[gi:n_groups])
        lo, hi = bounds[n_groups - 1], bounds[n_groups]
        last_name = bytes(batch.buf[name_off[lo]:name_off[lo] + name_len[lo]])
        carry = (last_name, list(batch.raw_records(np.arange(lo, hi))))
    if carry is not None:
        yield ("py", carry[0], carry[1])


def iter_templates(reader):
    """Per-template items: (name, batch|None, lo, hi, records|None)."""
    for item in iter_template_windows(reader):
        if item[0] == "py":
            yield (item[1], None, 0, 0, item[2])
        else:
            _, batch, bounds = item
            name_off = batch.data_off + 32
            name_len = batch.l_read_name
            buf = batch.buf
            for j in range(len(bounds) - 1):
                lo = int(bounds[j])
                name = bytes(buf[name_off[lo]:name_off[lo]
                                 + name_len[lo] - 1])
                yield (name, batch, lo, int(bounds[j + 1]), None)


class FastZipper:
    """Window accumulator + vectorized processor (see module docstring)."""

    def __init__(self, tag_info, writer, skip_tc_tags=False):
        self.tag_info = tag_info
        self.writer = writer
        self.skip_tc = skip_tc_tags
        self._static_drop16 = np.array(
            sorted(_tag16(t.encode()) for t in tag_info.remove
                   if len(t) == 2), dtype=np.uint16)
        self._static_drop_b = {t.encode() for t in tag_info.remove
                               if len(t) == 2}
        self._has_transforms = bool(tag_info.reverse or tag_info.revcomp)
        self._reserved16 = np.array(
            sorted({_tag16(t) for t in _RESERVED_U_TAGS}
                   | set(self._static_drop16.tolist())), dtype=np.uint16)
        self._names_cache = None
        self.n_templates = 0
        self.n_records = 0
        # current window: same (m_batch, u_batch) run of simple candidates
        self._win = []
        self._win_batches = (None, None)

    # ------------------------------------------------------------- dispatch

    def passthrough(self, u):
        """Aligner-dropped template: the unmapped records pass through.

        Queued INSIDE an open window when it shares the unmapped batch —
        flushing here would fragment windows into ~20-template slivers on
        inputs with scattered dropped templates, multiplying the fixed
        vectorization overhead ~100x (round-5 zoo profile)."""
        name, ub, lo, hi, recs = u
        if recs is None and self._win and self._win_batches[1] is ub:
            self._win.append(("pass", u))
            return
        self._flush()
        if recs is None:
            w = b"".join(self._wire_rows(ub, lo, hi))
        else:
            w = b"".join(self._wire_rec(r.data) for r in recs)
        self.writer.write_serialized(w)
        self.n_templates += 1
        self.n_records += (hi - lo) if recs is None else len(recs)

    def pair(self, u, m):
        """One matched (unmapped, mapped) template."""
        if u[1] is None or m[1] is None:
            self._flush()
            self._classic(u, m)
            return
        if self._win_batches != (m[1], u[1]):
            self._flush()
            self._win_batches = (m[1], u[1])
        self._win.append(("pair", u, m))
        if len(self._win) >= 8192:
            self._flush()

    def finish(self):
        self._flush()

    # -------------------------------------------------------------- helpers

    @staticmethod
    def _wire_rows(batch, lo, hi):
        base = int(batch.rec_off[lo])
        end = int(batch.data_end[hi - 1])
        yield batch.buf[base:end].tobytes()

    @staticmethod
    def _wire_rec(data: bytes) -> bytes:
        return len(data).to_bytes(4, "little") + data

    def _classic(self, u, m):
        """Per-template oracle path (materialized RawRecords)."""
        name, ub, ulo, uhi, urecs = u
        if urecs is None:
            urecs = list(ub.raw_records(np.arange(ulo, uhi)))
        mname, mb, mlo, mhi, mrecs = m
        if mrecs is None:
            mrecs = list(mb.raw_records(np.arange(mlo, mhi)))
        t = MappedTemplate.from_records(mname, mrecs)
        out = merge_template(urecs, t, self.tag_info, self.skip_tc)
        self.writer.write_serialized(
            b"".join(self._wire_rec(d) for d in out))
        self.n_templates += 1
        self.n_records += len(out)

    # ----------------------------------------------------------- vectorized

    def _flush(self):
        items, self._win = self._win, []
        mb, ub = self._win_batches
        self._win_batches = (None, None)
        if not items:
            return
        win = [(it[1], it[2]) for it in items if it[0] == "pair"]
        if win:
            simple, order = self._classify(win, mb, ub)
        else:
            simple, order = None, ()
        blob = pos = None
        if simple is not None:
            blob, pos, row_of = simple
        # emit in stream order, interleaving queued passthroughs
        k = 0
        for it in items:
            if it[0] == "pass":
                _, pub, lo, hi, _ = it[1]
                self.writer.write_serialized(
                    b"".join(self._wire_rows(pub, lo, hi)))
                self.n_templates += 1
                self.n_records += hi - lo
                continue
            u, m = it[1], it[2]
            if order[k] >= 0:
                j0 = order[k]
                n_rows = m[3] - m[2]
                w = blob[pos[j0]:pos[j0 + n_rows]].tobytes()
                self.writer.write_serialized(w)
                self.n_templates += 1
                self.n_records += n_rows
            else:
                self._classic(u, m)
            k += 1

    def _classify(self, win, mb, ub):
        """Split the window into vectorizable rows and fallbacks.

        Returns ((wire blob, row positions, row map) | None,
        order[k] = first output-row index of template k, or -1 = classic)."""
        K = len(win)
        m_lo = np.array([m[2] for _, m in win])
        m_hi = np.array([m[3] for _, m in win])
        u_lo = np.array([u[2] for u, _ in win])
        u_hi = np.array([u[3] for u, _ in win])
        m_cnt = m_hi - m_lo
        u_cnt = u_hi - u_lo

        # per-template screens, vectorized over cumulative sums with
        # EXPLICIT [lo, hi) boundaries: template segments are monotone
        # within each batch but may have gaps (queued passthrough rows sit
        # between pair templates), so nothing here may assume the segments
        # tile the run
        def seg_any(values, lo, hi):
            csum = np.concatenate(([0], np.cumsum(values[lo[0]:hi[-1]])))
            return (csum[hi - lo[0]] - csum[lo - lo[0]]) > 0

        def seg_count(values, lo, hi):
            csum = np.concatenate(([0], np.cumsum(values[lo[0]:hi[-1]])))
            return csum[hi - lo[0]] - csum[lo - lo[0]]

        mflag = mb.flag.astype(np.int64)
        uflag = ub.flag.astype(np.int64)
        # secondary/supplementary mapped rows are vectorizable (round 5):
        # per-row routing below covers their mate/MQ/MC/ms/tc semantics;
        # only UNMAPPED mapped-side rows force the classic path
        bad_m = (mflag & FLAG_UNMAPPED) != 0
        bad_u = (uflag & _SEC_SUPP) != 0
        is_ss = (mflag & _SEC_SUPP) != 0
        n_ss = seg_count(is_ss, m_lo, m_hi)
        n_prim = m_cnt - n_ss
        ok = (n_prim == u_cnt) & ((n_prim == 1) | (n_prim == 2))
        ok &= ~seg_any(bad_m, m_lo, m_hi) & ~seg_any(bad_u, u_lo, u_hi)
        is_prim = ~is_ss
        m_paired = seg_count((mflag & FLAG_PAIRED) != 0, m_lo, m_hi)
        u_paired = seg_count((uflag & FLAG_PAIRED) != 0, u_lo, u_hi)
        m_first_p = seg_count(((mflag & FLAG_FIRST) != 0) & is_prim,
                              m_lo, m_hi)
        u_first = seg_count((uflag & FLAG_FIRST) != 0, u_lo, u_hi)
        # paired: both primaries present, one FIRST, and EVERY mapped row
        # (incl. sec/supp) paired so per-row FIRST/LAST routing is defined
        pair_ok = (u_paired == 2) & (m_first_p == 1) & (u_first == 1) \
            & (m_paired == m_cnt)
        frag_ok = (m_paired == 0) & (u_paired == 0)
        ok &= np.where(n_prim == 2, pair_ok, frag_ok)
        if self._has_transforms:
            ok &= ~seg_any((mflag & FLAG_REVERSE) != 0, m_lo, m_hi)

        # unmapped tag-name screen (native scan, cached per batch): any
        # reserved/static-dropped name or an overflowed scan -> classic
        names, counts, row_bad = self._u_names(ub)
        ok &= ~seg_any(row_bad, u_lo, u_hi)

        order = np.full(K, -1, dtype=np.int64)
        sel = np.nonzero(ok)[0]
        if len(sel) == 0:
            return None, order
        # output rows: mapped rows of selected templates, in window order
        rows = np.concatenate([np.arange(m_lo[k], m_hi[k]) for k in sel])
        row_t = np.concatenate([np.full(m_hi[k] - m_lo[k], k) for k in sel])
        order[sel] = np.cumsum(
            np.concatenate(([0], (m_hi - m_lo)[sel[:-1]])))
        try:
            blob, pos = self._process_rows(mb, ub, rows, row_t,
                                           m_lo, m_hi, u_lo, u_hi,
                                           names, counts)
        except _FallbackBatch:
            return None, np.full(K, -1, dtype=np.int64)
        return (blob, pos, rows), order

    def _u5_of(self, mb):
        """Per-record unclipped 5' positions, cached per mapped batch."""
        cache = getattr(self, "_u5_cache", None)
        if cache is None or cache[0] is not mb:
            cache = (mb, nb.unclipped_5prime(mb))
            self._u5_cache = cache
        return cache[1]

    def _u_names(self, ub):
        cache = self._names_cache
        if cache is None or cache[0] is not ub:  # RecordBatch has __slots__
            names, counts = nb.tag_name_list(ub.buf, ub.aux_off, ub.data_end)
            col_ok = np.arange(names.shape[1]) < counts[:, None]
            row_bad = (counts < 0) \
                | (np.isin(names, self._reserved16) & col_ok).any(1)
            # zero the cells past each row's count once, so downstream
            # consumers can use the matrix without per-row slicing (a zero
            # cell matches no real tag name)
            names = np.where(col_ok, names, 0)
            cache = (ub, (names, counts, row_bad))
            self._names_cache = cache
        return cache[1]

    def _process_rows(self, mb, ub, rows, row_t, m_lo, m_hi, u_lo, u_hi,
                      u_names, u_counts):
        """The vectorized merge over selected mapped rows (see module doc)."""
        n = len(rows)
        buf = mb.buf
        do = mb.data_off[rows]
        flag = mb.flag[rows].astype(np.int64)
        paired = (flag & FLAG_PAIRED) != 0
        first = ((flag & FLAG_FIRST) != 0) | ~paired
        is_sec = (flag & FLAG_SECONDARY) != 0
        is_supp = (flag & FLAG_SUPPLEMENTARY) != 0
        ts = np.unique(row_t)
        big = np.int64(1 << 60)

        # primary FIRST/LAST rows per template (absolute ids) via
        # reduceat-min over the mapped run — the mate of every primary AND
        # supplementary row is the OPPOSITE side's primary
        # (template.rs:459-605); secondaries keep their mate fields
        m_base = int(m_lo[ts[0]])
        m_end = int(m_hi[ts[-1]])
        run = np.arange(m_base, m_end)
        rf = mb.flag[m_base:m_end].astype(np.int64)
        run_prim = (rf & _SEC_SUPP) == 0
        run_first = ((rf & FLAG_FIRST) != 0) | ((rf & FLAG_PAIRED) == 0)
        mseg = np.stack([m_lo[ts], m_hi[ts]], axis=1).ravel() - m_base
        p1_cand = np.append(np.where(run_prim & run_first, run, big), big)
        p2_cand = np.append(np.where(run_prim & ~run_first, run, big), big)
        p1_abs = np.minimum.reduceat(p1_cand, mseg)[::2]
        p2_abs = np.minimum.reduceat(p2_cand, mseg)[::2]  # big: fragment
        t_pos_m = np.searchsorted(ts, row_t)
        opp_abs = np.where(first, p2_abs[t_pos_m], p1_abs[t_pos_m])
        has_mate = (opp_abs < big) & ~is_sec
        mate = np.where(has_mate,
                        np.searchsorted(rows, np.minimum(opp_abs, big - 1)),
                        -1)

        # u primary row per output row: FIRST (or unpaired) -> u's
        # FIRST/unpaired record, else u's LAST record. Selected templates'
        # u rows form a contiguous run, but only SELECTED templates count,
        # so reduceat runs over the selected segments explicitly.
        u_base = int(u_lo[ts[0]])
        u_end = int(u_hi[ts[-1]])
        uf_run = ub.flag[u_base:u_end].astype(np.int64)
        is_first = ((uf_run & FLAG_FIRST) != 0) | ((uf_run & FLAG_PAIRED) == 0)
        idx = np.arange(u_base, u_end)
        # selected templates may be non-contiguous (classic ones interleave)
        # -> reduceat over explicit [lo, hi) boundary pairs, sentinel-padded
        # so hi == len is a valid index
        f_cand = np.append(np.where(is_first, idx, big), big)
        o_cand = np.append(np.where(~is_first, idx, big), big)
        seg = np.stack([u_lo[ts], u_hi[ts]], axis=1).ravel() - u_base
        fidx = np.minimum.reduceat(f_cand, seg)[::2]
        oidx = np.minimum.reduceat(o_cand, seg)[::2]
        oidx = np.where(oidx == big, fidx, oidx)
        u_row = np.where(first, fidx[t_pos_m], oidx[t_pos_m])

        # ---- field patches (in place on the mapped batch buffer; the
        # classic fallback recomputes identical values from the mate
        # records, so a window that later falls back is unaffected)
        mate_rows = rows[np.maximum(mate, 0)]
        mate_ref = mb.ref_id[mate_rows].astype(np.int64)
        mate_pos = mb.pos[mate_rows].astype(np.int64)
        mate_flag = mb.flag[mate_rows].astype(np.int64)
        ends = nb.ref_spans(buf, mb.cigar_off[rows], mb.n_cigar[rows],
                            mb.pos[rows])
        own_5p = np.where((flag & FLAG_REVERSE) != 0,
                          ends.astype(np.int64), mb.pos[rows] + 1)
        mate_5p = own_5p[np.maximum(mate, 0)]
        raw_t = mate_5p - own_5p
        # sign adjustment is decided from the FIRST read's perspective
        # (_insert_size: second_5p >= first_5p -> +1; R2 takes the negative)
        # so at an exact 5' tie R1 gets +1 and R2 gets -1
        adj = np.where(raw_t > 0, 1, np.where(raw_t < 0, -1,
                                              np.where(first, 1, -1)))
        tlen = raw_t + adj
        tlen = np.where(mb.ref_id[rows] == mate_ref, tlen, 0)
        tlen = np.where(has_mate, tlen, mb.tlen[rows])
        # supplementaries carry -(opposite primary's tlen) — which equals
        # the same-side primary's formula tlen (template.rs:513-605)
        tlen = np.where(is_supp & has_mate,
                        -tlen[np.maximum(mate, 0)], tlen)

        new_flag = flag.copy()
        nf = (flag & ~(FLAG_MATE_REVERSE | FLAG_MATE_UNMAPPED)) \
            | np.where((mate_flag & FLAG_REVERSE) != 0, FLAG_MATE_REVERSE, 0)
        new_flag = np.where(has_mate, nf, flag)
        u_qc = (ub.flag[u_row] & FLAG_QC_FAIL) != 0
        new_flag = np.where(u_qc, new_flag | FLAG_QC_FAIL,
                            new_flag & ~FLAG_QC_FAIL)

        def put_i32(field_off, values, mask=None):
            arr = values.astype("<i4").view(np.uint8).reshape(-1, 4)
            offs = do + field_off
            if mask is not None:
                arr, offs = arr[mask], offs[mask]
            buf[offs[:, None] + np.arange(4)] = arr

        put_i32(20, mate_ref, has_mate)
        put_i32(24, mate_pos, has_mate)
        put_i32(28, tlen, has_mate)
        buf[(do + 14)[:, None] + np.arange(2)] = \
            new_flag.astype("<u2").view(np.uint8).reshape(-1, 2)

        # ---- appends: scratch slots
        # [MQ 0:7 | ms 7:14 | AS 14:21 | XS 21:28 | tc 28:60]
        scratch = np.zeros(4 + n * 60, dtype=np.uint8)
        scratch[0:4] = np.frombuffer(b"MCZ\x00", dtype=np.uint8)
        slots = scratch[4:].reshape(n, 60)
        slots[:, 0:2] = np.frombuffer(b"MQ", np.uint8)
        slots[:, 2] = ord("i")
        slots[:, 3:7] = mb.mapq[mate_rows].astype("<i4").view(
            np.uint8).reshape(-1, 4)

        as_val, as_present = self._int_tag(mb, b"AS", rows)
        xs_val, xs_present = self._int_tag(mb, b"XS", rows)
        mate_as = as_val[np.maximum(mate, 0)]
        mate_as_present = as_present[np.maximum(mate, 0)] & has_mate
        slots[:, 7:9] = np.frombuffer(b"ms", np.uint8)
        slots[:, 9] = ord("i")
        slots[:, 10:14] = mate_as.astype("<i4").view(np.uint8).reshape(-1, 4)

        as_len = self._norm_entry(slots[:, 14:21], b"AS", as_val, as_present)
        xs_len = self._norm_entry(slots[:, 21:28], b"XS", xs_val, xs_present)

        # tc (B:i [tid1,pos1,neg1,tid2,pos2,neg2], lower coordinate first)
        # on secondary/supplementary rows (zipper.rs:281-357): values are
        # per template from the primaries' unclipped 5' coordinates
        tc_on = (is_sec | is_supp) if not self.skip_tc \
            else np.zeros(n, dtype=bool)
        if tc_on.any():
            u5 = self._u5_of(mb)
            p1t = np.minimum(p1_abs, len(u5) - 1).astype(np.int64)
            p2t = np.minimum(p2_abs, len(u5) - 1).astype(np.int64)
            have2 = p2_abs < big

            def pinfo(pt):
                return (mb.ref_id[pt].astype(np.int64), u5[pt],
                        ((mb.flag[pt] & FLAG_REVERSE) != 0).astype(np.int64))
            tid1, p51, ng1 = pinfo(p1t)
            tid2, p52, ng2 = pinfo(p2t)
            tid2 = np.where(have2, tid2, tid1)
            p52 = np.where(have2, p52, p51)
            ng2 = np.where(have2, ng2, ng1)
            swap = (tid2 < tid1) | ((tid2 == tid1) & (p52 < p51))
            vals = np.stack([np.where(swap, tid2, tid1),
                             np.where(swap, p52, p51),
                             np.where(swap, ng2, ng1),
                             np.where(swap, tid1, tid2),
                             np.where(swap, p51, p52),
                             np.where(swap, ng1, ng2)], axis=1)
            slots[:, 28:30] = np.frombuffer(b"tc", np.uint8)
            slots[:, 30] = ord("B")
            slots[:, 31] = ord("i")
            slots[:, 32:36] = np.frombuffer(
                np.array([6], dtype="<i4").tobytes(), np.uint8)
            slots[:, 36:60] = vals[t_pos_m].astype("<i4").view(
                np.uint8).reshape(-1, 24)
        tc_len = np.where(tc_on, 32, 0)

        # MC: mate cigar strings (omit when the mate has no cigar)
        cig_blob, cig_off = nb.cigar_strings(buf, mb.cigar_off[mate_rows],
                                             mb.n_cigar[mate_rows])
        mc_on = has_mate & (mb.n_cigar[mate_rows] > 0)
        mq_on = has_mate

        # unmapped aux copy spans (split around PG when the mapped row has
        # its own PG)
        u_aux0 = ub.aux_off[u_row]
        u_auxE = ub.data_end[u_row]
        m_pg_off, _, _ = mb.tag_locs(b"PG")
        has_pg = m_pg_off[rows] >= 0
        u_pg_off, u_pg_len, u_pg_typ = ub.tag_locs(b"PG")
        upg_off = u_pg_off[u_row]
        upg_present = upg_off >= 0
        z_like = (u_pg_typ[u_row] == ord("Z")) | (u_pg_typ[u_row] == ord("H"))
        upg_end = upg_off + u_pg_len[u_row] + np.where(z_like, 1, 0)
        split = has_pg & upg_present
        uA_off = u_aux0
        uA_len = np.where(split, (upg_off - 3) - u_aux0, u_auxE - u_aux0)
        uB_off = np.where(split, upg_end, 0)
        uB_len = np.where(split, u_auxE - upg_end, 0)

        # span table: 10 parts per row, sources 0=scratch 1=cig blob 2=u buf
        base = (np.arange(n, dtype=np.int64) * 60) + 4
        part_src = np.tile(np.array([0, 0, 1, 0, 0, 2, 2, 0, 0, 0],
                                    dtype=np.int32), n)
        part_off = np.stack([
            base + 0,                                   # MQ slot
            np.zeros(n, dtype=np.int64),                # "MCZ" const
            cig_off[:-1],                               # cigar string
            np.full(n, 3, dtype=np.int64),              # NUL const
            base + 7,                                   # ms slot
            uA_off, uB_off,
            base + 14, base + 21,
            base + 28], axis=1).ravel()                 # tc slot
        cig_len = (cig_off[1:] - cig_off[:-1])
        part_len = np.stack([
            np.where(mq_on, 7, 0),
            np.where(mc_on, 3, 0),
            np.where(mc_on, cig_len, 0),
            np.where(mc_on, 1, 0),
            np.where(mate_as_present, 7, 0),
            uA_len, uB_len,
            as_len, xs_len, tc_len], axis=1).ravel().astype(np.int64)
        if (part_len < 0).any():
            raise _FallbackBatch()
        appends, app_all = nb.concat_spans(
            [scratch, cig_blob, ub.buf], part_src, part_off, part_len)
        app_off = app_all[::10]

        # ---- drop lists: fixed-width per-record matrices (a zero cell
        # matches no real tag name, so unused slots need no compaction):
        # static + [MQ MC ms when mated] + [AS/XS when normalized] +
        # unmapped tag names (minus the skipped PG)
        ns = len(self._static_drop16)
        max_u = u_names.shape[1]
        width = ns + 6 + max_u
        dmat = np.zeros((n, width), dtype=np.uint16)
        if ns:
            dmat[:, :ns] = self._static_drop16
        dmat[:, ns + 0] = np.where(mq_on, _tag16(b"MQ"), 0)
        dmat[:, ns + 1] = np.where(has_mate, _tag16(b"MC"), 0)
        # ms is REPLACED only when the mate has an AS tag — classic keeps a
        # stale ms otherwise (fix_mate_info only calls set_i32 under p_as)
        dmat[:, ns + 2] = np.where(mate_as_present, _tag16(b"ms"), 0)
        dmat[:, ns + 3] = np.where(as_len > 0, _tag16(b"AS"), 0)
        dmat[:, ns + 4] = np.where(xs_len > 0, _tag16(b"XS"), 0)
        dmat[:, ns + 5] = np.where(tc_len > 0, _tag16(b"tc"), 0)
        ublock = u_names[u_row]  # (n, max_u), already zero-padded past count
        ublock = np.where(split[:, None] & (ublock == _PG16), 0, ublock)
        dmat[:, ns + 6:] = ublock
        drop = dmat.ravel()
        drop_off = np.arange(n + 1, dtype=np.int64) * width

        got = nb.rebuild_aux_records(buf, do, mb.aux_off[rows],
                                     mb.data_end[rows], drop, drop_off,
                                     appends, app_off)
        if got is None:
            raise _FallbackBatch()
        return got

    @staticmethod
    def _int_tag(batch, tag, rows):
        """(values int64, present bool) for an integer-typed tag."""
        vo, vl, vt = batch.tag_locs(tag)
        vo, vt = vo[rows], vt[rows]
        present = vo >= 0
        vals = np.zeros(len(rows), dtype=np.int64)
        buf = batch.buf
        for t, dt in ((ord("c"), "<i1"), (ord("C"), "<u1"),
                      (ord("s"), "<i2"), (ord("S"), "<u2"),
                      (ord("i"), "<i4"), (ord("I"), "<u4")):
            m = present & (vt == t)
            if m.any():
                w = np.dtype(dt).itemsize
                raw = buf[vo[m][:, None] + np.arange(w)]
                vals[m] = raw.reshape(-1, w).copy().view(dt).ravel()
        present &= np.isin(vt, np.frombuffer(b"cCsSiI", np.uint8))
        return vals, present

    @staticmethod
    def _norm_entry(slot, tag, values, present):
        """Write smallest-signed-int entries into 7-byte slots; returns
        per-row entry lengths (0 when absent or out of i32 range)."""
        n = len(values)
        lens = np.zeros(n, dtype=np.int64)
        in_range = present & (values >= -(2 ** 31)) & (values < 2 ** 31)
        small = in_range & (values >= -128) & (values <= 127)
        mid = in_range & ~small & (values >= -32768) & (values <= 32767)
        big = in_range & ~small & ~mid
        slot[:, 0:2] = np.frombuffer(tag, np.uint8)
        slot[small, 2] = ord("c")
        slot[small, 3] = values[small].astype("<i1").view(np.uint8)
        lens[small] = 4
        slot[mid, 2] = ord("s")
        slot[mid, 3:5] = values[mid].astype("<i2").view(np.uint8).reshape(-1, 2)
        lens[mid] = 5
        slot[big, 2] = ord("i")
        slot[big, 3:7] = values[big].astype("<i4").view(np.uint8).reshape(-1, 4)
        lens[big] = 7
        return lens


_PG16 = ord("P") | (ord("G") << 8)


class _FallbackBatch(Exception):
    """Raised when a vectorized window must re-run classically."""


def run_zipper_fast(mapped_reader, unmapped_reader, writer, tag_info, *,
                    skip_tc_tags=False, exclude_missing_reads=False):
    """Drop-in replacement for zipper.run_zipper over BamBatchReaders."""
    fz = FastZipper(tag_info, writer, skip_tc_tags)
    m_it = iter_templates(mapped_reader)
    u_it = iter_templates(unmapped_reader)
    m = next(m_it, None)
    n_missing = 0
    for u in u_it:
        if m is None or m[0] != u[0]:
            n_missing += 1
            if not exclude_missing_reads:
                fz.passthrough(u)
            continue
        fz.pair(u, m)
        m = next(m_it, None)
    fz.finish()
    if m is not None:
        raise ValueError(
            f"read '{m[0].decode(errors='replace')}' present in the mapped "
            "BAM but not in the unmapped BAM; inputs must share queryname "
            "ordering")
    return fz.n_templates, fz.n_records, n_missing
