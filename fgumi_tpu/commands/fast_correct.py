"""Batch engine for the `correct` command.

The classic loop (commands/correct.py run_correct, reference
/root/reference/src/lib/commands/correct.rs) walks every record through
BamReader + find_tag; per-template work is tiny (a cached whitelist match),
so the wall time is pure per-record Python. This engine reuses the batch
template-window machinery (commands/fast_zipper.iter_template_windows) and
rebuilds corrected records with the native aux editor:

- one hash pass groups rows into templates and verifies per-template UMI
  consistency (mixed presence/value raises exactly like the classic path);
- corrections compute once per DISTINCT observed UMI (dict cache in front
  of the segment-level matcher cache);
- all written rows of a window rebuild in one fgumi_rebuild_aux_records
  call — rows needing no correction carry empty drop/append spans and copy
  through verbatim, corrected rows drop RX (and OX) and append the staged
  OX/RX entries in apply_correction's order.

Byte parity with the classic engine is pinned by tests/test_correct.py's
fast-vs-classic sweeps.
"""

import numpy as np

from ..io.bam import RawRecord
from ..native import batch as nb
from .correct import (TARGET_TAGS, CorrectStats, _credit,
                      compute_template_correction, extract_template_umi)
from .fast_zipper import iter_template_windows


def _tag_entry(tag: bytes, value: bytes) -> bytes:
    return tag + b"Z" + value + b"\x00"


def run_correct_fast(reader, writer, matcher, umi_length: int, *,
                     target: str = "umi", revcomp: bool = False,
                     store_original: bool = True,
                     rejects_writer=None) -> CorrectStats:
    """Drop-in replacement for run_correct over a BamBatchReader."""
    umi_tag, original_tag = TARGET_TAGS[target]
    stats = CorrectStats()
    unmatched_umi = "N" * umi_length
    corr_cache = {}

    def correction_for(umi: str):
        corr = corr_cache.get(umi)
        if corr is None:
            corr = compute_template_correction(umi, umi_length, revcomp,
                                               matcher)
            corr_cache[umi] = corr
        return corr

    def handle_py(records):
        """Classic per-template path (cross-buffer templates)."""
        stats.templates += 1
        umi = extract_template_umi(records, umi_tag)
        if umi is None:
            stats.missing_umis += len(records)
            if rejects_writer is not None:
                for rec in records:
                    rejects_writer.write_record_bytes(rec.data)
            return
        corr = correction_for(umi)
        if corr.matches:
            _credit(stats.umi_metrics, corr.matches, len(records),
                    unmatched_umi)
        if corr.matched:
            from .correct import apply_correction

            for rec in records:
                writer.write_record_bytes(apply_correction(
                    rec, corr, umi_tag, original_tag, store_original))
                stats.records_written += 1
        else:
            if corr.rejection == "wrong_length":
                stats.wrong_length += len(records)
            else:
                stats.mismatched += len(records)
            if rejects_writer is not None:
                for rec in records:
                    rejects_writer.write_record_bytes(rec.data)

    for item in iter_template_windows(reader):
        if item[0] == "py":
            handle_py(item[2])
            continue
        _, batch, bounds = item
        buf = batch.buf
        vo, vl, _vt = batch.tag_locs_str(umi_tag)
        nT = len(bounds) - 1
        lo = bounds[:-1].astype(np.int64)
        hi = bounds[1:].astype(np.int64)
        present = vo >= 0

        # per-template presence/value consistency (extract_template_umi):
        # every row must agree with the template's first row. The window's
        # bounds may start past row 0 (earlier groups were carried), so all
        # comparisons run over the window's row range only.
        rep = lo
        rows_w = np.arange(int(bounds[0]), int(bounds[-1]))
        rep_of_row = np.repeat(rep, hi - lo)
        p_row = present[rows_w]
        p_rep = present[rep_of_row]
        row_ok = p_row == p_rep
        eq = nb.ranges_equal(buf, vo[rows_w], np.where(p_row, vl[rows_w], 0),
                             vo[rep_of_row],
                             np.where(p_rep, vl[rep_of_row], 0))
        row_ok &= ~p_row | eq.astype(bool)
        if not row_ok.all():
            # reproduce the classic error text for the first bad template
            bad_row = int(rows_w[np.nonzero(~row_ok)[0][0]])
            bt = int(np.searchsorted(hi, bad_row, side="right"))
            extract_template_umi(
                list(batch.raw_records(np.arange(lo[bt], hi[bt]))), umi_tag)
            raise ValueError("template has inconsistent UMIs")  # unreachable

        # template UMI strings in one gather (blank for missing)
        offs = vo[rep]
        lens = np.where(offs >= 0, vl[rep], 0).astype(np.int64)
        blob, boff = nb.concat_spans([buf], np.zeros(nT, np.int32), offs,
                                     lens)
        s = blob.tobytes().decode()
        bo = boff.tolist()

        write_rows = []
        drops = []
        appends = []
        app_scratch = bytearray()
        for t in range(nT):
            stats.templates += 1
            n_recs = int(hi[t] - lo[t])
            if offs[t] < 0:
                stats.missing_umis += n_recs
                if rejects_writer is not None:
                    base = int(batch.rec_off[lo[t]])
                    rejects_writer.write_serialized(
                        buf[base:int(batch.data_end[hi[t] - 1])].tobytes())
                continue
            corr = correction_for(s[bo[t]:bo[t + 1]])
            if corr.matches:
                _credit(stats.umi_metrics, corr.matches, n_recs,
                        unmatched_umi)
            if not corr.matched:
                if corr.rejection == "wrong_length":
                    stats.wrong_length += n_recs
                else:
                    stats.mismatched += n_recs
                if rejects_writer is not None:
                    base = int(batch.rec_off[lo[t]])
                    rejects_writer.write_serialized(
                        buf[base:int(batch.data_end[hi[t] - 1])].tobytes())
                continue
            if corr.needs_correction:
                add_ox = store_original and corr.has_mismatches
                entry = b""
                if add_ox:
                    entry += _tag_entry(original_tag,
                                        corr.original_umi.encode())
                entry += _tag_entry(umi_tag, corr.corrected_umi.encode())
                a0 = len(app_scratch)
                app_scratch += entry
                drop = (umi_tag, original_tag) if add_ox else (umi_tag,)
                for r in range(int(lo[t]), int(hi[t])):
                    write_rows.append((r, corr))
                    drops.append(drop)
                    appends.append((a0, len(entry)))
            else:
                for r in range(int(lo[t]), int(hi[t])):
                    write_rows.append((r, None))
                    drops.append(())
                    appends.append((0, 0))
            stats.records_written += n_recs

        if not write_rows:
            continue
        rows = np.asarray([r for r, _ in write_rows], dtype=np.int64)
        width = 2
        dmat = np.zeros((len(rows), width), dtype=np.uint16)
        for i, d in enumerate(drops):
            for k, tg in enumerate(d):
                dmat[i, k] = tg[0] | (tg[1] << 8)
        drop_off = np.arange(len(rows) + 1, dtype=np.int64) * width
        app = np.asarray(appends, dtype=np.int64)
        # concat the per-row append spans into a dense blob + offsets
        scratch = np.frombuffer(bytes(app_scratch) or b"\x00", dtype=np.uint8)
        dense, dense_off = nb.concat_spans(
            [scratch], np.zeros(len(rows), np.int32), app[:, 0], app[:, 1])
        got = nb.rebuild_aux_records(
            buf, batch.data_off[rows], batch.aux_off[rows],
            batch.data_end[rows], dmat.ravel(), drop_off, dense, dense_off)
        if got is None:
            # malformed aux: classic apply per record (stats are already
            # counted for these templates — only serialization remains)
            from .correct import apply_correction

            for r, corr in write_rows:
                rec = RawRecord(bytes(buf[batch.data_off[r]:
                                          batch.data_end[r]]))
                data = rec.data if corr is None else apply_correction(
                    rec, corr, umi_tag, original_tag, store_original)
                writer.write_record_bytes(data)
            continue
        wire, _pos = got
        writer.write_serialized(wire.tobytes())
    return stats
