"""GroupReadsByUmi: assign molecule identifiers to templates by position + UMI.

Mirrors /root/reference/src/lib/commands/group.rs:
- requires template-coordinate sorted input (SO:unsorted GO:query
  SS:...:template-coordinate), or query-grouped with --allow-unmapped
  (classify_input_ordering, group.rs:470-500);
- streaming position groups at ReadInfo key boundaries (RecordPositionGrouper
  analog, grouper.rs:409-572);
- template filtering: min-map-q (both reads + MQ tag), non-PF, N-containing UMIs,
  min-umi-length (filter_template_raw, group.rs:110-270);
- per-group UMI assignment via the strategy assigners, with templates split by
  pair orientation for non-paired strategies (assign_umi_groups_impl,
  group.rs:505-560);
- MI:Z tags minted from a single global counter in stream order (the
  deterministic-MI-numbering contract, docs/design/deterministic-mi-numbering.md);
- family-size and position-group-size metrics.
"""

import logging
import struct

from ..core.template import (is_r1_genomically_earlier, iter_templates,
                             library_lookup_from_header, read_info_key)
from ..io.bam import (FLAG_MATE_UNMAPPED, FLAG_PAIRED, FLAG_QC_FAIL,
                      FLAG_REVERSE, FLAG_UNMAPPED, RawRecord)
from ..umi.assigners import make_assigner

log = logging.getLogger("fgumi_tpu.group")


class FilterMetrics:
    def __init__(self):
        self.total_templates = 0
        self.accepted = 0
        self.poor_alignment = 0
        self.non_pf = 0
        self.ns_in_umi = 0
        self.umi_too_short = 0

    def as_dict(self):
        return {k: v for k, v in self.__dict__.items() if v}


def _umi_base_count(umi: str) -> int:
    return sum(len(seg) for seg in umi.split("-"))


def filter_template(t, *, umi_tag: bytes, min_mapq: int, include_non_pf: bool,
                    min_umi_length, no_umi: bool, allow_unmapped: bool,
                    metrics: FilterMetrics) -> bool:
    """filter_template_raw (group.rs:110-270)."""
    primaries = t.primary_records()
    metrics.total_templates += len(primaries)
    if not primaries:
        metrics.poor_alignment += len(primaries)
        return False
    reads = [r for r in (t.r1, t.r2, t.fragment) if r is not None]
    both_unmapped = all(r.flag & FLAG_UNMAPPED for r in reads)
    if both_unmapped and not allow_unmapped:
        metrics.poor_alignment += len(primaries)
        return False
    for r in reads:
        if not include_non_pf and r.flag & FLAG_QC_FAIL:
            metrics.non_pf += len(primaries)
            return False
        if not r.flag & FLAG_UNMAPPED and r.mapq < min_mapq:
            metrics.poor_alignment += len(primaries)
            return False
    for r in reads:
        # mate MAPQ (MQ tag) check when the mate is mapped
        if r.flag & FLAG_PAIRED and not r.flag & FLAG_MATE_UNMAPPED:
            mq = r.get_int(b"MQ")
            if mq is not None and mq < min_mapq:
                metrics.poor_alignment += len(primaries)
                return False
        if no_umi:
            continue
        umi = r.get_str(umi_tag)
        if umi is None:
            metrics.poor_alignment += len(primaries)
            return False
        if "N" in umi.upper():
            metrics.ns_in_umi += len(primaries)
            return False
        if min_umi_length is not None and _umi_base_count(umi) < min_umi_length:
            metrics.umi_too_short += len(primaries)
            return False
    return True


def iter_position_groups(templates, library_of):
    """Group consecutive templates by ReadInfo key (RecordPositionGrouper analog)."""
    current_key = None
    bucket = []
    for t in templates:
        r = t.primary_r1 or t.r2
        rg = r.get_str(b"RG") if r is not None else None
        key = read_info_key(t, library_of.get(rg, "unknown"))
        if key != current_key:
            if bucket:
                yield bucket
            current_key = key
            bucket = [t]
        else:
            bucket.append(t)
    if bucket:
        yield bucket


def pair_orientation(t):
    """(r1_positive, r2_positive), None-reads read as positive (group.rs:276-287)."""
    r1_pos = t.r1 is None or not t.r1.flag & FLAG_REVERSE
    r2_pos = t.r2 is None or not t.r2.flag & FLAG_REVERSE
    return (r1_pos, r2_pos)


def extract_umi(t, umi_tag: bytes, assigner) -> str:
    """umi_for_read_impl (group.rs:295-344): uppercase; paired strategies get
    orientation prefixes by genomic order of R1/R2."""
    r = t.primary_r1 or t.r2
    umi = r.get_str(umi_tag)
    if umi is None:
        raise ValueError(f"template {t.name!r} missing UMI tag {umi_tag.decode()}")
    umi = umi.upper()
    if assigner.split_by_orientation():
        return umi
    parts = umi.split("-")
    if len(parts) != 2:
        raise ValueError(
            f"Paired strategy used but UMI did not contain 2 segments "
            f"delimited by '-': {umi}")
    if t.r1 is not None and t.r2 is not None:
        r1_earlier = is_r1_genomically_earlier(t.r1, t.r2)
    else:
        r1_earlier = True
    lo, hi = assigner.lower_prefix, assigner.higher_prefix
    if r1_earlier:
        return f"{lo}:{parts[0]}-{hi}:{parts[1]}"
    return f"{hi}:{parts[0]}-{lo}:{parts[1]}"


def truncate_umis(umis, min_umi_length):
    """truncate_umis_impl (group.rs:346-358)."""
    if min_umi_length is None:
        return umis
    shortest = min((len(u) for u in umis), default=0)
    if shortest < min_umi_length:
        raise ValueError(
            f"UMI found that had shorter length than expected "
            f"({shortest} < {min_umi_length})")
    return [u[:min_umi_length] for u in umis]


def assign_group(templates, assigner, umi_tag: bytes, min_umi_length, no_umi: bool):
    """Assign MoleculeIds to one position group's templates (in place)."""
    if assigner.split_by_orientation():
        subgroups = {}
        for idx, t in enumerate(templates):
            subgroups.setdefault(pair_orientation(t), []).append(idx)
        ordered = sorted(subgroups.items())
        index_sets = [idxs for _, idxs in ordered]
    else:
        index_sets = [list(range(len(templates)))]
    for indices in index_sets:
        if no_umi:
            umis = [""] * len(indices)
        else:
            umis = [extract_umi(templates[i], umi_tag, assigner) for i in indices]
            umis = truncate_umis(umis, min_umi_length)
        assignments = assigner.assign(umis)
        for i, idx in enumerate(indices):
            templates[idx].mi = assignments[i]


def append_mi_tag(rec: RawRecord, mi: str, assigned_tag: bytes = b"MI") -> bytes:
    """Record bytes with the assigned tag set (pre-existing occurrences removed,
    so re-running group replaces rather than duplicates the tag)."""
    return rec.data_without_tag(assigned_tag) + assigned_tag + b"Z" + mi.encode() + b"\x00"


def run_group(reader, writer, *, strategy: str = "adjacency", edits: int = 1,
              umi_tag: bytes = b"RX", assigned_tag: bytes = b"MI", min_mapq: int = 1,
              include_non_pf: bool = False, min_umi_length=None, no_umi: bool = False,
              allow_unmapped: bool = False):
    """Stream reader -> writer assigning MI tags. Returns (metrics dict)."""
    assigner = make_assigner(strategy, edits)
    if no_umi and strategy == "paired":
        raise ValueError("--no-umi cannot be combined with the paired strategy")
    library_of = library_lookup_from_header(reader.header.text)
    metrics = FilterMetrics()
    family_sizes = {}
    position_group_sizes = {}
    n_out = 0

    for group in iter_position_groups(iter_templates(reader), library_of):
        kept = [t for t in group
                if filter_template(t, umi_tag=umi_tag, min_mapq=min_mapq,
                                   include_non_pf=include_non_pf,
                                   min_umi_length=min_umi_length, no_umi=no_umi,
                                   allow_unmapped=allow_unmapped, metrics=metrics)]
        if not kept:
            continue
        metrics.accepted += sum(len(t.primary_records()) for t in kept)
        assign_group(kept, assigner, umi_tag, min_umi_length, no_umi)
        # family sizes: templates per molecule id in this group
        sizes = {}
        for t in kept:
            key = t.mi.render()
            sizes[key] = sizes.get(key, 0) + 1
        for size in sizes.values():
            family_sizes[size] = family_sizes.get(size, 0) + 1
        pg = sum(sizes.values())
        position_group_sizes[pg] = position_group_sizes.get(pg, 0) + 1
        for t in kept:
            mi = t.mi.render()
            for rec in t.primary_records():
                writer.write_record_bytes(append_mi_tag(rec, mi, assigned_tag))
                n_out += 1
    return {
        "records_out": n_out,
        "filter": metrics.as_dict(),
        "family_sizes": dict(sorted(family_sizes.items())),
        "position_group_sizes": dict(sorted(position_group_sizes.items())),
    }
