"""simplex-metrics: simplex yield/family QC metrics (no fgbio equivalent).

Mirrors /root/reference/src/lib/commands/simplex_metrics.rs +
crates/fgumi-metrics/src/simplex.rs: CS/SS family size distributions, UMI
count metrics (per-component RX consensus, no strand swapping), and yield
metrics at 20 downsampling levels (mean SS family size, singleton fraction,
families meeting --min-reads). Rejects duplex-UMI input (base UMIs on both
/A and /B strands) with a pointer at duplex-metrics.

Outputs: <output>.family_sizes.txt, <output>.simplex_yield_metrics.txt,
<output>.umi_counts.txt.
"""

import logging

from ..metrics import UmiCountTracker, family_size_rows, frac, write_metrics
from .duplex_metrics import UMI_FIELDS, _safe_consensus
from .metrics_common import (DOWNSAMPLING_FRACTIONS, compute_template_metadata,
                             parse_intervals, process_templates_from_bam,
                             validate_not_consensus_bam)

log = logging.getLogger("fgumi_tpu")

FAMILY_SIZE_FIELDS = [
    "family_size", "cs_count", "cs_fraction", "cs_fraction_gt_or_eq_size",
    "ss_count", "ss_fraction", "ss_fraction_gt_or_eq_size"]
YIELD_FIELDS = ["fraction", "read_pairs", "cs_families", "ss_families",
                "mean_ss_family_size", "ss_singletons", "ss_singleton_fraction",
                "ss_consensus_families"]


class SimplexMetricsCollector:
    """Per-fraction accumulator (fgumi-metrics simplex.rs)."""

    def __init__(self):
        self.cs_family_sizes = {}
        self.ss_family_sizes = {}
        self.umi_counts = UmiCountTracker()

    def record_cs_family(self, size: int):
        self.cs_family_sizes[size] = self.cs_family_sizes.get(size, 0) + 1

    def record_ss_family(self, size: int):
        self.ss_family_sizes[size] = self.ss_family_sizes.get(size, 0) + 1

    def family_size_metrics(self) -> list:
        return family_size_rows({"cs": self.cs_family_sizes,
                                 "ss": self.ss_family_sizes})


def _yield_metric(collector, fraction, read_pairs, min_reads):
    """SimplexYieldMetric (simplex_metrics.rs:333-371)."""
    rows = collector.family_size_metrics()
    cs_families = sum(r["cs_count"] for r in rows)
    ss_families = sum(r["ss_count"] for r in rows)
    total_ss_reads = sum(r["family_size"] * r["ss_count"] for r in rows)
    ss_singletons = next((r["ss_count"] for r in rows if r["family_size"] == 1), 0)
    ss_consensus = sum(r["ss_count"] for r in rows
                       if r["family_size"] >= min_reads)
    return {
        "fraction": fraction, "read_pairs": read_pairs,
        "cs_families": cs_families, "ss_families": ss_families,
        "mean_ss_family_size": frac(total_ss_reads, ss_families),
        "ss_singletons": ss_singletons,
        "ss_singleton_fraction": frac(ss_singletons, ss_families),
        "ss_consensus_families": ss_consensus,
    }


def run_simplex_metrics(args) -> int:
    if args.min_reads < 1:
        log.error("--min-reads must be >= 1 (got %d)", args.min_reads)
        return 2
    try:
        validate_not_consensus_bam(args.input)
        intervals = parse_intervals(args.intervals) if args.intervals else []
    except (ValueError, OSError) as e:
        log.error("%s", e)
        return 2

    fractions = DOWNSAMPLING_FRACTIONS
    collectors = [SimplexMetricsCollector() for _ in fractions]
    last_idx = len(fractions) - 1

    def process_group(group, fraction_counts):
        metadata = compute_template_metadata(group)
        # duplex-data guard (SIMM3-01): a base UMI on both strands means
        # duplex input; the per-family RX consensus below would mix the two
        # strand orientations.
        strands = {}
        for m in metadata:
            seen = strands.setdefault(m.base_umi, [False, False])
            seen[0] |= m.is_a_strand
            seen[1] |= m.is_b_strand
            if seen[0] and seen[1]:
                raise ValueError(
                    f"simplex-metrics received duplex-UMI data: base UMI "
                    f"{m.base_umi!r} has reads on both the /A and /B strands. "
                    "Run duplex-metrics for duplex data.")

        for idx, fraction in enumerate(fractions):
            downsampled = [m for m in metadata
                           if m.template.hash_fraction <= fraction]
            if not downsampled:
                continue
            fraction_counts[idx] += len(downsampled)
            collectors[idx].record_cs_family(len(downsampled))

            ss_groups = {}
            for m in downsampled:
                ss_groups[m.template.mi] = ss_groups.get(m.template.mi, 0) + 1
            for size in ss_groups.values():
                collectors[idx].record_ss_family(size)

            if idx == last_idx:
                umi_groups = {}
                for m in downsampled:
                    umi_groups.setdefault(m.base_umi, []).append(m.template.rx)
                for rx_tags in umi_groups.values():
                    split_rx = [rx.split("-") for rx in rx_tags]
                    num_components = len(split_rx[0]) if split_rx else 0
                    for pos in range(num_components):
                        umis = [parts[pos] for parts in split_rx
                                if pos < len(parts)]
                        if not umis:
                            continue
                        cons = _safe_consensus(umis)
                        errors = sum(1 for u in umis if u != cons)
                        collectors[idx].umi_counts.record(
                            cons, len(umis), errors, True)

    try:
        total, fraction_counts = process_templates_from_bam(
            args.input, intervals, len(fractions), process_group)
    except ValueError as e:
        log.error("%s", e)
        return 2

    full = collectors[last_idx]
    write_metrics(f"{args.output}.family_sizes.txt",
                  full.family_size_metrics(), FAMILY_SIZE_FIELDS)
    yields = [_yield_metric(c, f, n, args.min_reads)
              for c, f, n in zip(collectors, fractions, fraction_counts)]
    write_metrics(f"{args.output}.simplex_yield_metrics.txt", yields,
                  YIELD_FIELDS)
    write_metrics(f"{args.output}.umi_counts.txt",
                  full.umi_counts.to_metrics(), UMI_FIELDS)

    log.info("simplex-metrics: %d templates -> %s.{family_sizes,"
             "simplex_yield_metrics,umi_counts}.txt", total, args.output)
    return 0
