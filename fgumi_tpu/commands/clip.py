"""clip: overlapping-pair clipping + fixed-end clipping + tag repair.

Mirrors /root/reference/src/lib/commands/clip.rs:
- query-grouped input required (clipping is template-based);
- per template: optional --upgrade-clipping pre-pass over EVERY read
  (including secondary/supplementary, ClipBam.scala:123), then clip the
  primary pair/fragment found by SAM flags (find_primary_pair_indices,
  clip.rs:1023-1050; duplicate primaries are an error), then repair mate info
  on the pair (set_mate_info_raw, clip.rs:926-990) and on supplementary
  alignments (fix_supplemental_mate_info, clip.rs:1054-1080);
- fixed 5'/3' clipping per read with R1/R2 thresholds routed by first/last
  segment flags (clip_pair, clip.rs:390-480);
- overlap clipping (FR midpoint) and extending-past-mate clipping;
- NM/UQ/MD regeneration against the reference FASTA for every record
  (clip.rs:649,763);
- a lone primary R2 or an all-secondary template passes through untouched
  (fgbio ClipBam case _ => ());
- metrics: per-read-type clipped-bases and clipped-read counts.
"""

import logging
from dataclasses import dataclass, field

from ..core.alignment_tags import regenerate_alignment_tags
from ..core.clipper import MutableRecord, RecordClipper, clipped_bases
from ..core.template import iter_name_groups
from ..io.bam import (FLAG_FIRST, FLAG_LAST, FLAG_MATE_REVERSE,
                      FLAG_MATE_UNMAPPED, FLAG_PAIRED, FLAG_REVERSE,
                      FLAG_SECONDARY, FLAG_SUPPLEMENTARY, FLAG_UNMAPPED)

log = logging.getLogger("fgumi_tpu.clip")


@dataclass
class ClipParams:
    clipping_mode: str = "hard"
    clip_overlapping_reads: bool = False
    clip_extending_past_mate: bool = False
    read_one_five_prime: int = 0
    read_one_three_prime: int = 0
    read_two_five_prime: int = 0
    read_two_three_prime: int = 0
    upgrade_clipping: bool = False
    auto_clip_attributes: bool = False

    def any_clipping(self) -> bool:
        return (self.upgrade_clipping or self.clip_overlapping_reads
                or self.clip_extending_past_mate or self.read_one_five_prime > 0
                or self.read_one_three_prime > 0 or self.read_two_five_prime > 0
                or self.read_two_three_prime > 0)


@dataclass
class ClipTypeMetrics:
    """Per read-type clipping counters (metrics/clip.rs analog)."""
    reads: int = 0
    reads_unmapped: int = 0
    reads_clipped_pre: int = 0
    reads_clipped_five_prime: int = 0
    reads_clipped_three_prime: int = 0
    reads_clipped_overlapping: int = 0
    reads_clipped_extending: int = 0
    bases: int = 0
    bases_clipped_pre: int = 0
    bases_clipped_five_prime: int = 0
    bases_clipped_three_prime: int = 0
    bases_clipped_overlapping: int = 0
    bases_clipped_extending: int = 0

    def update(self, rec: MutableRecord, prior: int, five: int, three: int,
               overlapping: int = 0, extending: int = 0):
        self.reads += 1
        self.bases += len(rec.seq)
        if rec.is_unmapped():
            self.reads_unmapped += 1
        for count, rattr, battr in (
                (prior, "reads_clipped_pre", "bases_clipped_pre"),
                (five, "reads_clipped_five_prime", "bases_clipped_five_prime"),
                (three, "reads_clipped_three_prime", "bases_clipped_three_prime"),
                (overlapping, "reads_clipped_overlapping", "bases_clipped_overlapping"),
                (extending, "reads_clipped_extending", "bases_clipped_extending")):
            if count > 0:
                setattr(self, rattr, getattr(self, rattr) + 1)
                setattr(self, battr, getattr(self, battr) + count)


@dataclass
class ClipMetrics:
    templates: int = 0
    overlap_clipped: int = 0
    extend_clipped: int = 0
    fragment: ClipTypeMetrics = field(default_factory=ClipTypeMetrics)
    read_one: ClipTypeMetrics = field(default_factory=ClipTypeMetrics)
    read_two: ClipTypeMetrics = field(default_factory=ClipTypeMetrics)


def find_primary_pair(records):
    """(i1, i2) indices of the primary R1 (or fragment) and R2 by SAM flags;
    duplicates are an error (clip.rs:1023-1050)."""
    i1 = i2 = None
    for i, rec in enumerate(records):
        if rec.flag & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY):
            continue
        if not rec.flag & FLAG_PAIRED or rec.flag & FLAG_FIRST:
            if i1 is not None:
                raise ValueError(
                    f"Multiple non-secondary, non-supplemental R1s for "
                    f"{records[i].name.decode(errors='replace')}")
            i1 = i
        elif rec.flag & FLAG_LAST:
            if i2 is not None:
                raise ValueError(
                    f"Multiple non-secondary, non-supplemental R2s for "
                    f"{records[i].name.decode(errors='replace')}")
            i2 = i
    return i1, i2


def _insert_size(r1: MutableRecord, r2: MutableRecord) -> int:
    """htsjdk computeInsertSize on the post-clip pair (5'-to-5', signed)."""
    if r1.ref_id != r2.ref_id:
        return 0
    pos1 = r1.alignment_end() + 1 if r1.is_reverse() else r1.pos + 1
    pos2 = r2.alignment_end() + 1 if r2.is_reverse() else r2.pos + 1
    adjustment = 1 if pos2 >= pos1 else -1
    return pos2 - pos1 + adjustment


def _set_mate_flags(rec: MutableRecord, mate_reverse: bool, mate_unmapped: bool):
    rec.flag &= ~(FLAG_MATE_REVERSE | FLAG_MATE_UNMAPPED)
    if mate_reverse:
        rec.flag |= FLAG_MATE_REVERSE
    if mate_unmapped:
        rec.flag |= FLAG_MATE_UNMAPPED


def _set_mate_mq_mc(rec: MutableRecord, mate: MutableRecord):
    rec.set_int_tag(b"MQ", mate.mapq)
    cig = mate.cigar_string()
    if cig != "*":
        rec.set_str_tag(b"MC", cig.encode())
    else:
        rec.remove_tag(b"MC")


def set_mate_info(r1: MutableRecord, r2: MutableRecord):
    """set_mate_info_raw (clip.rs:926-990): refresh mate pointers after
    clipping may have moved/unmapped either read."""
    u1, u2 = r1.is_unmapped(), r2.is_unmapped()
    if not u1 and not u2:
        for rec, mate in ((r1, r2), (r2, r1)):
            rec.next_ref_id = mate.ref_id
            rec.next_pos = mate.pos
            _set_mate_flags(rec, mate.is_reverse(), False)
            _set_mate_mq_mc(rec, mate)
        tlen = _insert_size(r1, r2)
        r1.tlen, r2.tlen = tlen, -tlen
    elif u1 and u2:
        for rec, mate in ((r1, r2), (r2, r1)):
            rec.ref_id = rec.next_ref_id = -1
            rec.pos = rec.next_pos = -1
            _set_mate_flags(rec, mate.is_reverse(), True)
            rec.remove_tag(b"MQ")
            rec.remove_tag(b"MC")
            rec.tlen = 0
    else:
        mapped, unmapped = (r2, r1) if u1 else (r1, r2)
        unmapped.ref_id = unmapped.next_ref_id = mapped.ref_id
        unmapped.pos = unmapped.next_pos = mapped.pos
        _set_mate_flags(unmapped, mapped.is_reverse(), False)
        _set_mate_mq_mc(unmapped, mapped)
        unmapped.tlen = 0
        mapped.next_ref_id = mapped.ref_id
        mapped.next_pos = mapped.pos
        _set_mate_flags(mapped, unmapped.is_reverse(), True)
        mapped.remove_tag(b"MQ")
        mapped.remove_tag(b"MC")
        mapped.tlen = 0


def fix_supplemental_mate_info(records, i1, i2):
    """Supplementals point at the opposite primary (clip.rs:1054-1080)."""
    for rec in records:
        if not rec.flag & FLAG_SUPPLEMENTARY:
            continue
        if not rec.flag & FLAG_PAIRED or rec.flag & FLAG_FIRST:
            mate_i = i2
        elif rec.flag & FLAG_LAST:
            mate_i = i1
        else:
            continue
        if mate_i is None:
            continue
        mate = records[mate_i]
        rec.next_ref_id = mate.ref_id
        rec.next_pos = mate.pos
        _set_mate_flags(rec, mate.is_reverse(), mate.is_unmapped())
        rec.tlen = -mate.tlen
        if mate.is_unmapped():
            rec.remove_tag(b"MC")
        else:
            rec.set_str_tag(b"MC", mate.cigar_string().encode())
        rec.set_int_tag(b"MQ", mate.mapq)


def clip_template(records, clipper: RecordClipper, params: ClipParams,
                  metrics: ClipMetrics):
    """Clip one template's primary reads in place; returns
    (overlap_clipped, extend_clipped)."""
    if params.upgrade_clipping:
        for rec in records:
            clipper.upgrade_all_clipping(rec)
    i1, i2 = find_primary_pair(records)
    if i1 is not None and i2 is not None:
        r1, r2 = records[i1], records[i2]
        outcome = _clip_pair(clipper, params, r1, r2, metrics)
        set_mate_info(r1, r2)
        fix_supplemental_mate_info(records, i1, i2)
        return outcome
    if i1 is not None:
        _clip_fragment(clipper, params, records[i1], metrics)
    return (False, False)


def _clip_fragment(clipper, params, rec, metrics: ClipMetrics):
    prior = clipped_bases(rec)
    five = (clipper.clip_5_prime_end_of_read(rec, params.read_one_five_prime)
            if params.read_one_five_prime > 0 else 0)
    three = (clipper.clip_3_prime_end_of_read(rec, params.read_one_three_prime)
             if params.read_one_three_prime > 0 else 0)
    metrics.fragment.update(rec, prior, five, three)


def _clip_pair(clipper, params, r1, r2, metrics: ClipMetrics):
    prior1, prior2 = clipped_bases(r1), clipped_bases(r2)
    is_r1_first = bool(r1.flag & FLAG_FIRST) or not r1.flag & FLAG_PAIRED
    is_r2_last = bool(r2.flag & FLAG_LAST)

    def fixed(rec, first_thresholds):
        five_t, three_t = first_thresholds
        five = clipper.clip_5_prime_end_of_read(rec, five_t) if five_t > 0 else 0
        three = clipper.clip_3_prime_end_of_read(rec, three_t) if three_t > 0 else 0
        return five, three

    one = (params.read_one_five_prime, params.read_one_three_prime)
    two = (params.read_two_five_prime, params.read_two_three_prime)
    five1, three1 = fixed(r1, one if is_r1_first else two)
    five2, three2 = fixed(r2, two if is_r2_last else one)

    if params.clip_overlapping_reads:
        over1, over2 = clipper.clip_overlapping_reads(r1, r2)
    else:
        over1 = over2 = 0
    if params.clip_extending_past_mate:
        ext1, ext2 = clipper.clip_extending_past_mate_ends(r1, r2)
    else:
        ext1 = ext2 = 0

    (metrics.read_one if is_r1_first else metrics.read_two).update(
        r1, prior1, five1, three1, over1, ext1)
    (metrics.read_two if is_r2_last else metrics.read_one).update(
        r2, prior2, five2, three2, over2, ext2)
    return (over1 > 0 or over2 > 0, ext1 > 0 or ext2 > 0)


def run_clip(reader, writer, reference, params: ClipParams):
    """Stream reader -> writer clipping templates; returns ClipMetrics."""
    clipper = RecordClipper(params.clipping_mode, params.auto_clip_attributes)
    metrics = ClipMetrics()
    ref_names = reader.header.ref_names
    for _name, raw_records in iter_name_groups(reader):
        records = [MutableRecord.from_raw(r) for r in raw_records]
        metrics.templates += 1
        overlap, extend = clip_template(records, clipper, params, metrics)
        if overlap:
            metrics.overlap_clipped += 1
        if extend:
            metrics.extend_clipped += 1
        for rec in records:
            regenerate_alignment_tags(rec, ref_names, reference)
            writer.write_record_bytes(rec.encode())
    return metrics


_METRIC_COLUMNS = [
    "read_type", "reads", "reads_unmapped", "reads_clipped_pre",
    "reads_clipped_five_prime", "reads_clipped_three_prime",
    "reads_clipped_overlapping", "reads_clipped_extending", "bases",
    "bases_clipped_pre", "bases_clipped_five_prime",
    "bases_clipped_three_prime", "bases_clipped_overlapping",
    "bases_clipped_extending",
]


def write_clip_metrics(metrics: ClipMetrics, path: str):
    from ..utils.atomic import open_output

    with open_output(path, "w") as f:
        f.write("\t".join(_METRIC_COLUMNS) + "\n")
        for read_type, m in (("fragment", metrics.fragment),
                             ("read_one", metrics.read_one),
                             ("read_two", metrics.read_two)):
            row = [read_type] + [str(getattr(m, c)) for c in _METRIC_COLUMNS[1:]]
            f.write("\t".join(row) + "\n")
