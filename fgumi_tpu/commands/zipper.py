"""zipper: merge an aligner's output BAM with the original unmapped BAM.

Streaming merge matching the reference (/root/reference/src/lib/commands/
zipper.rs): both inputs must be queryname-sorted/grouped with identical
ordering. Per template: fix mate info (MC/MQ/ms, TLEN), remove requested tags,
copy all tags from the unmapped primaries onto the matching mapped records
(reverse / reverse-complement per-base tags on negative-strand reads), transfer
the QC-fail flag, normalize AS/XS to the smallest signed int type, and add a
``tc`` template-coordinate tag (B:i array) to secondary/supplementary records.
"""

from dataclasses import dataclass, field

from ..core.record_edit import (TagEditor, cigar_string, raw_tag_entries,
                                set_bin, set_flags, set_mate_pos,
                                set_mate_ref_id, set_pos, set_ref_id,
                                set_tlen)
from ..core.tag_reversal import (TAGS_TO_REVERSE, TAGS_TO_REVERSE_COMPLEMENT,
                                 revcomp_tag_value_at, reverse_tag_value_at)
from ..core.template import iter_name_groups, unclipped_5prime
from ..io.bam import (FLAG_FIRST, FLAG_MATE_REVERSE, FLAG_MATE_UNMAPPED,
                      FLAG_PAIRED, FLAG_QC_FAIL, FLAG_REVERSE, FLAG_SECONDARY,
                      FLAG_SUPPLEMENTARY, FLAG_UNMAPPED, RawRecord)

# The "Consensus" named tag set (umi TagSets; tag_reversal.rs:88-90), derived
# from the canonical byte constants in core.tag_reversal.
CONSENSUS_REVERSE_TAGS = tuple(t.decode() for t in TAGS_TO_REVERSE)
CONSENSUS_REVCOMP_TAGS = tuple(t.decode() for t in TAGS_TO_REVERSE_COMPLEMENT)


@dataclass
class TagInfo:
    remove: set = field(default_factory=set)
    reverse: set = field(default_factory=set)
    revcomp: set = field(default_factory=set)

    @classmethod
    def from_options(cls, remove=(), reverse=(), revcomp=()):
        def expand(names, consensus):
            out = set()
            for n in names:
                if n == "Consensus":
                    out.update(consensus)
                else:
                    out.add(n)
            return out

        return cls(remove=expand(remove, ()),
                   reverse=expand(reverse, CONSENSUS_REVERSE_TAGS),
                   revcomp=expand(revcomp, CONSENSUS_REVCOMP_TAGS))


@dataclass
class MappedTemplate:
    """One QNAME's mapped records as mutable bytearrays, classified."""
    name: bytes
    bufs: list  # bytearray per record, input order
    r1: int | None = None  # index of primary R1 (or fragment)
    r2: int | None = None
    r1_others: list = field(default_factory=list)  # secondary/supp of R1/fragment
    r2_others: list = field(default_factory=list)
    r1_supplementals: list = field(default_factory=list)
    r2_supplementals: list = field(default_factory=list)

    @classmethod
    def from_records(cls, name, records):
        t = cls(name=name, bufs=[bytearray(r.data) for r in records])
        for i, rec in enumerate(records):
            flg = rec.flag
            first = (not flg & FLAG_PAIRED) or bool(flg & FLAG_FIRST)
            if flg & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY):
                (t.r1_others if first else t.r2_others).append(i)
                if flg & FLAG_SUPPLEMENTARY:
                    (t.r1_supplementals if first
                     else t.r2_supplementals).append(i)
            elif first:
                t.r1 = i
            else:
                t.r2 = i
        return t


def _flag(buf) -> int:
    return int.from_bytes(buf[14:16], "little")


def _rec(buf) -> RawRecord:
    return RawRecord(bytes(buf))


def _set_mate_flags(buf, mate_reverse: bool, mate_unmapped: bool):
    f = _flag(buf) & ~(FLAG_MATE_REVERSE | FLAG_MATE_UNMAPPED)
    if mate_reverse:
        f |= FLAG_MATE_REVERSE
    if mate_unmapped:
        f |= FLAG_MATE_UNMAPPED
    set_flags(buf, f)


def _insert_size(rec1: RawRecord, rec2: RawRecord) -> int:
    """TLEN via 5'-to-5' distance (template.rs:819-851, htsjdk convention)."""
    if rec1.flag & FLAG_UNMAPPED or rec2.flag & FLAG_UNMAPPED:
        return 0
    if rec1.ref_id != rec2.ref_id:
        return 0
    pos1, pos2 = rec1.pos + 1, rec2.pos + 1
    end1 = pos1 + rec1.reference_length() - 1
    end2 = pos2 + rec2.reference_length() - 1
    first_5p = end1 if rec1.flag & FLAG_REVERSE else pos1
    second_5p = end2 if rec2.flag & FLAG_REVERSE else pos2
    adjustment = 1 if second_5p >= first_5p else -1
    return second_5p - first_5p + adjustment


def _as_tag(rec: RawRecord):
    return rec.get_int(b"AS")


def _set_mate_from(buf, ed: TagEditor, mate: RawRecord, tlen=None):
    """Write mate ref/pos/flags/MQ/MC from `mate` onto `buf`/its editor."""
    set_mate_ref_id(buf, mate.ref_id)
    set_mate_pos(buf, mate.pos)
    mate_unmapped = bool(mate.flag & FLAG_UNMAPPED)
    _set_mate_flags(buf, bool(mate.flag & FLAG_REVERSE), mate_unmapped)
    ed.set_i32(b"MQ", mate.mapq)
    cig = cigar_string(mate)
    if cig != "*" and not mate_unmapped:
        ed.set_str(b"MC", cig.encode())
    else:
        ed.remove(b"MC")
    if tlen is not None:
        set_tlen(buf, tlen)


def fix_mate_info(t: MappedTemplate, editors=None):
    """template.rs:459-605: primary pair mate pointers, MQ/MC/ms tags, TLEN,
    and supplementals pointing at the opposite primary. With editors=None
    (standalone use) the staged aux edits apply back into t.bufs."""
    standalone = editors is None
    if standalone:
        editors = [TagEditor(buf) for buf in t.bufs]
    if t.r1 is not None and t.r2 is not None:
        b1, b2 = t.bufs[t.r1], t.bufs[t.r2]
        e1, e2 = editors[t.r1], editors[t.r2]
        r1, r2 = _rec(b1), _rec(b2)
        r1_unmapped = bool(r1.flag & FLAG_UNMAPPED)
        r2_unmapped = bool(r2.flag & FLAG_UNMAPPED)
        r1_as, r2_as = _as_tag(r1), _as_tag(r2)
        if not r1_unmapped and not r2_unmapped:
            tlen = _insert_size(r1, r2)
            _set_mate_from(b1, e1, r2, tlen)
            _set_mate_from(b2, e2, r1, -tlen)
        elif r1_unmapped and r2_unmapped:
            for b, ed, other in ((b1, e1, r2), (b2, e2, r1)):
                set_ref_id(b, -1)
                set_pos(b, -1)
                set_mate_ref_id(b, -1)
                set_mate_pos(b, -1)
                _set_mate_flags(b, bool(other.flag & FLAG_REVERSE), True)
                ed.remove(b"MQ")
                ed.remove(b"MC")
                set_tlen(b, 0)
                set_bin(b)  # POS moved to -1: bin must be reg2bin(-1,0)=4680
        else:
            mapped_i, unmapped_i = (t.r2, t.r1) if r1_unmapped                 else (t.r1, t.r2)
            mapped_b, unmapped_b = t.bufs[mapped_i], t.bufs[unmapped_i]
            mapped = _rec(mapped_b)
            unmapped = _rec(unmapped_b)
            # place the unmapped read at its mate's coordinates
            set_ref_id(unmapped_b, mapped.ref_id)
            set_pos(unmapped_b, mapped.pos)
            set_mate_ref_id(mapped_b, mapped.ref_id)
            set_mate_pos(mapped_b, mapped.pos)
            _set_mate_flags(mapped_b, bool(unmapped.flag & FLAG_REVERSE), True)
            editors[mapped_i].remove(b"MQ")
            editors[mapped_i].remove(b"MC")
            set_tlen(mapped_b, 0)
            _set_mate_from(unmapped_b, editors[unmapped_i], mapped, 0)
            set_bin(unmapped_b)
        # ms (mate score) from the mate's AS, both cases
        if r2_as is not None:
            e1.set_i32(b"ms", int(r2_as))
        if r1_as is not None:
            e2.set_i32(b"ms", int(r1_as))

    # Supplementals point at the opposite primary (template.rs:513-605).
    for supp_list, primary_i in ((t.r1_supplementals, t.r2),
                                 (t.r2_supplementals, t.r1)):
        if primary_i is None or not supp_list:
            continue
        pbuf = t.bufs[primary_i]
        primary = _rec(pbuf)
        p_tlen = primary.tlen
        p_as = _as_tag(primary)
        for i in supp_list:
            _set_mate_from(t.bufs[i], editors[i], primary, -p_tlen)
            if p_as is not None:
                editors[i].set_i32(b"ms", int(p_as))
    if standalone:
        for i, ed in enumerate(editors):
            t.bufs[i][:] = ed.finish()


def add_template_coordinate_tags(t: MappedTemplate, editors=None):
    """tc tag (B:i [tid1,pos1,neg1,tid2,pos2,neg2], lower coordinate first) on
    secondary/supplementary records only (zipper.rs:281-357). With
    editors=None (standalone use) the edits apply back into t.bufs."""
    others = t.r1_others + t.r2_others
    if not others:
        return
    standalone = editors is None
    if standalone:
        others_set = set(others)
        editors = [TagEditor(t.bufs[i]) if i in others_set else None
                   for i in range(len(t.bufs))]

    def info(i):
        if i is None:
            return None
        rec = _rec(t.bufs[i])
        if rec.flag & FLAG_UNMAPPED:
            return None
        return (rec.ref_id, unclipped_5prime(rec),
                1 if rec.flag & FLAG_REVERSE else 0)

    i1, i2 = info(t.r1), info(t.r2)
    if i1 is not None and i2 is not None:
        a, b = (i1, i2) if (i1[0], i1[1]) <= (i2[0], i2[1]) else (i2, i1)
    elif i1 is not None or i2 is not None:
        a = b = i1 if i1 is not None else i2
    else:
        return
    values = [a[0], a[1], a[2], b[0], b[1], b[2]]
    for i in others:
        editors[i].set_i32_array(b"tc", values)
    if standalone:
        for i in others:
            t.bufs[i][:] = editors[i].finish()


def merge_template(unmapped_records, t: MappedTemplate, tag_info: TagInfo,
                   skip_tc_tags: bool = False):
    """Transfer tags/flags from an unmapped template onto the mapped one
    (zipper.rs merge_raw:397-545). Returns the rebuilt record bytes (one
    aux-region rebuild per record via TagEditor)."""
    editors = [TagEditor(buf) for buf in t.bufs]
    fix_mate_info(t, editors)

    for ed in editors:
        for tag in tag_info.remove:
            if len(tag) == 2:
                ed.remove(tag.encode())

    primaries = [r for r in unmapped_records
                 if not r.flag & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY)]
    for u in primaries:
        u_flags = u.flag
        is_unpaired = not u_flags & FLAG_PAIRED
        is_first = bool(u_flags & FLAG_FIRST)
        if is_unpaired or is_first:
            indices = ([t.r1] if t.r1 is not None else []) + t.r1_others
        else:
            indices = ([t.r2] if t.r2 is not None else []) + t.r2_others
        u_tags = [(tag, typ, vb) for tag, typ, vb in raw_tag_entries(u)
                  if tag.decode(errors="replace") not in tag_info.remove]
        for i in indices:
            ed = editors[i]
            has_pg = ed.find(b"PG") is not None
            negative = bool(_flag(t.bufs[i]) & FLAG_REVERSE)
            for entry in u_tags:
                tag, typ, vb = entry
                if tag == b"PG" and has_pg:
                    continue
                ed.remove(tag)
                if negative:
                    tag_str = tag.decode(errors="replace")
                    if tag_str in tag_info.reverse:
                        vb = bytearray(vb)
                        reverse_tag_value_at(vb, typ, 0)
                        vb = bytes(vb)
                    elif tag_str in tag_info.revcomp:
                        vb = bytearray(vb)
                        revcomp_tag_value_at(vb, typ, 0)
                        vb = bytes(vb)
                ed.append_entry(tag, typ, vb)
        # QC pass/fail transfer
        qc_fail = bool(u_flags & FLAG_QC_FAIL)
        for i in indices:
            f = _flag(t.bufs[i])
            f = (f | FLAG_QC_FAIL) if qc_fail else (f & ~FLAG_QC_FAIL)
            set_flags(t.bufs[i], f)

    for ed in editors:
        ed.normalize_int_smallest(b"AS")
        ed.normalize_int_smallest(b"XS")

    if not skip_tc_tags:
        add_template_coordinate_tags(t, editors)
    return [ed.finish() for ed in editors]


def run_zipper(mapped_reader, unmapped_reader, writer, tag_info: TagInfo, *,
               skip_tc_tags: bool = False, exclude_missing_reads: bool = False,
               restore_unconverted=None):
    """Lockstep merge by QNAME. Returns (templates, records_out, missing).

    Both inputs must share queryname ordering. An unmapped template absent from
    the mapped BAM (aligner dropped it) is written through as unmapped records,
    or dropped under exclude_missing_reads (zipper.rs:896-928); a mapped
    template absent from the unmapped BAM is always an error (the unmapped BAM
    is the source of truth).
    """
    mapped_groups = iter_name_groups(mapped_reader)
    n_templates = 0
    n_records = 0
    n_missing = 0
    mapped_item = next(mapped_groups, None)
    for u_name, u_records in iter_name_groups(unmapped_reader):
        if mapped_item is None or mapped_item[0] != u_name:
            # aligner omitted this template: write it through as unmapped
            # records (zipper.rs:896-928), or drop under exclude_missing_reads
            n_missing += 1
            if not exclude_missing_reads:
                for rec in u_records:
                    writer.write_record_bytes(rec.data)
                    n_records += 1
                n_templates += 1
            continue
        t = MappedTemplate.from_records(mapped_item[0], mapped_item[1])
        out_bytes = merge_template(u_records, t, tag_info, skip_tc_tags)
        for data in out_bytes:
            if restore_unconverted is not None:
                data = restore_unconverted_bases_record(
                    data, restore_unconverted[0], restore_unconverted[1])
            writer.write_record_bytes(data)
            n_records += 1
        n_templates += 1
        mapped_item = next(mapped_groups, None)
    if mapped_item is not None:
        raise ValueError(
            f"read '{mapped_item[0].decode(errors='replace')}' present in the "
            "mapped BAM but not in the unmapped BAM; inputs must share "
            "queryname ordering")
    return n_templates, n_records, n_missing


def restore_unconverted_bases_record(data: bytes, reference,
                                     ref_names) -> bytes:
    """EM-Seq post-bwameth restore (zipper.rs:629-760): for a mapped record
    carrying the bwameth YD strand tag ('f' top / 'r' bottom), rewrite
    converted bases back to the unconverted reference form at aligned
    ref-C (top) / ref-G (bottom) positions. SEQ is stored in reference
    orientation, so the (strand, reverse-flag) pair picks the target:
    (top, fwd) and (bottom, rev) restore C<-T; the other two G<-A.
    Methylation state stays in the MM/ML/cu/ct tags."""
    import numpy as np

    from ..constants import BASE_TO_CODE
    from ..io.bam import FLAG_REVERSE, FLAG_UNMAPPED

    rec = RawRecord(data)
    if rec.flag & FLAG_UNMAPPED or rec.ref_id < 0 \
            or rec.ref_id >= len(ref_names):
        return data
    yd = rec.get_str(b"YD")
    if yd == "f":
        is_top = True
    elif yd == "r":
        is_top = False
    else:
        return data
    ref_seq = reference.get(ref_names[rec.ref_id]) \
        if hasattr(reference, "get") else None
    if ref_seq is None:
        return data
    is_reverse = bool(rec.flag & FLAG_REVERSE)
    if is_top != is_reverse:  # (top, fwd) / (bottom, rev)
        target, conv, unconv = ord("C"), ord("T"), ord("C")
    else:
        target, conv, unconv = ord("G"), ord("A"), ord("G")

    # per-query-position ref byte (uppercased), -1 for I/S — the shared
    # resolver used by the methylation filters too
    from ..consensus.methylation import ref_bytes_for_alignment

    l_seq = rec.l_seq
    ref_at = ref_bytes_for_alignment(rec.cigar(), rec.pos, ref_seq, l_seq)

    seq = rec.seq_bytes()
    codes = np.frombuffer(seq, dtype=np.uint8)
    hit = (ref_at[:len(codes)] == target) & (codes == conv)
    if not hit.any():
        return data
    # rewrite the packed nibbles in place
    buf = bytearray(data)
    l_read_name = buf[8]
    n_cigar = int.from_bytes(buf[12:14], "little")
    seq_off = 32 + l_read_name + 4 * n_cigar
    packed = np.frombuffer(bytes(buf[seq_off:seq_off + (l_seq + 1) // 2]),
                           dtype=np.uint8)
    nib = np.empty(2 * len(packed), dtype=np.uint8)
    nib[0::2] = packed >> 4
    nib[1::2] = packed & 0xF
    nib = nib[:l_seq].copy()
    # BAM nibble code for the unconverted base (A=1 C=2 G=4 T=8 in SAM spec
    # 16-code space; BASE_TO_CODE is our 0..4 space, so map via seq chars)
    nib[hit] = 2 if unconv == ord("C") else 4
    out = np.zeros(((l_seq + 1) // 2) * 2, dtype=np.uint8)
    out[:l_seq] = nib
    buf[seq_off:seq_off + (l_seq + 1) // 2] = \
        ((out[0::2] << 4) | out[1::2]).astype(np.uint8).tobytes()
    return bytes(buf)
