"""Typed metric rows + fgbio-compatible TSV writing (fgumi-metrics analog).

Mirrors /root/reference/crates/fgumi-metrics/src/: float formatting follows
float.rs (integral values drop the fraction; NaN/Infinity use Java tokens so
fgbio's Metric.read can parse them); metric files are TSVs whose header row is
the field-name list (writer.rs). UmiCountTracker ports shared.rs.
"""

import math
from dataclasses import fields, is_dataclass


def format_metric_value(v) -> str:
    """fgbio Metric cell format (crates/fgumi-metrics/src/float.rs:30-57)."""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if math.isinf(v):
            return "Infinity" if v > 0 else "-Infinity"
        if v == int(v) and abs(v) < 2**63:
            return str(int(v))
        return repr(v)
    return str(v)


def write_metrics(path: str, rows: list, fieldnames=None):
    """Write metric rows (dataclasses or dicts) as an fgbio-style TSV.

    The header is the field-name list; an empty `rows` with explicit
    `fieldnames` still writes the header (fgbio writes headers for empty
    metric files).
    """
    if fieldnames is None:
        if not rows:
            raise ValueError("fieldnames required when rows is empty")
        first = rows[0]
        fieldnames = [f.name for f in fields(first)] if is_dataclass(first) \
            else list(first.keys())
    from .utils.atomic import open_output

    with open_output(path, "w") as fh:
        fh.write("\t".join(fieldnames) + "\n")
        for row in rows:
            get = (lambda r, k: getattr(r, k)) if is_dataclass(row) \
                else (lambda r, k: r[k])
            fh.write("\t".join(format_metric_value(get(row, k))
                              for k in fieldnames) + "\n")


def frac(n: int, d: int) -> float:
    """n/d with 0 for an empty denominator (fgumi-metrics lib.rs frac)."""
    return n / d if d else 0.0


def family_size_rows(histograms: dict) -> list:
    """Sparse per-size rows with reversed-cumulative >=size fractions.

    `histograms` maps a column prefix (e.g. "cs") to its {size: count} map;
    output rows carry `<prefix>_count`, `<prefix>_fraction`, and
    `<prefix>_fraction_gt_or_eq_size` per prefix, sorted ascending by
    family_size (fgumi-metrics duplex.rs:333-388 / simplex.rs equivalent).
    """
    totals = {p: sum(h.values()) for p, h in histograms.items()}
    sizes = sorted(set().union(*histograms.values()) if histograms else ())
    rows = []
    for size in sizes:
        row = {"family_size": size}
        for prefix, hist in histograms.items():
            count = hist.get(size, 0)
            row[f"{prefix}_count"] = count
            row[f"{prefix}_fraction"] = frac(count, totals[prefix])
            row[f"{prefix}_fraction_gt_or_eq_size"] = 0.0
        rows.append(row)
    for prefix in histograms:
        running = 0.0
        for row in reversed(rows):
            running += row[f"{prefix}_fraction"]
            row[f"{prefix}_fraction_gt_or_eq_size"] = running
    return rows


def size_distribution_fields(size_field: str) -> list:
    """Column schema of size_distribution_rows (one place: callers pass
    this as write_metrics' fieldnames so empty inputs still write the
    correct header)."""
    return [size_field, "count", "fraction",
            f"fraction_gt_or_eq_{size_field}"]


def size_distribution_rows(counts: dict, size_field: str) -> list:
    """fgbio-format size distribution over one {size: count} map: ascending
    `size_field` rows with `count`, `fraction`, and the reverse-cumulative
    `fraction_gt_or_eq_<size_field>` (fgumi-metrics group.rs
    build_size_distribution: the family-size and position-group-size
    files of the `group` command)."""
    total = sum(counts.values())
    rows = []
    for size in sorted(counts):
        rows.append({size_field: size, "count": counts[size],
                     "fraction": frac(counts[size], total),
                     f"fraction_gt_or_eq_{size_field}": 0.0})
    running = 0.0
    for row in reversed(rows):
        running += row["fraction"]
        row[f"fraction_gt_or_eq_{size_field}"] = running
    return rows


def umi_grouping_metrics_row(filter_metrics: dict) -> dict:
    """The 5-column fgbio `UmiGroupingMetric` row (fgumi-metrics
    group.rs:55-77, incl. fgbio's `discarded_umis_to_short` spelling),
    from the group engines' filter-metrics dict (zero-valued counters are
    dropped by as_dict, so absent keys read as 0)."""
    return {
        "accepted_sam_records": filter_metrics.get("accepted", 0),
        "discarded_non_pf": filter_metrics.get("non_pf", 0),
        "discarded_poor_alignment": filter_metrics.get("poor_alignment", 0),
        "discarded_ns_in_umi": filter_metrics.get("ns_in_umi", 0),
        "discarded_umis_to_short": filter_metrics.get("umi_too_short", 0),
    }


class UmiCountTracker:
    """Raw/error/unique observation counts per UMI (shared.rs:61-140)."""

    def __init__(self):
        self.counts = {}  # umi -> [raw, errors, unique]

    def record(self, umi: str, raw_count: int, error_count: int, is_unique: bool):
        entry = self.counts.setdefault(umi, [0, 0, 0])
        entry[0] += raw_count
        entry[1] += error_count
        if is_unique:
            entry[2] += 1

    def total_raw(self) -> int:
        return sum(e[0] for e in self.counts.values())

    def total_unique(self) -> int:
        return sum(e[2] for e in self.counts.values())

    def to_metrics(self) -> list:
        """Sorted [{umi, raw_observations, ...}] rows (shared.rs:110-140)."""
        total_raw = self.total_raw()
        total_unique = self.total_unique()
        rows = []
        for umi in sorted(self.counts):
            raw, errors, unique = self.counts[umi]
            rows.append({
                "umi": umi,
                "raw_observations": raw,
                "raw_observations_with_errors": errors,
                "unique_observations": unique,
                "fraction_raw_observations": frac(raw, total_raw),
                "fraction_unique_observations": frac(unique, total_unique),
            })
        return rows


def binomial_cdf(k: int, n: int, p: float = 0.5) -> float:
    """P(X <= k) for X ~ Binomial(n, p), via log-space term accumulation.

    Exact-enough replacement for statrs Binomial::cdf
    (duplex_metrics.rs:522-545); log-gamma keeps large n stable.
    """
    if k < 0:
        return 0.0
    if k >= n:
        return 1.0
    if p <= 0.0:
        return 1.0
    if p >= 1.0:
        return 0.0
    log_p = math.log(p)
    log_q = math.log(1.0 - p)
    total = 0.0
    lg_n = math.lgamma(n + 1)
    for i in range(k + 1):
        log_term = (lg_n - math.lgamma(i + 1) - math.lgamma(n - i + 1)
                    + i * log_p + (n - i) * log_q)
        total += math.exp(log_term)
    return min(total, 1.0)


def _murmur3_mix_k1(k1: int) -> int:
    k1 = (k1 * 0xCC9E2D51) & 0xFFFFFFFF
    k1 = ((k1 << 15) | (k1 >> 17)) & 0xFFFFFFFF
    return (k1 * 0x1B873593) & 0xFFFFFFFF


def _murmur3_mix_h1(h1: int, k1: int) -> int:
    h1 ^= k1
    h1 = ((h1 << 13) | (h1 >> 19)) & 0xFFFFFFFF
    return (h1 * 5 + 0xE6546B64) & 0xFFFFFFFF


def compute_hash_fraction(read_name: str) -> float:
    """fgbio-compatible Murmur3 downsampling score in [0, 1].

    Ports htsjdk Murmur3.hashUnencodedChars over UTF-16 code units with seed
    42, including the Java Math.abs(Int.MinValue) quirk
    (shared_metrics.rs:122-205).
    """
    chars = [ord(c) for c in read_name]  # BMP names: code units == code points
    # surrogate-pair expansion for non-BMP characters (UTF-16 code units)
    units = []
    for c in chars:
        if c > 0xFFFF:
            c -= 0x10000
            units.append(0xD800 + (c >> 10))
            units.append(0xDC00 + (c & 0x3FF))
        else:
            units.append(c)

    h1 = 42
    length = len(units)
    i = 1
    while i < length:
        k1 = units[i - 1] | (units[i] << 16)
        h1 = _murmur3_mix_h1(h1, _murmur3_mix_k1(k1))
        i += 2
    if length & 1:
        h1 ^= _murmur3_mix_k1(units[length - 1])

    # fmix
    h1 ^= (2 * length) & 0xFFFFFFFF
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & 0xFFFFFFFF
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & 0xFFFFFFFF
    h1 ^= h1 >> 16

    # to signed i32, then Java Math.abs (Int.MinValue stays negative)
    signed = h1 - 0x100000000 if h1 >= 0x80000000 else h1
    abs_val = signed if signed == -0x80000000 else abs(signed)
    return abs_val / 2147483647.0
