"""Fused streaming chain: in-memory stage handoff for the pipeline command.

The reference ships FastqToConsensus as a Snakemake workflow over separate
process invocations (/root/reference/docs/FastqToConsensus-RnD.smk); our
``pipeline`` command chained the stages in one process but still
materialized full intermediate BAMs — four complete serialize+BGZF-encode
passes and four decompress+parse passes per run, with zero overlap between
stages. This module removes the files entirely: adjacent stages hand off
uncompressed BAM *wire chunks* (block_size-prefixed record runs, exactly
the bytes a level-0 intermediate would carry between its BGZF frames)
through a bounded in-memory channel, so

- the producer's serialized output feeds the consumer with no BGZF encode,
  no file write, no file read, and no BGZF decode in between;
- stages genuinely overlap (each runs on its own thread, blocking on the
  channel's byte budget for backpressure);
- byte identity with the staged run holds by construction: the handed-off
  bytes ARE the record wire bytes a file round trip would deliver, and
  headers travel through :func:`fgumi_tpu.io.bam.header_roundtrip` so
  header-derived provenance (@HD rewrites, @PG chaining) sees exactly what
  a decode-from-file would have produced.

Three pieces:

- :class:`ChainChannel` — the bounded, byte-budgeted blob queue with
  backpressure, abort/cancel propagation in both directions, the
  ``chain.handoff`` fault point, and ``pipeline.chain.*`` metrics.
- :class:`ChannelBamWriter` — a ``BamWriter``-compatible sink writing into
  a channel (the writer-to-channel adapter; pairs with
  ``io.bam.header_roundtrip`` for exact header handoff).
- :class:`ChannelBatchReader` — a ``BamBatchReader``-compatible source
  assembling channel blobs into :class:`~fgumi_tpu.io.batch_reader.RecordBatch`
  objects (the reader-from-batches adapter; shares the boundary-scan
  assembler with the file reader, so re-chunking behavior is identical).

The fused topology itself (extract ⇒ sort-ingest overlapped, sort-merge as
the natural barrier, group ⇒ simplex ⇒ filter as one streaming segment)
lives in ``cli.cmd_pipeline``; this module is deliberately topology-free.
"""

import logging
import struct
import threading
import time
from collections import deque

import numpy as np

log = logging.getLogger("fgumi_tpu")

#: Default per-channel byte budget. Two wire chunks of the default 16 MiB
#: batch target fit with headroom; FGUMI_TPU_CHAIN_BYTES overrides.
DEFAULT_CHANNEL_BYTES = 64 << 20


class ChainAborted(RuntimeError):
    """Control-flow signal inside a fused chain: the stage at the *other*
    end of a channel failed (or the driver cancelled the run), so this
    stage should unwind quietly — it is a cascade victim, not the root
    cause. Stage runners catch this and report "aborted" instead of an
    error of their own."""


def channel_bytes_budget() -> int:
    """Per-channel byte budget: FGUMI_TPU_CHAIN_BYTES or the default."""
    import os

    raw = os.environ.get("FGUMI_TPU_CHAIN_BYTES", "")
    if not raw.strip():
        return DEFAULT_CHANNEL_BYTES
    try:
        n = int(raw)
        if n <= 0:
            raise ValueError
        return n
    except ValueError:
        log.warning("FGUMI_TPU_CHAIN_BYTES=%s: not a positive integer; "
                    "using default %d", raw, DEFAULT_CHANNEL_BYTES)
        return DEFAULT_CHANNEL_BYTES


class ChainChannel:
    """Bounded in-memory handoff between two pipeline stages.

    Carries a header (published once by the producer, awaited by the
    consumer) followed by a stream of wire-chunk blobs (``bytes``,
    ``bytearray`` or uint8 ``ndarray``). Producers block while admitting
    another blob would exceed the byte budget — except that one blob is
    always admitted, so an oversized chunk degrades to serial flow instead
    of deadlocking (the same discipline as ``pipeline._ByteBudget``).

    Failure propagation is bidirectional: :meth:`abort` (producer died)
    makes every consumer call raise :class:`ChainAborted`; :meth:`cancel`
    (consumer died) makes every producer call raise it. Both are
    idempotent and keep the first reason.

    Every :meth:`put` passes through the ``chain.handoff`` fault point
    (kinds ``raise``/``oom``/``hang``/``corrupt-bytes``), so chaos tests can
    prove a mid-chain failure exits 3, commits no final output, and leaves
    no temp files behind.
    """

    def __init__(self, name: str, max_bytes: int = None):
        from .utils.governor import GOVERNOR, DynamicBudget

        self.name = name
        # the channel keeps its own byte accounting under its own condition
        # (header + blobs + cancel state share it); the DynamicBudget is
        # the governed *limit* holder. An explicit max_bytes (tests, tools)
        # stays static; the default budget registers with the process-wide
        # governor so a contended channel can borrow bytes from idle ones.
        if max_bytes is None:
            self._budget = DynamicBudget(f"chain.{name}",
                                         channel_bytes_budget())
            self._gov_token = GOVERNOR.register_budget(
                self._budget, demand_fn=self._demand)
        else:
            self._budget = DynamicBudget(f"chain.{name}", int(max_bytes),
                                         damp_s=0.0)
            self._gov_token = None
        # a grown budget must release producers already blocked on it
        self._budget.on_resize = self._notify_waiters
        self._cv = threading.Condition()
        self._header = None
        self._have_header = False
        self._blobs = deque()  # FIFO
        self._bytes = 0
        self._closed = False
        self._cancelled = False
        self._abort_reason = None
        # counters folded into METRICS once by fold_metrics()
        self.n_blobs = 0
        self.total_bytes = 0
        self.peak_bytes = 0
        self.put_wait_s = 0.0
        self.get_wait_s = 0.0
        self._metrics_folded = False
        from .utils import faults

        self._fault_armed = faults.armed("chain.handoff")
        from .observe import trace as _trace

        self._trace_on = _trace.tracing_enabled()

    @property
    def max_bytes(self) -> int:
        """The current (possibly governor-adjusted) byte budget."""
        return self._budget.limit

    def _demand(self) -> dict:
        """Live wait counters for the governor's rebalance tick: put_wait
        growing = producer starved on this budget; get_wait growing =
        consumer starved (budget irrelevant — a donor)."""
        return {"put_wait_s": self.put_wait_s,
                "get_wait_s": self.get_wait_s,
                "used": self._bytes}

    def _notify_waiters(self):
        with self._cv:
            self._cv.notify_all()

    def _ungovern(self):
        from .utils.governor import GOVERNOR

        GOVERNOR.unregister_budget(self._gov_token)
        self._gov_token = None

    # ------------------------------------------------------------- producer

    def put_header(self, header) -> None:
        """Publish the stream header (a ``BamHeader``), exactly as a file
        round trip would deliver it (see ``io.bam.header_roundtrip``)."""
        from .io.bam import header_roundtrip

        hdr = header_roundtrip(header)
        with self._cv:
            if self._cancelled or self._abort_reason is not None:
                raise ChainAborted(self._reason_locked())
            self._header = hdr
            self._have_header = True
            self._cv.notify_all()

    def put(self, blob) -> None:
        """Hand one wire-chunk blob to the consumer (blocks on the byte
        budget; ownership transfers — the producer must not reuse a
        mutable blob after putting it)."""
        if self._fault_armed:
            from .utils import faults

            blob = faults.fire("chain.handoff", blob)
            if blob is None:
                return
        n = len(blob)
        if n == 0:
            # an empty blob carries nothing, and the consumer's assembler
            # treats an empty chunk as end-of-stream — never enqueue one
            return
        if self._trace_on:
            from .observe.trace import span

            with span("chain.put", channel=self.name, bytes=n):
                self._put(blob, n)
        else:
            self._put(blob, n)

    def _put(self, blob, n: int) -> None:
        from .utils.governor import GOVERNOR

        t0 = time.monotonic()
        with self._cv:
            while (self._bytes > 0 and self._bytes + n > self.max_bytes
                   and not self._cancelled
                   and self._abort_reason is None):
                # hard pressure fails the producing stage cleanly (exit 4,
                # chain abort cascade) instead of queueing into an OOM
                GOVERNOR.check_hard()
                self._cv.wait(0.1)
            if self._cancelled or self._abort_reason is not None:
                raise ChainAborted(self._reason_locked())
            if self._closed:
                raise RuntimeError(
                    f"chain channel {self.name}: put after close")
            self._blobs.append(blob)
            self._bytes += n
            self.n_blobs += 1
            self.total_bytes += n
            self.peak_bytes = max(self.peak_bytes, self._bytes)
            wait = time.monotonic() - t0
            self.put_wait_s += wait
            self._cv.notify_all()
        from .observe.metrics import METRICS

        METRICS.observe("pipeline.chain.put_wait_s", wait)

    def close(self) -> None:
        """Producer EOF: the consumer drains remaining blobs, then sees end
        of stream. Idempotent."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._ungovern()  # no more puts: stop competing for the cap

    def abort(self, reason: str) -> None:
        """Producer-side failure: every pending and future consumer call
        raises :class:`ChainAborted`. Idempotent (first reason wins)."""
        with self._cv:
            if self._abort_reason is None:
                self._abort_reason = reason
            self._closed = True
            self._blobs.clear()
            self._bytes = 0
            self._cv.notify_all()
        self._ungovern()

    @property
    def has_header(self) -> bool:
        """True once the producer has published the stream header (a
        non-blocking peek — the fused driver's heartbeat gauge uses it to
        tell a stage that is actually consuming from one still parked in
        its ``header`` wait)."""
        with self._cv:
            return self._have_header

    # ------------------------------------------------------------- consumer

    @property
    def header(self):
        """The stream's ``BamHeader`` (blocks until the producer publishes;
        raises :class:`ChainAborted` if it never will)."""
        with self._cv:
            while not self._have_header:
                if self._abort_reason is not None or self._cancelled:
                    raise ChainAborted(self._reason_locked())
                if self._closed:
                    raise ChainAborted(
                        f"chain channel {self.name}: closed with no header")
                self._cv.wait(0.1)
            return self._header

    def get(self):
        """Next blob, or None at end of stream."""
        from .observe.metrics import METRICS

        t0 = time.monotonic()
        with self._cv:
            while True:
                if self._abort_reason is not None:
                    raise ChainAborted(self._reason_locked())
                if self._cancelled:
                    raise ChainAborted(self._reason_locked())
                if self._blobs:
                    blob = self._blobs.popleft()
                    self._bytes -= len(blob)
                    wait = time.monotonic() - t0
                    self.get_wait_s += wait
                    self._cv.notify_all()
                    break
                if self._closed:
                    self.get_wait_s += time.monotonic() - t0
                    return None
                self._cv.wait(0.1)
        # observe outside the channel lock (same discipline as put): the
        # registry lock must not extend this CV's critical section
        METRICS.observe("pipeline.chain.get_wait_s", wait)
        return blob

    def cancel(self) -> None:
        """Consumer-side failure / early exit: every blocked or future
        producer call raises :class:`ChainAborted`; buffered blobs are
        dropped. Idempotent."""
        with self._cv:
            self._cancelled = True
            self._blobs.clear()
            self._bytes = 0
            self._cv.notify_all()
        self._ungovern()

    def _reason_locked(self) -> str:
        if self._abort_reason is not None:
            return self._abort_reason
        return f"chain channel {self.name}: consumer cancelled"

    # -------------------------------------------------------------- metrics

    def fold_metrics(self) -> None:
        """Fold this channel's counters into METRICS under
        ``pipeline.chain.<name>.*`` (once; the driver calls this in its
        finally so failed runs still report)."""
        if self._metrics_folded:
            return
        self._metrics_folded = True
        self._ungovern()
        from .observe.metrics import METRICS

        p = f"pipeline.chain.{self.name}"
        METRICS.inc(f"{p}.batches", self.n_blobs)
        METRICS.inc(f"{p}.bytes", self.total_bytes)
        METRICS.max(f"{p}.peak_bytes", self.peak_bytes)
        METRICS.inc(f"{p}.put_wait_s", round(self.put_wait_s, 6))
        METRICS.inc(f"{p}.get_wait_s", round(self.get_wait_s, 6))
        # final (possibly governor-adjusted) budget + resize counters, so
        # a run report shows where the rebalancer moved bytes
        METRICS.set(f"{p}.budget_limit", self.max_bytes)
        if self._budget.grows or self._budget.shrinks:
            METRICS.inc(f"{p}.budget_grows", self._budget.grows)
            METRICS.inc(f"{p}.budget_shrinks", self._budget.shrinks)


class ChannelBamWriter:
    """``BamWriter``-compatible sink writing wire chunks into a channel.

    Small writes coalesce into ~``chunk_bytes`` blobs (one channel handoff
    per chunk, not per record); blobs already at or above the chunk size
    pass through with no copy after the pending buffer flushes, so a
    producer that hands over large wire chunks (the native serializers, the
    sort merge) pays zero re-buffering.
    """

    def __init__(self, channel: ChainChannel, header,
                 chunk_bytes: int = 1 << 20):
        self._chan = channel
        self._chunk_bytes = int(chunk_bytes)
        self._buf = bytearray()
        self._closed = False
        channel.put_header(header)

    def write_record_bytes(self, data: bytes) -> None:
        self._buf += struct.pack("<I", len(data))
        self._buf += data
        if len(self._buf) >= self._chunk_bytes:
            self._flush()

    def write_record(self, rec) -> None:
        self.write_record_bytes(rec.data)

    def write_serialized(self, blob) -> None:
        """Append records already carrying their block_size prefixes."""
        if len(blob) >= self._chunk_bytes:
            self._flush()
            self._chan.put(blob)
            return
        self._buf += memoryview(blob)
        if len(self._buf) >= self._chunk_bytes:
            self._flush()

    def _flush(self) -> None:
        if self._buf:
            # hand over a fresh buffer (the channel owns it from here); a
            # bytearray, not bytes, so the consumer can wrap it writable
            # without a second copy
            self._chan.put(bytearray(self._buf))
            self._buf.clear()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._flush()
        self._chan.close()

    def discard(self) -> None:
        """Abandon the stream (error path): the consumer sees an abort, not
        a truncated-looking EOF."""
        if self._closed:
            return
        self._closed = True
        self._buf.clear()
        self._chan.abort(
            f"chain channel {self._chan.name}: producer discarded output")

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.close()
        else:
            self.discard()


class ChannelBatchReader:
    """``BamBatchReader``-compatible source decoding channel blobs into
    :class:`~fgumi_tpu.io.batch_reader.RecordBatch` objects.

    Re-chunks the producer's blob stream to ``target_bytes`` batches with
    the same accumulate → boundary-scan → tail-carry assembler the file
    reader uses (``io.batch_reader._BatchAssembler``), so a fused stage
    sees batches shaped like the file-backed run's. The single-blob case
    wraps the producer's buffer directly — no extra copy (the microbench
    ``chain_rechunk`` entry pins this). With ``writable=True`` (the safe
    default) read-only blobs (plain ``bytes``) are copied once, because
    ``RecordBatch.buf`` must be mutable for in-place edits like simplex's
    overlap correction or filter's native base masking; a consumer known
    to only *read* its batches (sort ingest, group) passes
    ``writable=False`` and skips that copy. The read-only flag is a
    guard against *numpy-level* writes only — native calls that take the
    raw pointer bypass it — so opt out strictly for consumers whose whole
    path is known read-only.
    """

    def __init__(self, channel: ChainChannel, target_bytes: int = 16 << 20,
                 writable: bool = True):
        from .io.batch_reader import _BatchAssembler

        self._chan = channel
        self._writable = writable
        self._asm = _BatchAssembler(self._read_chunk, target_bytes)
        self._exhausted = False

    @property
    def header(self):
        return self._chan.header

    def _read_chunk(self) -> np.ndarray:
        blob = self._chan.get()
        if blob is None:
            self._exhausted = True
            return np.empty(0, dtype=np.uint8)
        if isinstance(blob, np.ndarray):
            return blob
        arr = np.frombuffer(blob, dtype=np.uint8)
        if self._writable and not arr.flags.writeable:
            # this consumer mutates batches in place (overlap correction);
            # an immutable handoff pays one counted copy here
            arr = arr.copy()
            from .observe.metrics import METRICS

            METRICS.inc(f"pipeline.chain.{self._chan.name}.copies")
        return arr

    def __iter__(self):
        return iter(self._asm)

    def close(self) -> None:
        if not self._exhausted:
            # early exit (stage failed downstream of this reader): release
            # a producer blocked on the byte budget
            self._chan.cancel()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
