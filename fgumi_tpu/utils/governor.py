"""Process-wide resource governor: dynamic budgets + pressure sentinels.

Every byte budget in the host pipeline used to be static — ``run_stages``
held ``--max-memory``'s split forever, each fused-chain channel got a flat
64 MiB, the device feeder a flat 256 MiB — and the only adaptive mechanism
was the stall watchdog's blind ``widen()`` nudge. The reference rebalances
instead: its ``DynamicRebalancer`` (unified_pipeline/rebalancer.rs:20-66)
samples per-queue demand and shifts budget from idle queues to contended
ones under one global cap. This module is that analog, plus the pressure
half a production system needs: RSS and disk-free watermarks that degrade
the run *predictably* (soft → shrink budgets, spill earlier, shed serve
admission) or fail it *cleanly* (hard → :class:`ResourceExhausted`, the
exit-code contract, atomic temps swept, a ``resource`` section in the run
report) instead of dying on a raw ``OSError`` mid-merge.

Two halves:

- :class:`DynamicBudget` — the byte-budget primitive shared by
  ``pipeline.run_stages``, ``pipeline_chain.ChainChannel`` and the
  ``DeviceFeeder``: acquire/release accounting with the one-item-always-
  admits discipline, plus damped grow/shrink with floor/ceiling clamps and
  direction hysteresis so rebalancing cannot oscillate.
- :class:`ResourceGovernor` — the process-wide singleton
  (:data:`GOVERNOR`): components register budgets (with a demand callback
  reporting producer/consumer wait time) and watch paths (spill dir,
  output dir); a periodic thread samples demand and pressure, shifts
  budget toward starved producers under the global cap
  (``FGUMI_TPU_MEM_BUDGET``, default from detected available RAM), and
  drives the soft/hard watermark state machine.

Budgets change *when* bytes move, never *what* bytes are written: a
governed run's output is byte-identical to an ungoverned one
(``FGUMI_TPU_GOVERNOR=0``) by construction — the acceptance test pins it.

Knobs (docs/performance-tuning.md):

- ``FGUMI_TPU_GOVERNOR=0`` — escape hatch: no thread, budgets stay static.
- ``FGUMI_TPU_MEM_BUDGET`` — global cap (human size; default: detected
  available memory minus a reserve, ``utils.memory.auto_budget``).
- ``FGUMI_TPU_GOVERNOR_PERIOD_S`` — sample period (default 0.5).
- ``FGUMI_TPU_RSS_SOFT`` / ``FGUMI_TPU_RSS_HARD`` — RSS watermarks
  (human sizes; defaults 85% / 95% of the detected memory total).
- ``FGUMI_TPU_DISK_SOFT`` / ``FGUMI_TPU_DISK_HARD`` — free-space
  watermarks for watched paths (defaults 512 MiB / 64 MiB).
- ``FGUMI_TPU_MERGE_PREFETCH`` — phase-2 merge prefetch budget
  (default 64 MiB; 0 disables; forced to 0 under soft pressure).
"""

import errno as _errno
import logging
import os
import threading
import time

log = logging.getLogger("fgumi_tpu")

#: default floor for a governed budget (a budget shrunk below this stops
#: being a pipeline and starts being a serializer)
_DEFAULT_FLOOR = 4 << 20

_MB = 1 << 20


class ResourceExhausted(RuntimeError):
    """A resource hard limit was hit (disk full, RSS hard watermark).

    The *clean-failure* signal of the resource contract: commands map it
    to exit code 4 with a one-line diagnostic, atomic temps are swept by
    the ordinary error paths, and the run report carries a ``resource``
    section describing the event. ``kind`` is the event kind recorded
    with the governor (``enospc``, ``rss_hard``, ``disk_hard``)."""

    def __init__(self, message: str, kind: str = "resource"):
        super().__init__(message)
        self.kind = kind


class StopSignal(threading.Event):
    """A stop event that can wake condition-variable waiters immediately.

    ``DynamicBudget.acquire`` used to poll its condition every 100 ms to
    notice cancellation; subscribing the budget's condition here turns
    ``set()`` into an instant wakeup instead (the reader thread of a
    failed pipeline exits now, not at the next poll tick)."""

    def __init__(self):
        super().__init__()
        self._subs_lock = threading.Lock()
        self._subs = []

    def subscribe(self, cv: threading.Condition):
        with self._subs_lock:
            self._subs.append(cv)

    def unsubscribe(self, cv: threading.Condition):
        with self._subs_lock:
            try:
                self._subs.remove(cv)
            except ValueError:
                pass

    def set(self):  # noqa: A003 - threading.Event API
        super().set()
        with self._subs_lock:
            subs = list(self._subs)
        for cv in subs:
            with cv:
                cv.notify_all()


class DynamicBudget:
    """Bytes-in-flight budget with damped, hysteretic resizing.

    The acquire/release contract is ``pipeline._ByteBudget``'s: producers
    block while admitting another item would exceed the limit, except that
    one item is always admitted (an oversized batch degrades to serial
    flow instead of deadlocking); ``limit <= 0`` disables accounting.

    Resizing (the governor's lever) is damped so the rebalancer cannot
    oscillate: at most one resize per ``damp_s`` window, a direction
    *flip* (grow after shrink or vice versa) needs ``4 * damp_s`` of
    quiet, and every resize clamps to ``[floor, ceiling]``. The watchdog's
    deadlock-breaking :meth:`widen` bypasses damping (a wedged pipeline
    cannot wait out a cooldown) but still respects the ceiling.
    """

    def __init__(self, name: str, limit: int, floor: int = None,
                 ceiling: int = None, damp_s: float = None):
        limit = int(limit)
        self.name = name
        self.limit = limit
        if limit > 0:
            self.floor = int(floor) if floor is not None \
                else min(limit, _DEFAULT_FLOOR)
            self.ceiling = int(ceiling) if ceiling is not None \
                else limit * 8
        else:
            self.floor = 0
            self.ceiling = 0
        self.used = 0
        self.peak = 0
        self.wait_s = 0.0  # producer time blocked in acquire()
        self.grows = 0
        self.shrinks = 0
        self.flips = 0  # direction reversals (the oscillation gauge)
        self.damp_s = governor_period() if damp_s is None else damp_s
        #: optional callable run (outside the lock) after every applied
        #: resize — channels hook their own condition's notify here so a
        #: grown budget releases blocked producers immediately
        self.on_resize = None
        self._last_resize = 0.0
        self._last_dir = 0
        self._cv = threading.Condition()

    # ------------------------------------------------------- acquire/release

    def acquire(self, n: int, stop=None) -> bool:
        """Charge ``n`` bytes, blocking while the budget is exhausted.

        Returns False (without charging) when ``stop`` is set; a
        :class:`StopSignal` wakes the wait immediately, a plain Event is
        polled. Raises :class:`ResourceExhausted` under a hard pressure
        state — the waiting producer is exactly who must stop producing.
        """
        if self.limit <= 0:
            return True
        sub = getattr(stop, "subscribe", None)
        t0 = time.monotonic()
        waited = False
        observe_dt = None
        try:
            with self._cv:
                if sub is not None:
                    sub(self._cv)
                try:
                    while self.used > 0 and self.used + n > self.limit:
                        if stop is not None and stop.is_set():
                            return False
                        GOVERNOR.check_hard()
                        waited = True
                        self._cv.wait(None if sub is not None else 0.1)
                finally:
                    if sub is not None:
                        stop.unsubscribe(self._cv)
                    if waited:
                        dt = time.monotonic() - t0
                        self.wait_s += dt
                        observe_dt = dt
                self.used += n
                self.peak = max(self.peak, self.used)
                return True
        finally:
            if observe_dt is not None:
                # blocking acquires feed the budget-wait latency histogram
                # (per wait, not cumulative — the run report's p99 answers
                # "how long do producers stall"). Observed OUTSIDE the
                # budget CV: the registry lock must not extend this
                # critical section (same discipline as ChainChannel)
                from ..observe.metrics import METRICS

                METRICS.observe("governor.budget.wait_s", observe_dt)

    def release(self, n: int):
        if self.limit <= 0:
            return
        with self._cv:
            self.used -= n
            self._cv.notify_all()

    # --------------------------------------------------------------- resizing

    def widen(self, factor: int = 2):
        """Deadlock-breaking grow (stall watchdog): undamped, and allowed
        past the rebalancer's ceiling — the static budget it replaced
        widened unconditionally, and a stall-breaker that silently no-ops
        because demand growth already consumed the ceiling is no breaker
        at all (the ceiling is raised to keep the escape permanent)."""
        with self._cv:
            if self.limit <= 0:
                return
            new = self.limit * factor
            if new > self.ceiling:
                log.warning("budget %s: stall widen %d -> %d MiB exceeds "
                            "the rebalance ceiling; raising it", self.name,
                            self.limit // _MB, new // _MB)
                self.ceiling = new
        # outside the lock: _resize runs the on_resize hook, which takes
        # the owning component's condition
        self._resize(new, +1, force=True)

    def grow(self, add: int) -> int:
        """Damped grow by ``add`` bytes; returns bytes actually granted."""
        before = self.limit
        self._resize(self.limit + int(add), +1)
        return self.limit - before

    def shrink(self, factor: float = 0.5) -> int:
        """Damped shrink toward the floor; returns bytes actually freed."""
        before = self.limit
        self._resize(int(self.limit * factor), -1)
        return before - self.limit

    def _resize(self, new_limit: int, direction: int, force: bool = False):
        cb = None
        with self._cv:
            if self.limit <= 0:
                return
            now = time.monotonic()
            if not force:
                if now - self._last_resize < self.damp_s:
                    return  # damped: one resize per window
                if self._last_dir and direction != self._last_dir \
                        and now - self._last_resize < 4 * self.damp_s:
                    return  # hysteresis: no quick direction flip
            new_limit = max(self.floor, min(int(new_limit), self.ceiling))
            if new_limit == self.limit:
                return
            if self._last_dir and direction != self._last_dir:
                self.flips += 1
            self._last_dir = direction
            self._last_resize = now
            if new_limit > self.limit:
                self.grows += 1
            else:
                self.shrinks += 1
            self.limit = new_limit
            self._cv.notify_all()
            cb = self.on_resize
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 - a hook must not kill a resize
                log.exception("budget %s: on_resize hook failed", self.name)

    # ---------------------------------------------------------------- metrics

    def snapshot(self) -> dict:
        with self._cv:
            return {"limit": self.limit, "used": self.used,
                    "peak": self.peak, "floor": self.floor,
                    "ceiling": self.ceiling,
                    "wait_s": round(self.wait_s, 6),
                    "grows": self.grows, "shrinks": self.shrinks,
                    "flips": self.flips}


# --------------------------------------------------------------------- config


def _parse_size_env(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if not raw.strip():
        return default
    from .memory import parse_size

    try:
        return parse_size(raw)
    except ValueError:
        log.warning("%s=%s: unparseable size; using default %d", name, raw,
                    default)
        return default


def governor_enabled() -> bool:
    """False only under the FGUMI_TPU_GOVERNOR=0 escape hatch."""
    return os.environ.get("FGUMI_TPU_GOVERNOR", "").strip() != "0"


def governor_period() -> float:
    try:
        return max(float(os.environ.get("FGUMI_TPU_GOVERNOR_PERIOD_S",
                                        "0.5")), 0.05)
    except ValueError:
        return 0.5


def mem_budget() -> int:
    """The global process cap every governed budget shares
    (``FGUMI_TPU_MEM_BUDGET``, default detected-available minus reserve)."""
    from .memory import auto_budget

    return _parse_size_env("FGUMI_TPU_MEM_BUDGET", auto_budget())


def _mem_total():
    """Detected memory ceiling: cgroup limit when containerized, else
    MemTotal."""
    from .memory import _cgroup_limit

    limit = _cgroup_limit()
    if limit:
        return limit
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) << 10
    except (OSError, ValueError, IndexError):
        pass
    return None


def _read_rss():
    """Resident set size in bytes, or None."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    return int(line.split()[1]) << 10
    except (OSError, ValueError, IndexError):
        pass
    return None


def _disk_free(path: str):
    """Free bytes on the filesystem holding ``path``, or None."""
    try:
        st = os.statvfs(path)
    except OSError:
        return None
    return st.f_bavail * st.f_frsize


def merge_prefetch_bytes() -> int:
    """Byte budget for phase-2 merge frame prefetch (sort/external.py):
    ``FGUMI_TPU_MERGE_PREFETCH`` (0 disables), default 64 MiB, forced to 0
    while the governor reports memory/disk pressure."""
    n = _parse_size_env("FGUMI_TPU_MERGE_PREFETCH", 64 << 20)
    if n > 0 and GOVERNOR.soft_pressure():
        return 0
    return n


# ------------------------------------------------------------------ governor


class _Entry:
    __slots__ = ("budget", "demand_fn", "last_put", "last_get")

    def __init__(self, budget, demand_fn):
        self.budget = budget
        self.demand_fn = demand_fn
        self.last_put = 0.0
        self.last_get = 0.0


#: producer wait growth per tick that marks a queue contended / idle
_HOT_WAIT_S = 0.02
_COLD_WAIT_S = 0.001

#: bounded event history carried into the run report
_MAX_EVENTS = 50


class ResourceGovernor:
    """The process-wide budget rebalancer + pressure sentinel.

    Passive until :meth:`maybe_start` (called at every top-level CLI
    command and by the serve daemon): registration alone never starts the
    thread, so library users and unit tests keep fully static budgets
    unless they opt in. ``sample_once()`` is the whole per-tick body and
    is what tests drive directly (with injected ``rss_fn``/``disk_fn``
    samplers) for determinism.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._entries = {}
        self._watch = {}
        self._next_token = 0
        self._thread = None
        self._stop = threading.Event()
        # pressure state: a plain attribute so hot paths can read it
        # without a lock (torn reads are impossible for a str ref)
        self.state = "ok"  # ok | soft | hard
        self.hard_reason = None
        self._soft_reason = None
        self.rss_peak = 0
        self.disk_free_min = None
        self.samples = 0
        self.rebalances = 0
        self.shed_count = 0
        self._events = []
        # injectable samplers (tests): () -> bytes | None
        self._rss_fn = _read_rss
        self._disk_fn = _disk_free

    # ------------------------------------------------------------ registration

    def register_budget(self, budget: DynamicBudget, demand_fn=None) -> int:
        """Put ``budget`` under governance. ``demand_fn()`` (optional)
        returns ``{"put_wait_s": float, "get_wait_s": float}`` — cumulative
        producer/consumer wait seconds; budgets without one are exempt from
        demand rebalancing but still shrink under soft pressure. Returns an
        unregister token."""
        with self._lock:
            self._next_token += 1
            token = self._next_token
            self._entries[token] = _Entry(budget, demand_fn)
            return token

    def unregister_budget(self, token):
        if token is None:
            return
        with self._lock:
            self._entries.pop(token, None)

    def watch_path(self, label: str, path: str) -> int:
        """Watch the filesystem holding ``path`` (spill dir, output dir)
        against the disk-free watermarks. Returns an unwatch token."""
        with self._lock:
            self._next_token += 1
            token = self._next_token
            self._watch[token] = (label, path)
            return token

    def unwatch_path(self, token):
        if token is None:
            return
        with self._lock:
            self._watch.pop(token, None)

    # ---------------------------------------------------------------- lifecycle

    def maybe_start(self):
        """Start the sampling thread (idempotent; no-op when disabled)."""
        if not governor_enabled():
            return
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop = threading.Event()
            # a plain daemon thread on purpose (no telemetry-scope copy):
            # the governor serves every job in the process, so binding it
            # to whichever command started it would misattribute metrics
            self._thread = threading.Thread(target=self._loop,
                                            name="fgumi-governor",
                                            daemon=True)
            self._thread.start()

    def stop(self):
        with self._lock:
            thread = self._thread
            self._thread = None
        self._stop.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout=5)

    def _loop(self):
        while not self._stop.wait(governor_period()):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 - the sentinel must survive
                log.exception("resource governor sample failed")

    # ------------------------------------------------------------------ events

    def record_event(self, kind: str, **info):
        """Append one resource event (ENOSPC conversion, watermark
        transitions) for the run report's ``resource`` section."""
        ev = {"kind": kind, "t": round(time.time(), 3), **info}
        with self._lock:
            self._events.append(ev)
            del self._events[:-_MAX_EVENTS]
        # resource events (pressure transitions, ENOSPC conversions) are
        # exactly the state changes a post-mortem black box needs
        from ..observe.flight import FLIGHT

        FLIGHT.note("governor." + kind, **info)

    # ---------------------------------------------------------------- pressure

    def check_hard(self):
        """Raise :class:`ResourceExhausted` when the hard watermark is
        breached (called from budget waits / channel puts / sorter adds —
        the spots where stopping is clean)."""
        if self.state == "hard":
            raise ResourceExhausted(
                f"resource hard limit: {self.hard_reason}",
                kind="hard_watermark")

    def soft_pressure(self) -> bool:
        return self.state != "ok"

    def admission_pressure(self):
        """None when admission is fine; else a shed record
        ``{"reason", "retry_after_s"}`` for the serve daemon (the
        ``resource_pressure`` rejection + Retry-After-style hint)."""
        if self.state == "ok":
            return None
        with self._lock:
            self.shed_count += 1
            reason = (self.hard_reason if self.state == "hard"
                      else self._soft_reason) or "resource pressure"
        return {"reason": reason,
                "retry_after_s": 30.0 if self.state == "hard" else 5.0}

    def _sample_pressure(self):
        rss = self._rss_fn()
        soft = hard = None
        if rss is not None:
            self.rss_peak = max(self.rss_peak, rss)
            total = _mem_total()
            rss_soft = _parse_size_env(
                "FGUMI_TPU_RSS_SOFT",
                int(total * 0.85) if total else 1 << 62)
            rss_hard = _parse_size_env(
                "FGUMI_TPU_RSS_HARD",
                int(total * 0.95) if total else 1 << 62)
            if rss >= rss_hard:
                hard = (f"rss {rss // _MB} MiB >= hard watermark "
                        f"{rss_hard // _MB} MiB")
            elif rss >= rss_soft:
                soft = (f"rss {rss // _MB} MiB >= soft watermark "
                        f"{rss_soft // _MB} MiB")
        disk_soft = _parse_size_env("FGUMI_TPU_DISK_SOFT", 512 << 20)
        disk_hard = _parse_size_env("FGUMI_TPU_DISK_HARD", 64 << 20)
        with self._lock:
            watched = list(self._watch.values())
        for label, path in watched:
            free = self._disk_fn(path)
            if free is None:
                continue
            if self.disk_free_min is None or free < self.disk_free_min:
                self.disk_free_min = free
            if free <= disk_hard:
                hard = (f"{label} ({path}): {free // _MB} MiB free <= hard "
                        f"watermark {disk_hard // _MB} MiB")
            elif free <= disk_soft and soft is None:
                soft = (f"{label} ({path}): {free // _MB} MiB free <= soft "
                        f"watermark {disk_soft // _MB} MiB")
        new_state = "hard" if hard else ("soft" if soft else "ok")
        if new_state != self.state:
            self.record_event(f"pressure_{new_state}",
                              reason=hard or soft or "cleared")
            if new_state == "ok":
                log.info("resource pressure cleared")
            else:
                log.warning("resource pressure %s: %s", new_state,
                            hard or soft)
        self.hard_reason = hard
        self._soft_reason = soft
        self.state = new_state
        if new_state != "ok":
            # degrade: walk every governed budget toward its floor (damped
            # inside the budget, so this is one gentle step per tick) and
            # wake any blocked producer so it re-checks the hard state
            with self._lock:
                budgets = [e.budget for e in self._entries.values()]
            for b in budgets:
                b.shrink(0.5)
                if new_state == "hard":
                    with b._cv:
                        b._cv.notify_all()

    # --------------------------------------------------------------- rebalance

    def sample_once(self):
        """One governor tick: chaos point, pressure sentinels, demand
        rebalance. Exactly what the thread runs; tests call it directly."""
        from . import faults

        faults.fire("governor.sample")
        self.samples += 1
        self._sample_pressure()
        if self.state == "ok":
            self._rebalance()

    def _rebalance(self):
        with self._lock:
            entries = list(self._entries.values())
        hot, cold, total = [], [], 0
        for e in entries:
            b = e.budget
            if b.limit <= 0:
                continue
            total += b.limit
            if e.demand_fn is None:
                continue
            try:
                d = e.demand_fn()
            except Exception:  # noqa: BLE001 - a dead gauge never governs
                continue
            dput = float(d.get("put_wait_s", 0.0)) - e.last_put
            dget = float(d.get("get_wait_s", 0.0)) - e.last_get
            e.last_put += dput
            e.last_get += dget
            if dput > _HOT_WAIT_S:
                hot.append((dput, e))
            elif dput <= _COLD_WAIT_S:
                cold.append((dget, e))
        if not hot:
            return
        cap = mem_budget()
        hot.sort(key=lambda pair: pair[0], reverse=True)
        # donors: idle-producer queues — a starved CONSUMER (get_wait
        # growing) is positive evidence the queue runs empty and its budget
        # is over-provisioned, so the most consumer-starved donate first;
        # headroom above the floor breaks ties
        cold.sort(key=lambda pair: (pair[0],
                                    pair[1].budget.limit
                                    - pair[1].budget.floor),
                  reverse=True)
        for dput, e in hot:
            b = e.budget
            want = min(max(b.limit // 2, _MB), b.ceiling - b.limit)
            if want <= 0:
                continue
            for _, c in cold:
                if cap - total >= want:
                    break
                total -= c.budget.shrink(0.5)
            grant = min(want, cap - total)
            if grant <= 0:
                continue
            granted = b.grow(grant)
            if granted:
                total += granted
                self.rebalances += 1
                log.debug("governor: +%d MiB to %s (put_wait +%.3fs, "
                          "limit now %d MiB)", granted // _MB, b.name,
                          dput, b.limit // _MB)

    # ----------------------------------------------------------------- report

    def has_activity(self) -> bool:
        with self._lock:
            return bool(self._events or self.rebalances
                        or self.shed_count or self.state != "ok")

    def snapshot(self) -> dict:
        """JSON-safe state for the run report's ``resource`` section."""
        with self._lock:
            out = {
                "state": self.state,
                "samples": self.samples,
                "rebalances": self.rebalances,
                "shed": self.shed_count,
                "rss_peak_bytes": self.rss_peak,
                "events": list(self._events),
                "budgets": {e.budget.name: e.budget.snapshot()
                            for e in self._entries.values()},
            }
            if self.disk_free_min is not None:
                out["disk_free_min_bytes"] = self.disk_free_min
            if self.hard_reason:
                out["hard_reason"] = self.hard_reason
        return out

    def fold_metrics(self):
        """Fold governor state into METRICS (called at command exit inside
        the command's telemetry scope, like ``fold_device_stats`` — the
        sampling thread itself is scope-less on purpose)."""
        from ..observe.metrics import METRICS

        with self._lock:
            METRICS.set("governor.samples", self.samples)
            METRICS.set("governor.rebalances", self.rebalances)
            METRICS.set("resource.state", self.state)
            if self.rss_peak:
                METRICS.max("resource.rss_peak_bytes", self.rss_peak)
            if self.disk_free_min is not None:
                METRICS.set("resource.disk_free_min_bytes",
                            self.disk_free_min)
            if self.shed_count:
                METRICS.set("serve.shed.resource", self.shed_count)
            kinds = {}
            for ev in self._events:
                kinds[ev["kind"]] = kinds.get(ev["kind"], 0) + 1
            for kind, n in kinds.items():
                METRICS.set(f"resource.event.{kind}", n)
            for e in self._entries.values():
                snap = e.budget.snapshot()
                p = f"governor.budget.{e.budget.name}"
                METRICS.set(f"{p}.limit", snap["limit"])
                METRICS.max(f"{p}.peak", snap["peak"])
                METRICS.set(f"{p}.wait_s", snap["wait_s"])
                METRICS.set(f"{p}.grows", snap["grows"])
                METRICS.set(f"{p}.shrinks", snap["shrinks"])
                METRICS.set(f"{p}.flips", snap["flips"])

    # ------------------------------------------------------------------- tests

    def reset_for_tests(self):
        """Restore pristine pressure/event state (budget registrations are
        their owners' to manage). Tests use this between scenarios."""
        self.stop()
        with self._lock:
            self.state = "ok"
            self.hard_reason = None
            self._soft_reason = None
            self.rss_peak = 0
            self.disk_free_min = None
            self.samples = 0
            self.rebalances = 0
            self.shed_count = 0
            self._events = []
            self._rss_fn = _read_rss
            self._disk_fn = _disk_free


#: The process-wide governor every component registers with.
GOVERNOR = ResourceGovernor()


def reraise_enospc(exc: BaseException, where: str, path: str = None):
    """Convert ``OSError(ENOSPC)`` into the clean-failure contract.

    Records an ``enospc`` resource event and raises
    :class:`ResourceExhausted`; any other exception returns so the caller
    can re-raise the original. Call from ``except`` blocks around disk
    writes (spill runs, BGZF output)::

        except OSError as e:
            reraise_enospc(e, "sort.spill", path=self._tmp_dir)
            raise
    """
    if not isinstance(exc, OSError) or exc.errno != _errno.ENOSPC:
        return
    info = {"where": where}
    if path:
        info["path"] = path
        free = _disk_free(path)
        if free is not None:
            info["free_bytes"] = free
    GOVERNOR.record_event("enospc", **info)
    raise ResourceExhausted(
        f"disk full during {where}"
        + (f" ({path})" if path else "")
        + f": {exc}", kind="enospc") from exc
