"""Memory budget parsing and cgroup-aware detection.

Analog of the reference's --max-memory handling
(/root/reference/src/lib/commands/common.rs:759-993 parse + `auto`, and
src/lib/system.rs:1-26 cgroup-aware totals): accepts plain MiB counts, human
sizes (K/M/G/T, binary), or "auto" = detected available memory minus a
reserve, clamped to a sane floor.
"""

import os
import re

_SIZE = re.compile(r"^(\d+(?:\.\d+)?)\s*([KMGT]i?B?|B)?$", re.IGNORECASE)
_UNIT = {"b": 1, "k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}

_FLOOR = 64 << 20  # never budget below 64 MiB
_DEFAULT_RESERVE = 1 << 30


def parse_size(value: str) -> int:
    """Human size -> bytes. A bare number means MiB (reference convention)."""
    s = str(value).strip()
    m = _SIZE.match(s)
    if not m:
        raise ValueError(f"unparseable size: {value!r}")
    num = float(m.group(1))
    unit = m.group(2)
    if unit is None:
        return int(num * (1 << 20))
    return int(num * _UNIT[unit[0].lower()])


def _cgroup_limit():
    """Container memory limit in bytes, or None (v2 then v1 paths)."""
    for path in ("/sys/fs/cgroup/memory.max",
                 "/sys/fs/cgroup/memory/memory.limit_in_bytes"):
        try:
            with open(path) as f:
                raw = f.read().strip()
        except OSError:
            continue
        if raw == "max":
            return None
        try:
            limit = int(raw)
        except ValueError:
            continue
        if 0 < limit < 1 << 50:  # v1 reports ~2^63 for "unlimited"
            return limit
    return None


def _mem_available():
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) << 10
    except (OSError, ValueError, IndexError):
        pass
    return None


def auto_budget(reserve: int = _DEFAULT_RESERVE) -> int:
    """Detected usable memory minus `reserve` (>= the floor)."""
    candidates = [v for v in (_cgroup_limit(), _mem_available()) if v]
    total = min(candidates) if candidates else 4 << 30
    return max(total - reserve, _FLOOR)


def resolve_budget(value, reserve: int = _DEFAULT_RESERVE) -> int:
    """CLI --max-memory value ("auto" | human size | MiB count) -> bytes."""
    if value is None:
        return auto_budget(reserve)
    if str(value).strip().lower() == "auto":
        return auto_budget(reserve)
    return max(parse_size(value), _FLOOR)
