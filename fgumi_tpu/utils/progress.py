"""Every-N-records progress logging.

Analog of the reference's ProgressTracker
(/root/reference/crates/fgumi-bam-io/src/progress.rs:130): long-running
commands log a heartbeat with cumulative count and rate every `every`
records, so operators can distinguish slow from stuck.
"""

import logging
import time

log = logging.getLogger("fgumi_tpu")


class ProgressTracker:
    def __init__(self, label: str, every: int = 1_000_000,
                 total: int = None):
        self.label = label
        self.every = every
        self.count = 0
        self.total = total
        self._next = every
        self._t0 = time.monotonic()
        self._hb_token = None
        if total:
            # a known workload size arms the heartbeat's ETA column: the
            # goal plus a live record gauge lets the beat print
            # `rate=N/s eta=Ms` even for commands outside run_stages.
            # First tracker wins — a concurrent goal holder (another
            # daemon job) means no ETA here, not a clobbered one. The
            # gauge token rides the goal so the ETA is computed against
            # THIS tracker's counter, never a neighbour's
            from ..observe import heartbeat

            token = heartbeat.register_gauge(
                lambda: {"records": self.count})
            if heartbeat.set_goal(total, self, gauge_token=token):
                self._hb_token = token
            else:
                heartbeat.unregister_gauge(token)

    def add(self, n: int = 1):
        self.count += n
        if self.count >= self._next:
            dt = time.monotonic() - self._t0
            log.info("%s: %d records processed (%.0f/s)", self.label,
                     self.count, self.count / dt if dt else 0)
            while self._next <= self.count:
                self._next += self.every

    def finish(self):
        """Final summary line — always emitted when anything was counted.

        Runs shorter than `every` used to drop the done-line entirely, so a
        short run reported no rate at all; they now log it at debug level
        (long runs keep the info-level line). Totals also fold into the
        metrics registry so the run report carries records-processed counts.
        """
        if self._hb_token is not None:
            from ..observe import heartbeat

            heartbeat.clear_goal(self)
            heartbeat.unregister_gauge(self._hb_token)
            self._hb_token = None
        if self.count <= 0:
            return
        dt = time.monotonic() - self._t0
        level = logging.INFO if self.count >= self.every else logging.DEBUG
        log.log(level, "%s: done, %d records in %.1fs (%.0f/s)", self.label,
                self.count, dt, self.count / dt if dt else 0)
        from ..observe.metrics import METRICS

        METRICS.inc(f"records.{self.label}", self.count)
