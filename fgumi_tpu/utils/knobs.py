"""One diagnostic grammar for every tunable-knob parse error.

The profile-relevant knobs (``FGUMI_TPU_SHAPE_BUCKETS``, ``FGUMI_TPU_MESH``,
``FGUMI_TPU_SP``, the DeploymentProfile fields) are parsed in four different
modules; before ISSUE 20 each invented its own error wording, so the same
class of mistake read differently depending on where it was caught. Every
knob parse error now goes through :func:`knob_error`:

    KNOB=<offending token>: <what is wrong>; expected <accepted grammar>

All of them surface as exit 2 (``cli._run_command`` maps MeshConfigError /
argparse type errors there; the profile loader raises
:class:`ProfileError`, mapped the same way).
"""


def knob_error(knob: str, token, problem: str, grammar: str) -> str:
    """The one true knob-diagnostic format. ``token`` is the offending
    value exactly as the user supplied it (repr'd so whitespace and empty
    strings survive); ``grammar`` states what would have been accepted."""
    return f"{knob}={token!r}: {problem}; expected {grammar}"
