"""Deterministic fault injection for chaos testing.

The pipeline's watchdog, error plumbing, device retry, and crash-safe
output commit all claim to handle specific failure modes; this registry
makes every one of them *provable* by injecting those failures on demand
at named points in the real code paths (the analog of a failpoint
framework: each point is a one-line `faults.fire(...)` call that is a
cheap no-op unless armed).

Arm via the environment::

    FGUMI_TPU_FAULT=point:kind:prob[:count][,point:kind:prob[:count]...]

- ``point``: one of :data:`FAULT_POINTS` (unknown names are a loud
  ``ValueError`` at the first fire — a typo must not silently disarm a
  chaos test).
- ``kind``: ``raise`` (an :class:`InjectedFault`), ``hang`` (sleep for
  ``FGUMI_TPU_FAULT_HANG_S`` seconds, default 30 — what the stall
  watchdog exists to diagnose), ``corrupt-bytes`` (deterministically flip
  bytes in the payload passing through the point), ``corrupt-result``
  (deterministically flip bits spread across the numpy array(s) passing
  through the point — a silently-wrong accelerator answer, the SDC class
  of failure the shadow-audit sentinel exists to catch; arm at
  ``device.fetch``), ``oom`` (an :class:`InjectedOom` whose message
  carries ``RESOURCE_EXHAUSTED``, the XLA out-of-memory status the device
  retry path batch-halves on), or ``enospc`` (an ``OSError(ENOSPC)`` — a
  full disk exactly where a real one would surface; the resource
  clean-failure contract converts it to exit code 4,
  docs/resilience.md).
- ``prob``: trigger probability per fire, drawn from a
  ``random.Random`` seeded by ``FGUMI_TPU_FAULT_SEED`` (default 0) xor
  the point name, so single-threaded runs are exactly reproducible.
- ``count``: optional cap on total triggers (default unlimited). With
  ``prob`` 1.0 this makes multi-threaded runs deterministic too: the
  first ``count`` arrivals trigger, every later one passes.

Faults are re-parsed whenever the env var's value changes, so tests can
arm/disarm between in-process CLI runs without touching this module.
"""

import logging
import os
import random
import threading
import time
import zlib

log = logging.getLogger("fgumi_tpu")

#: Named fault points threaded through the codebase.
FAULT_POINTS = frozenset({
    "reader.decompress",   # BGZF/gzip reader raw-chunk ingest (io/bgzf.py)
    "pipeline.process",    # per-item process stage (pipeline.run_stages)
    "device.dispatch",     # XLA upload+dispatch attempt (ops/kernel.py)
    "device.wedge",        # dispatch entry, fires once per dispatch — arm
                           # kind `hang` (stall via FGUMI_TPU_FAULT_HANG_S)
                           # to simulate a dispatch that never returns; the
                           # deadline/breaker layer must absorb it
    "device.fetch",        # fetched device result arrays at resolve time
                           # (ops/kernel.py) — arm kind `corrupt-result`
                           # (usually with count 1, like device.wedge) to
                           # simulate a chip silently returning the wrong
                           # answer; the shadow-audit sentinel
                           # (ops/sentinel.py) must catch it
    "writer.compress",     # BGZF writer block emit (io/bgzf.py)
    "native.batch",        # native batch-op entry (native/batch.py)
    "serve.dispatch",      # job-service worker dispatch (serve/daemon.py)
    "serve.coalesce",      # merged cross-job device dispatch
                           # (ops/coalesce.py) — fires on the feeder
                           # thread inside every coalesced launch; arm
                           # `raise` (or `hang`) to prove a fault inside a
                           # merged dispatch degrades only its partners to
                           # the host engine, byte-identically
    "chain.handoff",       # fused-pipeline channel put (pipeline_chain.py)
    "sort.spill",          # external-sort spill-run write (sort/external.py)
                           # — arm kind `enospc` to simulate a disk filling
                           # mid-spill; the clean-failure contract (exit 4,
                           # temps swept, `resource` report section) must
                           # absorb it
    "governor.sample",     # resource-governor sampling tick
                           # (utils/governor.py)
})

KINDS = frozenset({"raise", "hang", "corrupt-bytes", "corrupt-result",
                   "oom", "enospc"})


class InjectedFault(RuntimeError):
    """A fault raised on purpose by the injection registry."""


class InjectedOom(InjectedFault):
    """Injected out-of-memory; message carries RESOURCE_EXHAUSTED so the
    device retry path classifies it exactly like a real XLA OOM."""


class _Fault:
    __slots__ = ("point", "kind", "prob", "remaining", "rng", "fired")

    def __init__(self, point, kind, prob, count, seed):
        self.point = point
        self.kind = kind
        self.prob = prob
        self.remaining = count  # -1 = unlimited
        self.fired = 0
        # per-point stream: arming two points never couples their coins.
        # crc32, not hash(): str hash is salted per process (PYTHONHASHSEED)
        # and the whole contract here is cross-process reproducibility.
        self.rng = random.Random((seed << 32) ^ zlib.crc32(point.encode()))


_lock = threading.Lock()
_env_cache = None  # last-parsed value of FGUMI_TPU_FAULT
_armed = {}  # point -> _Fault


def _parse(env: str) -> dict:
    seed = int(os.environ.get("FGUMI_TPU_FAULT_SEED", "0"))
    armed = {}
    for spec in env.split(","):
        spec = spec.strip()
        if not spec:
            continue
        parts = spec.split(":")
        if len(parts) not in (3, 4):
            raise ValueError(
                f"FGUMI_TPU_FAULT spec {spec!r}: expected "
                "point:kind:prob[:count]")
        point, kind, prob = parts[0], parts[1], float(parts[2])
        count = int(parts[3]) if len(parts) == 4 else -1
        if point not in FAULT_POINTS:
            raise ValueError(
                f"FGUMI_TPU_FAULT: unknown fault point {point!r} "
                f"(known: {', '.join(sorted(FAULT_POINTS))})")
        if kind not in KINDS:
            raise ValueError(
                f"FGUMI_TPU_FAULT: unknown kind {kind!r} "
                f"(known: {', '.join(sorted(KINDS))})")
        armed[point] = _Fault(point, kind, prob, count, seed)
        log.warning("fault injection armed: %s kind=%s prob=%g count=%s",
                    point, kind, prob, count if count >= 0 else "inf")
    return armed


def _refresh_locked():
    global _env_cache, _armed
    env = os.environ.get("FGUMI_TPU_FAULT", "")
    if env == _env_cache:
        return
    _env_cache = env
    _armed = _parse(env) if env else {}


def reset():
    """Drop parsed state so the next fire() re-reads the environment (and
    trigger budgets restart). Tests use this between in-process runs that
    reuse an identical FGUMI_TPU_FAULT value."""
    global _env_cache
    with _lock:
        _env_cache = None
        _armed.clear()


def armed(point: str) -> bool:
    """True when `point` has an armed fault with trigger budget left."""
    with _lock:
        _refresh_locked()
        f = _armed.get(point)
        return f is not None and f.remaining != 0


def fire(point: str, data=None):
    """Trigger the fault armed at `point`, if any.

    Returns `data` (possibly corrupted for kind ``corrupt-bytes``); raises
    for kinds ``raise``/``oom``; sleeps for kind ``hang``. A cheap no-op
    (one env read + dict lookup) when nothing is armed.
    """
    with _lock:
        _refresh_locked()
        f = _armed.get(point)
        if f is None or f.remaining == 0:
            return data
        if f.prob < 1.0 and f.rng.random() >= f.prob:
            return data
        if f.remaining > 0:
            f.remaining -= 1
        f.fired += 1
        kind = f.kind
        if kind == "corrupt-bytes":
            if data is None:
                return None
            out = _corrupt(f.rng, data)
            log.warning("fault injection: corrupted %d bytes at %s",
                        len(out), point)
            return out
        if kind == "corrupt-result":
            if data is None:
                return None
            out = _corrupt_result(data)
            log.warning("fault injection: bit-flipped result arrays at %s",
                        point)
            return out
    # act outside the lock: a hang must not wedge every other fire()
    if kind == "raise":
        log.warning("fault injection: raising at %s", point)
        raise InjectedFault(f"injected fault at {point}")
    if kind == "oom":
        log.warning("fault injection: injected OOM at %s", point)
        raise InjectedOom(
            f"RESOURCE_EXHAUSTED: injected out-of-memory at {point}")
    if kind == "enospc":
        import errno

        log.warning("fault injection: injected ENOSPC at %s", point)
        raise OSError(errno.ENOSPC,
                      f"No space left on device (injected at {point})")
    # hang
    t = float(os.environ.get("FGUMI_TPU_FAULT_HANG_S", "30"))
    log.warning("fault injection: hanging %.1fs at %s", t, point)
    time.sleep(t)
    return data


def _corrupt(rng, data):
    """Flip a deterministic handful of bytes (~1 per KiB, max 16)."""
    b = bytearray(data)
    if not b:
        return bytes(b)
    for _ in range(min(max(len(b) // 1024, 1), 16)):
        b[rng.randrange(len(b))] ^= 0xFF
    return bytes(b)


def _corrupt_result(data):
    """Flip bits in numpy result array(s): a handful of XORed bytes spread
    evenly across each array, so real (non-padding) rows are always hit
    regardless of the dispatch's padded layout. Deterministic by
    construction — the same arrays corrupt identically on every run.
    Accepts a single ndarray or a tuple/list of them (the fetched device
    result shape); non-array leaves pass through untouched."""
    import numpy as np

    def flip(a):
        if not isinstance(a, np.ndarray) or a.size == 0:
            return a
        out = np.array(a, copy=True)  # writable + C-contiguous
        flat = out.reshape(-1).view(np.uint8)
        n = flat.size
        k = min(max(n // 4096, 4), 64)
        idx = (np.arange(k, dtype=np.int64) * n) // k
        flat[idx] ^= 0xFF
        return out

    if isinstance(data, (tuple, list)):
        return type(data)(flip(a) for a in data)
    return flip(data)


def snapshot():
    """{point: fired count} for armed faults (chaos-test assertions)."""
    with _lock:
        _refresh_locked()
        return {p: f.fired for p, f in _armed.items()}
