"""Persistent XLA compilation cache across CLI invocations.

The reference is an AOT-compiled Rust binary: its per-invocation startup cost
is process exec only. A JAX-based CLI pays JIT compilation on every fresh
process instead — several seconds across the consensus kernel's size buckets
— which lands on every stage of a best-practice chain
(extract -> group -> simplex -> filter) because each stage is its own
process. The persistent compilation cache makes second and later invocations
load compiled executables from disk (~0.1s instead of ~0.4-3s per kernel
shape), the closest JAX analog of shipping an AOT binary.

One shared implementation: the CLI enables it up front (so every command's
jits benefit, not just the consensus kernel's), and ConsensusKernel
construction enables it for library users who never go through the CLI.

Env contract:
  FGUMI_TPU_NO_XLA_CACHE=1      disable
  JAX_COMPILATION_CACHE_DIR=..  respected, left entirely alone
  unset                         default to ~/.cache/fgumi_tpu/xla_cache

Failures are non-fatal by design: a read-only HOME or an old jax simply means
no cross-process reuse.
"""

import logging
import os

log = logging.getLogger("fgumi_tpu.compile_cache")

_enabled = False
_cache_dir = None


def cache_dir():
    """The directory the persistent cache was enabled with, or None."""
    return _cache_dir


def enable_persistent_cache(path: str = None):
    """Point jax at an on-disk compilation cache (idempotent).

    ``path`` pins an explicit directory (the serve daemon's
    ``--compile-cache DIR``, also how the smoke gate gets a countable cache
    to assert warm-kernel behaviour from); default is the env contract
    above. Returns the cache dir, or None when disabled/unavailable/already
    configured elsewhere.
    """
    global _enabled, _cache_dir
    opt_out = os.environ.get("FGUMI_TPU_NO_XLA_CACHE", "").lower() \
        not in ("", "0", "false")
    if _enabled or opt_out or os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        _enabled = True
        return _cache_dir
    if path is None:
        path = os.path.join(
            os.path.expanduser("~"), ".cache", "fgumi_tpu", "xla_cache")
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything: the chain's cost is many small-to-medium kernels,
        # not one big one, so the default entry-size/compile-time floors
        # would skip exactly the executables we want reused
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as e:  # non-fatal: just no cross-process reuse
        log.debug("persistent compile cache unavailable: %s", e)
        return None
    _enabled = True
    _cache_dir = path
    return path
