"""Crash-safe output commit: write to a temp file, fsync, atomic rename.

A killed process (OOM killer, SIGKILL, node preemption) must never leave a
truncated BAM/FASTQ/metrics file under the final output name — a torn BGZF
tail *looks* valid to a consumer until it hits the missing EOF sentinel
mid-analysis. Every command output therefore goes to a same-directory
``.<name>.tmp.<pid>`` and is fsync'd + atomically renamed over the final
name only on successful close (the rename is atomic on POSIX because the
temp lives in the same directory, hence the same filesystem).

Escape hatch: the ``--no-atomic-output`` CLI flag or
``FGUMI_TPU_NO_ATOMIC=1`` writes directly to the final name (e.g. for
FIFO/special-file outputs, or filesystems where the extra rename matters).

Stale temps from crashed runs are swept opportunistically: opening an
atomic output for ``name`` removes ``.name.tmp.<pid>[.<seq>]`` leftovers
whose *owning pid* is no longer alive. The sweep parses the pid out of the
component right after ``tmp`` — never the trailing token — so a temp
created by a live process can never be mistaken for a dead one's, and this
process's own temps are always skipped (two concurrent daemon jobs share a
pid; the per-open ``<seq>`` keeps their temp names distinct).
"""

import contextvars
import errno
import glob
import itertools
import logging
import os
import time as _time

log = logging.getLogger("fgumi_tpu")

# context-scoped so concurrent daemon jobs in one process can differ (a job
# running with --no-atomic-output must not turn its neighbour's commit off);
# plain CLI runs set it once per invocation like before
_flag_disabled = contextvars.ContextVar("fgumi_tpu_no_atomic", default=False)

# per-open uniquifier: two writers in one process targeting the same path
# (daemon jobs) must never share a temp file
_seq = itertools.count(1)


def set_atomic_enabled(enabled: bool):
    """CLI hook for --no-atomic-output (per invocation, context-scoped)."""
    _flag_disabled.set(not enabled)


def atomic_enabled() -> bool:
    if _flag_disabled.get():
        return False
    return os.environ.get("FGUMI_TPU_NO_ATOMIC", "").lower() \
        not in ("1", "true", "yes")


def _tmp_path(path: str) -> str:
    d, base = os.path.split(os.path.abspath(path))
    return os.path.join(d, f".{base}.tmp.{os.getpid()}.{next(_seq)}")


def _owning_pid(temp_name: str, base: str):
    """The pid embedded in a temp file name, or None when unparseable.

    Reads the component immediately after ``.tmp.`` — both the current
    ``.<base>.tmp.<pid>.<seq>`` and the legacy ``.<base>.tmp.<pid>`` form —
    rather than the last dot token, which in the current form is the
    sequence number (treating *that* as the pid is exactly the bug that let
    a sweep delete a live writer's temp)."""
    suffix = temp_name[len(f".{base}.tmp."):]
    pid_s = suffix.split(".", 1)[0]
    try:
        return int(pid_s)
    except ValueError:
        return None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    except (OSError, OverflowError):
        return False
    return True


def cleanup_stale_temps(path: str):
    """Remove ``.<name>.tmp.<pid>[.<seq>]`` leftovers (for this target)
    whose *owning* process is dead. Temps owned by any live pid — this
    process included, which may have several jobs writing near this target
    concurrently — are never touched. Best-effort: unlink races are
    ignored."""
    d, base = os.path.split(os.path.abspath(path))
    pattern = os.path.join(glob.escape(d), f".{glob.escape(base)}.tmp.*")
    for p in glob.glob(pattern):
        pid = _owning_pid(os.path.basename(p), base)
        if pid is None or pid == os.getpid() or _pid_alive(pid):
            continue
        try:
            os.unlink(p)
            log.info("removed stale temp output %s (pid %d is gone)", p, pid)
        except OSError:
            pass


def _fsync_dir(d: str):
    """Persist the rename itself (the directory entry), best-effort."""
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class AtomicOutputFile:
    """File-like write target committed by atomic rename.

    ``close()`` commits (flush + fsync + rename to the final name);
    ``discard()`` abandons the temp file. As a context manager, a clean
    exit commits and an exception discards — an interrupted run can never
    leave a partial file under the final name either way.
    """

    def __init__(self, path: str, mode: str = "wb"):
        self.name = path
        self._tmp = _tmp_path(path)
        cleanup_stale_temps(path)
        self._f = open(self._tmp, mode)
        self._done = False
        # optional pre-commit verification hook (--audit-output,
        # io/bam.py): called with the temp path after flush+fsync+close,
        # BEFORE the rename — a raise aborts the commit and discards the
        # temp, so a file that fails its own audit is never published
        self.pre_commit_check = None

    # -- the file-object surface the writers actually use ------------------
    def write(self, data):
        return self._f.write(data)

    def flush(self):
        self._f.flush()

    def fileno(self):
        return self._f.fileno()

    def tell(self):
        return self._f.tell()

    def writable(self):
        return True

    @property
    def closed(self):
        return self._done

    # -- commit protocol ---------------------------------------------------
    def commit(self):
        if self._done:
            return
        t0 = _time.monotonic()
        try:
            self._f.flush()
            try:
                os.fsync(self._f.fileno())
            except OSError as e:
                # only targets that cannot fsync (pipes, /dev/null) are
                # ignorable; a real write-back failure (EIO, ENOSPC) must
                # NOT commit — that would rename data the kernel just
                # reported as unwritten over the final name
                if e.errno not in (errno.EINVAL, errno.ENOTSUP,
                                   errno.EBADF, errno.EROFS):
                    raise
            self._f.close()
            if self.pre_commit_check is not None:
                self.pre_commit_check(self._tmp)
            os.replace(self._tmp, self.name)
        except BaseException:
            # ANY commit failure (flush ENOSPC, close, rename) discards:
            # the temp must not linger with an open fd, and _done must not
            # be set early or the discard would no-op
            self.discard()
            raise
        self._done = True
        _fsync_dir(os.path.dirname(self.name) or ".")
        # the run report's latency decomposition charges flush+fsync+rename
        # time to its "commit" component (io.commit_s histogram sum)
        try:
            from ..observe.metrics import METRICS

            METRICS.observe("io.commit_s", _time.monotonic() - t0)
        except Exception:  # noqa: BLE001 - telemetry never fails a commit
            pass

    def discard(self):
        """Abandon the output: close and remove the temp file."""
        if self._done:
            return
        self._done = True
        try:
            self._f.close()
        finally:
            try:
                os.unlink(self._tmp)
            except OSError:
                pass

    # plain close == successful completion (matches every writer's
    # success-path close() call); error paths use discard()/__exit__
    close = commit

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.commit()
        else:
            self.discard()


def open_output(path: str, mode: str = "wb"):
    """Open a command output for writing, atomically unless disabled.

    Returns an :class:`AtomicOutputFile` (or a plain file when atomic
    commit is disabled). Both support the context-manager protocol and
    ``discard()`` is present only on the atomic variant — error paths use
    :func:`discard_output` which handles either.
    """
    if atomic_enabled():
        return AtomicOutputFile(path, mode)
    return open(path, mode)


def discard_output(fileobj):
    """Abandon an open_output() object: discard if atomic, else close."""
    disc = getattr(fileobj, "discard", None)
    if disc is not None:
        disc()
    else:
        fileobj.close()
