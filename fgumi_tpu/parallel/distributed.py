"""Multi-host execution: the jax.distributed backend for fgumi-tpu.

The reference scales with an in-process thread pool on one machine
(/root/reference/src/lib/unified_pipeline/scheduler/mod.rs:70-178); the
TPU-native analog of "more workers" is more chips, and past one host that
means a jax.distributed process group: one Python process per host, a
coordinator address, and a GLOBAL device mesh whose collectives are placed
by XLA onto ICI within a host/slice and DCN across hosts.

Axis placement policy (the scaling-book recipe applied to this workload):

- ``dp`` (families) carries NO collectives — families are independent — so
  it is the axis allowed to span hosts: the only cross-host traffic is the
  initial shard distribution, which rides DCN regardless.
- ``sp`` (reads within a family) carries the hot-path ``psum`` of partial
  likelihood reductions, so sp groups are always built from one process's
  LOCAL devices: the psum stays on ICI, never DCN.

`device_grid` is pure (testable on any device list); `initialize_from_env`
wires the standard JAX coordinator env contract so a Snakemake/sbatch-style
launcher can start N identical processes:

    FGUMI_TPU_COORDINATOR=host0:8476 FGUMI_TPU_NUM_PROCESSES=4 \\
    FGUMI_TPU_PROCESS_ID=$RANK fgumi-tpu simplex ... --devices auto
"""

import logging
import os

log = logging.getLogger("fgumi_tpu")

_initialized = False


def initialize_from_env() -> bool:
    """jax.distributed.initialize from FGUMI_TPU_COORDINATOR /
    _NUM_PROCESSES / _PROCESS_ID (idempotent; False = single-process run).

    Must run before the first backend touch in each process; _build_dp_mesh
    calls it ahead of jax.devices().
    """
    global _initialized
    coord = os.environ.get("FGUMI_TPU_COORDINATOR")
    if _initialized or not coord:
        return _initialized
    num = int(os.environ.get("FGUMI_TPU_NUM_PROCESSES", "0"))
    pid = int(os.environ.get("FGUMI_TPU_PROCESS_ID", "-1"))
    if num <= 0 or pid < 0:
        raise ValueError(
            "FGUMI_TPU_COORDINATOR requires FGUMI_TPU_NUM_PROCESSES and "
            "FGUMI_TPU_PROCESS_ID")
    import jax

    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=num, process_id=pid)
    _initialized = True
    log.info("distributed: process %d/%d via %s; %d global / %d local "
             "devices", pid, num, coord, len(jax.devices()),
             len(jax.local_devices()))
    return True


def device_grid(devices, local_count: int, sp: int = 1):
    """Arrange a host-major global device list into a (dp, sp) grid where
    every sp group lies within one host's `local_count` block.

    jax.devices() orders devices by process, so rows of the returned
    (dp, sp) array that split the read axis never cross a host boundary —
    the construction that keeps the sp psum on ICI. Raises when sp does not
    divide the per-host device count.
    """
    import numpy as np

    n = len(devices)
    if local_count <= 0 or n % local_count != 0:
        raise ValueError(f"{n} devices not a multiple of per-host "
                         f"count {local_count}")
    if sp <= 0 or local_count % sp != 0:
        raise ValueError(f"sp={sp} does not divide the per-host device "
                         f"count {local_count}")
    hosts = n // local_count
    arr = np.array(devices, dtype=object).reshape(hosts, local_count // sp,
                                                  sp)
    return arr.reshape(hosts * (local_count // sp), sp)


def make_global_mesh(sp: int = 1):
    """A (dp, sp) Mesh over every device of every participating process.

    Single-process: identical to parallel.mesh.make_mesh. Multi-process
    (after initialize_from_env): dp spans hosts, sp stays on-host (ICI).
    Devices are explicitly grouped by process_index first — jax.devices()
    orders by device id, which is NOT guaranteed process-contiguous on
    every topology, and the sp-on-ICI invariant depends on grouping, not
    on id order.
    """
    import jax
    from jax.sharding import Mesh

    devs = sorted(jax.devices(),
                  key=lambda d: (d.process_index, getattr(d, "id", 0)))
    per_host = {}
    for d in devs:
        per_host[d.process_index] = per_host.get(d.process_index, 0) + 1
    counts = set(per_host.values())
    if len(counts) > 1:
        raise ValueError(f"uneven per-process device counts {per_host}; "
                         "cannot build a uniform (dp, sp) grid")
    grid = device_grid(devs, counts.pop(), sp)
    return Mesh(grid, axis_names=("dp", "sp"))
