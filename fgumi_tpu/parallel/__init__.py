"""Multi-chip / multi-host execution (mesh.py, distributed.py).

Only :class:`MeshConfigError` lives at package level: mesh.py imports jax
at module scope, and the CLI's top-level exception contract must be able
to name the error class without paying the jax import on host-only runs.
"""


class MeshConfigError(ValueError):
    """A mesh specification that cannot be satisfied (malformed spec or a
    shape that does not match the live device count). CLI commands map it
    to exit 2 with the message as the one-line diagnostic."""
