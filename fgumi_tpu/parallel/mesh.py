"""Multi-chip execution: consensus kernel sharded over a device mesh.

Families are embarrassingly parallel (exactly like the reference's per-group Process
step, SURVEY.md §5.7), so the natural mesh is:

- ``dp``: the family axis F — data parallel, no communication;
- ``sp``: the read axis R — "sequence parallel" for very deep families: each shard
  reduces its local reads' likelihood contributions, then a single psum over ``sp``
  combines them (the only collective in the hot path, riding ICI).

This module provides the shard_map-wrapped kernel plus mesh construction helpers
and the production mesh resolution (``FGUMI_TPU_MESH`` / ``--mesh`` / ``--devices``
-> a live jax Mesh, docs/multi-chip.md). The reference has no distributed backend
(single host, SURVEY.md §5.8); this is the TPU-native scale-out design the
reference's thread pool maps to.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.kernel import (_call_epilogue, _reduce_contributions,
                          shard_map_compat)

from . import MeshConfigError

#: snapshot of the last production mesh built by resolve_mesh/publish —
#: the run report and flight dumps read it without holding a Mesh reference
LAST_MESH_SNAPSHOT = None

_MESH_RE = re.compile(r"^dp(\d+)(?:xsp(\d+))?$")


def parse_mesh_spec(spec):
    """``FGUMI_TPU_MESH`` / ``--mesh`` value -> ``None`` (off), ``"auto"``,
    or ``(dp, sp)``.

    Accepted forms (loud errors otherwise, same discipline as
    FGUMI_TPU_SHAPE_BUCKETS): empty/``off``/``0`` (mesh disabled, legacy
    single-device path), ``auto`` (dp = all visible devices, sp = 1), or
    ``dpNxspM`` / ``dpN`` (explicit shape; sp defaults to 1).
    """
    if spec is None:
        return None
    s = str(spec).strip().lower()
    if s in ("", "off", "none", "0", "1"):
        return None
    if s == "auto":
        return "auto"
    from ..utils.knobs import knob_error

    grammar = "'auto', 'off', or 'dpNxspM' (e.g. dp4xsp2) with dp, sp >= 1"
    m = _MESH_RE.match(s)
    if not m:
        raise MeshConfigError(knob_error(
            "FGUMI_TPU_MESH", spec, f"unrecognized shape {s!r}", grammar))
    dp = int(m.group(1))
    sp = int(m.group(2)) if m.group(2) else 1
    if dp < 1 or sp < 1:
        raise MeshConfigError(knob_error(
            "FGUMI_TPU_MESH", spec, f"dp={dp} sp={sp} below the >= 1 floor",
            grammar))
    return dp, sp


def resolve_mesh(devices=None, spec=None, sp_default=1):
    """The production (dp, sp) Mesh for this process, or None (single
    device / mesh disabled).

    ``spec`` is a parse_mesh_spec result (or raw string). An explicit
    ``(dp, sp)`` shape is validated against the live device count with a
    loud :class:`MeshConfigError` — a silently smaller mesh would report
    itself as N-way while computing on fewer chips. ``auto`` uses every
    visible device with ``sp_default``. ``None`` disables the mesh.
    """
    if isinstance(spec, str):
        spec = parse_mesh_spec(spec)
    if spec is None:
        return None
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if spec == "auto":
        if n <= 1:
            return None
        sp = sp_default if n % max(sp_default, 1) == 0 else 1
        return make_mesh(devices, sp=sp)
    dp, sp = spec
    if dp * sp > n:
        raise MeshConfigError(
            f"FGUMI_TPU_MESH=dp{dp}xsp{sp} needs {dp * sp} devices but only "
            f"{n} are visible (XLA_FLAGS=--xla_force_host_platform_device_"
            f"count=N forces virtual CPU devices)")
    if dp * sp == 1:
        return None
    return make_mesh(devices[:dp * sp], dp=dp, sp=sp)


def mesh_snapshot(mesh) -> dict:
    """Machine-readable mesh description for reports / artifacts."""
    dp = int(mesh.shape.get("dp", mesh.size))
    sp = int(mesh.shape.get("sp", 1))
    devs = list(mesh.devices.flat)
    return {"dp": dp, "sp": sp, "devices": len(devs),
            "platform": getattr(devs[0], "platform", "unknown")}


def publish_mesh(mesh) -> dict:
    """Record the active production mesh: ``device.mesh.{dp,sp,devices}``
    gauges, a flight-ring note, and the module snapshot the run report
    attaches to its ``device`` section. Returns the snapshot."""
    global LAST_MESH_SNAPSHOT
    snap = mesh_snapshot(mesh)
    LAST_MESH_SNAPSHOT = snap
    from ..observe.flight import FLIGHT
    from ..observe.metrics import METRICS

    METRICS.set("device.mesh.dp", snap["dp"])
    METRICS.set("device.mesh.sp", snap["sp"])
    METRICS.set("device.mesh.devices", snap["devices"])
    FLIGHT.note("device.mesh", **snap)
    return snap


def make_mesh(devices=None, dp: int = None, sp: int = 1) -> Mesh:
    """Build a (dp, sp) mesh over the given (default: all) devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dp is None:
        dp = n // sp
    if dp * sp != n:
        raise ValueError(f"dp*sp ({dp}*{sp}) != device count {n}")
    arr = np.array(devices).reshape(dp, sp)
    return Mesh(arr, axis_names=("dp", "sp"))


def sharded_consensus_fn(mesh: Mesh, correct_tab, err_tab, ln_error_pre_umi):
    """Returns a jitted fn(codes, quals) sharded over the mesh.

    codes/quals: (F, R, L) with F divisible by dp and R divisible by sp.
    Outputs are (F, L) arrays sharded along dp.
    """
    correct_tab = jnp.asarray(correct_tab, dtype=jnp.float32)
    err_tab = jnp.asarray(err_tab, dtype=jnp.float32)
    pre = jnp.float32(ln_error_pre_umi)

    def local(codes, quals):
        contrib, obs = _reduce_contributions(codes, quals, correct_tab, err_tab)
        # Combine partial read-axis reductions across the sp axis — the one
        # collective in the hot path.
        contrib = jax.lax.psum(contrib, "sp")
        obs = jax.lax.psum(obs, "sp")
        return _call_epilogue(contrib, obs, pre)

    mapped = shard_map_compat(
        local,
        mesh=mesh,
        in_specs=(P("dp", "sp", None), P("dp", "sp", None)),
        out_specs=(P("dp"), P("dp"), P("dp"), P("dp"), P("dp")),
    )
    return jax.jit(mapped)


def pad_for_mesh(codes: np.ndarray, quals: np.ndarray, mesh: Mesh):
    """Pad (F, R, L) arrays so F % dp == 0 and R % sp == 0 (pad = N/qual 0)."""
    from ..constants import N_CODE

    dp = mesh.shape["dp"]
    sp = mesh.shape["sp"]
    F, R, L = codes.shape
    Fp = -(-F // dp) * dp
    Rp = -(-R // sp) * sp
    if (Fp, Rp) != (F, R):
        pc = np.full((Fp, Rp, L), N_CODE, dtype=np.uint8)
        pq = np.zeros((Fp, Rp, L), dtype=np.uint8)
        pc[:F, :R] = codes
        pq[:F, :R] = quals
        codes, quals = pc, pq
    return codes, quals, F
