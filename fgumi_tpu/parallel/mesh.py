"""Multi-chip execution: consensus kernel sharded over a device mesh.

Families are embarrassingly parallel (exactly like the reference's per-group Process
step, SURVEY.md §5.7), so the natural mesh is:

- ``dp``: the family axis F — data parallel, no communication;
- ``sp``: the read axis R — "sequence parallel" for very deep families: each shard
  reduces its local reads' likelihood contributions, then a single psum over ``sp``
  combines them (the only collective in the hot path, riding ICI).

This module provides the shard_map-wrapped kernel plus mesh construction helpers.
The reference has no distributed backend (single host, SURVEY.md §5.8); this is the
TPU-native scale-out design the reference's thread pool maps to.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.kernel import (_call_epilogue, _reduce_contributions,
                          shard_map_compat)


def make_mesh(devices=None, dp: int = None, sp: int = 1) -> Mesh:
    """Build a (dp, sp) mesh over the given (default: all) devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dp is None:
        dp = n // sp
    if dp * sp != n:
        raise ValueError(f"dp*sp ({dp}*{sp}) != device count {n}")
    arr = np.array(devices).reshape(dp, sp)
    return Mesh(arr, axis_names=("dp", "sp"))


def sharded_consensus_fn(mesh: Mesh, correct_tab, err_tab, ln_error_pre_umi):
    """Returns a jitted fn(codes, quals) sharded over the mesh.

    codes/quals: (F, R, L) with F divisible by dp and R divisible by sp.
    Outputs are (F, L) arrays sharded along dp.
    """
    correct_tab = jnp.asarray(correct_tab, dtype=jnp.float32)
    err_tab = jnp.asarray(err_tab, dtype=jnp.float32)
    pre = jnp.float32(ln_error_pre_umi)

    def local(codes, quals):
        contrib, obs = _reduce_contributions(codes, quals, correct_tab, err_tab)
        # Combine partial read-axis reductions across the sp axis — the one
        # collective in the hot path.
        contrib = jax.lax.psum(contrib, "sp")
        obs = jax.lax.psum(obs, "sp")
        return _call_epilogue(contrib, obs, pre)

    mapped = shard_map_compat(
        local,
        mesh=mesh,
        in_specs=(P("dp", "sp", None), P("dp", "sp", None)),
        out_specs=(P("dp"), P("dp"), P("dp"), P("dp"), P("dp")),
    )
    return jax.jit(mapped)


def pad_for_mesh(codes: np.ndarray, quals: np.ndarray, mesh: Mesh):
    """Pad (F, R, L) arrays so F % dp == 0 and R % sp == 0 (pad = N/qual 0)."""
    from ..constants import N_CODE

    dp = mesh.shape["dp"]
    sp = mesh.shape["sp"]
    F, R, L = codes.shape
    Fp = -(-F // dp) * dp
    Rp = -(-R // sp) * sp
    if (Fp, Rp) != (F, R):
        pc = np.full((Fp, Rp, L), N_CODE, dtype=np.uint8)
        pq = np.zeros((Fp, Rp, L), dtype=np.uint8)
        pc[:F, :R] = codes
        pq[:F, :R] = quals
        codes, quals = pc, pq
    return codes, quals, F
