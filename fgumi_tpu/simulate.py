"""Synthetic UMI-family data generation (test + benchmark infrastructure).

Analog of the reference's `fgumi simulate` tooling (/root/reference/src/lib/simulate/,
grouped-reads mode): deterministic, seeded generation of MI-grouped BAM input for the
consensus callers, so E2E tests compare pipeline outputs without golden files
(SURVEY.md §4 test strategy).
"""

import numpy as np

from .constants import BASE_TO_CODE, CODE_TO_BASE
from .io.bam import (BamHeader, BamWriter, FLAG_FIRST, FLAG_LAST,
                     FLAG_MATE_REVERSE, FLAG_PAIRED, FLAG_REVERSE, RecordBuilder)
import struct



def _open_truth(truth_path):
    """Truth-table output, crash-safe committed like every other output."""
    if not truth_path:
        return None
    from .utils.atomic import open_output

    return open_output(truth_path, "w")

def _build_mapped_record(name, flag, ref_id, pos, mapq, cigar_ops, seq, quals,
                         next_ref_id, next_pos, tlen, tags):
    """Assemble a mapped BAM record (RecordBuilder only covers unmapped)."""
    buf = bytearray()
    l_name = len(name) + 1
    buf += struct.pack("<iiBBHHHiiii", ref_id, pos, l_name, mapq, 0,
                       len(cigar_ops), flag, len(seq), next_ref_id, next_pos, tlen)
    buf += name + b"\x00"
    op_codes = {"M": 0, "I": 1, "D": 2, "N": 3, "S": 4, "H": 5, "P": 6, "=": 7, "X": 8}
    for op, length in cigar_ops:
        buf += struct.pack("<I", (length << 4) | op_codes[op])
    from .io.bam import pack_seq
    buf += pack_seq(seq)
    buf += np.asarray(quals, dtype=np.uint8).tobytes()
    for tag, typ, value in tags:
        if typ == "Z":
            buf += tag + b"Z" + value + b"\x00"
        elif typ == "i":
            buf += tag + b"i" + struct.pack("<i", value)
        elif typ == "f":
            buf += tag + b"f" + struct.pack("<f", value)
        elif typ == "B":
            arr = np.asarray(value)
            sub = {np.dtype(np.int16): b"s", np.dtype(np.uint16): b"S",
                   np.dtype(np.int8): b"c", np.dtype(np.uint8): b"C",
                   np.dtype(np.int32): b"i", np.dtype(np.uint32): b"I",
                   np.dtype(np.float32): b"f"}[arr.dtype]
            buf += tag + b"B" + sub + struct.pack("<I", len(arr))
            buf += arr.tobytes()
        else:
            raise ValueError(f"unsupported tag type {typ!r}")
    return bytes(buf)


def simulate_mapped_bam(path: str, num_families: int = 100, family_size: int = 5,
                        read_length: int = 100, umi_length: int = 8,
                        umi_error_rate: float = 0.02, error_rate: float = 0.01,
                        base_quality: int = 35, seed: int = 42, paired_umis: bool = False,
                        ref_name: str = "chr1", ref_length: int = 10_000_000):
    """Write a template-coordinate-ordered mapped BAM with RX UMI tags (pre-`group`).

    Families share a genomic position and a true UMI; per-read UMIs carry errors at
    ``umi_error_rate`` per base. With ``paired_umis`` the RX is dual ("AAAA-CCCC") and
    half the reads come from the opposite strand with the flipped UMI — the
    `group --strategy paired` input shape.
    """
    rng = np.random.default_rng(seed)
    header = BamHeader(
        text="@HD\tVN:1.6\tSO:unsorted\tGO:query\tSS:unsorted:template-coordinate\n"
             f"@SQ\tSN:{ref_name}\tLN:{ref_length}\n"
             "@RG\tID:A\tSM:sample\tLB:lib\n",
        ref_names=[ref_name], ref_lengths=[ref_length],
    )
    # families at distinct positions, emitted in position order (template-coordinate)
    starts = np.sort(rng.choice(ref_length - 4 * read_length,
                                size=num_families, replace=False))
    n_written = 0
    with BamWriter(path, header) as w:
        for fam, start in enumerate(starts):
            start = int(start)
            insert = 2 * read_length
            r2_pos = start + insert - read_length
            half = umi_length // 2
            u1 = CODE_TO_BASE[rng.integers(0, 4, size=half)].tobytes().decode()
            u2 = CODE_TO_BASE[rng.integers(0, 4, size=umi_length - half)].tobytes().decode()
            true_umi = f"{u1}-{u2}" if paired_umis else (u1 + u2)
            cigar = [("M", read_length)]
            mc = f"{read_length}M".encode()
            truth1 = rng.integers(0, 4, size=read_length).astype(np.uint8)
            truth2 = rng.integers(0, 4, size=read_length).astype(np.uint8)

            def mutate_seq(truth):
                codes = truth.copy()
                errs = rng.random(read_length) < error_rate
                n_err = int(errs.sum())
                if n_err:
                    codes[errs] = (codes[errs] + rng.integers(1, 4, n_err)) % 4
                return CODE_TO_BASE[codes].tobytes()

            for r in range(family_size):
                # per-read UMI with at most one error (so `--edits 1` strategies
                # provably re-merge every family; rate = per-base rate * length)
                def mutate_umi(u):
                    chars = list(u)
                    base_positions = [i for i, c in enumerate(chars) if c != "-"]
                    if rng.random() < umi_error_rate * len(base_positions):
                        i = int(rng.choice(base_positions))
                        c = chars[i]
                        chars[i] = "ACGT"[("ACGT".index(c) + int(rng.integers(1, 4))) % 4]
                    return "".join(chars)

                is_ba = paired_umis and bool(rng.integers(0, 2))
                rx = mutate_umi(true_umi)
                if is_ba:
                    a, b = rx.split("-")
                    rx = f"{b}-{a}"
                seq1 = mutate_seq(truth1)
                seq2 = mutate_seq(truth2)
                quals = np.full(read_length, base_quality, dtype=np.uint8)
                name = f"t{fam}:{r}".encode()
                tags = [(b"MC", "Z", mc), (b"RG", "Z", b"A"), (b"RX", "Z", rx.encode())]
                # BA-strand templates flip which physical end is R1
                first_flag, last_flag = (FLAG_LAST, FLAG_FIRST) if is_ba else (FLAG_FIRST, FLAG_LAST)
                rec1 = _build_mapped_record(
                    name, FLAG_PAIRED | first_flag | FLAG_MATE_REVERSE, 0, start, 60,
                    cigar, seq1, quals, 0, r2_pos, insert, tags)
                rec2 = _build_mapped_record(
                    name, FLAG_PAIRED | last_flag | FLAG_REVERSE, 0, r2_pos, 60,
                    cigar, seq2, quals, 0, start, -insert, tags)
                w.write_record_bytes(rec1)
                w.write_record_bytes(rec2)
                n_written += 2
    return n_written


def simulate_duplex_bam(path: str, num_molecules: int = 100, reads_per_strand: int = 3,
                        read_length: int = 100, error_rate: float = 0.01,
                        base_quality: int = 35, qual_jitter: int = 5, seed: int = 42,
                        ref_name: str = "chr1", ref_length: int = 10_000_000,
                        ba_fraction: float = 1.0, strand_bias_alpha: float = None,
                        strand_bias_beta: float = None):
    """Write a duplex-grouped BAM: molecules with /A (AB) and /B (BA) strand reads.

    Geometry mirrors real duplex ligation: AB-R1 and BA-R2 sequence the top strand
    forward; AB-R2 and BA-R1 sequence the bottom strand (stored reverse-complement,
    FLAG_REVERSE). RX carries the dual UMI, strand-flipped between /A and /B.

    strand_bias_alpha/beta: Beta-distributed A/B read split (the reference's
    PCR amplification bias model, simulate/strand_bias.rs): each molecule's
    2*reads_per_strand total reads split by a Beta(alpha, beta) ratio draw
    (possibly leaving one strand empty — single-strand families are real
    duplex rejects). None (default) keeps the symmetric fixed split.

    Interaction with ba_fraction (deliberate, ADVICE r4): the Beta draw
    splits the molecule's total yield FIRST; a molecule suppressed by
    ba_fraction then loses its B-share reads entirely, so its surviving A
    family carries only the Beta share n_a, not a full 2*reads_per_strand.
    This models amplification bias and strand dropout as independent
    physical processes on one fixed molecular yield (the dropped strand's
    reads existed and were lost), which is why single-strand families are
    systematically smaller under bias — matching how real dropout skews
    family-size distributions rather than re-normalizing them.
    """
    rng = np.random.default_rng(seed)
    header = BamHeader(
        text="@HD\tVN:1.6\tSO:unsorted\tGO:query\n"
             f"@SQ\tSN:{ref_name}\tLN:{ref_length}\n"
             "@RG\tID:A\tSM:sample\tLB:lib\n",
        ref_names=[ref_name], ref_lengths=[ref_length],
    )
    from .constants import CODE_COMPLEMENT
    n_written = 0
    with BamWriter(path, header) as w:
        for mol in range(num_molecules):
            start = int(rng.integers(0, ref_length - 3 * read_length))
            insert = int(rng.integers(int(read_length * 1.5), 3 * read_length))
            r2_pos = start + insert - read_length
            # one duplex molecule truth over the insert (reference orientation);
            # the bottom-strand read covers the insert end, in its own orientation
            molecule = rng.integers(0, 4, size=insert).astype(np.uint8)
            truth_top = molecule[:read_length]
            truth_bot = CODE_COMPLEMENT[molecule[insert - read_length:][::-1]]
            umi_codes = rng.integers(0, 4, size=8)
            u1 = CODE_TO_BASE[umi_codes[:4]].tobytes().decode()
            u2 = CODE_TO_BASE[umi_codes[4:]].tobytes().decode()
            cigar = [("M", read_length)]
            mc = f"{read_length}M".encode()

            def mutate(truth):
                codes = truth.copy()
                errs = rng.random(read_length) < error_rate
                n_err = int(errs.sum())
                if n_err:
                    codes[errs] = (codes[errs] + rng.integers(1, 4, n_err)) % 4
                return codes

            def qgen():
                return np.clip(base_quality + rng.integers(-qual_jitter, qual_jitter + 1,
                                                           read_length), 2, 40).astype(np.uint8)

            emit_ba = rng.random() < ba_fraction
            if strand_bias_alpha is not None:
                ratio = rng.beta(strand_bias_alpha,
                                 strand_bias_beta
                                 if strand_bias_beta is not None
                                 else strand_bias_alpha)
                total = 2 * reads_per_strand
                n_a = int(round(ratio * total))
                strand_reads = {"A": n_a, "B": total - n_a}
            else:
                strand_reads = {"A": reads_per_strand, "B": reads_per_strand}
            for strand, mi_suffix, rx in (("A", "/A", f"{u1}-{u2}"),
                                          ("B", "/B", f"{u2}-{u1}")):
                if strand == "B" and not emit_ba:
                    continue
                for r in range(strand_reads[strand]):
                    name = f"mol{mol}:{strand}{r}".encode()
                    tags = [(b"MC", "Z", mc), (b"RG", "Z", b"A"),
                            (b"MI", "Z", f"{mol}{mi_suffix}".encode()),
                            (b"RX", "Z", rx.encode())]
                    # top-strand-forward read (AB-R1 / BA-R2)
                    fwd_flag = FLAG_PAIRED | FLAG_MATE_REVERSE | (
                        FLAG_FIRST if strand == "A" else FLAG_LAST)
                    rec_f = _build_mapped_record(
                        name, fwd_flag, 0, start, 60, cigar,
                        CODE_TO_BASE[mutate(truth_top)].tobytes(), qgen(),
                        0, r2_pos, insert, tags)
                    # bottom-strand read, stored as reverse-complement (AB-R2 / BA-R1)
                    rev_flag = FLAG_PAIRED | FLAG_REVERSE | (
                        FLAG_LAST if strand == "A" else FLAG_FIRST)
                    stored = CODE_COMPLEMENT[mutate(truth_bot)[::-1]]
                    rec_r = _build_mapped_record(
                        name, rev_flag, 0, r2_pos, 60, cigar,
                        CODE_TO_BASE[stored].tobytes(), qgen(),
                        0, start, -insert, tags)
                    w.write_record_bytes(rec_f)
                    w.write_record_bytes(rec_r)
                    n_written += 2
    return n_written


def simulate_codec_bam(path: str, num_molecules: int = 100, pairs_per_molecule: int = 1,
                       read_length: int = 100, error_rate: float = 0.01,
                       base_quality: int = 35, qual_jitter: int = 5, seed: int = 42,
                       overlap_fraction: float = 0.5, umi_length: int = 8,
                       ref_name: str = "chr1", ref_length: int = 10_000_000):
    """Write a CODEC-shaped grouped BAM: each FR pair covers both strands.

    One read-pair per duplex molecule (optionally more): R1 forward from the
    insert start, R2 reverse from the insert end, overlapping on the genome by
    ``overlap_fraction * read_length`` bases. MI tags carry plain molecule ids
    (no /A,/B — the `codec` command's input contract), plus RX UMIs.
    """
    rng = np.random.default_rng(seed)
    header = BamHeader(
        text="@HD\tVN:1.6\tSO:unsorted\tGO:query\n"
             f"@SQ\tSN:{ref_name}\tLN:{ref_length}\n"
             "@RG\tID:A\tSM:sample\tLB:lib\n",
        ref_names=[ref_name], ref_lengths=[ref_length],
    )
    overlap = max(1, int(read_length * overlap_fraction))
    insert = 2 * read_length - overlap
    n_written = 0
    with BamWriter(path, header) as w:
        for mol in range(num_molecules):
            start = int(rng.integers(0, ref_length - 2 * insert))
            r2_pos = start + insert - read_length
            # reference-orientation truth over the whole insert
            truth = rng.integers(0, 4, size=insert).astype(np.uint8)
            umi = CODE_TO_BASE[rng.integers(0, 4, size=umi_length)].tobytes().decode()
            cigar = [("M", read_length)]
            mc = f"{read_length}M".encode()

            def mutate(segment):
                codes = segment.copy()
                errs = rng.random(len(codes)) < error_rate
                n_err = int(errs.sum())
                if n_err:
                    codes[errs] = (codes[errs] + rng.integers(1, 4, n_err)) % 4
                return CODE_TO_BASE[codes].tobytes()

            def qgen():
                return np.clip(
                    base_quality + rng.integers(-qual_jitter, qual_jitter + 1,
                                                read_length), 2, 40).astype(np.uint8)

            for r in range(pairs_per_molecule):
                name = f"codec{mol}:{r}".encode()
                tags = [(b"MC", "Z", mc), (b"RG", "Z", b"A"),
                        (b"MI", "Z", str(mol).encode()),
                        (b"RX", "Z", umi.encode())]
                rec1 = _build_mapped_record(
                    name, FLAG_PAIRED | FLAG_FIRST | FLAG_MATE_REVERSE, 0, start,
                    60, cigar, mutate(truth[:read_length]), qgen(),
                    0, r2_pos, insert, tags)
                rec2 = _build_mapped_record(
                    name, FLAG_PAIRED | FLAG_LAST | FLAG_REVERSE, 0, r2_pos,
                    60, cigar, mutate(truth[insert - read_length:]), qgen(),
                    0, start, -insert, tags)
                w.write_record_bytes(rec1)
                w.write_record_bytes(rec2)
                n_written += 2
    return n_written


def simulate_grouped_bam(path: str, num_families: int = 100, family_size: int = 5,
                         family_size_distribution: str = "fixed",
                         read_length: int = 100, error_rate: float = 0.01,
                         base_quality: int = 35, qual_jitter: int = 5,
                         paired: bool = True, seed: int = 42,
                         read_length_jitter: int = 0,
                         qual_slope: float = 0.0,
                         insert_size_mean: int = None,
                         insert_size_sd: int = 0,
                         ref_name: str = "chr1", ref_length: int = 10_000_000):
    """Write a grouped (MI-tagged) BAM simulating PCR families of reads.

    Models (reference src/lib/simulate/mod.rs:41-47 analogs): family sizes
    fixed/lognormal/longtail (_family_size), per-READ length variation
    (`read_length_jitter` bases truncated from the 3' end — stresses the
    ragged consensus-length rule), normal insert sizes
    (`insert_size_mean`/`insert_size_sd`; default uniform 1.5-3x read), and
    a per-position quality decay (`qual_slope`, _read_quals).

    Returns the number of records written. Families appear consecutively in
    MI order (the post-`group` layout simplex consumes).
    """
    rng = np.random.default_rng(seed)
    header = BamHeader(
        text="@HD\tVN:1.6\tSO:unsorted\tGO:query\n"
             f"@SQ\tSN:{ref_name}\tLN:{ref_length}\n"
             "@RG\tID:A\tSM:sample\tLB:lib\n",
        ref_names=[ref_name], ref_lengths=[ref_length],
    )
    from .utils.progress import ProgressTracker

    # a fixed family-size distribution means the record total is known
    # upfront — exactly what the heartbeat's ETA column wants
    expected = num_families * family_size * (2 if paired else 1) \
        if family_size_distribution == "fixed" else None
    progress = ProgressTracker("simulate", total=expected)
    try:
        n_written = _write_grouped_records(
            path, header, rng, num_families, family_size,
            family_size_distribution, paired, read_length,
            read_length_jitter, insert_size_mean, insert_size_sd,
            ref_length, error_rate, base_quality, qual_jitter, qual_slope,
            progress)
    finally:
        # finish() in a finally: the tracker registered a process-global
        # heartbeat gauge + goal, which must not outlive a failed run
        progress.finish()
    return n_written


def _write_grouped_records(path, header, rng, num_families, family_size,
                           family_size_distribution, paired, read_length,
                           read_length_jitter, insert_size_mean,
                           insert_size_sd, ref_length, error_rate,
                           base_quality, qual_jitter, qual_slope, progress):
    n_written = 0
    with BamWriter(path, header) as w:
        for fam in range(num_families):
            size = _family_size(rng, family_size_distribution, family_size)
            if insert_size_mean:
                insert = int(rng.normal(insert_size_mean,
                                        insert_size_sd or 1))
                # keep the molecule on the contig; generous ceiling so a
                # requested N(mean, sd) well beyond 3x read length is honored
                insert = max(read_length + 1,
                             min(insert, 10 * read_length,
                                 ref_length // 2))
            else:
                insert = int(rng.integers(int(read_length * 1.5),
                                          3 * read_length))
            start = int(rng.integers(0, ref_length - insert - 1))
            # one molecule truth over the insert: R1/R2 agree where they overlap
            truth = rng.integers(0, 4, size=insert).astype(np.uint8)
            mi = str(fam)

            # never truncate below 20 bases (or below 1 for tiny reads)
            jit = max(min(read_length_jitter, read_length - 20), 0)

            def rlen():
                if not jit:
                    return read_length
                return read_length - int(rng.integers(0, jit + 1))

            for r in range(size):
                ln1 = rlen()
                ln2 = rlen()
                truth_r1 = truth[:ln1]
                truth_r2 = truth[insert - ln2:]

                # per-read errors
                def mutate(truth):
                    codes = truth.copy()
                    errs = rng.random(len(codes)) < error_rate
                    n_err = int(errs.sum())
                    if n_err:
                        codes[errs] = (codes[errs] + rng.integers(1, 4, n_err)) % 4
                    return CODE_TO_BASE[codes].tobytes()

                cigar = [("M", ln1)]
                quals = _read_quals(rng, ln1, base_quality, qual_jitter,
                                    qual_slope)
                name = f"fam{fam}:r{r}".encode()
                if paired:
                    cigar2 = [("M", ln2)]
                    mc1 = f"{ln2}M".encode()   # mate (R2) cigar
                    mc2 = f"{ln1}M".encode()   # mate (R1) cigar
                    r2_pos = start + insert - ln2
                    rec1 = _build_mapped_record(
                        name, FLAG_PAIRED | FLAG_FIRST | FLAG_MATE_REVERSE, 0, start,
                        60, cigar, mutate(truth_r1), quals, 0, r2_pos, insert,
                        [(b"MC", "Z", mc1), (b"RG", "Z", b"A"), (b"MI", "Z", mi.encode())])
                    quals2 = _read_quals(rng, ln2, base_quality, qual_jitter,
                                         qual_slope)
                    rec2 = _build_mapped_record(
                        name, FLAG_PAIRED | FLAG_LAST | FLAG_REVERSE, 0, r2_pos,
                        60, cigar2, mutate(truth_r2), quals2, 0, start, -insert,
                        [(b"MC", "Z", mc2), (b"RG", "Z", b"A"), (b"MI", "Z", mi.encode())])
                    w.write_record_bytes(rec1)
                    w.write_record_bytes(rec2)
                    n_written += 2
                    progress.add(2)
                else:
                    rec = _build_mapped_record(
                        name, 0, 0, start, 60, cigar, mutate(truth_r1), quals,
                        -1, -1, 0,
                        [(b"RG", "Z", b"A"), (b"MI", "Z", mi.encode())])
                    w.write_record_bytes(rec)
                    n_written += 1
                    progress.add(1)
    return n_written


def _family_size(rng, distribution: str, mean: int) -> int:
    """Family-size model (the reference's family-size distributions,
    /root/reference/src/lib/simulate/mod.rs:41-47):

    - fixed:     every family has `mean` members
    - lognormal: lognormal around `mean` (sigma 0.6)
    - longtail:  Pareto-tailed mixture capped at 50 — mostly singletons and
      small families with a heavy tail, the BASELINE eval-config-2 shape
      ("real targeted panel, mixed family sizes 1-50")
    """
    if distribution == "fixed":
        return mean
    if distribution == "lognormal":
        return max(1, int(rng.lognormal(np.log(max(mean, 1)), 0.6)))
    if distribution == "longtail":
        return min(50, 1 + int(rng.pareto(1.3) * max(mean, 1) * 0.5))
    raise ValueError(distribution)


def _read_quals(rng, n: int, base_quality: int, qual_jitter: int,
                qual_slope: float = 0.0):
    """Per-position quality model: linear 3'-decay (`qual_slope` Phred per
    base, the Illumina-like degradation profile) plus uniform jitter."""
    q = base_quality - qual_slope * np.arange(n)
    if qual_jitter:
        q = q + rng.integers(-qual_jitter, qual_jitter + 1, n)
    return np.clip(q, 2, 40).astype(np.uint8)


def _random_umi(rng, length):
    return CODE_TO_BASE[rng.integers(0, 4, size=length)].tobytes()


def _mutate_bases(rng, seq_bytes, error_rate):
    """Substitute bases at `error_rate` (ACGT only)."""
    if error_rate <= 0:
        return seq_bytes
    codes = BASE_TO_CODE[np.frombuffer(seq_bytes, dtype=np.uint8)].copy()
    errs = rng.random(len(codes)) < error_rate
    n_err = int(errs.sum())
    if n_err:
        codes[errs] = (codes[errs] + rng.integers(1, 4, n_err)) % 4
    return CODE_TO_BASE[codes].tobytes()


def simulate_fastq_reads(r1_path: str, r2_path: str, truth_path: str = None,
                         num_families: int = 100, family_size: int = 5,
                         family_size_distribution: str = "fixed",
                         read_length: int = 100, umi_length: int = 8,
                         error_rate: float = 0.0, base_quality: int = 35,
                         qual_jitter: int = 5, duplex: bool = False,
                         includelist: str = None, seed: int = 42):
    """Paired gzip FASTQ with UMI prefixes (simulate fastq-reads analog,
    /root/reference/src/lib/commands/simulate/fastq_reads.rs:40-99).

    R1 = UMI + template-forward (read structure f"{umi_length}M+T"); R2 =
    template-reverse-complement (+T), or UMI + body when duplex=True. The
    truth TSV records family -> UMI(s) and size for validation. Returns the
    number of read pairs written.
    """
    import gzip

    from .constants import reverse_complement_bytes

    rng = np.random.default_rng(seed)
    whitelist = None
    if includelist is not None:
        with open(includelist) as f:
            whitelist = [line.strip().encode() for line in f if line.strip()]
        if not whitelist:
            raise ValueError(f"includelist {includelist!r} contains no UMIs")
        umi_length = len(whitelist[0])

    def qline(n, umi_prefix=0):
        q = np.clip(base_quality + rng.integers(-qual_jitter, qual_jitter + 1,
                                                n), 2, 40)
        if umi_prefix:
            q[:umi_prefix] = 37  # UMI bases kept high-quality
        return (q + 33).astype(np.uint8).tobytes()

    n_pairs = 0
    truth_f = _open_truth(truth_path)
    try:
        if truth_f:
            truth_f.write("family\tumi\tsize\n")
        from .utils.atomic import open_output

        # crash-safe like every other output: GzipFile closes (trailer)
        # before the atomic wrapper commits; an exception discards both
        with open_output(r1_path) as raw1, \
                open_output(r2_path) as raw2, \
                gzip.GzipFile(fileobj=raw1, mode="wb", compresslevel=1,
                              mtime=0) as f1, \
                gzip.GzipFile(fileobj=raw2, mode="wb", compresslevel=1,
                              mtime=0) as f2:
            for fam in range(num_families):
                size = _family_size(rng, family_size_distribution,
                                    family_size)
                if whitelist:
                    umi1 = whitelist[int(rng.integers(len(whitelist)))]
                    umi2 = whitelist[int(rng.integers(len(whitelist)))]
                else:
                    umi1 = _random_umi(rng, umi_length)
                    umi2 = _random_umi(rng, umi_length)
                insert = int(read_length * 1.8)
                template = CODE_TO_BASE[rng.integers(0, 4, size=insert)].tobytes()
                body1 = template[:read_length]
                body2 = reverse_complement_bytes(template[-read_length:])
                umi_str = (umi1 + b"-" + umi2).decode() if duplex \
                    else umi1.decode()
                if truth_f:
                    truth_f.write(f"{fam}\t{umi_str}\t{size}\n")
                for r in range(size):
                    name = f"fam{fam}:r{r}".encode()
                    r1_seq = umi1 + _mutate_bases(rng, body1, error_rate)
                    r2_body = _mutate_bases(rng, body2, error_rate)
                    r2_seq = (umi2 + r2_body) if duplex else r2_body
                    f1.write(b"@" + name + b"/1\n" + r1_seq + b"\n+\n"
                             + qline(len(r1_seq), umi_length) + b"\n")
                    f2.write(b"@" + name + b"/2\n" + r2_seq + b"\n+\n"
                             + qline(len(r2_seq),
                                     umi_length if duplex else 0) + b"\n")
                    n_pairs += 1
    except BaseException:
        if truth_f:
            from .utils.atomic import discard_output

            discard_output(truth_f)  # never commit a partial truth table
        raise
    else:
        if truth_f:
            truth_f.close()
    return n_pairs


def simulate_consensus_bam(path: str, truth_path: str = None,
                           num_reads: int = 1000, read_length: int = 150,
                           min_depth: int = 1, max_depth: int = 10,
                           depth_mean: float = 5.0, depth_stddev: float = 2.0,
                           error_rate_mean: float = 0.01,
                           per_base_tags: bool = True, seed: int = 42,
                           ref_name: str = "chr1",
                           ref_length: int = 10_000_000):
    """Unmapped query-grouped BAM shaped like simplex consensus output
    (cD/cM/cE + cd/ce per-base tags), the `filter` command's input (simulate
    consensus-reads analog, consensus_reads.rs:43-90; unmapped like this
    build's pre-zipper consensus stream). Returns records written."""
    del ref_name, ref_length  # consensus records are unmapped here
    rng = np.random.default_rng(seed)
    header = BamHeader(
        text="@HD\tVN:1.6\tSO:unsorted\tGO:query\n"
             "@RG\tID:A\tSM:sample\tLB:lib\n",
        ref_names=[], ref_lengths=[])
    truth_f = _open_truth(truth_path)
    n = 0
    try:
        if truth_f:
            truth_f.write("name\tdepth\terror_rate\n")
        with BamWriter(path, header) as w:
            for i in range(num_reads):
                depth = int(np.clip(round(rng.normal(depth_mean, depth_stddev)),
                                    min_depth, max_depth))
                err = float(np.clip(rng.exponential(error_rate_mean), 0, 0.5))
                seq = CODE_TO_BASE[rng.integers(0, 4, size=read_length)].tobytes()
                quals = np.clip(rng.integers(25, 60, size=read_length), 2,
                                93).astype(np.uint8)
                name = f"fgumi:{i}".encode()
                per_base = np.maximum(
                    depth - (rng.random(read_length) < 0.2), 1).astype(np.int16)
                errors = (rng.random(read_length) < err).astype(np.int16)
                b = RecordBuilder().start_unmapped(name, 0x4, seq, quals)
                b.tag_str(b"RG", b"A")
                b.tag_str(b"MI", str(i).encode())
                b.tag_str(b"RX", _random_umi(rng, 8))
                b.tag_int(b"cD", depth)
                b.tag_int(b"cM", int(per_base.min()))
                b.tag_float(b"cE", err)
                if per_base_tags:
                    b.tag_array_i16(b"cd", per_base)
                    b.tag_array_i16(b"ce", errors)
                w.write_record_bytes(b.finish())
                n += 1
                if truth_f:
                    truth_f.write(f"{name.decode()}\t{depth}\t{err:.6f}\n")
    except BaseException:
        if truth_f:
            from .utils.atomic import discard_output

            discard_output(truth_f)  # never commit a partial truth table
        raise
    else:
        if truth_f:
            truth_f.close()
    return n


def simulate_correct_reads(path: str, includelist_path: str,
                           truth_path: str = None, num_reads: int = 10000,
                           num_umis: int = 1000, umi_length: int = 8,
                           read_length: int = 100, max_errors: int = 2,
                           base_quality: int = 35, seed: int = 42):
    """Unmapped BAM with RX UMIs drawn from a generated includelist, plus the
    includelist file and a truth TSV (simulate correct-reads analog,
    correct_reads.rs:36-76). Returns records written."""
    rng = np.random.default_rng(seed)
    umis = set()
    while len(umis) < num_umis:
        umis.add(_random_umi(rng, umi_length))
    whitelist = sorted(umis)
    with open(includelist_path, "w") as f:
        for u in whitelist:
            f.write(u.decode() + "\n")
    header = BamHeader(text="@HD\tVN:1.6\tSO:unsorted\n"
                            "@RG\tID:A\tSM:sample\tLB:lib\n",
                       ref_names=[], ref_lengths=[])
    truth_f = _open_truth(truth_path)
    try:
        if truth_f:
            truth_f.write("name\ttrue_umi\tobserved_umi\terrors\n")
        with BamWriter(path, header) as w:
            for i in range(num_reads):
                true_umi = whitelist[int(rng.integers(len(whitelist)))]
                n_err = int(rng.integers(0, min(max_errors,
                                                umi_length) + 1))
                if n_err:
                    # exact error count at random positions
                    codes = BASE_TO_CODE[
                        np.frombuffer(true_umi, np.uint8)].copy()
                    pos = rng.choice(umi_length, size=n_err, replace=False)
                    codes[pos] = (codes[pos] + rng.integers(1, 4, n_err)) % 4
                    observed = CODE_TO_BASE[codes].tobytes()
                else:
                    observed = true_umi
                seq = CODE_TO_BASE[
                    rng.integers(0, 4, size=read_length)].tobytes()
                quals = np.clip(base_quality + rng.integers(-5, 6,
                                                            read_length),
                                2, 40)
                b = RecordBuilder().start_unmapped(
                    f"r{i}".encode(), 0x4, seq, quals.astype(np.uint8))
                b.tag_str(b"RG", b"A")
                b.tag_str(b"RX", observed)
                w.write_record_bytes(b.finish())
                if truth_f:
                    truth_f.write(f"r{i}\t{true_umi.decode()}\t"
                                  f"{observed.decode()}\t{n_err}\n")
    except BaseException:
        if truth_f:
            from .utils.atomic import discard_output

            discard_output(truth_f)  # never commit a partial truth table
        raise
    else:
        if truth_f:
            truth_f.close()
    return num_reads
