"""Numpy-facing wrappers for the native batch record layer.

Each call hands whole numpy arrays to C++ (fgumi_native.cc batch section), so
Python cost is per-batch, not per-record — the discipline the reference keeps
with its raw-record design (crates/fgumi-raw-bam/src/raw_bam_record.rs:6-13).

All wrappers require the native library; callers check `available()` once and
fall back to the pure-Python record path when it is False.
"""

import numpy as np

from . import get_lib
# operand-for-a-C++-entry-point: same object when already a C-contiguous
# ndarray of the requested dtype (the common case on the dispatch hot
# path), one conversion copy otherwise — the shared no-copy rule lives in
# ops/datapath.as_device_operand
from ..ops.datapath import as_device_operand as _as_c
from ..utils import faults


def available() -> bool:
    return get_lib() is not None


def _addr(arr: np.ndarray) -> int:
    assert arr.flags["C_CONTIGUOUS"]
    return arr.ctypes.data


def find_boundaries(buf: np.ndarray, max_records: int):
    """(offsets int64[n], scanned) — record starts in decompressed BAM bytes."""
    import ctypes

    faults.fire("native.batch")
    lib = get_lib()
    offsets = np.empty(max_records, dtype=np.int64)
    scanned = ctypes.c_int64(0)
    n = lib.fgumi_find_record_boundaries(
        buf.ctypes.data_as(ctypes.c_char_p), len(buf),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), max_records,
        ctypes.byref(scanned))
    return offsets[:n], scanned.value


def decode_fields(buf: np.ndarray, rec_off: np.ndarray) -> dict:
    """Struct-of-arrays fixed-field decode (fields.rs:7-24 layout)."""
    lib = get_lib()
    n = len(rec_off)
    i32 = {k: np.empty(n, dtype=np.int32)
           for k in ("ref_id", "pos", "mapq", "flag", "l_seq", "n_cigar",
                     "l_read_name", "next_ref_id", "next_pos", "tlen")}
    data_off = np.empty(n, dtype=np.int64)
    data_end = np.empty(n, dtype=np.int64)
    lib.fgumi_decode_fields(
        _addr(buf), _addr(rec_off), n,
        _addr(i32["ref_id"]), _addr(i32["pos"]), _addr(i32["mapq"]),
        _addr(i32["flag"]), _addr(i32["l_seq"]), _addr(i32["n_cigar"]),
        _addr(i32["l_read_name"]), _addr(i32["next_ref_id"]),
        _addr(i32["next_pos"]), _addr(i32["tlen"]), _addr(data_off),
        _addr(data_end))
    i32["data_off"] = data_off
    i32["data_end"] = data_end
    return i32


def scan_tags(buf: np.ndarray, aux_off: np.ndarray, aux_end: np.ndarray,
              tags: list):
    """Per-record aux-tag locations for k tags.

    Returns (val_off int64[n,k], val_len int32[n,k], val_type uint8[n,k]);
    val_off -1 where the tag is absent.
    """
    lib = get_lib()
    n = len(aux_off)
    k = len(tags)
    tag_bytes = np.frombuffer(b"".join(tags), dtype=np.uint8)
    val_off = np.empty((n, k), dtype=np.int64)
    val_len = np.empty((n, k), dtype=np.int32)
    val_type = np.empty((n, k), dtype=np.uint8)
    lib.fgumi_scan_tags(_addr(buf), _addr(aux_off), _addr(aux_end), n,
                        _addr(tag_bytes), k, _addr(val_off), _addr(val_len),
                        _addr(val_type))
    return val_off, val_len, val_type


def group_starts(buf: np.ndarray, off: np.ndarray, length: np.ndarray):
    """Group indices by byte-range equality; raises if any off < 0 (missing)."""
    lib = get_lib()
    n = len(off)
    starts = np.empty(n, dtype=np.int64)
    # converted arrays must stay referenced until the foreign call returns
    length = np.ascontiguousarray(length, np.int32)
    g = lib.fgumi_group_starts(_addr(buf), _addr(off), _addr(length),
                               n, _addr(starts))
    if g < 0:
        raise ValueError(f"record {-g - 1} missing grouping tag; run `group` first")
    return starts[:g]


def pack_reads(buf: np.ndarray, seq_off: np.ndarray, qual_off: np.ndarray,
               l_seq: np.ndarray, reverse: np.ndarray, clip: np.ndarray,
               min_q: int, stride: int, mode: int = 0):
    """Batch SourceRead conversion into (n, stride) code/qual rows.

    Returns (codes uint8[n,stride], quals uint8[n,stride], final_len int32[n]);
    final_len -1 marks rejected reads (empty / all-0xFF quals). mode bit0
    keeps all-0xFF reads, bit1 keeps trailing Ns (the CODEC conversion).
    """
    lib = get_lib()
    n = len(seq_off)
    codes = np.empty((n, stride), dtype=np.uint8)
    quals = np.empty((n, stride), dtype=np.uint8)
    final_len = np.empty(n, dtype=np.int32)
    # converted arrays must stay referenced until the foreign call returns
    l_seq = np.ascontiguousarray(l_seq, np.int32)
    reverse = np.ascontiguousarray(reverse, np.uint8)
    clip = np.ascontiguousarray(clip, np.int32)
    lib.fgumi_pack_reads(
        _addr(buf), _addr(seq_off), _addr(qual_off), _addr(l_seq),
        _addr(reverse), _addr(clip),
        n, min_q, stride, mode, _addr(codes), _addr(quals),
        _addr(final_len))
    return codes, quals, final_len


def mate_clips(buf: np.ndarray, cigar_off: np.ndarray, n_cigar: np.ndarray,
               flag: np.ndarray, ref_id: np.ndarray, pos: np.ndarray,
               next_ref_id: np.ndarray, next_pos: np.ndarray,
               tlen: np.ndarray, mc_off: np.ndarray, mc_len: np.ndarray):
    """Batch num_bases_extending_past_mate (overlap.rs:117-140) -> int32[n]."""
    lib = get_lib()
    n = len(cigar_off)
    clip = np.empty(n, dtype=np.int32)
    # converted arrays must stay referenced until the foreign call returns
    keep = [np.ascontiguousarray(a, np.int32)
            for a in (n_cigar, flag, ref_id, pos, next_ref_id, next_pos, tlen,
                      mc_len)]
    n_cigar, flag, ref_id, pos, next_ref_id, next_pos, tlen, mc_len = keep
    lib.fgumi_mate_clips(
        _addr(buf), _addr(cigar_off), _addr(n_cigar), _addr(flag),
        _addr(ref_id), _addr(pos), _addr(next_ref_id), _addr(next_pos),
        _addr(tlen), _addr(mc_off), _addr(mc_len), n, _addr(clip))
    return clip


def build_consensus_records(code_addr, qual_addr, depth_addr, err_addr, lens,
                            flags, prefix: bytes, mi_addr, mi_len,
                            rx_addr, rx_len, rg: bytes,
                            per_base_tags: bool):
    """Serialize J consensus records into one block_size-prefixed wire blob.

    The *_addr arrays are raw element addresses (int64) into caller-owned
    arrays, which MUST stay referenced for the duration of the call; MI/RX
    values are addresses too (rx_addr 0 = absent tag).
    Returns bytes (the concatenated records, ready for BamWriter raw append).
    """
    lib = get_lib()
    J = len(lens)
    lens = np.ascontiguousarray(lens, np.int32)
    flags = np.ascontiguousarray(flags, np.int32)
    mi_len = np.ascontiguousarray(mi_len, np.int32)
    rx_len = np.ascontiguousarray(rx_len, np.int32)
    mi_addr = np.ascontiguousarray(mi_addr, np.int64)
    rx_addr = np.ascontiguousarray(rx_addr, np.int64)
    # exact per-record size bound (mirrors the C size computation)
    per_rec = (4 + 32 + len(prefix) + 1 + mi_len.astype(np.int64) + 1
               + (lens + 1) // 2 + lens + (3 + len(rg) + 1) + 21
               + (3 + mi_len.astype(np.int64) + 1)
               + np.where(rx_addr != 0, 3 + rx_len.astype(np.int64) + 1, 0))
    if per_base_tags:
        per_rec = per_rec + 2 * (8 + 2 * lens.astype(np.int64))
    out_cap = int(per_rec.sum())
    out = np.empty(out_cap, dtype=np.uint8)
    rec_end = np.empty(J, dtype=np.int64)
    prefix_arr = np.frombuffer(prefix, dtype=np.uint8)
    rg_arr = np.frombuffer(rg, dtype=np.uint8)
    total = lib.fgumi_build_consensus_records(
        _addr(code_addr), _addr(qual_addr), _addr(depth_addr),
        _addr(err_addr), _addr(lens), _addr(flags), J,
        _addr(prefix_arr), len(prefix), _addr(mi_addr), _addr(mi_len),
        _addr(rx_addr), _addr(rx_len),
        _addr(rg_arr), len(rg), int(per_base_tags), _addr(out), out_cap,
        _addr(rec_end))
    if total == -2:
        raise ValueError("read name too long (prefix + MI exceeds 254 bytes)")
    if total < 0:
        raise RuntimeError("consensus record serialization overflow")
    return out[:total].tobytes(), rec_end


def build_duplex_records(code_addr, qual_addr, err_addr, lens, flags,
                         prefix: bytes, mi_addr, mi_len,
                         a_code, a_qual, a_depth, a_err, a_len,
                         b_code, b_qual, b_depth, b_err, b_len, b_present,
                         rx_addr, rx_len, rg: bytes, per_base_tags: bool):
    """Serialize J duplex consensus records into one wire blob.

    All *_addr / strand arrays are raw element addresses (int64) into
    caller-owned arrays that MUST stay referenced for the call duration;
    b_present 0 = BA strand absent, rx_addr 0 = no RX tag.
    """
    lib = get_lib()
    J = len(lens)
    lens = np.ascontiguousarray(lens, np.int32)
    flags = np.ascontiguousarray(flags, np.int32)
    mi_len = np.ascontiguousarray(mi_len, np.int32)
    a_len = np.ascontiguousarray(a_len, np.int32)
    b_len = np.ascontiguousarray(b_len, np.int32)
    b_present = np.ascontiguousarray(b_present, np.uint8)
    rx_len = np.ascontiguousarray(rx_len, np.int32)
    addrs = [np.ascontiguousarray(a, np.int64)
             for a in (code_addr, qual_addr, err_addr, mi_addr, a_code, a_qual,
                       a_depth, a_err, b_code, b_qual, b_depth, b_err,
                       rx_addr)]
    (code_addr, qual_addr, err_addr, mi_addr, a_code, a_qual, a_depth, a_err,
     b_code, b_qual, b_depth, b_err, rx_addr) = addrs
    L64 = lens.astype(np.int64)
    aL64 = a_len.astype(np.int64)
    bL64 = np.where(b_present != 0, b_len, 0).astype(np.int64)
    per_rec = (4 + 32 + len(prefix) + 1 + mi_len.astype(np.int64) + 1
               + (L64 + 1) // 2 + L64
               + (3 + mi_len.astype(np.int64) + 1) + (3 + len(rg) + 1)
               + 9 * 7
               + np.where(rx_addr != 0, 3 + rx_len.astype(np.int64) + 1, 0))
    if per_base_tags:
        per_rec = per_rec + 2 * (4 + aL64) + 16 + 4 * aL64 \
            + np.where(b_present != 0, 2 * (4 + bL64) + 16 + 4 * bL64, 0)
    out_cap = int(per_rec.sum())
    out = np.empty(out_cap, dtype=np.uint8)
    rec_end = np.empty(J, dtype=np.int64)
    prefix_arr = np.frombuffer(prefix, dtype=np.uint8)
    rg_arr = np.frombuffer(rg, dtype=np.uint8)
    total = lib.fgumi_build_duplex_records(
        _addr(code_addr), _addr(qual_addr), _addr(err_addr), _addr(lens),
        _addr(flags), J, _addr(prefix_arr), len(prefix), _addr(mi_addr),
        _addr(mi_len), _addr(a_code), _addr(a_qual), _addr(a_depth),
        _addr(a_err), _addr(a_len), _addr(b_code), _addr(b_qual),
        _addr(b_depth), _addr(b_err), _addr(b_len), _addr(b_present),
        _addr(rx_addr), _addr(rx_len), _addr(rg_arr), len(rg),
        int(per_base_tags), _addr(out), out_cap, _addr(rec_end))
    if total == -2:
        raise ValueError("read name too long (prefix + MI exceeds 254 bytes)")
    if total < 0:
        raise RuntimeError("duplex record serialization overflow")
    return out[:total].tobytes(), rec_end


def consensus_segments(codes2d: np.ndarray, quals2d: np.ndarray,
                       starts: np.ndarray, correct_tab: np.ndarray,
                       err_alt_tab: np.ndarray, g_sat: float, qual_const: int,
                       min_phred: int, tab1_winner: np.ndarray,
                       tab1_qual: np.ndarray, tab2_winner: np.ndarray,
                       tab2_qual: np.ndarray):
    """One f64 consensus pass over ragged segments (fgumi_consensus_segments).

    Returns (winner (J,L) u8, qual (J,L) u8, depth (J,L) i32,
    errors (J,L) i32, slow_idx int64[K], slow_ll (K,4) f64,
    slow_obs (K,4) i32): fast/tabled positions are fully resolved; the K slow
    positions carry their bit-exact lane sums and observation counts for the
    caller's oracle epilogue.
    """
    faults.fire("native.batch")
    lib = get_lib()
    J = len(starts) - 1
    L = codes2d.shape[1] if codes2d.ndim == 2 else 0
    codes2d = _as_c(codes2d, np.uint8)
    quals2d = _as_c(quals2d, np.uint8)
    starts = _as_c(starts, np.int64)
    winner = np.empty((J, L), dtype=np.uint8)
    qual = np.empty((J, L), dtype=np.uint8)
    depth = np.empty((J, L), dtype=np.int32)
    errors = np.empty((J, L), dtype=np.int32)
    cap = max(4096, (J * L) // 8)
    while True:
        slow_idx = np.empty(cap, dtype=np.int64)
        slow_ll = np.empty((cap, 4), dtype=np.float64)
        slow_obs = np.empty((cap, 4), dtype=np.int32)
        n_slow = lib.fgumi_consensus_segments(
            _addr(codes2d), _addr(quals2d), _addr(starts), J, L,
            _addr(correct_tab), _addr(err_alt_tab),
            float(g_sat), int(qual_const), int(min_phred),
            _addr(tab1_winner), _addr(tab1_qual), _addr(tab2_winner),
            _addr(tab2_qual), _addr(winner), _addr(qual), _addr(depth),
            _addr(errors), _addr(slow_idx), _addr(slow_ll), _addr(slow_obs),
            cap)
        if n_slow <= cap:
            return (winner, qual, depth, errors, slow_idx[:n_slow],
                    slow_ll[:n_slow], slow_obs[:n_slow])
        cap = n_slow  # adversarial input: every position borderline


def consensus_classify(codes2d: np.ndarray, quals2d: np.ndarray,
                       starts: np.ndarray, delta_tab: np.ndarray,
                       g_sat: float, qual_const: int, min_phred: int,
                       tab1_winner: np.ndarray, tab1_qual: np.ndarray,
                       tab2_winner: np.ndarray, tab2_qual: np.ndarray):
    """Easy/hard column classification + hard export
    (fgumi_consensus_classify; the host half of the hybrid device dispatch).

    Returns (winner, qual, depth, errors, hard_idx, hard_depth,
    hard_counts (K,4) i32, hard_codes (M,) u8, hard_quals (M,) u8): the
    (J, L) outputs are written for EASY columns only; the K hard columns
    (flat indices, ascending) carry their valid observations concatenated
    in hard_codes/hard_quals (M = hard_depth.sum()).
    """
    faults.fire("native.batch")
    lib = get_lib()
    J = len(starts) - 1
    L = codes2d.shape[1] if codes2d.ndim == 2 else 0
    codes2d = _as_c(codes2d, np.uint8)
    quals2d = _as_c(quals2d, np.uint8)
    starts = _as_c(starts, np.int64)
    delta_tab = _as_c(delta_tab, np.float64)
    winner = np.empty((J, L), dtype=np.uint8)
    qual = np.empty((J, L), dtype=np.uint8)
    depth = np.empty((J, L), dtype=np.int32)
    errors = np.empty((J, L), dtype=np.int32)
    N = int(starts[-1]) if J else 0
    cap = max(4096, (J * L) // 8)
    obs_cap = max(16384, (N * L) // 8)
    n_obs = np.zeros(1, dtype=np.int64)
    while True:
        hard_idx = np.empty(cap, dtype=np.int64)
        hard_depth = np.empty(cap, dtype=np.int32)
        hard_counts = np.empty((cap, 4), dtype=np.int32)
        hard_codes = np.empty(obs_cap, dtype=np.uint8)
        hard_quals = np.empty(obs_cap, dtype=np.uint8)
        n_hard = lib.fgumi_consensus_classify(
            _addr(codes2d), _addr(quals2d), _addr(starts), J, L,
            _addr(delta_tab), float(g_sat), int(qual_const), int(min_phred),
            _addr(tab1_winner), _addr(tab1_qual), _addr(tab2_winner),
            _addr(tab2_qual), _addr(winner), _addr(qual), _addr(depth),
            _addr(errors), _addr(hard_idx), _addr(hard_depth),
            _addr(hard_counts), _addr(hard_codes), _addr(hard_quals),
            cap, obs_cap, _addr(n_obs))
        M = int(n_obs[0])
        if n_hard <= cap and M <= obs_cap:
            return (winner, qual, depth, errors, hard_idx[:n_hard],
                    hard_depth[:n_hard], hard_counts[:n_hard],
                    hard_codes[:M], hard_quals[:M])
        cap = max(n_hard, cap)
        obs_cap = max(M, obs_cap)


def umi_neighbor_pairs(mat_a: np.ndarray, mat_b, d: int, index: str = "auto"):
    """Candidate (i, j) pairs with hamming <= d.

    mat_b None means the symmetric same-matrix case (pairs emitted once,
    i < j); otherwise all cross pairs with i != j. Returns (i, j) int64
    arrays, duplicate-free. `index` selects the search structure
    (reference assigner.rs:228,267 keeps both flavors): "pigeonhole"
    (fgumi_umi_neighbor_pairs sorted partition buckets) or "bktree"
    (fgumi_umi_bktree_pairs triangle-inequality pruning). "auto" picks
    pigeonhole: measured on 4-16k random UMIs of length 8-12 at d=1..4
    the bucketed memcmp scan beats the pointer-chasing tree 3-6x at every
    d — short UMIs distance-discriminate too weakly for BK pruning to pay
    (mean pairwise distance ~0.75*L, so |d(child)-d(query)| <= d prunes
    little). FGUMI_TPU_UMI_INDEX=bktree overrides for verification.
    """
    import os

    lib = get_lib()
    mat_a = np.ascontiguousarray(mat_a, np.uint8)
    n, L = mat_a.shape
    if mat_b is None:
        b_ptr, m = _addr(mat_a), n
    else:
        mat_b = np.ascontiguousarray(mat_b, np.uint8)
        b_ptr, m = _addr(mat_b), mat_b.shape[0]
    if index == "auto":
        index = os.environ.get("FGUMI_TPU_UMI_INDEX", "pigeonhole")
    if index not in ("pigeonhole", "bktree"):
        # a silently-ignored typo would "verify" pigeonhole against itself
        raise ValueError(f"unknown UMI index {index!r} "
                         "(expected pigeonhole or bktree)")
    fn = lib.fgumi_umi_bktree_pairs if index == "bktree" \
        else lib.fgumi_umi_neighbor_pairs
    cap = max(4 * max(n, m), 4096)
    while True:
        out_i = np.empty(cap, dtype=np.int64)
        out_j = np.empty(cap, dtype=np.int64)
        count = fn(_addr(mat_a), n, b_ptr, m, L, int(d), _addr(out_i),
                   _addr(out_j), cap)
        if count <= cap:
            return out_i[:count], out_j[:count]
        cap = count


def adjacency_bfs(nbr_flat: np.ndarray, nbr_start: np.ndarray,
                  counts: np.ndarray):
    """Directed adjacency BFS roots (fgumi_adjacency_bfs): root_of int64[n]."""
    lib = get_lib()
    n = len(nbr_start) - 1
    nbr_flat = np.ascontiguousarray(nbr_flat, np.int64)
    nbr_start = np.ascontiguousarray(nbr_start, np.int64)
    counts = np.ascontiguousarray(counts, np.int64)
    root_of = np.empty(n, dtype=np.int64)
    lib.fgumi_adjacency_bfs(_addr(nbr_flat), _addr(nbr_start), _addr(counts),
                            n, _addr(root_of))
    return root_of


def segment_depth_errors(codes2d: np.ndarray, winner: np.ndarray,
                         starts: np.ndarray):
    """Per-segment depth/error counts: (J, L) int32 pair.

    codes2d: dense (N, L) uint8 read rows; winner: (J, L) uint8 called bases;
    starts: (J+1,) row boundaries.
    """
    lib = get_lib()
    J, L = winner.shape
    depth = np.empty((J, L), dtype=np.int32)
    errors = np.empty((J, L), dtype=np.int32)
    codes2d = _as_c(codes2d, np.uint8)
    winner = _as_c(winner, np.uint8)
    starts = _as_c(starts, np.int64)
    lib.fgumi_segment_depth_errors(_addr(codes2d), _addr(winner),
                                   _addr(starts), J, L, _addr(depth),
                                   _addr(errors))
    return depth, errors


def segment_depth_errors_ranges(codes2d: np.ndarray, winner: np.ndarray,
                                lo, hi):
    """segment_depth_errors over explicit [lo[j], hi[j]) row ranges."""
    lib = get_lib()
    J, L = winner.shape
    depth = np.empty((J, L), dtype=np.int32)
    errors = np.empty((J, L), dtype=np.int32)
    codes2d = _as_c(codes2d, np.uint8)
    winner = _as_c(winner, np.uint8)
    lo = _as_c(lo, np.int64)
    hi = _as_c(hi, np.int64)
    lib.fgumi_segment_depth_errors_ranges(
        _addr(codes2d), _addr(winner), _addr(lo), _addr(hi), J, L,
        _addr(depth), _addr(errors))
    return depth, errors


def ranges_equal(buf: np.ndarray, off_a, len_a, off_b, len_b):
    """uint8[n] mask: byte ranges (off_a, len_a) == (off_b, len_b) in buf."""
    lib = get_lib()
    n = len(off_a)
    out = np.empty(n, dtype=np.uint8)
    off_a = np.ascontiguousarray(off_a, np.int64)
    off_b = np.ascontiguousarray(off_b, np.int64)
    len_a = np.ascontiguousarray(len_a, np.int32)
    len_b = np.ascontiguousarray(len_b, np.int32)
    lib.fgumi_ranges_equal(_addr(buf), _addr(off_a), _addr(len_a),
                           _addr(off_b), _addr(len_b), n, _addr(out))
    return out


def template_coord_keys(batch, lib_ord: np.ndarray):
    """Packed template-coordinate sort keys for a whole RecordBatch.

    Returns (out uint8 blob, out_off int64[n+1]) — record i's key is
    out[out_off[i]:out_off[i+1]].
    """
    lib = get_lib()
    n = batch.n
    # only Z/H-typed tags count as present (RawRecord.get_str semantics);
    # e.g. an MI:i: tag must fall back to (0, 0) like the per-record path
    batch.prefetch_tags([b"MC", b"MI", b"RG"])  # one fused aux scan
    mc_off, mc_len, _ = batch.tag_locs_str(b"MC")
    mi_off, mi_len, _ = batch.tag_locs_str(b"MI")
    key_len = (30 + batch.l_read_name).astype(np.int64)  # 29 + name + NUL + up
    out_off = np.concatenate(([0], np.cumsum(key_len)))
    out = np.empty(int(out_off[-1]), dtype=np.uint8)
    args = [np.ascontiguousarray(a) for a in (
        batch.data_off, batch.l_read_name, batch.cigar_off, batch.n_cigar,
        batch.flag, batch.ref_id, batch.pos, batch.next_ref_id,
        batch.next_pos, mc_off, mc_len, mi_off, mi_len)]
    lib_ord = np.ascontiguousarray(lib_ord, np.int32)
    lib.fgumi_template_coord_keys(
        _addr(batch.buf), *(map(_addr, args)), _addr(lib_ord), n, _addr(out),
        _addr(out_off))
    return out, out_off


def natural_name_keys(batch):
    """Packed natural-queryname sort keys for a whole RecordBatch.

    Returns (out uint8 blob, out_off int64[n], out_len int32[n]).
    """
    lib = get_lib()
    n = batch.n
    # worst case 3 bytes per name char (alternating single-char digit/text
    # runs) + NUL + 4-byte rank
    cap = (3 * batch.l_read_name + 2).astype(np.int64)
    out_off = np.concatenate(([0], np.cumsum(cap)))[:-1]
    out = np.empty(int(cap.sum()), dtype=np.uint8)
    out_len = np.empty(n, dtype=np.int32)
    args = [np.ascontiguousarray(a) for a in (
        batch.data_off, batch.l_read_name, batch.flag)]
    lib.fgumi_natural_name_keys(_addr(batch.buf), *(map(_addr, args)), n,
                                _addr(out), _addr(out_off), _addr(out_len))
    return out, out_off, out_len


def unclipped_5prime(batch):
    """Per-record unclipped 5' positions (int64[n]; meaningful for mapped)."""
    lib = get_lib()
    out = np.empty(batch.n, dtype=np.int64)
    args = [np.ascontiguousarray(a) for a in (
        batch.cigar_off, batch.n_cigar, batch.flag, batch.pos)]
    lib.fgumi_unclipped_5prime(_addr(batch.buf), *(map(_addr, args)), batch.n,
                               _addr(out))
    return out


def umi_scan(buf: np.ndarray, off, length):
    """(has_n uint8[n], bases int32[n], ascii uint8[n]) per byte range;
    off < 0 -> (0, -1, 1)."""
    lib = get_lib()
    n = len(off)
    has_n = np.empty(n, dtype=np.uint8)
    bases = np.empty(n, dtype=np.int32)
    ascii_ = np.empty(n, dtype=np.uint8)
    off = np.ascontiguousarray(off, np.int64)
    length = np.ascontiguousarray(length, np.int32)
    lib.fgumi_umi_scan(_addr(buf), _addr(off), _addr(length), n,
                       _addr(has_n), _addr(bases), _addr(ascii_))
    return has_n, bases, ascii_


def rewrite_tag_records(batch, rows, tag: bytes, values, new_flags=None):
    """Wire blob for `rows` with `tag` replaced by per-row Z values.

    values: list of bytes, parallel to rows. new_flags: optional int32 array
    (per row; -1 = keep the record's flag). Returns the contiguous
    block_size-prefixed wire blob with every prior occurrence of the tag
    removed and the new value appended per record. Raises ValueError on a
    malformed aux region (callers fall back to the Python record editor).
    """
    lib = get_lib()
    rows = np.ascontiguousarray(rows, np.int64)
    k = len(rows)
    if isinstance(values, np.ndarray) and values.dtype.kind == "S":
        # fixed-stride S-array fast path: true lengths + stride offsets
        # into the array's own buffer (NUL padding is simply never read)
        val_len = np.char.str_len(values).astype(np.int32)
        stride = values.dtype.itemsize
        val_off = np.arange(k, dtype=np.int64) * stride
        v = np.ascontiguousarray(values)
        val_blob = v.view(np.uint8) if k else np.zeros(1, np.uint8)
    else:
        val_blob = np.frombuffer(b"".join(values) or b"\x00", dtype=np.uint8)
        val_len = np.array([len(v) for v in values], dtype=np.int32)
        val_off = np.concatenate(
            ([0], np.cumsum(val_len, dtype=np.int64)))[:-1] \
            if k else np.empty(0, dtype=np.int64)
    data_off = np.ascontiguousarray(batch.data_off[rows])
    data_end = np.ascontiguousarray(batch.data_end[rows])
    aux_off = np.ascontiguousarray(batch.aux_off[rows])
    cap = int(((data_end - data_off) + 8 + val_len).sum())
    out = np.empty(cap, dtype=np.uint8)
    flags_arg = 0
    if new_flags is not None:
        new_flags = np.ascontiguousarray(new_flags, np.int32)
        flags_arg = _addr(new_flags)
    total = lib.fgumi_rewrite_tag_records(
        _addr(batch.buf), _addr(data_off), _addr(data_end), _addr(aux_off),
        k, tag[0], tag[1], _addr(val_blob), _addr(val_off), _addr(val_len),
        flags_arg, _addr(out))
    if total < 0:
        raise ValueError(f"malformed aux region in record {-(total + 1)}")
    return out[:total].tobytes()


def qual_scores(batch, min_q: int, cap: int):
    """Per-record Picard base-quality score (sum of quals >= min_q, capped)."""
    lib = get_lib()
    out = np.empty(batch.n, dtype=np.int32)
    qual_off = np.ascontiguousarray(batch.qual_off)
    l_seq = np.ascontiguousarray(batch.l_seq)
    lib.fgumi_qual_scores(_addr(batch.buf), _addr(qual_off), _addr(l_seq),
                          batch.n, min_q, cap, _addr(out))
    return out


def gather_u16_arrays(buf: np.ndarray, val_off, L: int):
    """Dense (n, L) uint16 matrix from B:s/B:S tag values (zero-padded).

    Returns (values, counts): counts -1 = tag absent, -2 = non-16-bit
    subtype (caller reroutes that record).
    """
    lib = get_lib()
    n = len(val_off)
    out = np.empty((n, L), dtype=np.uint16)
    counts = np.empty(n, dtype=np.int32)
    val_off = np.ascontiguousarray(val_off, np.int64)
    lib.fgumi_gather_u16_arrays(_addr(buf), _addr(val_off), n, L, _addr(out),
                                _addr(counts))
    return out, counts


def apply_masks(batch, rows, mask: np.ndarray, skip_existing_n: bool):
    """In-place N/Q2 masking of `rows`' seq/qual regions.

    mask: (len(rows), L) uint8 over each record's first l_seq positions.
    Returns (newly_masked int32[k], n_after int32[k]).
    """
    lib = get_lib()
    rows = np.ascontiguousarray(rows, np.int64)
    k = len(rows)
    mask = np.ascontiguousarray(mask, np.uint8)
    seq_off = np.ascontiguousarray(batch.seq_off[rows])
    qual_off = np.ascontiguousarray(batch.qual_off[rows])
    l_seq = np.ascontiguousarray(batch.l_seq[rows])
    newly = np.empty(k, dtype=np.int32)
    n_after = np.empty(k, dtype=np.int32)
    lib.fgumi_apply_masks(_addr(batch.buf), _addr(seq_off), _addr(qual_off),
                          _addr(l_seq), k, _addr(mask), mask.shape[1],
                          int(skip_existing_n), _addr(newly), _addr(n_after))
    return newly, n_after


def hash_ranges(buf: np.ndarray, off, length):
    """FNV-1a 64-bit hash per byte range (off < 0 -> 0)."""
    lib = get_lib()
    n = len(off)
    out = np.empty(n, dtype=np.uint64)
    off = np.ascontiguousarray(off, np.int64)
    length = np.ascontiguousarray(length, np.int32)
    lib.fgumi_hash_ranges(_addr(buf), _addr(off), _addr(length), n, _addr(out))
    return out


def rx_unanimous(buf: np.ndarray, off, length, starts):
    """Per-segment RX unanimity: (out_off int64[J], out_len int32[J]).

    out_off -1 = no tag anywhere in the segment; -2 = caller must run the
    Python consensus; >= 0 = verbatim unanimous value at that buffer range.
    """
    lib = get_lib()
    J = len(starts) - 1
    out_off = np.empty(J, dtype=np.int64)
    out_len = np.empty(J, dtype=np.int32)
    off = np.ascontiguousarray(off, np.int64)
    length = np.ascontiguousarray(length, np.int32)
    starts = np.ascontiguousarray(starts, np.int64)
    lib.fgumi_rx_unanimous(_addr(buf), _addr(off), _addr(length),
                           _addr(starts), J, _addr(out_off), _addr(out_len))
    return out_off, out_len


def overlap_correct_pairs(buf: np.ndarray, r1_off: np.ndarray,
                          r2_off: np.ndarray, agreement: int,
                          disagreement: int) -> np.ndarray:
    """In-place R1/R2 overlap correction on a WRITABLE buffer.

    agreement: 0=consensus 1=max-qual 2=pass-through; disagreement:
    0=consensus 1=mask-both 2=mask-lower-qual. Returns int64[4] stats
    (overlapping, agreeing, disagreeing, corrected).
    """
    lib = get_lib()
    assert buf.flags["WRITEABLE"]
    stats = np.zeros(4, dtype=np.int64)
    lib.fgumi_overlap_correct_pairs(_addr(buf), _addr(r1_off), _addr(r2_off),
                                    len(r1_off), agreement, disagreement,
                                    _addr(stats))
    return stats


def extract_records(bufs, name_off, name_len, seq_off, seq_len, qual_off,
                    segments, qual_offset: int, rg: bytes,
                    store_umi_quals: bool):
    """Batched FASTQ -> unmapped-BAM record assembly (fgumi_extract_records).

    bufs: list of per-input uint8 chunk buffers; the offset/len arrays are
    (n_inputs, n) int64/int32; segments: flattened [(input, kind, len)] with
    kind 0=template 1=UMI 2=skip and len -1 = rest-of-read.
    Returns the block_size-prefixed wire blob (bytes).
    """
    lib = get_lib()
    n_inputs = len(bufs)
    n = name_off.shape[1]
    buf_addr = np.array([b.ctypes.data for b in bufs], dtype=np.int64)
    name_off = np.ascontiguousarray(name_off, np.int64)
    name_len = np.ascontiguousarray(name_len, np.int32)
    seq_off = np.ascontiguousarray(seq_off, np.int64)
    seq_len = np.ascontiguousarray(seq_len, np.int32)
    qual_off = np.ascontiguousarray(qual_off, np.int64)
    seg_input = np.array([s[0] for s in segments], dtype=np.int32)
    seg_kind = np.array([s[1] for s in segments], dtype=np.int32)
    seg_len = np.array([s[2] for s in segments], dtype=np.int32)
    # capacity: every read byte appears at most twice (packed seq + quals,
    # UMI segments again in RX+QX), plus per emitted record header+name+tags
    n_templates = max(1, int((seg_kind == 0).sum()))
    max_name = int(name_len.max()) if n else 0
    # packed seq + quals appear once per read byte; the joined UMI (fixed M
    # segments only on this path, _fast_extract_ok) repeats in every emitted
    # record's RX and QX
    umi_total = int(seg_len[seg_kind == 1].sum()) + int((seg_kind == 1).sum())
    out_cap = (int(2 * seq_len.astype(np.int64).sum())
               + n * n_templates * (104 + max_name + len(rg) + 2 * umi_total)
               + 4096)
    out = np.empty(out_cap, dtype=np.uint8)
    state = np.zeros(2, dtype=np.int64)
    rg_arr = np.frombuffer(rg, dtype=np.uint8)
    rc = lib.fgumi_extract_records(
        n_inputs, n, _addr(buf_addr), _addr(name_off), _addr(name_len),
        _addr(seq_off), _addr(seq_len), _addr(qual_off), len(segments),
        _addr(seg_input), _addr(seg_kind), _addr(seg_len), qual_offset,
        _addr(rg_arr), len(rg), int(store_umi_quals), _addr(out), out_cap,
        _addr(state))
    if rc == -1:
        raise RuntimeError("extract output capacity overflow")
    if rc in (-2, -3, -4):
        raise NativeExtractError(int(rc), int(state[1]))
    return out[:int(state[0])].tobytes()


class NativeExtractError(ValueError):
    """Record-level extract failure; the caller re-runs the offending record
    through the Python path to produce the canonical error message."""

    def __init__(self, code: int, record_index: int):
        super().__init__(f"extract error {code} at batch record {record_index}")
        self.code = code
        self.record_index = record_index


def build_codec_records(seq_addr, qual_addr, cons_err_addr,
                        a_base, a_qual, a_depth, a_err,
                        b_base, b_qual, b_depth, b_err,
                        lens, name_addr, name_len, mi_addr, mi_len,
                        rx_addr, rx_len, rg: bytes, flags: int,
                        per_base_tags: bool):
    """Serialize J CODEC consensus records into one wire blob.

    Byte-exact analog of CodecConsensusCaller._build_record (codec.py; ref
    codec_caller.rs:1374-1539). All *_addr arrays are raw element addresses
    (int64) into caller-owned arrays that MUST stay referenced for the call;
    seq/qual/strand base+qual rows are uint8, cons_err/depth/error rows are
    int64, all of length lens[j]. mi_len[j] < 0 skips MI; rx_addr[j] == 0
    skips RX.
    """
    lib = get_lib()
    J = len(lens)
    lens = np.ascontiguousarray(lens, np.int32)
    name_len = np.ascontiguousarray(name_len, np.int32)
    mi_len = np.ascontiguousarray(mi_len, np.int32)
    rx_len = np.ascontiguousarray(rx_len, np.int32)
    addrs = [np.ascontiguousarray(a, np.int64)
             for a in (seq_addr, qual_addr, cons_err_addr, a_base, a_qual,
                       a_depth, a_err, b_base, b_qual, b_depth, b_err,
                       name_addr, mi_addr, rx_addr)]
    (seq_addr, qual_addr, cons_err_addr, a_base, a_qual, a_depth, a_err,
     b_base, b_qual, b_depth, b_err, name_addr, mi_addr, rx_addr) = addrs
    L64 = lens.astype(np.int64)
    per_rec = (4 + 32 + name_len.astype(np.int64) + 1 + (L64 + 1) // 2 + L64
               + (3 + len(rg) + 1) + 9 * 7
               + np.where(mi_len >= 0, 3 + mi_len.astype(np.int64) + 1, 0)
               + np.where(rx_addr != 0, 3 + rx_len.astype(np.int64) + 1, 0))
    if per_base_tags:
        per_rec = per_rec + 4 * (8 + 2 * L64) + 4 * (3 + L64 + 1)
    out_cap = int(per_rec.sum())
    out = np.empty(out_cap, dtype=np.uint8)
    rec_end = np.empty(J, dtype=np.int64)
    rg_arr = np.frombuffer(rg, dtype=np.uint8)
    total = lib.fgumi_build_codec_records(
        _addr(seq_addr), _addr(qual_addr), _addr(cons_err_addr),
        _addr(a_base), _addr(a_qual), _addr(a_depth), _addr(a_err),
        _addr(b_base), _addr(b_qual), _addr(b_depth), _addr(b_err),
        _addr(lens), J, _addr(name_addr), _addr(name_len), _addr(mi_addr),
        _addr(mi_len), _addr(rx_addr), _addr(rx_len), _addr(rg_arr), len(rg),
        int(flags), int(per_base_tags), _addr(out), out_cap, _addr(rec_end))
    if total == -2:
        raise ValueError("read name too long (exceeds 254 bytes)")
    if total < 0:
        raise RuntimeError("codec record serialization overflow")
    return out[:total].tobytes(), rec_end


def ref_spans(buf: np.ndarray, cigar_off, n_cigar, pos):
    """Per-record reference-span end (pos + ref-consumed CIGAR length, min 1)."""
    lib = get_lib()
    n = len(pos)
    out = np.empty(n, dtype=np.int32)
    co = np.ascontiguousarray(cigar_off, np.int64)
    nc = np.ascontiguousarray(n_cigar, np.int32)
    ps = np.ascontiguousarray(pos, np.int32)
    lib.fgumi_ref_spans(_addr(buf), _addr(co), _addr(nc), _addr(ps), n,
                        _addr(out))
    return out


def tag_name_list(buf: np.ndarray, aux_off, aux_end, max_per: int = 24):
    """Per-record aux tag names: (names uint16 (n, max_per), counts int32);
    counts[i] == -1 means too many/malformed (caller falls back)."""
    lib = get_lib()
    n = len(aux_off)
    names = np.empty((n, max_per), dtype=np.uint16)
    counts = np.empty(n, dtype=np.int32)
    ao = np.ascontiguousarray(aux_off, np.int64)
    ae = np.ascontiguousarray(aux_end, np.int64)
    lib.fgumi_tag_name_list(_addr(buf), _addr(ao), _addr(ae), n, max_per,
                            _addr(names), _addr(counts))
    return names, counts


def cigar_strings(buf: np.ndarray, cigar_off, n_cigar):
    """Batched CIGAR rendering: (blob bytes, (n+1,) int64 offsets)."""
    lib = get_lib()
    n = len(n_cigar)
    nc = np.ascontiguousarray(n_cigar, np.int32)
    co = np.ascontiguousarray(cigar_off, np.int64)
    cap = int(np.maximum(11 * nc.astype(np.int64), 1).sum())
    out = np.empty(cap, dtype=np.uint8)
    out_off = np.empty(n + 1, dtype=np.int64)
    rc = lib.fgumi_cigar_strings(_addr(buf), _addr(co), _addr(nc), n,
                                 _addr(out), _addr(out_off))
    if rc < 0:
        raise ValueError("invalid CIGAR op code")
    return out, out_off


def rebuild_aux_records(buf: np.ndarray, data_off, aux_off, data_end,
                        drop: np.ndarray, drop_off, appends: np.ndarray,
                        app_off):
    """Rebuild records with filtered aux + appended TLV bytes; returns
    (wire blob bytes incl. block_size prefixes, (n+1,) int64 offsets) or
    None when a record is malformed (caller falls back per record)."""
    lib = get_lib()
    n = len(data_off)
    do = np.ascontiguousarray(data_off, np.int64)
    ao = np.ascontiguousarray(aux_off, np.int64)
    de = np.ascontiguousarray(data_end, np.int64)
    dro = np.ascontiguousarray(drop_off, np.int64)
    apo = np.ascontiguousarray(app_off, np.int64)
    drop = np.ascontiguousarray(drop, np.uint16)
    appends = np.ascontiguousarray(appends, np.uint8)
    cap = int((de - do).sum() + (apo[-1] - apo[0]) + 4 * n)
    out = np.empty(max(cap, 1), dtype=np.uint8)
    out_pos = np.empty(n + 1, dtype=np.int64)
    total = lib.fgumi_rebuild_aux_records(
        _addr(buf), _addr(do), _addr(ao), _addr(de), n, _addr(drop),
        _addr(dro), _addr(appends), _addr(apo), _addr(out), _addr(out_pos))
    if total < 0:
        return None
    return out[:total], out_pos


def concat_spans(srcs, src_id, off, length):
    """Concatenate spans from up to 8 source uint8 arrays: returns
    (blob uint8, (n+1,) int64 offsets). Zero-length spans are legal."""
    lib = get_lib()
    n = len(src_id)
    addrs = np.zeros(8, dtype=np.int64)
    keep = []
    for i, s in enumerate(srcs):
        s = np.ascontiguousarray(s, np.uint8)
        keep.append(s)
        addrs[i] = s.ctypes.data
    sid = np.ascontiguousarray(src_id, np.int32)
    so = np.ascontiguousarray(off, np.int64)
    sl = np.ascontiguousarray(length, np.int32)
    out = np.empty(max(int(sl[sl > 0].sum()), 1), dtype=np.uint8)
    out_off = np.empty(n + 1, dtype=np.int64)
    lib.fgumi_concat_spans(_addr(addrs), _addr(sid), _addr(so), _addr(sl), n,
                           _addr(out), _addr(out_off))
    del keep
    return out, out_off


def codec_combine(b1, b2, q1, q2, d1, d2, e1, e2, min_phred: int,
                  no_call: int, no_call_lower: int, i16_max: int):
    """Single-pass CODEC duplex combine (fgumi_codec_combine).

    The native form of consensus/codec.py combine_arrays plus the
    both/disagree flag derivation — one C pass instead of ~25 whole-array
    numpy passes. Inputs: uint8 base/qual arrays and int32 depth/error
    arrays of equal length. Returns (base u8, qual u8, depth i32,
    errors i32, both bool, disag bool).
    """
    lib = get_lib()
    n = len(b1)
    b1 = np.ascontiguousarray(b1, np.uint8)
    b2 = np.ascontiguousarray(b2, np.uint8)
    q1 = np.ascontiguousarray(q1, np.uint8)
    q2 = np.ascontiguousarray(q2, np.uint8)
    d1 = np.ascontiguousarray(d1, np.int32)
    d2 = np.ascontiguousarray(d2, np.int32)
    e1 = np.ascontiguousarray(e1, np.int32)
    e2 = np.ascontiguousarray(e2, np.int32)
    cb = np.empty(n, dtype=np.uint8)
    cq = np.empty(n, dtype=np.uint8)
    cd = np.empty(n, dtype=np.int32)
    ce = np.empty(n, dtype=np.int32)
    both = np.empty(n, dtype=np.uint8)
    disag = np.empty(n, dtype=np.uint8)
    lib.fgumi_codec_combine(
        _addr(b1), _addr(b2), _addr(q1), _addr(q2), _addr(d1), _addr(d2),
        _addr(e1), _addr(e2), n, int(min_phred), int(no_call),
        int(no_call_lower), int(i16_max), _addr(cb), _addr(cq), _addr(cd),
        _addr(ce), _addr(both), _addr(disag))
    return cb, cq, cd, ce, both.view(np.bool_), disag.view(np.bool_)


def duplex_rx_fast(buf, una_off, una_len, cnt, a_seg, b_seg):
    """Duplex consensus-RX fast path (fgumi_duplex_rx_fast).

    Resolves every output whose contributing segs are unanimous (or
    absent) entirely in C — single-read verbatim / all-equal uppercased,
    with the b-side strand flip done on bytes. Returns (rx_off i64,
    rx_len i32, blob u8, fb_idx i64): outputs listed in fb_idx (divergent
    segs or disagreeing values) are untouched and need the Python
    likelihood path.
    """
    lib = get_lib()
    K = len(a_seg)
    una_off = np.ascontiguousarray(una_off, np.int64)
    una_len = np.ascontiguousarray(una_len, np.int32)
    cnt = np.ascontiguousarray(cnt, np.int64)
    a_seg = np.ascontiguousarray(a_seg, np.int64)
    b_seg = np.ascontiguousarray(b_seg, np.int64)
    # exact bound: each output emits at most one contributing value
    pos_len = np.where(una_off >= 0, una_len.astype(np.int64), 0)
    cap = int(pos_len[a_seg[a_seg >= 0]].sum()
              + pos_len[b_seg[b_seg >= 0]].sum()) + 1
    blob = np.empty(cap, dtype=np.uint8)
    rx_off = np.empty(K, dtype=np.int64)
    rx_len = np.empty(K, dtype=np.int32)
    fb_idx = np.empty(max(K, 1), dtype=np.int64)
    used = np.zeros(1, dtype=np.int64)
    n_fb = lib.fgumi_duplex_rx_fast(
        _addr(buf), _addr(una_off), _addr(una_len), _addr(cnt),
        _addr(a_seg), _addr(b_seg), K, _addr(blob), cap, _addr(rx_off),
        _addr(rx_len), _addr(fb_idx), _addr(used))
    assert n_fb >= 0, "duplex_rx_fast blob overflow (sizing bug)"
    return rx_off, rx_len, blob[:int(used[0])], fb_idx[:n_fb]
